#!/usr/bin/env python3
"""Bench trend gate (VERDICT r3 weak #4): the round-3 4.8x reconcile
regression arrived silently because nothing compared BENCH_rN against
BENCH_rN-1. This check fails CI when the newest benchmark regressed
more than REGRESSION_FACTOR on either headline axis — p50 latency up
or flips/min down — unless the regression is acknowledged in a note
(extras.regression_note in the newer BENCH file, or a "## r<N>"
section in BENCH_NOTES.md). A noted regression is a decision; an
unnoted one is a bug.

Usage: python scripts/bench_trend.py [repo_root]
Exit 0 = no unexplained regression (or <2 bench files to compare).
"""

import glob
import json
import os
import re
import sys

REGRESSION_FACTOR = 2.0

#: extras axes gated like the headline pair — axis -> direction
#: ("lower" = seconds-valued, bigger is worse; "higher" = throughput,
#: smaller is worse). Rounds where either side lacks the axis (older
#: bench, a CPU-only host for real_chip) skip the comparison silently,
#: so mixed-era histories stay green; once both rounds carry a number,
#: an unnoted >2x move in the bad direction fails CI.
#: real_chip_flip_s joined after the r05 4.43s jump arrived unnoticed
#: (VERDICT r5 weak #3); pool256_convergence_s is the simlab
#: live-fleet scenario; multichip_flip_s is the 8-device parallel flip
#: pipeline wall clock (BENCH_NOTES r06) — the axis that regresses if
#: the executor ever quietly re-serializes;
#: flips_per_min_windowed joined as a first-class gated axis in r07
#: (the coalesced flip-path writes round, ISSUE 6) — the steady-state
#: throughput the write-batching work is judged on.
#: fleet_scan_warm_s / planner_tick_100k_s joined in r08 (the
#: array-native planner round, ISSUE 7): the warm per-tick fleet scan
#: (compile economics stripped out — the number a steady-state
#: controller pays every interval) and the synthetic 100k-node planner
#: tick (the ROADMAP item 3 scale proof). The COLD scan number stays
#: visible as scale256.fleet_scan_s but ungated: with the persistent
#: compile cache it measures cache priming, a one-per-deploy cost.
#: e2e_convergence_p99_s joined in r09 (the flight-recorder round,
#: ISSUE 8): label-commit -> state-published latency per node in the
#: pool256 scenario, measured from CROSS-PROCESS stitched traces
#: (desired_write span start to the last adopted reconcile span end)
#: rather than the driver's convergence poll — the causal tail-latency
#: axis ROADMAP item 2 asks for, and the one that regresses if trace
#: propagation (or the reconcile path under it) quietly breaks. A
#: FULLY broken stitch (zero samples -> null axis) cannot hide in the
#: skip-if-absent rule here: bench.py itself exits 1 when the scenario
#: converges with no stitched e2e samples.
#: lifecycle_convergence_s joined in r12 (the lifecycle-chaos round,
#: ISSUE 12): the upgrade-256 scenario's convergence THROUGH a rolling
#: agent upgrade (four cohorts restarting with a new code version
#: mid-double-wave), judged green by the simlab invariants oracle
#: before the number is even exported — the axis that regresses if
#: upgrade churn starts fighting the reconcile path.
#: pool1024_convergence_s / shard_failover_convergence_s joined in r11
#: (the sharded-control-plane round, ISSUE 11): 1,024 live replicas
#: through N consistent-hash controller shards over one shared node
#: informer — the axis that regresses if the shard layer (or the
#: informer read path under it) quietly re-serializes, and the
#: shard-kill -> reconverged latency that regresses if lease handoff
#: or partition re-acquisition breaks. pool1024 is additionally bound
#: RELATIVE to pool256 (RELATIVE_CEILINGS below): 4x the fleet must
#: stay within 3x the convergence wall clock.
#: flip_write_rtt_p50_s joined in r13 (the async-reconcile-core round,
#: ISSUE 13): per-node-write round trip (PATCH/PUT, queueing included)
#: under the pool bench's offered load, measured on the async I/O
#: core's pipeline — the axis that rises FIRST if multiplexing quietly
#: re-serializes, before flips_per_min_windowed falls. The same round
#: raised the flips_per_min_windowed floor 21k -> 25k (the async core
#: measures ~1.3x the threaded client's windowed throughput on the
#: same host; BENCH_NOTES ## r13 carries the host-variance
#: acknowledgment forward).
GATED_EXTRA_AXES = {
    "real_chip_flip_s": "lower",
    "pool256_convergence_s": "lower",
    "multichip_flip_s": "lower",
    "flips_per_min_windowed": "higher",
    "fleet_scan_warm_s": "lower",
    "planner_tick_100k_s": "lower",
    "e2e_convergence_p99_s": "lower",
    "pool1024_convergence_s": "lower",
    "shard_failover_convergence_s": "lower",
    "lifecycle_convergence_s": "lower",
    "flip_write_rtt_p50_s": "lower",
    # joined in r14 (the reactive-rollout round, ISSUE 14): group
    # terminal -> the NEXT group's first desired write, measured
    # store-side around the event-driven rollout judge. This is the
    # axis that regresses if the judge quietly falls back to interval
    # clocking (it would jump from ~ms to ~poll_s/2); the interval
    # baseline is re-measured every round in
    # extras.rollout_reactive.interval_advance_p50_s.
    "rollout_advance_p50_s": "lower",
    # joined in r15 (the incident-autopsy round, ISSUE 15): the armed
    # sampling profiler's flip-loop overhead (four interleaved
    # disarmed/armed runs, min-based estimator
    # min(armed)/min(disarmed) - 1 — single-run scheduler noise on the
    # sandbox exceeds the real cost; the axis that regresses if the
    # sampler's per-tick cost grows past its 5% admission ceiling) and
    # the anomaly fire -> incident-packet-complete latency (exemplar
    # harvest + live profile capture burst + throttled flight-recorder
    # dump; regresses if packet assembly starts blocking the sampling
    # loop it runs on).
    "profiler_overhead_pct": "lower",
    "incident_capture_s": "lower",
    # joined in r16 (the multi-region federation round, ISSUE 16):
    # region_evacuate injection -> the fleet stable again (evacuated
    # region fully cordoned through its own API server AND every other
    # region converged after its window collapsed to absorb) on the
    # federation-2x512 scenario — the axis that regresses if the
    # absorb signal stops collapsing sibling windows or the cordon
    # loop starts serializing behind posture retries; and the
    # CROSS-REGION desired-write -> state-published p99 stitched over
    # trace ids spanning both API servers (namespaced: the plain
    # e2e_convergence_p99_s axis is the single-server scale-256 run's).
    "region_evac_convergence_s": "lower",
    "federation_e2e_convergence_p99_s": "lower",
    # joined in r17 (the async-aware-analyzer round, ISSUE 17): wall
    # seconds for one full-repo ccaudit run — the cost `make lint`
    # pays on every invocation. v4's whole-program passes (call-graph
    # fixpoints, loop-confinement, caller-held locksets) all ride one
    # parse; this is the axis that regresses if a new rule family
    # starts re-walking the tree or a fixpoint loses termination
    # sharpness.
    "ccaudit_wall_s": "lower",
    # joined in r19 (the incremental-planner round, ISSUE 19): the
    # steady-state INCREMENTAL tick over a synthetic million-node
    # fleet at a 1% delta rate (device-resident sharded columns,
    # delta scatter instead of re-upload) — the axis that regresses
    # if the session quietly falls back to rebuild-per-tick; and the
    # incremental-vs-full speedup ratio, which collapses toward 1.0
    # on the same failure even when absolute wall time hides it on a
    # fast host. bench-smoke runs the same code path at 250k
    # (TPU_CC_BENCH_PLANNER_NODES) so the nightly-tier 1M axis never
    # rots unexercised.
    "planner_tick_1m_s": "lower",
    "planner_tick_incr_speedup": "higher",
}

#: absolute bars on the newest round (ISSUE 6 acceptance): floors are
#: minima for higher-is-better axes, ceilings are maxima. Skipped when
#: the newest round lacks the axis; a miss is acknowledgeable through
#: the same BENCH_NOTES/regression_note escape as a trend regression —
#: a noted miss (e.g. a degraded sandbox host, see BENCH_NOTES r07's
#: variance note) is a decision, an unnoted one is a bug.
THROUGHPUT_FLOORS = {
    # raised from 21000 in r13: the async reconcile core (ISSUE 13)
    # multiplexes the flip path's writes over pipelined connections,
    # measured ~1.3x the threaded client's windowed steady state on
    # the same sandbox (BENCH_NOTES ## r13 pre-explains the step and
    # carries the r07 degraded-host acknowledgment convention forward)
    "flips_per_min_windowed": 25000.0,
    # ISSUE 19 acceptance: incremental ticks at a 1% delta rate must
    # beat full ticks by >= 5x (measured ~10-13x on the 2-core
    # sandbox at 250k-1M nodes; the margin absorbs host noise, the
    # floor fails any fallback to whole-fleet re-evaluation)
    "planner_tick_incr_speedup": 5.0,
}
#: node_writes_per_flip: the coalescing contract is <= 2 writes per
#: flip on the hot path; 2.5 allows the idle-tick flush tail without
#: letting a silent un-batching regression (back toward the historical
#: ~5) pass.
WRITE_CEILINGS = {
    "node_writes_per_flip": 2.5,
}
#: absolute latency maxima on the newest round (ISSUE 7 acceptance):
#: the warm fleet scan must sit far under the old ~8s cold number
#: (0.5s allows the 256-node list round trips at QPS=50 plus the tick
#: itself), and the 100k-node planner tick must finish in single-digit
#: seconds on the 2-core sandbox. Same skip-if-absent and
#: BENCH_NOTES/regression_note escape as every other bar.
LATENCY_CEILINGS = {
    "fleet_scan_warm_s": 0.5,
    "planner_tick_100k_s": 9.0,
    # ISSUE 19 acceptance: a steady incremental tick at 10^6 nodes
    # must sit in the same latency decade as today's 100k full tick —
    # measured 0.09 s on the 2-core sandbox; 0.5 allows a loaded CI
    # host, not a session that re-uploads the block every tick.
    "planner_tick_1m_s": 0.5,
    # a flip write under offered load must stay well inside the flush
    # window (measured 0.027-0.034 s on the 2-core sandbox; the
    # ceiling allows a loaded CI host, not a re-serialized pipeline)
    "flip_write_rtt_p50_s": 0.25,
    # the event-driven judge advances the window in ~1 ms (measured
    # 0.0006 s sandbox); the interval judge it replaced paid ~poll/2
    # (~0.47 s at the bench's 0.5 s poll). 0.2 allows a loaded CI
    # host while still failing ANY fallback to interval clocking.
    "rollout_advance_p50_s": 0.2,
    # ISSUE 15 acceptance: the armed profiler may cost the flip loop
    # at most 5% (percent units, not seconds — same compare); measured
    # ~0-3% sandbox median. A miss on a noisy shared host takes the
    # BENCH_NOTES escape like every other bar.
    "profiler_overhead_pct": 5.0,
    # anomaly fire -> packet complete: dominated by the deliberate
    # 0.25 s profile capture burst; 2.0 allows a slow disk's
    # flight-recorder dump, not a wedged assembly path.
    "incident_capture_s": 2.0,
    # ISSUE 17 acceptance: a full-repo ccaudit run (v4 async families
    # included) measured ~6.6 s on the 2-core sandbox; 20 allows a
    # loaded CI host, not an analyzer that quietly went quadratic.
    # The `--files` changed-files path in `make lint-fast` is the
    # interactive escape hatch; THIS bar keeps the full run honest.
    "ccaudit_wall_s": 20.0,
}
#: relative bars WITHIN the newest round (ISSUE 11 acceptance):
#: numerator axis must stay <= factor x denominator axis. Skipped when
#: either side is absent; a miss takes the same BENCH_NOTES/
#: regression_note escape as every other bar.
RELATIVE_CEILINGS = {
    ("pool1024_convergence_s", "pool256_convergence_s"): 3.0,
}


def _round_num(path):
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def _print_attribution(prev, cur, problems, out):
    """Any gated-axis failure triggers the automatic attribution pass
    (scripts/bench_attr.py, ISSUE 9): ranked per-phase deltas + the
    probe/deps sentinels, printed next to the gate verdict so a
    regression arrives attributed instead of as an r05-style mystery.
    Best-effort by contract — attribution must never mask the gate."""
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_attr",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench_attr.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        axes = mod.axes_from_problems(problems)
        for line in mod.format_report(mod.attribute(prev, cur, axes)):
            print(line, file=out)
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench-trend: attribution pass failed: {e}", file=out)


def _load_bench(path):
    """The driver's BENCH_r*.json wraps the bench's one-line JSON
    inside a {"cmd", "rc", "tail"} envelope; accept both shapes."""
    with open(path) as f:
        doc = json.load(f)
    if "value" in doc:
        return doc
    for line in reversed((doc.get("tail") or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                return json.loads(line)
            except ValueError:
                return None
    return None


def main(root: str = ".") -> int:
    files = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                   key=_round_num)
    if len(files) < 2:
        print("bench-trend: <2 BENCH_r*.json files; nothing to compare")
        return 0
    prev_path, cur_path = files[-2], files[-1]
    prev = _load_bench(prev_path)
    cur = _load_bench(cur_path)
    if prev is None or cur is None:
        print("bench-trend: could not parse bench result(s); skipping")
        return 0

    problems = []
    p50_prev, p50_cur = prev.get("value"), cur.get("value")
    if (isinstance(p50_prev, (int, float)) and p50_prev > 0
            and isinstance(p50_cur, (int, float))
            and p50_cur > p50_prev * REGRESSION_FACTOR):
        problems.append(
            f"p50 {p50_prev} -> {p50_cur} "
            f"({p50_cur / p50_prev:.1f}x slower)"
        )
    # the un-windowed flips/min stays gated only as the mixed-era
    # fallback (rounds before r05 lack the windowed number; since r07
    # the windowed axis is gated first-class in GATED_EXTRA_AXES —
    # flips/elapsed dilutes with setup/teardown time, the r03->r04
    # story)
    prev_x, cur_x = prev.get("extras") or {}, cur.get("extras") or {}
    if not (isinstance(prev_x.get("flips_per_min_windowed"), (int, float))
            and isinstance(cur_x.get("flips_per_min_windowed"),
                           (int, float))):
        fpm_prev = prev_x.get("flips_per_min")
        fpm_cur = cur_x.get("flips_per_min")
        if (isinstance(fpm_prev, (int, float)) and fpm_prev > 0
                and isinstance(fpm_cur, (int, float)) and fpm_cur > 0
                and fpm_cur < fpm_prev / REGRESSION_FACTOR):
            problems.append(
                f"flips_per_min {fpm_prev} -> {fpm_cur} "
                f"({fpm_prev / fpm_cur:.1f}x fewer)"
            )
    for axis, direction in GATED_EXTRA_AXES.items():
        a, b = prev_x.get(axis), cur_x.get(axis)
        if not (isinstance(a, (int, float)) and a > 0
                and isinstance(b, (int, float)) and b > 0):
            continue
        if direction == "lower" and b > a * REGRESSION_FACTOR:
            problems.append(
                f"{axis} {a} -> {b} ({b / a:.1f}x slower)"
            )
        elif direction == "higher" and b < a / REGRESSION_FACTOR:
            problems.append(
                f"{axis} {a} -> {b} ({a / b:.1f}x fewer)"
            )
    for axis, floor in THROUGHPUT_FLOORS.items():
        b = cur_x.get(axis)
        if isinstance(b, (int, float)) and 0 < b < floor:
            problems.append(
                f"{axis} {b} below the {floor:g} floor"
            )
    for ceilings in (WRITE_CEILINGS, LATENCY_CEILINGS):
        for axis, ceiling in ceilings.items():
            b = cur_x.get(axis)
            if isinstance(b, (int, float)) and b > ceiling:
                problems.append(
                    f"{axis} {b} above the {ceiling:g} ceiling"
                )
    for (num_axis, den_axis), factor in RELATIVE_CEILINGS.items():
        num, den = cur_x.get(num_axis), cur_x.get(den_axis)
        if (isinstance(num, (int, float)) and num > 0
                and isinstance(den, (int, float)) and den > 0
                and num > den * factor):
            problems.append(
                f"{num_axis} {num} above {factor:g}x "
                f"{den_axis} ({den})"
            )
    if not problems:
        print(f"bench-trend: {os.path.basename(cur_path)} within "
              f"{REGRESSION_FACTOR}x of {os.path.basename(prev_path)}")
        return 0

    # regression found: attribute it automatically (ranked phase diff
    # + contention/deps sentinels) whether or not it is acknowledged —
    # an acknowledged regression still deserves its named cause
    _print_attribution(prev, cur, problems, sys.stderr)

    # is it acknowledged?
    note = (cur.get("extras") or {}).get("regression_note")
    if note:
        print(f"bench-trend: regression noted in bench extras: {note}")
        return 0
    notes_path = os.path.join(root, "BENCH_NOTES.md")
    cur_round = _round_num(cur_path)
    if os.path.exists(notes_path):
        with open(notes_path) as f:
            notes = f.read()
        if re.search(rf"^##\s*r0*{cur_round}\b", notes, re.M):
            print(f"bench-trend: regression explained in BENCH_NOTES.md "
                  f"(## r{cur_round})")
            return 0
    print("bench-trend: UNEXPLAINED regression vs "
          f"{os.path.basename(prev_path)}:", file=sys.stderr)
    for p in problems:
        print(f"  - {p}", file=sys.stderr)
    print("  add extras.regression_note to the bench output or a "
          f"'## r{cur_round}' section to BENCH_NOTES.md explaining it",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "."))
