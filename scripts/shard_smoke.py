#!/usr/bin/env python3
"""shard-smoke: the sharded control plane's CI gate (ISSUE 11).

Runs the reduced scale-512 scenario — 512 live replicas, 8 pools,
3 consistent-hash controller shards over one shared node informer,
one scripted shard kill mid-storm — and asserts the contract the
full scale-1024 bench axis rides on:

1. the fleet converges despite losing a controller shard;
2. the orphaned partition is re-acquired by a survivor (the lease
   handoff is stamped, and full coverage is restored);
3. the kill -> recovered failover number exists and is sane;
4. the merged per-shard /fleet/metrics exposition is VALID (one fleet
   view, strict text-format rules — duplicate series or non-monotone
   buckets fail here, not in a dashboard).

Exit 0 = all checks pass. Prints one CHECK line per assertion so a red
run names the broken contract, kind_smoke_local style.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_FAILED = []


def check(name: str, ok: bool, detail: str = "") -> None:
    print(f"CHECK {'ok  ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail else ""))
    if not ok:
        _FAILED.append(name)


def main() -> int:
    from tpu_cc_manager.simlab.runner import SimLab
    from tpu_cc_manager.simlab.scenario import load_scenario

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scenarios", "scale-512.json",
    )
    scenario = load_scenario(path)
    check("scenario is sharded", scenario.controllers.shards >= 2,
          f"shards={scenario.controllers.shards}")
    art = SimLab(scenario).run()

    check("fleet converged through the shard kill", art["ok"],
          str(art.get("notes")))
    m = art["metrics"]
    conv = m.get("pool512_convergence_s")
    check("convergence number present", conv is not None)

    shards = m.get("shards") or {}
    stats = shards.get("stats") or {}
    failovers = stats.get("failovers") or []
    check("the shard kill was recorded", len(failovers) == 1,
          f"failovers={failovers!r}")
    handoff = failovers[0].get("handoff_s") if failovers else None
    check("orphaned partition re-acquired (handoff stamped)",
          handoff is not None, f"failovers={failovers!r}")
    coverage = stats.get("coverage") or {}
    check("every partition covered by a live host",
          bool(coverage) and all(coverage.values()),
          f"coverage={coverage!r}")

    fo = m.get("shard_failover_convergence_s")
    check("shard_failover_convergence_s present", fo is not None)
    if fo is not None and handoff is not None:
        check("failover axis covers the lease handoff",
              fo >= handoff - 0.05, f"fo={fo} handoff={handoff}")

    check("merged /fleet/metrics exposition valid",
          shards.get("merged_exposition_problems") == 0,
          f"problems={shards.get('merged_exposition_problems')!r}")

    out = os.environ.get("SHARD_SMOKE_ARTIFACT")
    if out:
        with open(out, "w") as f:
            json.dump(art, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"artifact written to {out}")

    if _FAILED:
        print(f"shard-smoke: {len(_FAILED)} check(s) FAILED: "
              f"{_FAILED}", file=sys.stderr)
        return 1
    print("shard-smoke: all checks passed "
          f"(pool512_convergence_s={conv}, "
          f"shard_failover_convergence_s={fo}, handoff_s={handoff})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
