#!/bin/bash
# kind-smoke — BASELINE config 1 executed for real: label a node, watch
# the agent reconcile a (synthetic) device and publish the state label.
#
# Two paths:
#   1. kind + docker available: create a throwaway kind cluster, build +
#      load the distroless image, apply the SHIPPED daemonset.yaml
#      (patched only to point the device layer at a synthetic sysfs tree
#      on the kind node — scripts/kind_smoke_patch.py), then drive the
#      label->state round trip with kubectl.
#   2. otherwise (this repo's sandbox has no docker daemon): the
#      manifest-faithful process smoke scripts/kind_smoke_local.py — the
#      same agent entrypoint, env block extracted from the same
#      manifest, real HTTP API server. docs/kind-smoke.md records a
#      captured run.
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v kind >/dev/null && command -v kubectl >/dev/null \
   && command -v docker >/dev/null && docker info >/dev/null 2>&1; then
  CLUSTER=tpu-cc-smoke
  IMAGE=tpu-cc-manager:kind-smoke
  echo "[kind-smoke] kind path: creating cluster $CLUSTER"
  kind create cluster --name "$CLUSTER" --wait 180s
  trap 'kind delete cluster --name "$CLUSTER"' EXIT
  docker build -f deployments/container/Dockerfile.distroless -t "$IMAGE" .
  kind load docker-image "$IMAGE" --name "$CLUSTER"
  NODE=$(kubectl get nodes -o name | head -1 | cut -d/ -f2)
  # synthetic accel tree on the kind node (its /sys has no TPUs)
  docker exec "$CLUSTER-control-plane" sh -c '
    mkdir -p /var/tpu-smoke/sysfs/accel0/device /var/tpu-smoke/dev \
             /var/tpu-smoke/state &&
    printf "0x1ae0\n" > /var/tpu-smoke/sysfs/accel0/device/vendor &&
    printf "0x0063\n" > /var/tpu-smoke/sysfs/accel0/device/device &&
    touch /var/tpu-smoke/dev/accel0'
  # make the DaemonSet's nodeAffinity match the kind node
  kubectl label node "$NODE" cloud.google.com/gke-tpu-accelerator=tpu-v5p-slice
  python3 scripts/kind_smoke_patch.py deployments/manifests/daemonset.yaml \
    "$IMAGE" | kubectl apply -f -
  kubectl -n tpu-system rollout status ds/tpu-cc-manager --timeout=180s
  echo "[kind-smoke] label -> state round trip"
  kubectl label node "$NODE" tpu.google.com/cc.mode=devtools --overwrite
  for _ in $(seq 60); do
    STATE=$(kubectl get node "$NODE" \
      -o jsonpath='{.metadata.labels.tpu\.google\.com/cc\.mode\.state}')
    [ "$STATE" = devtools ] && break
    sleep 2
  done
  [ "$STATE" = devtools ] || {
    echo "[kind-smoke] FAILED: state=$STATE"
    kubectl -n tpu-system logs ds/tpu-cc-manager --tail=100
    exit 1
  }
  echo "[kind-smoke] ALL PASS: cc.mode=devtools -> cc.mode.state=devtools"
else
  echo "[kind-smoke] kind/docker unavailable; running the" \
       "manifest-faithful local smoke (see docs/kind-smoke.md)"
  exec python3 scripts/kind_smoke_local.py
fi
