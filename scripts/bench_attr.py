#!/usr/bin/env python3
"""Automated bench-regression attribution (ISSUE 9 part 3).

The r05 ``real_chip_flip_s`` 1.87 -> 4.43 s regression sat
*unattributed* because attributing it meant a human diffing
BENCH_r*.json extras by hand. Everything needed to attribute is
already stamped into the bench output — the per-phase sub-spans
(``real_chip_phase_s``, ``phase_p50_s``), the pre/post probe
contention sentinel (``real_chip_probe_pre_s`` / ``real_chip_probe_s``),
and the dep pins receipt (``bench_deps``). This tool closes ROADMAP
item 1's loop: given two rounds and the axes that regressed, it diffs
the relevant sub-surface, ranks the contributors, reads the sentinels,
and prints a verdict like::

    real_chip_flip_s 1.87 -> 4.43 (2.4x): wait_ready +2.31s, probe
    flat, deps unchanged -> chip-side (wait_ready)

``scripts/bench_trend.py`` calls :func:`attribute` automatically on
ANY gated-axis failure, so the next regression arrives with its
attribution attached instead of as a mystery. Standalone::

    python scripts/bench_attr.py [repo_root] [--axis AXIS]
"""

import argparse
import glob
import json
import os
import re
import sys

#: regressed axis -> the extras sub-dicts whose numeric entries are
#: that axis's attribution surface, most-specific first. Axes not
#: listed fall back to ``phase_p50_s`` (the per-phase budget every
#: round carries).
AXIS_SOURCES = {
    "real_chip_flip_s": ("real_chip_phase_s",),
    "pool256_convergence_s": ("simlab256",),
    "e2e_convergence_p99_s": ("simlab256",),
    "multichip_flip_s": ("phase_p50_s",),
    "flips_per_min_windowed": ("phase_p50_s",),
    "flips_per_min": ("phase_p50_s",),
    "node_writes_per_flip": ("phase_p50_s",),
    "fleet_scan_warm_s": ("scale256",),
    "planner_tick_100k_s": (),
    "flip_write_rtt_p50_s": ("kube_io", "phase_p50_s"),
    "rollout_advance_p50_s": ("rollout_reactive",),
    "profiler_overhead_pct": ("incident_autopsy",),
    "incident_capture_s": ("incident_autopsy",),
    "p50": ("phase_p50_s",),
}

#: extras key naming the substrate real_chip_phase_s came from
#: ("tpu" | "cpu-pjrt-fallback"); a cross-substrate comparison is
#: flagged in the verdict rather than silently ranked as a phase move
PHASE_SOURCE_KEY = "real_chip_phase_source"

#: probe pair: the real-chip host-contention sentinel (r07+)
PROBE_KEYS = ("real_chip_probe_pre_s", "real_chip_probe_s")

#: a probe move beyond this ratio reads as host contention
PROBE_INFLATED_RATIO = 1.5


def _round_num(path):
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def load_bench(path):
    """Accept both the bare bench JSON line and the driver's
    {"cmd","rc","tail"} envelope (same contract as bench_trend)."""
    with open(path) as f:
        doc = json.load(f)
    if "value" in doc:
        return doc
    for line in reversed((doc.get("tail") or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                return json.loads(line)
            except ValueError:
                return None
    return None


def _numeric_items(d, prefix=""):
    """Flatten one level of nesting into {dotted_key: number}."""
    out = {}
    for k, v in (d or {}).items():
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[prefix + k] = float(v)
        elif isinstance(v, dict) and not prefix:
            out.update(_numeric_items(v, prefix=f"{k}."))
    return out


def rank_deltas(prev_d, cur_d):
    """Ranked contributor list: every key present in either side, by
    descending absolute delta. Entries: {phase, prev, cur, delta}."""
    ranked = []
    for key in sorted(set(prev_d) | set(cur_d)):
        prev_v = prev_d.get(key)
        cur_v = cur_d.get(key)
        delta = (cur_v or 0.0) - (prev_v or 0.0)
        ranked.append({
            "phase": key, "prev": prev_v, "cur": cur_v,
            "delta": round(delta, 4),
        })
    ranked.sort(key=lambda e: abs(e["delta"]), reverse=True)
    return ranked


def _fmt_num(v):
    return "absent" if v is None else f"{v:.4g}"


def _dep_changes(prev_x, cur_x):
    """Changed pins between rounds ({dep: "old -> new"})."""
    prev_deps = prev_x.get("bench_deps") or {}
    cur_deps = cur_x.get("bench_deps") or {}
    out = {}
    for dep in sorted(set(prev_deps) | set(cur_deps)):
        a, b = prev_deps.get(dep, "absent"), cur_deps.get(dep, "absent")
        if a != b:
            out[dep] = f"{a} -> {b}"
    return out


def _probe_status(prev_x, cur_x):
    """'flat' | 'inflated' | 'missing' from the contention-sentinel
    probe pair; inflated means host contention is the lead suspect."""
    seen = False
    for key in PROBE_KEYS:
        a, b = prev_x.get(key), cur_x.get(key)
        if not (isinstance(a, (int, float)) and a > 0
                and isinstance(b, (int, float))):
            continue
        seen = True
        if b > a * PROBE_INFLATED_RATIO:
            return "inflated"
    return "flat" if seen else "missing"


def attribute_axis(axis, prev, cur):
    """One axis's attribution report:
    {axis, prev, cur, ranked, probe, dep_changes, verdict}."""
    prev_x = prev.get("extras") or {}
    cur_x = cur.get("extras") or {}
    if axis == "p50":
        prev_v, cur_v = prev.get("value"), cur.get("value")
    else:
        prev_v, cur_v = prev_x.get(axis), cur_x.get(axis)
    sources = AXIS_SOURCES.get(axis, ("phase_p50_s",))
    ranked = []
    missing = []
    for source in sources:
        prev_d = _numeric_items(prev_x.get(source))
        cur_d = _numeric_items(cur_x.get(source))
        if not prev_d and not cur_d:
            missing.append(source)
            continue
        if not prev_d or not cur_d:
            missing.append(
                f"{source} ({'previous' if not prev_d else 'current'} "
                "round lacks it)"
            )
        ranked.extend(rank_deltas(prev_d, cur_d))
    ranked.sort(key=lambda e: abs(e["delta"]), reverse=True)
    dep_changes = _dep_changes(prev_x, cur_x)
    probe = (_probe_status(prev_x, cur_x)
             if axis.startswith("real_chip") else None)

    # verdict synthesis: deps first (a toolchain change taints every
    # number), then the contention sentinel, then the ranked phases
    parts = []
    top = next((e for e in ranked if e["delta"] and e["prev"] is not None
                and e["cur"] is not None), None)
    if top is not None:
        parts.append(
            f"{top['phase']} {top['delta']:+.4g}"
            + ("s" if top["phase"].endswith("_s")
               or axis.endswith("_s") else "")
        )
    if probe == "inflated":
        parts.append("probe inflated")
    elif probe == "flat":
        parts.append("probe flat")
    if dep_changes:
        parts.append(
            "deps changed ("
            + ", ".join(f"{k} {v}" for k, v in dep_changes.items())
            + ")"
        )
    elif prev_x.get("bench_deps") or cur_x.get("bench_deps"):
        parts.append("deps unchanged")
    if dep_changes:
        conclusion = "suspect toolchain change"
    elif probe == "inflated":
        conclusion = "host contention"
    elif top is not None:
        where = ("chip-side" if axis.startswith("real_chip")
                 else "phase")
        conclusion = f"{where} ({top['phase']})"
    else:
        srcs = ", ".join(missing) or ", ".join(sources) or axis
        conclusion = f"cannot attribute — data missing ({srcs})"
    if axis.startswith("real_chip"):
        src_prev = prev_x.get(PHASE_SOURCE_KEY)
        src_cur = cur_x.get(PHASE_SOURCE_KEY)
        if src_prev and src_cur and src_prev != src_cur:
            # a TPU round next to a CPU-fallback round: the phase
            # deltas compare different substrates and prove nothing
            conclusion += (
                f" [caveat: phase sources differ — {src_prev} vs "
                f"{src_cur}; cross-substrate deltas are not evidence]"
            )
    verdict = (", ".join(parts) + " -> " if parts else "") + conclusion
    return {
        "axis": axis,
        "prev": prev_v,
        "cur": cur_v,
        "ranked": ranked[:8],
        "probe": probe,
        "dep_changes": dep_changes,
        "missing": missing,
        "verdict": verdict,
    }


def axes_from_problems(problems):
    """Map bench_trend problem strings back to axis names (each
    problem line leads with the axis)."""
    axes = []
    for p in problems:
        head = p.split(" ", 1)[0]
        axis = "p50" if head == "p50" else head
        if axis not in axes:
            axes.append(axis)
    return axes


def attribute(prev, cur, axes):
    """Attribution reports for every named axis, in order."""
    return [attribute_axis(axis, prev, cur) for axis in axes]


def format_report(reports):
    """Human lines, one block per axis (what bench_trend prints under
    a failing gate)."""
    lines = []
    for r in reports:
        ratio = ""
        if (isinstance(r["prev"], (int, float)) and r["prev"]
                and isinstance(r["cur"], (int, float))):
            ratio = f" ({r['cur'] / r['prev']:.1f}x)"
        lines.append(
            f"attribution: {r['axis']} {_fmt_num(r['prev'])} -> "
            f"{_fmt_num(r['cur'])}{ratio}: {r['verdict']}"
        )
        for e in r["ranked"][:4]:
            if not e["delta"]:
                continue
            lines.append(
                f"    {e['phase']}: {_fmt_num(e['prev'])} -> "
                f"{_fmt_num(e['cur'])} ({e['delta']:+.4g})"
            )
        for m in r["missing"]:
            lines.append(f"    missing: {m}")
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Attribute bench-axis regressions between the two "
        "newest BENCH_r*.json rounds (ranked per-phase diff + "
        "contention/deps sentinels)."
    )
    ap.add_argument("root", nargs="?", default=".")
    ap.add_argument(
        "--axis", action="append", default=None,
        help="axis to attribute (repeatable; default: every axis "
        "bench_attr knows a source for that both rounds carry)",
    )
    args = ap.parse_args(argv)
    files = sorted(
        glob.glob(os.path.join(args.root, "BENCH_r*.json")),
        key=_round_num,
    )
    if len(files) < 2:
        print("bench-attr: <2 BENCH_r*.json files; nothing to compare")
        return 0
    prev, cur = load_bench(files[-2]), load_bench(files[-1])
    if prev is None or cur is None:
        print("bench-attr: could not parse bench result(s)",
              file=sys.stderr)
        return 2
    axes = args.axis
    if not axes:
        cur_x, prev_x = cur.get("extras") or {}, prev.get("extras") or {}
        axes = [
            a for a in AXIS_SOURCES
            if a != "p50" and (a in cur_x or a in prev_x)
        ] or ["p50"]
    print(f"bench-attr: {os.path.basename(files[-2])} -> "
          f"{os.path.basename(files[-1])}")
    for line in format_report(attribute(prev, cur, axes)):
        print(line)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `bench_attr.py | head` is a normal use
        sys.exit(0)
