#!/bin/bash
# tpu-cc-manager.sh — shell mode engine (TPU-native rebuild of the
# reference's scripts/cc-manager.sh). The native agent can exec this as
# its engine command (the reference Go agent execs cc-manager.sh,
# cmd/main.go:172-182); it is also a standalone operator CLI.
#
#   tpu-cc-manager.sh set-cc-mode [-a | -d <dev>] -m <on|off|devtools|ici>
#   tpu-cc-manager.sh get-cc-mode [-a | -d <dev>]
#   tpu-cc-manager.sh help
#
# Device access goes through the native `tpudevctl` binary (the way the
# reference shells to nvidia_gpu_tools.py, scripts/cc-manager.sh:152),
# honoring TPU_SYSFS_ROOT / TPU_DEV_ROOT / TPU_CC_STATE_DIR /
# CC_CAPABLE_DEVICE_IDS. Kubernetes access goes through curl against
# KUBE_API_HOST:KUBE_API_PORT (kubectl-proxy pattern — the reference used
# kubectl directly, scripts/cc-manager.sh:219).
#
# Env (required like the reference, scripts/cc-manager.sh:5-6):
#   NODE_NAME            — this node
# Optional:
#   KUBE_API_HOST/PORT   — default 127.0.0.1:8001
#   OPERATOR_NAMESPACE   — default tpu-system
#   EVICT_OPERATOR_COMPONENTS — default true
#   TPUDEVCTL            — path to tpudevctl (default: alongside script or PATH)
#   CC_READINESS_FILE    — touched after successful set (reference :536)
#   EMIT_EVENTS          — default true; post core/v1 Events per outcome
#   SLICE_COORDINATION   — "false" opts a slice-labeled node out of the
#                          slice-aware delegation (flip unilaterally)
#   TPU_CC_SLICE_DELEGATE_CMD — printf template exec'd for slice members
#                          (default "python3 -m tpu_cc_manager set-cc-mode -m %s")
set -eo pipefail
[ -n "$TPU_CC_DEBUG" ] && set -x   # reference runs with set -x (:3)

SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
TPUDEVCTL="${TPUDEVCTL:-}"
if [ -z "$TPUDEVCTL" ]; then
  if [ -x "$SCRIPT_DIR/../native/build/tpudevctl" ]; then
    TPUDEVCTL="$SCRIPT_DIR/../native/build/tpudevctl"
  else
    TPUDEVCTL="tpudevctl"
  fi
fi
KUBE_API_HOST="${KUBE_API_HOST:-127.0.0.1}"
KUBE_API_PORT="${KUBE_API_PORT:-8001}"
# KUBE_API_TLS=true: speak HTTPS directly (no kubectl-proxy), verifying
# the cluster CA and sending the service-account token — the same
# direct-TLS posture as the native agent (daemonset-native-tls.yaml).
CURL_OPTS=()
_AUTH_HEADER_FILE=""
_TAINT_ACTIVE=0
_on_exit() {
  # runs on EVERY termination (the signal traps exit, which fires this):
  # - the 0600 token header file must never stay at rest in /tmp;
  # - a set flip taint must never outlive the run (a set -e abort
  #   between _set_flip_taint and _clear_flip_taint would otherwise
  #   leave the node NoSchedule forever — the Python engine's
  #   finally-block parity)
  [ -n "$_AUTH_HEADER_FILE" ] && rm -f "$_AUTH_HEADER_FILE"
  if [ "$_TAINT_ACTIVE" = "1" ]; then
    _TAINT_ACTIVE=0
    _taint_edit remove || true
  fi
}
trap _on_exit EXIT
trap 'exit 129' HUP
trap 'exit 130' INT
trap 'exit 143' TERM

_setup_auth_header() {
  # the token must NEVER ride in argv (visible to the whole host via
  # /proc/<pid>/cmdline while any curl runs): write the header to a
  # 0600 temp file and pass it by reference (-H @file)
  [ -n "${BEARER_TOKEN_FILE:-}" ] && [ -r "${BEARER_TOKEN_FILE:-}" ] || return 0
  _AUTH_HEADER_FILE="$(mktemp)" || return 0
  chmod 600 "$_AUTH_HEADER_FILE"
  printf 'Authorization: Bearer %s' "$(cat "$BEARER_TOKEN_FILE")" \
    > "$_AUTH_HEADER_FILE"
  CURL_OPTS+=(-H "@$_AUTH_HEADER_FILE")
}
if [ "${KUBE_API_TLS:-false}" = "true" ]; then
  API="https://${KUBE_API_HOST}:${KUBE_API_PORT}"
  KUBE_CA_FILE="${KUBE_CA_FILE:-/var/run/secrets/kubernetes.io/serviceaccount/ca.crt}"
  BEARER_TOKEN_FILE="${BEARER_TOKEN_FILE:-/var/run/secrets/kubernetes.io/serviceaccount/token}"
  CURL_OPTS+=(--cacert "$KUBE_CA_FILE")
else
  API="http://${KUBE_API_HOST}:${KUBE_API_PORT}"
fi
_setup_auth_header

kcurl() { curl "${CURL_OPTS[@]}" "$@"; }
OPERATOR_NAMESPACE="${OPERATOR_NAMESPACE:-tpu-system}"
EVICT_OPERATOR_COMPONENTS="${EVICT_OPERATOR_COMPONENTS:-true}"

MODE_LABEL_STATE="tpu.google.com/cc.mode.state"
FLIP_TAINT_KEY="tpu.google.com/cc.mode"   # labels.FLIP_TAINT_KEY parity
PAUSED_STR="paused-for-cc-flip"
COMPONENT_LABELS=(
  "tpu.google.com/pool.deploy.device-plugin"
  "tpu.google.com/pool.deploy.metrics-exporter"
  "tpu.google.com/pool.deploy.dra-driver"
  "tpu.google.com/pool.deploy.workload-validator"
  "tpu.google.com/pool.deploy.node-problem-detector"
)

log() { echo "$(date '+%F %T') tpu-cc-manager.sh $*" >&2; }

_require_node_name() {
  if [ -z "$NODE_NAME" ]; then
    log "ERROR: NODE_NAME env is required"
    exit 1
  fi
  # the drain wait parses PodList JSON with python3; without it every
  # poll would fail silently and the flip would burn the full eviction
  # timeout before dying with a misleading apiserver error — fail fast
  # with the real cause instead
  if [ "$EVICT_OPERATOR_COMPONENTS" = "true" ] && ! command -v python3 >/dev/null; then
    log "ERROR: python3 is required to wait for evicted component pods"
    exit 1
  fi
  # validate gating/holder knobs BEFORE any eviction or device gating, so
  # a typo'd value fails the run cleanly instead of dying mid-flip with
  # components drained and the device locked (Python parity: DeviceError
  # at engine construction)
  case "${TPU_CC_DEVICE_GATING:-chmod}" in
    chmod|""|none|off|false|0) ;;
    *) log "ERROR: unknown TPU_CC_DEVICE_GATING '${TPU_CC_DEVICE_GATING}' (chmod|none)"; exit 1 ;;
  esac
  case "${TPU_CC_HOLDER_CHECK:-proc}" in
    proc|""|none|off|false|0) ;;
    *) log "ERROR: unknown TPU_CC_HOLDER_CHECK '${TPU_CC_HOLDER_CHECK}' (proc|none)"; exit 1 ;;
  esac
}

# ------------------------------------------------------------- k8s (curl)
_patch_node_labels() {
  # $1 = JSON object of labels, e.g. {"k":"v","k2":null}
  kcurl -sf --max-time 30 -X PATCH \
    -H "Content-Type: application/merge-patch+json" \
    -d "{\"metadata\":{\"labels\":$1}}" \
    "$API/api/v1/nodes/$NODE_NAME" > /dev/null
}

_fetch_node_json() {
  kcurl -sf --max-time 30 "$API/api/v1/nodes/$NODE_NAME"
}

_label_from_json() {
  # $1 = node JSON, $2 = label key. k8s label values are [A-Za-z0-9._-],
  # so a regex extraction is exact (no escapes possible). Absent label
  # prints nothing and still returns 0 (set -e safe).
  { printf '%s' "$1" \
    | grep -o "\"$2\"[[:space:]]*:[[:space:]]*\"[^\"]*\"" \
    | head -1 | sed 's/.*:[[:space:]]*"\(.*\)"/\1/'; } || true
}

_set_state_label() {
  _patch_node_labels "{\"$MODE_LABEL_STATE\":\"$1\"}" \
    || log "WARN: could not set state label"
}

_post_event() {
  # $1 = reason, $2 = type (Normal|Warning), $3 = message. Best-effort
  # core/v1 Event against the node, matching the Python agent's emission
  # (agent.py _emit_reconcile_event): namespace "default" because Nodes
  # are cluster-scoped; unique name from PID + epoch + a per-run counter.
  [ "${EMIT_EVENTS:-true}" = "true" ] || return 0
  _EVENT_SEQ=$(( ${_EVENT_SEQ:-0} + 1 ))
  local ts name
  ts="$(date -u '+%Y-%m-%dT%H:%M:%SZ')"
  name="$NODE_NAME.cc-engine.$$.$(date +%s).$_EVENT_SEQ"
  kcurl -sf --max-time 10 -X POST -H "Content-Type: application/json" \
    -d "{\"kind\":\"Event\",\"apiVersion\":\"v1\",\
\"metadata\":{\"name\":\"$name\",\"namespace\":\"default\"},\
\"involvedObject\":{\"kind\":\"Node\",\"apiVersion\":\"v1\",\"name\":\"$NODE_NAME\"},\
\"reason\":\"$1\",\"message\":\"$3\",\"type\":\"$2\",\
\"source\":{\"component\":\"tpu-cc-manager.sh\",\"host\":\"$NODE_NAME\"},\
\"firstTimestamp\":\"$ts\",\"lastTimestamp\":\"$ts\",\"count\":1}" \
    "$API/api/v1/namespaces/default/events" > /dev/null \
    || log "WARN: could not post event $1"
}

# -------------------------------------------------- eviction (pause labels)
# reference scripts/cc-manager.sh:173-334
_evict_components() {
  [ "$EVICT_OPERATOR_COMPONENTS" = "true" ] || return 0
  local node_json patch="{" first=1 key val
  # an unreadable node is NOT "no components deployed": proceeding would
  # flip over possibly-running workloads
  node_json="$(_fetch_node_json)" || {
    log "ERROR: cannot read node $NODE_NAME for component eviction"
    return 1
  }
  for key in "${COMPONENT_LABELS[@]}"; do
    val="$(_label_from_json "$node_json" "$key")"
    if [ -n "$val" ] && [ "$val" != "false" ] && [[ "$val" != ${PAUSED_STR}* ]]; then
      [ $first -eq 0 ] && patch+=","
      patch+="\"$key\":\"${PAUSED_STR}_${val}\""
      first=0
    fi
  done
  patch+="}"
  if [ "$patch" != "{}" ]; then
    log "pausing components: $patch"
    _patch_node_labels "$patch"
    _wait_components_gone
  fi
}

_wait_components_gone() {
  # poll until no component pods remain on this node (timeout 300s like
  # kubectl wait --timeout=5m, reference :275). Timeout with pods KNOWN
  # present is warn-and-continue (reference gpu_operator_eviction.py:
  # 205-207 parity); timeout with the pod list NEVER obtained is a
  # failure — flipping with workloads possibly still bound to the TPU is
  # the one wrong answer.
  local deadline=$((SECONDS + ${EVICTION_TIMEOUT_S:-300}))
  local apps="tpu-device-plugin tpu-metrics-exporter tpu-dra-driver tpu-workload-validator tpu-node-problem-detector"
  local ever_listed_all=0
  while [ $SECONDS -lt $deadline ]; do
    local remaining=0 app listed_all=1
    for app in $apps; do
      # a failed/timed-out list means UNKNOWN, not zero. Count list items
      # by parsing the PodList JSON: a real apiserver omits TypeMeta
      # (kind/apiVersion) on list items, so grepping for '"kind":"Pod"'
      # would always count 0 against a real cluster and let the flip
      # proceed over still-terminating pods.
      local body n
      if body=$(kcurl -sf --max-time 30 "$API/api/v1/namespaces/$OPERATOR_NAMESPACE/pods?labelSelector=app%3D$app&fieldSelector=spec.nodeName%3D$NODE_NAME") \
         && n=$(printf '%s' "$body" | python3 -c 'import json,sys; print(len(json.load(sys.stdin).get("items") or []))' 2>/dev/null); then
        remaining=$((remaining + n))
      else
        listed_all=0
      fi
    done
    [ "$listed_all" -eq 1 ] && ever_listed_all=1
    [ "$remaining" -eq 0 ] && [ "$listed_all" -eq 1 ] && return 0
    sleep "${EVICTION_POLL_S:-2}"
  done
  if [ "$ever_listed_all" -eq 0 ]; then
    log "ERROR: could not list component pods before the eviction deadline"
    return 1
  fi
  log "WARN: timed out waiting for component pods to leave; continuing"
}

_reschedule_components() {
  [ "$EVICT_OPERATOR_COMPONENTS" = "true" ] || return 0
  local node_json patch="{" first=1 key val
  node_json="$(_fetch_node_json)"
  for key in "${COMPONENT_LABELS[@]}"; do
    val="$(_label_from_json "$node_json" "$key")"
    if [[ "$val" == ${PAUSED_STR}_* ]]; then
      [ $first -eq 0 ] && patch+=","
      patch+="\"$key\":\"${val#${PAUSED_STR}_}\""
      first=0
    fi
  done
  patch+="}"
  if [ "$patch" != "{}" ]; then
    log "restoring components: $patch"
    _patch_node_labels "$patch"
  fi
}

_taint_edit() {
  # $1 = add|remove the flip taint (parity with drain.NodeFlipTaint):
  # spec.taints is a list, so this is read-edit-REPLACE with the read
  # resourceVersion (PUT; 409 retried) — a merge patch would wipe
  # taints other controllers add concurrently.
  local action="$1" attempt node_json new_json rc code
  for attempt in 1 2 3 4 5 6 7 8; do
    node_json="$(_fetch_node_json)" || return 1
    rc=0
    new_json="$(printf '%s' "$node_json" | python3 -c "
import json, sys
node = json.load(sys.stdin)
action, key = sys.argv[1], sys.argv[2]
taints = node.setdefault('spec', {}).get('taints') or []
has = any(t.get('key') == key for t in taints)
if action == 'add':
    if has: sys.exit(3)
    taints = taints + [
        {'key': key, 'value': 'flipping', 'effect': 'NoSchedule'}]
else:
    if not has: sys.exit(3)
    taints = [t for t in taints if t.get('key') != key]
node['spec']['taints'] = taints
print(json.dumps(node))
" "$action" "$FLIP_TAINT_KEY")" || rc=$?
    [ "$rc" -eq 3 ] && return 0   # already in the desired state
    [ "$rc" -ne 0 ] && return 1
    code="$(kcurl -s -o /dev/null -w '%{http_code}' --max-time 30 \
      -X PUT -H 'Content-Type: application/json' \
      -d "$new_json" "$API/api/v1/nodes/$NODE_NAME")" || return 1
    [ "$code" = "200" ] && return 0
    [ "$code" = "409" ] || return 1   # lost the CAS: re-read and retry
  done
  return 1
}

_set_flip_taint() {
  # best-effort (Python engine parity): an untaintable node still gets
  # the drain + gate protections
  if _taint_edit add; then _TAINT_ACTIVE=1; else
    log "WARN: could not set flip taint"
  fi
}

_clear_flip_taint() {
  # flag drops only on SUCCESSFUL removal: a failed clear here must
  # leave the _on_exit safety net armed to retry
  if _taint_edit remove; then
    _TAINT_ACTIVE=0
  else
    log "WARN: could not clear flip taint"
  fi
}

# always restore on failure (reference _exit_failed, :210-215)
_exit_failed() {
  _set_state_label "failed"
  _post_event "CCModeFailed" "Warning" "cc mode flip failed on $NODE_NAME"
  _reschedule_components
  _clear_flip_taint
  exit 1
}

# ----------------------------------------------------------------- devices
_all_devices() {
  # prints "<dev_path> <is_switch> <capable>" per device
  "$TPUDEVCTL" list | awk '{print $1, $4, $5}'
}

_unbind_device_from_driver() {
  # sysfs driver unbind before the flip (reference :40-50); best-effort —
  # TPU VMs typically have no unbind attribute
  local dev_name sysfs_dev
  dev_name="$(basename "$1")"
  sysfs_dev="${TPU_SYSFS_ROOT:-/sys/class/accel}/$dev_name/device"
  if [ -w "$sysfs_dev/driver/unbind" ] 2>/dev/null; then
    echo "$dev_name" > "$sysfs_dev/driver/unbind" || true
  fi
}

_gating_enabled() {
  # value already validated in _require_node_name; unknown values were a
  # loud config error before any drain/gating side effects
  case "${TPU_CC_DEVICE_GATING:-chmod}" in
    none|off|false|0) return 1 ;;
    *) return 0 ;;
  esac
}

_gate_lock() {
  # workload-visible gating (parity with device/gate.py): lock the node
  # for the duration of the flip — a workload that could open the chip
  # before the flip observably cannot mid-flip. Fail-SECURE both ways:
  # a chmod failure on an existing node aborts the flip (refusing to
  # flip an ungated device), and a failed flip leaves the node locked.
  _gating_enabled || return 0
  if [ -e "$1" ]; then
    chmod 000 "$1" || { log "ERROR: cannot gate $1; refusing to flip"; return 1; }
  fi
}

_gate_apply() {
  # $1 dev, $2 effective cc mode: encode the verified mode in the node's
  # permission bits (on=0600 off=0666 devtools=0660)
  _gating_enabled || return 0
  [ -e "$1" ] || return 0
  local perms
  case "$2" in
    off) perms=666 ;;
    devtools) perms=660 ;;
    *) perms=600 ;;
  esac
  chmod "$perms" "$1" || true
}

_publish_evidence() {
  # per-flip attestation evidence (parity with the Python engines):
  # build the document in python (shared wire format, see
  # tpu_cc_manager/evidence.py), publish through this engine's own curl
  # path. Best-effort — evidence never fails a flip.
  [ "${TPU_CC_EVIDENCE:-true}" = "true" ] || return 0
  local patch
  if ! patch="$(python3 -m tpu_cc_manager.evidence 2>/dev/null)"; then
    log "WARN: evidence build failed; skipping evidence annotation"
    return 0
  fi
  kcurl -sf --max-time 30 -X PATCH \
    -H "Content-Type: application/merge-patch+json" \
    -d "$patch" "$API/api/v1/nodes/$NODE_NAME" > /dev/null \
    || log "WARN: evidence annotation publish failed"
}

_gate_cc_target() {
  # effective cc domain value for a node-level mode
  case "$1" in
    ici|off) echo off ;;
    *) echo "$1" ;;
  esac
}

_device_holders() {
  # pids (with comm) holding an open fd on $1 — the host-side ground
  # truth of "who has the chip". Excludes this engine process. ONE find
  # exec scans every fd table (-lname matches the symlink target), not
  # one readlink per fd — the poll loop below runs this every 0.5s.
  local real esc link pid last=""
  real="$(readlink -f "$1" 2>/dev/null)" || return 0
  [ -e "$real" ] || return 0
  # -lname fnmatches: escape glob metacharacters or a path containing
  # [ ] * ? silently matches nothing and the hold check fails OPEN
  esc="$(printf '%s' "$real" | sed 's/[][*?\\]/\\&/g')"
  find /proc/[0-9]*/fd -lname "$esc" 2>/dev/null | while IFS= read -r link; do
    pid="${link#/proc/}"; pid="${pid%%/*}"
    [ "$pid" = "$$" ] && continue
    [ "$pid" = "$last" ] && continue   # fd entries are per-pid contiguous
    last="$pid"
    echo "$(cat "/proc/$pid/comm" 2>/dev/null || echo '?')[$pid]"
  done
}

_hold_wait_s_int() {
  # TPU_CC_HOLD_WAIT_S is shared with the Python engine, which accepts
  # fractions; bash arithmetic doesn't — round up, and clamp to >=1
  # because `timeout 0` means UNBOUNDED to GNU timeout (a hung restart
  # hook must never hang the flip)
  local w="${TPU_CC_HOLD_WAIT_S:-30}"
  case "$w" in
    *.*) w="${w%%.*}"; [ -z "$w" ] && w=0; w=$((w + 1)) ;;
  esac
  [ "$w" -ge 1 ] 2>/dev/null || w=1
  echo "$w"
}

_ensure_device_free() {
  # exclusive-hold guarantee (parity with device/holders.py): never
  # commit a staged mode while a foreign process holds the device. If
  # TPU_CC_RUNTIME_RESTART_CMD is set it is run once (bounded by the
  # wait window — a hung hook must not hang the flip) to make the
  # external runtime let go, then we poll for TPU_CC_HOLD_WAIT_S.
  case "${TPU_CC_HOLDER_CHECK:-proc}" in
    none|off|false|0) return 0 ;;
  esac
  local dev="$1" holders wait_s
  wait_s="$(_hold_wait_s_int)"
  holders="$(_device_holders "$dev")"
  [ -z "$holders" ] && return 0
  if [ -n "${TPU_CC_RUNTIME_RESTART_CMD:-}" ]; then
    log "WARN: $dev held by: $holders; running runtime restart hook"
    timeout "$wait_s" bash -c "$TPU_CC_RUNTIME_RESTART_CMD" \
      || { log "ERROR: runtime restart hook failed or timed out"; return 1; }
  fi
  local deadline=$((SECONDS + wait_s))
  while [ $SECONDS -lt $deadline ]; do
    holders="$(_device_holders "$dev")"
    [ -z "$holders" ] && return 0
    sleep 0.5
  done
  log "ERROR: $dev still held by: $holders; refusing to flip under a live holder"
  return 1
}

_set_device_mode() {
  # $1 dev, $2 mode: gate + discard stale intent, stage the right
  # domains, commit (=reset), verify, regate
  # (reference set_gpu_cc_mode, :384-405)
  local dev="$1" mode="$2" cc_target ici_target
  case "$mode" in
    ici) cc_target="off"; ici_target="on" ;;
    on|devtools) cc_target="$mode"; ici_target="off" ;;
    off) cc_target="off"; ici_target="off" ;;
  esac
  _gate_lock "$dev" || return 1
  "$TPUDEVCTL" discard "$dev" || return 1
  "$TPUDEVCTL" stage "$dev" cc "$cc_target" || return 1
  "$TPUDEVCTL" stage "$dev" ici "$ici_target" || return 1
  _unbind_device_from_driver "$dev"
  _ensure_device_free "$dev" || return 1
  "$TPUDEVCTL" commit "$dev" || return 1
  local got_cc got_ici
  got_cc="$("$TPUDEVCTL" query "$dev" cc)"
  got_ici="$("$TPUDEVCTL" query "$dev" ici)"
  if [ "$got_cc" != "$cc_target" ] || [ "$got_ici" != "$ici_target" ]; then
    log "ERROR: $dev verify mismatch: cc=$got_cc (want $cc_target) ici=$got_ici (want $ici_target)"
    return 1
  fi
  _gate_apply "$dev" "$cc_target"
  return 0
}

_device_at_mode() {
  local dev="$1" mode="$2" cc ici
  cc="$("$TPUDEVCTL" query "$dev" cc)"
  ici="$("$TPUDEVCTL" query "$dev" ici)"
  case "$mode" in
    ici)  [ "$cc" = "off" ] && [ "$ici" = "on" ] ;;
    off)  [ "$cc" = "off" ] && [ "$ici" = "off" ] ;;
    *)    [ "$cc" = "$mode" ] && [ "$ici" = "off" ] ;;
  esac
}

# ---------------------------------------------------- slice coherence
SLICE_LABEL="tpu.google.com/cc.slice"

_slice_guard() {
  # Multi-host slice coherence on the bash/native path. The repo's
  # flagship slice guarantee (slice_coord.py:19-42) is that members of
  # one slice flip all-or-nothing; this engine has no quorum protocol,
  # so a slice-labeled node must NEVER flip unilaterally from here.
  # Resolution order:
  #   SLICE_COORDINATION=false  -> explicit opt-out, flip locally
  #   slice label absent        -> plain node, flip locally
  #   else                      -> exec the slice-aware Python one-shot
  #                                (same delegation pattern as doctor,
  #                                native/agent.cpp g_doctor_cmd); if
  #                                it is unavailable, REFUSE loudly —
  #                                a half-flipped slice is worse than
  #                                a failed reconcile
  local mode="$1" target_dev="$2"
  [ "${SLICE_COORDINATION:-}" = "false" ] && return 0
  local node_json slice_id
  if ! node_json="$(_fetch_node_json)"; then
    # FAIL CLOSED: an unreadable node means we cannot prove this isn't
    # a slice member, and a unilateral flip on one is the exact
    # half-flipped state this guard exists to prevent (same refusal
    # _evict_components makes on an unreadable node)
    log "ERROR: cannot read node to check slice membership; refusing" \
        "to flip. Set SLICE_COORDINATION=false to override explicitly."
    _post_event "CCSliceAborted" "Warning" \
      "refusing flip: node unreadable, slice membership unknown"
    exit 1
  fi
  slice_id="$(_label_from_json "$node_json" "$SLICE_LABEL")"
  [ -z "$slice_id" ] && return 0
  if [ -n "$target_dev" ]; then
    # a single-device flip on a slice member can't be quorum-coherent
    # (the protocol flips whole nodes), and silently broadening it to
    # all devices would be worse — refuse explicitly
    log "ERROR: per-device flip (-d $target_dev) refused on slice" \
        "'$slice_id' member; slice rounds are whole-node. Use -a, or" \
        "SLICE_COORDINATION=false to override explicitly."
    _post_event "CCSliceAborted" "Warning" \
      "refusing per-device flip on slice '$slice_id' member"
    exit 1
  fi
  local delegate="${TPU_CC_SLICE_DELEGATE_CMD:-python3 -m tpu_cc_manager set-cc-mode -m %s}"
  local delegate_bin="${delegate%% *}"
  if [ -n "$delegate" ] && command -v "$delegate_bin" >/dev/null 2>&1; then
    log "slice '$slice_id' member: delegating to the slice-aware engine"
    local cmd
    # shellcheck disable=SC2059
    printf -v cmd "$delegate" "$mode"
    # exec replaces this process: exactly one engine owns the flip,
    # and the delegate's exit code IS this engine's exit code
    SLICE_COORDINATION=true exec $cmd
  fi
  log "ERROR: node is in slice '$slice_id' but the slice-aware engine" \
      "('$delegate_bin') is unavailable; refusing a unilateral flip." \
      "Set SLICE_COORDINATION=false to override explicitly."
  _post_event "CCSliceAborted" "Warning" \
    "refusing unilateral flip on slice '$slice_id' member: no slice-aware engine available"
  exit 1
}

# ---------------------------------------------------------------- commands
_parse_mode() {
  # reference _parse_mode (:125-134): reject unknown values loudly
  case "$1" in
    on|off|devtools|ici) return 0 ;;
    *) log "ERROR: invalid mode '$1' (must be on|off|devtools|ici)"; exit 1 ;;
  esac
}

set_cc_mode() {
  local mode="$1" target_dev="$2"
  _require_node_name
  _slice_guard "$mode" "$target_dev"
  local devices=()
  while read -r dev is_switch capable; do
    [ -n "$target_dev" ] && [ "$dev" != "$target_dev" ] && continue
    # mixed-capability bailout (reference main.py:214-217 semantics);
    # fatal, but still visible cluster-wide (Python agent parity: the
    # "fatal" outcome emits CCModeFailed too)
    if [ "$capable" = "0" ] && [ "$is_switch" = "0" ] && [ "$mode" != "off" ]; then
      log "ERROR: $dev is not CC-capable; refusing mode '$mode' on a mixed node"
      _post_event "CCModeFailed" "Warning" \
        "refusing mode '$mode': non-capable device on a mixed node"
      exit 1
    fi
    devices+=("$dev")
  done < <(_all_devices)

  if [ ${#devices[@]} -eq 0 ]; then
    log "no TPU devices found; nothing to do"   # reference :338-340
    return 0
  fi

  # idempotent fast path (reference :342-346)
  local all_set=1 dev
  for dev in "${devices[@]}"; do
    _device_at_mode "$dev" "$mode" || { all_set=0; break; }
  done
  if [ $all_set -eq 1 ]; then
    log "all ${#devices[@]} device(s) already in mode '$mode'"
    # re-assert gate perms even on the no-op path (Python engine parity):
    # bookkeeping being converged doesn't mean /dev perms still are
    for dev in "${devices[@]}"; do
      _gate_apply "$dev" "$(_gate_cc_target "$mode")"
    done
    # a leftover flip taint from a crashed earlier run must not survive
    # a converged reconcile — this is the self-heal for the leak class
    _clear_flip_taint
    _set_state_label "$mode"
    _publish_evidence
    _post_event "CCModeApplied" "Normal" \
      "cc mode '$mode' already set on ${#devices[@]} device(s) (no-op)"
    return 0
  fi

  # taint first (Python engine parity): new TPU pods must stop landing
  # on a node whose devices are about to be gated
  _set_flip_taint
  _evict_components || _exit_failed
  for dev in "${devices[@]}"; do
    if ! _set_device_mode "$dev" "$mode"; then
      log "ERROR: failed to set mode on $dev"
      _exit_failed
    fi
  done
  # measured flip history (tpu_cc_manager/attest.py): a REAL transition
  # happened on this path (the idempotent fast path returned earlier),
  # so extend the PCR BEFORE publishing evidence — the quote attached
  # by the evidence build must already see this flip. Best-effort, and
  # a no-op unless TPU_CC_ATTESTATION configures a provider.
  python3 -m tpu_cc_manager.attest --extend "$mode" 2>/dev/null \
    || log "WARN: attestation extend failed (measured history will lag)"
  _set_state_label "$mode"
  _publish_evidence
  _post_event "CCModeApplied" "Normal" \
    "cc mode '$mode' applied to ${#devices[@]} device(s)"
  _reschedule_components
  _clear_flip_taint
  if [ -n "$CC_READINESS_FILE" ]; then
    mkdir -p "$(dirname "$CC_READINESS_FILE")" && touch "$CC_READINESS_FILE"
  fi
  log "mode '$mode' applied to ${#devices[@]} device(s)"
}

get_cc_mode() {
  local target_dev="$1"
  while read -r dev is_switch capable; do
    [ -n "$target_dev" ] && [ "$dev" != "$target_dev" ] && continue
    local cc="-" ici="-"
    if [ "$is_switch" = "0" ]; then cc="$("$TPUDEVCTL" query "$dev" cc)"; fi
    ici="$("$TPUDEVCTL" query "$dev" ici)"
    echo "$dev cc=$cc ici=$ici"
  done < <(_all_devices)
}

usage() {
  sed -n '2,12p' "$0" | sed 's/^# \{0,1\}//'
}

# ------------------------------------------------------- arg parsing
# (reference scripts/cc-manager.sh:472-533)
cmd="$1"; shift || true
MODE="" DEV="" ALL=0
while getopts ":am:d:" opt 2>/dev/null; do
  case "$opt" in
    a) ALL=1 ;;
    m) MODE="$OPTARG" ;;
    d) DEV="$OPTARG" ;;
    *) ;;
  esac
done

case "$cmd" in
  set-cc-mode)
    [ -z "$MODE" ] && { log "ERROR: -m <mode> is required"; exit 1; }
    _parse_mode "$MODE"
    set_cc_mode "$MODE" "$DEV"
    ;;
  get-cc-mode)
    get_cc_mode "$DEV"
    ;;
  help|--help|-h|"")
    usage
    ;;
  *)
    log "ERROR: unknown command '$cmd'"
    usage
    exit 1
    ;;
esac
