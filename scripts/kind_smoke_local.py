#!/usr/bin/env python3
"""Manifest-faithful local smoke — the no-docker fallback of
`make kind-smoke` (BASELINE config 1: "main.py dry-run reconcile on kind
cluster, mocked device list"; reference README_PYTHON.md:77-102 is the
manual flow this scripts).

Where the kind path schedules the shipped DaemonSet on a kind node, this
fallback reproduces the same wiring as host processes:

- the agent's environment is EXTRACTED FROM deployments/manifests/
  daemonset.yaml (the literal env block the DaemonSet injects), so the
  manifest's configuration is what gets smoke-tested;
- the Kubernetes API server is the real-wire FakeApiServer (HTTP);
- the device layer scans a synthetic accel sysfs tree (the manifest's
  /sys hostPath has no TPUs on a workstation either — kind would use the
  same TPU_SYSFS_ROOT override, scripts/kind_smoke_patch.py);
- the agent is the real entrypoint (`python -m tpu_cc_manager`) run as a
  subprocess.

Substitutions a kind cluster would otherwise provide, each logged in the
transcript: NODE_NAME (fieldRef spec.nodeName -> smoke node name),
in-cluster service-account auth (-> kubeconfig file), hostPath volumes
(-> scratch dirs). Exit code 0 = the label->state round trip converged.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_cc_manager import labels as L  # noqa: E402
from tpu_cc_manager.modes import Mode  # noqa: E402
from tpu_cc_manager.k8s.apiserver import FakeApiServer  # noqa: E402
from tpu_cc_manager.k8s.objects import make_node  # noqa: E402

NODE = "kind-smoke-node"


def log(msg):
    print(f"[kind-smoke-local] {msg}", flush=True)


def manifest_env():
    """The agent container's env block, exactly as the DaemonSet ships it."""
    path = os.path.join(REPO, "deployments", "manifests", "daemonset.yaml")
    for doc in yaml.safe_load_all(open(path)):
        if doc and doc.get("kind") == "DaemonSet":
            ctr = doc["spec"]["template"]["spec"]["containers"][0]
            env = {}
            for e in ctr.get("env", []):
                if "value" in e:
                    env[e["name"]] = e["value"]
                elif e.get("valueFrom", {}).get("fieldRef", {}).get(
                    "fieldPath"
                ) == "spec.nodeName":
                    env[e["name"]] = NODE  # kubelet downward API analog
            return env
    raise SystemExit("no DaemonSet in manifest")


def accel_tree(root):
    sysfs = os.path.join(root, "sysfs")
    dev = os.path.join(root, "dev")
    os.makedirs(dev, exist_ok=True)
    for i in range(2):
        d = os.path.join(sysfs, f"accel{i}", "device")
        os.makedirs(d)
        open(os.path.join(d, "vendor"), "w").write("0x1ae0\n")
        open(os.path.join(d, "device"), "w").write("0x0063\n")
        open(os.path.join(dev, f"accel{i}"), "w").close()
    return sysfs, dev


def state_label(store):
    return store.get_node(NODE)["metadata"]["labels"].get(
        L.CC_MODE_STATE_LABEL
    )


def wait_state(store, target, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if state_label(store) == target:
            return True
        time.sleep(0.2)
    return False


def main():
    env_from_manifest = manifest_env()
    log(f"env from daemonset.yaml: {json.dumps(env_from_manifest)}")

    with tempfile.TemporaryDirectory(prefix="kind-smoke-") as scratch:
        sysfs, dev = accel_tree(scratch)
        server = FakeApiServer().start()
        store = server.store
        # the node the DaemonSet's affinity would match (accelerator
        # label present, any value) with the manifest's component label
        # so the "components" drain strategy has something to pause
        store.add_node(
            make_node(
                NODE,
                labels={
                    L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
                    L.COMPONENT_LABELS[0]: "true",
                },
            )
        )
        kubeconfig = os.path.join(scratch, "kubeconfig.yaml")
        with open(kubeconfig, "w") as f:
            yaml.safe_dump(
                {
                    "apiVersion": "v1",
                    "kind": "Config",
                    "current-context": "kind-smoke",
                    "contexts": [
                        {
                            "name": "kind-smoke",
                            "context": {"cluster": "local", "user": "dev"},
                        }
                    ],
                    "clusters": [
                        {
                            "name": "local",
                            "cluster": {
                                "server": f"http://127.0.0.1:{server.port}"
                            },
                        }
                    ],
                    "users": [{"name": "dev", "user": {}}],
                },
                f,
            )

        env = dict(os.environ)
        env.update(env_from_manifest)
        readiness = os.path.join(
            scratch, env_from_manifest["CC_READINESS_FILE"].lstrip("/")
        )
        # the production security posture, not the keyless default:
        # the evidence-key Secret is "mounted" (the manifests point
        # TPU_CC_EVIDENCE_KEY_FILE at it) and the platform mints
        # identities (fake provider standing in for the GCE metadata
        # server) — so the smoke proves the keyed + identity-bearing
        # chain end-to-end, the combination round 3 never exercised
        evidence_key = os.path.join(scratch, "evidence-key")
        with open(evidence_key, "w") as f:
            f.write("smoke-pool-key")
        identity_key = os.path.join(scratch, "identity-key")
        with open(identity_key, "w") as f:
            f.write("smoke-identity-key")
        # the TEE rung (round 5): the fake TPM measures every real
        # flip, so the closing node-root drill can prove a forged
        # statefile is flagged even when re-signed with the pool key
        tpm_key = os.path.join(scratch, "tpm-key")
        with open(tpm_key, "w") as f:
            f.write("smoke-aik-key")
        env.update(
            KUBECONFIG=kubeconfig,  # kind: in-cluster SA
            PYTHONPATH=REPO,
            TPU_SYSFS_ROOT=sysfs,  # kind: /var/tpu-smoke hostPath
            TPU_DEV_ROOT=dev,
            TPU_CC_STATE_DIR=os.path.join(scratch, "state"),
            CC_READINESS_FILE=readiness,  # kind: validations hostPath
            TPU_CC_EVIDENCE_KEY_FILE=evidence_key,  # kind: Secret mount
            TPU_CC_IDENTITY="fake",
            TPU_CC_IDENTITY_KEY_FILE=identity_key,
            TPU_CC_ATTESTATION="fake",
            TPU_CC_TPM_STATE_DIR=os.path.join(scratch, "tpm"),
            TPU_CC_TPM_KEY_FILE=tpm_key,
        )
        log("starting agent: python -m tpu_cc_manager "
            f"(NODE_NAME={NODE}, DRAIN_STRATEGY="
            f"{env_from_manifest.get('DRAIN_STRATEGY')})")
        agent_log = open(os.path.join(scratch, "agent.log"), "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpu_cc_manager"],
            env=env, stdout=agent_log, stderr=subprocess.STDOUT, cwd=REPO,
        )
        failures = []
        try:
            # 1. no cc.mode label -> DEFAULT_CC_MODE from the manifest
            default = env_from_manifest.get("DEFAULT_CC_MODE", "on")
            if wait_state(store, default):
                log(f"PASS initial reconcile: cc.mode.state={default} "
                    "(manifest DEFAULT_CC_MODE, label absent)")
            else:
                failures.append("initial default reconcile")
            # the readiness touch happens after the reconcile returns
            # (evidence build sits between the state label and it) —
            # poll instead of racing a snapshot
            deadline = time.monotonic() + 10
            while (time.monotonic() < deadline
                   and not os.path.exists(readiness)):
                time.sleep(0.1)
            if os.path.exists(readiness):
                log(f"PASS readiness file created: {readiness}")
            else:
                failures.append("readiness file")

            # 2. health endpoints on the manifest's HEALTH_PORT
            port = env_from_manifest.get("HEALTH_PORT")
            if port:
                for ep in ("healthz", "readyz"):
                    code = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/{ep}", timeout=5
                    ).status
                    log(f"PASS /{ep} -> {code} (manifest probe path)")
                # 2b. the LIVE /metrics surface must parse under the
                # strict text-format validator (duplicate HELP/TYPE,
                # label escaping, histogram bucket monotonicity) — the
                # CI exposition gate, against the real agent, not a
                # unit fixture
                from tpu_cc_manager.obs import validate_exposition

                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ).read().decode()
                problems = validate_exposition(body)
                if not problems:
                    log("PASS /metrics parses as strict Prometheus "
                        "text exposition")
                else:
                    failures.append(
                        f"metrics exposition invalid: {problems[:3]}")
                # 2c. the flight recorder's on-demand snapshot route
                fr = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/flightrec",
                    timeout=5,
                ).read())
                if ("spans" in fr and "host_samples" in fr
                        and "events" in fr
                        and fr.get("flightrec_version") == 1):
                    log("PASS /debug/flightrec serves the live "
                        "black-box snapshot")
                else:
                    failures.append(
                        f"flightrec route shape: {sorted(fr)[:8]}")
                # 2d. the time-series ring route (tsring.py, ISSUE 9)
                ts = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/timeseries",
                    timeout=5,
                ).read())
                if (ts.get("tsring_version") == 1
                        and "samples" in ts and "window_s" in ts):
                    log("PASS /debug/timeseries serves the live "
                        "metric history ring")
                else:
                    failures.append(
                        f"timeseries route shape: {sorted(ts)[:8]}")
                # 2d-bis. the ?metric= family filter and the anomaly
                # watchdog's incident route (ISSUE 15)
                tsf = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/timeseries"
                    "?metric=tpu_cc_reconciles_total",
                    timeout=5,
                ).read())
                filtered_fams = set(
                    (tsf.get("derived") or {}).get("counters") or {}
                ) | set((tsf.get("derived") or {}).get("histograms")
                        or {})
                if (tsf.get("metric_prefix")
                        == "tpu_cc_reconciles_total"
                        and filtered_fams
                        <= {"tpu_cc_reconciles_total"}):
                    log("PASS /debug/timeseries?metric= narrows to "
                        "the requested family")
                else:
                    failures.append(
                        f"timeseries filter: {sorted(filtered_fams)[:4]}")
                inc = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/incidents",
                    timeout=5,
                ).read())
                if (inc.get("watchdog_version") == 1
                        and "incidents" in inc and "series" in inc):
                    log("PASS /debug/incidents serves the anomaly "
                        "watchdog surface")
                else:
                    failures.append(
                        f"incidents route shape: {sorted(inc)[:8]}")
                # 2e. the fleet observatory over HTTP (fleetobs.py,
                # ISSUE 9): scrape the agent's live /metrics as a real
                # HTTP target, merge (fleet of one), and re-validate
                # the AGGREGATED exposition — the kind-smoke half of
                # the scrape contract (simlab covers in-process)
                try:
                    from tpu_cc_manager import fleetobs
                except ImportError:
                    fleetobs = None
                    log("SKIP fleetobs HTTP scrape (pyyaml not "
                        "installed)")
                if fleetobs is not None:
                    try:
                        objectives = fleetobs.load_slo(
                            fleetobs.default_slo_path())
                    except ImportError:
                        objectives = None
                        log("SKIP fleetobs HTTP scrape (pyyaml not "
                            "installed)")
                    except fleetobs.SloError as e:
                        # a broken committed slo.yaml is a smoke
                        # FAILURE like any other check, never an
                        # uncaught traceback that aborts the rest
                        objectives = None
                        failures.append(
                            f"fleetobs slo.yaml invalid: {e}")
                    if objectives is not None:
                        observer = fleetobs.FleetObserver(objectives)
                        observer.observe(
                            [f"http://127.0.0.1:{port}/metrics"] * 2
                        )
                        if (not observer.aggregation_problems
                                and observer.metrics.scrapes_total
                                .value("ok") == 2
                                and not observer.problems()):
                            log("PASS fleetobs scrapes /metrics over "
                                "HTTP, merged exposition validates, "
                                "no SLO burns")
                        else:
                            failures.append(
                                "fleetobs HTTP scrape: "
                                f"agg={observer.aggregation_problems[:2]} "
                                f"problems={observer.problems()[:2]}")

            # 3. label -> state round trip (the core of config 1)
            for mode in ("devtools", "ici", "off"):
                store.set_node_labels(NODE, {L.CC_MODE_LABEL: mode})
                if wait_state(store, mode):
                    log(f"PASS round trip: cc.mode={mode} -> "
                        f"cc.mode.state={mode}")
                else:
                    failures.append(f"round trip {mode}")

            # 4. invalid mode -> visible failure, agent stays up
            store.set_node_labels(NODE, {L.CC_MODE_LABEL: "bogus"})
            if wait_state(store, "failed"):
                log("PASS invalid mode: cc.mode.state=failed")
            else:
                failures.append("invalid mode visibility")
            if proc.poll() is None:
                log("PASS agent still running after invalid mode")
            else:
                failures.append("agent exited")

            # 5. reconcile Events recorded (kubectl-describe-node
            # analog). Poll: the agent POSTs the event after the state
            # label lands, so a single snapshot would race.
            deadline = time.monotonic() + 10
            reasons = []
            while time.monotonic() < deadline:
                reasons = [e["reason"] for e in store.list_events("default")]
                if "CCModeApplied" in reasons and "CCModeInvalid" in reasons:
                    break
                time.sleep(0.2)
            if "CCModeApplied" in reasons and "CCModeInvalid" in reasons:
                log(f"PASS events recorded: {reasons}")
            else:
                failures.append(f"events missing: {reasons}")

            # 6. round-3 enforcement surface: a good reconcile leaves a
            # verifiable evidence annotation, no leftover flip taint,
            # and mode-encoding device-node permissions
            store.set_node_labels(NODE, {L.CC_MODE_LABEL: Mode.ON.value})
            if not wait_state(store, "on"):
                failures.append("final reconcile to on")
            import stat as _stat

            from tpu_cc_manager.evidence import (
                evidence_mode, verify_evidence,
            )

            deadline = time.monotonic() + 10
            doc = None
            while time.monotonic() < deadline:
                node = store.get_node(NODE)
                raw = node["metadata"].get("annotations", {}).get(
                    L.EVIDENCE_ANNOTATION)
                if raw:
                    doc = json.loads(raw)
                    if evidence_mode(doc) == "on":
                        break
                time.sleep(0.2)  # evidence rides the async recorder
            if doc and verify_evidence(
                    doc, key=b"smoke-pool-key") == (True, "ok") \
                    and evidence_mode(doc) == "on":
                log("PASS evidence annotation verifies and attests 'on'")
            else:
                failures.append(f"evidence: {doc}")
            if doc and str(doc.get("digest", "")).startswith(
                    "hmac-sha256:"):
                log("PASS evidence is HMAC-signed with the mounted "
                    "pool key (no-downgrade posture)")
            else:
                failures.append(
                    f"evidence not HMAC-signed: {doc and doc.get('digest')}"
                )
            from tpu_cc_manager.identity import judge_identity

            iverdict = judge_identity(
                doc or {}, NODE, key=b"smoke-identity-key"
            )
            if iverdict == ("ok", "ok"):
                log("PASS platform identity token verifies and binds "
                    "to the node")
            else:
                failures.append(f"identity: {iverdict}")
            taints = store.get_node(NODE).get("spec", {}).get("taints") or []
            if not any(t.get("key") == L.FLIP_TAINT_KEY for t in taints):
                log("PASS no leftover flip taint after the cycle")
            else:
                failures.append(f"leftover flip taint: {taints}")
            dev0 = os.path.join(dev, "accel0")
            perms = _stat.S_IMODE(os.stat(dev0).st_mode)
            if perms == 0o600:
                log("PASS device node gated 0600 for cc=on")
            else:
                failures.append(f"device perms {oct(perms)} != 0o600")

            # 7. declarative path: a TPUCCPolicy object is the ONLY
            # input; the real policy-controller subprocess (the
            # policy-controller.yaml deployment unit) must notice it,
            # drive a rollout, and the agent converges
            store.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
                "apiVersion": f"{L.POLICY_GROUP}/{L.POLICY_VERSION}",
                "kind": L.POLICY_KIND,
                "metadata": {"name": "smoke-policy"},
                "spec": {
                    "mode": "devtools",
                    "nodeSelector": L.TPU_ACCELERATOR_LABEL,
                    "strategy": {"groupTimeoutSeconds": 60},
                },
            })
            pc_log = open(os.path.join(scratch, "policy.log"), "w")
            pc = subprocess.Popen(
                [sys.executable, "-m", "tpu_cc_manager",
                 "policy-controller", "--interval", "1", "--port", "0"],
                env=env, stdout=pc_log, stderr=subprocess.STDOUT,
                cwd=REPO,
            )
            try:
                if wait_state(store, "devtools"):
                    log("PASS policy-controller: TPUCCPolicy mode="
                        "devtools -> node converged declaratively")
                else:
                    failures.append("policy-driven convergence")
                deadline = time.monotonic() + 20
                phase = None
                while time.monotonic() < deadline:
                    phase = store.get_cluster_custom(
                        L.POLICY_GROUP, L.POLICY_VERSION,
                        L.POLICY_PLURAL, "smoke-policy",
                    ).get("status", {}).get("phase")
                    if phase == "Converged":
                        break
                    time.sleep(0.2)
                if phase == "Converged":
                    log("PASS TPUCCPolicy status published: "
                        "phase=Converged")
                else:
                    failures.append(f"policy status phase={phase}")
            finally:
                pc.terminate()
                try:
                    pc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pc.kill()
                pc_log.close()

            # 8. admission webhook on the wire: a confidential pod is
            # steered onto the observed mode the pool just converged to
            import base64 as _b64

            from tpu_cc_manager.webhook import AdmissionServer

            review = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {"uid": "smoke-1", "object": {
                    "metadata": {"name": "train", "labels": {
                        L.REQUIRES_CC_LABEL: "devtools"}},
                    "spec": {},
                }},
            }
            with AdmissionServer(0, tls=False) as wh:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{wh.port}/mutate",
                    data=json.dumps(review).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                resp = json.loads(urllib.request.urlopen(req, timeout=5).read())
                ops = json.loads(_b64.b64decode(resp["response"]["patch"]))
                injected = {
                    op["path"].split("/spec/nodeSelector/", 1)[1]
                    .replace("~1", "/").replace("~0", "~"): op["value"]
                    for op in ops if op["path"] != "/spec/nodeSelector"
                }
            node_state = state_label(store)
            if injected.get(L.CC_MODE_STATE_LABEL) == node_state == "devtools":
                log("PASS webhook steers requires-cc pod onto "
                    f"{L.CC_MODE_STATE_LABEL}={node_state}")
            else:
                failures.append(
                    f"webhook selector {injected} vs node {node_state}"
                )

            # 9. the diagnostic tour: doctor on the node (healthy ->
            # rc 0, verdict published) and the one-shot fleet audit
            r = subprocess.run(
                [sys.executable, "-m", "tpu_cc_manager", "doctor",
                 "--publish"],
                env=env, capture_output=True, text=True, cwd=REPO,
            )
            node_meta = store.get_node(NODE)["metadata"]
            verdict_raw = node_meta.get("annotations", {}).get(
                L.DOCTOR_ANNOTATION)
            ok_label = node_meta.get("labels", {}).get(L.DOCTOR_OK_LABEL)
            try:
                verdict_ok = bool(
                    verdict_raw and json.loads(verdict_raw)["ok"]
                )
            except (ValueError, KeyError):
                verdict_ok = False
            if r.returncode != 0:
                failures.append(
                    f"doctor rc={r.returncode}: "
                    f"{(r.stdout + r.stderr)[-400:]}"
                )
            elif not verdict_ok or ok_label != "true":
                failures.append(
                    "doctor ran clean but publication is wrong: "
                    f"verdict={verdict_raw!r} ok_label={ok_label!r}"
                )
            else:
                log("PASS doctor: healthy node, verdict published "
                    "(cc.doctor.ok label set)")
            r = subprocess.run(
                [sys.executable, "-m", "tpu_cc_manager",
                 "fleet-controller", "--once"],
                env=env, capture_output=True, text=True, cwd=REPO,
            )
            if r.returncode == 0:
                log("PASS fleet-controller --once: audit clean (rc 0)")
            else:
                failures.append(
                    f"fleet --once rc={r.returncode}: "
                    f"{(r.stdout + r.stderr)[-400:]}"
                )

            # 10. key rotation against the LIVE agent: the Secret
            # rotates in place (new primary; old key retired to the
            # old-keys entry). The interim old signature must verify
            # under the rotated set (stale, never digest_mismatch),
            # the running agent's idle tick re-signs with the new
            # primary, and the keyed one-shot audit stays clean.
            from tpu_cc_manager.evidence import signed_with_primary

            rotated_keys = (b"smoke-pool-key-2", b"smoke-pool-key")
            old_keys_file = os.path.join(scratch, "old-keys")
            with open(old_keys_file, "w") as f:
                f.write("smoke-pool-key\n")
            # atomic swap, the way kubelet rotates a Secret mount — an
            # in-place truncate-then-write would race the agent's 1 Hz
            # key watch into reading an EMPTY (keyless) file
            tmp_key = evidence_key + ".new"
            with open(tmp_key, "w") as f:
                f.write("smoke-pool-key-2")
            os.replace(tmp_key, evidence_key)
            env["TPU_CC_EVIDENCE_OLD_KEYS_FILE"] = old_keys_file
            deadline = time.monotonic() + 45
            resigned = False
            while time.monotonic() < deadline:
                raw = store.get_node(NODE)["metadata"].get(
                    "annotations", {}).get(L.EVIDENCE_ANNOTATION)
                doc = json.loads(raw) if raw else None
                if doc and verify_evidence(
                        doc, key=rotated_keys)[0] is not True:
                    failures.append(
                        "rotation: interim signature rejected "
                        f"({verify_evidence(doc, key=rotated_keys)})"
                    )
                    break
                if doc and signed_with_primary(doc, key=rotated_keys):
                    resigned = True
                    break
                time.sleep(0.5)
            if resigned:
                log("PASS key rotation: agent re-signed with the new "
                    "primary; interim old-key doc verified throughout")
            elif not any("rotation" in f for f in failures):
                failures.append("rotation: agent never re-signed")
            r = subprocess.run(
                [sys.executable, "-m", "tpu_cc_manager",
                 "fleet-controller", "--once"],
                env=env, capture_output=True, text=True, cwd=REPO,
            )
            if r.returncode == 0:
                log("PASS keyed audit clean after rotation "
                    "(stale_key drained)")
            else:
                failures.append(
                    f"post-rotation fleet --once rc={r.returncode}: "
                    f"{(r.stdout + r.stderr)[-400:]}"
                )

            # 11. webhook warn-mode rehearsal: admission unchanged,
            # warnings describe what enforce would do, each within the
            # API server's 256-char per-warning truncation limit
            os.environ["TPU_CC_WEBHOOK_REQUIRE_DOCTOR"] = "warn"
            try:
                with AdmissionServer(0, tls=False) as wh:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{wh.port}/mutate",
                        data=json.dumps(review).encode(),
                        headers={"Content-Type": "application/json"},
                        method="POST",
                    )
                    resp = json.loads(
                        urllib.request.urlopen(req, timeout=5).read()
                    )
            finally:
                del os.environ["TPU_CC_WEBHOOK_REQUIRE_DOCTOR"]
            wr = resp["response"]
            ops = json.loads(_b64.b64decode(wr["patch"]))
            warn_ok = (
                wr["allowed"]
                and wr.get("warnings")
                and all(len(w) <= 256 for w in wr["warnings"])
                and not any("doctor" in op["path"] for op in ops)
            )
            if warn_ok:
                log("PASS webhook warn mode: admission unchanged, "
                    f"{len(wr['warnings'])} rehearsal warning(s) "
                    "within the 256-char cap")
            else:
                failures.append(f"webhook warn mode: {wr}")

            # 12. the node-root forgery drill (round 5, TEE rung):
            # the live evidence's quote verifies and matches measured
            # history; then "root" rewrites the statefile OUTSIDE the
            # engine path, republishes pool-key-perfect evidence with
            # a fresh quote via the same tooling — and the keyed audit
            # flags attestation mismatch, because the measured flip
            # log cannot be rewritten.
            from tpu_cc_manager.attest import judge_attestation

            raw = store.get_node(NODE)["metadata"].get(
                "annotations", {}).get(L.EVIDENCE_ANNOTATION)
            live_doc = json.loads(raw) if raw else {}
            with open(tpm_key, "rb") as kf:
                smoke_aik = kf.read()
            averdict, adetail = judge_attestation(
                live_doc, NODE, key=smoke_aik)
            if averdict == "ok":
                log("PASS attestation: live quote verifies and "
                    "matches the measured flip history")
            else:
                failures.append(
                    f"attestation on live doc: {averdict} ({adetail})")
            # stop the agent first: its self-repair would re-flip the
            # drift (a REAL flip) and honestly heal the forgery
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            from tpu_cc_manager.device.tpu import SysfsTpuBackend

            be = SysfsTpuBackend(
                sysfs_root=sysfs, dev_root=dev,
                state_dir=os.path.join(scratch, "state"),
            )
            # forge a mode DIFFERENT from the last MEASURED flip (the
            # state label can diverge from measured history when an
            # upstream check regressed — deriving from the log keeps
            # this drill's diagnostic truthful even then): the attack
            # claims a state no real flip produced
            from tpu_cc_manager.attest import FakeTpm, measured_mode

            _, tpm_events = FakeTpm(
                state_dir=os.path.join(scratch, "tpm"),
            )._read_state()
            honest = measured_mode(tpm_events)
            forged_mode = (
                Mode.ON.value if honest != Mode.ON.value
                else Mode.DEVTOOLS.value
            )
            for chip in be.find_tpus()[0]:
                be.store.stage(chip.path, "cc", forged_mode)
                be.store.commit(chip.path)
            store.set_node_labels(NODE, {
                L.CC_MODE_LABEL: forged_mode,
                L.CC_MODE_STATE_LABEL: forged_mode,
            })
            r = subprocess.run(
                [sys.executable, "-m", "tpu_cc_manager.evidence",
                 "--sync"],
                env=env, capture_output=True, text=True, cwd=REPO,
            )
            r2 = subprocess.run(
                [sys.executable, "-m", "tpu_cc_manager",
                 "fleet-controller", "--once"],
                env=env, capture_output=True, text=True, cwd=REPO,
            )
            flagged = False
            rep = None
            try:
                rep = json.loads(r2.stdout)
                flagged = any(
                    "attestation mismatch" in p
                    for p in rep.get("problems", [])
                )
            except ValueError:
                pass
            if r.returncode == 0 and r2.returncode != 0 and flagged:
                log("PASS node-root drill: forged statefile re-signed "
                    "with the pool key is flagged as attestation "
                    "mismatch (measured history contradicts the claim)")
            else:
                post = store.get_node(NODE)["metadata"].get(
                    "annotations", {}).get(L.EVIDENCE_ANNOTATION, "")
                try:
                    post_att = json.loads(post).get("attestation")
                except ValueError:
                    post_att = "<unparseable>"
                failures.append(
                    "node-root drill not flagged: sync rc="
                    f"{r.returncode} ({(r.stdout + r.stderr)[-200:]}) "
                    f"audit rc={r2.returncode} flagged={flagged} "
                    f"problems={rep.get('problems') if rep else '?'} "
                    f"post_attestation={str(post_att)[:300]}"
                )
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            agent_log.close()
            server.stop()

        if failures:
            log(f"FAILED: {failures}")
            print(open(os.path.join(scratch, "agent.log")).read()[-4000:])
            return 1
        log("ALL PASS — label->state round trip verified against the "
            "manifest's env, device layer on synthetic sysfs tree")
        return 0


if __name__ == "__main__":
    sys.exit(main())
