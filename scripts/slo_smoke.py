#!/usr/bin/env python3
"""SLO-engine smoke (ISSUE 9, the slo-smoke CI job): prove the
burn-rate machinery end to end on live replicas, both directions —

1. ``scenarios/slo-fault-24.json`` (a write_429 storm under a mode
   storm) must FIRE the multi-window burn alert: the burn-rate gauge
   rises past the threshold, the budget burns, and the ``slo_burn``
   event lands in the observer's flight-recorder black box.
2. ``scenarios/slo-clean-16.json`` (the same shape, no fault) must
   burn NOTHING: no alerts, every error-ratio budget intact.

An alerting layer that can't demonstrate both halves is worse than
none — silent on real faults or crying on clean runs. Exit 0 only
when both hold.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# responsive scrape cadence for the short smoke scenarios (the lab
# default is 1 s; the fault window is a few seconds wide)
os.environ.setdefault("TPU_CC_FLEETOBS_INTERVAL_S", "0.25")

from tpu_cc_manager.simlab.runner import SimLab  # noqa: E402
from tpu_cc_manager.simlab.scenario import load_scenario  # noqa: E402

SCENARIO_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scenarios")

checks = []


def check(name, ok, detail=""):
    checks.append(ok)
    print(f"{'PASS' if ok else 'FAIL'} {name}" + (f": {detail}" if detail else ""))


def run(scenario):
    lab = SimLab(load_scenario(os.path.join(SCENARIO_DIR, scenario)))
    art = lab.run()
    return lab, art


def main():
    # ---- the burn half
    lab, art = run("slo-fault-24.json")
    slo = art["metrics"]["slo"]
    check("fault scenario converged", art["ok"], art.get("notes") or "")
    check("slo engine ran", "objectives" in slo,
          slo.get("skipped", ""))
    alerts = slo.get("alerts") or []
    fired = [a for a in alerts if a["objective"] == "flip-success"]
    check("flip-success burn alert fired", bool(fired),
          json.dumps(alerts))
    if fired:
        check(
            "burn rate rose past the threshold",
            fired[0]["fast_burn"] >= 2.0 and fired[0]["slow_burn"] >= 2.0,
            f"fast {fired[0]['fast_burn']}x / slow {fired[0]['slow_burn']}x",
        )
        check("budget burned", fired[0]["budget_remaining"] < 1.0)
    events = [e for e in lab.obs_rec.snapshot()["events"]
              if e["kind"] == "slo_burn"]
    check("slo_burn event landed in the flight recorder", bool(events))
    check("aggregated exposition stayed valid under the storm",
          not slo.get("aggregation_problems"),
          str(slo.get("aggregation_problems"))[:160])

    # ---- the quiet half
    _, art = run("slo-clean-16.json")
    slo = art["metrics"]["slo"]
    check("clean scenario converged", art["ok"], art.get("notes") or "")
    check("clean run fired no alerts", not slo.get("alerts"),
          json.dumps(slo.get("alerts"))[:200])
    objectives = slo.get("objectives") or {}
    for name in ("flip-success", "publish-loss"):
        o = objectives.get(name) or {}
        check(f"clean run left the {name} budget untouched",
              o.get("budget_remaining") == 1.0,
              str(o.get("budget_remaining")))
    check("clean aggregation valid",
          not slo.get("aggregation_problems"))

    print(f"\nslo-smoke: {sum(checks)}/{len(checks)} checks passed")
    return 0 if all(checks) else 1


if __name__ == "__main__":
    sys.exit(main())
