#!/usr/bin/env python3
"""Patch deployments/manifests/daemonset.yaml for a kind smoke run.

kind nodes have no TPU accel devices, so the smoke points the device
layer at a synthetic sysfs tree created on the kind node
(/var/tpu-smoke, see scripts/kind-smoke.sh) — exactly the "fake device
env" clause of BASELINE config 1. Everything else (RBAC, probes, env,
readiness file, DaemonSet scheduling) runs as shipped.

Usage: kind_smoke_patch.py <manifest> <image> | kubectl apply -f -
"""

import sys

import yaml


def patch(docs, image):
    for doc in docs:
        if not doc or doc.get("kind") != "DaemonSet":
            continue
        spec = doc["spec"]["template"]["spec"]
        ctr = spec["containers"][0]
        ctr["image"] = image
        ctr["imagePullPolicy"] = "Never"  # kind-loaded image
        env = ctr.setdefault("env", [])
        env.extend(
            [
                {"name": "TPU_SYSFS_ROOT", "value": "/var/tpu-smoke/sysfs"},
                {"name": "TPU_DEV_ROOT", "value": "/var/tpu-smoke/dev"},
                {"name": "TPU_CC_STATE_DIR", "value": "/var/tpu-smoke/state"},
            ]
        )
        ctr.setdefault("volumeMounts", []).append(
            {"name": "tpu-smoke", "mountPath": "/var/tpu-smoke"}
        )
        spec.setdefault("volumes", []).append(
            {
                "name": "tpu-smoke",
                "hostPath": {
                    "path": "/var/tpu-smoke",
                    "type": "DirectoryOrCreate",
                },
            }
        )
    return docs


def main():
    manifest, image = sys.argv[1], sys.argv[2]
    with open(manifest) as f:
        docs = list(yaml.safe_load_all(f))
    yaml.safe_dump_all(patch(docs, image), sys.stdout)


if __name__ == "__main__":
    main()
