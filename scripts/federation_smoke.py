#!/usr/bin/env python3
"""Federation smoke (ISSUE 16, the federation-smoke CI job): prove the
multi-region plane end to end on live FakeApiServers, both directions —

1. ``scenarios/federation-2x128.json`` — two regions (64 nodes each),
   a region partition racing the posture windows, then us-east
   evacuated mid-rollout (evac-races-upgrade). The run must CONVERGE
   with eu-west absorbing: the evacuation collapses eu-west's 30 s
   window to NOW, so fleet convergence lands far inside that window;
   us-east ends fully cordoned; the stitched cross-region trace axes
   and the region_evac_convergence_s axis are measured; each region's
   API server saw only its informer-priming node reads (the
   zero-cross-region-reads ledger); and the convergence-and-invariants
   oracle reports ZERO violations.
2. ``scenarios/federation-clean-2x128.json`` — the same fleet, no
   faults: ZERO evacuations, no region partitioned, no
   region_evac_convergence_s axis (nothing was evacuated), and the
   same zero-violation oracle.

A federation layer that can't demonstrate both halves is worse than
none — blind on real drains or evacuating healthy regions. Exit 0
only when both hold.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tpu_cc_manager.simlab import invariants  # noqa: E402
from tpu_cc_manager.simlab.federation import FederationLab  # noqa: E402
from tpu_cc_manager.simlab.report import convergence_key  # noqa: E402
from tpu_cc_manager.simlab.scenario import load_scenario  # noqa: E402

SCENARIO_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scenarios")

#: the informer/pump priming LISTs are the only sanctioned node reads;
#: anything past this bound means a judge fell off its informer cache
MAX_PRIMING_READS_PER_REGION = 8

checks = []


def check(name, ok, detail=""):
    checks.append(ok)
    print(f"{'PASS' if ok else 'FAIL'} {name}"
          + (f": {detail}" if detail else ""))


def run(scenario):
    lab = FederationLab(load_scenario(
        os.path.join(SCENARIO_DIR, scenario)))
    art = lab.run()
    violations = invariants.check_run(lab, art)
    return lab, art, violations


def main():
    # ---- the drill half: partition + evacuation, eu-west absorbs
    lab, art, violations = run("federation-2x128.json")
    check("drill scenario converged", art["ok"], art.get("notes") or "")
    check("zero invariant violations (drill)", not violations,
          "; ".join(f"{v.invariant}: {v.detail[:90]}"
                    for v in violations[:3]))
    fed = art["metrics"].get("federation") or {}
    evacuated = [e["region"] for e in fed.get("evacuations") or []]
    check("us-east was evacuated", evacuated == ["us-east"],
          json.dumps(evacuated))
    check("eu-west stayed in service (absorbing, not evacuated)",
          not fed.get("regions", {}).get("eu-west", {}).get("evacuated"))
    # the absorb proof: the scenario grants eu-west a 30 s window, so
    # a convergence far inside it means the evacuation collapsed the
    # window to NOW rather than waiting it out
    conv = art["metrics"].get(convergence_key(128))
    check("convergence landed inside eu-west's 30s window (absorb)",
          conv is not None and conv < 25.0, str(conv))
    evac_s = art["metrics"].get("region_evac_convergence_s")
    check("region_evac_convergence_s measured", evac_s is not None,
          str(evac_s))
    e2e = art["metrics"].get("trace_stitch") or {}
    check("cross-region traces stitched across processes",
          (e2e.get("cross_process_traces") or 0) >= 1
          and (e2e.get("e2e_samples") or 0) >= 128,
          json.dumps({k: e2e.get(k) for k in
                      ("cross_process_traces", "e2e_samples")}))
    reads = {name: r.get("node_read_requests")
             for name, r in (fed.get("regions") or {}).items()}
    check("zero steady-state node reads per region (priming only)",
          bool(reads) and all(
              isinstance(n, int) and n <= MAX_PRIMING_READS_PER_REGION
              for n in reads.values()),
          json.dumps(reads))

    # ---- the quiet half: no faults, nothing evacuates
    lab, art, violations = run("federation-clean-2x128.json")
    check("clean scenario converged", art["ok"], art.get("notes") or "")
    check("zero invariant violations (clean)", not violations,
          "; ".join(f"{v.invariant}: {v.detail[:90]}"
                    for v in violations[:3]))
    fed = art["metrics"].get("federation") or {}
    check("clean run evacuated NOTHING",
          not fed.get("evacuations")
          and not any(r.get("evacuated")
                      for r in (fed.get("regions") or {}).values()),
          json.dumps(fed.get("evacuations")))
    check("clean run partitioned nothing", not fed.get("partitioned"),
          json.dumps(fed.get("partitioned")))
    check("no evac axis on a run with no evacuation",
          "region_evac_convergence_s" not in art["metrics"])

    print(f"\nfederation-smoke: {sum(checks)}/{len(checks)} "
          "checks passed")
    return 0 if all(checks) else 1


if __name__ == "__main__":
    sys.exit(main())
