"""ConfidentialSpaceAttestor's fetch path (attest.py) — the unix-socket
HTTP client that runs in REAL production VMs, previously zero-covered
(VERDICT r5 weak #2): a typo in the POST body or status handling would
first have surfaced inside a Confidential Space VM. A fake launcher
(AF_UNIX HTTP server speaking /v1/token) drives the whole surface:
auto resolution with the socket present, the end-to-end quote (request
shape, nonce in body, token attach), and every degradation path
(non-200, empty body, timeout -> evidence published without
attestation, never a flip failure)."""

import http.server
import json
import socketserver
import threading
import time

import pytest

from tpu_cc_manager.attest import (
    ConfidentialSpaceAttestor, get_attestor,
)


class FakeLauncher:
    """In-VM launcher double: AF_UNIX HTTP server serving POST
    /v1/token. Records every request; response is configurable per
    test (status, body, artificial delay)."""

    def __init__(self, socket_path, *, status=200,
                 token="header.payload.sig", body=None, delay_s=0.0):
        self.socket_path = str(socket_path)
        self.status = status
        self.token = token
        self.body = body  # overrides token verbatim when not None
        self.delay_s = delay_s
        self.requests = []
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0) or 0)
                raw = self.rfile.read(length)
                outer.requests.append({
                    "path": self.path,
                    "content_type": self.headers.get("Content-Type"),
                    "body": json.loads(raw) if raw else None,
                })
                if outer.delay_s:
                    time.sleep(outer.delay_s)
                data = (outer.body if outer.body is not None
                        else outer.token).encode()
                self.send_response(outer.status)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

            def get_request(self):
                # BaseHTTPRequestHandler expects a (host, port) peer;
                # AF_UNIX peers are '' — substitute a printable one
                request, _ = super().get_request()
                return request, ("localhost", 0)

        self._server = Server(self.socket_path, Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


@pytest.fixture
def launcher(tmp_path):
    """Default healthy launcher; tests mutate status/body/delay."""
    lch = FakeLauncher(tmp_path / "teeserver.sock")
    yield lch
    lch.stop()


NONCE = "ab" * 32


def test_auto_resolution_picks_cs_when_socket_present(
        launcher, monkeypatch):
    monkeypatch.setenv("TPU_CC_ATTESTATION", "auto")
    monkeypatch.setenv("TPU_CC_CS_SOCKET", launcher.socket_path)
    att = get_attestor(refresh=True)
    assert isinstance(att, ConfidentialSpaceAttestor)
    assert att.socket_path == launcher.socket_path
    monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
    get_attestor(refresh=True)


def test_quote_end_to_end_request_shape_and_token_attach(launcher):
    att = ConfidentialSpaceAttestor(socket_path=launcher.socket_path)
    quote = att.quote(NONCE)
    # one POST, to the token endpoint, as JSON
    assert len(launcher.requests) == 1
    req = launcher.requests[0]
    assert req["path"] == "/v1/token"
    assert req["content_type"] == "application/json"
    # the launcher contract: audience + OIDC + the evidence digest as
    # the EAT nonce
    assert req["body"] == {
        "audience": "tpu-cc-manager",
        "token_type": "OIDC",
        "nonces": [NONCE],
    }
    # the returned envelope carries the token verbatim
    assert quote["provider"] == "confidential-space"
    assert quote["nonce"] == NONCE
    assert quote["token"] == "header.payload.sig"


def test_quote_non_200_raises(launcher):
    launcher.status = 500
    att = ConfidentialSpaceAttestor(socket_path=launcher.socket_path)
    with pytest.raises(RuntimeError, match="http 500"):
        att.quote(NONCE)


def test_quote_empty_body_raises(launcher):
    launcher.body = ""
    att = ConfidentialSpaceAttestor(socket_path=launcher.socket_path)
    with pytest.raises(RuntimeError):
        att.quote(NONCE)


def test_quote_timeout_raises(launcher):
    launcher.delay_s = 1.0
    att = ConfidentialSpaceAttestor(
        socket_path=launcher.socket_path, timeout_s=0.2
    )
    with pytest.raises(OSError):
        att.quote(NONCE)


def test_missing_socket_raises_connect_error(tmp_path):
    att = ConfidentialSpaceAttestor(
        socket_path=str(tmp_path / "absent.sock"), timeout_s=0.2
    )
    with pytest.raises(OSError):
        att.quote(NONCE)


@pytest.mark.parametrize("break_it", ["status", "empty", "timeout"])
def test_degraded_launcher_evidence_published_without_attestation(
        launcher, tmp_path, monkeypatch, break_it):
    """The production posture: a broken launcher must degrade to
    evidence WITHOUT a quote (the attestation_missing audit finding),
    never to a failed build or flip."""
    from tpu_cc_manager.device.fake import fake_backend
    from tpu_cc_manager.evidence import build_evidence

    if break_it == "status":
        launcher.status = 404
    elif break_it == "empty":
        launcher.body = ""
    else:
        launcher.delay_s = 1.0
    monkeypatch.setenv("TPU_CC_ATTESTATION", "confidential-space")
    monkeypatch.setenv("TPU_CC_CS_SOCKET", launcher.socket_path)
    att = get_attestor(refresh=True)
    att.timeout_s = 0.2
    try:
        doc = build_evidence("cs-node", fake_backend(n_chips=1))
    finally:
        monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
        get_attestor(refresh=True)
    assert doc["node"] == "cs-node" or "devices" in doc
    assert "attestation" not in doc


def test_healthy_launcher_evidence_carries_cs_quote(
        launcher, monkeypatch):
    """The green path end to end: build_evidence fetches the token over
    the live socket and embeds it, nonce bound to the document."""
    from tpu_cc_manager.attest import attestation_nonce
    from tpu_cc_manager.device.fake import fake_backend
    from tpu_cc_manager.evidence import build_evidence

    monkeypatch.setenv("TPU_CC_ATTESTATION", "confidential-space")
    monkeypatch.setenv("TPU_CC_CS_SOCKET", launcher.socket_path)
    get_attestor(refresh=True)
    try:
        doc = build_evidence("cs-node", fake_backend(n_chips=1))
    finally:
        monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
        get_attestor(refresh=True)
    att = doc.get("attestation")
    assert att is not None and att["provider"] == "confidential-space"
    assert att["token"] == "header.payload.sig"
    # the nonce commits to the rest of the document
    assert att["nonce"] == attestation_nonce(doc)
