"""Cluster-scale simulation: N independent agents against ONE API server,
coordinating only through node labels — the reference's real distributed
model (SURVEY.md §2.3 "cluster-wide concurrency"), which it never tested.
"""

import threading
import time

from tpu_cc_manager import labels as L
from tpu_cc_manager.agent import CCManagerAgent
from tpu_cc_manager.config import AgentConfig
from tpu_cc_manager.device.fake import fake_backend
from tpu_cc_manager.k8s import FakeKube
from tpu_cc_manager.k8s.objects import make_node, make_pod


class SimNode:
    def __init__(self, kube, name, tmp_path, label=None, n_chips=4,
                 slice_id=None, coordinate=False):
        node_labels = {L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice"}
        if label:
            node_labels[L.CC_MODE_LABEL] = label
        if slice_id:
            node_labels[L.TPU_SLICE_LABEL] = slice_id
        kube.add_node(make_node(name, labels=node_labels))
        self.backend = fake_backend(n_chips=n_chips)
        cfg = AgentConfig(
            node_name=name,
            default_mode="off",
            readiness_file=str(tmp_path / f"ready-{name}"),
            health_port=0,
            drain_strategy="none",
        )
        coordinator = None
        if coordinate:
            from tpu_cc_manager.slice_coord import SliceCoordinator

            coordinator = SliceCoordinator(
                kube, name, poll_s=0.05, commit_timeout_s=30, hb_ttl_s=3
            )
        self.agent = CCManagerAgent(
            kube, cfg, backend=self.backend, slice_coordinator=coordinator
        )
        self.agent.watcher.watch_timeout_s = 2
        self.agent.watcher.backoff_s = 0.05
        self.thread = None

    def start(self):
        self.thread = threading.Thread(target=self.agent.run, daemon=True)
        self.thread.start()

    def stop(self):
        self.agent.shutdown()


def _wait(predicate, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return False


def test_pool_wide_reconcile_32_nodes(tmp_path):
    """BASELINE config 4 shape (scaled for CI): 32 agents, one label flip
    each, all converge; then a second concurrent flip back."""
    kube = FakeKube()
    nodes = [SimNode(kube, f"tpu-{i:02d}", tmp_path, label="off") for i in range(32)]
    for n in nodes:
        n.start()
    try:
        # wait for initial reconcile everywhere
        assert _wait(
            lambda: all(
                kube.get_node(f"tpu-{i:02d}")["metadata"]["labels"].get(
                    L.CC_MODE_STATE_LABEL
                )
                == "off"
                for i in range(32)
            )
        )
        # flip the whole pool to on
        for i in range(32):
            kube.set_node_labels(f"tpu-{i:02d}", {L.CC_MODE_LABEL: "on"})
        assert _wait(
            lambda: all(
                kube.get_node(f"tpu-{i:02d}")["metadata"]["labels"].get(
                    L.CC_MODE_STATE_LABEL
                )
                == "on"
                for i in range(32)
            )
        )
        assert all(
            c.query_cc_mode() == "on" for n in nodes for c in n.backend.chips
        )
    finally:
        for n in nodes:
            n.stop()


def test_divergent_per_node_policies(tmp_path):
    kube = FakeKube()
    modes = ["on", "off", "devtools", "ici"]
    nodes = [
        SimNode(kube, f"m-{i}", tmp_path, label=modes[i % 4]) for i in range(8)
    ]
    for n in nodes:
        n.start()
    try:
        assert _wait(
            lambda: all(
                kube.get_node(f"m-{i}")["metadata"]["labels"].get(
                    L.CC_MODE_STATE_LABEL
                )
                == modes[i % 4]
                for i in range(8)
            )
        )
    finally:
        for n in nodes:
            n.stop()


def test_reconcile_under_pod_churn(tmp_path):
    """BASELINE config 4: flips land while unrelated pods churn in the
    namespace; the agents must converge regardless."""
    kube = FakeKube()
    nodes = [SimNode(kube, f"c-{i}", tmp_path, label="off") for i in range(4)]
    for n in nodes:
        n.start()
    stop_churn = threading.Event()

    def churn():
        i = 0
        while not stop_churn.is_set():
            kube.add_pod(make_pod(f"churn-{i}", "default", node_name=f"c-{i % 4}"))
            if i > 4:
                try:
                    kube.delete_pod("default", f"churn-{i - 4}")
                except Exception:
                    pass
            i += 1
            time.sleep(0.01)

    churn_t = threading.Thread(target=churn, daemon=True)
    churn_t.start()
    try:
        for i in range(4):
            kube.set_node_labels(f"c-{i}", {L.CC_MODE_LABEL: "on"})
        assert _wait(
            lambda: all(
                kube.get_node(f"c-{i}")["metadata"]["labels"].get(
                    L.CC_MODE_STATE_LABEL
                )
                == "on"
                for i in range(4)
            )
        )
    finally:
        stop_churn.set()
        churn_t.join(timeout=2)
        for n in nodes:
            n.stop()
