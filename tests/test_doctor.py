"""`doctor` diagnostic (tpu_cc_manager.doctor).

One command cross-checking every node-local trust surface — statefile
commit state, independent-reader agreement, device-node gate perms,
holders, cluster labels, and evidence. The reference's only debugging
surface is the pod log of a `set -x` bash script (SURVEY.md §5.1).
"""

import json
import os

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.statefile import ModeStateStore
from tpu_cc_manager.device.tpu import SysfsTpuBackend
from tpu_cc_manager.doctor import run_doctor
from tpu_cc_manager.engine import ModeEngine
from tpu_cc_manager.evidence import publish_evidence
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node

NODE = "doc-node"


def _backend(tmp_path, monkeypatch, n=1, gating="none"):
    sysfs = tmp_path / "sysfs"
    dev = tmp_path / "dev"
    dev.mkdir(exist_ok=True)
    for i in range(n):
        d = sysfs / f"accel{i}" / "device"
        d.mkdir(parents=True)
        (d / "vendor").write_text("0x1ae0\n")
        (d / "device").write_text("0x0063\n")
        (dev / f"accel{i}").write_text("")
    monkeypatch.setenv("TPU_CC_DEVICE_GATING", gating)
    monkeypatch.setenv("TPU_CC_HOLDER_CHECK", "none")
    return SysfsTpuBackend(
        sysfs_root=str(sysfs), dev_root=str(dev),
        state_dir=str(tmp_path / "state"),
    )


def _flip(backend, mode="on"):
    ModeEngine(set_state_label=lambda v: None, backend=backend,
               evict_components=False).set_mode(mode)


def by_name(report):
    out = {}
    for c in report["checks"]:
        out.setdefault(c["name"], []).append(c)
    return out


def worst(report, name):
    sevs = [c["severity"] for c in by_name(report).get(name, [])]
    for s in ("fail", "warn", "ok"):
        if s in sevs:
            return s
    return None


# ---------------------------------------------------------------------------
# device-local checks
# ---------------------------------------------------------------------------

def test_healthy_node_all_ok_offline(tmp_path, monkeypatch):
    backend = _backend(tmp_path, monkeypatch)
    _flip(backend, "on")
    report = run_doctor(backend=backend)
    assert worst(report, "enumerate") == "ok"
    assert worst(report, "staged-committed") == "ok"
    assert worst(report, "independent-read") == "ok"
    # no cluster access: warned, not failed — and the report is still ok
    assert worst(report, "cluster") == "warn"
    assert report["ok"] is True


def test_interrupted_flip_is_a_fail(tmp_path, monkeypatch):
    backend = _backend(tmp_path, monkeypatch)
    _flip(backend, "on")
    # stage without commit: the crash window between stage and reset
    backend.store.stage(f"{tmp_path}/dev/accel0", "cc", "off")
    report = run_doctor(backend=backend)
    assert worst(report, "staged-committed") == "fail"
    assert report["ok"] is False


def test_statefile_tamper_fails_independent_read(tmp_path, monkeypatch):
    backend = _backend(tmp_path, monkeypatch)
    _flip(backend, "on")

    class LyingStore(ModeStateStore):
        def effective(self, path, domain):
            real = super().effective(path, domain)
            return "off" if domain == "cc" else real

    backend.store = LyingStore(backend.store.state_dir)
    report = run_doctor(backend=backend)
    assert worst(report, "independent-read") == "fail"


def test_gate_drift_detected(tmp_path, monkeypatch):
    backend = _backend(tmp_path, monkeypatch, gating="chmod")
    _flip(backend, "on")
    dev0 = f"{tmp_path}/dev/accel0"
    os.chmod(dev0, 0o666)  # someone reopened a cc=on device
    report = run_doctor(backend=backend)
    assert worst(report, "gate-perms") == "fail"
    os.chmod(dev0, 0o600)
    assert worst(run_doctor(backend=backend), "gate-perms") == "ok"


def test_flip_lock_is_warn_not_fail(tmp_path, monkeypatch):
    backend = _backend(tmp_path, monkeypatch, gating="chmod")
    _flip(backend, "on")
    os.chmod(f"{tmp_path}/dev/accel0", 0o000)  # fail-secure hold
    report = run_doctor(backend=backend)
    assert worst(report, "gate-perms") == "warn"
    assert report["ok"] is True


# ---------------------------------------------------------------------------
# cluster checks
# ---------------------------------------------------------------------------

def _cluster(tmp_path, monkeypatch, state="on", desired="on",
             evidence=True):
    backend = _backend(tmp_path, monkeypatch)
    _flip(backend, "on")
    kube = FakeKube()
    labels = {L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice"}
    if desired:
        labels[L.CC_MODE_LABEL] = desired
    if state:
        labels[L.CC_MODE_STATE_LABEL] = state
    kube.add_node(make_node(NODE, labels=labels))
    if evidence:
        assert publish_evidence(kube, NODE, backend=backend)
    return backend, kube


def test_healthy_cluster_node_all_ok(tmp_path, monkeypatch):
    backend, kube = _cluster(tmp_path, monkeypatch)
    report = run_doctor(kube=kube, node_name=NODE, backend=backend)
    assert worst(report, "state-label") == "ok"
    assert worst(report, "desired-converged") == "ok"
    assert worst(report, "evidence") == "ok"
    assert worst(report, "flip-taint") == "ok"
    assert report["ok"] is True


def test_lying_state_label_fails(tmp_path, monkeypatch):
    backend, kube = _cluster(tmp_path, monkeypatch, state="off",
                             desired="off", evidence=False)
    report = run_doctor(kube=kube, node_name=NODE, backend=backend)
    assert worst(report, "state-label") == "fail"
    assert report["ok"] is False


def test_divergence_is_warn(tmp_path, monkeypatch):
    backend, kube = _cluster(tmp_path, monkeypatch, desired="devtools")
    report = run_doctor(kube=kube, node_name=NODE, backend=backend)
    assert worst(report, "desired-converged") == "warn"
    assert report["ok"] is True


def test_tampered_statefile_fails_evidence(tmp_path, monkeypatch):
    backend, kube = _cluster(tmp_path, monkeypatch)
    # tamper AFTER evidence publication: recomputed digest mismatches.
    # Write through the store (not raw) so both readers agree and only
    # the evidence check trips.
    dev0 = f"{tmp_path}/dev/accel0"
    backend.store.stage(dev0, "cc", "off")
    backend.store.commit(dev0)
    report = run_doctor(kube=kube, node_name=NODE, backend=backend)
    assert worst(report, "evidence") == "fail"
    assert report["ok"] is False


def test_replayed_evidence_fails(tmp_path, monkeypatch):
    backend, kube = _cluster(tmp_path, monkeypatch)
    doc = json.loads(
        kube.get_node(NODE)["metadata"]["annotations"][
            L.EVIDENCE_ANNOTATION]
    )
    doc["node"] = "other-node"
    # re-publish verbatim under this node (digest now wrong too — use a
    # raw annotation write to simulate a replay attacker without a key)
    kube.set_node_annotations(NODE, {
        L.EVIDENCE_ANNOTATION: json.dumps(doc, sort_keys=True,
                                          separators=(",", ":")),
    })
    report = run_doctor(kube=kube, node_name=NODE, backend=backend)
    assert worst(report, "evidence") == "fail"


def test_leftover_flip_taint_is_warn(tmp_path, monkeypatch):
    backend, kube = _cluster(tmp_path, monkeypatch)
    kube.patch_node(NODE, {"spec": {"taints": [{
        "key": L.FLIP_TAINT_KEY, "value": L.FLIP_TAINT_VALUE,
        "effect": L.FLIP_TAINT_EFFECT,
    }]}})
    report = run_doctor(kube=kube, node_name=NODE, backend=backend)
    assert worst(report, "flip-taint") == "warn"
    assert report["ok"] is True


def test_signed_evidence_without_key_is_warn(tmp_path, monkeypatch):
    """Signed fleet, keyless doctor shell: a blind spot, not a node
    failure (the same tolerance the rollout judge applies)."""
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-key")
    backend, kube = _cluster(tmp_path, monkeypatch)
    monkeypatch.delenv("TPU_CC_EVIDENCE_KEY")
    report = run_doctor(kube=kube, node_name=NODE, backend=backend)
    assert worst(report, "evidence") == "warn"
    assert report["ok"] is True


def test_unknown_effective_mode_skips_gate_check(tmp_path, monkeypatch):
    """When the statefile check itself fails for a device, gate-perms
    must not judge drift against an assumed 'off' — that would
    misdirect the operator from the real problem."""
    backend = _backend(tmp_path, monkeypatch, gating="chmod")
    _flip(backend, "on")  # device correctly gated 0600

    class BrokenStore(ModeStateStore):
        def staged(self, path, domain):
            raise RuntimeError("corrupt statefile")

    backend.store = BrokenStore(backend.store.state_dir)
    report = run_doctor(backend=backend)
    assert worst(report, "staged-committed") == "fail"  # the real issue
    assert worst(report, "gate-perms") == "warn"  # not a spurious fail


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_doctor_offline(tmp_path, monkeypatch, capsys):
    from tpu_cc_manager.__main__ import main
    from tpu_cc_manager.device import base as device_base

    backend = _backend(tmp_path, monkeypatch)
    _flip(backend, "on")
    device_base.set_backend(backend)
    monkeypatch.setenv("NODE_NAME", NODE)
    rc = main(["doctor", "--offline"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] is True
    assert any(c["name"] == "independent-read" for c in out["checks"])


def test_doctor_judges_identity_posture(tmp_path, monkeypatch):
    """The node self-diagnoses its identity posture: ok with a bound
    verifiable token, warn when a configured provider produced no
    token, fail on a foreign token — so identity problems surface on
    the node before the fleet audit pages."""
    from tpu_cc_manager.doctor import run_doctor
    from tpu_cc_manager.evidence import build_evidence
    from tpu_cc_manager.identity import FakePlatformIdentity, mint_fake_token

    monkeypatch.setenv("TPU_CC_IDENTITY", "fake")
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", "dk")
    be = _backend(tmp_path, monkeypatch)
    kube = FakeKube()

    def publish(doc):
        import json as _json

        kube.set_node_annotations("doc-node", {
            L.EVIDENCE_ANNOTATION: _json.dumps(doc)})

    def check_named(report, name):
        return next(c for c in report["checks"] if c["name"] == name)

    kube.add_node(make_node("doc-node"))
    # healthy: identity bound + verifiable
    publish(build_evidence(
        "doc-node", be, identity_provider=FakePlatformIdentity(b"dk")))
    c = check_named(run_doctor(kube=kube, node_name="doc-node",
                               backend=be), "identity")
    assert c["severity"] == "ok", c

    # provider configured but token missing from the published doc
    publish(build_evidence("doc-node", be, identity_provider=None))
    c = check_named(run_doctor(kube=kube, node_name="doc-node",
                               backend=be), "identity")
    assert c["severity"] == "warn", c
    assert "no token" in c["detail"]

    # foreign token (replay): fail
    class Replaying:
        provider = "fake"

        def token(self, node_name, audience=None):
            return mint_fake_token("other-node", b"dk")

    publish(build_evidence("doc-node", be, identity_provider=Replaying()))
    c = check_named(run_doctor(kube=kube, node_name="doc-node",
                               backend=be), "identity")
    assert c["severity"] == "fail", c

    # no provider configured at all: absence is healthy
    monkeypatch.setenv("TPU_CC_IDENTITY", "none")
    publish(build_evidence("doc-node", be, identity_provider=None))
    c = check_named(run_doctor(kube=kube, node_name="doc-node",
                               backend=be), "identity")
    assert c["severity"] == "ok", c
