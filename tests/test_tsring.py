"""tsring (ISSUE 9): snapshot reflection, windowed rates, histogram
quantile estimation edge cases, and the /debug/timeseries + flight-
recorder surfaces."""

import json
import math
import urllib.request

from tpu_cc_manager.obs import HealthServer, Histogram, Metrics
from tpu_cc_manager.tsring import (
    TimeSeriesRing,
    bucket_deltas,
    counter_delta,
    derive_window,
    quantile_from_buckets,
    snapshot_metric_set,
    window_pair,
)


# ------------------------------------------------------------- snapshots
def test_snapshot_reflects_every_metric_primitive():
    """The ring samples by the same reflection the render uses: add a
    metric attribute, touch nothing else, and it is sampled."""
    m = Metrics()
    m.reconciles_total.inc("success")
    m.reconcile_duration.observe(0.2)
    m.phase_duration.observe("flip", 0.1)
    m.set_current_mode("on")
    snap = snapshot_metric_set(m)
    assert snap["tpu_cc_reconciles_total"]["type"] == "counter"
    assert snap["tpu_cc_reconciles_total"]["series"][
        'outcome="success"'] == 1.0
    assert snap["tpu_cc_mode_info"]["series"]['mode="on"'] == 1.0
    hist = snap["tpu_cc_reconcile_duration_seconds"]["hist"][""]
    assert hist["count"] == 1
    assert hist["buckets"]["+Inf"] == 1
    # HistogramVec children keyed by their family label
    vec = snap["tpu_cc_phase_duration_seconds"]["hist"]
    assert 'phase="flip"' in vec
    from tpu_cc_manager.obs import Gauge

    m.zz_added = Gauge("tpu_cc_tsring_drift_probe", "added in a test")
    m.zz_added.set(7.0)
    snap2 = snapshot_metric_set(m)
    assert snap2["tpu_cc_tsring_drift_probe"]["series"][""] == 7.0


# ----------------------------------------------------------- window math
def test_counter_rate_clamps_to_zero_on_reset():
    """ISSUE 9 satellite: a counter reset (process restart inside the
    window) must read as rate 0, never negative."""
    assert counter_delta(100.0, 5.0) == 0.0
    assert counter_delta(5.0, 100.0) == 95.0
    assert counter_delta(None, 3.0) == 3.0


def test_histogram_quantile_empty_window_is_none():
    h = Histogram("h", "t", buckets=(0.1, 1.0))
    snap1 = h.snapshot()
    h_deltas = bucket_deltas(snap1, h.snapshot())
    assert quantile_from_buckets(h_deltas, 0.5) is None
    assert quantile_from_buckets([], 0.99) is None


def test_histogram_quantile_single_bucket_interpolates():
    # every windowed observation landed in the (0.1, 1.0] bucket:
    # the estimate interpolates between the bounds
    deltas = [(0.1, 0.0), (1.0, 10.0), (math.inf, 0.0)]
    q50 = quantile_from_buckets(deltas, 0.5)
    assert 0.1 < q50 <= 1.0
    # single FIRST bucket: lower bound is 0
    deltas = [(0.1, 4.0), (1.0, 0.0), (math.inf, 0.0)]
    q = quantile_from_buckets(deltas, 0.5)
    assert 0.0 < q <= 0.1


def test_histogram_quantile_all_inf_saturates_at_highest_finite():
    """Observations beyond every finite bucket: the estimate saturates
    at the largest finite bound (never invents an unbounded number);
    with no finite bucket at all it degrades to None."""
    deltas = [(0.1, 0.0), (1.0, 0.0), (math.inf, 7.0)]
    assert quantile_from_buckets(deltas, 0.99) == 1.0
    assert quantile_from_buckets([(math.inf, 3.0)], 0.5) is None


def test_histogram_window_counter_reset_clamps():
    """A restarted process's histogram (smaller cumulative counts)
    must yield a zero-observation window, not negative buckets."""
    old = {"buckets": {"0.1": 50, "1": 80, "+Inf": 100},
           "sum": 10.0, "count": 100}
    new = {"buckets": {"0.1": 1, "1": 2, "+Inf": 3},
           "sum": 0.5, "count": 3}
    deltas = bucket_deltas(old, new)
    assert all(n >= 0 for _, n in deltas)
    assert sum(n for _, n in deltas) == 0
    assert quantile_from_buckets(deltas, 0.99) is None


def test_derive_window_rates_and_quantiles():
    m = Metrics()
    m.reconciles_total.inc("success")
    m.reconcile_duration.observe(0.3)
    old = (100.0, snapshot_metric_set(m))
    for _ in range(10):
        m.reconciles_total.inc("success")
        m.reconcile_duration.observe(0.3)
    new = (160.0, snapshot_metric_set(m))
    doc = derive_window(old, new)
    assert doc["window_s"] == 60.0
    entry = doc["counters"]["tpu_cc_reconciles_total"][
        'outcome="success"']
    assert entry["value"] == 11
    assert entry["window_delta"] == 10
    assert entry["per_min"] == 10.0  # 10 flips in 60s
    hist = doc["histograms"]["tpu_cc_reconcile_duration_seconds"][""]
    assert hist["window_count"] == 10
    # 0.3 lands in the (0.1, 0.5] bucket; the estimate must too
    assert 0.1 < hist["p50"] <= 0.5
    assert 0.1 < hist["p99"] <= 0.5


def test_window_pair_spans_requested_window():
    samples = [(float(t), {}) for t in range(0, 100, 10)]
    old, new = window_pair(samples, 30.0)
    assert new[0] == 90.0
    assert old[0] == 60.0  # latest sample at-or-before the cutoff
    # ring younger than the window: the whole ring answers
    old, new = window_pair(samples, 1000.0)
    assert old[0] == 0.0
    assert window_pair(samples[:1], 30.0) is None


# ------------------------------------------------------------- the ring
def test_ring_tick_and_doc():
    m = Metrics()
    ring = TimeSeriesRing(m, interval_s=10.0, name="t")
    m.reconciles_total.inc("success")
    ring.tick(now=100.0)
    for _ in range(5):
        m.reconciles_total.inc("success")
    ring.tick(now=130.0)
    doc = ring.to_doc()
    assert doc["tsring_version"] == 1
    assert doc["samples"] == 2
    assert doc["span_s"] == 30.0
    rate = doc["derived"]["counters"]["tpu_cc_reconciles_total"][
        'outcome="success"']
    assert rate["per_min"] == 10.0
    # raw points present on the route doc, elided for dumps
    assert "points" in doc
    pts = doc["points"]["tpu_cc_reconciles_total"]['outcome="success"']
    assert pts == [[100.0, 1], [130.0, 6]]
    assert "points" not in ring.to_doc(include_points=False)


def test_ring_metric_prefix_filter():
    """?metric=<prefix> (ISSUE 15 satellite): the filtered doc carries
    only matching families — in derived AND points — while the full
    route stays byte-compatible with the historical shape."""
    m = Metrics()
    ring = TimeSeriesRing(m, interval_s=10.0, name="t")
    m.reconciles_total.inc("success")
    m.reconcile_duration.observe(0.05)
    ring.tick(now=100.0)
    ring.tick(now=130.0)
    doc = ring.to_doc(metric_prefix="tpu_cc_reconcile_duration")
    assert doc["metric_prefix"] == "tpu_cc_reconcile_duration"
    assert list(doc["derived"]["histograms"]) == [
        "tpu_cc_reconcile_duration_seconds"]
    assert doc["derived"]["counters"] == {}
    assert list(doc["points"]) == ["tpu_cc_reconcile_duration_seconds"]
    # no match -> empty families, not an error
    empty = ring.to_doc(metric_prefix="tpu_cc_nope")
    assert empty["derived"]["counters"] == {}
    assert empty["derived"]["histograms"] == {}
    # the unfiltered doc is unchanged by the feature
    full = ring.to_doc()
    assert "metric_prefix" not in full
    assert "tpu_cc_reconciles_total" in full["derived"]["counters"]


def test_health_server_timeseries_metric_query():
    m = Metrics()
    ring = TimeSeriesRing(m, interval_s=10.0, name="agent")
    m.reconciles_total.inc("success")
    m.reconcile_duration.observe(0.05)
    ring.tick(now=1.0)
    ring.tick(now=11.0)
    srv = HealthServer(m, port=0, tsring=ring).start()
    try:
        base = f"http://127.0.0.1:{srv.port}/debug/timeseries"
        with urllib.request.urlopen(
            base + "?metric=tpu_cc_reconciles_total", timeout=5
        ) as r:
            doc = json.loads(r.read())
        assert list(doc["derived"]["counters"]) == [
            "tpu_cc_reconciles_total"]
        assert doc["derived"]["histograms"] == {}
        # the unfiltered route still serves everything
        with urllib.request.urlopen(base, timeout=5) as r:
            full = json.loads(r.read())
        assert "tpu_cc_reconcile_duration_seconds" in (
            full["derived"]["histograms"])
    finally:
        srv.stop()


def test_ring_listener_sees_every_tick():
    m = Metrics()
    ring = TimeSeriesRing(m, interval_s=10.0, name="t")
    seen = []
    ring.add_listener(lambda samples: seen.append(len(samples)))
    ring.tick(now=1.0)
    ring.tick(now=2.0)
    assert seen == [1, 2]
    # a broken listener costs itself, never the sampler
    ring.add_listener(lambda samples: 1 / 0)
    assert ring.tick(now=3.0) is not None
    assert seen == [1, 2, 3]


def test_ring_tick_never_raises():
    ring = TimeSeriesRing(lambda: 1 / 0, name="broken")
    assert ring.tick() is None
    assert ring.samples() == []


def test_ring_bounded_capacity():
    m = Metrics()
    ring = TimeSeriesRing(m, interval_s=1.0, capacity=4)
    for t in range(10):
        ring.tick(now=float(t))
    samples = ring.samples()
    assert len(samples) == 4
    assert samples[0][0] == 6.0


# ------------------------------------------------------------- surfaces
def test_health_server_serves_debug_timeseries():
    m = Metrics()
    ring = TimeSeriesRing(m, interval_s=10.0, name="agent")
    m.reconciles_total.inc("success")
    ring.tick(now=1.0)
    ring.tick(now=11.0)
    srv = HealthServer(m, port=0, tsring=ring).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/timeseries", timeout=5
        ) as r:
            doc = json.loads(r.read())
        assert doc["tsring_version"] == 1
        assert doc["samples"] == 2
    finally:
        srv.stop()


def test_health_server_404_when_tsring_unwired():
    m = Metrics()
    srv = HealthServer(m, port=0).start()
    try:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/timeseries",
                timeout=5,
            )
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_flightrec_embeds_timeseries():
    from tpu_cc_manager.flightrec import FlightRecorder

    m = Metrics()
    ring = TimeSeriesRing(m, interval_s=10.0, name="agent")
    ring.tick(now=1.0)
    ring.tick(now=11.0)
    rec = FlightRecorder(name="n1", tsring=ring)
    snap = rec.snapshot("test")
    ts = snap["timeseries"]
    assert ts["tsring_version"] == 1
    assert ts["samples"] == 2
    # dumps stay small: the embed carries the derivation, not the
    # raw ring points
    assert "points" not in ts
    # unwired recorders keep the historical shape
    assert "timeseries" not in FlightRecorder(name="n2").snapshot("t")
