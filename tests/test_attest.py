"""Platform attestation (tpu_cc_manager.attest) — the TEE rung of the
evidence chain (VERDICT r4 missing #1 / next #3). The headline drill:
node root rewrites the statefile, re-signs with the node's own pool
key, carries the node's own identity — and is STILL flagged, because
the forged claim contradicts the measured flip history inside the
quote, and extend-only history cannot be rewritten.
"""

import json

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.attest import (
    FakeTpm, PCR_INITIAL, attestation_nonce, extend_pcr, get_attestor,
    judge_attestation, measured_mode, replay_log, verify_quote,
)
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node

KEY = b"aik-test-key"


@pytest.fixture
def tpm(tmp_path, monkeypatch):
    """A FakeTpm rooted in tmp, with env wired so build_evidence and
    judge_attestation resolve the same provider/key."""
    state = tmp_path / "tpm"
    keyfile = tmp_path / "tpm.key"
    keyfile.write_bytes(KEY)
    monkeypatch.setenv("TPU_CC_ATTESTATION", "fake")
    monkeypatch.setenv("TPU_CC_TPM_STATE_DIR", str(state))
    monkeypatch.setenv("TPU_CC_TPM_KEY_FILE", str(keyfile))
    get_attestor(refresh=True)
    yield FakeTpm(state_dir=str(state), key=KEY)
    get_attestor(refresh=True)


def _statefile_backend(tmp_path):
    """Synthetic-sysfs backend with a durable statefile (the thing the
    drill's attacker rewrites)."""
    from tpu_cc_manager.device.tpu import SysfsTpuBackend

    sysfs = tmp_path / "sysfs"
    devd = sysfs / "accel0" / "device"
    devd.mkdir(parents=True)
    (devd / "vendor").write_text("0x1ae0\n")
    (devd / "device").write_text("0x0063\n")
    (tmp_path / "dev").mkdir()
    (tmp_path / "dev" / "accel0").write_text("")
    return SysfsTpuBackend(sysfs_root=str(sysfs),
                           dev_root=str(tmp_path / "dev"),
                           state_dir=str(tmp_path / "state"))


# ------------------------------------------------------- PCR mechanics
def test_extend_and_replay_agree():
    events = ["mode:on", "mode:off", "mode:devtools"]
    pcr = PCR_INITIAL
    for e in events:
        pcr = extend_pcr(pcr, e)
    assert replay_log(events) == pcr
    assert replay_log(events[:-1]) != pcr  # truncation changes the PCR
    assert measured_mode(events) == "devtools"
    assert measured_mode(["boot"]) is None
    assert measured_mode([]) is None


def test_fake_tpm_state_survives_reopen(tmp_path):
    t1 = FakeTpm(state_dir=str(tmp_path / "t"), key=KEY)
    t1.extend("mode:on")
    t1.extend("mode:off")
    # a new handle over the same "hardware" sees the same history
    t2 = FakeTpm(state_dir=str(tmp_path / "t"), key=KEY)
    q = t2.quote("00" * 32)
    assert q["log"] == ["mode:on", "mode:off"]
    assert replay_log(q["log"]) == q["pcr"]
    verdict, _ = verify_quote(q, "00" * 32, key=KEY)
    assert verdict == "ok"


def test_key_rotation_tail_accepts_old_quotes(tmp_path, monkeypatch):
    """ISSUE 12: TPU_CC_TPM_OLD_KEYS[_FILE] is a verify-only rotation
    tail behind the TPU_CC_TPM_KEY[_FILE] primary (the evidence pool
    key's posture, evidence_keys). Mid-rotation, still-old quotes must
    verify instead of reading as forgery; the primary keeps its legacy
    whole-value semantics; quotes under a never-provisioned key still
    fail."""
    from tpu_cc_manager.attest import tpm_key, tpm_keys

    monkeypatch.delenv("TPU_CC_TPM_KEY_FILE", raising=False)
    monkeypatch.delenv("TPU_CC_TPM_OLD_KEYS_FILE", raising=False)
    tpm = FakeTpm(state_dir=str(tmp_path / "t"), key=b"old-key")
    tpm.extend("mode:on")
    nonce = "ab" * 32
    old_quote = tpm.quote(nonce)
    # rotated posture: new primary + old key in the verify-only tail
    monkeypatch.setenv("TPU_CC_TPM_KEY", "new-key")
    monkeypatch.setenv("TPU_CC_TPM_OLD_KEYS", "old-key")
    assert tpm_keys() == (b"new-key", b"old-key")
    assert tpm_key() == b"new-key"  # the PRIMARY signs
    assert verify_quote(old_quote, nonce)[0] == "ok"
    # the node re-quotes under the rotated key (set_key = the drill)
    tpm.set_key(b"new-key")
    assert verify_quote(tpm.quote(nonce), nonce)[0] == "ok"
    # tail dropped after the fleet re-quoted: old quotes now fail
    monkeypatch.delenv("TPU_CC_TPM_OLD_KEYS")
    assert verify_quote(old_quote, nonce)[0] == "mismatch"
    # a quote under a key that was NEVER provisioned fails either way
    stranger = FakeTpm(state_dir=str(tmp_path / "s"), key=b"rogue")
    monkeypatch.setenv("TPU_CC_TPM_OLD_KEYS", "old-key")
    assert verify_quote(stranger.quote(nonce), nonce)[0] == "mismatch"
    # legacy whole-value semantics: a primary containing a newline is
    # ONE key, never silently split into two
    monkeypatch.setenv("TPU_CC_TPM_KEY", "raw\nbinary-ish")
    monkeypatch.delenv("TPU_CC_TPM_OLD_KEYS")
    assert tpm_keys() == (b"raw\nbinary-ish",)
    # retired keys alone must not make this a keyed verifier
    monkeypatch.delenv("TPU_CC_TPM_KEY")
    monkeypatch.setenv("TPU_CC_TPM_OLD_KEYS", "old-key")
    assert tpm_keys() == ()


def test_quote_verification_catches_each_tamper(tmp_path):
    tpm = FakeTpm(state_dir=str(tmp_path / "t"), key=KEY)
    tpm.extend("mode:on")
    nonce = "ab" * 32
    good = tpm.quote(nonce)
    assert verify_quote(good, nonce, key=KEY)[0] == "ok"
    # replayed onto a different document
    assert verify_quote(good, "cd" * 32, key=KEY)[0] == "mismatch"
    # log rewritten without re-folding the PCR
    bad_log = dict(good, log=["mode:devtools"])
    assert verify_quote(bad_log, nonce, key=KEY)[0] == "mismatch"
    # signature from a different key
    other = FakeTpm(state_dir=str(tmp_path / "t"), key=b"other").quote(
        nonce
    )
    assert verify_quote(other, nonce, key=KEY)[0] == "mismatch"
    # keyless verifier: structure checks pass, authentication cannot
    assert verify_quote(good, nonce, key=None)[0] == "unverifiable"


# -------------------------------------------------- evidence integration
def test_build_evidence_attaches_verifying_quote(tmp_path, tpm,
                                                 monkeypatch):
    from tpu_cc_manager.engine import ModeEngine
    from tpu_cc_manager.evidence import build_evidence, verify_evidence

    be = _statefile_backend(tmp_path)
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-secret")
    engine = ModeEngine(set_state_label=lambda v: None,
                        evict_components=False, backend=be)
    assert engine.set_mode("on")
    doc = build_evidence("w1", be)
    assert doc["attestation"]["provider"] == "fake-tpm"
    # the pool-key digest covers the quote
    ok, reason = verify_evidence(doc)
    assert ok, reason
    verdict, detail = judge_attestation(doc, "w1")
    assert verdict == "ok", detail
    # the engine extended on the real transition
    assert measured_mode(doc["attestation"]["log"]) == "on"


def test_idempotent_reconcile_does_not_extend(tmp_path, tpm,
                                              monkeypatch):
    """The measured log is FLIP history: the idempotent fast path must
    not grow it, or steady-state reconciles would bloat every quote."""
    from tpu_cc_manager.engine import ModeEngine

    be = _statefile_backend(tmp_path)
    engine = ModeEngine(set_state_label=lambda v: None,
                        evict_components=False, backend=be)
    assert engine.set_mode("on")
    assert engine.set_mode("on")  # fast path
    assert engine.set_mode("on")
    _, events = tpm._read_state()
    assert events == ["mode:on"]
    assert engine.set_mode("off")  # real transition
    _, events = tpm._read_state()
    assert events == ["mode:on", "mode:off"]


def _forged_backend(tmp_path, monkeypatch):
    """The node-root drill's shared setup: a statefile backend with
    REAL measured history ending at 'off' (a fresh statefile is
    already off, so the honest lifecycle flips on THEN off — the
    first set_mode("off") alone would be the idempotent fast path and
    measure nothing), then root rewrites device truth to 'on' OUTSIDE
    the engine path (no drain, no gate, no measured extend)."""
    from tpu_cc_manager.engine import ModeEngine

    be = _statefile_backend(tmp_path)
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-secret")
    engine = ModeEngine(set_state_label=lambda v: None,
                        evict_components=False, backend=be)
    assert engine.set_mode("on")
    assert engine.set_mode("off")
    for chip in be.find_tpus()[0]:
        be.store.stage(chip.path, "cc", "on")
        be.store.commit(chip.path)
    return be


def test_node_root_forgery_drill(tmp_path, tpm, monkeypatch):
    """THE drill this module exists for: root rewrites the statefile to
    claim CC without a real flip, re-signs with the node's own pool
    key (root can read the mount), and even requests a fresh quote —
    the TPM obliges, but the measured history still says 'off', so the
    forged document lands in attestation mismatch everywhere: judge,
    doctor, and the fleet audit's problems digest."""
    from tpu_cc_manager.doctor import _attestation_check
    from tpu_cc_manager.evidence import (
        audit_evidence, build_evidence, verify_evidence,
    )
    from tpu_cc_manager.fleet import fleet_problems

    be = _forged_backend(tmp_path, monkeypatch)
    forged = build_evidence("w1", be)  # root runs the same tooling
    # the forgery is pool-key perfect...
    ok, _ = verify_evidence(forged)
    assert ok
    assert forged["devices"][0]["cc"] == "on"
    # ...but the quote's measured history contradicts the claim
    verdict, detail = judge_attestation(forged, "w1")
    assert verdict == "mismatch"
    assert "measured flip history" in detail
    assert "'off'" in detail

    # doctor: fail-severity attestation check
    checks = []
    _attestation_check(checks, forged, "w1")
    (c,) = [c for c in checks if c["name"] == "attestation"]
    assert c["severity"] == "fail"

    # fleet audit: attestation_mismatch bucket + problems line
    node = make_node("w1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(forged)})
    audit = audit_evidence([node])
    assert audit["attestation_mismatch"] == ["w1"]
    problems = fleet_problems({"evidence_audit": audit})
    assert any("attestation mismatch" in p for p in problems)


def test_quote_replay_onto_other_document_is_mismatch(tmp_path, tpm,
                                                      monkeypatch):
    """Splicing a genuine quote into a different document breaks the
    nonce commitment even when the attacker re-signs the envelope with
    the pool key."""
    from tpu_cc_manager.engine import ModeEngine
    from tpu_cc_manager.evidence import (
        _canonical, _digest, build_evidence,
    )

    be = _statefile_backend(tmp_path)
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-secret")
    engine = ModeEngine(set_state_label=lambda v: None,
                        evict_components=False, backend=be)
    assert engine.set_mode("on")
    honest = build_evidence("w1", be)
    tampered = dict(honest)
    tampered["timestamp"] = "2031-01-01T00:00:00Z"  # any body change
    tampered.pop("digest")
    tampered["digest"] = _digest(_canonical(tampered), b"pool-secret")
    verdict, detail = judge_attestation(tampered, "w1")
    assert verdict == "mismatch"
    assert "commit" in detail


def test_audit_attestation_missing_mirrors_identity_rules(tmp_path,
                                                          monkeypatch):
    """Missing quotes flag only on MIXED pools or under
    TPU_CC_REQUIRE_ATTESTATION — an all-missing pool simply has no TEE
    configured; quote-bearing pools make the bare node the tell."""
    from tpu_cc_manager.evidence import audit_evidence, build_evidence

    be = _statefile_backend(tmp_path)
    bare_doc = json.dumps(build_evidence("bare", be, key=None))

    def node(name, doc):
        return make_node(name, labels={
            L.TPU_ACCELERATOR_LABEL: "v5p",
            L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
            annotations={L.EVIDENCE_ANNOTATION: doc})

    # uniform quote-less pool: not a finding
    audit = audit_evidence([node("bare", bare_doc)])
    assert audit["attestation_missing"] == []

    # required: flagged even when uniform
    monkeypatch.setenv("TPU_CC_REQUIRE_ATTESTATION", "true")
    audit = audit_evidence([node("bare", bare_doc)])
    assert audit["attestation_missing"] == ["bare"]
    monkeypatch.delenv("TPU_CC_REQUIRE_ATTESTATION")

    # mixed pool: the quote-bearing node makes the bare one the tell
    state = tmp_path / "tpm2"
    keyfile = tmp_path / "tpm2.key"
    keyfile.write_bytes(KEY)
    monkeypatch.setenv("TPU_CC_ATTESTATION", "fake")
    monkeypatch.setenv("TPU_CC_TPM_STATE_DIR", str(state))
    monkeypatch.setenv("TPU_CC_TPM_KEY_FILE", str(keyfile))
    get_attestor(refresh=True)
    try:
        attested_doc = json.dumps(build_evidence("att", be, key=None))
    finally:
        monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
        get_attestor(refresh=True)
    # the attested doc carries node name "att" but judges under its own
    # node; the bare node is the missing one
    audit = audit_evidence([
        node("att", attested_doc), node("bare", bare_doc),
    ])
    assert audit["attestation_missing"] == ["bare"]


def test_unverifiable_bucket_when_no_trust_root(tmp_path, monkeypatch):
    """Quote present, verifier without the attestation key: visible as
    attestation_unverifiable (metric), never a problem line — the
    mid-enablement posture, mirroring identity's unverifiable."""
    from tpu_cc_manager.evidence import audit_evidence, build_evidence
    from tpu_cc_manager.fleet import fleet_problems

    be = _statefile_backend(tmp_path)
    state = tmp_path / "tpm3"
    monkeypatch.setenv("TPU_CC_ATTESTATION", "fake")
    monkeypatch.setenv("TPU_CC_TPM_STATE_DIR", str(state))
    monkeypatch.setenv("TPU_CC_TPM_KEY", "agent-only-key")
    get_attestor(refresh=True)
    try:
        doc = json.dumps(build_evidence("w1", be, key=None))
    finally:
        monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
        monkeypatch.delenv("TPU_CC_TPM_KEY")
        get_attestor(refresh=True)
    n = make_node("w1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: doc})
    audit = audit_evidence([n])
    assert audit["attestation_unverifiable"] == ["w1"]
    assert audit["attestation_mismatch"] == []
    assert not any(
        "attestation" in p
        for p in fleet_problems({"evidence_audit": audit})
    )


def test_get_attestor_resolution(monkeypatch, tmp_path):
    monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
    assert get_attestor(refresh=True) is None
    monkeypatch.setenv("TPU_CC_ATTESTATION", "fake")
    att = get_attestor(refresh=True)
    assert isinstance(att, FakeTpm)
    monkeypatch.setenv("TPU_CC_ATTESTATION", "bogus-provider")
    assert get_attestor(refresh=True) is None
    # auto without a Confidential Space socket: none
    monkeypatch.setenv("TPU_CC_ATTESTATION", "auto")
    monkeypatch.setenv("TPU_CC_CS_SOCKET", str(tmp_path / "nope.sock"))
    assert get_attestor(refresh=True) is None
    monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
    get_attestor(refresh=True)


def test_rollout_refuses_attestation_mismatched_convergence(
        tmp_path, tpm, monkeypatch):
    """The rollout judge holds the same line as the fleet audit: a
    node whose label converged but whose evidence quote contradicts
    the measured flip history (the node-root forgery) must NOT count
    as converged — its group times out naming the attestation
    contradiction."""
    import threading
    import time as _time

    from tpu_cc_manager.engine import ModeEngine
    from tpu_cc_manager.evidence import build_evidence
    from tpu_cc_manager.rollout import Rollout

    be = _statefile_backend(tmp_path)
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-secret")
    engine = ModeEngine(set_state_label=lambda v: None,
                        evict_components=False, backend=be)
    # measured history ends at 'off'...
    assert engine.set_mode("on")
    assert engine.set_mode("off")
    # ...but root rewrites device truth to 'on' and publishes a
    # pool-key-perfect document claiming it
    for chip in be.find_tpus()[0]:
        be.store.stage(chip.path, "cc", "on")
        be.store.commit(chip.path)
    forged = json.dumps(build_evidence("fg1", be))

    kube = FakeKube()
    kube.add_node(make_node("fg1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: forged}))

    # a label-only "agent": converges the state label without touching
    # the planted forged evidence (exactly what the forgery wants)
    stop = threading.Event()

    def agent():
        while not stop.is_set():
            labels = kube.get_node("fg1")["metadata"]["labels"]
            want = labels.get(L.CC_MODE_LABEL)
            if want and labels.get(L.CC_MODE_STATE_LABEL) != want:
                kube.set_node_labels(
                    "fg1", {L.CC_MODE_STATE_LABEL: want})
            _time.sleep(0.02)

    t = threading.Thread(target=agent, daemon=True)
    t.start()
    try:
        report = Rollout(kube, "on", poll_s=0.05,
                         group_timeout_s=1.5).run()
    finally:
        stop.set()
        t.join(timeout=2)
    (group,) = report.groups
    assert group.outcome == "timeout"
    assert "attestation" in group.detail
    assert "measured flip history" in group.detail


def test_keyless_verifier_still_catches_history_contradiction(
        tmp_path, monkeypatch):
    """A verifier WITHOUT the attestation key can't authenticate the
    quote, but the measured-history-vs-claim comparison needs no key
    (nonce + PCR replay are structural): a lazy forger who reuses the
    real TPM's quote still lands in mismatch; only a fully fabricated
    quote passes (caught by the keyed fleet audit)."""
    tpm = FakeTpm(state_dir=str(tmp_path / "t"), key=KEY)
    tpm.extend("mode:off")
    doc = {"version": 1, "node": "k1", "devices": [
        {"path": "/dev/accel0", "cc": "on", "ici": None}]}
    from tpu_cc_manager.attest import attestation_nonce

    doc["attestation"] = tpm.quote(attestation_nonce(doc))
    verdict, detail = judge_attestation(doc, "k1", key=None)
    assert verdict == "mismatch"
    assert "needs no key" in detail
    # an honest doc under a keyless verifier reads unverifiable
    honest = {"version": 1, "node": "k1", "devices": [
        {"path": "/dev/accel0", "cc": "off", "ici": None}]}
    honest["attestation"] = tpm.quote(attestation_nonce(honest))
    verdict, _ = judge_attestation(honest, "k1", key=None)
    assert verdict == "unverifiable"


def test_idle_agent_requotes_on_tpm_key_rotation(tmp_path, monkeypatch):
    """Rotating the attestation key (TPU_CC_TPM_KEY_FILE swapped in
    place, like any mounted Secret) must re-sign quotes on the idle
    tick, exactly as a rotated pool key re-signs digests — otherwise
    every idle node's quote fails verification under the new key
    until the next periodic sync."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    be = _statefile_backend(tmp_path)
    kube = FakeKube()
    kube.add_node(make_node("tk-node"))
    state = tmp_path / "tpm"
    keyfile = tmp_path / "tpm.key"
    keyfile.write_bytes(b"aik-v1")
    monkeypatch.setenv("TPU_CC_ATTESTATION", "fake")
    monkeypatch.setenv("TPU_CC_TPM_STATE_DIR", str(state))
    monkeypatch.setenv("TPU_CC_TPM_KEY_FILE", str(keyfile))
    get_attestor(refresh=True)
    try:
        cfg = AgentConfig(node_name="tk-node", drain_strategy="none",
                          health_port=0, emit_events=False)
        agent = CCManagerAgent(kube, cfg, backend=be)
        assert agent.reconcile("on") is True
        assert agent.flush_events(timeout=10)
        doc = json.loads(kube.get_node("tk-node")["metadata"]
                         ["annotations"][L.EVIDENCE_ANNOTATION])
        assert judge_attestation(doc, "tk-node", key=b"aik-v1")[0] == "ok"

        # rotate the attestation key in place; force the throttled
        # check due and idle-tick
        keyfile.write_bytes(b"aik-v2")
        agent._evidence_key_check_due = 0.0
        agent._maybe_repair()
        assert agent.flush_events(timeout=10)
        doc = json.loads(kube.get_node("tk-node")["metadata"]
                         ["annotations"][L.EVIDENCE_ANNOTATION])
        assert judge_attestation(doc, "tk-node", key=b"aik-v2")[0] == "ok"
    finally:
        get_attestor(refresh=True)


# ------------------------------------------------- Confidential Space
@pytest.fixture(scope="module")
def cs_rsa(tmp_path_factory):
    """Real RSA keypair via the openssl CLI for Confidential Space
    token verification (same shape as identity's RS256 fixture; an
    implementation sharing nothing with the verifier under test)."""
    import base64
    import shutil
    import subprocess

    if shutil.which("openssl") is None:
        pytest.skip("openssl binary unavailable")
    d = tmp_path_factory.mktemp("cs-rsa")
    key = d / "key.pem"
    r = subprocess.run(["openssl", "genrsa", "-out", str(key), "2048"],
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"openssl genrsa unavailable: {r.stderr}")
    mod = subprocess.run(
        ["openssl", "rsa", "-in", str(key), "-noout", "-modulus"],
        capture_output=True, text=True, check=True,
    ).stdout.strip()
    n = bytes.fromhex(mod.split("=", 1)[1])

    def b64url(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    jwks = {"keys": [{
        "kty": "RSA", "kid": "cs-kid", "alg": "RS256", "use": "sig",
        "n": b64url(n), "e": b64url((65537).to_bytes(3, "big")),
    }]}
    return str(key), jwks


def _mint_cs_token(key_path, nonce, *, exp_delta=3600.0):
    """An attestation token shaped like Confidential Space's: RS256,
    eat_nonce claim carrying the evidence nonce."""
    import base64
    import subprocess
    import tempfile
    import time as _time

    def b64url(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    now = _time.time()
    header = {"alg": "RS256", "typ": "JWT", "kid": "cs-kid"}
    payload = {
        "iss": "https://confidentialcomputing.googleapis.com",
        "aud": "tpu-cc-manager",
        "iat": int(now), "exp": int(now + exp_delta),
        "eat_nonce": [nonce],
        "submods": {"container": {"image_digest": "sha256:feedface"}},
    }
    signing_input = (
        b64url(json.dumps(header, sort_keys=True).encode()) + "." +
        b64url(json.dumps(payload, sort_keys=True).encode())
    )
    with tempfile.NamedTemporaryFile(suffix=".bin") as f:
        f.write(signing_input.encode())
        f.flush()
        sig = subprocess.run(
            ["openssl", "dgst", "-sha256", "-sign", key_path, f.name],
            capture_output=True, check=True,
        ).stdout
    return signing_input + "." + b64url(sig)


def test_confidential_space_token_judging(cs_rsa, tmp_path,
                                          monkeypatch):
    """The real-TEE path end to end at the verifier: a CS-shaped RS256
    token with the right eat_nonce verifies offline against the
    provisioned JWKS; a replayed token (wrong nonce) is a mismatch; an
    aged-out token is 'expired' (staleness, missing-shaped in the
    audit — never the forgery alarm); no JWKS means unverifiable."""
    from tpu_cc_manager.attest import attestation_nonce
    from tpu_cc_manager.evidence import audit_evidence

    key_path, jwks = cs_rsa
    jwks_file = tmp_path / "jwks.json"
    jwks_file.write_text(json.dumps(jwks))

    doc = {"version": 1, "node": "csn", "devices": [
        {"path": "/dev/accel0", "cc": "on", "ici": None}]}
    nonce = attestation_nonce(doc)
    doc["attestation"] = {
        "version": 1, "provider": "confidential-space",
        "nonce": nonce, "token": _mint_cs_token(key_path, nonce),
    }

    # no JWKS provisioned: unverifiable, never an alarm
    monkeypatch.delenv("TPU_CC_ATTESTATION_JWKS_FILE", raising=False)
    assert judge_attestation(doc, "csn")[0] == "unverifiable"

    monkeypatch.setenv("TPU_CC_ATTESTATION_JWKS_FILE", str(jwks_file))
    verdict, detail = judge_attestation(doc, "csn")
    assert verdict == "ok", detail

    # replay onto a different document: nonce no longer commits
    other = dict(doc)
    other["devices"] = [{"path": "/dev/accel0", "cc": "off",
                         "ici": None}]
    assert judge_attestation(other, "csn")[0] == "mismatch"

    # aged-out token: expired (classed with missing by the audit)
    stale = {"version": 1, "node": "csn", "devices": [
        {"path": "/dev/accel0", "cc": "on", "ici": None}]}
    snonce = attestation_nonce(stale)
    stale["attestation"] = {
        "version": 1, "provider": "confidential-space",
        "nonce": snonce,
        "token": _mint_cs_token(key_path, snonce, exp_delta=-60),
    }
    assert judge_attestation(stale, "csn")[0] == "expired"
    # the audit only judges attestation on digest-plausible documents
    from tpu_cc_manager.evidence import _canonical, _digest

    stale["digest"] = _digest(_canonical(stale), None)
    node = make_node("csn", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(stale)})
    audit = audit_evidence([node], key=None)
    assert audit["attestation_mismatch"] == []
    assert audit["attestation_missing"] == ["csn"]


def test_forged_attestation_fails_doctor_and_webhook_steers_away(
        tmp_path, tpm, monkeypatch):
    """The scheduler-level consequence of the node-root drill: the
    forged node's doctor verdict goes unhealthy (attestation check
    fails), cc.doctor.ok flips to false, and with
    TPU_CC_WEBHOOK_REQUIRE_DOCTOR=true the admission webhook pins
    confidential pods onto doctor-healthy nodes — the forged node
    stops receiving requires-cc workloads without any new webhook
    machinery."""
    from tpu_cc_manager.doctor import publish_report, run_doctor
    from tpu_cc_manager.evidence import build_evidence
    from tpu_cc_manager.webhook import mutate_pod

    be = _forged_backend(tmp_path, monkeypatch)

    kube = FakeKube()
    kube.add_node(make_node("fw1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(
            build_evidence("fw1", be))}))
    report = run_doctor(kube=kube, node_name="fw1", backend=be)
    att_checks = [c for c in report["checks"]
                  if c["name"] == "attestation"]
    assert att_checks and att_checks[0]["severity"] == "fail"
    assert report["ok"] is False
    assert publish_report(kube, "fw1", report)
    labels = kube.get_node("fw1")["metadata"]["labels"]
    assert labels[L.DOCTOR_OK_LABEL] == "false"

    # the webhook's doctor pin now excludes this node by construction
    monkeypatch.setenv("TPU_CC_WEBHOOK_REQUIRE_DOCTOR", "true")
    pod = {"metadata": {"labels": {L.REQUIRES_CC_LABEL: "on"}},
           "spec": {}}
    ops = mutate_pod(pod)
    values = {o["path"]: o.get("value") for o in ops}
    doctor_pin = next(v for p, v in values.items() if "doctor" in p)
    assert doctor_pin == "true"
    assert labels[L.DOCTOR_OK_LABEL] != doctor_pin


def test_fleet_metrics_carry_attestation_buckets(tmp_path, tpm,
                                                 monkeypatch):
    """The audit's attestation buckets must reach /metrics — a bucket
    that exists only in the JSON report cannot be alerted on."""
    from tpu_cc_manager.evidence import audit_evidence, build_evidence
    from tpu_cc_manager.fleet import FleetMetrics

    be = _forged_backend(tmp_path, monkeypatch)
    node = make_node("m1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(
            build_evidence("m1", be))})
    audit = audit_evidence([node])
    assert audit["attestation_mismatch"] == ["m1"]
    metrics = FleetMetrics()
    metrics.update({
        "nodes": 1, "mode_counts": {}, "needs_flip": [], "failed": [],
        "incoherent_slices": [], "half_flipped_slices": [],
        "evidence_audit": audit,
    })
    body = metrics.render()
    assert ('tpu_cc_fleet_evidence_issues'
            '{issue="attestation_mismatch"} 1') in body
    assert ('tpu_cc_fleet_evidence_issues'
            '{issue="attestation_missing"} 0') in body


def test_attestation_outage_latch(tmp_path, monkeypatch):
    """VERDICT r5 weak #5: identity's cross-scan latch, granted to
    attestation for the failure identity cannot see. A fleet whose
    quotes VERIFIED once dropping wholesale to 'unverifiable' means the
    VERIFIER lost its trust root — that must be a problems line, not a
    metric fade. A fleet still mid-enablement (never verified) stays
    quiet."""
    from tpu_cc_manager.evidence import audit_evidence, build_evidence
    from tpu_cc_manager.fleet import fleet_problems

    be = _statefile_backend(tmp_path)
    keyfile = tmp_path / "aik.key"
    keyfile.write_bytes(KEY)
    monkeypatch.setenv("TPU_CC_ATTESTATION", "fake")
    monkeypatch.setenv("TPU_CC_TPM_STATE_DIR", str(tmp_path / "tpm"))
    monkeypatch.setenv("TPU_CC_TPM_KEY_FILE", str(keyfile))
    get_attestor(refresh=True)
    try:
        doc = json.dumps(build_evidence("n1", be, key=None))
    finally:
        monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
        get_attestor(refresh=True)

    def node(name):
        return make_node(name, labels={
            L.TPU_ACCELERATOR_LABEL: "v5p",
            L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
            annotations={L.EVIDENCE_ANNOTATION: doc})

    # scan 1, verifier keyed: quote verifies -> latch feed True, quiet
    audit = audit_evidence([node("n1")])
    assert audit["attestation_seen"] is True
    assert audit["attestation_outage"] == []

    # verifier loses the key: unverifiable everywhere
    monkeypatch.delenv("TPU_CC_TPM_KEY_FILE")
    # fresh fleet (latch never armed): enablement-in-progress, quiet
    audit = audit_evidence([node("n1")])
    assert audit["attestation_seen"] is False
    assert audit["attestation_unverifiable"] == ["n1"]
    assert audit["attestation_outage"] == []
    assert not any("trust root" in p for p in
                   fleet_problems({"evidence_audit": audit}))

    # latched fleet: the same scan is now a loud verifier outage
    audit = audit_evidence([node("n1")], attestation_seen_before=True)
    assert audit["attestation_outage"] == ["n1"]
    problems = fleet_problems({"evidence_audit": audit})
    assert any("trust root" in p and "n1" in p for p in problems)


def test_fleet_controller_arms_attestation_latch_across_scans(
        tmp_path, monkeypatch):
    """End to end through the controller: keyed scan arms the sticky
    latch; the key vanishing turns the NEXT scan's report loud."""
    from tpu_cc_manager.evidence import build_evidence
    from tpu_cc_manager.fleet import FleetController

    be = _statefile_backend(tmp_path)
    keyfile = tmp_path / "aik.key"
    keyfile.write_bytes(KEY)
    monkeypatch.setenv("TPU_CC_ATTESTATION", "fake")
    monkeypatch.setenv("TPU_CC_TPM_STATE_DIR", str(tmp_path / "tpm"))
    monkeypatch.setenv("TPU_CC_TPM_KEY_FILE", str(keyfile))
    get_attestor(refresh=True)
    try:
        doc = json.dumps(build_evidence("f1", be, key=None))
    finally:
        monkeypatch.setenv("TPU_CC_ATTESTATION", "none")
        get_attestor(refresh=True)
    kube = FakeKube()
    kube.add_node(make_node("f1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: doc}))
    ctrl = FleetController(kube, interval_s=30, port=0)
    report = ctrl.scan_once()
    assert report["evidence_audit"]["attestation_outage"] == []
    assert not any("trust root" in p for p in report["problems"])
    # verifier key lost between scans
    monkeypatch.delenv("TPU_CC_TPM_KEY_FILE")
    report = ctrl.scan_once()
    assert report["evidence_audit"]["attestation_outage"] == ["f1"]
    assert any("trust root" in p for p in report["problems"])
