"""The incremental delta-tick planner (ISSUE 19): dirty-row/slice
masks, the device-resident sharded session, incremental == full
checksum pins, transfer-count pins, and the delta paths the tentpole
leans on (remove-swap x compaction, doctor-details cleanup, sync's
fingerprint-skip counts, the events-dropped counter)."""

import copy
import json
import threading

import numpy as np
import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager import plan
from tpu_cc_manager.k8s.objects import make_node


def _node(name, desired="on", observed="on", slice_id=None, taint=False,
          doctor=None, ev=None):
    labels = {L.CC_MODE_LABEL: desired, L.CC_MODE_STATE_LABEL: observed}
    if slice_id:
        labels[L.TPU_SLICE_LABEL] = slice_id
    node = make_node(name, labels=labels)
    ann = node["metadata"].setdefault("annotations", {})
    if doctor is not None:
        ann[L.DOCTOR_ANNOTATION] = json.dumps(doctor)
    if ev is not None:
        ann[L.EVIDENCE_ANNOTATION] = json.dumps({"timestamp": ev})
    if taint:
        node.setdefault("spec", {})["taints"] = [
            {"key": L.FLIP_TAINT_KEY, "effect": "NoSchedule"}
        ]
    return node


def _mixed_nodes(n=40):
    nodes = {}
    for i in range(n):
        nodes[f"n{i:03d}"] = _node(
            f"n{i:03d}", slice_id=f"s{i // 4}",
            desired="on", observed="on" if i % 5 else "off",
            taint=(i % 7 == 0),
            doctor=({"ok": False, "fail": ["hw"]} if i % 11 == 0
                    else {"ok": True}),
        )
    return nodes


def _norm(report):
    """Order-insensitive report compare (name lists are set-like)."""
    r = copy.deepcopy(report)
    for key in ("needs_flip", "failed", "flipping", "stale_evidence",
                "incoherent_slices", "half_flipped_slices"):
        r[key] = sorted(r[key])
    r["doctor"] = {
        k: (sorted(v, key=lambda x: json.dumps(x, sort_keys=True))
            if isinstance(v, list) else v)
        for k, v in r["doctor"].items()
    }
    return r


def _encode_all(nodes):
    enc = plan.FleetEncoding()
    for nd in nodes.values():
        enc.apply(copy.deepcopy(nd))
    return enc


def _legacy_report(nodes):
    return plan.analyze_encoding(_encode_all(nodes))


def test_incremental_matches_full_after_mixed_deltas():
    """The core pin: a session report after mode flips, an add, a
    swap-remove and a slice move equals a from-scratch legacy tick over
    the same fleet — and the rebuild only happened once."""
    nodes = _mixed_nodes()
    enc = _encode_all(nodes)
    sess = plan.TickSession(full_every=0)
    assert _norm(plan.analyze_encoding(enc, sess)) == _norm(
        _legacy_report(nodes))
    assert sess.stats["rebuilds"] == 1

    for i in (3, 8, 21):
        nd = copy.deepcopy(nodes[f"n{i:03d}"])
        nd["metadata"]["labels"][L.CC_MODE_STATE_LABEL] = "off"
        nodes[f"n{i:03d}"] = nd
        enc.apply(nd)
    nodes["n100"] = _node("n100", slice_id="s2", desired="off",
                          observed="on")
    enc.apply(nodes["n100"])
    enc.remove("n005")
    del nodes["n005"]
    moved = copy.deepcopy(nodes["n012"])
    moved["metadata"]["labels"][L.TPU_SLICE_LABEL] = "s9"
    nodes["n012"] = moved
    enc.apply(moved)

    assert _norm(plan.analyze_encoding(enc, sess)) == _norm(
        _legacy_report(nodes))
    assert sess.stats["rebuilds"] == 1
    assert sess.stats["incr_ticks"] == 1


def test_forced_full_tick_checksum_pin():
    """The tier-1 incremental == full pin: a forced full tick runs the
    whole device kernel over the resident block and compares EVERY
    output array against the incrementally maintained state — it
    returning (instead of raising IncrementalDriftError) IS the
    checksum pin, and the report still matches legacy."""
    nodes = _mixed_nodes()
    enc = _encode_all(nodes)
    sess = plan.TickSession(full_every=0)
    plan.analyze_encoding(enc, sess)
    nd = copy.deepcopy(nodes["n002"])
    nd["metadata"]["labels"][L.CC_MODE_STATE_LABEL] = "failed"
    nodes["n002"] = nd
    enc.apply(nd)
    report = plan.analyze_encoding(enc, sess, force_full=True)
    assert _norm(report) == _norm(_legacy_report(nodes))
    assert sess.stats["verifies"] == 1
    assert sess.last_checksum is not None
    res = sess.tick(enc, force_full=True)
    assert res.kind == "full"
    assert res.checksum == sess.last_checksum


def test_drift_raises_and_next_tick_rebuilds():
    """Divergence between the incremental state and the full kernel is
    a HARD failure, and the session recovers by rebuilding from
    encoding truth on the next tick."""
    nodes = _mixed_nodes()
    enc = _encode_all(nodes)
    sess = plan.TickSession(full_every=0)
    plan.analyze_encoding(enc, sess)
    sess._state["mode_counts"][0] += 1  # inject drift
    with pytest.raises(plan.IncrementalDriftError):
        plan.analyze_encoding(enc, sess, force_full=True)
    assert _norm(plan.analyze_encoding(enc, sess)) == _norm(
        _legacy_report(nodes))
    assert sess.stats["rebuilds"] == 2


def test_zero_column_round_trips_between_ticks():
    """The donation contract's observable: node columns are uploaded
    ONCE per rebuild (8 device_puts) and never again — steady-state
    incremental ticks (including verifying full ticks) move only the
    kb-sized delta operands, and a tick with nothing dirty dispatches
    nothing at all."""
    nodes = _mixed_nodes()
    enc = _encode_all(nodes)
    sess = plan.TickSession(full_every=0)
    plan.analyze_encoding(enc, sess)
    assert sess.stats["column_puts"] == 8
    for round_ in range(3):
        for i in (1, 6, 17):
            nd = copy.deepcopy(nodes[f"n{i:03d}"])
            nd["metadata"]["labels"][L.CC_MODE_STATE_LABEL] = (
                "on" if round_ % 2 else "off")
            nodes[f"n{i:03d}"] = nd
            enc.apply(nd)
        plan.analyze_encoding(enc, sess)
    plan.analyze_encoding(enc, sess, force_full=True)
    assert sess.stats["column_puts"] == 8, sess.stats
    assert sess.stats["delta_puts"] > 0
    assert sess.stats["delta_rows"] >= 9
    # nothing dirty -> the cached report, no dispatch, no transfers
    before = dict(sess.stats)
    plan.analyze_encoding(enc, sess)
    assert sess.stats["cached_ticks"] == before["cached_ticks"] + 1
    assert sess.stats["delta_puts"] == before["delta_puts"]
    assert sess.stats["column_puts"] == 8


def test_bucket_growth_triggers_rebuild_and_stays_correct():
    """Crossing a node-bucket boundary is compile geometry: the session
    must rebuild (new block, new kernels) and keep report parity."""
    nodes = _mixed_nodes(40)
    enc = _encode_all(nodes)
    sess = plan.TickSession(full_every=0)
    plan.analyze_encoding(enc, sess)
    for i in range(40, 70):  # bucket 64 -> 128
        nodes[f"n{i:03d}"] = _node(f"n{i:03d}", slice_id=f"s{i // 4}",
                                   observed="off" if i % 3 else "on")
        enc.apply(nodes[f"n{i:03d}"])
    assert _norm(plan.analyze_encoding(enc, sess)) == _norm(
        _legacy_report(nodes))
    assert sess.stats["rebuilds"] == 2
    assert sess.node_bucket == plan.bucket_nodes(70)


def test_single_device_mesh_parity(monkeypatch):
    """The 1-device CPU path runs the same sharded program and must
    produce the identical report (the psum/pmin/pmax combines make
    1-device == multi-chip — no Python fallback path to drift)."""
    monkeypatch.setenv("TPU_CC_PLANNER_MESH", "1")
    nodes = _mixed_nodes(24)
    enc = _encode_all(nodes)
    sess = plan.TickSession(full_every=0)
    r1 = plan.analyze_encoding(enc, sess)
    nd = copy.deepcopy(nodes["n003"])
    nd["metadata"]["labels"][L.CC_MODE_STATE_LABEL] = "off"
    nodes["n003"] = nd
    enc.apply(nd)
    r2 = plan.analyze_encoding(enc, sess, force_full=True)
    assert _norm(r1) == _norm(_legacy_report(
        {k: v for k, v in nodes.items() if k != "n003"}
        | {"n003": _mixed_nodes(24)["n003"]}))
    assert _norm(r2) == _norm(_legacy_report(nodes))


def test_remove_swap_with_last_interacts_with_compaction():
    """Satellite: swap-with-last removal while slice-id compaction
    fires. A churn of ephemeral solo slices drives dead slots past the
    compaction threshold; removing rows mid-churn exercises the
    released-sid-then-swap path, and the session must stay in lockstep
    the whole way."""
    nodes = {}
    enc = plan.FleetEncoding()
    sess = plan.TickSession(full_every=0)
    for i in range(30):
        nodes[f"n{i:03d}"] = _node(f"n{i:03d}", slice_id=f"s{i // 3}",
                                   observed="off" if i % 4 else "on")
        enc.apply(nodes[f"n{i:03d}"])
    plan.analyze_encoding(enc, sess)
    for round_ in range(25):
        # ephemeral slice churn on one node drives dead-slot growth
        nd = _node("churn", slice_id=f"eph-{round_}", observed="off")
        nodes["churn"] = nd
        enc.apply(nd)
        if round_ % 5 == 2:
            victim = f"n{round_:03d}"
            enc.remove(victim)  # swaps the LAST row into the hole
            nodes.pop(victim, None)
        assert _norm(plan.analyze_encoding(enc, sess)) == _norm(
            _legacy_report(nodes))
    # internal invariants survived: membership sets mirror the column
    n = len(enc._names)
    for sid, rows in enc._slice_rows.items():
        for row in rows:
            assert row < n and int(enc._slice[row]) == sid
    assert all(v < plan.bucket_nodes(n)
               for v in enc._slice_index.values())


def test_doctor_details_cleanup_on_remove():
    """Satellite: removing a node drops its _doctor_details entry —
    a stale entry would resurrect a dead node's verdict in the next
    report's doctor details."""
    enc = plan.FleetEncoding()
    enc.apply(_node("sick", doctor={"ok": False, "fail": ["iommu"]}))
    enc.apply(_node("fine", doctor={"ok": True}))
    assert "sick" in enc._doctor_details
    assert enc.remove("sick")
    assert "sick" not in enc._doctor_details
    report = plan.analyze_encoding(enc)
    assert report["doctor"]["failing"] == []


def test_sync_changed_count_under_fingerprint_skips():
    """Satellite: sync() returns how many rows actually changed —
    unchanged nodes fingerprint-skip, removals count."""
    enc = plan.FleetEncoding()
    nodes = [_node(f"n{i}", observed="on") for i in range(6)]
    assert enc.sync(nodes) == 6
    assert enc.sync(nodes) == 0  # pure fingerprint compares
    nodes[2] = _node("n2", observed="off")
    assert enc.sync(nodes) == 1
    assert enc.sync(nodes[:-1]) == 1  # n5 vanished -> one removal
    assert len(enc) == 5


def test_apply_event_drop_counts():
    """Satellite: malformed watch events are dropped (never thrown in
    a watch thread) AND counted — silent drops are observable."""
    enc = plan.FleetEncoding()
    enc.apply_event("ADDED", {"metadata": {}})  # no name -> KeyError
    enc.apply_event("ADDED", {"metadata": {"name": "ok", "labels": {}}})
    assert enc.events_dropped == 1
    assert len(enc) == 1


def test_events_dropped_total_rendered_by_fleet_metrics():
    """The counter reaches /metrics through the reflection path: the
    scan mirrors the encoding's total onto the FleetMetrics counter."""
    from tpu_cc_manager.fleet import FleetController
    from tpu_cc_manager.k8s.fake import FakeKube

    kube = FakeKube()
    kube.add_node(make_node("n1", labels={
        L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on",
    }))
    ctrl = FleetController(kube, port=0)
    ctrl._encoding.apply_event("ADDED", {"metadata": {}})  # dropped
    ctrl.scan_once()
    text = ctrl.metrics.render()
    assert "tpu_cc_planner_events_dropped_total 1" in text, text


def test_policy_scratch_reuses_device_buffers():
    """Satellite: analyze_pools with a PoolScanScratch matches the
    throwaway-encoding path exactly, and repeated scans allocate NO
    new device buffers (column_puts flat after the first rebuild) —
    even when a policy's target mode changes."""
    nodes = _mixed_nodes(16)
    pools = [
        ("pool-a", "on",
         [copy.deepcopy(nodes[f"n{i:03d}"]) for i in (1, 2, 3, 4)]),
        ("pool-b", "off",
         [copy.deepcopy(nodes[f"n{i:03d}"]) for i in (8, 9)]),
    ]
    scratch = plan.PoolScanScratch()
    assert plan.analyze_pools(pools, scratch=scratch) == \
        plan.analyze_pools(pools)
    puts = scratch.session.stats["column_puts"]
    assert puts == 8
    assert plan.analyze_pools(pools, scratch=scratch) == \
        plan.analyze_pools(pools)
    assert scratch.session.stats["column_puts"] == puts
    retarget = [("pool-a", "off", pools[0][2]), pools[1]]
    assert plan.analyze_pools(retarget, scratch=scratch) == \
        plan.analyze_pools(retarget)
    assert scratch.session.stats["column_puts"] == puts


def test_fleet_scan_skips_sync_behind_live_delta_feed():
    """With a live delta feed the scan trusts apply_event and skips the
    per-scan list reconcile; a feed gap (or cadence) forces the next
    scan to sync again."""
    from tpu_cc_manager.fleet import FleetController
    from tpu_cc_manager.k8s.fake import FakeKube

    def fleet_node(name):
        return make_node(name, labels={
            L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
            L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on",
        })

    kube = FakeKube()
    kube.add_node(fleet_node("n1"))
    ctrl = FleetController(kube, port=0)
    assert ctrl.scan_once()["nodes"] == 1
    # live feed: a list-only change is invisible until resync
    ctrl._delta_feed_active = True
    kube.add_node(fleet_node("n2"))
    assert ctrl.scan_once()["nodes"] == 1  # sync skipped
    # the same change via the delta feed IS visible
    ctrl._on_watch_event("ADDED", kube.get_node("n2"))
    assert ctrl.scan_once()["nodes"] == 2
    # a feed gap forces the next scan to list-reconcile
    kube.add_node(fleet_node("n3"))
    assert ctrl.scan_once()["nodes"] == 2
    ctrl._watch_gap()
    assert ctrl.scan_once()["nodes"] == 3
    # cadence resync: the Nth skipped scan reconciles regardless
    ctrl.sync_every = 1
    kube.add_node(fleet_node("n4"))
    reports = [ctrl.scan_once()["nodes"] for _ in range(2)]
    assert reports[-1] == 4


def test_run_node_watch_fires_on_gap_per_fresh_connect():
    """on_gap fires at every from-scratch (re)connect — initial
    establishment and after a stream failure — before the gap-covering
    wake, so the woken scan already knows to resync."""
    import logging

    from tpu_cc_manager.k8s.client import ApiException
    from tpu_cc_manager.watch import run_node_watch

    stop = threading.Event()
    gaps = []
    wakes = []

    class GapKube:
        calls = 0

        def watch_nodes(self, resource_version=None, timeout_s=None):
            GapKube.calls += 1
            if GapKube.calls == 1:
                def gen():
                    yield "ADDED", {"metadata": {
                        "name": "a", "resourceVersion": "5"}}
                    raise ApiException(500, "stream broke")
                return gen()
            stop.set()
            return iter(())

    def on_gap():
        gaps.append(len(wakes))  # records wakes-at-gap-time

    run_node_watch(
        GapKube(), stop, lambda: wakes.append(1),
        timeout_s=1, backoff_s=0.01,
        logger=logging.getLogger("test"), who="test",
        on_gap=on_gap,
    )
    assert len(gaps) == 2  # initial connect + post-failure reconnect
    # each gap preceded its wake (on_gap fires first)
    assert gaps[0] == 0 and gaps[1] <= len(wakes)
