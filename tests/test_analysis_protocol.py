"""ccaudit v2 — the flow-sensitive protocol rule families.

Same fixture idiom as test_analysis.py: inline snippets through
``analyze_source`` for the per-module rules, hand-built ``Module`` pairs
through ``analyze_modules`` for the cross-module liveness pass, and a
tmp-dir manifest tree for the code↔manifest drift pass (the ABBA-style
fixture: a key the code does not export must fail, both through the
library entry point and through the CLI gate itself).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.analysis import (
    analyze_source,
    diff_against_baseline,
    load_baseline,
    write_baseline,
)
from tpu_cc_manager.analysis.core import Module, analyze_modules
from tpu_cc_manager.analysis.manifests import (
    MANIFEST_GLOBS,
    code_protocol_keys,
    manifest_findings,
)
from tpu_cc_manager.modes import VALID_MODES


def run(src: str, relpath: str = "tpu_cc_manager/snippet.py"):
    return analyze_source(textwrap.dedent(src), relpath)


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------- protocol-literal


def test_raw_failed_into_state_label_flagged():
    (f,) = run(
        """
        class A:
            def bad(self):
                self._set_state_label("failed")
        """
    )
    assert f.rule == "protocol-literal"
    assert "'failed'" in f.message


def test_state_failed_constant_passes():
    assert run(
        """
        from tpu_cc_manager.modes import STATE_FAILED

        class A:
            def good(self):
                self._set_state_label(STATE_FAILED)
        """
    ) == []


def test_mode_value_constant_passes():
    assert run(
        """
        from tpu_cc_manager.modes import Mode

        def good(kube, node):
            set_cc_mode_state_label(kube, node, Mode.ON.value)
        """
    ) == []


def test_raw_literal_through_local_assignment_flagged():
    (f,) = run(
        """
        def bad(kube, node):
            value = "failed"
            set_cc_mode_state_label(kube, node, value)
        """
    )
    assert f.rule == "protocol-literal"
    assert f.line == 4


def test_constant_through_local_assignment_passes():
    assert run(
        """
        from tpu_cc_manager.modes import STATE_FAILED

        def good(kube, node):
            value = STATE_FAILED
            set_cc_mode_state_label(kube, node, value)
        """
    ) == []


def test_unknowable_value_passes():
    # the rules only fire on what they can prove — a parameter is UNKNOWN
    assert run(
        """
        def publish(kube, node, value):
            set_cc_mode_state_label(kube, node, value)
        """
    ) == []


def test_one_hop_call_summary_flags_raw_argument():
    # publish()'s parameter flows into the sink; the raw literal at the
    # same-module call site is one interprocedural hop away
    (f,) = run(
        """
        class A:
            def publish(self, value):
                set_cc_mode_state_label(self.kube, self.node, value)

            def bad(self):
                self.publish("failed")
        """
    )
    assert f.rule == "protocol-literal"
    assert "publish" in f.message


def test_one_hop_call_summary_constant_passes():
    assert run(
        """
        from tpu_cc_manager.modes import STATE_FAILED

        class A:
            def publish(self, value):
                set_cc_mode_state_label(self.kube, self.node, value)

            def good(self):
                self.publish(STATE_FAILED)
        """
    ) == []


def test_raw_mode_in_label_dict_value_flagged():
    (f,) = run(
        """
        from tpu_cc_manager import labels as L

        def bad(kube, node):
            kube.set_node_labels(node, {L.CC_MODE_LABEL: "on"})
        """
    )
    assert f.rule == "protocol-literal"


def test_mode_constant_in_label_dict_value_passes():
    assert run(
        """
        from tpu_cc_manager import labels as L
        from tpu_cc_manager.modes import Mode

        def good(kube, node):
            kube.set_node_labels(node, {L.CC_MODE_LABEL: Mode.ON.value})
        """
    ) == []


def test_flowed_raw_key_in_label_dict_flagged():
    # a raw key LITERAL is label-literal's finding; a key that FLOWED
    # through a local is the dataflow rule's
    findings = run(
        """
        def bad(kube, node, v):
            key = "tpu.google.com/cc.mode"
            kube.set_node_labels(node, {key: v})
        """
    )
    assert rules_of(findings) == ["label-literal", "protocol-literal"]


def test_branch_join_keeps_raw_from_either_branch():
    # a clean else-branch must not launder the legacy branch's literal:
    # branches are joined worst-class-wins
    (f,) = run(
        """
        from tpu_cc_manager.modes import Mode

        def bad(kube, node, legacy):
            if legacy:
                mode = "on"
            else:
                mode = Mode.ON.value
            set_cc_mode_state_label(kube, node, mode)
        """
    )
    assert f.rule == "protocol-literal"


def test_branch_join_both_branches_clean_passes():
    assert run(
        """
        from tpu_cc_manager.modes import Mode, STATE_FAILED

        def good(kube, node, ok):
            if ok:
                mode = Mode.ON.value
            else:
                mode = STATE_FAILED
            set_cc_mode_state_label(kube, node, mode)
        """
    ) == []


def test_branch_join_keeps_taint_from_either_branch():
    (f,) = run(
        """
        import subprocess
        from tpu_cc_manager import labels as L
        from tpu_cc_manager.modes import parse_mode

        def bad(node, cond, x):
            if cond:
                mode = node["metadata"]["labels"].get(L.CC_MODE_LABEL)
            else:
                mode = parse_mode(x)
            subprocess.run(["cc-tool", mode])
        """
    )
    assert f.rule == "unvalidated-mode"


def test_protocol_literal_pragma_suppresses():
    assert run(
        """
        class A:
            def deliberate(self):
                self._set_state_label("failed")  # ccaudit: allow-protocol-literal(failure-injection fixture)
        """
    ) == []


def test_non_protocol_string_at_sink_passes():
    assert run(
        """
        def good(kube, node):
            set_cc_mode_state_label(kube, node, "true")
        """
    ) == []


# ------------------------------------------------------- unvalidated-mode


def test_label_read_into_subprocess_flagged():
    (f,) = run(
        """
        import subprocess
        from tpu_cc_manager import labels as L

        def bad(node):
            mode = node["metadata"]["labels"].get(L.CC_MODE_LABEL)
            subprocess.run(["cc-tool", mode])
        """
    )
    assert f.rule == "unvalidated-mode"
    assert "parse_mode" in f.message


def test_label_read_into_device_call_flagged():
    (f,) = run(
        """
        from tpu_cc_manager import labels as L

        def bad(node, dev):
            mode = node["metadata"]["labels"].get(L.CC_MODE_LABEL)
            dev.set_cc_mode(mode)
        """
    )
    assert f.rule == "unvalidated-mode"


def test_parse_mode_sanitizes():
    assert run(
        """
        from tpu_cc_manager import labels as L
        from tpu_cc_manager.modes import parse_mode

        def good(node, dev):
            mode = parse_mode(node["metadata"]["labels"].get(L.CC_MODE_LABEL))
            dev.set_cc_mode(mode.value)
        """
    ) == []


def test_reassignment_through_parse_mode_sanitizes():
    assert run(
        """
        import subprocess
        from tpu_cc_manager import labels as L
        from tpu_cc_manager.modes import parse_mode

        def good(node):
            mode = node["metadata"]["labels"].get(L.CC_MODE_LABEL)
            mode = parse_mode(mode)
            subprocess.run(["cc-tool", str(mode)])
        """
    ) == []


def test_tuple_reassignment_invalidates_stale_taint():
    # `mode, ok = ...` rebinds mode through a tuple target: the stale
    # TAINTED classification must not survive the rebinding
    assert run(
        """
        import subprocess
        from tpu_cc_manager import labels as L
        from tpu_cc_manager.modes import parse_mode

        def good(node):
            mode = node["metadata"]["labels"].get(L.CC_MODE_LABEL)
            mode, ok = str(parse_mode(mode).value), True
            subprocess.run(["cc-tool", mode])
        """
    ) == []


def test_tainted_with_raw_default_still_tainted():
    # `labels.get(K) or "off"` carries BOTH facts: the raw fallback must
    # not launder the taint past a subprocess sink
    (f,) = run(
        """
        import subprocess
        from tpu_cc_manager import labels as L

        def bad(node):
            v = node["metadata"]["labels"].get(L.CC_MODE_LABEL) or "off"
            subprocess.run(["cc-tool", v])
        """
    )
    assert f.rule == "unvalidated-mode"


def test_explicit_self_call_maps_args_unshifted():
    # `A.publish(a, "failed")` passes self explicitly: the one-hop
    # summary must still line the literal up with the sink parameter
    findings = run(
        """
        class A:
            def publish(self, value):
                set_cc_mode_state_label(self.kube, self.node, value)

        def bad(a):
            A.publish(a, "failed")
        """
    )
    assert "protocol-literal" in rules_of(findings)


def test_non_label_value_into_subprocess_passes():
    assert run(
        """
        import subprocess

        def good(tool):
            subprocess.run([tool, "--version"])
        """
    ) == []


def test_unvalidated_mode_pragma_suppresses():
    assert run(
        """
        import subprocess
        from tpu_cc_manager import labels as L

        def deliberate(node):
            mode = node["metadata"]["labels"].get(L.CC_MODE_LABEL)
            subprocess.run(["echo", mode])  # ccaudit: allow-unvalidated-mode(diagnostic echo only)
        """
    ) == []


# ------------------------------------------------------- mode-exhaustive


def test_partial_if_elif_dispatch_flagged():
    (f,) = run(
        """
        from tpu_cc_manager.modes import Mode

        def dispatch(mode):
            if mode is Mode.ON:
                return 1
            elif mode is Mode.OFF:
                return 2
            elif mode is Mode.DEVTOOLS:
                return 3
        """
    )
    assert f.rule == "mode-exhaustive"
    assert "Mode.ICI" in f.message


def test_full_if_elif_dispatch_passes():
    assert run(
        """
        from tpu_cc_manager.modes import Mode

        def dispatch(mode):
            if mode is Mode.ON:
                return 1
            elif mode is Mode.OFF:
                return 2
            elif mode is Mode.DEVTOOLS:
                return 3
            elif mode is Mode.ICI:
                return 4
        """
    ) == []


def test_partial_dispatch_with_raising_else_passes():
    assert run(
        """
        from tpu_cc_manager.modes import Mode

        def dispatch(mode):
            if mode is Mode.ON:
                return 1
            elif mode is Mode.OFF:
                return 2
            else:
                raise ValueError(f"unhandled mode {mode}")
        """
    ) == []


def test_partial_dispatch_with_silent_else_flagged():
    (f,) = run(
        """
        from tpu_cc_manager.modes import Mode

        def dispatch(mode):
            if mode is Mode.ON:
                return 1
            elif mode is Mode.OFF:
                return 2
            else:
                return 0
        """
    )
    assert f.rule == "mode-exhaustive"


def test_membership_test_counts_all_members():
    assert run(
        """
        from tpu_cc_manager.modes import Mode

        def dispatch(mode):
            if mode in (Mode.ON, Mode.DEVTOOLS):
                return 1
            elif mode in (Mode.OFF, Mode.ICI):
                return 2
        """
    ) == []


def test_single_guard_is_not_a_dispatch():
    assert run(
        """
        from tpu_cc_manager.modes import Mode

        def guard(mode):
            if mode is Mode.OFF:
                return
            arm(mode)
        """
    ) == []


def test_partial_dict_dispatch_flagged():
    (f,) = run(
        """
        from tpu_cc_manager.modes import Mode

        HANDLERS = {Mode.ON: 1, Mode.OFF: 2, Mode.DEVTOOLS: 3}
        """
    )
    assert f.rule == "mode-exhaustive"
    assert "Mode.ICI" in f.message


def test_full_dict_dispatch_passes():
    assert run(
        """
        from tpu_cc_manager.modes import Mode

        HANDLERS = {Mode.ON: 1, Mode.OFF: 2, Mode.DEVTOOLS: 3, Mode.ICI: 4}
        """
    ) == []


def test_single_mode_key_dict_is_not_a_dispatch():
    assert run(
        """
        from tpu_cc_manager.modes import Mode

        DEFAULT = {Mode.OFF: 0o666}
        """
    ) == []


def test_mode_exhaustive_pragma_suppresses():
    assert run(
        """
        from tpu_cc_manager.modes import Mode

        def dispatch(mode):
            # ccaudit: allow-mode-exhaustive(ici handled by the caller)
            if mode is Mode.ON:
                return 1
            elif mode is Mode.OFF:
                return 2
        """
    ) == []


# ----------------------------------------------------- protocol-liveness


_LABELS_FIXTURE = (
    'X_LABEL = "tpu.google' + '.com/cc.x"\n'
)


def _liveness(user_src: str):
    mods = [
        Module("tpu_cc_manager/labels.py", _LABELS_FIXTURE),
        Module("tpu_cc_manager/user.py", textwrap.dedent(user_src)),
    ]
    return [f for f in analyze_modules(mods) if f.rule == "protocol-liveness"]


def test_liveness_written_and_read_passes():
    assert _liveness(
        """
        from tpu_cc_manager import labels as L

        def write(kube, node, v):
            kube.set_node_labels(node, {L.X_LABEL: v})

        def read(node):
            return node["metadata"]["labels"].get(L.X_LABEL)
        """
    ) == []


def test_liveness_dead_constant_flagged():
    (f,) = _liveness("import tpu_cc_manager.labels\n")
    assert f.rule == "protocol-liveness"
    assert f.file == "tpu_cc_manager/labels.py"
    assert "no reader or writer" in f.message


def test_liveness_read_only_flagged():
    (f,) = _liveness(
        """
        from tpu_cc_manager import labels as L

        def read(node):
            return node["metadata"]["labels"].get(L.X_LABEL)
        """
    )
    assert "never written" in f.message


def test_liveness_write_only_flagged():
    (f,) = _liveness(
        """
        from tpu_cc_manager import labels as L

        def write(kube, node, v):
            kube.set_node_labels(node, {L.X_LABEL: v})
        """
    )
    assert "never read" in f.message


def test_liveness_subscript_store_counts_as_write():
    assert _liveness(
        """
        from tpu_cc_manager import labels as L

        def write(ann, v):
            ann[L.X_LABEL] = v

        def read(ann):
            return ann[L.X_LABEL]
        """
    ) == []


def test_liveness_other_context_counts_as_both():
    # a constant handed to a helper could be either side — never flagged
    assert _liveness(
        """
        from tpu_cc_manager import labels as L

        def selector():
            return make_selector(L.X_LABEL)
        """
    ) == []


def test_liveness_pragma_on_declaration_suppresses():
    mods = [
        Module(
            "tpu_cc_manager/labels.py",
            'X_LABEL = "tpu.google' + '.com/cc.x"  '
            "# ccaudit: allow-protocol-liveness(GKE writes it)\n",
        ),
        Module("tpu_cc_manager/user.py", "import tpu_cc_manager.labels\n"),
    ]
    assert [
        f for f in analyze_modules(mods) if f.rule == "protocol-liveness"
    ] == []


def test_liveness_skipped_without_other_modules():
    assert analyze_modules(
        [Module("tpu_cc_manager/labels.py", _LABELS_FIXTURE)]
    ) == []


# ------------------------------------------------------- manifest-drift


def _write(root, rel, content):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(textwrap.dedent(content))
    return path


def _manifest_tree(root, daemonset_key=None, scenario_mode="on",
                   crd_enum=None):
    """A minimal tree satisfying every MANIFEST_GLOBS pattern."""
    key = daemonset_key or L.CC_MODE_LABEL
    enum = list(VALID_MODES) if crd_enum is None else crd_enum
    # 24 spaces: _write() dedents the surrounding doc by 8, leaving these
    # nested under `enum:` at 16
    enum_yaml = "".join(f"{' ' * 24}- '{v}'\n" for v in enum)
    _write(root, "deployments/kustomize/resources.yaml", f"""\
        apiVersion: apps/v1
        kind: DaemonSet
        spec:
          template:
            spec:
              tolerations:
              - key: {key}
                operator: Exists
        """)
    _write(root, "deployments/manifests/crd.yaml", f"""\
        apiVersion: apiextensions.k8s.io/v1
        kind: CustomResourceDefinition
        spec:
          group: tpu.google{'.'}com
          versions:
          - name: v1alpha1
            schema:
              openAPIV3Schema:
                properties:
                  spec:
                    properties:
                      mode:
                        type: string
                        enum:
{enum_yaml}""")
    _write(root, "scenarios/smoke.json", json.dumps({
        "name": "smoke", "nodes": 4, "initial_mode": "off",
        "actions": [{"action": "set_mode", "at": 0.1,
                     "mode": scenario_mode}],
        "converge": {"mode": scenario_mode, "timeout_s": 60},
    }, indent=2))
    # the slo cross-check (analysis/slo.py) scans the same surface
    # with the same loud-missing contract; pragma'd because this
    # minimal tree declares no Python metrics at all
    _write(root, "deployments/slo.yaml", """\
        version: 1
        objectives:
          - name: smoke
            kind: error_ratio
            # ccaudit: allow-metric-name(fixture tree declares no metrics)
            metric: tpu_cc_reconciles_total
            bad_labels:
              outcome: [failure]
            target: 0.99
            windows: {fast_s: 2, slow_s: 10}
            burn_threshold: 2.0
        """)


def test_clean_manifest_tree_passes(tmp_path):
    _manifest_tree(str(tmp_path))
    assert manifest_findings(str(tmp_path)) == []


def test_unknown_protocol_key_flagged(tmp_path):
    # THE drift fixture: a key the code does not export fails the gate
    _manifest_tree(
        str(tmp_path),
        daemonset_key="tpu.google" + ".com/does-not-exist",
    )
    (f,) = manifest_findings(str(tmp_path))
    assert f.rule == "manifest-drift"
    assert "does-not-exist" in f.message
    assert f.file == "deployments/kustomize/resources.yaml"


def test_renamed_code_constant_orphans_manifest_key(tmp_path):
    # the other drift direction: labels.py loses/renames a constant and
    # the manifest key it used to export goes stale
    _manifest_tree(str(tmp_path))
    findings = manifest_findings(
        str(tmp_path),
        known_keys=code_protocol_keys() - {L.CC_MODE_LABEL},
    )
    assert [f.rule for f in findings] == ["manifest-drift"]


def test_unknown_scenario_mode_flagged(tmp_path):
    _manifest_tree(str(tmp_path), scenario_mode="onn")
    findings = manifest_findings(str(tmp_path))
    assert findings and all(f.rule == "manifest-drift" for f in findings)
    assert any("'onn'" in f.message for f in findings)
    assert all(f.file == "scenarios/smoke.json" for f in findings)


def test_unknown_scenario_fault_kind_flagged(tmp_path):
    """ISSUE 12: a fault kind the simlab schema does not know is
    manifest drift — the scenario would be rejected at load, so the
    lint tier fails first with a named finding."""
    _manifest_tree(str(tmp_path))
    _write(str(tmp_path), "scenarios/smoke.json", json.dumps({
        "name": "smoke", "nodes": 4, "initial_mode": "off",
        "actions": [
            {"action": "set_mode", "at": 0.1, "mode": "on"},
            {"action": "fault", "at": 0.2, "fault": "meteor_strike"},
        ],
        "converge": {"mode": "on", "timeout_s": 60},
    }, indent=2))
    (f,) = manifest_findings(str(tmp_path))
    assert f.rule == "manifest-drift"
    assert "'meteor_strike'" in f.message
    assert "FAULT_PARAMS" in f.message
    assert f.file == "scenarios/smoke.json"


def test_scenario_fault_kinds_track_live_schema(tmp_path):
    """The fault vocabulary is pulled from the LIVE schema — the
    lifecycle kinds added in ISSUE 12 must be known, and injecting a
    reduced set flags a scenario using the removed kind."""
    from tpu_cc_manager.analysis.manifests import scenario_fault_kinds

    kinds = scenario_fault_kinds()
    assert {"agent_upgrade", "key_rotation", "root_revoked",
            "policy_conflict", "evacuation_drain"} <= kinds
    _manifest_tree(str(tmp_path))
    _write(str(tmp_path), "scenarios/smoke.json", json.dumps({
        "name": "smoke", "nodes": 4, "initial_mode": "off",
        "actions": [
            {"action": "fault", "at": 0.1, "fault": "watch_410"},
            {"action": "set_mode", "at": 0.2, "mode": "on"},
        ],
        "converge": {"mode": "on", "timeout_s": 60},
    }, indent=2))
    assert manifest_findings(str(tmp_path)) == []
    findings = manifest_findings(
        str(tmp_path), known_faults=kinds - {"watch_410"},
    )
    assert [f.rule for f in findings] == ["manifest-drift"]
    assert "'watch_410'" in findings[0].message


def test_rival_mode_checked_as_mode_field(tmp_path):
    """policy_conflict's rival_mode is a mode-valued field: a typo'd
    mode there fails the lint tier, not a user's scenario load."""
    _manifest_tree(str(tmp_path))
    _write(str(tmp_path), "scenarios/smoke.json", json.dumps({
        "name": "smoke", "nodes": 4, "initial_mode": "off",
        "actions": [
            {"action": "fault", "at": 0.1, "fault": "policy_conflict",
             "mode": "on", "rival_mode": "devtoolz"},
        ],
        "converge": {"mode": "on", "timeout_s": 60},
    }, indent=2))
    findings = manifest_findings(str(tmp_path))
    assert any("'devtoolz'" in f.message
               and "VALID_MODES" in f.message for f in findings)


def test_crd_enum_missing_mode_flagged(tmp_path):
    enum = [m for m in VALID_MODES if m != "ici"]
    _manifest_tree(str(tmp_path), crd_enum=enum)
    (f,) = manifest_findings(str(tmp_path))
    assert f.rule == "manifest-drift"
    assert "missing 'ici'" in f.message


def test_crd_enum_extra_mode_flagged(tmp_path):
    _manifest_tree(str(tmp_path), crd_enum=list(VALID_MODES) + ["bogus"])
    (f,) = manifest_findings(str(tmp_path))
    assert "'bogus'" in f.message


def test_yaml_pragma_suppresses(tmp_path):
    _manifest_tree(str(tmp_path))
    _write(str(tmp_path), "deployments/manifests/extra.yaml", """\
        metadata:
          annotations:
            # ccaudit: allow-manifest-drift(legacy key kept for the v0 fleet)
            legacy: tpu.google""" + """.com/retired-key
        """)
    assert manifest_findings(str(tmp_path)) == []


def test_multi_doc_enums_anchor_successively(tmp_path):
    # two CRD docs in one file, second enum is the broken one: its
    # finding must anchor past the first doc's enum line so the pragma
    # and baseline point at the real defect site
    _manifest_tree(str(tmp_path))
    good = "".join(f"{' ' * 12}- '{v}'\n" for v in VALID_MODES)
    bad = "".join(
        f"{' ' * 12}- '{v}'\n" for v in VALID_MODES if v != "ici"
    )
    _write(str(tmp_path), "deployments/manifests/two-crds.yaml", f"""\
        kind: CustomResourceDefinition
        properties:
          mode:
            type: string
            enum:
{good}        ---
        kind: CustomResourceDefinition
        properties:
          mode:
            type: string
            enum:
{bad}""")
    (f,) = manifest_findings(str(tmp_path))
    assert "missing 'ici'" in f.message
    first_enum = 5  # line of the first doc's `enum:` in the fixture
    assert f.line > first_enum


def test_unparseable_manifest_yaml_is_a_finding(tmp_path):
    # a manifest the cluster would reject silently disables the enum
    # cross-check unless the parse failure itself is drift
    _manifest_tree(str(tmp_path))
    _write(str(tmp_path), "deployments/manifests/broken.yaml", """\
        kind: Deployment
          badly: indented
        """)
    (f,) = manifest_findings(str(tmp_path))
    assert f.rule == "manifest-drift"
    assert "unparseable manifest YAML" in f.message
    assert f.file == "deployments/manifests/broken.yaml"


def test_empty_glob_fails_loud(tmp_path):
    _manifest_tree(str(tmp_path))
    os.remove(os.path.join(str(tmp_path), "scenarios/smoke.json"))
    with pytest.raises(FileNotFoundError):
        manifest_findings(str(tmp_path))


def test_real_repo_manifest_tree_is_clean():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert manifest_findings(repo) == []


def test_manifest_globs_cover_deploy_and_scenarios():
    assert any("kustomize" in g for g in MANIFEST_GLOBS)
    assert any(g.startswith("scenarios/") for g in MANIFEST_GLOBS)


# --------------------------------------------- CLI + baseline integration


def test_cli_gates_manifest_drift(tmp_path):
    """Acceptance fixture: the ccaudit CLI itself exits nonzero when a
    deployments/ key has no labels.py counterpart."""
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "ok.py").write_text("x = 1\n")
    _manifest_tree(
        str(root), daemonset_key="tpu.google" + ".com/drifted-key"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--manifests", "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "[manifest-drift]" in proc.stdout
    assert "drifted-key" in proc.stdout

    # and the same tree passes once the key speaks the real protocol
    _manifest_tree(str(root))
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--manifests", "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0


def test_cli_no_manifests_skips_the_pass(tmp_path):
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "ok.py").write_text("x = 1\n")
    # no manifest tree at all: only --no-manifests can pass here with
    # default-surface semantics forced off
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--no-manifests", "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0


def test_protocol_finding_flows_through_baseline(tmp_path):
    findings = run(
        """
        class A:
            def bad(self):
                self._set_state_label("failed")
        """
    )
    assert rules_of(findings) == ["protocol-literal"]
    path = str(tmp_path / "baseline.json")
    write_baseline(findings, path)
    new, suppressed, stale = diff_against_baseline(
        findings, load_baseline(path)
    )
    assert new == [] and stale == [] and len(suppressed) == 1
