"""Agent end-to-end against the fake clientset + fake device backend —
the minimum end-to-end slice of SURVEY.md §7.3: label a node → agent
reconciles the device mode → state label flips."""

import os
import threading
import time

from tpu_cc_manager import labels as L
from tpu_cc_manager.agent import CCManagerAgent, with_default
from tpu_cc_manager.config import AgentConfig
from tpu_cc_manager.device.base import set_backend
from tpu_cc_manager.device.fake import FakeBackend, FakeChip, fake_backend
from tpu_cc_manager.k8s import FakeKube
from tpu_cc_manager.k8s.objects import make_node


def test_with_default():
    # reference main.py:691-697
    assert with_default("on", "off") == "on"
    assert with_default(None, "off") == "off"
    assert with_default("", "off") == "off"
    assert with_default(None, None) is None


def _agent(kube, tmp_path, node="n1", default_mode="on", **cfg_kw):
    cfg = AgentConfig(
        node_name=node,
        default_mode=default_mode,
        readiness_file=str(tmp_path / "ready"),
        health_port=0,
        drain_strategy=cfg_kw.pop("drain_strategy", "none"),
        **cfg_kw,
    )
    agent = CCManagerAgent(kube, cfg)
    # keep fake watch streams short so shutdown joins promptly
    agent.watcher.watch_timeout_s = 1
    agent.watcher.backoff_s = 0.05
    return agent


def test_agent_initial_reconcile_from_label(tmp_path):
    backend = fake_backend(n_chips=2)
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "devtools"}))
    agent = _agent(kube, tmp_path)
    rc = agent.run(max_reconciles=1)
    assert rc == 0
    assert all(c.query_cc_mode() == "devtools" for c in backend.chips)
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "devtools"
    assert os.path.exists(str(tmp_path / "ready"))  # readiness after initial


def test_agent_applies_default_when_label_absent(tmp_path):
    backend = fake_backend(n_chips=1)
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    agent = _agent(kube, tmp_path, default_mode="on")
    rc = agent.run(max_reconciles=1)
    assert rc == 0
    assert backend.chips[0].query_cc_mode() == "on"


def test_agent_follows_label_changes(tmp_path):
    backend = fake_backend(n_chips=1)
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "off"}))
    agent = _agent(kube, tmp_path)

    t = threading.Thread(target=lambda: agent.run(max_reconciles=2))
    t.start()
    try:
        deadline = time.monotonic() + 5
        while agent.reconcile_count < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        kube.set_node_labels("n1", {L.CC_MODE_LABEL: "on"})
        t.join(timeout=10)
        assert not t.is_alive()
        assert backend.chips[0].query_cc_mode() == "on"
        assert (
            kube.get_node("n1")["metadata"]["labels"][L.CC_MODE_STATE_LABEL]
            == "on"
        )
    finally:
        agent.shutdown()
        t.join(timeout=5)


def test_agent_reconcile_failure_continues_and_reports(tmp_path):
    backend = fake_backend(n_chips=1)
    backend.chips[0].fail_set = True
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path)
    rc = agent.run(max_reconciles=1)
    assert rc == 0  # reconcile failure is not fatal (cmd/main.go:164-167)
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "failed"
    assert agent.metrics.reconciles_total.value("failure") == 1


def test_agent_invalid_label_value_reports_failed(tmp_path):
    set_backend(fake_backend(n_chips=1))
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "bogus"}))
    agent = _agent(kube, tmp_path)
    rc = agent.run(max_reconciles=1)
    assert rc == 0
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "failed"
    assert agent.metrics.reconciles_total.value("invalid") == 1


def test_agent_mixed_node_fatal_exit(tmp_path):
    chips = [FakeChip(path="/dev/accel0"),
             FakeChip(path="/dev/accel1", cc_capable=False, ici_capable=False)]
    set_backend(FakeBackend(chips=chips))
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path)
    rc = agent.run(max_reconciles=1)
    assert rc == 1  # FatalModeError -> exit (main.py:214-217)


def test_agent_startup_default_apply_failure_is_fatal(tmp_path):
    backend = fake_backend(n_chips=1)
    backend.chips[0].fail_set = True
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1"))  # no label -> default path
    agent = _agent(kube, tmp_path, default_mode="on")
    rc = agent.run(max_reconciles=1)
    assert rc == 1  # cmd/main.go:141-145


def test_agent_metrics_histogram_records(tmp_path):
    set_backend(fake_backend(n_chips=1))
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path)
    agent.run(max_reconciles=1)
    assert agent.metrics.reconcile_duration.count == 1
    assert agent.metrics.reconcile_duration.quantile(0.5) is not None


def test_agent_drains_components_around_flip(tmp_path):
    set_backend(fake_backend(n_chips=1))
    kube = FakeKube()
    dp = "tpu.google.com/pool.deploy.device-plugin"
    kube.add_node(
        make_node("n1", labels={L.CC_MODE_LABEL: "on", dp: "true"})
    )
    agent = _agent(kube, tmp_path, drain_strategy="components")
    rc = agent.run(max_reconciles=1)
    assert rc == 0
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[dp] == "true"  # paused then restored
    assert labels[L.CC_MODE_STATE_LABEL] == "on"


def test_agent_self_repair_heals_failed_reconcile(tmp_path):
    # VERDICT r1 item 8: after a failed reconcile the agent retries on its
    # own (repair_interval_s) — no new label event, no operator action.
    backend = fake_backend(n_chips=1)
    backend.chips[0].fail_set = True
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path, repair_interval_s=0.2)

    t = threading.Thread(target=agent.run)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            labels = kube.get_node("n1")["metadata"]["labels"]
            if labels.get(L.CC_MODE_STATE_LABEL) == "failed":
                break
            time.sleep(0.05)
        assert labels.get(L.CC_MODE_STATE_LABEL) == "failed"
        # the device fault clears; the agent must converge unprompted
        backend.chips[0].fail_set = False
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            labels = kube.get_node("n1")["metadata"]["labels"]
            if labels.get(L.CC_MODE_STATE_LABEL) == "on":
                break
            time.sleep(0.05)
        assert labels.get(L.CC_MODE_STATE_LABEL) == "on"
        assert backend.chips[0].query_cc_mode() == "on"
        assert agent.metrics.repairs_total.value() >= 1
    finally:
        agent.shutdown()
        t.join(timeout=10)
    assert not t.is_alive()


def test_agent_repair_backoff_is_exponential(tmp_path):
    # A persistently failing reconcile must not retry at a fixed cadence:
    # consecutive failures for the same mode double the repair delay
    # (capped), so a wedged slice member cannot starve the event loop or
    # hammer the API server.
    backend = fake_backend(n_chips=1)
    backend.chips[0].fail_set = True
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path, repair_interval_s=30.0)

    agent.reconcile("on")
    assert agent._repair_mode == "on" and agent._repair_failures == 1
    first_due = agent._repair_due
    agent.reconcile("on")
    assert agent._repair_failures == 2
    assert agent._repair_due - first_due >= 25.0  # ~2x base, not 1x
    for _ in range(10):
        agent.reconcile("on")
    # capped at 32x the base interval
    import time as _t
    assert agent._repair_due - _t.monotonic() <= 32 * 30.0 + 1.0
    # a different mode resets the ladder
    agent.reconcile("devtools")
    assert agent._repair_failures == 1
    # success disarms and resets
    backend.chips[0].fail_set = False
    agent.reconcile("devtools")
    assert agent._repair_mode is None and agent._repair_failures == 0


def test_agent_repair_disabled_means_no_retry(tmp_path):
    backend = fake_backend(n_chips=1)
    backend.chips[0].fail_set = True
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path, repair_interval_s=0.0)

    t = threading.Thread(target=agent.run)
    t.start()
    try:
        time.sleep(2.5)  # several idle ticks
        backend.chips[0].fail_set = False
        time.sleep(1.5)
        labels = kube.get_node("n1")["metadata"]["labels"]
        assert labels.get(L.CC_MODE_STATE_LABEL) == "failed"  # untouched
        assert agent.metrics.repairs_total.value() == 0
    finally:
        agent.shutdown()
        t.join(timeout=10)


def test_agent_emits_reconcile_events(tmp_path):
    """Reconcile outcomes surface as core/v1 Events on the node, so
    `kubectl describe node` carries the mode-flip history (capability the
    reference lacks — it records outcomes only in a label + pod logs)."""
    set_backend(fake_backend(n_chips=1))
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path)

    assert agent.reconcile("on") is True
    assert agent.reconcile("bogus") is False
    assert agent.flush_events()

    events = kube.cluster_events
    assert len(events) == 2
    ok, bad = events
    assert ok["reason"] == "CCModeApplied" and ok["type"] == "Normal"
    # cluster-scoped involvedObject -> "default" ns (real apiserver rule)
    assert ok["metadata"]["namespace"] == "default"
    assert ok["involvedObject"] == {
        "kind": "Node", "apiVersion": "v1", "name": "n1",
    }
    assert "'on': success" in ok["message"]
    assert bad["reason"] == "CCModeInvalid" and bad["type"] == "Warning"
    # unique names (k8s rejects duplicate event names in a namespace)
    assert ok["metadata"]["name"] != bad["metadata"]["name"]


def test_agent_event_emission_is_best_effort(tmp_path):
    """A clientset without Events support (base-class 501) must never
    affect the reconcile result."""
    set_backend(fake_backend(n_chips=1))

    class NoEventsKube(FakeKube):
        def create_event(self, namespace, event):
            from tpu_cc_manager.k8s.client import ApiException
            raise ApiException(501, "nope")

    kube = NoEventsKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path)
    assert agent.reconcile("on") is True
    assert agent.flush_events()
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "on"


def test_agent_events_disabled_by_config(tmp_path):
    set_backend(fake_backend(n_chips=1))
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path, emit_events=False)
    assert agent.reconcile("on") is True
    assert agent.flush_events()
    assert kube.cluster_events == []


def test_agent_node_drain_with_pdb_blocked_pod(tmp_path):
    """GKE-native drain end-to-end through the real agent: cordon, PDB
    429 retries while blocked, eviction once released, flip, uncordon
    (the path the reference lacks entirely, SURVEY.md §7.1)."""
    from tpu_cc_manager.k8s.objects import make_pod

    backend = fake_backend(n_chips=2)
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("nd", labels={L.CC_MODE_LABEL: "on"}))
    kube.add_pod(
        make_pod("tpu-job", "default", labels={"tpu-workload": "y"},
                 node_name="nd")
    )
    kube.pdb_blocked.add(("default", "tpu-job"))
    agent = _agent(kube, tmp_path, node="nd", drain_strategy="node")
    agent.engine._drainer.timeout_s = 10
    agent.engine._drainer.poll_s = 0.1

    done = {}

    def run():
        done["ok"] = agent.reconcile("on")

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # while the PDB blocks, the node must already be cordoned
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if kube.get_node("nd").get("spec", {}).get("unschedulable"):
            break
        time.sleep(0.05)
    assert kube.get_node("nd")["spec"].get("unschedulable") is True
    kube.pdb_blocked.clear()
    t.join(timeout=20)
    assert done.get("ok") is True
    assert kube.get_node("nd")["metadata"]["labels"][L.CC_MODE_STATE_LABEL] == "on"
    # uncordoned and pod gone
    assert not kube.get_node("nd")["spec"].get("unschedulable")
    assert kube.list_pods("default", label_selector="tpu-workload=y") == []
    assert all(c.query_cc_mode() == "on" for c in backend.chips)


def test_agent_emits_slice_abort_event(tmp_path):
    """A slice round that never reaches quorum surfaces as a
    CCSliceAborted Warning event, not just a log line."""
    set_backend(fake_backend(n_chips=1))
    kube = FakeKube()
    kube.add_node(make_node(
        "n1", labels={L.CC_MODE_LABEL: "on", L.TPU_SLICE_LABEL: "s0"}
    ))
    # a second, permanently silent member keeps the quorum incomplete
    kube.add_node(make_node(
        "n2", labels={L.CC_MODE_LABEL: "on", L.TPU_SLICE_LABEL: "s0"}
    ))
    from tpu_cc_manager.slice_coord import SliceCoordinator

    coord = SliceCoordinator(
        kube, "n1", poll_s=0.05, commit_timeout_s=0.5, hb_ttl_s=60,
    )
    # make n2 look alive so the leader keeps waiting for its ack
    import time as _t
    kube.set_node_annotations(
        "n2", {"tpu.google.com/cc.slice.hb": str(int(_t.time()))}
    )
    cfg = AgentConfig(
        node_name="n1", default_mode="on",
        readiness_file=str(tmp_path / "ready"), health_port=0,
        drain_strategy="none",
    )
    agent = CCManagerAgent(kube, cfg, backend=fake_backend(n_chips=1),
                           slice_coordinator=coord)
    assert agent.reconcile("on") is False
    assert agent.flush_events()
    reasons = [e["reason"] for e in kube.cluster_events]
    assert reasons == ["CCSliceAborted"]
    assert kube.cluster_events[0]["type"] == "Warning"


def test_agent_publishes_doctor_verdict_on_idle_tick(tmp_path):
    """The agent's periodic doctor self-check (TPU_CC_DOCTOR_INTERVAL_S)
    publishes the cc.doctor annotation without anyone running doctor by
    hand — keeping the fleet controller's aggregation fresh."""
    import json

    backend = fake_backend(n_chips=1)
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path, doctor_interval_s=0.2)
    t = threading.Thread(target=agent.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 15
        raw = None
        while time.monotonic() < deadline:
            raw = kube.get_node("n1")["metadata"].get(
                "annotations", {}
            ).get(L.DOCTOR_ANNOTATION)
            if raw:
                break
            time.sleep(0.05)
        assert raw, "doctor verdict never published"
        verdict = json.loads(raw)
        assert verdict["ok"] is True
        assert verdict["fail"] == []
        assert "at" in verdict
        assert kube.get_node("n1")["metadata"]["labels"][
            L.DOCTOR_OK_LABEL] == "true"
    finally:
        agent.shutdown()
        t.join(timeout=10)


def test_doctor_interval_zero_disables_self_check(tmp_path):
    backend = fake_backend(n_chips=1)
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    agent = _agent(kube, tmp_path, doctor_interval_s=0)
    t = threading.Thread(target=agent.run, daemon=True)
    t.start()
    try:
        time.sleep(1.0)
        assert L.DOCTOR_ANNOTATION not in kube.get_node("n1")[
            "metadata"].get("annotations", {})
    finally:
        agent.shutdown()
        t.join(timeout=10)


def test_sigterm_is_a_clean_shutdown(tmp_path):
    """The kubelet stops pods with SIGTERM: the real entrypoint must
    exit 0 (clean shutdown, recorder flushed) — parity with the C++
    agent's on_signal and the bash engine's traps."""
    import signal
    import subprocess
    import sys

    import yaml

    from tpu_cc_manager.k8s.apiserver import FakeApiServer
    from tpu_cc_manager.k8s.objects import make_node as _mk

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sysfs = tmp_path / "sysfs" / "accel0" / "device"
    sysfs.mkdir(parents=True)
    (sysfs / "vendor").write_text("0x1ae0\n")
    (sysfs / "device").write_text("0x0063\n")
    (tmp_path / "dev").mkdir()
    (tmp_path / "dev" / "accel0").write_text("")
    with FakeApiServer() as srv:
        srv.store.add_node(_mk("sig-node", labels={
            L.CC_MODE_LABEL: "off"}))
        kubeconfig = tmp_path / "kubeconfig.yaml"
        kubeconfig.write_text(yaml.safe_dump({
            "apiVersion": "v1", "kind": "Config",
            "current-context": "t",
            "contexts": [{"name": "t",
                          "context": {"cluster": "c", "user": "u"}}],
            "clusters": [{"name": "c", "cluster": {
                "server": f"http://127.0.0.1:{srv.port}"}}],
            "users": [{"name": "u", "user": {}}],
        }))
        ready = tmp_path / "ready"
        env = dict(
            os.environ,
            NODE_NAME="sig-node",
            KUBECONFIG=str(kubeconfig),
            PYTHONPATH=repo,
            TPU_SYSFS_ROOT=str(tmp_path / "sysfs"),
            TPU_DEV_ROOT=str(tmp_path / "dev"),
            TPU_CC_STATE_DIR=str(tmp_path / "state"),
            DRAIN_STRATEGY="none",
            CC_READINESS_FILE=str(ready),
            HEALTH_PORT="0",
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpu_cc_manager"], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not ready.exists():
                time.sleep(0.1)
            assert ready.exists(), "agent never became ready"
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=20)
        finally:
            if proc.poll() is None:
                proc.kill()
        out = proc.stdout.read().decode()
        assert rc == 0, f"SIGTERM exit {rc}; log tail: {out[-1500:]}"


def test_failed_reconcile_fast_tracks_doctor_verdict(tmp_path):
    """A failed flip changes the node's trust surfaces; the fleet
    should see the updated doctor verdict within seconds instead of
    waiting out the remaining doctor interval."""
    import json

    backend = fake_backend(n_chips=1)
    chip = backend.find_tpus()[0][0]
    set_backend(backend)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "on"}))
    # a very long doctor interval: only the failure fast-track can
    # explain a verdict refresh
    agent = _agent(kube, tmp_path, doctor_interval_s=3600)
    t = threading.Thread(target=agent.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            ann = kube.get_node("n1")["metadata"].get("annotations", {})
            if L.DOCTOR_ANNOTATION in ann:
                break
            time.sleep(0.05)
        first = kube.get_node("n1")["metadata"]["annotations"][
            L.DOCTOR_ANNOTATION]
        # now make the device fail and trigger a reconcile
        chip.fail_set = True
        kube.set_node_labels("n1", {L.CC_MODE_LABEL: "devtools"})
        deadline = time.monotonic() + 20
        refreshed = None
        while time.monotonic() < deadline:
            ann = kube.get_node("n1")["metadata"].get("annotations", {})
            raw = ann.get(L.DOCTOR_ANNOTATION)
            if raw and raw != first:
                refreshed = json.loads(raw)
                break
            time.sleep(0.05)
        assert refreshed is not None, (
            "doctor verdict never refreshed after the failed flip"
        )
    finally:
        agent.shutdown()
        t.join(timeout=10)


# ------------------------------------------- coalesced flip-path writes
def test_flip_costs_at_most_two_node_writes(tmp_path):
    """ISSUE 6 tentpole pin: a steady-state flip's node-write round
    trips collapse to at most two (taint set carrying the previous
    evidence, taint clear+state), down from the historical five — the
    evidence annotation rides the carrier writes instead of paying its
    own PATCH."""
    backend = fake_backend(n_chips=2)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "off"}))
    agent = _agent(kube, tmp_path, emit_events=False)
    agent._backend = backend
    agent.engine._backend = backend
    assert agent.reconcile("on") is True  # warm-up: caches, evidence gen 1
    w0 = kube.node_write_stats()
    assert agent.reconcile("off") is True
    w1 = kube.node_write_stats()
    assert w1["requests"] - w0["requests"] <= 2, (w0, w1)
    # the carrier transported the PREVIOUS reconcile's evidence: it is
    # on the cluster without ever paying its own round trip
    import json as _json

    from tpu_cc_manager.evidence import evidence_mode

    ann = kube.get_node("n1")["metadata"]["annotations"]
    assert evidence_mode(_json.loads(ann[L.EVIDENCE_ANNOTATION])) == "on"
    assert agent._evidence_published_gen == 1
    assert agent._evidence_wanted_gen == 2  # "off"'s doc still pending
    # the explicit flush delivers the newest generation
    assert agent.flush_events()
    ann = kube.get_node("n1")["metadata"]["annotations"]
    assert evidence_mode(_json.loads(ann[L.EVIDENCE_ANNOTATION])) == "off"
    assert agent._evidence_published_gen == agent._evidence_wanted_gen


def test_coalesced_publications_counted_in_metrics(tmp_path):
    """Loss accounting (ISSUE 6 acceptance): a publication superseded
    before it was sent increments publications_coalesced_total — the
    drop is by design and visible, never silent."""
    backend = fake_backend(n_chips=1)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "off"}))
    agent = _agent(kube, tmp_path, emit_events=False)
    agent._backend = backend
    agent.engine._backend = backend
    assert agent.reconcile("on") is True
    # two builds with no carrier write in between: the second supersedes
    # the first in the batcher
    agent._publish_evidence()
    agent._publish_evidence()
    assert (
        agent.metrics.publications_coalesced_total.value("evidence") >= 1
    )
    assert agent.flush_events()
    assert agent._evidence_published_gen == agent._evidence_wanted_gen


def test_failed_flip_publishes_failed_state_not_half_applied(tmp_path):
    """Fail-secure ordering pin (ISSUE 6): a failed flip's batched
    state write still lands cc.mode.state=failed synchronously, and a
    pending evidence publication from the PREVIOUS success rides that
    same write — there is no interleaving where the node shows a fresh
    evidence document with a stale state label, and no half-applied
    merge (the patch is atomic server-side)."""
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.CC_MODE_LABEL: "off"}))
    chip = FakeChip(path=str(tmp_path / "accel0"))
    agent = _agent(kube, tmp_path, emit_events=False)
    agent._backend = FakeBackend(chips=[chip])
    agent.engine._backend = agent._backend
    assert agent.reconcile("on") is True  # evidence gen 1 deferred
    chip.fail_reset = True
    assert agent.reconcile("off") is False
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "failed"
    # the failed reconcile produced NO new evidence; the previous
    # success's document rode the failed flip's writes intact
    import json as _json

    from tpu_cc_manager.evidence import evidence_mode

    ann = kube.get_node("n1")["metadata"]["annotations"]
    assert evidence_mode(_json.loads(ann[L.EVIDENCE_ANNOTATION])) == "on"
    assert agent._evidence_wanted_gen == 1
    assert agent._evidence_published_gen == 1
    assert not agent.batcher.has_pending()


def test_prime_backoff_cut_by_shutdown_does_not_apply_default(tmp_path):
    """ISSUE 14 satellite regression pin: the startup prime backoff is
    now an event wait on the stop event — a shutdown arriving during
    it must NOT read as 'node has no label' and reconcile the default
    mode on the way out."""
    kube = FakeKube()  # node n1 absent: every prime read 404s
    agent = _agent(kube, tmp_path)
    agent.watcher.backoff_s = 5.0  # the wait the stop must cut short
    agent._stop.set()
    t0 = time.monotonic()
    assert agent._prime_with_retry() is None
    assert time.monotonic() - t0 < 2.0, "stop did not cut the backoff"
    # and run()'s guard: a stopping agent never runs the initial
    # reconcile (which would drain + flip toward the default mode)
    calls = []
    agent._reconcile_current = lambda mode: calls.append(mode) or True
    rc = agent.run()
    assert calls == [], "shutting-down agent reconciled the default"
    assert rc == 0
