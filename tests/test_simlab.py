"""simlab — the fleet-scale scenario lab (tpu_cc_manager/simlab).

Three surfaces under test: the STRICT scenario schema (unknown keys
anywhere are errors — the freshness gate depends on it), the committed
``scenarios/*.json`` examples (parse + validate + canonical formatting,
the kustomize-tree treatment from test_manifests.py), and the live
harness itself — replicas, shared watch pump, worker pool, fault
injector — run small enough for the suite but through the same wire
path the 256-node scenario uses."""

import glob
import json
import os

import pytest

from tpu_cc_manager.simlab.scenario import (
    ScenarioError, canonical_scenario_text, load_scenario,
    validate_scenario,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCENARIO_DIR = os.path.join(ROOT, "scenarios")


def _minimal(**over):
    doc = {
        "version": 1,
        "name": "t",
        "nodes": 4,
        "actions": [{"at": 0.0, "action": "set_mode", "mode": "on"}],
        "converge": {"mode": "on", "timeout_s": 30},
    }
    doc.update(over)
    return doc


# ---------------------------------------------------------------- schema
def test_minimal_scenario_validates():
    sc = validate_scenario(_minimal())
    assert sc.nodes == 4 and sc.workers == 8 and sc.qps == 0.0
    assert sc.converge.mode == "on"
    assert [a.kind for a in sc.actions] == ["set_mode"]


def test_unknown_keys_rejected_everywhere():
    with pytest.raises(ScenarioError, match="unknown key"):
        validate_scenario(_minimal(extra=1))
    with pytest.raises(ScenarioError, match="unknown key"):
        validate_scenario(_minimal(
            actions=[{"at": 0, "action": "set_mode", "mode": "on",
                      "bogus": 1}]))
    with pytest.raises(ScenarioError, match="unknown key"):
        validate_scenario(_minimal(
            converge={"mode": "on", "bogus": 1}))
    with pytest.raises(ScenarioError, match="unknown key"):
        validate_scenario(_minimal(controllers={"bogus": True}))


def test_version_gate_refuses_future_schema():
    with pytest.raises(ScenarioError, match="version"):
        validate_scenario(_minimal(version=2))


def test_invalid_modes_and_faults_rejected():
    with pytest.raises(ScenarioError):
        validate_scenario(_minimal(
            actions=[{"at": 0, "action": "set_mode",
                      "mode": "warp-speed"}]))
    with pytest.raises(ScenarioError, match="unknown fault"):
        validate_scenario(_minimal(
            actions=[{"at": 0, "action": "fault",
                      "fault": "meteor_strike"}]))
    with pytest.raises(ScenarioError, match="missing required"):
        validate_scenario(_minimal(
            actions=[{"at": 0, "action": "fault",
                      "fault": "agent_crash"}]))  # no count


def test_cross_field_requirements():
    # policy actions need the policy controller
    with pytest.raises(ScenarioError, match="controllers.policy"):
        validate_scenario(_minimal(
            actions=[{"at": 0, "action": "create_policy",
                      "mode": "on"}]))
    # leader_flap needs the elected pair
    with pytest.raises(ScenarioError, match="leader_elect"):
        validate_scenario(_minimal(
            actions=[{"at": 0, "action": "fault",
                      "fault": "leader_flap"}]))
    # leader_elect without policy is meaningless
    with pytest.raises(ScenarioError, match="requires controllers.policy"):
        validate_scenario(_minimal(
            controllers={"leader_elect": True}))


def test_actions_sorted_by_time():
    sc = validate_scenario(_minimal(actions=[
        {"at": 2.0, "action": "set_mode", "mode": "on"},
        {"at": 0.5, "action": "set_mode", "mode": "off"},
    ]))
    assert [a.at for a in sc.actions] == [0.5, 2.0]


# --------------------------------------------- committed-example freshness
def test_committed_scenarios_validate_and_are_fresh():
    """Every scenarios/*.json must parse, validate, and match the
    canonical formatting byte for byte — the schema-example staleness
    gate (test_manifests.py's kustomize freshness treatment). A schema
    change that orphans an example fails here, not in a user's lap."""
    paths = sorted(glob.glob(os.path.join(SCENARIO_DIR, "*.json")))
    names = {os.path.basename(p) for p in paths}
    # the CI smoke scenario and the bench's gated scenario must exist
    assert {"smoke-64.json", "scale-256.json"} <= names
    for path in paths:
        with open(path) as f:
            text = f.read()
        doc = json.loads(text)
        sc = validate_scenario(doc)  # semantics
        assert text == canonical_scenario_text(doc), (
            f"{path} is not canonically formatted; regenerate with "
            "canonical_scenario_text()"
        )
        # committed examples must be runnable as written
        assert sc.nodes >= 1 and sc.actions


def test_bench_gated_scenario_is_256_nodes():
    """bench.py's extras key is pool256_convergence_s — the scenario it
    runs must actually be 256 nodes, or the gated axis silently changes
    meaning."""
    sc = load_scenario(os.path.join(SCENARIO_DIR, "scale-256.json"))
    assert sc.nodes == 256
    faults = [a.params["fault"] for a in sc.actions
              if a.kind == "fault"]
    assert "watch_drop" in faults and "agent_crash" in faults


def test_named_lifecycle_scenarios_exercise_their_families():
    """ISSUE 12 satellite: the four promoted lifecycle interleavings
    must exist, stay schema-valid (canonical formatting is enforced by
    test_committed_scenarios_validate_and_are_fresh above), and keep
    exercising the fault family their name promises — a refactor that
    quietly dropped the upgrade fault from upgrade-256 would silently
    change what the gated lifecycle_convergence_s axis measures."""
    def faults_of(name):
        sc = load_scenario(os.path.join(SCENARIO_DIR, name))
        return sc, [a.params["fault"] for a in sc.actions
                    if a.kind == "fault"]

    sc, faults = faults_of("upgrade-256.json")
    assert sc.nodes == 256
    assert "agent_upgrade" in faults
    # the upgrade must land MID-rollout: a set_mode wave on each side
    upgrade_at = next(a.at for a in sc.actions
                      if a.kind == "fault"
                      and a.params["fault"] == "agent_upgrade")
    waves = [a.at for a in sc.actions if a.kind == "set_mode"]
    assert min(waves) < upgrade_at < max(waves)

    sc, faults = faults_of("keyrot-64.json")
    assert sc.nodes == 64
    assert sc.attestation and sc.evidence and sc.controllers.fleet
    assert "key_rotation" in faults
    # rotation must be followed by a wave, so the fleet re-quotes
    rot_at = next(a.at for a in sc.actions
                  if a.kind == "fault"
                  and a.params["fault"] == "key_rotation")
    assert any(a.at > rot_at for a in sc.actions
               if a.kind == "set_mode")

    sc, faults = faults_of("policy-conflict-32.json")
    assert sc.nodes == 32
    assert sc.controllers.policy
    assert "policy_conflict" in faults
    conflict = next(a for a in sc.actions
                    if a.kind == "fault"
                    and a.params["fault"] == "policy_conflict")
    assert conflict.params["mode"] == sc.converge.mode

    sc, faults = faults_of("evac-race-96.json")
    assert sc.nodes == 96
    assert faults.count("evacuation_drain") >= 2
    # the drain must RACE a flip wave, not follow it
    wave_at = min(a.at for a in sc.actions if a.kind == "set_mode")
    assert any(a.at <= wave_at + 0.5 for a in sc.actions
               if a.kind == "fault"
               and a.params["fault"] == "evacuation_drain")


def test_incident_scenarios_are_a_matched_pair():
    """ISSUE 15: the incident-smoke scenarios must stay a true A/B —
    the latency one injects flip_latency AFTER enough baseline waves
    for the watchdog's min_windows, and the clean twin is the same
    timeline minus the fault (so a firing there is a watchdog bug,
    never a shape difference)."""
    lat = load_scenario(
        os.path.join(SCENARIO_DIR, "incident-latency-64.json"))
    clean = load_scenario(
        os.path.join(SCENARIO_DIR, "incident-clean-64.json"))
    faults = [a for a in lat.actions if a.kind == "fault"]
    assert [a.params["fault"] for a in faults] == ["flip_latency"]
    fault_at = faults[0].at
    # >= 4 baseline set_mode waves strictly before the fault (the
    # watchdog's default min_windows)
    baseline_waves = [a for a in lat.actions
                     if a.kind == "set_mode" and a.at < fault_at]
    assert len(baseline_waves) >= 4
    # one anomalous wave after the fault, toward the converge mode
    after = [a for a in lat.actions
             if a.kind == "set_mode" and a.at > fault_at]
    assert after and after[-1].params["mode"] == lat.converge.mode
    # the clean twin: identical shape, no fault action
    assert all(a.kind != "fault" for a in clean.actions)
    assert clean.nodes == lat.nodes
    assert [(a.at, a.params.get("mode")) for a in clean.actions] == [
        (a.at, a.params.get("mode")) for a in lat.actions
        if a.kind != "fault"]


# ---------------------------------------------------- fault injector race
def test_fault_injector_cancel_vs_inflight_timer():
    """ISSUE 12 satellite: a timer callback that fires AFTER cancel()
    must be a no-op — before the fix it would restart (mutate) a
    replica the teardown already owned. Pinned deterministically by
    invoking the armed Timer's callback by hand after cancel, i.e. the
    exact interleaving where Timer.cancel() came too late."""
    from tpu_cc_manager.simlab.faults import FaultInjector

    class StubReplica:
        def __init__(self):
            self.alive = True
            self.restarts = 0

        def crash(self):
            self.alive = False

        def restart(self):
            self.alive = True
            self.restarts += 1

    class StubPool:
        def submit(self, *a, **k):
            raise AssertionError("submit after cancel")

        def requeue(self, *a, **k):
            raise AssertionError("requeue after cancel")

    replica = StubReplica()
    inj = FaultInjector(
        store=None, replicas={"n1": replica}, pool=StubPool(),
        data_kube=None, ops_kube=None, base_qps=0.0, lease_names=[],
    )
    entry = inj.inject("agent_crash",
                       {"count": 1, "restart_after_s": 60.0}, 0.0)
    assert entry["crashed"] == 1 and not replica.alive
    (timer,) = inj._timers
    inj.cancel()
    # the race: the timer already fired past cancel() — run its
    # callback directly. The guarded wrapper must bail out.
    timer.function(*timer.args, **timer.kwargs)
    assert not replica.alive
    assert replica.restarts == 0
    assert inj.restarted_total == 0
    # and a timer armed AFTER cancel never starts at all
    inj._timer(0.01, lambda: replica.restart())
    import time as _time

    _time.sleep(0.1)
    assert replica.restarts == 0


def test_fault_injector_settle_runs_and_waits_restores():
    """settle() must run unclaimed restorative callbacks AND wait out
    ones already executing on a timer thread — the oracle judges the
    restored fleet, never a mid-restore snapshot."""
    import threading as _threading
    import time as _time

    from tpu_cc_manager.simlab.faults import FaultInjector

    inj = FaultInjector(
        store=None, replicas={}, pool=None, data_kube=None,
        ops_kube=None, base_qps=0.0, lease_names=[],
    )
    done = []
    started = _threading.Event()

    def slow_restore():
        started.set()
        _time.sleep(0.3)
        done.append("slow")

    inj._timer(0.01, slow_restore, restore=True)   # fires, runs slow
    inj._timer(60.0, lambda: done.append("late"), restore=True)
    assert started.wait(2.0)
    inj.settle()  # must run "late" early AND wait "slow" out
    assert sorted(done) == ["late", "slow"]
    # exactly-once: the late timer eventually firing is a no-op
    assert inj._restores == {}


# ------------------------------------------------------------- live runs
def test_live_run_with_faults_converges(tmp_path):
    """The harness end to end at suite scale: 16 live replicas, every
    storefront fault kind, convergence reached and the artifact carries
    the full metric surface (the acceptance shape of the 256-node
    scenario, small)."""
    from tpu_cc_manager.simlab.report import write_artifact
    from tpu_cc_manager.simlab.runner import SimLab

    doc = _minimal(
        name="live-16", nodes=16, pools=2, workers=4,
        watch_timeout_s=2, qps=50,
        actions=[
            {"at": 0.0, "action": "fault", "fault": "watch_drop",
             "count": 2},
            {"at": 0.05, "action": "fault", "fault": "agent_crash",
             "count": 4, "restart_after_s": 0.8},
            {"at": 0.2, "action": "set_mode", "mode": "on"},
            {"at": 0.5, "action": "fault", "fault": "watch_410"},
            {"at": 0.6, "action": "fault", "fault": "throttle_squeeze",
             "qps": 5, "duration_s": 0.5},
            {"at": 0.7, "action": "fault", "fault": "list_429",
             "count": 1},
        ],
        converge={"mode": "on", "timeout_s": 60},
    )
    art = SimLab(validate_scenario(doc)).run()
    assert art["ok"], art.get("notes")
    m = art["metrics"]
    assert m["pool16_convergence_s"] is not None
    assert m["pool16_convergence_s"] < 30
    # live churn was measured, not simulated
    assert m["watch_pump"]["delivered"] >= 16
    assert m["watch_pump"]["lag_samples"] >= 12
    assert m["watch_pump"]["lag_p50_s"] is not None
    assert m["reconciles"]["total"] >= 32  # init + storm
    assert m["reconciles"]["crashed"] == 4
    assert m["reconciles"]["restarted"] == 4
    assert "reconcile" in m["phase_p50_s"]
    assert m["throttle"]["histogram"]["count"] > 0
    assert len(art["faults"]) == 6
    # ---- fleet-timeline stitch (ISSUE 8): ONE trace demonstrably
    # spans the driver's desired-write and replica reconciles — trace
    # id equality ACROSS replica boundaries, pinned here
    st = m["trace_stitch"]
    assert st["cross_process_traces"] >= 1
    assert st["e2e_samples"] >= 16  # one per node for the set_mode
    assert m["e2e_convergence_p99_s"] is not None
    assert 0 < m["e2e_convergence_p99_s"] < 60
    tl = st["timeline_example"]
    assert len({s["trace"] for s in tl}) == 1  # one stitched trace
    recorders = {s.get("recorder") for s in tl}
    assert "driver" in recorders and len(recorders) >= 2
    desired = next(s for s in tl if s["name"] == "desired_write")
    reconciles = [s for s in tl if s["name"] == "reconcile"]
    assert reconciles
    for r in reconciles:
        assert r["trace"] == desired["trace"]
        assert r["parent"] == desired["span"]
        assert r["attrs"]["node"] == r["recorder"]  # replica-side span
    # the pump-lag measurement lands on pump-delivered reconciles
    # (repair/restart resubmissions legitimately carry no lag, and may
    # share the trace — don't require it on every span)
    lagged = [r for r in reconciles if "pump_lag_s" in r["attrs"]]
    assert lagged and all(r["attrs"]["pump_lag_s"] >= 0 for r in lagged)
    # artifact writer round-trips
    out = tmp_path / "artifact.json"
    write_artifact(str(out), art)
    assert json.loads(out.read_text())["ok"] is True


def test_pump_relists_through_410_and_delivers(tmp_path):
    """Deterministic 410 drill: compact the watch history UNDER the
    pump while it is disconnected, change a label, and the pump must
    410 -> full relist -> deliver (reference main.py:675-687 behavior
    at fleet scale)."""
    from tpu_cc_manager import labels as L
    from tpu_cc_manager.k8s.apiserver import FakeApiServer
    from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig
    from tpu_cc_manager.k8s.objects import make_node
    from tpu_cc_manager.obs import watch_pump_lag_histogram
    from tpu_cc_manager.simlab.pump import LagStamps, WatchPump

    delivered = []

    class PoolStub:
        def submit(self, name, value, trace=None, lag=None):
            delivered.append((name, value))

    with FakeApiServer() as server:
        store = server.store
        for i in range(4):
            store.add_node(make_node(f"p{i}", labels={
                L.CC_MODE_LABEL: "off"}))
        kube = HttpKubeClient(
            KubeConfig("127.0.0.1", server.port, use_tls=False)
        )
        pump = WatchPump(
            kube, {f"p{i}": object() for i in range(4)}, PoolStub(),
            LagStamps(), watch_pump_lag_histogram(),
            watch_timeout_s=1, backoff_s=0.05,
        )
        pump.prime()  # rv captured BEFORE the churn below
        # churn + compaction while the pump is not connected: its
        # resume rv is now below retained history
        store.set_node_labels("p0", {L.CC_MODE_LABEL: "on"})
        store.compact_watch_history()
        store.set_node_labels("p1", {L.CC_MODE_LABEL: "on"})
        pump.start()
        try:
            deadline = __import__("time").monotonic() + 10
            while (len(delivered) < 2
                   and __import__("time").monotonic() < deadline):
                __import__("time").sleep(0.02)
        finally:
            pump.stop()
        assert pump.gone_410_total >= 1
        assert pump.relists_total >= 1
        assert ("p0", "on") in delivered and ("p1", "on") in delivered


def test_cli_validate_and_scaled_run(tmp_path):
    """The __main__ surface: `simlab validate` on the committed files,
    and a `simlab run` with --nodes/--workers overrides small enough
    for the suite — the artifact lands at --out and rc says ok."""
    from tpu_cc_manager.__main__ import main

    committed = sorted(glob.glob(os.path.join(SCENARIO_DIR, "*.json")))
    assert main(["simlab", "validate"] + committed) == 0

    out = tmp_path / "art.json"
    rc = main([
        "simlab", "run",
        os.path.join(SCENARIO_DIR, "smoke-64.json"),
        "--nodes", "6", "--workers", "2", "--out", str(out),
    ])
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["ok"] is True
    assert art["metrics"]["pool6_convergence_s"] is not None


def test_write_429_storm_coalesces_and_newest_generation_lands():
    """ISSUE 6 acceptance pin: a scripted 429 storm on the node WRITE
    path, pre-armed so the next flip wave runs INTO it. The coalescing
    publish core must (a) absorb the storm — every node still
    converges, because failed state writes re-enter via replica repair
    and deferred evidence retries with backoff; (b) account every
    retried/superseded publication instead of silently dropping; and
    (c) land the NEWEST evidence generation on every node by settle
    time."""
    from tpu_cc_manager import labels as L
    from tpu_cc_manager.simlab.runner import SimLab

    doc = _minimal(
        name="write-429", nodes=8, workers=4, watch_timeout_s=2,
        evidence=True,
        actions=[
            # armed BEFORE the wave: the driver's own set_mode writes
            # are out-of-band store writes, so the storm is consumed
            # exclusively by the system under test
            {"at": 0.0, "action": "fault", "fault": "write_429",
             "count": 60},
            {"at": 0.05, "action": "set_mode", "mode": "on"},
            # post-storm wave: the clean carrier path (state write
            # transporting the previous evidence generation)
            {"at": 3.0, "action": "set_mode", "mode": "devtools"},
        ],
        converge={"mode": "devtools", "timeout_s": 60},
    )
    lab = SimLab(validate_scenario(doc))
    art = lab.run()
    assert art["ok"], art.get("notes")
    rec = art["metrics"]["reconciles"]
    # the storm bit: state writes failed and re-entered via repair
    assert rec["repairs"] > 0
    publish = rec["publish"]
    # loss accounting: flush attempts that hit the storm are counted
    # as retries (and superseded generations, when any, as coalesced)
    assert publish["retries"] > 0
    assert publish["dropped"] == 0  # budget never exhausted here
    assert publish["pending"] == 0  # settle flushed everything
    # the newest generation landed on every node: each replica's
    # on-cluster evidence reports the FINAL mode, and its generation
    # bookkeeping agrees
    import json as _json

    from tpu_cc_manager.evidence import evidence_mode

    for name, replica in lab.replicas.items():
        assert replica.evidence_published_gen == replica.evidence_wanted_gen, name
        node = lab.server.store.get_node(name)
        raw = node["metadata"]["annotations"][L.EVIDENCE_ANNOTATION]
        assert evidence_mode(_json.loads(raw)) == "devtools", name
    # the storm really happened: rejected writes were counted as
    # requests (the server paid for them) and the write accounting
    # distinguishes round trips from the mutations they carried
    writes = rec["api_writes"]
    assert writes["requests"] > writes["mutations"] or (
        writes["requests"] > 0 and writes["mutations"] > 0
    )


# -------------------------------------------------------- sharded plane
def test_shard_schema_cross_field_requirements():
    # shards ride the fleet plane
    with pytest.raises(ScenarioError, match="controllers.fleet"):
        validate_scenario(_minimal(controllers={"shards": 2}))
    # the policy-pair Lease does not exist in sharded mode
    with pytest.raises(ScenarioError, match="mutually exclusive"):
        validate_scenario(_minimal(controllers={
            "fleet": True, "policy": True, "leader_elect": True,
            "shards": 2}))
    # shard_kill needs a shard plane, and the host must exist
    with pytest.raises(ScenarioError, match="controllers.shards"):
        validate_scenario(_minimal(
            actions=[{"at": 0, "action": "fault",
                      "fault": "shard_kill"}]))
    with pytest.raises(ScenarioError, match="out of range"):
        validate_scenario(_minimal(
            controllers={"fleet": True, "shards": 2},
            actions=[{"at": 0, "action": "fault", "fault": "shard_kill",
                      "host": 5}]))
    with pytest.raises(ScenarioError, match="must be an int"):
        validate_scenario(_minimal(controllers={
            "fleet": True, "shards": True}))
    sc = validate_scenario(_minimal(
        controllers={"fleet": True, "shards": 3}))
    assert sc.controllers.shards == 3


def test_bench_gated_shard_scenario_is_1024_nodes_with_kills():
    """bench.py's pool1024_convergence_s / shard_failover_convergence_s
    come from scale-1024.json: it must actually be 1024 nodes through a
    sharded plane with a mid-rollout shard kill, or the gated axes
    silently change meaning."""
    sc = load_scenario(os.path.join(SCENARIO_DIR, "scale-1024.json"))
    assert sc.nodes == 1024
    assert sc.controllers.shards >= 2
    kills = [a for a in sc.actions
             if a.kind == "fault" and a.params["fault"] == "shard_kill"]
    assert kills, "scale-1024 must script a shard-kill failover"
    # the shard-smoke scenario is the reduced CI twin
    sc512 = load_scenario(os.path.join(SCENARIO_DIR, "scale-512.json"))
    assert sc512.nodes == 512 and sc512.controllers.shards >= 2


def test_live_sharded_run_survives_shard_kill(tmp_path):
    """The sharded plane end to end at suite scale: consistent-hash
    shards over one shared informer, a mid-storm shard kill, fleet
    convergence anyway, and an artifact carrying the failover number
    and a VALID merged fleet exposition."""
    from tpu_cc_manager.simlab.runner import SimLab

    doc = _minimal(
        name="shard-16", nodes=16, pools=4, workers=4,
        watch_timeout_s=2, qps=50,
        controllers={"fleet": True, "shards": 2},
        actions=[
            {"at": 0.2, "action": "set_mode", "mode": "on"},
            {"at": 0.5, "action": "fault", "fault": "shard_kill",
             "host": 0},
        ],
        converge={"mode": "on", "timeout_s": 60},
    )
    art = SimLab(validate_scenario(doc)).run()
    assert art["ok"], art.get("notes")
    shards = art["metrics"]["shards"]
    assert shards["merged_exposition_problems"] == 0
    stats = shards["stats"]
    assert stats["shards"] == 2 and stats["hosts_live"] == 1
    (failover,) = stats["failovers"]
    assert failover["handoff_s"] is not None, (
        "the orphaned partition was never re-acquired")
    # the gated axis: kill -> converged AND coverage restored
    fo = art["metrics"]["shard_failover_convergence_s"]
    assert fo is not None and fo >= failover["handoff_s"] - 0.05
    # every partition is covered by the surviving host
    assert all(h == "host-1" for h in stats["coverage"].values()), stats


def test_shared_loop_mode_multiplexes_one_connection_pool(monkeypatch):
    """ISSUE 13: TPU_CC_SIMLAB_SHARED_LOOP=1 rehosts the fleet's data
    plane onto the async I/O core — the run converges, the artifact
    records the aio core, and the dial count proves multiplexing
    (a bounded connection budget, not per-replica sockets)."""
    from tpu_cc_manager.simlab.runner import SimLab

    monkeypatch.setenv("TPU_CC_SIMLAB_SHARED_LOOP", "1")
    doc = _minimal(
        name="shared-loop-16", nodes=16, pools=2, workers=4,
        watch_timeout_s=2,
        actions=[{"at": 0.1, "action": "set_mode", "mode": "on"}],
        converge={"mode": "on", "timeout_s": 60},
    )
    art = SimLab(validate_scenario(doc)).run()
    assert art["ok"], art.get("notes")
    io = art["metrics"]["kube_io"]
    assert io["core"] == "aio"
    assert io["requests"] >= 32  # 16 replicas x >= 2 writes each
    assert io["dials"] <= 8  # the connection budget, not 16 sockets
    assert io["replays"] == 0
    # the threaded default still reports itself honestly
    monkeypatch.delenv("TPU_CC_SIMLAB_SHARED_LOOP")
    art2 = SimLab(validate_scenario(_minimal(
        name="threaded-8", nodes=8, workers=4, watch_timeout_s=2,
        actions=[{"at": 0.1, "action": "set_mode", "mode": "on"}],
        converge={"mode": "on", "timeout_s": 60},
    ))).run()
    assert art2["ok"]
    assert art2["metrics"]["kube_io"] == {"core": "threaded"}
