"""The online anomaly watchdog (tpu_cc_manager/watchdog.py, ISSUE 15):
robust-z firing, cold-ring/restart hygiene, incident packet assembly."""

import math

from tpu_cc_manager.flightrec import FlightRecorder
from tpu_cc_manager.obs import Metrics
from tpu_cc_manager.profiler import SamplingProfiler
from tpu_cc_manager.tsring import snapshot_metric_set
from tpu_cc_manager.watchdog import (
    DEFAULT_SERIES, WatchSeries, Watchdog,
)


def _latency_samples(metrics, values, start=1000.0, traced=True):
    """One sample per observation — each window holds one value."""
    samples = []
    t = start
    for i, v in enumerate(values):
        metrics.reconcile_duration.observe(
            v, trace_id=(f"tid{i}" if traced else None))
        samples.append((t, snapshot_metric_set(metrics)))
        t += 1.0
    return samples


def _feed(wd, samples):
    fired = []
    for i in range(1, len(samples) + 1):
        fired.extend(wd.consume(samples[:i]))
    return fired


# ------------------------------------------------------------- firing


def test_latency_excursion_fires_once():
    m = Metrics()
    wd = Watchdog(sources=[m], name="t")
    samples = _latency_samples(m, [0.02] * 6 + [0.8])
    fired = _feed(wd, samples)
    assert len(fired) == 1
    p = fired[0]
    assert p["incident_version"] == 1
    assert p["series"]["metric"] == "tpu_cc_reconcile_duration_seconds"
    assert p["series"]["stat"] == "p99"
    assert p["value"] > p["baseline"]["ewma"]
    assert p["z"] >= wd.z_threshold
    assert p["baseline"]["windows"] >= wd.min_windows
    assert isinstance(p["window"], dict) and "window_count" in p["window"]
    assert p["capture_s"] >= 0
    assert wd.incidents_total == 1


def test_exemplars_harvested_from_sources():
    m = Metrics()
    wd = Watchdog(sources=[m], name="t")
    samples = _latency_samples(m, [0.02] * 6 + [0.8])
    (p,) = _feed(wd, samples)
    tids = [e["trace_id"] for e in p["exemplars"]]
    assert "tid6" in tids  # the anomalous observation's trace id
    assert len(tids) <= Watchdog.MAX_EXEMPLARS


def test_profile_and_flightrec_ride_the_packet():
    m = Metrics()
    rec = FlightRecorder(name="t")
    wd = Watchdog(
        sources=[m], profiler=SamplingProfiler(hz=200),
        recorder=rec, capture_s=0.02, name="t",
    )
    samples = _latency_samples(m, [0.02] * 6 + [0.8])
    (p,) = _feed(wd, samples)
    assert (p["profile"]["ticks"] or 0) >= 1
    # no dump dir configured -> honest None, but the event landed
    assert p["flightrec_dump"] is None
    events = [e for e in rec.snapshot()["events"]
              if e["kind"] == "incident"]
    assert events and events[0]["metric"] == (
        "tpu_cc_reconcile_duration_seconds")


def test_incident_dump_written_when_dir_configured(tmp_path):
    m = Metrics()
    rec = FlightRecorder(name="t", dump_dir=str(tmp_path),
                         min_dump_interval_s=0.0)
    wd = Watchdog(sources=[m], recorder=rec, name="t")
    samples = _latency_samples(m, [0.02] * 6 + [0.8])
    (p,) = _feed(wd, samples)
    assert p["flightrec_dump"] and "incident" in p["flightrec_dump"]


def test_cooldown_throttles_refires():
    m = Metrics()
    wd = Watchdog(sources=[m], name="t", cooldown_s=3600.0)
    samples = _latency_samples(m, [0.02] * 6 + [0.9, 0.9, 0.9])
    fired = _feed(wd, samples)
    assert len(fired) == 1  # the repeats landed inside the cooldown


def test_one_sided_a_latency_drop_never_fires():
    m = Metrics()
    wd = Watchdog(sources=[m], name="t")
    # high stable baseline, then a dramatic IMPROVEMENT
    samples = _latency_samples(m, [2.0] * 6 + [0.005])
    assert _feed(wd, samples) == []


# ------------------------------------------------------ firing hygiene


def test_cold_ring_stays_silent():
    """Fewer than min_windows baseline windows -> silence, whatever
    the values look like (ISSUE 15 satellite)."""
    m = Metrics()
    wd = Watchdog(sources=[m], name="t", min_windows=4)
    # an immediate excursion with only 2 prior windows
    samples = _latency_samples(m, [0.02, 0.02, 5.0])
    assert _feed(wd, samples) == []
    assert wd.incidents_total == 0


def test_counter_restart_cannot_fire(monkeypatch):
    """A process restart mid-window resets cumulative counters; the
    window delta clamps to 0 (tsring.counter_delta), so the rate
    series reads 0/min — never a negative, never a spike, NEVER an
    incident on its own (ISSUE 15 satellite)."""
    wd = Watchdog(
        series=(WatchSeries("tpu_cc_publish_retries_total", "rate",
                            min_scale=30.0),),
        name="t",
    )
    fam = lambda total: {  # noqa: E731
        "tpu_cc_publish_retries_total": {
            "type": "counter", "series": {"": float(total)},
        },
    }
    samples = [(float(t), fam(t * 5)) for t in range(8)]  # 300/min steady
    assert _feed(wd, samples) == []
    # restart: the counter falls back to (then climbs from) zero
    samples.append((8.0, fam(0)))
    samples.append((9.0, fam(3)))
    fired = []
    fired.extend(wd.consume(samples[:9]))
    fired.extend(wd.consume(samples))
    assert fired == []
    assert wd.incidents_total == 0


def test_empty_windows_do_not_feed_the_baseline():
    """Windows with no observations yield p99=None: skipped entirely —
    they neither advance min_windows nor dilute the EWMA."""
    m = Metrics()
    wd = Watchdog(sources=[m], name="t")
    samples = _latency_samples(m, [0.02, 0.02, 0.02])
    # idle ticks: snapshots advance, the histogram does not
    t = samples[-1][0]
    for i in range(5):
        samples.append((t + 1.0 + i, snapshot_metric_set(m)))
    for i in range(1, len(samples) + 1):
        wd.consume(samples[:i])
    key = ("tpu_cc_reconcile_duration_seconds", "", "p99")
    # adjacent-sample windows: 3 observation samples -> 2 populated
    # windows; the 5 idle windows contributed nothing
    assert wd._state[key].n == 2


def test_consume_never_raises():
    wd = Watchdog(name="t")
    assert wd.consume([(1.0, {"broken": None})]) == []
    assert wd.consume([(1.0, {"broken": None}), (2.0, object())]) == []


# ------------------------------------------------------------- surfaces


def test_route_and_doc_shape():
    m = Metrics()
    wd = Watchdog(sources=[m], name="box")
    samples = _latency_samples(m, [0.02] * 6 + [0.8])
    _feed(wd, samples)
    doc = wd.to_doc()
    assert doc["watchdog_version"] == 1
    assert doc["name"] == "box"
    assert doc["incidents_total"] == 1
    assert len(doc["incidents"]) == 1
    assert {s["metric"] for s in doc["series"]} == {
        ws.metric for ws in DEFAULT_SERIES}
    code, body, ctype = wd.route()
    assert code == 200 and ctype == "application/json"
    assert b"incidents" in body


def test_health_server_serves_incidents():
    import json
    import urllib.error
    import urllib.request

    from tpu_cc_manager.obs import HealthServer

    m = Metrics()
    wd = Watchdog(sources=[m], name="agent")
    srv = HealthServer(m, port=0, watchdog=wd).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/incidents", timeout=5
        ) as r:
            doc = json.loads(r.read())
        assert doc["watchdog_version"] == 1
        assert doc["incidents"] == []
    finally:
        srv.stop()
    srv2 = HealthServer(m, port=0).start()
    try:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv2.port}/debug/incidents",
                timeout=5,
            )
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv2.stop()


def test_flightrec_embeds_profile_only_when_sampled():
    import threading
    import time as _time

    p = SamplingProfiler(hz=200)
    rec = FlightRecorder(name="t", profiler=p)
    assert "profile" not in rec.snapshot("t")  # idle profiler: no bloat
    stop = threading.Event()
    worker = threading.Thread(
        target=lambda: stop.wait(5), daemon=True)
    worker.start()
    try:
        deadline = _time.monotonic() + 5
        while p.samples_total == 0 and _time.monotonic() < deadline:
            p.sample_once()
    finally:
        stop.set()
        worker.join(timeout=5)
    snap = rec.snapshot("t")
    assert snap["profile"]["samples"] >= 1
    assert "folded" in snap["profile"]


def test_robust_scale_floor_blocks_constant_baseline_jitter():
    """With a near-constant baseline the MAD collapses to ~0; the
    min_scale floor keeps ordinary jitter from reading as infinite z."""
    m = Metrics()
    wd = Watchdog(sources=[m], name="t")
    # identical windows, then a +20 ms wiggle: real but tiny
    samples = _latency_samples(m, [0.02] * 8 + [0.04])
    assert _feed(wd, samples) == []


def test_math_stays_finite_on_zero_baseline():
    wd = Watchdog(
        series=(WatchSeries("tpu_cc_publish_retries_total", "rate",
                            min_scale=30.0),),
        name="t",
    )
    fam = {"tpu_cc_publish_retries_total": {
        "type": "counter", "series": {"": 0.0}}}
    samples = [(float(t), fam) for t in range(6)]
    assert _feed(wd, samples) == []
    state = wd._state[("tpu_cc_publish_retries_total", "", "rate")]
    assert math.isfinite(state.ewma) and math.isfinite(state.mad)
