"""The sampling profiler (tpu_cc_manager/profiler.py, ISSUE 15):
span-keyed wall-clock stacks, bounded aggregation, arm/disarm."""

import threading
import time

from tpu_cc_manager.profiler import SamplingProfiler
from tpu_cc_manager.trace import Tracer, span_on_thread


class _Busy:
    """A worker parked inside a named span until released."""

    def __init__(self, phase="reset"):
        self.stop = threading.Event()
        self.started = threading.Event()
        self.phase = phase
        self.tracer = Tracer()
        self.thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self.tracer.span(self.phase):
            self.started.set()
            while not self.stop.is_set():
                time.sleep(0.002)

    def __enter__(self):
        self.thread.start()
        assert self.started.wait(5)
        return self

    def __exit__(self, *exc):
        self.stop.set()
        self.thread.join(timeout=5)


def test_sample_keys_stack_to_active_span():
    with _Busy("reset") as busy:
        assert span_on_thread(busy.thread.ident).name == "reset"
        p = SamplingProfiler(hz=200, name="t")
        for _ in range(5):
            p.sample_once()
        folded = p.folded()
        assert any(line.startswith("reset;") for line in folded), folded
        # folded format: phase;root;...;leaf count
        line = [l for l in folded if l.startswith("reset;")][0]
        stack, count = line.rsplit(" ", 1)
        assert int(count) >= 1
        assert "_run" in stack
    assert span_on_thread(busy.thread.ident) is None  # span closed


def test_phase_totals_exclude_untraced_threads():
    with _Busy("verify"):
        p = SamplingProfiler(hz=200)
        for _ in range(4):
            p.sample_once()
    totals = dict(p.phase_totals())
    assert "verify" in totals
    assert "-" not in totals
    # but untraced samples still count toward the total accounting
    assert p.summary()["samples"] >= totals["verify"]


def test_capture_is_synchronous_and_bounded():
    p = SamplingProfiler(hz=100)
    with _Busy("reset"):
        t0 = time.monotonic()
        s = p.capture(0.1)
        elapsed = time.monotonic() - t0
    assert 0.05 <= elapsed <= 2.0
    assert s["samples"] >= 1
    assert s["ticks"] >= 1
    assert isinstance(s["folded"], list)
    assert isinstance(s["phase_totals"], list)


def test_arm_disarm_lifecycle():
    p = SamplingProfiler(hz=100)
    with _Busy("reset"):
        assert not p.armed
        p.arm()
        assert p.armed
        p.arm()  # idempotent
        deadline = time.monotonic() + 5
        while p.samples_total == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        p.disarm()
    assert not p.armed
    assert p.samples_total > 0
    n = p.ticks_total
    time.sleep(0.05)
    assert p.ticks_total == n  # actually stopped


def test_arm_with_duration_self_disarms():
    p = SamplingProfiler(hz=200)
    p.arm(duration_s=0.05)
    deadline = time.monotonic() + 5
    while p.armed and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not p.armed


def test_stack_table_is_bounded():
    p = SamplingProfiler(hz=100, max_stacks=1)
    with _Busy("reset"), _Busy("verify"):
        for _ in range(4):
            p.sample_once()
    assert p.summary()["distinct_stacks"] == 1
    assert p.overflow_dropped > 0


def test_reset_clears_aggregate():
    p = SamplingProfiler(hz=100)
    with _Busy("reset"):
        p.sample_once()
    assert p.samples_total > 0
    p.reset()
    s = p.summary()
    assert s["samples"] == 0 and s["folded"] == []
