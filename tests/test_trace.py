"""Tracing subsystem: span trees, sinks, per-phase metrics, /debug/traces.

The reference's only tracing is ``set -x`` in its bash engine
(reference scripts/cc-manager.sh:3); these tests cover the structured
replacement (SURVEY.md §5.1 / §7.2 step 5).
"""

import json
import threading
import urllib.request

import pytest

from tpu_cc_manager.device.fake import fake_backend
from tpu_cc_manager.engine import ModeEngine, NullDrainer
from tpu_cc_manager.obs import HealthServer, Metrics
from tpu_cc_manager.trace import JsonlSink, Tracer


def test_span_nesting_and_ids():
    tr = Tracer()
    with tr.span("reconcile", mode="on") as root:
        with tr.span("evict") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
    spans = tr.recent()
    # children complete (and are recorded) before their parent
    assert [s["name"] for s in spans] == ["evict", "reconcile"]
    assert spans[0]["trace"] == spans[1]["trace"]
    assert spans[1]["attrs"] == {"mode": "on"}
    assert all(s["status"] == "ok" for s in spans)
    assert all(s["dur_s"] >= 0 for s in spans)


def test_span_error_status_propagates_exception():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("flip", device="/dev/accel0"):
            raise ValueError("boom")
    (span,) = tr.recent()
    assert span["status"] == "error"
    assert "ValueError: boom" in span["error"]


def test_sibling_traces_get_distinct_ids():
    tr = Tracer()
    with tr.span("reconcile"):
        pass
    with tr.span("reconcile"):
        pass
    a, b = tr.recent()
    assert a["trace"] != b["trace"]
    assert len(tr.traces()) == 2


def test_ring_buffer_bounded():
    tr = Tracer(ring_size=8)
    for _ in range(50):
        with tr.span("plan"):
            pass
    assert len(tr.recent(limit=100)) == 8


def test_threads_keep_separate_span_stacks():
    tr = Tracer()
    errs = []

    def worker(i):
        try:
            with tr.span("reconcile", worker=i) as root:
                with tr.span("flip", worker=i) as child:
                    assert child.parent_id == root.span_id
                    assert child.trace_id == root.trace_id
        except BaseException as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    spans = tr.recent()
    assert len(spans) == 16
    # every flip's parent is the reconcile of the same worker
    roots = {s["span"]: s for s in spans if s["name"] == "reconcile"}
    for s in spans:
        if s["name"] == "flip":
            parent = roots[s["parent"]]
            assert parent["attrs"]["worker"] == s["attrs"]["worker"]


def test_jsonl_sink(tmp_path):
    path = tmp_path / "trace.jsonl"
    tr = Tracer()
    tr.add_sink(JsonlSink(str(path)))
    with tr.span("reconcile", mode="on"):
        with tr.span("evict"):
            pass
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["name"] for l in lines] == ["evict", "reconcile"]


def test_broken_sink_does_not_break_spans():
    tr = Tracer()
    tr.add_sink(lambda s: (_ for _ in ()).throw(RuntimeError("sink down")))
    with tr.span("reconcile"):
        pass
    assert tr.recent()[0]["status"] == "ok"


def test_engine_emits_phase_spans(monkeypatch):
    # concurrency 1 pins the HISTORICAL serial span order exactly; the
    # parallel pipeline's span tree (same spans, same parenting, order
    # interleaved across worker threads) is pinned in
    # test_engine_parallel.py
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    tr = Tracer()
    backend = fake_backend(n_chips=2)
    engine = ModeEngine(
        set_state_label=lambda v: None,
        drainer=NullDrainer(),
        evict_components=True,
        backend=backend,
        tracer=tr,
    )
    assert engine.set_mode("on")
    names = [s["name"] for s in tr.recent()]
    # spans land on COMPLETION, so a flip's sub-phases (stage ->
    # holder_check -> reset -> wait_ready -> verify) precede their
    # parent "flip" span
    per_flip = ["stage", "holder_check", "reset", "wait_ready",
                "verify", "flip"]
    assert names == (
        ["enumerate", "plan", "taint_set", "evict"]
        + per_flip + per_flip
        + ["reschedule", "taint_clear", "state_label"]
    )
    plan_span = next(s for s in tr.recent() if s["name"] == "plan")
    assert plan_span["attrs"] == {"mode": "on", "devices": 2, "divergent": 2}
    flips = [s for s in tr.recent() if s["name"] == "flip"]
    assert {f["attrs"]["device"] for f in flips} == {"/dev/accel0", "/dev/accel1"}
    assert all(f["attrs"]["changes"] == {"cc": "on"} for f in flips)


def test_engine_flip_span_error_on_device_failure():
    tr = Tracer()
    backend = fake_backend(n_chips=1)
    backend.chips[0].fail_reset = True
    engine = ModeEngine(
        set_state_label=lambda v: None,
        drainer=NullDrainer(),
        evict_components=False,
        backend=backend,
        tracer=tr,
    )
    assert engine.set_mode("on") is False
    flip = next(s for s in tr.recent() if s["name"] == "flip")
    assert flip["status"] == "error"
    assert "reset failed" in flip["error"]


def test_engine_flip_span_error_on_verify_mismatch():
    tr = Tracer()
    backend = fake_backend(n_chips=1)
    backend.chips[0].drop_staged_mode = True
    engine = ModeEngine(
        set_state_label=lambda v: None,
        drainer=NullDrainer(),
        evict_components=False,
        backend=backend,
        tracer=tr,
    )
    assert engine.set_mode("on") is False
    flip = next(s for s in tr.recent() if s["name"] == "flip")
    assert flip["status"] == "error"
    assert "verify mismatch" in flip["error"]


def test_metrics_phase_histogram_sink():
    tr = Tracer()
    m = Metrics()
    tr.add_sink(m.observe_span)
    with tr.span("reconcile"):
        with tr.span("flip"):
            pass
    assert m.phase_duration.labels("reconcile").count == 1
    assert m.phase_duration.labels("flip").count == 1
    text = m.render()
    assert 'tpu_cc_phase_duration_seconds_count{phase="flip"} 1' in text
    assert 'tpu_cc_phase_duration_seconds_bucket{phase="reconcile",le="+Inf"} 1' in text


def test_debug_traces_endpoint():
    tr = Tracer()
    with tr.span("reconcile", mode="on"):
        pass
    srv = HealthServer(Metrics(), port=0, tracer=tr).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/traces"
        ) as resp:
            body = json.load(resp)
        assert body and body[-1]["name"] == "reconcile"
        assert body[-1]["attrs"] == {"mode": "on"}
    finally:
        srv.stop()


def test_agent_wires_reconcile_spans():
    """End-to-end: agent reconcile produces a rooted span tree and the
    per-phase histogram via its own tracer/metrics."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig
    from tpu_cc_manager.k8s.fake import FakeKube
    from tpu_cc_manager.k8s.objects import make_node

    kube = FakeKube()
    kube.add_node(make_node("n1"))
    cfg = AgentConfig(
        node_name="n1", drain_strategy="none", health_port=0,
        readiness_file="/tmp/.test-trace-ready",
    )
    backend = fake_backend(n_chips=1)
    agent = CCManagerAgent(kube, cfg, backend=backend)
    assert agent.reconcile("on")
    spans = agent.tracer.recent()
    root = next(s for s in spans if s["name"] == "reconcile")
    assert root["attrs"]["outcome"] == "success"
    assert root.get("parent") is None
    # every span of the reconcile TREE shares its trace id. Spans
    # emitted from the async recorder thread (evidence_publish) are
    # deliberately their own roots — the publish happens OFF the
    # reconcile path, and the tracer's stacks are thread-local — and
    # they may or may not have landed yet (that's the async contract,
    # and why they are excluded rather than awaited here)
    async_roots = ("evidence_publish",)
    for s in spans:
        if s["name"] == "reconcile" or s["name"] in async_roots:
            continue
        assert s["trace"] == root["trace"], s
    assert agent.metrics.phase_duration.labels("reconcile").count == 1
    assert agent.metrics.phase_duration.labels("flip").count == 1


# ------------------------------------------- cross-process propagation


def test_traceparent_roundtrip_across_tracers():
    """The ISSUE 8 propagation contract: a controller-side span
    formatted as the cc.trace annotation value re-seats on a DIFFERENT
    tracer (different process in production), so the consuming
    reconcile tree carries the producer's trace id."""
    from tpu_cc_manager.trace import format_traceparent, parse_traceparent

    controller, agent_tr = Tracer(), Tracer()
    with controller.span("desired_write", mode="on") as dw:
        context = format_traceparent(dw)  # safe while OPEN
    assert context == f"00-{dw.trace_id}-{dw.span_id}-01"
    parsed = parse_traceparent(context)
    assert (parsed.trace_id, parsed.span_id) == (dw.trace_id, dw.span_id)
    with agent_tr.adopt_remote(context):
        with agent_tr.span("reconcile", mode="on") as root:
            with agent_tr.span("flip") as child:
                pass
    assert root.trace_id == dw.trace_id
    assert root.parent_id == dw.span_id
    assert child.trace_id == dw.trace_id
    assert child.parent_id == root.span_id


def test_adopt_remote_degrades_on_garbage():
    """A node annotation is hostile surface: every malformed context
    yields a LOCAL root, never an exception."""
    tr = Tracer()
    for bad in (None, "", "garbage", "00-a-b", "01-a-b-01", "00--b-01",
                "00-a--01", "00-a-b-01-extra", 42, {"trace": "x"}):
        with tr.adopt_remote(bad):
            with tr.span("reconcile") as root:
                pass
        assert root.parent_id is None, bad
        assert root.trace_id == root.span_id


def test_tracer_id_prefixes_prevent_cross_process_collisions():
    """Two tracers (two processes, in production) both mint span #1;
    a fleet-wide stitch by trace id must not conflate them."""
    ids = set()
    for tr in (Tracer(), Tracer(), Tracer()):
        with tr.span("reconcile") as s:
            pass
        ids.add(s.trace_id)
    assert len(ids) == 3


def test_current_trace_ids_join_key_for_logs():
    from tpu_cc_manager.trace import current_trace_ids

    tr = Tracer()
    assert current_trace_ids() == (None, None)
    with tr.span("reconcile") as root:
        assert current_trace_ids() == (root.trace_id, root.span_id)
        with tr.span("flip") as child:
            assert current_trace_ids() == (child.trace_id, child.span_id)
        assert current_trace_ids() == (root.trace_id, root.span_id)
    assert current_trace_ids() == (None, None)


def test_current_trace_ids_sees_adopted_remote_context():
    """obs.JsonLogFormatter's key: inside an adopted remote context the
    active span carries the REMOTE trace id."""
    from tpu_cc_manager.trace import current_trace_ids

    tr = Tracer()
    with tr.adopt_remote("00-remotetrace-remotespan-01"):
        with tr.span("reconcile"):
            trace_id, _ = current_trace_ids()
            assert trace_id == "remotetrace"


# --------------------------------------------------- JSONL sink bounds


def test_jsonl_sink_rotates_at_cap_exactly_one_line_per_span(tmp_path):
    path = tmp_path / "t.jsonl"
    tr = Tracer()
    sink = JsonlSink(str(path), max_bytes=2000)
    tr.add_sink(sink)
    for i in range(120):
        with tr.span("plan", i=i):
            pass
    assert sink.rotations >= 1
    rotated = tmp_path / "t.jsonl.1"
    assert rotated.exists()
    # the live file honors the cap (a span line is never split)
    assert path.stat().st_size <= 2000
    assert rotated.stat().st_size <= 2000
    seen = []
    for f in (rotated, path):
        for line in f.read_text().splitlines():
            seen.append(json.loads(line)["attrs"]["i"])  # every line whole
    # exactly-one-line-per-span within retention: no dup, no tear, the
    # newest span present, retained window contiguous
    assert len(seen) == len(set(seen))
    assert seen[-1] == 119
    assert seen == list(range(seen[0], 120))


def test_jsonl_sink_unbounded_without_cap(tmp_path):
    path = tmp_path / "t.jsonl"
    tr = Tracer()
    tr.add_sink(JsonlSink(str(path), max_bytes=0))
    for i in range(50):
        with tr.span("plan", i=i):
            pass
    assert len(path.read_text().splitlines()) == 50
    assert not (tmp_path / "t.jsonl.1").exists()


def test_jsonl_cap_env_knob(monkeypatch, tmp_path):
    monkeypatch.setenv("TPU_CC_TRACE_JSONL_MAX_MB", "2")
    assert JsonlSink(str(tmp_path / "a.jsonl")).max_bytes == 2 * 1024 * 1024
    monkeypatch.setenv("TPU_CC_TRACE_JSONL_MAX_MB", "0.5")
    assert JsonlSink(str(tmp_path / "b.jsonl")).max_bytes == 512 * 1024
    # a typo degrades to unbounded (historical behavior), not a crash
    monkeypatch.setenv("TPU_CC_TRACE_JSONL_MAX_MB", "lots")
    assert JsonlSink(str(tmp_path / "c.jsonl")).max_bytes == 0
    monkeypatch.delenv("TPU_CC_TRACE_JSONL_MAX_MB")
    assert JsonlSink(str(tmp_path / "d.jsonl")).max_bytes == 0


def test_jsonl_sink_failed_rotation_does_not_reset_accounting(tmp_path):
    """A failed os.replace must NOT convince the sink the file is
    empty — otherwise the file grows by max_bytes per failed attempt
    while the sink believes it's under the cap."""
    import os

    path = tmp_path / "t.jsonl"
    os.mkdir(str(path) + ".1")  # rotation target blocked: replace fails
    tr = Tracer()
    sink = JsonlSink(str(path), max_bytes=600)
    tr.add_sink(sink)
    for i in range(40):
        with tr.span("plan", i=i):
            pass
    assert sink.rotations == 0  # every attempt failed
    # no span lost, every line whole (degraded mode keeps appending)
    lines = path.read_text().splitlines()
    assert [json.loads(l)["attrs"]["i"] for l in lines] == list(range(40))
    # the tracked size stayed honest: once over the cap, EVERY further
    # write re-attempts rotation (it never thinks it reset to zero)
    assert sink._size >= path.stat().st_size


def test_remove_sink_detaches():
    tr = Tracer()
    seen = []
    sink = seen.append
    tr.add_sink(sink)
    with tr.span("plan"):
        pass
    tr.remove_sink(sink)
    tr.remove_sink(sink)  # absent: no-op, no raise
    with tr.span("plan"):
        pass
    assert len(seen) == 1
