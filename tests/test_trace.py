"""Tracing subsystem: span trees, sinks, per-phase metrics, /debug/traces.

The reference's only tracing is ``set -x`` in its bash engine
(reference scripts/cc-manager.sh:3); these tests cover the structured
replacement (SURVEY.md §5.1 / §7.2 step 5).
"""

import json
import threading
import urllib.request

import pytest

from tpu_cc_manager.device.fake import fake_backend
from tpu_cc_manager.engine import ModeEngine, NullDrainer
from tpu_cc_manager.obs import HealthServer, Metrics
from tpu_cc_manager.trace import JsonlSink, Tracer


def test_span_nesting_and_ids():
    tr = Tracer()
    with tr.span("reconcile", mode="on") as root:
        with tr.span("evict") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
    spans = tr.recent()
    # children complete (and are recorded) before their parent
    assert [s["name"] for s in spans] == ["evict", "reconcile"]
    assert spans[0]["trace"] == spans[1]["trace"]
    assert spans[1]["attrs"] == {"mode": "on"}
    assert all(s["status"] == "ok" for s in spans)
    assert all(s["dur_s"] >= 0 for s in spans)


def test_span_error_status_propagates_exception():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("flip", device="/dev/accel0"):
            raise ValueError("boom")
    (span,) = tr.recent()
    assert span["status"] == "error"
    assert "ValueError: boom" in span["error"]


def test_sibling_traces_get_distinct_ids():
    tr = Tracer()
    with tr.span("reconcile"):
        pass
    with tr.span("reconcile"):
        pass
    a, b = tr.recent()
    assert a["trace"] != b["trace"]
    assert len(tr.traces()) == 2


def test_ring_buffer_bounded():
    tr = Tracer(ring_size=8)
    for _ in range(50):
        with tr.span("plan"):
            pass
    assert len(tr.recent(limit=100)) == 8


def test_threads_keep_separate_span_stacks():
    tr = Tracer()
    errs = []

    def worker(i):
        try:
            with tr.span("reconcile", worker=i) as root:
                with tr.span("flip", worker=i) as child:
                    assert child.parent_id == root.span_id
                    assert child.trace_id == root.trace_id
        except BaseException as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    spans = tr.recent()
    assert len(spans) == 16
    # every flip's parent is the reconcile of the same worker
    roots = {s["span"]: s for s in spans if s["name"] == "reconcile"}
    for s in spans:
        if s["name"] == "flip":
            parent = roots[s["parent"]]
            assert parent["attrs"]["worker"] == s["attrs"]["worker"]


def test_jsonl_sink(tmp_path):
    path = tmp_path / "trace.jsonl"
    tr = Tracer()
    tr.add_sink(JsonlSink(str(path)))
    with tr.span("reconcile", mode="on"):
        with tr.span("evict"):
            pass
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["name"] for l in lines] == ["evict", "reconcile"]


def test_broken_sink_does_not_break_spans():
    tr = Tracer()
    tr.add_sink(lambda s: (_ for _ in ()).throw(RuntimeError("sink down")))
    with tr.span("reconcile"):
        pass
    assert tr.recent()[0]["status"] == "ok"


def test_engine_emits_phase_spans(monkeypatch):
    # concurrency 1 pins the HISTORICAL serial span order exactly; the
    # parallel pipeline's span tree (same spans, same parenting, order
    # interleaved across worker threads) is pinned in
    # test_engine_parallel.py
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    tr = Tracer()
    backend = fake_backend(n_chips=2)
    engine = ModeEngine(
        set_state_label=lambda v: None,
        drainer=NullDrainer(),
        evict_components=True,
        backend=backend,
        tracer=tr,
    )
    assert engine.set_mode("on")
    names = [s["name"] for s in tr.recent()]
    # spans land on COMPLETION, so a flip's sub-phases (stage ->
    # holder_check -> reset -> wait_ready -> verify) precede their
    # parent "flip" span
    per_flip = ["stage", "holder_check", "reset", "wait_ready",
                "verify", "flip"]
    assert names == (
        ["enumerate", "plan", "taint_set", "evict"]
        + per_flip + per_flip
        + ["reschedule", "taint_clear", "state_label"]
    )
    plan_span = next(s for s in tr.recent() if s["name"] == "plan")
    assert plan_span["attrs"] == {"mode": "on", "devices": 2, "divergent": 2}
    flips = [s for s in tr.recent() if s["name"] == "flip"]
    assert {f["attrs"]["device"] for f in flips} == {"/dev/accel0", "/dev/accel1"}
    assert all(f["attrs"]["changes"] == {"cc": "on"} for f in flips)


def test_engine_flip_span_error_on_device_failure():
    tr = Tracer()
    backend = fake_backend(n_chips=1)
    backend.chips[0].fail_reset = True
    engine = ModeEngine(
        set_state_label=lambda v: None,
        drainer=NullDrainer(),
        evict_components=False,
        backend=backend,
        tracer=tr,
    )
    assert engine.set_mode("on") is False
    flip = next(s for s in tr.recent() if s["name"] == "flip")
    assert flip["status"] == "error"
    assert "reset failed" in flip["error"]


def test_engine_flip_span_error_on_verify_mismatch():
    tr = Tracer()
    backend = fake_backend(n_chips=1)
    backend.chips[0].drop_staged_mode = True
    engine = ModeEngine(
        set_state_label=lambda v: None,
        drainer=NullDrainer(),
        evict_components=False,
        backend=backend,
        tracer=tr,
    )
    assert engine.set_mode("on") is False
    flip = next(s for s in tr.recent() if s["name"] == "flip")
    assert flip["status"] == "error"
    assert "verify mismatch" in flip["error"]


def test_metrics_phase_histogram_sink():
    tr = Tracer()
    m = Metrics()
    tr.add_sink(m.observe_span)
    with tr.span("reconcile"):
        with tr.span("flip"):
            pass
    assert m.phase_duration.labels("reconcile").count == 1
    assert m.phase_duration.labels("flip").count == 1
    text = m.render()
    assert 'tpu_cc_phase_duration_seconds_count{phase="flip"} 1' in text
    assert 'tpu_cc_phase_duration_seconds_bucket{phase="reconcile",le="+Inf"} 1' in text


def test_debug_traces_endpoint():
    tr = Tracer()
    with tr.span("reconcile", mode="on"):
        pass
    srv = HealthServer(Metrics(), port=0, tracer=tr).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/traces"
        ) as resp:
            body = json.load(resp)
        assert body and body[-1]["name"] == "reconcile"
        assert body[-1]["attrs"] == {"mode": "on"}
    finally:
        srv.stop()


def test_agent_wires_reconcile_spans():
    """End-to-end: agent reconcile produces a rooted span tree and the
    per-phase histogram via its own tracer/metrics."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig
    from tpu_cc_manager.k8s.fake import FakeKube
    from tpu_cc_manager.k8s.objects import make_node

    kube = FakeKube()
    kube.add_node(make_node("n1"))
    cfg = AgentConfig(
        node_name="n1", drain_strategy="none", health_port=0,
        readiness_file="/tmp/.test-trace-ready",
    )
    backend = fake_backend(n_chips=1)
    agent = CCManagerAgent(kube, cfg, backend=backend)
    assert agent.reconcile("on")
    spans = agent.tracer.recent()
    root = next(s for s in spans if s["name"] == "reconcile")
    assert root["attrs"]["outcome"] == "success"
    assert root.get("parent") is None
    # every span of the reconcile TREE shares its trace id. Spans
    # emitted from the async recorder thread (evidence_publish) are
    # deliberately their own roots — the publish happens OFF the
    # reconcile path, and the tracer's stacks are thread-local — and
    # they may or may not have landed yet (that's the async contract,
    # and why they are excluded rather than awaited here)
    async_roots = ("evidence_publish",)
    for s in spans:
        if s["name"] == "reconcile" or s["name"] in async_roots:
            continue
        assert s["trace"] == root["trace"], s
    assert agent.metrics.phase_duration.labels("reconcile").count == 1
    assert agent.metrics.phase_duration.labels("flip").count == 1
