"""TPUCCPolicy controller (tpu_cc_manager.policy).

The reference has no declarative surface at all (admins patch node
labels by hand, reference README_PYTHON.md:77-102); these tests cover
the custom-resource plumbing (FakeKube store, FakeApiServer wire
protocol, HttpKubeClient) and the level-triggered controller built on
top of the rollout layer.
"""

import json
import threading
import time
import urllib.request

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.client import (
    ApiException, HttpKubeClient, KubeConfig,
)
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.policy import (
    PolicyController, PolicySpecError, parse_policy_spec,
)

G, V, P = L.POLICY_GROUP, L.POLICY_VERSION, L.POLICY_PLURAL


def make_policy(name, mode="on", selector=L.TPU_ACCELERATOR_LABEL,
                paused=False, strategy=None):
    spec = {"mode": mode, "nodeSelector": selector}
    if paused:
        spec["paused"] = True
    if strategy:
        spec["strategy"] = strategy
    return {
        "apiVersion": f"{G}/{V}",
        "kind": L.POLICY_KIND,
        "metadata": {"name": name},
        "spec": spec,
    }


def _node(name, desired=None, state=None, slice_id=None, extra=None):
    labels = {L.TPU_ACCELERATOR_LABEL: "tpu-v5e-slice"}
    if desired:
        labels[L.CC_MODE_LABEL] = desired
    if state:
        labels[L.CC_MODE_STATE_LABEL] = state
    if slice_id:
        labels[L.TPU_SLICE_LABEL] = slice_id
    labels.update(extra or {})
    return make_node(name, labels=labels)


class _ReactiveAgents(threading.Thread):
    """Simulated per-node agents: when a node's desired label changes,
    publish the observed state after a small delay ('failed' for nodes
    in fail_nodes)."""

    def __init__(self, kube, node_names, fail_nodes=(), delay_s=0.03):
        super().__init__(daemon=True)
        self.kube = kube
        self.node_names = list(node_names)
        self.fail_nodes = set(fail_nodes)
        self.delay_s = delay_s
        self.stop = threading.Event()

    def run(self):
        while not self.stop.is_set():
            for name in self.node_names:
                try:
                    labels = self.kube.get_node(name)["metadata"]["labels"]
                except ApiException:
                    continue
                desired = labels.get(L.CC_MODE_LABEL)
                state = labels.get(L.CC_MODE_STATE_LABEL)
                if desired and state != desired and state != "failed":
                    time.sleep(self.delay_s)
                    value = "failed" if name in self.fail_nodes else desired
                    self.kube.set_node_labels(
                        name, {L.CC_MODE_STATE_LABEL: value}
                    )
            time.sleep(0.01)


def controller(kube, **kw):
    kw.setdefault("poll_s", 0.02)
    return PolicyController(kube, **kw)


# ---------------------------------------------------------------------------
# spec validation
# ---------------------------------------------------------------------------

def test_parse_policy_spec_defaults():
    spec = parse_policy_spec(make_policy("p", mode="on"))
    assert spec["mode"] == "on"
    assert spec["max_unavailable"] == 1
    assert spec["failure_budget"] == 0
    assert spec["group_timeout_s"] == 600.0
    assert not spec["paused"]


@pytest.mark.parametrize("mutate, match", [
    (lambda p: p.pop("spec"), "spec missing"),
    (lambda p: p["spec"].update(mode="bogus"), "invalid CC mode"),
    (lambda p: p["spec"].update(nodeSelector=""), "nodeSelector"),
    (lambda p: p["spec"].update(strategy={"maxUnavailable": 0}),
     "maxUnavailable"),
    (lambda p: p["spec"].update(strategy={"failureBudget": -1}),
     "failureBudget"),
    (lambda p: p["spec"].update(strategy={"groupTimeoutSeconds": 0}),
     "groupTimeoutSeconds"),
    (lambda p: p["spec"].update(strategy="nope"), "must be an object"),
])
def test_parse_policy_spec_rejects(mutate, match):
    pol = make_policy("p")
    mutate(pol)
    with pytest.raises(PolicySpecError, match=match):
        parse_policy_spec(pol)


def test_window_open_semantics():
    from tpu_cc_manager.policy import window_open

    assert window_open(None, 0) and window_open(None, 1439)
    day = (9 * 60, 17 * 60)  # 09:00-17:00
    assert window_open(day, 9 * 60)
    assert window_open(day, 12 * 60)
    assert not window_open(day, 17 * 60)  # end exclusive
    assert not window_open(day, 3 * 60)
    night = (22 * 60, 4 * 60)  # 22:00-04:00 spans midnight
    assert window_open(night, 23 * 60)
    assert window_open(night, 2 * 60)
    assert not window_open(night, 12 * 60)
    frozen = (6 * 60, 6 * 60)  # start == end: never
    assert not window_open(frozen, 6 * 60)


def test_window_spec_validation():
    pol = make_policy("w", strategy={"window": {"start": "26:00",
                                                "end": "04:00"}})
    with pytest.raises(PolicySpecError, match="out of range"):
        parse_policy_spec(pol)
    pol = make_policy("w", strategy={"window": "02:00-04:00"})
    with pytest.raises(PolicySpecError, match="window"):
        parse_policy_spec(pol)
    spec = parse_policy_spec(make_policy(
        "w", strategy={"window": {"start": "22:30", "end": "04:00"}}
    ))
    assert spec["window"] == (22 * 60 + 30, 4 * 60)


def test_maintenance_window_gates_rollout_starts():
    """Outside the window a divergent policy stays Pending with an
    explanatory message; once the clock enters the window, the same
    scan logic rolls it."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    kube.add_custom(G, P, make_policy("p", strategy={
        "groupTimeoutSeconds": 10,
        "window": {"start": "02:00", "end": "04:00"},
    }))
    clock = {"m": 12 * 60}  # noon: closed
    c = PolicyController(kube, poll_s=0.02,
                         utcnow_minutes_fn=lambda: clock["m"])
    st = c.scan_once()["policies"]["p"]
    assert st["phase"] == "Pending"
    assert "maintenance window" in st["message"]
    assert kube.get_node("n0")["metadata"]["labels"][L.CC_MODE_LABEL] \
        == "off"  # nothing patched

    clock["m"] = 3 * 60  # 03:00: open
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    try:
        st = c.scan_once()["policies"]["p"]
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert st["phase"] == "Converged"


# ---------------------------------------------------------------------------
# canary groups (rollout layer)
# ---------------------------------------------------------------------------

def test_canary_serializes_then_widens_window():
    """With canary=1 and max_unavailable=3, the first group must run
    alone and succeed before the remaining groups run wide."""
    from tpu_cc_manager.rollout import Rollout

    kube = FakeKube()
    names = [f"c{i}" for i in range(4)]
    for n in names:
        kube.add_node(_node(n, desired="off", state="off"))
    concurrency = []

    orig_patch = kube.patch_node

    # desired writes are ONE patch_node carrying the label plus the
    # cc.trace annotation (ISSUE 8) — hook the patch verb
    def recording_patch(name, patch):
        if L.CC_MODE_LABEL in (
                (patch.get("metadata") or {}).get("labels") or {}):
            concurrency.append(name)
        return orig_patch(name, patch)

    kube.patch_node = recording_patch
    agents = _ReactiveAgents(kube, names, delay_s=0.1)
    agents.start()
    try:
        report = Rollout(kube, "on", max_unavailable=3, canary=1,
                         poll_s=0.02, group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.ok
    assert len(report.succeeded) == 4
    # the canary (first group, name order) was patched strictly before
    # any other group's desired label
    assert concurrency[0] == "c0"
    # by the time the second patch happened, the canary had converged
    # (serial phase) — meaning c0's state was already 'on'
    rec = json.loads(
        kube.get_node(sorted(names)[0])["metadata"]["annotations"][
            L.ROLLOUT_ANNOTATION]
    )
    assert rec["canary_left"] == 0


def test_canary_failure_aborts_despite_budget():
    from tpu_cc_manager.rollout import Rollout

    kube = FakeKube()
    names = [f"c{i}" for i in range(3)]
    for n in names:
        kube.add_node(_node(n, desired="off", state="off"))
    agents = _ReactiveAgents(kube, names, fail_nodes={"c0"})
    agents.start()
    try:
        report = Rollout(kube, "on", canary=1, failure_budget=5,
                         poll_s=0.02, group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.aborted
    by = {g.name: g.outcome for g in report.groups}
    assert by["node/c0"] == "failed"
    # the budget (5) would have tolerated it; the canary does not
    assert by["node/c1"] == "not_attempted"
    assert by["node/c2"] == "not_attempted"


def test_canary_dry_run_preview_marks_canary_groups():
    from tpu_cc_manager.rollout import Rollout

    kube = FakeKube()
    for i in range(3):
        kube.add_node(_node(f"c{i}", desired="off", state="off"))
    report = Rollout(kube, "on", canary=1, dry_run=True).run()
    by = {g.name: g for g in report.groups}
    assert by["node/c0"].detail == "canary: serial, must succeed"
    assert by["node/c1"].detail == ""
    assert by["node/c2"].detail == ""


def test_canary_failure_and_abort_persist_in_one_write():
    """The abort flag must ride in the SAME record write as the failed
    canary outcome: a crash between two separate persists would leave a
    record that resumes as a budget-excused ordinary failure, wide
    window and all."""
    from tpu_cc_manager.rollout import Rollout

    kube = FakeKube()
    for i in range(3):
        kube.add_node(_node(f"c{i}", desired="off", state="off"))
    snapshots = []
    orig = kube.set_node_annotations

    def recording(name, ann):
        if L.ROLLOUT_ANNOTATION in ann:
            snapshots.append(json.loads(ann[L.ROLLOUT_ANNOTATION]))
        return orig(name, ann)

    kube.set_node_annotations = recording
    agents = _ReactiveAgents(kube, [f"c{i}" for i in range(3)],
                             fail_nodes={"c0"})
    agents.start()
    try:
        report = Rollout(kube, "on", canary=1, failure_budget=5,
                         poll_s=0.02, group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.aborted
    # EVERY persisted record in which the canary shows 'failed' must
    # already carry aborted=true — no intermediate crash window
    saw_failed = False
    for rec in snapshots:
        if rec.get("groups", {}).get("node/c0", {}).get("outcome") \
                == "failed":
            saw_failed = True
            assert rec.get("aborted") is True, rec
    assert saw_failed


def test_canary_discipline_survives_resume():
    """A crash during the canary phase must not let the resumed rollout
    skip the canary: canary_left rides in the durable record."""
    from tpu_cc_manager.rollout import Rollout

    kube = FakeKube()
    for i in range(3):
        kube.add_node(_node(f"c{i}", desired="off", state="off"))
    # a crashed canary rollout: canary group in flight, 2 pending
    record = {
        "id": "cnry01", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 3,
        "failure_budget": 0, "canary_left": 1,
        "complete": False, "aborted": False,
        "groups": {
            "node/c0": {"nodes": ["c0"], "outcome": "in_flight"},
            "node/c1": {"nodes": ["c1"], "outcome": "pending"},
            "node/c2": {"nodes": ["c2"], "outcome": "pending"},
        },
    }
    kube.set_node_annotations(
        "c0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    # the canary node will FAIL: the resumed run must abort, not roll
    # c1/c2 under the wide window
    agents = _ReactiveAgents(kube, ["c0", "c1", "c2"],
                             fail_nodes={"c0"})
    agents.start()
    try:
        report = Rollout.resume(kube, poll_s=0.02,
                                group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.aborted
    by = {g.name: g.outcome for g in report.groups}
    assert by["node/c1"] == "not_attempted"
    assert by["node/c2"] == "not_attempted"


def test_rollout_progress_hook_reports_terminal_groups():
    from tpu_cc_manager.rollout import Rollout

    kube = FakeKube()
    for i in range(3):
        kube.add_node(_node(f"p{i}", desired="off", state="off"))
    seen = []
    agents = _ReactiveAgents(kube, [f"p{i}" for i in range(3)])
    agents.start()
    try:
        report = Rollout(
            kube, "on", poll_s=0.02, group_timeout_s=10,
            on_group=lambda g, o, done, total: seen.append(
                (g, o, done, total)),
        ).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.ok
    assert [s[1] for s in seen] == ["succeeded"] * 3
    assert [s[2] for s in seen] == [1, 2, 3]  # done count advances
    assert all(s[3] == 3 for s in seen)
    # a hook that raises must not fail the rollout
    kube2 = FakeKube()
    kube2.add_node(_node("q0", desired="off", state="off"))
    agents2 = _ReactiveAgents(kube2, ["q0"])
    agents2.start()
    try:
        def boom(*a):
            raise RuntimeError("observer bug")

        assert Rollout(kube2, "on", poll_s=0.02, group_timeout_s=10,
                       on_group=boom).run().ok
    finally:
        agents2.stop.set()
        agents2.join(timeout=2)


def test_policy_status_shows_mid_rollout_progress():
    """During a rollout the policy status message carries per-group
    progress, not just a static 'Rolling'."""
    messages = []

    class Capturing(FakeKube):
        def patch_cluster_custom(self, *a, **k):
            if k.get("subresource") == "status":
                messages.append(a[4]["status"]["message"])
            return super().patch_cluster_custom(*a, **k)

    kube = Capturing()
    for i in range(2):
        kube.add_node(_node(f"n{i}", desired="off", state="off"))
    kube.add_custom(G, P, make_policy(
        "p", strategy={"groupTimeoutSeconds": 10},
    ))
    agents = _ReactiveAgents(kube, ["n0", "n1"])
    agents.start()
    try:
        controller(kube).scan_once()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    progress = [m for m in messages if "group(s) done" in m]
    assert any("1/2" in m for m in progress)
    assert any("2/2" in m for m in progress)


def test_policy_canary_flows_through():
    kube = FakeKube()
    for i in range(2):
        kube.add_node(_node(f"n{i}", desired="off", state="off"))
    kube.add_custom(G, P, make_policy("p", strategy={
        "canary": 1, "maxUnavailable": 2, "groupTimeoutSeconds": 10,
    }))
    agents = _ReactiveAgents(kube, ["n0", "n1"])
    agents.start()
    try:
        st = controller(kube).scan_once()["policies"]["p"]
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert st["phase"] == "Converged"
    assert st["lastRollout"]["ok"] is True


# ---------------------------------------------------------------------------
# custom-resource plumbing: FakeKube semantics
# ---------------------------------------------------------------------------

def test_fake_custom_resource_generation_semantics():
    kube = FakeKube()
    kube.add_custom(G, P, make_policy("p1"))
    got = kube.get_cluster_custom(G, V, P, "p1")
    assert got["metadata"]["generation"] == 1

    # spec patch bumps generation
    kube.patch_cluster_custom(G, V, P, "p1", {"spec": {"mode": "off"}})
    got = kube.get_cluster_custom(G, V, P, "p1")
    assert got["metadata"]["generation"] == 2
    assert got["spec"]["mode"] == "off"

    # status subresource patch does NOT bump generation and does not
    # touch spec
    kube.patch_cluster_custom(
        G, V, P, "p1",
        {"status": {"phase": "Converged"}, "spec": {"mode": "on"}},
        subresource="status",
    )
    got = kube.get_cluster_custom(G, V, P, "p1")
    assert got["metadata"]["generation"] == 2
    assert got["spec"]["mode"] == "off"
    assert got["status"]["phase"] == "Converged"

    # main-resource patch ignores status (it has a subresource)
    kube.patch_cluster_custom(
        G, V, P, "p1", {"status": {"phase": "Bogus"}}
    )
    assert kube.get_cluster_custom(
        G, V, P, "p1"
    )["status"]["phase"] == "Converged"


def test_fake_custom_resource_404s():
    kube = FakeKube()
    with pytest.raises(ApiException) as ei:
        kube.get_cluster_custom(G, V, P, "absent")
    assert ei.value.status == 404
    with pytest.raises(ApiException) as ei:
        kube.patch_cluster_custom(G, V, P, "absent", {})
    assert ei.value.status == 404


def test_list_cluster_custom_sorted_and_scoped():
    kube = FakeKube()
    kube.add_custom(G, P, make_policy("zeta"))
    kube.add_custom(G, P, make_policy("alpha"))
    kube.add_custom(G, "othercollection", make_policy("other"))
    names = [o["metadata"]["name"] for o in kube.list_cluster_custom(G, V, P)]
    assert names == ["alpha", "zeta"]


# ---------------------------------------------------------------------------
# custom-resource plumbing: real wire protocol
# ---------------------------------------------------------------------------

def test_fake_custom_resource_watch():
    kube = FakeKube()
    rv = kube.latest_rv
    kube.add_custom(G, P, make_policy("w1"))
    kube.patch_cluster_custom(G, V, P, "w1", {"spec": {"mode": "off"}})
    events = list(kube.watch_cluster_custom(G, V, P, resource_version=rv,
                                            timeout_s=0.3))
    assert [(t, o["metadata"]["name"]) for t, o in events] == [
        ("ADDED", "w1"), ("MODIFIED", "w1"),
    ]
    # a different collection's watcher sees nothing
    assert list(kube.watch_cluster_custom(
        G, "othercoll", "othercoll", resource_version=rv, timeout_s=0.2
    )) == []


def test_custom_resource_watch_over_the_wire():
    store = FakeKube()
    with FakeApiServer(store) as srv:
        client = HttpKubeClient(
            KubeConfig("127.0.0.1", srv.port, use_tls=False)
        )
        rv = store.latest_rv
        got = []
        done = threading.Event()

        def watch():
            for etype, obj in client.watch_cluster_custom(
                G, V, P, resource_version=rv, timeout_s=3
            ):
                got.append((etype, obj["metadata"]["name"]))
                if len(got) >= 2:
                    break
            done.set()

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        time.sleep(0.2)
        store.add_custom(G, P, make_policy("wired"))
        store.patch_cluster_custom(G, V, P, "wired",
                                   {"spec": {"paused": True}})
        assert done.wait(5)
        t.join(timeout=5)
        assert got == [("ADDED", "wired"), ("MODIFIED", "wired")]


def test_run_loop_reacts_to_policy_events_before_interval():
    """Event-driven reconciliation: with a one-hour interval, a newly
    created policy must still converge the pool within seconds because
    the CR watch wakes the scan loop."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    c = controller(kube, interval_s=3600)
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    t = threading.Thread(target=c.run, daemon=True)
    t.start()
    try:
        time.sleep(0.3)  # first (empty) scan done; loop is now waiting
        kube.add_custom(G, P, make_policy(
            "evt", strategy={"groupTimeoutSeconds": 10},
        ))
        deadline = time.monotonic() + 10
        phase = None
        while time.monotonic() < deadline:
            try:
                phase = kube.get_cluster_custom(
                    G, V, P, "evt"
                ).get("status", {}).get("phase")
            except ApiException:
                phase = None
            if phase == "Converged":
                break
            time.sleep(0.05)
        assert phase == "Converged"
        labels = kube.get_node("n0")["metadata"]["labels"]
        assert labels[L.CC_MODE_STATE_LABEL] == "on"
    finally:
        agents.stop.set()
        agents.join(timeout=2)
        c.stop()
        t.join(timeout=10)


def test_policy_events_record_rollout_history():
    """kubectl-describe-tpuccpolicy visibility: rollout start/outcome
    and conflict ENTRY (not every scan while it persists) post Events
    against the policy object."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    kube.add_custom(G, P, make_policy(
        "p", strategy={"groupTimeoutSeconds": 10},
    ))
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    c = controller(kube)
    try:
        c.scan_once()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    events = [
        (e["reason"], e["involvedObject"]["kind"],
         e["involvedObject"]["name"])
        for e in kube.list_events("default")
    ]
    assert ("PolicyRolloutStarted", "TPUCCPolicy", "p") in events
    assert ("PolicyRolloutSucceeded", "TPUCCPolicy", "p") in events

    # conflict entry fires once, then stays quiet while it persists
    # (paused so the earlier-named claimant never drives a rollout of
    # its own — claiming is independent of pause)
    kube.add_custom(G, P, make_policy("aaa", mode="off", paused=True))
    c.scan_once()
    c.scan_once()
    conflicts = [
        e for e in kube.list_events("default")
        if e["reason"] == "PolicyConflict"
    ]
    assert len(conflicts) == 1
    assert conflicts[0]["involvedObject"]["name"] == "p"
    assert conflicts[0]["type"] == "Warning"


def test_own_status_patches_do_not_self_wake():
    """The controller's status writes echo back as MODIFIED watch
    events with an unchanged generation; waking on them would re-scan
    after every scan that wrote status."""
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    scans = []

    class Counting(PolicyController):
        def scan_once(self, wait_rollout=True):
            scans.append(time.monotonic())
            return super().scan_once(wait_rollout=wait_rollout)

    c = Counting(kube, interval_s=3600, poll_s=0.02)
    # no coalescing gap: every wake becomes a scan immediately, so the
    # stability windows below observe wakes directly (the gap would
    # defer a pending startup wake past them and read as a self-wake)
    c.min_scan_gap_s = 0.0
    kube.add_custom(G, P, make_policy("p"))
    t = threading.Thread(target=c.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not scans:
            time.sleep(0.05)
        assert scans, "no initial scan"
        # let startup scans (incl. the reconnect gap-cover wake)
        # stabilize, then prove the steady state is quiet: each scan
        # published status (a MODIFIED event), and waking on those
        # would produce an unending scan->patch->wake loop
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            n = len(scans)
            time.sleep(1.0)
            if len(scans) == n:
                break
        stable = len(scans)
        time.sleep(1.0)
        assert len(scans) == stable, (
            f"{len(scans) - stable} extra scans: status patches "
            "self-woke the loop"
        )
        # a real spec change still wakes it
        kube.patch_cluster_custom(G, V, P, "p", {"spec": {"paused": True}})
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(scans) < 2:
            time.sleep(0.05)
        assert len(scans) >= 2
    finally:
        c.stop()
        t.join(timeout=10)


def test_watch_outage_gap_is_covered_by_a_scan():
    """Events during a watch outage are not replayed by a from-scratch
    reconnect; the restart must wake one scan so a policy created in
    the gap doesn't wait out a long interval."""
    fail = {"n": 1}

    class FlakyWatchKube(FakeKube):
        def watch_cluster_custom(self, *a, **k):
            if fail["n"] > 0:
                fail["n"] -= 1
                raise ApiException(500, "watch transport lost")
            return super().watch_cluster_custom(*a, **k)

    kube = FlakyWatchKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    c = controller(kube, interval_s=3600)
    # the policy is created while the watch is down (before run starts
    # its first successful watch): only the restart-wake can see it
    # before the hour is up... but the first scan at startup would too,
    # so create it after the first scan. Easiest deterministic order:
    # let the first watch attempt fail, then create the policy in the
    # 5s retry window.
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    t = threading.Thread(target=c.run, daemon=True)
    t.start()
    try:
        time.sleep(0.3)  # first scan (no policies) done; watch failed
        kube.add_custom(G, P, make_policy(
            "gap", strategy={"groupTimeoutSeconds": 10},
        ))
        deadline = time.monotonic() + 15
        phase = None
        while time.monotonic() < deadline:
            try:
                phase = kube.get_cluster_custom(
                    G, V, P, "gap"
                ).get("status", {}).get("phase")
            except ApiException:
                phase = None
            if phase == "Converged":
                break
            time.sleep(0.1)
        assert phase == "Converged"
    finally:
        agents.stop.set()
        agents.join(timeout=2)
        c.stop()
        t.join(timeout=10)


def test_custom_resources_over_the_wire():
    store = FakeKube()
    store.add_custom(G, P, make_policy("wire-pol"))
    with FakeApiServer(store) as srv:
        client = HttpKubeClient(
            KubeConfig("127.0.0.1", srv.port, use_tls=False)
        )
        objs = client.list_cluster_custom(G, V, P)
        assert [o["metadata"]["name"] for o in objs] == ["wire-pol"]

        got = client.get_cluster_custom(G, V, P, "wire-pol")
        assert got["spec"]["mode"] == "on"

        client.patch_cluster_custom(
            G, V, P, "wire-pol", {"status": {"phase": "Pending"}},
            subresource="status",
        )
        assert store.get_cluster_custom(
            G, V, P, "wire-pol"
        )["status"]["phase"] == "Pending"

        with pytest.raises(ApiException) as ei:
            client.get_cluster_custom(G, V, P, "absent")
        assert ei.value.status == 404


# ---------------------------------------------------------------------------
# controller: phase derivation (no rollout needed)
# ---------------------------------------------------------------------------

def test_converged_policy_reports_converged():
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.add_custom(G, P, make_policy("p"))
    report = controller(kube).scan_once()
    st = report["policies"]["p"]
    assert st["phase"] == "Converged"
    assert (st["nodes"], st["converged"], st["divergent"]) == (1, 1, 0)
    # status published to the CR
    assert kube.get_cluster_custom(G, V, P, "p")["status"]["phase"] == \
        "Converged"


def test_invalid_policy_is_reported_not_crashed():
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.add_custom(G, P, make_policy("bad", mode="bogus"))
    kube.add_custom(G, P, make_policy("good"))
    report = controller(kube).scan_once()
    assert report["policies"]["bad"]["phase"] == "Invalid"
    assert "invalid CC mode" in report["policies"]["bad"]["message"]
    # the good policy still reconciled
    assert report["policies"]["good"]["phase"] == "Converged"


def test_paused_policy_patches_nothing():
    kube = FakeKube()
    kube.add_node(_node("n1", desired="off", state="off"))
    kube.add_custom(G, P, make_policy("p", paused=True))
    st = controller(kube).scan_once()["policies"]["p"]
    assert st["phase"] == "Paused"
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[L.CC_MODE_LABEL] == "off"  # untouched


def test_empty_selector_is_pending_not_degraded():
    kube = FakeKube()
    kube.add_custom(G, P, make_policy("p", selector="no-such-label"))
    st = controller(kube).scan_once()["policies"]["p"]
    assert st["phase"] == "Pending"
    assert "no nodes match" in st["message"]


def test_failed_node_reports_degraded():
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="failed"))
    kube.add_custom(G, P, make_policy("p"))
    st = controller(kube).scan_once()["policies"]["p"]
    assert st["phase"] == "Degraded"
    assert st["failed"] == 1


def test_overlapping_policies_conflict_name_order():
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    # both select the same node; 'alpha' wins by name order
    kube.add_custom(G, P, make_policy("beta", mode="off"))
    kube.add_custom(G, P, make_policy("alpha", mode="on"))
    report = controller(kube).scan_once()
    assert report["policies"]["alpha"]["phase"] == "Converged"
    st = report["policies"]["beta"]
    assert st["phase"] == "Conflicted"
    assert "n1" in st["message"]
    # the conflicted policy patched nothing
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[L.CC_MODE_LABEL] == "on"


def test_status_conditions_follow_k8s_conventions():
    """`kubectl wait --for=condition=Converged tpuccpolicy/x` relies on
    a conventional conditions array whose lastTransitionTime only moves
    on an actual status flip."""
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.add_custom(G, P, make_policy("p"))
    c = controller(kube)
    c.scan_once()
    conds = {
        cd["type"]: cd
        for cd in kube.get_cluster_custom(G, V, P, "p")["status"][
            "conditions"]
    }
    assert conds["Converged"]["status"] == "True"
    assert conds["Healthy"]["status"] == "True"
    assert conds["Converged"]["reason"] == "Converged"
    t0 = conds["Converged"]["lastTransitionTime"]

    c.scan_once()  # steady state: no flip, no time movement, no write
    conds2 = {
        cd["type"]: cd
        for cd in kube.get_cluster_custom(G, V, P, "p")["status"][
            "conditions"]
    }
    assert conds2["Converged"]["lastTransitionTime"] == t0

    # pause: Converged flips False (phase Paused), Healthy stays True
    kube.patch_cluster_custom(G, V, P, "p", {"spec": {"paused": True}})
    c.scan_once()
    conds3 = {
        cd["type"]: cd
        for cd in kube.get_cluster_custom(G, V, P, "p")["status"][
            "conditions"]
    }
    assert conds3["Converged"]["status"] == "False"
    assert conds3["Converged"]["reason"] == "Paused"
    assert conds3["Healthy"]["status"] == "True"
    assert conds3["Healthy"]["lastTransitionTime"] == \
        conds["Healthy"]["lastTransitionTime"]


def test_observed_generation_tracks_spec_changes():
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.add_custom(G, P, make_policy("p"))
    c = controller(kube)
    c.scan_once()
    assert kube.get_cluster_custom(
        G, V, P, "p"
    )["status"]["observedGeneration"] == 1
    kube.patch_cluster_custom(G, V, P, "p", {"spec": {"paused": True}})
    c.scan_once()
    got = kube.get_cluster_custom(G, V, P, "p")
    assert got["metadata"]["generation"] == 2
    assert got["status"]["observedGeneration"] == 2
    assert got["status"]["phase"] == "Paused"


# ---------------------------------------------------------------------------
# controller: driving rollouts
# ---------------------------------------------------------------------------

def test_divergent_pool_converges_via_rollout():
    kube = FakeKube()
    for i in range(3):
        kube.add_node(_node(f"n{i}", desired="off", state="off"))
    kube.add_custom(G, P, make_policy(
        "p", strategy={"maxUnavailable": 2, "groupTimeoutSeconds": 10},
    ))
    agents = _ReactiveAgents(kube, [f"n{i}" for i in range(3)])
    agents.start()
    try:
        st = controller(kube).scan_once()["policies"]["p"]
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert st["phase"] == "Converged"
    assert st["lastRollout"]["ok"] is True
    assert len(st["lastRollout"]["succeeded"]) == 3
    for i in range(3):
        labels = kube.get_node(f"n{i}")["metadata"]["labels"]
        assert labels[L.CC_MODE_LABEL] == "on"
        assert labels[L.CC_MODE_STATE_LABEL] == "on"
    # published status matches
    assert kube.get_cluster_custom(
        G, V, P, "p"
    )["status"]["phase"] == "Converged"


def test_rollout_failure_degrades_policy_and_is_retried_next_tick():
    kube = FakeKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    kube.add_node(_node("n1", desired="off", state="off"))
    kube.add_custom(G, P, make_policy(
        "p", strategy={"groupTimeoutSeconds": 5},
    ))
    agents = _ReactiveAgents(kube, ["n0", "n1"], fail_nodes={"n1"})
    agents.start()
    c = controller(kube)
    try:
        st = c.scan_once()["policies"]["p"]
        assert st["phase"] == "Degraded"
        assert st["lastRollout"]["ok"] is False
        # level-triggered: the next tick sees the failed node and the
        # preflight refusal, stays Degraded, crashes nothing
        st2 = c.scan_once()["policies"]["p"]
        assert st2["phase"] == "Degraded"
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def _two_disjoint_pools(kube):
    kube.add_node(_node("a1", desired="off", state="off",
                        extra={"pool": "a"}))
    kube.add_node(_node("b1", desired="off", state="off",
                        extra={"pool": "b"}))
    kube.add_custom(G, P, make_policy(
        "pol-a", selector="pool=a",
        strategy={"groupTimeoutSeconds": 10},
    ))
    kube.add_custom(G, P, make_policy(
        "pol-b", selector="pool=b",
        strategy={"groupTimeoutSeconds": 10},
    ))


def test_disjoint_pools_roll_concurrently_in_one_tick():
    """Two policies over DISJOINT pools both converge in a single tick
    (VERDICT r4 weak #1: the old single slot serialized independent
    pools — 10 policies x a multi-minute drain was hours of avoidable
    queueing)."""
    kube = FakeKube()
    _two_disjoint_pools(kube)
    agents = _ReactiveAgents(kube, ["a1", "b1"])
    agents.start()
    c = controller(kube)
    try:
        report = c.scan_once()
        assert report["policies"]["pol-a"]["phase"] == "Converged"
        assert report["policies"]["pol-b"]["phase"] == "Converged"
        assert report.get("rolling") == ["pol-a", "pol-b"]
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def test_max_rollouts_1_serializes_in_deterministic_order():
    """TPU_CC_MAX_ROLLOUTS=1 restores strict serialization: name order
    picks pol-a first; pol-b queues with a slots-busy message and
    converges next tick."""
    kube = FakeKube()
    _two_disjoint_pools(kube)
    agents = _ReactiveAgents(kube, ["a1", "b1"])
    agents.start()
    c = controller(kube, max_rollouts=1)
    try:
        report = c.scan_once()
        # name order: pol-a rolled this tick, pol-b queued
        assert report["policies"]["pol-a"]["phase"] == "Converged"
        assert report["policies"]["pol-b"]["phase"] == "Pending"
        assert "queued" in report["policies"]["pol-b"]["message"]
        report2 = c.scan_once()
        assert report2["policies"]["pol-b"]["phase"] == "Converged"
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def test_controller_adopts_unfinished_rollout_record():
    """Crash-safety: an unfinished rollout record on the pool (a crashed
    controller or operator run) is resumed before anything new starts."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    kube.add_node(_node("n1", desired="on", state="off"))
    # a crashed rollout: n1's label was already patched (in_flight),
    # n0 still pending
    record = {
        "id": "deadbeef", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        "groups": {
            "node/n1": {"nodes": ["n1"], "outcome": "in_flight"},
            "node/n0": {"nodes": ["n0"], "outcome": "pending"},
        },
    }
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    kube.add_custom(G, P, make_policy(
        "p", strategy={"groupTimeoutSeconds": 10},
    ))
    agents = _ReactiveAgents(kube, ["n0", "n1"])
    agents.start()
    c = controller(kube, adopt_after_s=0)
    try:
        c.scan_once()  # tick 1: observes the (static) heartbeat
        c.scan_once()  # tick 2: adopts + finishes the crashed rollout
        rec = json.loads(
            kube.get_node("n0")["metadata"]["annotations"][
                L.ROLLOUT_ANNOTATION
            ]
        )
        assert rec["complete"] is True
        assert rec["groups"]["node/n1"]["outcome"] == "succeeded"
        assert rec["groups"]["node/n0"]["outcome"] == "succeeded"
        st = c.scan_once()["policies"]["p"]  # tick 2: level-triggered
        assert st["phase"] == "Converged"
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def test_paused_policy_holds_adoption_of_unfinished_rollout():
    """spec.paused is an emergency brake: it must freeze even the
    crash-recovery resume path for the policy's nodes."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    record = {
        "id": "cafe01", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        "groups": {"node/n0": {"nodes": ["n0"], "outcome": "in_flight"}},
    }
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    kube.add_custom(G, P, make_policy("p", paused=True))
    c = controller(kube, adopt_after_s=0)
    c.scan_once()  # tick 1 only observes the heartbeat
    st = c.scan_once()["policies"]["p"]  # tick 2: staleness ripened
    assert st["phase"] == "Paused"
    assert "held by pause" in st["message"]
    # nothing resumed: the record is still incomplete, desired untouched
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert rec["complete"] is False
    assert kube.get_node("n0")["metadata"]["labels"][L.CC_MODE_LABEL] == "off"

    # unpausing releases the brake: adoption resumes the record
    kube.patch_cluster_custom(G, V, P, "p", {"spec": {"paused": False}})
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    try:
        c.scan_once()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert rec["complete"] is True
    assert rec["groups"]["node/n0"]["outcome"] == "succeeded"


def test_list_failure_of_earlier_policy_holds_later_rollouts():
    """A transient node-list failure for a name-ordered-earlier policy
    must not hand its nodes to a later overlapping policy for the tick —
    that would flip-flop the pool on every API blip."""
    fail = {"on": True}

    class FlakyKube(FakeKube):
        def list_nodes(self, selector=None):
            # only 'alpha's selector fails; 'beta' (the overlap) lists fine
            if fail["on"] and selector == "pool=shared":
                raise ApiException(500, "transient")
            return super().list_nodes(selector)

    kube = FlakyKube()
    kube.add_node(_node("n1", desired="on", state="on",
                        extra={"pool": "shared"}))
    kube.add_custom(G, P, make_policy("alpha", mode="on",
                                      selector="pool=shared"))
    kube.add_custom(G, P, make_policy(
        "beta", mode="off", selector=L.TPU_ACCELERATOR_LABEL,
        strategy={"groupTimeoutSeconds": 5},
    ))
    c = controller(kube)
    report = c.scan_once()
    assert report["policies"]["alpha"]["phase"] == "Degraded"
    assert report["policies"]["beta"]["phase"] == "Pending"
    assert "holding" in report["policies"]["beta"]["message"]
    # beta patched nothing: n1 still at alpha's mode
    assert kube.get_node("n1")["metadata"]["labels"][L.CC_MODE_LABEL] == "on"

    # once alpha lists again, the overlap is visible as a plain conflict
    fail["on"] = False
    report = c.scan_once()
    assert report["policies"]["alpha"]["phase"] == "Converged"
    assert report["policies"]["beta"]["phase"] == "Conflicted"


def test_steady_state_emits_no_status_patches():
    """A converged fleet must not generate a status PATCH per policy per
    tick forever (etcd write + watch churn for zero information)."""
    patches = []

    class CountingKube(FakeKube):
        def patch_cluster_custom(self, *a, **k):
            if k.get("subresource") == "status":
                patches.append(a[3])
            return super().patch_cluster_custom(*a, **k)

    kube = CountingKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.add_custom(G, P, make_policy("p"))
    c = controller(kube)
    c.scan_once()
    assert patches == ["p"]  # first publication
    c.scan_once()
    c.scan_once()
    assert patches == ["p"]  # steady state: no further writes
    # a real change writes again
    kube.patch_cluster_custom(G, V, P, "p", {"spec": {"paused": True}})
    c.scan_once()
    assert patches == ["p", "p"]


def test_moving_heartbeat_is_never_adopted_static_one_is():
    """Liveness is judged by OBSERVATION on the controller's own clock
    (a wall-clock comparison would break under cross-host clock skew):
    a record whose heartbeat keeps changing is someone else's live
    rollout and must be left alone; once the heartbeat stops moving for
    the observation window, the record is abandoned and gets adopted."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="on", state="off"))
    record = {
        "id": "live01", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        # deliberately ANCIENT wall-clock stamp: a skewed writer's clock
        # must not matter — only whether the value moves
        "heartbeat": time.time() - 7200,
        "groups": {"node/n0": {"nodes": ["n0"], "outcome": "in_flight"}},
    }

    def write(rec):
        kube.set_node_annotations(
            "n0", {L.ROLLOUT_ANNOTATION: json.dumps(rec)}
        )

    write(record)
    kube.add_custom(G, P, make_policy("p"))
    c = controller(kube, adopt_after_s=0.2)
    st = c.scan_once()["policies"]["p"]  # first sighting: observe only
    assert st["phase"] == "Pending"  # not Degraded: nothing went wrong
    # the (skewed-clock) owner stamps again: value moved -> still live
    record["heartbeat"] += 5
    write(record)
    time.sleep(0.25)
    c.scan_once()
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert rec["complete"] is False  # untouched: owner still driving
    # owner dies: value sits still past the window -> adopted
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    try:
        c.scan_once()          # re-observes the now-static value
        time.sleep(0.25)
        c.scan_once()          # ripened: adopts and finishes
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert rec["complete"] is True


def test_rollout_run_stamps_heartbeat_and_owner():
    from tpu_cc_manager.rollout import Rollout

    kube = FakeKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    try:
        report = Rollout(kube, "on", poll_s=0.02,
                         group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.ok
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert isinstance(rec["heartbeat"], float)
    assert rec["owner"]


def test_persist_fences_foreign_owner():
    """Fencing: once another process claims the record, this writer's
    very next persist must raise instead of clobbering the adopter's
    state — the revived-original-owner half of the takeover story."""
    from tpu_cc_manager.rollout import OwnershipLostError, Rollout

    kube = FakeKube()
    kube.add_node(_node("n0"))
    taken = {
        "id": "q1", "complete": False, "owner": "adopter-b",
        "groups": {},
    }
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(taken)}
    )
    r = Rollout(kube, "on")
    r._record = {"id": "q1", "complete": False, "groups": {}}
    r._record_node = "n0"
    with pytest.raises(OwnershipLostError, match="taken over"):
        r._persist()
    # the adopter's record was NOT overwritten
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert rec["owner"] == "adopter-b"


def test_persist_fences_foreign_unfinished_record_id():
    """A wedged writer must also stop when the anchor carries a
    DIFFERENT unfinished record (its own rollout was adopted, finished,
    and a newer one launched) — clobbering the newer record would mask
    it from every resume/concurrency guard. A COMPLETE foreign record
    is history and may be overwritten."""
    from tpu_cc_manager.rollout import OwnershipLostError, Rollout

    kube = FakeKube()
    kube.add_node(_node("n0"))
    newer = {"id": "q9", "complete": False, "owner": "c", "groups": {}}
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(newer)}
    )
    r = Rollout(kube, "on")
    r._record = {"id": "q2-old", "complete": False, "groups": {}}
    r._record_node = "n0"
    with pytest.raises(OwnershipLostError, match="stale"):
        r._persist()
    # ...but overwriting a COMPLETE old record is the normal new-rollout
    # path
    kube.set_node_annotations("n0", {L.ROLLOUT_ANNOTATION: json.dumps(
        {"id": "done", "complete": True, "groups": {}}
    )})
    r._persist()  # no raise
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert rec["id"] == "q2-old"


def test_revived_owner_stops_after_adoption():
    """End-to-end takeover: an adopter resumes a stale record (seizing
    ownership); when the original owner's process comes back and tries
    to persist, it stops with OwnershipLostError rather than judging
    groups alongside the adopter."""
    from tpu_cc_manager.rollout import OwnershipLostError, Rollout

    kube = FakeKube()
    kube.add_node(_node("n0", desired="on", state="off"))
    crashed = {
        "id": "q2", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        "owner": "original-a",
        "groups": {"node/n0": {"nodes": ["n0"], "outcome": "in_flight"}},
    }
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(crashed)}
    )
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    try:
        assert Rollout.resume(kube, poll_s=0.02,
                              group_timeout_s=10).run().ok
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    # the original owner revives with its private (stale) copy
    orig = Rollout(kube, "on")
    orig._owner = "original-a"
    orig._record = dict(crashed)
    orig._record_node = "n0"
    with pytest.raises(OwnershipLostError):
        orig._persist()


def test_manual_resume_outranks_heartbeat():
    """`rollout --resume` is a human asserting the old run is dead —
    it must work even against a fresh heartbeat (e.g. a wedged process
    still stamping), unlike automatic adoption."""
    from tpu_cc_manager.rollout import Rollout

    kube = FakeKube()
    kube.add_node(_node("n0", desired="on", state="off"))
    record = {
        "id": "wedge1", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        "heartbeat": time.time(),
        "groups": {"node/n0": {"nodes": ["n0"], "outcome": "in_flight"}},
    }
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    try:
        report = Rollout.resume(kube, poll_s=0.02,
                                group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.ok


def test_claims_incomplete_holds_adoption_too():
    """When a policy's node list fails, pause coverage is unknown —
    adoption of an unfinished rollout must hold along with new rollouts,
    or the paused policy's brake could be bypassed for the tick."""
    fail = {"on": True}

    class FlakyKube(FakeKube):
        def list_nodes(self, selector=None):
            if fail["on"] and selector == "pool=paused":
                raise ApiException(500, "transient")
            return super().list_nodes(selector)

    kube = FlakyKube()
    kube.add_node(_node("n0", desired="off", state="off",
                        extra={"pool": "paused"}))
    record = {
        "id": "feed02", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        "groups": {"node/n0": {"nodes": ["n0"], "outcome": "in_flight"}},
    }
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    # 'aaa' (paused, owns n0 via pool=paused) lists first but fails;
    # 'zzz' (broad selector) still sees n0
    kube.add_custom(G, P, make_policy("aaa", paused=True,
                                      selector="pool=paused"))
    kube.add_custom(G, P, make_policy("zzz"))
    c = controller(kube, adopt_after_s=0)
    c.scan_once()  # observe heartbeat
    c.scan_once()  # ripened: the claims_incomplete hold is now the gate
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert rec["complete"] is False  # nothing resumed blind
    # once the list recovers, the pause brake itself holds the record
    fail["on"] = False
    c.scan_once()
    c.scan_once()
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][L.ROLLOUT_ANNOTATION]
    )
    assert rec["complete"] is False


def test_recreated_policy_gets_status_written_again():
    """The no-op-patch suppression must baseline on the LIVE object's
    status: a deleted-and-recreated policy arrives status-less and needs
    its first write even if the derived status is identical."""
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.add_custom(G, P, make_policy("p"))
    c = controller(kube)
    c.scan_once()
    assert kube.get_cluster_custom(G, V, P, "p")["status"]["phase"] == \
        "Converged"
    # delete + recreate (same name/spec, no status)
    with kube._lock:
        del kube._customs[(G, P, "p")]
    kube.add_custom(G, P, make_policy("p"))
    c.scan_once()
    assert kube.get_cluster_custom(G, V, P, "p")["status"]["phase"] == \
        "Converged"


def test_busy_port_raises_oserror_not_hang():
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    port = sock.getsockname()[1]
    try:
        c = PolicyController(FakeKube(), port=port)
        with pytest.raises(OSError):
            c.run()
    finally:
        sock.close()


# ---------------------------------------------------------------------------
# controller: service surface
# ---------------------------------------------------------------------------

def test_http_surface_and_metrics():
    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.add_custom(G, P, make_policy("p"))
    c = controller(kube, port=0)
    c._server.start()
    try:
        base = f"http://127.0.0.1:{c.port}"
        # before any scan: /report 503, /healthz ok
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/report")
        assert ei.value.code == 503
        assert urllib.request.urlopen(f"{base}/healthz").status == 200

        c.scan_once()
        body = json.loads(urllib.request.urlopen(f"{base}/report").read())
        assert body["policies"]["p"]["phase"] == "Converged"
        metrics = urllib.request.urlopen(
            f"{base}/metrics"
        ).read().decode()
        assert 'tpu_cc_policy_phase{phase="Converged"} 1' in metrics
        assert "tpu_cc_policy_count 1" in metrics
    finally:
        c.stop()


def test_full_stack_policy_to_scheduler(tmp_path):
    """The whole round-3 chain in one scenario: a declarative policy
    drives a slice-aware rollout, REAL agents (full reconcile path,
    fake device backend) converge and publish evidence, the evidence
    audit comes back clean, and the admission webhook then steers a
    confidential pod onto exactly the converged nodes."""
    from test_multinode import SimNode, _wait

    from tpu_cc_manager.evidence import audit_evidence
    from tpu_cc_manager.webhook import mutate_pod, validate_pod
    from tpu_cc_manager.k8s.objects import match_selector

    kube = FakeKube()
    sims = [
        SimNode(kube, "s1-a", tmp_path, slice_id="s1"),
        SimNode(kube, "s1-b", tmp_path, slice_id="s1"),
        SimNode(kube, "solo-1", tmp_path),
    ]
    for s in sims:
        s.start()
    try:
        # agents settle at the default mode first
        assert _wait(lambda: all(
            kube.get_node(n)["metadata"]["labels"].get(
                L.CC_MODE_STATE_LABEL) == "off"
            for n in ("s1-a", "s1-b", "solo-1")
        ))
        kube.add_custom(G, P, make_policy(
            "prod", strategy={"groupTimeoutSeconds": 30},
        ))
        st = controller(kube).scan_once()["policies"]["prod"]
        assert st["phase"] == "Converged"
        # slice group + singleton both rolled
        assert sorted(st["lastRollout"]["succeeded"]) == [
            "node/solo-1", "slice/s1",
        ]
        nodes = kube.list_nodes(None)
        for n in nodes:
            assert n["metadata"]["labels"][L.CC_MODE_STATE_LABEL] == "on"
        # evidence audit: every node's label claim is evidence-backed
        audit = audit_evidence(nodes)
        # every bucket empty, whatever buckets the audit grows
        assert {k: v for k, v in audit.items() if v} == {}
        # admission: a confidential pod gets steered onto these nodes
        pod = {
            "metadata": {"name": "train",
                         "labels": {L.REQUIRES_CC_LABEL: "on"}},
            "spec": {},
        }
        ok, _ = validate_pod(pod)
        assert ok
        ops = mutate_pod(pod)
        sel = {}
        for op in ops:
            if op["path"].endswith("cc.mode.state"):
                sel[L.CC_MODE_STATE_LABEL] = op["value"]
        selector_str = ",".join(f"{k}={v}" for k, v in sel.items())
        schedulable = [
            n["metadata"]["name"] for n in nodes
            if match_selector(n["metadata"]["labels"], selector_str)
        ]
        assert sorted(schedulable) == ["s1-a", "s1-b", "solo-1"]
    finally:
        for s in sims:
            s.stop()


def test_missing_crd_is_a_deployment_race_not_a_crash():
    """The controller Deployment may win the apply race against the
    CRD: a 404 on the policy list must keep the controller healthy and
    retrying, not crash-loop it; once the CRD (and a policy) appear,
    reconciliation starts."""
    crd = {"installed": False}

    class RacingKube(FakeKube):
        def list_cluster_custom(self, *a, **k):
            if not crd["installed"]:
                raise ApiException(404, "the server could not find the "
                                        "requested resource")
            return super().list_cluster_custom(*a, **k)

    kube = RacingKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    c = controller(kube)
    for _ in range(3):
        report = c.scan_once()
        assert report == {
            "policies": {}, "claimed_nodes": 0, "scanned": 0,
            "crd_missing": True, "unhealthy_policies": [],
        }
    assert c.healthy and c.consecutive_errors == 0
    crd["installed"] = True
    kube.add_custom(G, P, make_policy("p"))
    assert c.scan_once()["policies"]["p"]["phase"] == "Converged"


def test_missing_crd_does_not_busy_scan_but_recovers_promptly():
    """With the CRD absent, the watch layer probes quietly — no
    gap-scan wakes per retry (that would be a scan loop at backoff
    cadence). But the MOMENT the CRD appears, the probe's success must
    wake a scan: a policy created before the watch establishes would
    otherwise wait out watch_timeout_s/interval_s."""
    scans = []
    crd = {"installed": False}

    class RacingKube(FakeKube):
        def list_cluster_custom(self, *a, **k):
            if not crd["installed"]:
                raise ApiException(404, "not found")
            return super().list_cluster_custom(*a, **k)

        def watch_cluster_custom(self, *a, **k):
            if not crd["installed"]:
                raise ApiException(404, "not found")
            return super().watch_cluster_custom(*a, **k)

    class Counting(PolicyController):
        def scan_once(self, wait_rollout=True):
            scans.append(time.monotonic())
            return super().scan_once(wait_rollout=wait_rollout)

    kube = RacingKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    c = Counting(kube, interval_s=3600, poll_s=0.02)
    c.watch_backoff_s = 0.05
    c.watch_timeout_s = 300  # deliberately long: only the probe wake
    t = threading.Thread(target=c.run, daemon=True)
    t.start()
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    try:
        time.sleep(1.5)
        # ~30 watch retries happened; scans must stay at startup count
        # (1 initial + at most 1 from the startup gap-wake race)
        assert len(scans) <= 2, (
            f"{len(scans)} scans in 1.5s: 404 retries are waking the "
            "scan loop"
        )
        # CRD + policy land while the watch is still down: the probe's
        # first success must wake the scan that reconciles it
        crd["installed"] = True
        kube.add_custom(G, P, make_policy(
            "late", strategy={"groupTimeoutSeconds": 10},
        ))
        deadline = time.monotonic() + 10
        phase = None
        while time.monotonic() < deadline:
            try:
                phase = kube.get_cluster_custom(
                    G, V, P, "late"
                ).get("status", {}).get("phase")
            except ApiException:
                phase = None
            if phase == "Converged":
                break
            time.sleep(0.05)
        assert phase == "Converged", (
            "policy created during the CRD-install window was not "
            "reconciled promptly"
        )
    finally:
        agents.stop.set()
        agents.join(timeout=2)
        c.stop()
        t.join(timeout=10)


def test_scan_failure_degrades_healthz():
    class BrokenKube(FakeKube):
        def list_cluster_custom(self, *a, **k):
            raise ApiException(500, "boom")

    c = controller(BrokenKube(), max_consecutive_errors=2)
    for _ in range(2):
        with pytest.raises(ApiException):
            c.scan_once()
    assert not c.healthy


def test_interval_validation():
    with pytest.raises(ValueError, match="interval"):
        PolicyController(FakeKube(), interval_s=0)


def test_cli_policy_controller_once(monkeypatch, capsys):
    """--once: one pass, report on stdout, exit code reflects policy
    health (cron/CI usage)."""
    from tpu_cc_manager import __main__ as cli

    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.add_custom(G, P, make_policy("healthy"))
    monkeypatch.setattr(cli, "_kube_client", lambda cfg: kube)
    rc = cli.main(["policy-controller", "--once"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["policies"]["healthy"]["phase"] == "Converged"

    kube.add_custom(G, P, make_policy("broken", mode="bogus"))
    rc = cli.main(["policy-controller", "--once"])
    assert rc == 1


def test_cli_once_fails_when_crd_missing(monkeypatch, capsys):
    """A one-shot has no next tick: exiting green against a cluster
    where the CRD is absent would lie to the pipeline."""
    from tpu_cc_manager import __main__ as cli

    class NoCrdKube(FakeKube):
        def list_cluster_custom(self, *a, **k):
            raise ApiException(404, "not found")

    monkeypatch.setattr(cli, "_kube_client", lambda cfg: NoCrdKube())
    rc = cli.main(["policy-controller", "--once"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["crd_missing"] is True


# ---------------------------------------------------------------------------
# fairness + non-blocking scans (VERDICT r3 weak #2/#3)
# ---------------------------------------------------------------------------

def test_starving_policy_cannot_block_others():
    """Policy 'aaa' (first in name order) owns a pool that never
    converges; 'bbb' owns a healthy pool. Backoff + round-robin must
    give 'bbb' the slot within a couple of ticks — name order alone
    must not starve it."""
    kube = FakeKube()
    kube.add_node(_node("dead-1", desired="off", state="off",
                        extra={"pool": "a"}))
    kube.add_node(_node("ok-1", desired="off", state="off",
                        extra={"pool": "b"}))
    kube.add_custom(G, P, make_policy(
        "aaa", selector="pool=a",
        strategy={"groupTimeoutSeconds": 1},
    ))
    kube.add_custom(G, P, make_policy(
        "bbb", selector="pool=b",
        strategy={"groupTimeoutSeconds": 30},
    ))
    agents = _ReactiveAgents(kube, ["ok-1"])  # dead-1 has NO agent
    agents.start()
    c = controller(kube, interval_s=0.2)
    try:
        # scan 1: aaa wins the slot, times out (1s), backs off
        st = c.scan_once()["policies"]
        assert st["aaa"]["phase"] == "Degraded"
        # scan 2: aaa is backing off -> bbb converges
        st = c.scan_once()["policies"]
        assert st["bbb"]["phase"] == "Converged", st["bbb"]
        assert "backing off" in st["aaa"]["message"]
    finally:
        agents.stop.set()


def test_round_robin_rotates_launch_slot():
    """With neither policy failing, consecutive ticks alternate which
    actionable policy gets the rollout slot."""
    kube = FakeKube()
    kube.add_node(_node("a-1", desired="off", state="off",
                        extra={"pool": "a"}))
    kube.add_node(_node("b-1", desired="off", state="off",
                        extra={"pool": "b"}))
    kube.add_custom(G, P, make_policy("aaa", selector="pool=a"))
    kube.add_custom(G, P, make_policy("bbb", selector="pool=b"))
    agents = _ReactiveAgents(kube, ["a-1", "b-1"])
    agents.start()
    # rotation is observable only when the slot is scarce
    c = controller(kube, interval_s=0.2, max_rollouts=1)
    try:
        launched = []
        orig = c._drive_rollout

        def recording(pol, spec, st, entry):
            launched.append(pol["metadata"]["name"])
            return orig(pol, spec, st, entry)

        c._drive_rollout = recording
        c.scan_once()
        # both pools now converged; force both divergent again
        kube.set_node_labels("a-1", {L.CC_MODE_STATE_LABEL: "off",
                                     L.CC_MODE_LABEL: "off"})
        kube.set_node_labels("b-1", {L.CC_MODE_STATE_LABEL: "off",
                                     L.CC_MODE_LABEL: "off"})
        c.scan_once()
        assert launched[0] != launched[1], launched
    finally:
        agents.stop.set()


def test_scan_stays_live_during_slow_rollout():
    """wait_rollout=False (the run-loop mode): while one policy's
    rollout drains a dead pool, further scans return promptly, keep the
    rolling policy's live worker status, and keep OTHER policies'
    statuses fresh."""
    kube = FakeKube()
    kube.add_node(_node("dead-1", desired="off", state="off",
                        extra={"pool": "a"}))
    kube.add_node(_node("idle-1", desired="on", state="on",
                        extra={"pool": "b"}))
    kube.add_custom(G, P, make_policy(
        "slow", selector="pool=a",
        strategy={"groupTimeoutSeconds": 4},
    ))
    kube.add_custom(G, P, make_policy("fine", selector="pool=b"))
    c = controller(kube, interval_s=0.2)
    try:
        r1 = c.scan_once(wait_rollout=False)
        assert r1["policies"]["slow"]["phase"] == "Rolling"
        # the worker is still draining its 4s group timeout; scans in
        # the meantime are fast and fully-populated
        t0 = time.monotonic()
        r2 = c.scan_once(wait_rollout=False)
        assert time.monotonic() - t0 < 2.0
        assert r2.get("rolling") == ["slow"]
        assert r2["policies"]["slow"]["phase"] == "Rolling"
        assert r2["policies"]["fine"]["phase"] == "Converged"
        # the on-cluster status of 'fine' was refreshed mid-roll
        live = kube.get_cluster_custom(G, V, P, "fine")
        assert live["status"]["phase"] == "Converged"
    finally:
        c._join_workers()


def test_adoption_attributes_progress_to_matching_policy():
    """After a failover (or crash), the adopted rollout is the normal
    continuation of some policy's work: the policy whose spec matches
    the record (selector + mode) shows live adoption progress and the
    final outcome in its status, instead of going dark for the whole
    resume."""
    kube = FakeKube()
    kube.add_node(_node("a0", desired="off", state="off",
                        extra={"pool": "a"}))
    kube.add_node(_node("a1", desired="on", state="off",
                        extra={"pool": "a"}))
    record = {
        "id": "cafe01", "started": time.time(), "mode": "on",
        "selector": "pool=a", "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        "groups": {
            "node/a1": {"nodes": ["a1"], "outcome": "in_flight"},
            "node/a0": {"nodes": ["a0"], "outcome": "pending"},
        },
    }
    kube.set_node_annotations(
        "a0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    kube.add_custom(G, P, make_policy(
        "matching", selector="pool=a",
        strategy={"groupTimeoutSeconds": 10},
    ))
    kube.add_custom(G, P, make_policy("other", selector="pool=b"))

    seen_messages = []
    agents = _ReactiveAgents(kube, ["a0", "a1"])
    agents.start()
    c = controller(kube, adopt_after_s=0)
    orig_patch = c._patch_status

    def recording_patch(pol, st):
        if pol["metadata"]["name"] == "matching":
            seen_messages.append((st["phase"], st["message"]))
        return orig_patch(pol, st)

    c._patch_status = recording_patch
    try:
        c.scan_once()  # observes the static heartbeat
        st = c.scan_once()["policies"]["matching"]  # adopts + finishes
        # the report carries the worker's final status
        assert st["phase"] == "Converged"
        assert "adopted rollout 'cafe01'" in st["message"]
        # mid-roll the policy showed the adoption and per-group progress
        assert any("adopted unfinished rollout 'cafe01'" in m
                   for _, m in seen_messages), seen_messages
        assert any("group(s) done" in m for _, m in seen_messages), \
            seen_messages
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def test_adoption_without_matching_policy_still_resumes():
    """A record no current policy claims (operator-run rollout, or the
    policy was deleted) still resumes; no policy status is touched."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="on", state="off"))
    record = {
        "id": "feed02", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        "groups": {
            "node/n0": {"nodes": ["n0"], "outcome": "in_flight"},
        },
    }
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    # a policy with a DIFFERENT mode: must not claim the adoption
    kube.add_custom(G, P, make_policy("off-policy", mode="off"))
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    c = controller(kube, adopt_after_s=0)
    try:
        c.scan_once()
        c.scan_once()  # adopts
        rec = json.loads(
            kube.get_node("n0")["metadata"]["annotations"][
                L.ROLLOUT_ANNOTATION
            ]
        )
        assert rec["complete"] is True
        live = kube.get_cluster_custom(G, V, P, "off-policy")
        msg = (live.get("status") or {}).get("message", "")
        assert "adopted" not in msg
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def test_adoption_posts_policy_event():
    """`kubectl describe tpuccpolicy` must carry the failover history:
    adopting an unfinished record posts PolicyRolloutAdopted on the
    owning policy."""
    kube = FakeKube()
    kube.add_node(_node("e0", desired="on", state="off"))
    record = {
        "id": "ev123", "started": time.time(), "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "max_unavailable": 1,
        "failure_budget": 0, "complete": False, "aborted": False,
        "groups": {
            "node/e0": {"nodes": ["e0"], "outcome": "in_flight"},
        },
    }
    kube.set_node_annotations(
        "e0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    kube.add_custom(G, P, make_policy("evpol"))
    agents = _ReactiveAgents(kube, ["e0"])
    agents.start()
    c = controller(kube, adopt_after_s=0)
    try:
        c.scan_once()
        c.scan_once()  # adopts
        reasons = [
            (e.get("reason"), e.get("involvedObject", {}).get("name"))
            for e in kube.cluster_events
        ]
        assert ("PolicyRolloutAdopted", "evpol") in reasons, reasons
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def test_node_watch_refreshes_status_between_intervals():
    """An agent converging OUT-OF-BAND (drift heal, operator fix) must
    refresh the policy's converged counts promptly via the NODE watch —
    the interval here is an hour, and a paused policy never rolls, so
    only the node watch can explain a fresh status."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="on", state="off"))
    kube.add_custom(G, P, make_policy("pw", paused=True))
    c = controller(kube, interval_s=3600)
    c.min_scan_gap_s = 0.2

    def status():
        try:
            return kube.get_cluster_custom(G, V, P, "pw").get(
                "status") or {}
        except ApiException:
            return {}

    t = threading.Thread(target=c.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if status().get("nodes") == 1:
                break
            time.sleep(0.05)
        assert status().get("nodes") == 1
        assert status().get("converged") == 0

        kube.set_node_labels("n0", {L.CC_MODE_STATE_LABEL: "on"})
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if status().get("converged") == 1:
                break
            time.sleep(0.1)
        assert status().get("converged") == 1, status()
    finally:
        c.stop()
        t.join(timeout=10)
        assert not t.is_alive()


def test_future_record_version_holds_slot_and_warns():
    """Rolling-upgrade skew: an unfinished record written by a NEWER
    controller (schema version > supported) must not be adopted — its
    shape cannot be parsed safely — but its existence still holds the
    rollout slot so this controller does not start a second rollout
    over the same nodes. Loudness: error-level status message on the
    owning policy, plus ONE Warning PolicyRolloutVersionSkew event."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="on", state="off"))
    record = {
        "version": 99, "id": "futrec", "started": time.time(),
        "mode": "on", "selector": L.TPU_ACCELERATOR_LABEL,
        "complete": False,
        # the evolved shape this controller cannot understand
        "phases": [{"wave": 1, "members": ["n0"], "state": "rolling"}],
    }
    kube.set_node_annotations(
        "n0", {L.ROLLOUT_ANNOTATION: json.dumps(record)}
    )
    kube.add_custom(G, P, make_policy("skewpol"))
    c = controller(kube, adopt_after_s=0)
    r1 = c.scan_once()
    r2 = c.scan_once()  # would adopt were the version supported
    for r in (r1, r2):
        st = r["policies"]["skewpol"]
        assert "version 99" in st["message"], st
        assert "refusing to adopt" in st["message"]
    # slot held: no worker ever launched, no new rollout started
    assert not c._workers
    rec = json.loads(
        kube.get_node("n0")["metadata"]["annotations"][
            L.ROLLOUT_ANNOTATION]
    )
    assert rec == record, "the future record must not be touched"
    skew_events = [e for e in kube.cluster_events
                   if e.get("reason") == "PolicyRolloutVersionSkew"]
    assert len(skew_events) == 1, "event fires once per record"
    assert skew_events[0]["type"] == "Warning"
    assert skew_events[0]["involvedObject"]["name"] == "skewpol"


def test_version_skew_event_waits_for_resolvable_owner():
    """The one-shot PolicyRolloutVersionSkew Warning must not be burned
    while the owning policy is unresolvable (created a tick later, or
    its spec momentarily unparseable): the event fires on the first
    tick the owner resolves."""
    kube = FakeKube()
    kube.add_node(_node("n0", desired="on", state="off"))
    kube.set_node_annotations("n0", {L.ROLLOUT_ANNOTATION: json.dumps({
        "version": 99, "id": "laterec", "started": time.time(),
        "mode": "on", "selector": L.TPU_ACCELERATOR_LABEL,
        "complete": False, "groups": {},
    })})
    c = controller(kube, adopt_after_s=0)
    c.scan_once()  # no policy yet: slot held, no event to attach
    assert not [e for e in kube.cluster_events
                if e.get("reason") == "PolicyRolloutVersionSkew"]
    kube.add_custom(G, P, make_policy("latepol"))
    c.scan_once()
    c.scan_once()
    skew = [e for e in kube.cluster_events
            if e.get("reason") == "PolicyRolloutVersionSkew"]
    assert len(skew) == 1, "fires once, on the first resolvable tick"
    assert skew[0]["involvedObject"]["name"] == "latepol"


def test_parallel_convergence_beats_serialized_wall_clock():
    """The point of concurrent slots: N disjoint pools with slow agents
    converge in ~one pool's time, not N x. Each agent takes ~0.5s per
    node; serialized convergence would be >= 1.0s of agent time alone,
    parallel stays well under it."""
    kube = FakeKube()
    _two_disjoint_pools(kube)
    # one agent thread PER node: the simulated agents respond
    # independently (like real per-node daemonset pods), so any
    # remaining serialization is the controller's
    agents = [_ReactiveAgents(kube, [n], delay_s=0.5)
              for n in ("a1", "b1")]
    for a in agents:
        a.start()
    c = controller(kube)
    try:
        t0 = time.monotonic()
        report = c.scan_once()
        wall = time.monotonic() - t0
        assert report["policies"]["pol-a"]["phase"] == "Converged"
        assert report["policies"]["pol-b"]["phase"] == "Converged"
        assert wall < 1.0, (
            f"parallel convergence took {wall:.2f}s — at least two "
            "0.5s agent delays were serialized"
        )
    finally:
        for a in agents:
            a.stop.set()
            a.join(timeout=2)


def _wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_demotion_stops_all_concurrent_workers():
    """A deposed leader stops EVERY in-flight worker, not just one:
    both records stay adoptable (unfinished, non-aborted) and both
    policies read as handoffs."""
    kube = FakeKube()
    _two_disjoint_pools(kube)  # no agents: both rollouts sit in their
    c = controller(kube)       # group timeouts until stopped
    r = c.scan_once(wait_rollout=False)
    assert r.get("rolling") == ["pol-a", "pol-b"]
    assert _wait_for(lambda: len(c._workers) == 2 and all(
        w.get("rollout") is not None for w in c._workers.values()
    ))
    c._on_demoted()
    assert _wait_for(lambda: not c._workers, timeout=5), \
        "not all workers stopped after demotion"
    from tpu_cc_manager.rollout import load_rollout_records
    records = [r for r, _ in load_rollout_records(
        kube, kube.list_nodes(None))]
    assert len(records) == 2
    for rec in records:
        assert rec["complete"] is False
        assert rec["aborted"] is False


def test_overlapping_record_queues_policy_but_disjoint_rolls():
    """An unfinished record with a LIVE heartbeat (an operator's
    in-flight rollout) blocks only the policies overlapping its nodes;
    a disjoint policy still rolls this tick."""
    kube = FakeKube()
    _two_disjoint_pools(kube)
    # an operator's live rollout over pol-a's node
    kube.set_node_annotations("a1", {L.ROLLOUT_ANNOTATION: json.dumps({
        "version": 1, "id": "oprec", "started": time.time(),
        "mode": "off", "selector": "pool=a",
        "complete": False, "heartbeat": time.time(),
        "groups": {"node/a1": {"nodes": ["a1"], "outcome": "in_flight"}},
    })})
    agents = _ReactiveAgents(kube, ["a1", "b1"])
    agents.start()
    c = controller(kube)  # adopt_after_s default: heartbeat observed
    try:
        report = c.scan_once()
        assert report["policies"]["pol-b"]["phase"] == "Converged"
        sta = report["policies"]["pol-a"]
        assert sta["phase"] == "Pending"
        assert "queued" in sta["message"], sta
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def test_multiple_crashed_records_adopted_concurrently():
    """Two crashed rollouts on disjoint pools are both adopted in the
    same tick (each into its own slot) and both finish."""
    kube = FakeKube()
    _two_disjoint_pools(kube)
    for node, rid in (("a1", "reca"), ("b1", "recb")):
        kube.set_node_labels(node, {L.CC_MODE_LABEL: "on"})
        kube.set_node_annotations(node, {
            L.ROLLOUT_ANNOTATION: json.dumps({
                "version": 1, "id": rid, "started": time.time(),
                "mode": "on",
                "selector": f"pool={node[0]}",
                "max_unavailable": 1, "failure_budget": 0,
                "complete": False, "aborted": False,
                "groups": {f"node/{node}": {
                    "nodes": [node], "outcome": "in_flight"}},
            })})
    agents = _ReactiveAgents(kube, ["a1", "b1"])
    agents.start()
    c = controller(kube, adopt_after_s=0)
    try:
        c.scan_once()  # observe both heartbeats
        report = c.scan_once()  # adopt both
        assert sorted(report.get("rolling") or []) == ["pol-a", "pol-b"]
        for node in ("a1", "b1"):
            rec = json.loads(kube.get_node(node)["metadata"][
                "annotations"][L.ROLLOUT_ANNOTATION])
            assert rec["complete"] is True, rec
            labels = kube.get_node(node)["metadata"]["labels"]
            assert labels[L.CC_MODE_STATE_LABEL] == "on"
    finally:
        agents.stop.set()
        agents.join(timeout=2)


def test_overlapping_unfinished_records_are_never_adopted():
    """Two unfinished records sharing a node (the overlap guard's
    record-write window can produce this): adopting EITHER would put
    two drivers on the shared node, so both are held — and nothing
    new launches on their nodes."""
    kube = FakeKube()
    kube.add_node(_node("s1", desired="on", state="off"))
    kube.add_node(_node("s2", desired="on", state="off"))
    now = time.time()
    kube.set_node_annotations("s1", {L.ROLLOUT_ANNOTATION: json.dumps({
        "version": 1, "id": "older", "started": now - 60, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL,
        "complete": False, "aborted": False,
        "groups": {"node/s1": {"nodes": ["s1"], "outcome": "in_flight"},
                   "node/s2": {"nodes": ["s2"], "outcome": "pending"}},
    })})
    kube.set_node_annotations("s2", {L.ROLLOUT_ANNOTATION: json.dumps({
        "version": 1, "id": "newer", "started": now, "mode": "off",
        "selector": "pool=other",
        "complete": False, "aborted": False,
        "groups": {"node/s2": {"nodes": ["s2"],
                               "outcome": "in_flight"}},
    })})
    kube.add_custom(G, P, make_policy("olpol"))
    c = controller(kube, adopt_after_s=0)
    c.scan_once()  # observe heartbeats (both static -> stale next tick)
    report = c.scan_once()
    assert not c._workers, "overlapped records must not be adopted"
    assert report.get("rolling") is None
    for node, rid in (("s1", "older"), ("s2", "newer")):
        rec = json.loads(kube.get_node(node)["metadata"][
            "annotations"][L.ROLLOUT_ANNOTATION])
        assert rec["id"] == rid and rec["complete"] is False


def test_controller_feeds_its_informer_to_rollouts_and_adoptions(
        monkeypatch):
    """ISSUE 14 wiring pin: the controller's shared informer reaches
    every Rollout it constructs — fresh launches AND adoptions — so
    policy-driven rollouts judge off the delta stream, not interval
    LISTs."""
    import tpu_cc_manager.policy as policy_mod
    from tpu_cc_manager.rollout import Rollout
    from tpu_cc_manager.watch import NodeInformer

    captured = []

    class _SpyRollout(Rollout):
        def __init__(self, *a, **kw):
            captured.append(("fresh", kw.get("informer")))
            super().__init__(*a, **kw)

        @classmethod
        def resume(cls, *a, **kw):
            captured.append(("resume", kw.get("informer")))
            return Rollout.resume(*a, **kw)

    monkeypatch.setattr(policy_mod, "Rollout", _SpyRollout)
    kube = FakeKube()
    kube.add_node(_node("n0", desired="off", state="off"))
    kube.add_custom(G, P, make_policy("p"))
    informer = NodeInformer(kube, name="test-policy")
    informer.prime()
    informer.start()
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    ctrl = PolicyController(kube, interval_s=30, port=0, poll_s=0.02,
                            verify_evidence=False, informer=informer)
    try:
        report = ctrl.scan_once()
    finally:
        agents.stop.set()
        informer.stop()
        ctrl.stop()
    assert report["policies"]["p"]["phase"] == "Converged"
    assert ("fresh", informer) in captured
