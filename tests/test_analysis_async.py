"""ccaudit blocking-in-async rule (ISSUE 13 satellite): blocking call
shapes inside ``async def`` bodies in the async kube core fail lint —
positive/negative/pragma, scoped to the async-core module set."""

from tpu_cc_manager.analysis.core import analyze_source

AIO = "tpu_cc_manager/k8s/aio.py"
BRIDGE = "tpu_cc_manager/k8s/aio_bridge.py"


def _rules(findings):
    return [f.rule for f in findings]


def _async_findings(src, relpath=AIO):
    return [f for f in analyze_source(src, relpath)
            if f.rule == "blocking-in-async"]


def test_time_sleep_in_async_def_flagged():
    src = (
        "import asyncio\n"
        "import time\n"
        "async def pump():\n"
        "    time.sleep(1)\n"
    )
    hits = _async_findings(src)
    assert len(hits) == 1
    assert hits[0].line == 4
    assert "time.sleep" in hits[0].message


def test_sleep_alias_seen_through_import_fold():
    src = (
        "from time import sleep\n"
        "async def pump():\n"
        "    sleep(0.5)\n"
    )
    assert len(_async_findings(src)) == 1


def test_sync_socket_and_http_client_flagged():
    src = (
        "import socket\n"
        "import http.client\n"
        "async def dial():\n"
        "    s = socket.create_connection(('h', 1))\n"
        "    c = http.client.HTTPConnection('h')\n"
    )
    hits = _async_findings(src)
    assert len(hits) == 2


def test_future_result_in_async_def_flagged():
    src = (
        "async def wait(fut):\n"
        "    return fut.result()\n"
    )
    hits = _async_findings(src)
    assert len(hits) == 1
    assert ".result()" in hits[0].message


def test_asyncio_sleep_and_sync_defs_not_flagged():
    src = (
        "import asyncio\n"
        "import time\n"
        "async def pump():\n"
        "    await asyncio.sleep(1)\n"
        "def sync_helper():\n"
        "    time.sleep(1)\n"  # not loop code
    )
    assert _async_findings(src) == []


def test_nested_sync_def_inside_async_not_flagged():
    # a nested sync def is executor-bound (run_in_executor target),
    # not loop code — flagging it would force pragmas on the exact
    # pattern the rule wants to encourage
    src = (
        "import time\n"
        "async def pump(loop):\n"
        "    def blocking():\n"
        "        time.sleep(1)\n"
        "    await loop.run_in_executor(None, blocking)\n"
    )
    assert _async_findings(src) == []


def test_pragma_suppresses_with_reason():
    src = (
        "import time\n"
        "async def pump():\n"
        "    time.sleep(0.001)  # ccaudit: allow-blocking-in-async(sub-ms jitter by design)\n"
    )
    assert _async_findings(src) == []


def test_rule_scoped_to_async_core_modules():
    src = (
        "import time\n"
        "async def pump():\n"
        "    time.sleep(1)\n"
    )
    # same code outside the async-core module set: other rules own it
    assert _async_findings(src, relpath="tpu_cc_manager/agent.py") == []
    # and the bridge module is in scope
    assert len(_async_findings(src, relpath=BRIDGE)) == 1


def test_live_async_core_is_clean():
    # the shipped aio modules must pass their own rule (anything
    # deliberate carries a pragma)
    import os

    from tpu_cc_manager.analysis.core import load_module, repo_root
    from tpu_cc_manager.analysis.rules import blocking_in_async_findings

    root = repo_root()
    mods = []
    for rel in sorted(
        {AIO, BRIDGE} & {
            p for p in (AIO, BRIDGE)
            if os.path.exists(os.path.join(root, p))
        }
    ):
        mod = load_module(root, rel)
        assert mod is not None
        mods.append(mod)
    assert mods
    assert blocking_in_async_findings(mods) == []
