"""ccaudit async rules: the v1 ``blocking-in-async`` lexical rule
(ISSUE 13 satellite) plus the v4 asyncflow families (ISSUE 17) —
await-atomicity, lock-across-await, loop-affinity/loop-self-deadlock,
orphan-task, async-exception. Positive/negative/pragma per family,
severity pins, and live-core cleanliness."""

from tpu_cc_manager.analysis.core import (
    Module,
    analyze_modules,
    analyze_source,
)

AIO = "tpu_cc_manager/k8s/aio.py"
BRIDGE = "tpu_cc_manager/k8s/aio_bridge.py"


def _rules(findings):
    return [f.rule for f in findings]


def _async_findings(src, relpath=AIO):
    return [f for f in analyze_source(src, relpath)
            if f.rule == "blocking-in-async"]


def _v4(src, rule, relpath=AIO):
    return [f for f in analyze_source(src, relpath) if f.rule == rule]


def test_time_sleep_in_async_def_flagged():
    src = (
        "import asyncio\n"
        "import time\n"
        "async def pump():\n"
        "    time.sleep(1)\n"
    )
    hits = _async_findings(src)
    assert len(hits) == 1
    assert hits[0].line == 4
    assert "time.sleep" in hits[0].message


def test_sleep_alias_seen_through_import_fold():
    src = (
        "from time import sleep\n"
        "async def pump():\n"
        "    sleep(0.5)\n"
    )
    assert len(_async_findings(src)) == 1


def test_sync_socket_and_http_client_flagged():
    src = (
        "import socket\n"
        "import http.client\n"
        "async def dial():\n"
        "    s = socket.create_connection(('h', 1))\n"
        "    c = http.client.HTTPConnection('h')\n"
    )
    hits = _async_findings(src)
    assert len(hits) == 2


def test_future_result_in_async_def_flagged():
    src = (
        "async def wait(fut):\n"
        "    return fut.result()\n"
    )
    hits = _async_findings(src)
    assert len(hits) == 1
    assert ".result()" in hits[0].message


def test_asyncio_sleep_and_sync_defs_not_flagged():
    src = (
        "import asyncio\n"
        "import time\n"
        "async def pump():\n"
        "    await asyncio.sleep(1)\n"
        "def sync_helper():\n"
        "    time.sleep(1)\n"  # not loop code
    )
    assert _async_findings(src) == []


def test_nested_sync_def_inside_async_not_flagged():
    # a nested sync def is executor-bound (run_in_executor target),
    # not loop code — flagging it would force pragmas on the exact
    # pattern the rule wants to encourage
    src = (
        "import time\n"
        "async def pump(loop):\n"
        "    def blocking():\n"
        "        time.sleep(1)\n"
        "    await loop.run_in_executor(None, blocking)\n"
    )
    assert _async_findings(src) == []


def test_pragma_suppresses_with_reason():
    src = (
        "import time\n"
        "async def pump():\n"
        "    time.sleep(0.001)  # ccaudit: allow-blocking-in-async(sub-ms jitter by design)\n"
    )
    assert _async_findings(src) == []


def test_rule_scoped_to_async_core_modules():
    src = (
        "import time\n"
        "async def pump():\n"
        "    time.sleep(1)\n"
    )
    # same code outside the async-core module set: other rules own it
    assert _async_findings(src, relpath="tpu_cc_manager/agent.py") == []
    # and the bridge module is in scope
    assert len(_async_findings(src, relpath=BRIDGE)) == 1


def test_live_async_core_is_clean():
    # the shipped aio modules must pass their own rule (anything
    # deliberate carries a pragma)
    import os

    from tpu_cc_manager.analysis.core import load_module, repo_root
    from tpu_cc_manager.analysis.rules import blocking_in_async_findings

    root = repo_root()
    mods = []
    for rel in sorted(
        {AIO, BRIDGE} & {
            p for p in (AIO, BRIDGE)
            if os.path.exists(os.path.join(root, p))
        }
    ):
        mod = load_module(root, rel)
        assert mod is not None
        mods.append(mod)
    assert mods
    assert blocking_in_async_findings(mods) == []


# ===================================================== v4: await-atomicity


def test_await_atomicity_check_then_act_flagged():
    src = (
        "import asyncio\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._ctx = None\n"
        "    async def ensure(self):\n"
        "        if self._ctx is None:\n"
        "            self._ctx = await build()\n"
        "        return self._ctx\n"
    )
    hits = _v4(src, "await-atomicity")
    assert len(hits) == 1
    assert hits[0].line == 7
    assert hits[0].severity == "warning"
    assert "await" in hits[0].message


def test_await_atomicity_guarded_by_asyncio_lock_clean():
    src = (
        "import asyncio\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._ctx = None\n"
        "        self._lk = asyncio.Lock()\n"
        "    async def ensure(self):\n"
        "        async with self._lk:\n"
        "            if self._ctx is None:\n"
        "                self._ctx = await build()\n"
        "        return self._ctx\n"
    )
    assert _v4(src, "await-atomicity") == []


def test_await_atomicity_no_await_between_clean():
    # read and write with the await OUTSIDE the window: plain
    # single-threaded loop code, nothing interleaves mid-sequence
    src = (
        "import asyncio\n"
        "class C:\n"
        "    async def bump(self):\n"
        "        await asyncio.sleep(0)\n"
        "        if self._n is None:\n"
        "            self._n = 1\n"
    )
    assert _v4(src, "await-atomicity") == []


def test_await_atomicity_threading_lock_is_not_a_guard():
    # a threading lock does not exclude coroutines on the same loop —
    # holding it across the await is its own finding, and it must NOT
    # launder the torn check-then-act
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._ctx = None\n"
        "        self._lk = threading.Lock()\n"
        "    async def ensure(self):\n"
        "        with self._lk:\n"
        "            if self._ctx is None:\n"
        "                self._ctx = await build()\n"
    )
    assert len(_v4(src, "await-atomicity")) == 1


def test_await_atomicity_caller_held_async_lock_recognized():
    # the _locked-suffix convention carries to coroutines: the callee's
    # RMW is guarded because EVERY resolved caller holds the asyncio
    # lock across the call (lockset.caller_held_locks fixpoint)
    src = (
        "import asyncio\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._ctx = None\n"
        "        self._lk = asyncio.Lock()\n"
        "    async def ensure(self):\n"
        "        async with self._lk:\n"
        "            await self._fill_locked()\n"
        "    async def _fill_locked(self):\n"
        "        if self._ctx is None:\n"
        "            self._ctx = await build()\n"
    )
    assert _v4(src, "await-atomicity") == []


def test_await_atomicity_module_global_flagged():
    src = (
        "import asyncio\n"
        "_cache = {}\n"
        "async def put(k, v):\n"
        "    global _cache\n"
        "    if _cache:\n"
        "        await asyncio.sleep(0)\n"
        "        _cache = v\n"
    )
    assert len(_v4(src, "await-atomicity")) == 1


def test_await_atomicity_pragma_suppresses():
    src = (
        "import asyncio\n"
        "class C:\n"
        "    async def ensure(self):\n"
        "        if self._ctx is None:\n"
        "            # ccaudit: allow-await-atomicity(single waiter by construction: ensure() is serialized by ensure_open)\n"
        "            self._ctx = await build()\n"
    )
    assert _v4(src, "await-atomicity") == []


def test_async_for_and_async_with_are_interleaving_points():
    # `async for` suspends at every iteration; the RMW spanning it is
    # just as torn as one spanning a bare await
    src = (
        "class C:\n"
        "    async def drain(self, agen):\n"
        "        if self._buf is None:\n"
        "            async for item in agen:\n"
        "                pass\n"
        "            self._buf = 1\n"
    )
    assert len(_v4(src, "await-atomicity")) == 1


# =================================================== v4: lock-across-await


def test_threading_lock_held_across_await_flagged():
    src = (
        "import threading, asyncio\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lk = threading.Lock()\n"
        "    async def f(self):\n"
        "        with self._lk:\n"
        "            await asyncio.sleep(0)\n"
    )
    hits = _v4(src, "lock-across-await")
    assert len(hits) == 1
    assert hits[0].line == 7
    assert hits[0].severity == "warning"


def test_asyncio_lock_held_across_await_clean():
    src = (
        "import asyncio\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lk = asyncio.Lock()\n"
        "    async def f(self):\n"
        "        async with self._lk:\n"
        "            await asyncio.sleep(0)\n"
    )
    assert _v4(src, "lock-across-await") == []


def test_thread_lock_released_before_await_clean():
    src = (
        "import threading, asyncio\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lk = threading.Lock()\n"
        "    async def f(self):\n"
        "        with self._lk:\n"
        "            x = 1\n"
        "        await asyncio.sleep(0)\n"
    )
    assert _v4(src, "lock-across-await") == []


def test_lock_across_await_pragma_suppresses():
    src = (
        "import threading, asyncio\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lk = threading.Lock()\n"
        "    async def f(self):\n"
        "        with self._lk:\n"
        "            # ccaudit: allow-lock-across-await(uncontended by design: the lock only guards process-exit teardown)\n"
        "            await asyncio.sleep(0)\n"
    )
    assert _v4(src, "lock-across-await") == []


# ====================================================== v4: loop-affinity


def test_mixed_context_sync_method_touching_loop_state_flagged():
    # stats() has no resolved caller -> MIXED; _conns is written in a
    # coroutine -> loop-owned; the touch fires
    src = (
        "import asyncio\n"
        "class Client:\n"
        "    def __init__(self):\n"
        "        self._conns = []\n"
        "    async def open(self):\n"
        "        self._conns.append(1)\n"
        "    def stats(self):\n"
        "        return len(self._conns)\n"
    )
    hits = _v4(src, "loop-affinity")
    assert len(hits) == 1
    assert hits[0].line == 8
    assert hits[0].severity == "warning"


def test_loop_confined_sync_helper_clean():
    # _pick is only ever called from a coroutine: the callgraph
    # fixpoint proves it loop-confined, so its touches are loop-side
    src = (
        "import asyncio\n"
        "class Client:\n"
        "    def __init__(self):\n"
        "        self._conns = []\n"
        "    async def open(self):\n"
        "        self._conns.append(1)\n"
        "        return self._pick()\n"
        "    def _pick(self):\n"
        "        return self._conns[0]\n"
    )
    assert _v4(src, "loop-affinity") == []


def test_init_writes_to_loop_state_clean():
    # __init__ happens-before the object ever reaches the loop
    src = (
        "import asyncio\n"
        "class Client:\n"
        "    def __init__(self):\n"
        "        self._conns = []\n"
        "        self._q = asyncio.Queue()\n"
        "    async def open(self):\n"
        "        self._conns.append(1)\n"
    )
    assert _v4(src, "loop-affinity") == []


def test_cross_module_chain_to_loop_owned_attr_flagged():
    aio_src = (
        "import asyncio\n"
        "class Client:\n"
        "    def __init__(self):\n"
        "        self._conns = []\n"
        "    async def open(self):\n"
        "        self._conns.append(1)\n"
    )
    other_src = (
        "from tpu_cc_manager.k8s.aio import Client\n"
        "class Facade:\n"
        "    def __init__(self):\n"
        "        self.aio = Client()\n"
        "    def peek(self):\n"
        "        return self.aio._conns\n"
    )
    findings = analyze_modules([
        Module(AIO, aio_src),
        Module("tpu_cc_manager/k8s/other.py", other_src),
    ])
    hits = [f for f in findings if f.rule == "loop-affinity"]
    assert len(hits) == 1
    assert hits[0].file == "tpu_cc_manager/k8s/other.py"
    assert hits[0].line == 6


def test_typed_local_chain_to_loop_owned_attr_flagged():
    aio_src = (
        "import asyncio\n"
        "class Client:\n"
        "    def __init__(self):\n"
        "        self._conns = []\n"
        "    async def open(self):\n"
        "        self._conns.append(1)\n"
    )
    user_src = (
        "from tpu_cc_manager.k8s.aio import Client\n"
        "def probe():\n"
        "    c = Client()\n"
        "    return c._conns\n"
    )
    findings = analyze_modules([
        Module(AIO, aio_src),
        Module("tpu_cc_manager/x.py", user_src),
    ])
    assert [f.line for f in findings if f.rule == "loop-affinity"] == [4]


def test_method_calls_through_facade_are_sanctioned():
    # bridge.call(self.aio.get_node(...)) touches only METHODS of the
    # core class — the sanctioned route stays clean
    aio_src = (
        "import asyncio\n"
        "class Client:\n"
        "    def __init__(self):\n"
        "        self._conns = []\n"
        "    async def open(self):\n"
        "        self._conns.append(1)\n"
        "    async def get_node(self, name):\n"
        "        return {}\n"
    )
    facade_src = (
        "from tpu_cc_manager.k8s.aio import Client\n"
        "class Facade:\n"
        "    def __init__(self, bridge):\n"
        "        self.bridge = bridge\n"
        "        self.aio = Client()\n"
        "    def get_node(self, name):\n"
        "        return self.bridge.call(self.aio.get_node(name))\n"
    )
    findings = analyze_modules([
        Module(AIO, aio_src),
        Module(BRIDGE, facade_src),
    ])
    assert [f for f in findings if f.rule == "loop-affinity"] == []


def test_loop_affinity_pragma_suppresses():
    src = (
        "import asyncio\n"
        "class Client:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "    async def open(self):\n"
        "        self.n += 1\n"
        "    def stats(self):\n"
        "        return self.n  # ccaudit: allow-loop-affinity(GIL-atomic counter snapshot)\n"
    )
    assert _v4(src, "loop-affinity") == []


# ================================================ v4: loop-self-deadlock


def test_bridge_call_inside_coroutine_is_error_severity():
    src = (
        "class C:\n"
        "    async def f(self, bridge):\n"
        "        return bridge.call(coro())\n"
    )
    hits = _v4(src, "loop-self-deadlock", relpath="tpu_cc_manager/x.py")
    assert len(hits) == 1
    assert hits[0].severity == "error"
    assert "self-deadlock" in hits[0].message


def test_get_bridge_call_inside_coroutine_flagged():
    src = (
        "from tpu_cc_manager.k8s.aio_bridge import get_bridge\n"
        "async def f():\n"
        "    return get_bridge().call(coro())\n"
    )
    assert len(
        _v4(src, "loop-self-deadlock", relpath="tpu_cc_manager/x.py")
    ) == 1


def test_bridge_gather_inside_coroutine_flagged():
    src = (
        "class C:\n"
        "    async def f(self, bridge, futs):\n"
        "        return bridge.gather(futs)\n"
    )
    assert len(
        _v4(src, "loop-self-deadlock", relpath="tpu_cc_manager/x.py")
    ) == 1


def test_seeded_result_on_loop_thread_fixture_caught():
    # THE acceptance fixture: a bridge future's .result() from the
    # loop thread — submit schedules onto this very loop, result()
    # blocks the loop waiting for it; nothing can ever progress
    src = (
        "class C:\n"
        "    async def f(self, bridge):\n"
        "        fut = bridge.submit(work)\n"
        "        return fut.result()\n"
    )
    hits = _v4(src, "loop-self-deadlock", relpath="tpu_cc_manager/x.py")
    assert len(hits) == 1
    assert hits[0].line == 4
    assert hits[0].severity == "error"
    assert "wrap_future" in hits[0].message


def test_asyncio_gather_not_mistaken_for_bridge_gather():
    src = (
        "import asyncio\n"
        "async def f(a, b):\n"
        "    return await asyncio.gather(a, b)\n"
    )
    assert _v4(
        src, "loop-self-deadlock", relpath="tpu_cc_manager/x.py"
    ) == []


def test_bridge_call_from_sync_function_clean():
    # sync land is exactly where bridge.call belongs
    src = (
        "def f(bridge):\n"
        "    return bridge.call(coro())\n"
    )
    assert _v4(
        src, "loop-self-deadlock", relpath="tpu_cc_manager/x.py"
    ) == []


def test_loop_self_deadlock_pragma_suppresses():
    src = (
        "class C:\n"
        "    async def f(self, bridge):\n"
        "        # ccaudit: allow-loop-self-deadlock(bridge is a SECOND loop in tests; cross-loop call is safe)\n"
        "        return bridge.call(coro())\n"
    )
    assert _v4(
        src, "loop-self-deadlock", relpath="tpu_cc_manager/x.py"
    ) == []


# ======================================================== v4: orphan-task


def test_discarded_create_task_flagged():
    src = (
        "import asyncio\n"
        "async def work(): pass\n"
        "async def main():\n"
        "    asyncio.create_task(work())\n"
    )
    hits = _v4(src, "orphan-task", relpath="tpu_cc_manager/x.py")
    assert len(hits) == 1
    assert hits[0].line == 4
    assert hits[0].severity == "warning"


def test_task_bound_but_never_used_flagged():
    src = (
        "import asyncio\n"
        "async def work(): pass\n"
        "async def main():\n"
        "    t = asyncio.create_task(work())\n"
        "    return 1\n"
    )
    assert len(_v4(src, "orphan-task", relpath="tpu_cc_manager/x.py")) == 1


def test_awaited_task_clean():
    src = (
        "import asyncio\n"
        "async def work(): pass\n"
        "async def main():\n"
        "    t = asyncio.create_task(work())\n"
        "    await t\n"
    )
    assert _v4(src, "orphan-task", relpath="tpu_cc_manager/x.py") == []


def test_task_stored_on_attribute_registry_clean():
    # self._reader_task = ...create_task(...) — the aio client's own
    # pattern: the handle outlives the frame, aclose cancels it
    src = (
        "import asyncio\n"
        "class C:\n"
        "    async def start(self, loop):\n"
        "        self._reader_task = loop.create_task(self._read())\n"
        "    async def _read(self): pass\n"
    )
    assert _v4(src, "orphan-task", relpath="tpu_cc_manager/x.py") == []


def test_taskgroup_create_task_clean():
    # structured concurrency: the TaskGroup owns and awaits its tasks
    src = (
        "import asyncio\n"
        "async def work(): pass\n"
        "async def main():\n"
        "    async with asyncio.TaskGroup() as tg:\n"
        "        tg.create_task(work())\n"
    )
    assert _v4(src, "orphan-task", relpath="tpu_cc_manager/x.py") == []


def test_discarded_coroutine_call_flagged():
    # work() creates a coroutine object and drops it: the body NEVER runs
    src = (
        "async def work(): pass\n"
        "async def main():\n"
        "    work()\n"
    )
    hits = _v4(src, "orphan-task", relpath="tpu_cc_manager/x.py")
    assert len(hits) == 1
    assert "never" in hits[0].message.lower()


def test_discarded_self_coroutine_method_flagged():
    src = (
        "class C:\n"
        "    async def flush(self): pass\n"
        "    async def run(self):\n"
        "        self.flush()\n"
    )
    assert len(_v4(src, "orphan-task", relpath="tpu_cc_manager/x.py")) == 1


def test_awaited_coroutine_call_clean():
    src = (
        "async def work(): pass\n"
        "async def main():\n"
        "    await work()\n"
    )
    assert _v4(src, "orphan-task", relpath="tpu_cc_manager/x.py") == []


def test_orphan_task_pragma_suppresses():
    src = (
        "import asyncio\n"
        "async def work(): pass\n"
        "async def main():\n"
        "    asyncio.create_task(work())  # ccaudit: allow-orphan-task(fire-and-forget telemetry; loss is acceptable)\n"
    )
    assert _v4(src, "orphan-task", relpath="tpu_cc_manager/x.py") == []


# ==================================================== v4: async-exception


def test_swallowing_except_in_async_request_path_flagged():
    src = (
        "class C:\n"
        "    async def req(self):\n"
        "        try:\n"
        "            await self.send()\n"
        "        except Exception:\n"
        "            log.debug('x')\n"
        "    async def send(self): pass\n"
    )
    hits = _v4(src, "async-exception")
    assert len(hits) == 1
    assert hits[0].line == 5
    assert hits[0].severity == "warning"


def test_reraising_handler_clean():
    src = (
        "class C:\n"
        "    async def req(self):\n"
        "        try:\n"
        "            await self.send()\n"
        "        except OSError as e:\n"
        "            raise ApiException(0, str(e)) from e\n"
        "    async def send(self): pass\n"
    )
    assert _v4(src, "async-exception") == []


def test_retry_continue_handler_clean():
    src = (
        "class C:\n"
        "    async def req(self):\n"
        "        while True:\n"
        "            try:\n"
        "                return await self.send()\n"
        "            except ConnectionResetError:\n"
        "                continue\n"
        "    async def send(self): pass\n"
    )
    assert _v4(src, "async-exception") == []


def test_forwarding_bound_exception_clean():
    # the watch pump's shape: the exception object is handed to the
    # consumer thread through the queue — propagation, not loss
    src = (
        "class C:\n"
        "    async def pump(self, q):\n"
        "        try:\n"
        "            await self.drain()\n"
        "        except BaseException as e:\n"
        "            q.put(e)\n"
        "    async def drain(self): pass\n"
    )
    assert _v4(src, "async-exception") == []


def test_settling_handler_clean():
    src = (
        "class C:\n"
        "    async def req(self, fut):\n"
        "        try:\n"
        "            await self.send()\n"
        "        except OSError:\n"
        "            fut.set_exception(ApiException(0, 'dead'))\n"
        "    async def send(self): pass\n"
    )
    assert _v4(src, "async-exception") == []


def test_transitively_settling_handler_clean():
    # the handler calls a helper whose closure reaches _fail_inflight:
    # the callgraph sink-summary proves the pending entries settle
    src = (
        "class C:\n"
        "    async def req(self):\n"
        "        try:\n"
        "            await self.send()\n"
        "        except OSError:\n"
        "            self._teardown()\n"
        "    def _teardown(self):\n"
        "        self._fail_inflight()\n"
        "    def _fail_inflight(self):\n"
        "        pass\n"
        "    async def send(self): pass\n"
    )
    assert _v4(src, "async-exception") == []


def test_enclosing_finally_settles_clean():
    src = (
        "class C:\n"
        "    async def req(self):\n"
        "        try:\n"
        "            try:\n"
        "                await self.send()\n"
        "            except OSError:\n"
        "                log.debug('transport died')\n"
        "        finally:\n"
        "            self.abort()\n"
        "    def abort(self): pass\n"
        "    async def send(self): pass\n"
    )
    assert _v4(src, "async-exception") == []


def test_async_exception_scoped_to_async_core_modules():
    src = (
        "class C:\n"
        "    async def req(self):\n"
        "        try:\n"
        "            await self.send()\n"
        "        except Exception:\n"
        "            log.debug('x')\n"
        "    async def send(self): pass\n"
    )
    assert _v4(src, "async-exception", relpath="tpu_cc_manager/agent.py") == []


def test_async_exception_pragma_suppresses():
    src = (
        "class C:\n"
        "    async def req(self):\n"
        "        try:\n"
        "            await self.send()\n"
        "        except Exception:  # ccaudit: allow-async-exception(observer isolation: nothing in flight here)\n"
        "            log.debug('x')\n"
        "    async def send(self): pass\n"
    )
    assert _v4(src, "async-exception") == []


# =============================================== v4: wiring + live pins


def test_legacy_rules_keep_error_severity():
    src = (
        "import time\n"
        "async def pump():\n"
        "    time.sleep(1)\n"
    )
    hits = _async_findings(src)
    assert len(hits) == 1
    assert hits[0].severity == "error"


def test_sarif_level_tracks_finding_severity():
    from tpu_cc_manager.analysis.sarif import to_sarif, validate_sarif

    src = (
        "import asyncio\n"
        "class C:\n"
        "    async def ensure(self):\n"
        "        if self._ctx is None:\n"
        "            self._ctx = await build()\n"
    )
    warn = _v4(src, "await-atomicity")
    dead = _v4(
        "class C:\n"
        "    async def f(self, bridge):\n"
        "        return bridge.call(coro())\n",
        "loop-self-deadlock", relpath="tpu_cc_manager/x.py",
    )
    doc = to_sarif(warn + dead, [], [])
    assert validate_sarif(doc) == []
    levels = {r["ruleId"]: r["level"] for r in doc["runs"][0]["results"]}
    assert levels["await-atomicity"] == "warning"
    assert levels["loop-self-deadlock"] == "error"


def test_asyncio_lock_discounted_for_thread_races():
    # an asyncio.Lock excludes coroutines, not threads: a location
    # shared with a real thread and "guarded" only by the async lock
    # must still be a race-lockset finding
    src = (
        "import asyncio\n"
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "        self._alk = asyncio.Lock()\n"
        "        threading.Thread(target=self.worker).start()\n"
        "        threading.Thread(target=self.other).start()\n"
        "    def worker(self):\n"
        "        self.n += 1\n"
        "    def other(self):\n"
        "        self.n += 1\n"
    )
    # sanity: same shape with a threading.Lock held at both writes is clean
    hits = [
        f for f in analyze_source(src, "tpu_cc_manager/x.py")
        if f.rule == "race-lockset"
    ]
    assert len(hits) == 2


def test_live_async_core_passes_v4():
    # the shipped async core must pass its own v4 pass against the
    # whole default surface (deliberate cases carry pragmas, never
    # silent baseline entries — ISSUE 17's burn-down-only contract)
    from tpu_cc_manager.analysis import analyze_paths
    from tpu_cc_manager.analysis.asyncflow import WARNING_RULES

    v4_rules = set(WARNING_RULES) | {"loop-self-deadlock"}
    hits = [
        f for f in analyze_paths()
        if f.rule in v4_rules
    ]
    assert hits == [], [f.render() for f in hits]
