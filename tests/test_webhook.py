"""Admission webhook (tpu_cc_manager.webhook).

Scheduler-level CC enforcement the reference lacks entirely: mutating
(inject a nodeSelector on the OBSERVED state label) and validating
(reject contradictory specs) admission for pods carrying the
requires-cc label, over the admission.k8s.io/v1 AdmissionReview wire
protocol on real HTTPS.
"""

import base64
import json
import ssl
import urllib.error
import urllib.request

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.webhook import (
    AdmissionServer, mutate_pod, required_mode, review_response,
    validate_pod,
)


def make_pod(requires=None, node_selector=None, tolerations=None):
    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "workload", "labels": {}},
        "spec": {"containers": [{"name": "c", "image": "busybox"}]},
    }
    if requires is not None:
        pod["metadata"]["labels"][L.REQUIRES_CC_LABEL] = requires
    if node_selector is not None:
        pod["spec"]["nodeSelector"] = node_selector
    if tolerations is not None:
        pod["spec"]["tolerations"] = tolerations
    return pod


def apply_json_patch(doc, ops):
    """Minimal RFC 6902 'add' applier — enough to prove the emitted
    patch produces the pod the scheduler must see."""
    doc = json.loads(json.dumps(doc))
    for op in ops:
        assert op["op"] == "add"
        tokens = [
            t.replace("~1", "/").replace("~0", "~")
            for t in op["path"].lstrip("/").split("/")
        ]
        target = doc
        for t in tokens[:-1]:
            target = target[t]
        target[tokens[-1]] = op["value"]
    return doc


# ---------------------------------------------------------------------------
# pure logic
# ---------------------------------------------------------------------------

def test_required_mode_parsing():
    assert required_mode(make_pod()) is None
    assert required_mode(make_pod(requires="on")) == "on"
    assert required_mode(make_pod(requires="ici")) == "ici"
    with pytest.raises(ValueError, match="must be one of"):
        required_mode(make_pod(requires="bogus"))


def test_mutate_injects_observed_state_selector():
    ops = mutate_pod(make_pod(requires="on"))
    patched = apply_json_patch(make_pod(requires="on"), ops)
    assert patched["spec"]["nodeSelector"] == {L.CC_MODE_STATE_LABEL: "on"}


def test_mutate_preserves_existing_selector_keys():
    pod = make_pod(requires="devtools",
                   node_selector={"pool": "prod"})
    patched = apply_json_patch(pod, mutate_pod(pod))
    assert patched["spec"]["nodeSelector"] == {
        "pool": "prod", L.CC_MODE_STATE_LABEL: "devtools",
    }


def test_mutate_noop_when_not_opted_in_or_already_right():
    assert mutate_pod(make_pod()) == []
    assert mutate_pod(make_pod(
        requires="on", node_selector={L.CC_MODE_STATE_LABEL: "on"}
    )) == []


def test_mutate_leaves_contradictory_pin_for_validation_to_reject():
    """Mutating webhooks run BEFORE validating ones: rewriting a
    contradictory explicit pin would silently admit the spec the
    validating webhook is documented to reject. Mutate must leave it
    alone so validation still fires."""
    pod = make_pod(requires="on",
                   node_selector={L.CC_MODE_STATE_LABEL: "off"})
    assert mutate_pod(pod) == []
    ok, reason = validate_pod(pod)
    assert not ok and "pins" in reason


def test_validate_allows_clean_and_unopted_pods():
    assert validate_pod(make_pod()) == (True, "")
    assert validate_pod(make_pod(requires="on")) == (True, "")


def test_validate_rejects_contradictory_selector():
    ok, reason = validate_pod(make_pod(
        requires="on", node_selector={L.CC_MODE_STATE_LABEL: "off"}
    ))
    assert not ok and "pins" in reason


@pytest.mark.parametrize("tol", [
    # exact key+value match
    {"key": L.FLIP_TAINT_KEY, "operator": "Equal",
     "value": L.FLIP_TAINT_VALUE, "effect": "NoSchedule"},
    # key Exists
    {"key": L.FLIP_TAINT_KEY, "operator": "Exists"},
    # tolerate-everything wildcard
    {"operator": "Exists"},
    # effect unset tolerates all effects
    {"key": L.FLIP_TAINT_KEY, "operator": "Equal",
     "value": L.FLIP_TAINT_VALUE},
])
def test_validate_rejects_flip_taint_toleration(tol):
    ok, reason = validate_pod(make_pod(requires="on", tolerations=[tol]))
    assert not ok and "flip" in reason


@pytest.mark.parametrize("tol", [
    # different key
    {"key": "node.kubernetes.io/not-ready", "operator": "Exists"},
    # right key, wrong value
    {"key": L.FLIP_TAINT_KEY, "operator": "Equal", "value": "other"},
    # right key but scoped to a different effect
    {"key": L.FLIP_TAINT_KEY, "operator": "Exists",
     "effect": "NoExecute"},
])
def test_validate_allows_unrelated_tolerations(tol):
    assert validate_pod(make_pod(requires="on", tolerations=[tol]))[0]


def test_validate_rejects_direct_node_binding():
    """spec.nodeName bypasses the scheduler, so the injected
    nodeSelector is never evaluated — the one placement path the
    guarantee can't cover must be refused for opted-in pods."""
    pod = make_pod(requires="on")
    pod["spec"]["nodeName"] = "some-node"
    ok, reason = validate_pod(pod)
    assert not ok and "nodeName" in reason
    # pods that don't opt in may direct-bind freely
    plain = make_pod()
    plain["spec"]["nodeName"] = "some-node"
    assert validate_pod(plain)[0]


def test_unopted_pod_with_wildcard_toleration_is_allowed():
    # the webhook only polices pods that ASK for confidential placement
    assert validate_pod(
        make_pod(tolerations=[{"operator": "Exists"}])
    )[0]


# ---------------------------------------------------------------------------
# AdmissionReview protocol
# ---------------------------------------------------------------------------

def make_review(pod, uid="uid-1"):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"uid": uid, "object": pod},
    }


def test_review_response_mutate_carries_base64_patch():
    out = review_response(make_review(make_pod(requires="on")), "mutate")
    resp = out["response"]
    assert resp["uid"] == "uid-1" and resp["allowed"]
    ops = json.loads(base64.b64decode(resp["patch"]))
    assert resp["patchType"] == "JSONPatch"
    patched = apply_json_patch(make_pod(requires="on"), ops)
    assert patched["spec"]["nodeSelector"][L.CC_MODE_STATE_LABEL] == "on"


def test_review_response_validate_denies_with_status():
    out = review_response(
        make_review(make_pod(requires="on", tolerations=[
            {"operator": "Exists"},
        ])),
        "validate",
    )
    resp = out["response"]
    assert resp["allowed"] is False
    assert resp["status"]["code"] == 403


def test_review_response_invalid_mode_denied_on_both_endpoints():
    for kind in ("mutate", "validate"):
        resp = review_response(
            make_review(make_pod(requires="bogus")), kind
        )["response"]
        assert resp["allowed"] is False
        assert resp["status"]["code"] == 400


def test_review_response_malformed_raises():
    with pytest.raises(ValueError, match="uid"):
        review_response({"request": {}}, "mutate")
    with pytest.raises(ValueError):
        review_response({"bogus": True}, "validate")


# ---------------------------------------------------------------------------
# the HTTPS server (real wire)
# ---------------------------------------------------------------------------

def _post(url, body, ctx=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, context=ctx) as resp:
        return resp.status, json.loads(resp.read())


def test_admission_server_over_https(tls_pki):
    cert, key = tls_pki
    ctx = ssl.create_default_context(cafile=cert)
    with AdmissionServer(0, cert_file=cert, key_file=key) as srv:
        base = f"https://127.0.0.1:{srv.port}"
        status, out = _post(
            f"{base}/mutate", make_review(make_pod(requires="on")), ctx
        )
        assert status == 200
        assert out["response"]["patchType"] == "JSONPatch"

        status, out = _post(
            f"{base}/validate",
            make_review(make_pod(
                requires="on",
                node_selector={L.CC_MODE_STATE_LABEL: "off"},
            ), uid="uid-2"),
            ctx,
        )
        assert out["response"] == {
            "uid": "uid-2", "allowed": False,
            "status": {"message": out["response"]["status"]["message"],
                       "code": 403},
        }

        # health + counters
        health = urllib.request.urlopen(f"{base}/healthz", context=ctx)
        assert health.status == 200
        assert srv.reviews == 2
        metrics = urllib.request.urlopen(
            f"{base}/metrics", context=ctx
        ).read().decode()
        assert "tpu_cc_webhook_reviews_total 2" in metrics

        # malformed review -> 400, counted
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/mutate", {"not": "a review"}, ctx)
        assert ei.value.code == 400
        assert srv.rejected_malformed == 1

        # unknown route -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/other", {}, ctx)
        assert ei.value.code == 404


def test_tls_required_unless_explicitly_disabled():
    with pytest.raises(ValueError, match="TLS"):
        AdmissionServer(0)
    # tests may opt out
    with AdmissionServer(0, tls=False) as srv:
        status, out = _post(
            f"http://127.0.0.1:{srv.port}/validate",
            make_review(make_pod()),
        )
        assert status == 200 and out["response"]["allowed"]


def test_cli_webhook_requires_cert(capsys):
    from tpu_cc_manager.__main__ import main

    assert main(["webhook", "--port", "0"]) == 1


def test_serving_cert_hot_reload(tmp_path):
    """cert-manager rotates the Secret under a running pod; the server
    must pick up the new chain for new handshakes without a restart,
    and keep the old one through a torn mid-rotation read."""
    import shutil
    import subprocess

    def gen(cn, prefix):
        cert = tmp_path / f"{prefix}.crt"
        key = tmp_path / f"{prefix}.key"
        r = subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", f"/CN={cn}",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"openssl unavailable: {r.stderr}")
        return str(cert), str(key)

    cert_a, key_a = gen("127.0.0.1", "a")
    cert_b, key_b = gen("127.0.0.1", "b")
    # the server serves from mutable paths (the Secret mount analog)
    live_cert = tmp_path / "tls.crt"
    live_key = tmp_path / "tls.key"
    shutil.copy(cert_a, live_cert)
    shutil.copy(key_a, live_key)

    with AdmissionServer(0, cert_file=str(live_cert),
                         key_file=str(live_key),
                         reload_check_s=3600) as srv:  # manual trigger
        base = f"https://127.0.0.1:{srv.port}"

        def handshake_ok(ca):
            ctx = ssl.create_default_context(cafile=ca)
            try:
                urllib.request.urlopen(f"{base}/healthz", context=ctx,
                                       timeout=5)
                return True
            except ssl.SSLError:
                return False
            except urllib.error.URLError as e:
                if isinstance(e.reason, ssl.SSLError):
                    return False
                raise

        assert handshake_ok(cert_a) and not handshake_ok(cert_b)

        # torn rotation: key not swapped yet -> reload refused, old
        # chain keeps serving
        shutil.copy(cert_b, live_cert)
        assert srv.reload_certs_if_changed() is False
        assert handshake_ok(cert_a)

        # rotation completes -> new chain serves new handshakes
        shutil.copy(key_b, live_key)
        assert srv.reload_certs_if_changed() is True
        assert handshake_ok(cert_b) and not handshake_ok(cert_a)


def test_doctor_aware_steering_opt_in(monkeypatch):
    """TPU_CC_WEBHOOK_REQUIRE_DOCTOR=true additionally pins opted-in
    pods to doctor-healthy nodes (cc.doctor.ok=true); off by default so
    mixed fleets (nodes that never published a verdict) aren't
    stranded."""
    from tpu_cc_manager.webhook import mutate_pod, validate_pod

    monkeypatch.delenv("TPU_CC_WEBHOOK_REQUIRE_DOCTOR", raising=False)
    pod = {"metadata": {"labels": {L.REQUIRES_CC_LABEL: "on"}},
           "spec": {}}
    # default off: only the state-label pin
    ops = mutate_pod(pod)
    paths = [o["path"] for o in ops]
    assert not any("doctor" in p for p in paths), paths

    monkeypatch.setenv("TPU_CC_WEBHOOK_REQUIRE_DOCTOR", "true")
    ops = mutate_pod(pod)
    values = {o["path"]: o.get("value") for o in ops}
    doctor_path = next(p for p in values if "doctor" in p)
    assert values[doctor_path] == "true"
    # an existing CORRECT doctor pin is left alone
    pod2 = {"metadata": {"labels": {L.REQUIRES_CC_LABEL: "on"}},
            "spec": {"nodeSelector": {L.DOCTOR_OK_LABEL: "true"}}}
    ops2 = mutate_pod(pod2)
    assert sum("doctor" in o["path"] for o in ops2) == 0

    # a pod that brought its OWN matching mode pin must not dodge the
    # doctor requirement
    pod3 = {"metadata": {"labels": {L.REQUIRES_CC_LABEL: "on"}},
            "spec": {"nodeSelector": {L.CC_MODE_STATE_LABEL: "on"}}}
    ops3 = mutate_pod(pod3)
    assert sum("doctor" in o["path"] for o in ops3) == 1, ops3

    # an explicit pin onto doctor-UNHEALTHY nodes is REJECTED, same
    # contradiction treatment as a wrong mode pin
    pod4 = {"metadata": {"labels": {L.REQUIRES_CC_LABEL: "on"}},
            "spec": {"nodeSelector": {L.CC_MODE_STATE_LABEL: "on",
                                      L.DOCTOR_OK_LABEL: "false"}}}
    allowed, reason = validate_pod(pod4)
    assert not allowed and "doctor" in reason
    # ...but only while the knob is on
    monkeypatch.delenv("TPU_CC_WEBHOOK_REQUIRE_DOCTOR")
    assert validate_pod(pod4)[0] is True


def test_doctor_steering_warn_mode_rehearses_without_enforcing(
        monkeypatch):
    """TPU_CC_WEBHOOK_REQUIRE_DOCTOR=warn is the enablement rehearsal:
    admission behaves exactly as off (no doctor pin injected, no
    denial), but every opted-in review response carries AdmissionReview
    ``warnings`` describing what enforce would have done — kubectl
    shows them to the submitter, so an operator can run warn until the
    fleet is quiet, then flip to true without stranding pods."""
    from tpu_cc_manager.webhook import (
        mutate_pod, review_response, validate_pod,
    )

    monkeypatch.setenv("TPU_CC_WEBHOOK_REQUIRE_DOCTOR", "warn")
    pod = {"metadata": {"labels": {L.REQUIRES_CC_LABEL: "on"}},
           "spec": {}}
    # no pin injected (enforcement unchanged from off)...
    assert not any("doctor" in o["path"] for o in mutate_pod(pod))
    # ...but both endpoints carry the would-pin warning
    for kind in ("mutate", "validate"):
        out = review_response(
            {"request": {"uid": "u1", "object": pod}}, kind,
        )
        assert out["response"]["allowed"] is True
        warns = out["response"].get("warnings")
        assert warns and any("doctor.unreported" in w for w in warns)
        # the API server truncates warnings >256 chars — exactly where
        # the actionable tail would live
        assert all(len(w) <= 256 for w in warns), warns

    # a contradictory pin is ALLOWED in warn mode, with a would-reject
    # warning (enforce mode denies it)
    pod2 = {"metadata": {"labels": {L.REQUIRES_CC_LABEL: "on"}},
            "spec": {"nodeSelector": {L.DOCTOR_OK_LABEL: "false"}}}
    assert validate_pod(pod2)[0] is True
    out = review_response(
        {"request": {"uid": "u2", "object": pod2}}, "validate",
    )
    assert out["response"]["allowed"] is True
    assert "REJECT" in out["response"]["warnings"][0]

    # a correct pin or a non-opted-in pod warns about nothing
    pod3 = {"metadata": {"labels": {L.REQUIRES_CC_LABEL: "on"}},
            "spec": {"nodeSelector": {L.DOCTOR_OK_LABEL: "true"}}}
    out = review_response(
        {"request": {"uid": "u3", "object": pod3}}, "mutate",
    )
    assert "warnings" not in out["response"]
    out = review_response(
        {"request": {"uid": "u4", "object": {"metadata": {}, "spec": {}}}},
        "mutate",
    )
    assert "warnings" not in out["response"]

    # enforce mode is unaffected by the warn plumbing; 'enforce' is an
    # accepted synonym of 'true'
    for value in ("true", "enforce"):
        monkeypatch.setenv("TPU_CC_WEBHOOK_REQUIRE_DOCTOR", value)
        assert any("doctor" in o["path"] for o in mutate_pod(pod))
        assert validate_pod(pod2)[0] is False
    # a typo reads as OFF (and logs), never as silent enforcement
    monkeypatch.setenv("TPU_CC_WEBHOOK_REQUIRE_DOCTOR", "warm")
    assert not any("doctor" in o["path"] for o in mutate_pod(pod))
    assert validate_pod(pod2)[0] is True

    # the rehearsal is fleet-visible: /metrics counts warned responses
    from tpu_cc_manager.webhook import AdmissionServer

    monkeypatch.setenv("TPU_CC_WEBHOOK_REQUIRE_DOCTOR", "warn")
    with AdmissionServer(0, tls=False) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        status, out = _post(
            f"{base}/mutate",
            {"request": {"uid": "m-1", "object": pod}},
        )
        assert status == 200 and out["response"]["warnings"]
        _post(f"{base}/mutate",
              {"request": {"uid": "m-2",
                           "object": {"metadata": {}, "spec": {}}}})
        import urllib.request

        metrics = urllib.request.urlopen(
            f"{base}/metrics", timeout=5,
        ).read().decode()
        assert "tpu_cc_webhook_warned_total 1" in metrics
        assert "tpu_cc_webhook_reviews_total 2" in metrics
