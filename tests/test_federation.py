"""Multi-region federation (tpu_cc_manager.federation, ISSUE 16): the
region-affine hash ring's determinism + movement bounds, the ONE
sanctioned owner lookup, one-posture/per-region-windows rollout with
evacuation absorb, partition deferral through the FakeKube fault gate,
the zero-cross-region-reads judging contract pinned against per-region
``node_read_requests``, per-region trust domains (revoked root latches
attestation_outage in THAT region only), and the schema-2 scenario
surface the federation labs consume."""

import time

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.federation import (
    FederationError, FederationManager, FleetPosture, RegionSpec,
    RegionTrustDomain, posture_from_policy,
)
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.shard import HashRing
from tpu_cc_manager.simlab.scenario import (
    ScenarioError, validate_scenario,
)

POOL_LABEL = "simlab.pool"


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _region_ring(per_region=3, regions=("us-east", "eu-west")):
    members, tags = [], {}
    for r in regions:
        for k in range(per_region):
            m = f"{r}/shard-{k}"
            members.append(m)
            tags[m] = r
    return HashRing(members, regions=tags)


# ----------------------------------------------------- region-affine ring
def test_region_ring_deterministic_and_home_region_pinned():
    """Region-constrained walks are a pure function of the member set:
    two independently constructed rings agree on every placement, and a
    region-pinned lookup always lands in the home region."""
    a, b = _region_ring(), _region_ring()
    keys = [f"p{i}" for i in range(128)]
    for region in ("us-east", "eu-west"):
        owners_a = [a.owner_of(k, region=region) for k in keys]
        owners_b = [b.owner_of(k, region=region) for k in keys]
        assert owners_a == owners_b
        assert all(a.regions[m] == region for m in owners_a)


def test_region_ring_without_moves_about_one_nth_within_region():
    """Consistent hashing survives the region constraint: dropping one
    of a region's N members moves ONLY that member's keys, and they
    redistribute among the region's survivors — the other region's
    placements do not move at all."""
    ring = _region_ring(per_region=4)
    keys = [f"p{i}" for i in range(256)]
    before_home = {k: ring.owner_of(k, region="us-east") for k in keys}
    before_away = {k: ring.owner_of(k, region="eu-west") for k in keys}
    smaller = ring.without("us-east/shard-1")
    moved = 0
    for k in keys:
        after = smaller.owner_of(k, region="us-east")
        assert smaller.regions[after] == "us-east"
        if before_home[k] == "us-east/shard-1":
            moved += 1
            assert after != "us-east/shard-1"
        else:
            assert after == before_home[k], k
        # the sibling region is untouched by us-east's membership churn
        assert smaller.owner_of(k, region="eu-west") == before_away[k]
    # ~1/4 of the region's keys lived on the removed member (loose
    # bounds: vnode placement is hash-distributed, not exact)
    assert 256 * 0.08 < moved < 256 * 0.45


def test_region_ring_fails_over_out_of_region_only_when_region_empty():
    ring = _region_ring(per_region=1)
    keys = [f"p{i}" for i in range(32)]
    # one member left in the region: every key stays home
    assert all(ring.owner_of(k, region="us-east") == "us-east/shard-0"
               for k in keys)
    # the WHOLE region gone: the walk falls back to the global ring —
    # failover leaves the home region only when the region is down
    drained = ring.without("us-east/shard-0")
    for k in keys:
        owner = drained.owner_of(k, region="us-east")
        assert drained.regions[owner] == "eu-west"


def test_members_in_and_unknown_region_falls_back_to_global():
    ring = _region_ring(per_region=2)
    assert ring.members_in("us-east") == [
        "us-east/shard-0", "us-east/shard-1"]
    assert ring.members_in("mars") == []
    # an unknown region pin degrades to the plain deterministic walk
    assert ring.owner_of("p0", region="mars") == ring.owner_of("p0")


# ------------------------------------------------------ federation manager
def _region_kube(region, n=4, pools=2, state=None):
    kube = FakeKube()
    for i in range(n):
        labels = {
            L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
            POOL_LABEL: f"{region}-p{i % pools}",
            L.CC_MODE_LABEL: "off",
        }
        if state is not None:
            labels[L.CC_MODE_STATE_LABEL] = state
        kube.add_node(make_node(f"{region}-{i:03d}", labels=labels))
    return kube


def _federation(kubes, **kw):
    specs = [
        RegionSpec(
            name=region,
            client_factory=(lambda k=kube: k),
            pools=[f"{region}-p0", f"{region}-p1"],
            trust_domain=kw.pop(f"_domain_{region}", None),
        )
        for region, kube in kubes.items()
    ]
    kw.setdefault("pool_label", POOL_LABEL)
    kw.setdefault("fleet_interval_s", 0.2)
    kw.setdefault("lease_duration_s", 0.4)
    kw.setdefault("renew_period_s", 0.1)
    kw.setdefault("retry_period_s", 0.05)
    return FederationManager(specs, **kw)


def test_owner_of_is_region_aware_and_rejects_strays():
    kubes = {"us-east": _region_kube("us-east"),
             "eu-west": _region_kube("eu-west")}
    fed = _federation(kubes, shards_per_region=2)
    for pool in ("us-east-p0", "us-east-p1"):
        region, member = fed.owner_of(pool)
        assert region == "us-east"
        assert member.startswith("us-east/")
    region, member = fed.owner_of("eu-west-p1")
    assert (region, member[:8]) == ("eu-west", "eu-west/")
    with pytest.raises(FederationError, match="belongs to no region"):
        fed.owner_of("nobody-p0")
    with pytest.raises(FederationError, match="unknown region"):
        fed.pools_in_region("mars")
    # a pool claimed twice is a spec bug, caught at construction
    with pytest.raises(FederationError, match="claimed by both"):
        FederationManager([
            RegionSpec("a", lambda: None, ["p0"]),
            RegionSpec("b", lambda: None, ["p0"]),
        ], pool_label=POOL_LABEL)


def test_posture_windows_absorb_on_evacuation_and_zero_region_reads():
    """THE tentpole flow in one live federation: region windows stagger
    the ONE posture; evacuating us-east parks its write, cordons its
    nodes, and collapses eu-west's still-waiting window to NOW; and
    once converged, the per-region judges run entirely from informer
    caches — both FakeKubes' node_read_requests counters freeze."""
    kubes = {"us-east": _region_kube("us-east"),
             "eu-west": _region_kube("eu-west")}
    fed = _federation(kubes).start()
    try:
        assert fed.wait_covered(timeout_s=15)
        # eu-west's window is far away: only us-east opens immediately
        fed.apply_posture(FleetPosture(
            "on", windows={"eu-west": 60.0}, source="test"))
        assert _wait(lambda: all(
            kubes["us-east"].peek_node_label(n, L.CC_MODE_LABEL) == "on"
            for n in ("us-east-000", "us-east-003")))
        assert kubes["eu-west"].peek_node_label(
            "eu-west-000", L.CC_MODE_LABEL) == "off"
        # evacuate us-east: eu-west absorbs NOW, 60s window be damned
        entry = fed.evacuate("us-east")
        assert entry["region"] == "us-east"
        assert _wait(lambda: all(
            kubes["eu-west"].peek_node_label(n, L.CC_MODE_LABEL) == "on"
            for n in ("eu-west-000", "eu-west-003")))
        assert _wait(lambda: fed.region_cordoned("us-east"))
        stats = fed.stats()
        assert stats["evacuated"] == ["us-east"]
        (evac,) = stats["evacuations"]
        assert evac["cordoned"] == 4 and evac["cordon_s"] is not None
        # agents "apply" the flip: state labels land via the watch
        for n in range(4):
            kubes["eu-west"].set_node_labels(
                f"eu-west-{n:03d}", {L.CC_MODE_STATE_LABEL: "on"})
        assert _wait(lambda: fed.region_converged("eu-west", "on"))
        assert fed.wait_posture(timeout_s=10)
        # the zero-read pin: steady-state judging is informer-fed on
        # BOTH sides — neither region's API server sees another node
        # GET/LIST, from its own judge or a sibling's
        reads = {r: kubes[r].node_read_requests for r in kubes}
        for _ in range(5):
            assert fed.region_converged("eu-west", "on")
            assert fed.region_cordoned("us-east")
            assert not fed.region_converged("us-east", "on")
        for r in kubes:
            assert kubes[r].node_read_requests == reads[r], r
    finally:
        fed.stop()


def test_partitioned_region_defers_posture_write_until_heal():
    """A partitioned region's desired-state write DEFERS (the window
    worker retries through ApiException) and lands when the region
    heals — it never half-lands and never reroutes to a sibling."""
    kubes = {"us-east": _region_kube("us-east"),
             "eu-west": _region_kube("eu-west")}
    fed = _federation(kubes).start()
    try:
        assert fed.wait_covered(timeout_s=15)
        kubes["eu-west"].blackout = True
        fed.set_partitioned("eu-west", True)
        fed.apply_posture(FleetPosture("on", source="test"))
        assert _wait(lambda: kubes["us-east"].peek_node_label(
            "us-east-000", L.CC_MODE_LABEL) == "on")
        time.sleep(0.4)  # retries are running; nothing may land
        assert kubes["eu-west"].peek_node_label(
            "eu-west-000", L.CC_MODE_LABEL) == "off"
        assert fed.stats()["partitioned"] == ["eu-west"]
        kubes["eu-west"].blackout = False
        fed.set_partitioned("eu-west", False)
        assert _wait(lambda: kubes["eu-west"].peek_node_label(
            "eu-west-000", L.CC_MODE_LABEL) == "on")
    finally:
        fed.stop()


def test_posture_from_policy_reads_region_windows():
    posture = posture_from_policy({
        "metadata": {"name": "fleet-posture"},
        "spec": {"mode": "on",
                 "nodeSelector": f"{L.TPU_ACCELERATOR_LABEL}",
                 "regionWindows": {"us-east": 0, "eu-west": 30}},
    })
    assert posture.mode == "on"
    assert posture.windows == {"us-east": 0.0, "eu-west": 30.0}
    assert posture.source == "fleet-posture"
    from tpu_cc_manager.policy import PolicySpecError

    with pytest.raises(PolicySpecError, match="regionWindows"):
        posture_from_policy({
            "metadata": {"name": "bad"},
            "spec": {"mode": "on",
                     "nodeSelector": f"{L.TPU_ACCELERATOR_LABEL}",
                     "regionWindows": {"eu-west": -1}},
        })


# --------------------------------------------------- per-region trust roots
def test_trust_domain_rotate_revoke_restore():
    d = RegionTrustDomain("us-east", (b"root-0",))
    assert d.keys() == (b"root-0",)
    d.rotate(b"root-1")
    # new primary first, old key kept as the rotation tail
    assert d.keys() == (b"root-1", b"root-0")
    d.revoke()
    # revoked = EXPLICITLY keyless (never None/env-fallback): every
    # quote judges 'unverifiable' and the outage latch can fire
    assert d.revoked and d.keys() == ()
    d.restore()
    assert d.keys() == (b"root-1", b"root-0")


def test_revoked_root_latches_outage_in_that_region_only(tmp_path):
    """THE region_attestation_latch pin at the audit layer: two regions
    whose quotes verify under their OWN trust domains; revoking region
    A's root drops A to explicitly-keyless — attestation_outage latches
    there — while region B's verified count is untouched. The same
    boundary the federation-2x512 drill exercises live."""
    import json

    from tpu_cc_manager.attest import FakeTpm
    from tpu_cc_manager.device.fake import fake_backend
    from tpu_cc_manager.evidence import audit_evidence, build_evidence

    domains = {r: RegionTrustDomain(r, (f"{r}-root".encode(),))
               for r in ("us-east", "eu-west")}
    fleets = {}
    for region, domain in domains.items():
        nodes = []
        for i in range(3):
            name = f"{region}-{i}"
            tpm = FakeTpm(state_dir=str(tmp_path / name),
                          key=domain.keys()[0])
            doc = build_evidence(name, fake_backend(n_chips=1),
                                 key=None, identity_provider=None,
                                 attestor=tpm)
            nodes.append(make_node(name, labels={
                L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
                L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off",
            }, annotations={L.EVIDENCE_ANNOTATION: json.dumps(doc)}))
        fleets[region] = nodes

    def audit(region):
        return audit_evidence(
            fleets[region], key=None, attestation_seen_before=True,
            attest_key=domains[region].keys(),
        )

    for region in domains:  # both regions verify under their own root
        a = audit(region)
        assert a["attestation_verified"] == 3, region
        assert a["attestation_outage"] == [], region
    # a region's quotes do NOT verify under the sibling's root — the
    # domains really are separate trust boundaries, not shared keys
    crossed = audit_evidence(
        fleets["us-east"], key=None,
        attest_key=domains["eu-west"].keys(),
    )
    assert crossed["attestation_verified"] == 0

    domains["us-east"].revoke()
    a = audit("us-east")
    assert a["attestation_verified"] == 0
    assert a["attestation_outage"] == sorted(
        n["metadata"]["name"] for n in fleets["us-east"])
    b = audit("eu-west")  # no spill: the sibling's posture is untouched
    assert b["attestation_verified"] == 3
    assert b["attestation_outage"] == []


# ------------------------------------------------------- schema-2 scenarios
def _fed_doc(**over):
    doc = {
        "version": 1,
        "schema": 2,
        "name": "fed-test",
        "nodes": 8,
        "pools": 2,
        "chips_per_node": 1,
        "initial_mode": "off",
        "workers": 4,
        "qps": 0,
        "evidence": False,
        "watch_timeout_s": 2,
        "regions": [
            {"name": "region-a", "nodes": 4, "pools": 1},
            {"name": "region-b", "nodes": 4, "pools": 1},
        ],
        "controllers": {"fleet": True},
        "actions": [
            {"at": 0.1, "action": "set_mode", "mode": "on"},
        ],
        "converge": {"mode": "on", "timeout_s": 30},
    }
    doc.update(over)
    return doc


def test_schema2_regions_validate_and_v1_documents_still_parse():
    sc = validate_scenario(_fed_doc())
    assert sc.schema == 2
    assert [r.name for r in sc.regions] == ["region-a", "region-b"]
    # schema-1 documents (no "schema" key) parse exactly as before
    v1 = _fed_doc()
    del v1["schema"], v1["regions"], v1["controllers"]
    sc1 = validate_scenario(v1)
    assert sc1.schema == 1 and sc1.regions == ()


def test_regions_require_schema_2_and_errors_name_the_source():
    doc = _fed_doc(schema=1)
    with pytest.raises(ScenarioError, match='"schema": 2'):
        validate_scenario(doc)
    # the strict error carries the offending FILE when source is given
    with pytest.raises(ScenarioError, match="scenarios/broken.json"):
        validate_scenario(doc, source="scenarios/broken.json")


def test_region_faults_and_windows_are_schema2_gated_and_checked():
    # a region fault naming an undeclared region is refused
    with pytest.raises(ScenarioError, match="region"):
        validate_scenario(_fed_doc(actions=[
            {"at": 0.1, "action": "fault", "fault": "region_partition",
             "region": "mars", "heal_after_s": 1.0},
            {"at": 0.2, "action": "set_mode", "mode": "on"},
        ]))
    # region sums must equal the top-level totals every derived knob
    # (worker split, convergence targets) is computed from
    with pytest.raises(ScenarioError, match="nodes"):
        validate_scenario(_fed_doc(nodes=9))
    # per-region set_mode windows validate region names too
    with pytest.raises(ScenarioError, match="region"):
        validate_scenario(_fed_doc(actions=[
            {"at": 0.1, "action": "set_mode", "mode": "on",
             "windows": {"mars": 5.0}},
        ]))
    ok = validate_scenario(_fed_doc(actions=[
        {"at": 0.1, "action": "set_mode", "mode": "on",
         "windows": {"region-a": 0.0, "region-b": 10.0}},
    ]))
    assert ok.actions[0].params["windows"] == {
        "region-a": 0.0, "region-b": 10.0}
