"""Slice-coherence protocol tests: atomic multi-host flips, leader
failover, timeout-refuses-to-flip, and per-slice policy divergence."""

import threading
import time

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.fake import FakeBackend, FakeChip
from tpu_cc_manager.engine import ModeEngine
from tpu_cc_manager.k8s import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.slice_coord import (
    DONE_ANNOTATION,
    HB_ANNOTATION,
    SliceAbortError,
    SliceCoordinator,
)


class SliceMember:
    """One node's agent-side slice stack: chip + engine + coordinator."""

    def __init__(self, kube, name, slice_id=None, **coord_kw):
        labels = {L.TPU_SLICE_LABEL: slice_id} if slice_id else {}
        kube.add_node(make_node(name, labels=labels))
        self.name = name
        self.chip = FakeChip(path=f"/dev/{name}")
        self.states = []
        # per-member engine bound to this member's own device backend
        self.engine = ModeEngine(
            set_state_label=self.states.append,
            evict_components=False,
            backend=FakeBackend(chips=[self.chip]),
        )
        self.coord = SliceCoordinator(
            kube, name,
            poll_s=0.05, commit_timeout_s=coord_kw.pop("commit_timeout_s", 5),
            hb_ttl_s=coord_kw.pop("hb_ttl_s", 2),
            **coord_kw,
        )

    def apply(self, mode):
        return self.coord.apply_slice_coherent(mode, self.engine)


def test_no_slice_label_falls_back_to_local_flip():
    kube = FakeKube()
    m = SliceMember(kube, "solo")
    assert m.apply("on") is True
    assert m.chip.query_cc_mode() == "on"


def test_slice_flip_is_atomic_across_members():
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in range(4)]
    results = {}

    def run(m):
        results[m.name] = m.apply("on")

    threads = [threading.Thread(target=run, args=(m,)) for m in members]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert all(results[m.name] for m in members)
    assert all(m.chip.query_cc_mode() == "on" for m in members)
    # leader (lexicographically first) committed with an epoch stamp
    leader = kube.get_node("n0")
    commit = leader["metadata"]["annotations"][L.SLICE_COMMIT_ANNOTATION]
    assert commit.startswith("on:")
    # every member recorded consuming that epoch
    for m in members:
        done = kube.get_node(m.name)["metadata"]["annotations"][DONE_ANNOTATION]
        assert done == commit


def test_missing_member_blocks_the_whole_slice():
    # 3 members alive+acking, 1 member's agent never shows up but has a
    # fresh heartbeat (alive, not acked) -> nobody flips; all abort
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a", commit_timeout_s=1.5)
               for i in range(3)]
    # the 4th node exists with a fresh heartbeat but never acks
    kube.add_node(make_node("n3", labels={L.TPU_SLICE_LABEL: "slice-a"}))
    kube.set_node_annotations("n3", {HB_ANNOTATION: str(time.time() + 1000)})

    results = {}

    def run(m):
        try:
            results[m.name] = m.apply("on")
        except SliceAbortError:
            results[m.name] = "aborted"

    threads = [threading.Thread(target=run, args=(m,)) for m in members]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert all(results[m.name] == "aborted" for m in members)
    assert all(m.chip.query_cc_mode() == "off" for m in members)
    # aborted members retracted their acks (review finding: a lingering
    # ack must not let a later leader commit on their behalf)
    for m in members:
        ann = kube.get_node(m.name)["metadata"]["annotations"]
        assert L.SLICE_ACK_ANNOTATION not in ann


def test_dead_member_staleness_excluded_leader_failover():
    # "n0" (the would-be leader) is dead: stale heartbeat -> excluded from
    # liveness, n1 takes leadership and the rest of the slice proceeds
    kube = FakeKube()
    kube.add_node(make_node("n0", labels={L.TPU_SLICE_LABEL: "slice-a"}))
    kube.set_node_annotations("n0", {HB_ANNOTATION: "1.0"})  # ancient
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in (1, 2)]
    results = {}
    threads = [
        threading.Thread(target=lambda m=m: results.update({m.name: m.apply("on")}))
        for m in members
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert all(results.values())
    assert all(m.chip.query_cc_mode() == "on" for m in members)
    # the commit is fenced on the ANCHOR node (n0 — smallest member, even
    # though its agent is dead: the node object still exists), written by
    # the failover leader n1
    ann = kube.get_node("n0")["metadata"]["annotations"]
    commit = ann.get(L.SLICE_COMMIT_ANNOTATION)
    assert commit and commit.startswith("on:")
    assert ann[L.SLICE_LEADER_ANNOTATION] == "n1"  # n1 became leader


def test_per_slice_policy_divergence():
    # two slices in one pool hold different modes (BASELINE config 5)
    kube = FakeKube()
    a = [SliceMember(kube, f"a{i}", "slice-a") for i in range(2)]
    b = [SliceMember(kube, f"b{i}", "slice-b") for i in range(2)]
    results = {}
    threads = [
        threading.Thread(target=lambda m=m, mode=mode: results.update(
            {m.name: m.apply(mode)}))
        for ms, mode in ((a, "on"), (b, "devtools"))
        for m in ms
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert all(results.values())
    assert all(m.chip.query_cc_mode() == "on" for m in a)
    assert all(m.chip.query_cc_mode() == "devtools" for m in b)


def test_stale_commit_from_old_round_is_ignored():
    # review finding: a commit left on a node from an old round (e.g. a
    # returned ex-leader) must never trigger a flip in a later round
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in range(2)]

    def both(mode, expect_ok=True):
        results = {}

        def run(m):
            try:
                results[m.name] = m.apply(mode)
            except SliceAbortError:
                results[m.name] = "aborted"

        ts = [threading.Thread(target=run, args=(m,)) for m in members]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=20)
        return results

    both("on")   # round 1: commit on:<e1> persists on n0
    both("off")  # round 2: done epochs advance past e1
    assert all(m.chip.query_cc_mode() == "off" for m in members)
    # round 3: desired 'on' again. n1 alone (n0's agent "slow"): n1 must
    # NOT flip off the stale on:<e1> commit — its done epoch e2 > e1.
    got = {}

    def run_n1():
        try:
            got["n1"] = members[1].apply("on")
        except SliceAbortError:
            got["n1"] = "aborted"

    members[1].coord.commit_timeout_s = 1.0
    t = threading.Thread(target=run_n1)
    t.start()
    t.join(timeout=10)
    assert got["n1"] == "aborted"  # waited for a FRESH commit; none came
    assert members[1].chip.query_cc_mode() == "off"


def test_shutdown_interrupts_pending_round():
    # review finding: agent shutdown must not block for commit_timeout_s
    kube = FakeKube()
    m = SliceMember(kube, "n0", "slice-a", commit_timeout_s=60)
    # a second member that never acks keeps the round pending
    kube.add_node(make_node("n1", labels={L.TPU_SLICE_LABEL: "slice-a"}))
    kube.set_node_annotations("n1", {HB_ANNOTATION: str(time.time() + 1000)})
    result = {}

    def run():
        t0 = time.monotonic()
        try:
            m.apply("on")
        except SliceAbortError:
            pass
        result["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.5)
    m.coord.stop()
    t.join(timeout=5)
    assert result["elapsed"] < 3  # returned promptly, not after 60s


def test_heartbeat_thread_updates_annotation():
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.TPU_SLICE_LABEL: "s"}))
    coord = SliceCoordinator(kube, "n1", hb_period_s=0.05)
    coord.start()
    try:
        time.sleep(0.3)
        ann = kube.get_node("n1")["metadata"]["annotations"]
        assert HB_ANNOTATION in ann
        first = float(ann[HB_ANNOTATION])
        time.sleep(0.2)
        second = float(
            kube.get_node("n1")["metadata"]["annotations"][HB_ANNOTATION]
        )
        assert second > first
    finally:
        coord.stop()


def test_agent_restart_reapplies_completed_mode_without_quorum():
    # review finding: a routine agent restart re-reconciling the unchanged
    # label must NOT wait for a new slice round (which would never come)
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in range(2)]
    results = {}

    def run(m, mode):
        try:
            results[m.name] = m.apply(mode)
        except SliceAbortError:
            results[m.name] = "aborted"

    ts = [threading.Thread(target=run, args=(m, "on")) for m in members]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=20)
    assert all(results[m.name] is True for m in members)

    # "restart": same member re-applies the already-done mode alone
    t0 = time.monotonic()
    assert members[1].apply("on") is True  # immediate, no coordination
    assert time.monotonic() - t0 < 1.0
    assert members[1].states[-1] == "on"


def test_shutdown_abort_is_flagged():
    kube = FakeKube()
    m = SliceMember(kube, "n0", "slice-a", commit_timeout_s=60)
    kube.add_node(make_node("n1", labels={L.TPU_SLICE_LABEL: "slice-a"}))
    kube.set_node_annotations("n1", {HB_ANNOTATION: str(time.time() + 1000)})
    caught = {}

    def run():
        try:
            m.apply("on")
        except SliceAbortError as e:
            caught["shutting_down"] = e.shutting_down

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.5)
    m.coord.stop()
    t.join(timeout=5)
    assert caught.get("shutting_down") is True


def test_half_flipped_slice_heals_on_retry():
    # VERDICT r1 item 8: a member whose local flip fails AFTER the quorum
    # commit leaves the slice half-flipped; a plain retry (what the
    # agent's self-repair loop does) must converge it with no operator
    # relabeling and no new quorum round.
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in range(3)]
    members[2].chip.fail_set = True  # device fault on one member
    results = {}

    def run(m):
        try:
            results[m.name] = m.apply("on")
        except SliceAbortError:
            results[m.name] = "aborted"

    threads = [threading.Thread(target=run, args=(m,)) for m in members]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    # quorum committed; healthy members flipped, the faulty one failed
    assert results["n0"] is True and results["n1"] is True
    assert results["n2"] is False
    assert members[2].chip.query_cc_mode() == "off"  # half-flipped
    assert members[2].states[-1] == "failed"
    # done was NOT recorded for the laggard, so the commit stays actionable
    ann2 = kube.get_node("n2")["metadata"]["annotations"]
    assert DONE_ANNOTATION not in ann2

    # heal: fault clears, the laggard retries alone — it observes the
    # still-actionable commit on the anchor and converges immediately
    members[2].chip.fail_set = False
    t0 = time.monotonic()
    assert members[2].apply("on") is True
    assert time.monotonic() - t0 < 3
    assert members[2].chip.query_cc_mode() == "on"
    done = kube.get_node("n2")["metadata"]["annotations"][DONE_ANNOTATION]
    commit = kube.get_node("n0")["metadata"]["annotations"][
        L.SLICE_COMMIT_ANNOTATION
    ]
    assert done == commit


class _StaleAnchorKube:
    """Delegating kube whose get_node serves a frozen pre-commit snapshot
    of the anchor — models a dual leader acting on a stale read."""

    def __init__(self, real, stale_anchor):
        self._real = real
        self._stale = stale_anchor

    def get_node(self, name):
        import copy as _copy
        if name == self._stale["metadata"]["name"]:
            return _copy.deepcopy(self._stale)
        return self._real.get_node(name)

    def __getattr__(self, attr):
        return getattr(self._real, attr)


def test_superseded_round_aborts_fast_via_label(monkeypatch):
    """VERDICT r2 item 4: the operator changes the desired label while a
    round is stuck waiting for quorum. The member must abort as
    superseded within a few poll periods — no commit-timeout stall, no
    spurious failure — and retract its ack."""
    kube = FakeKube()
    m1 = SliceMember(kube, "p1", "slice-s", commit_timeout_s=30)
    # alive (fresh heartbeat) but never acks: quorum can't form
    SliceMember(kube, "p2", "slice-s")
    kube.set_node_annotations("p2", {HB_ANNOTATION: str(time.time() + 1000)})

    errs = {}

    def run():
        try:
            m1.apply("on")
        except SliceAbortError as e:
            errs["e"] = e

    t = threading.Thread(target=run)
    t0 = time.monotonic()
    t.start()
    # let the round publish its ack and start waiting
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        ann = kube.get_node("p1")["metadata"].get("annotations", {})
        if ann.get(L.SLICE_ACK_ANNOTATION) == "on":
            break
        time.sleep(0.02)
    # operator changes the desired mode mid-round
    kube.set_node_labels("p1", {L.CC_MODE_LABEL: "devtools"})
    t.join(timeout=5)
    assert not t.is_alive()
    assert time.monotonic() - t0 < 10  # nowhere near commit_timeout_s
    e = errs["e"]
    assert e.superseded is True
    assert "superseded" in str(e)
    # devices untouched, no state label published, ack retracted
    assert m1.chip.query_cc_mode() == "off"
    assert m1.states == []
    ann = kube.get_node("p1")["metadata"].get("annotations", {})
    assert ann.get(L.SLICE_ACK_ANNOTATION) is None


def test_superseded_round_aborts_via_should_abort_callback():
    """The agent wires should_abort to its mailbox: the coordinator must
    poll it and abort without touching devices."""
    kube = FakeKube()
    flagged = threading.Event()
    m1 = SliceMember(kube, "q1", "slice-t", commit_timeout_s=30,
                     should_abort=lambda mode: flagged.is_set())
    SliceMember(kube, "q2", "slice-t")  # alive but never acks
    kube.set_node_annotations("q2", {HB_ANNOTATION: str(time.time() + 1000)})

    errs = {}

    def run():
        try:
            m1.apply("on")
        except SliceAbortError as e:
            errs["e"] = e

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)
    flagged.set()
    t.join(timeout=5)
    assert not t.is_alive()
    assert errs["e"].superseded is True
    assert m1.chip.query_cc_mode() == "off"


def test_agent_superseded_round_reconciles_new_mode_without_failed():
    """Full agent path: label flips mid-round; the agent must never
    publish cc.mode.state=failed, and must converge to the NEW mode
    within a few poll periods once the slice acks it."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    kube = FakeKube()
    m2 = SliceMember(kube, "r2", "slice-u")  # peer, acks later
    # alive from the start, so the "on" round cannot trivially commit
    kube.set_node_annotations("r2", {HB_ANNOTATION: str(time.time() + 1000)})
    # the agent under test runs on r1 (anchor + leader by name order)
    labels = {L.TPU_SLICE_LABEL: "slice-u",
              L.CC_MODE_LABEL: "on"}
    kube.add_node(make_node("r1", labels=labels))
    chip = FakeChip(path="/dev/r1")
    coord = SliceCoordinator(kube, "r1", poll_s=0.05, commit_timeout_s=30,
                             hb_ttl_s=2)
    cfg = AgentConfig(node_name="r1", drain_strategy="none", health_port=0,
                      emit_events=False, emit_evidence=False,
                      repair_interval_s=0)
    agent = CCManagerAgent(kube, cfg, slice_coordinator=coord,
                           backend=FakeBackend(chips=[chip]))
    assert coord.should_abort is not None  # wired to the mailbox

    results = []

    def run():
        # the agent consumed "on" from its mailbox; mid-round the
        # operator flips to devtools
        agent.config_mailbox.set("on")
        agent.config_mailbox.get(timeout=1)
        results.append(("on", agent.reconcile("on")))

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)
    # supersede mid-round: in production the watch feeds both the node
    # label and the mailbox, so update both here
    kube.set_node_labels("r1", {L.CC_MODE_LABEL: "devtools"})
    agent.config_mailbox.set("devtools")
    t.join(timeout=10)
    assert not t.is_alive()
    assert results == [("on", False)]
    assert agent.last_outcome == "superseded"
    # the spurious-failed bug: state label must never read failed
    node_labels = kube.get_node("r1")["metadata"]["labels"]
    assert node_labels.get(L.CC_MODE_STATE_LABEL) != "failed"

    # the new mode then converges normally once the peer acks it
    got, value = agent.config_mailbox.get(timeout=1)
    assert (got, value) == (True, "devtools")

    def peer():
        try:
            m2.apply("devtools")
        except SliceAbortError:
            pass

    pt = threading.Thread(target=peer)
    pt.start()
    assert agent.reconcile("devtools") is True
    pt.join(timeout=10)
    assert chip.query_cc_mode() == "devtools"
    assert kube.get_node("r1")["metadata"]["labels"][
        L.CC_MODE_STATE_LABEL] == "devtools"


def test_already_won_commit_beats_supersession():
    """A commit the slice already won is honored BEFORE any supersession
    abort: peers may flip on that commit in the same poll, so aborting
    would leave the slice mixed. The member must flip to the committed
    mode even though its desired label already shows a newer one."""
    kube = FakeKube()
    m1 = SliceMember(kube, "w1", "slice-w", commit_timeout_s=10)
    SliceMember(kube, "w2", "slice-w")
    # an actionable commit for 'on' is already on the anchor (w1)...
    kube.set_node_annotations("w1", {L.SLICE_COMMIT_ANNOTATION: "on:5"})
    # ...while the desired label has ALREADY moved on to devtools
    kube.set_node_labels("w1", {L.CC_MODE_LABEL: "devtools"})
    assert m1.apply("on") is True  # flips, no superseded abort
    assert m1.chip.query_cc_mode() == "on"
    ann = kube.get_node("w1")["metadata"]["annotations"]
    assert ann[DONE_ANNOTATION] == "on:5"


def test_empty_label_value_does_not_supersede():
    """cc.mode='' resolves to the agent default; it must NOT abort the
    in-flight round for that default as superseded (the round should run
    to its normal outcome — here, a quorum timeout)."""
    kube = FakeKube()
    m1 = SliceMember(kube, "e1", "slice-e", commit_timeout_s=1.0)
    SliceMember(kube, "e2", "slice-e")  # alive but never acks
    kube.set_node_annotations("e2", {HB_ANNOTATION: str(time.time() + 1000)})
    kube.set_node_labels("e1", {L.CC_MODE_LABEL: ""})

    try:
        m1.apply("on")
        assert False, "expected timeout abort"
    except SliceAbortError as e:
        assert e.superseded is False  # a timeout, not a supersession


def test_label_flap_back_to_same_mode_reruns_round(monkeypatch):
    """X->Y->X flap observed mid-round: the agent must abort the round
    (ack was retracted) and immediately RE-RUN mode X — not block on the
    mailbox with X unapplied forever."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    kube = FakeKube()
    kube.add_node(make_node("f1"))
    cfg = AgentConfig(node_name="f1", drain_strategy="none", health_port=0,
                      emit_events=False, emit_evidence=False,
                      repair_interval_s=0)
    agent = CCManagerAgent(kube, cfg, backend=FakeBackend(chips=[]))

    calls = []
    outcomes = iter(["superseded", "success"])

    def fake_reconcile(mode):
        calls.append(mode)
        agent.last_outcome = next(outcomes)
        return agent.last_outcome == "success"

    monkeypatch.setattr(agent, "reconcile", fake_reconcile)
    # the flap already coalesced away: mailbox has nothing pending
    assert agent._reconcile_current("on") is True
    assert calls == ["on", "on"]  # re-ran the SAME mode after the abort

    # and with a pending different mode, the retry uses the new mode
    calls.clear()
    outcomes = iter(["superseded", "success"])
    agent.config_mailbox.set("devtools")
    assert agent._reconcile_current("on") is True
    assert calls == ["on", "devtools"]


def test_pending_peek_is_mode_resolved():
    """A pending label REMOVAL that resolves back to the in-flight mode
    (default) is not a supersession — no churny abort."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    kube = FakeKube()
    kube.add_node(make_node("g1"))
    cfg = AgentConfig(node_name="g1", default_mode="on",
                      drain_strategy="none", health_port=0,
                      emit_events=False, emit_evidence=False)
    agent = CCManagerAgent(kube, cfg, backend=FakeBackend(chips=[]))
    agent.config_mailbox.set("on")
    agent.config_mailbox.get(timeout=1)  # in-flight round consumed "on"

    agent.config_mailbox.set(None)  # label removed -> default "on"
    assert agent._superseded_by_pending("on") is False
    agent.config_mailbox.set("devtools")
    assert agent._superseded_by_pending("on") is True


def test_commit_cas_exactly_one_writer_per_epoch():
    # VERDICT r1 item 7: during a heartbeat-staleness window two members
    # can both believe they are leader. The CAS fence on the anchor must
    # let exactly one commit through per epoch.
    import copy

    kube = FakeKube()
    now = time.time()
    for i in range(3):
        kube.add_node(make_node(f"n{i}", labels={L.TPU_SLICE_LABEL: "s"}))
        kube.set_node_annotations(
            f"n{i}",
            {HB_ANNOTATION: str(now + 1000), L.SLICE_ACK_ANNOTATION: "on"},
        )

    replaces = []
    real_replace = kube.replace_node

    def counting_replace(name, node):
        out = real_replace(name, node)
        replaces.append(name)
        return out

    kube.replace_node = counting_replace

    c0 = SliceCoordinator(kube, "n0")
    members = c0.members("s")
    stale_anchor = copy.deepcopy(kube.get_node("n0"))
    stale_members = copy.deepcopy(members)

    # leader n0 commits from a fresh view
    c0._maybe_commit("on", members, members)
    ann = kube.get_node("n0")["metadata"]["annotations"]
    commit1 = ann[L.SLICE_COMMIT_ANNOTATION]
    assert commit1.startswith("on:")
    assert ann[L.SLICE_LEADER_ANNOTATION] == "n0"
    assert len(replaces) == 1

    # dual leader n1 acts on the PRE-COMMIT snapshot: its CAS must lose
    # (409) and leave the winner's commit untouched
    c1 = SliceCoordinator(_StaleAnchorKube(kube, stale_anchor), "n1")
    c1._maybe_commit("on", stale_members, stale_members)
    ann = kube.get_node("n0")["metadata"]["annotations"]
    assert ann[L.SLICE_COMMIT_ANNOTATION] == commit1  # winner intact
    assert ann[L.SLICE_LEADER_ANNOTATION] == "n0"
    assert len(replaces) == 1  # no second successful write

    # a FRESH-view leader with the round already actionable writes nothing
    c2 = SliceCoordinator(kube, "n1")
    c2._maybe_commit("on", c2.members("s"), c2.members("s"))
    assert len(replaces) == 1


def test_commit_cas_churn_many_concurrent_leaders():
    # heartbeat-churn stress: many would-be leaders race one round; the
    # anchor must end with exactly one commit epoch and one leader, and
    # every successful write must be CAS-serialized (no lost updates).
    kube = FakeKube()
    now = time.time()
    n = 6
    for i in range(n):
        kube.add_node(make_node(f"n{i}", labels={L.TPU_SLICE_LABEL: "s"}))
        kube.set_node_annotations(
            f"n{i}",
            {HB_ANNOTATION: str(now + 1000), L.SLICE_ACK_ANNOTATION: "on"},
        )
    wrote = []
    real_replace = kube.replace_node

    def counting_replace(name, node):
        out = real_replace(name, node)
        wrote.append(node["metadata"]["annotations"][L.SLICE_COMMIT_ANNOTATION])
        return out

    kube.replace_node = counting_replace

    coords = [SliceCoordinator(kube, f"n{i}") for i in range(n)]

    def race(c):
        members = c.members("s")
        c._maybe_commit("on", members, members)

    threads = [threading.Thread(target=race, args=(c,)) for c in coords]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    # exactly one commit epoch survives on the anchor...
    ann = kube.get_node("n0")["metadata"]["annotations"]
    final = ann[L.SLICE_COMMIT_ANNOTATION]
    assert final.startswith("on:")
    # ...and every write that succeeded carried the SAME mode; successful
    # writers were serialized by CAS, each with a strictly newer epoch
    assert all(w.startswith("on:") for w in wrote)
    epochs = [int(w.rpartition(":")[2]) for w in wrote]
    assert epochs == sorted(set(epochs))
    assert final == wrote[-1]
