"""Slice-coherence protocol tests: atomic multi-host flips, leader
failover, timeout-refuses-to-flip, and per-slice policy divergence."""

import threading
import time

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.fake import FakeBackend, FakeChip
from tpu_cc_manager.engine import ModeEngine
from tpu_cc_manager.k8s import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.slice_coord import (
    DONE_ANNOTATION,
    HB_ANNOTATION,
    SliceAbortError,
    SliceCoordinator,
)


class SliceMember:
    """One node's agent-side slice stack: chip + engine + coordinator."""

    def __init__(self, kube, name, slice_id=None, **coord_kw):
        labels = {L.TPU_SLICE_LABEL: slice_id} if slice_id else {}
        kube.add_node(make_node(name, labels=labels))
        self.name = name
        self.chip = FakeChip(path=f"/dev/{name}")
        self.states = []
        # per-member engine bound to this member's own device backend
        self.engine = ModeEngine(
            set_state_label=self.states.append,
            evict_components=False,
            backend=FakeBackend(chips=[self.chip]),
        )
        self.coord = SliceCoordinator(
            kube, name,
            poll_s=0.05, commit_timeout_s=coord_kw.pop("commit_timeout_s", 5),
            hb_ttl_s=coord_kw.pop("hb_ttl_s", 2),
            **coord_kw,
        )

    def apply(self, mode):
        return self.coord.apply_slice_coherent(mode, self.engine)


def test_no_slice_label_falls_back_to_local_flip():
    kube = FakeKube()
    m = SliceMember(kube, "solo")
    assert m.apply("on") is True
    assert m.chip.query_cc_mode() == "on"


def test_slice_flip_is_atomic_across_members():
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in range(4)]
    results = {}

    def run(m):
        results[m.name] = m.apply("on")

    threads = [threading.Thread(target=run, args=(m,)) for m in members]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert all(results[m.name] for m in members)
    assert all(m.chip.query_cc_mode() == "on" for m in members)
    # leader (lexicographically first) committed with an epoch stamp
    leader = kube.get_node("n0")
    commit = leader["metadata"]["annotations"][L.SLICE_COMMIT_ANNOTATION]
    assert commit.startswith("on:")
    # every member recorded consuming that epoch
    for m in members:
        done = kube.get_node(m.name)["metadata"]["annotations"][DONE_ANNOTATION]
        assert done == commit


def test_missing_member_blocks_the_whole_slice():
    # 3 members alive+acking, 1 member's agent never shows up but has a
    # fresh heartbeat (alive, not acked) -> nobody flips; all abort
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a", commit_timeout_s=1.5)
               for i in range(3)]
    # the 4th node exists with a fresh heartbeat but never acks
    kube.add_node(make_node("n3", labels={L.TPU_SLICE_LABEL: "slice-a"}))
    kube.set_node_annotations("n3", {HB_ANNOTATION: str(time.time() + 1000)})

    results = {}

    def run(m):
        try:
            results[m.name] = m.apply("on")
        except SliceAbortError:
            results[m.name] = "aborted"

    threads = [threading.Thread(target=run, args=(m,)) for m in members]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert all(results[m.name] == "aborted" for m in members)
    assert all(m.chip.query_cc_mode() == "off" for m in members)
    # aborted members retracted their acks (review finding: a lingering
    # ack must not let a later leader commit on their behalf)
    for m in members:
        ann = kube.get_node(m.name)["metadata"]["annotations"]
        assert L.SLICE_ACK_ANNOTATION not in ann


def test_dead_member_staleness_excluded_leader_failover():
    # "n0" (the would-be leader) is dead: stale heartbeat -> excluded from
    # liveness, n1 takes leadership and the rest of the slice proceeds
    kube = FakeKube()
    kube.add_node(make_node("n0", labels={L.TPU_SLICE_LABEL: "slice-a"}))
    kube.set_node_annotations("n0", {HB_ANNOTATION: "1.0"})  # ancient
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in (1, 2)]
    results = {}
    threads = [
        threading.Thread(target=lambda m=m: results.update({m.name: m.apply("on")}))
        for m in members
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert all(results.values())
    assert all(m.chip.query_cc_mode() == "on" for m in members)
    commit = kube.get_node("n1")["metadata"]["annotations"].get(
        L.SLICE_COMMIT_ANNOTATION
    )
    assert commit and commit.startswith("on:")  # n1 became leader


def test_per_slice_policy_divergence():
    # two slices in one pool hold different modes (BASELINE config 5)
    kube = FakeKube()
    a = [SliceMember(kube, f"a{i}", "slice-a") for i in range(2)]
    b = [SliceMember(kube, f"b{i}", "slice-b") for i in range(2)]
    results = {}
    threads = [
        threading.Thread(target=lambda m=m, mode=mode: results.update(
            {m.name: m.apply(mode)}))
        for ms, mode in ((a, "on"), (b, "devtools"))
        for m in ms
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert all(results.values())
    assert all(m.chip.query_cc_mode() == "on" for m in a)
    assert all(m.chip.query_cc_mode() == "devtools" for m in b)


def test_stale_commit_from_old_round_is_ignored():
    # review finding: a commit left on a node from an old round (e.g. a
    # returned ex-leader) must never trigger a flip in a later round
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in range(2)]

    def both(mode, expect_ok=True):
        results = {}

        def run(m):
            try:
                results[m.name] = m.apply(mode)
            except SliceAbortError:
                results[m.name] = "aborted"

        ts = [threading.Thread(target=run, args=(m,)) for m in members]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=20)
        return results

    both("on")   # round 1: commit on:<e1> persists on n0
    both("off")  # round 2: done epochs advance past e1
    assert all(m.chip.query_cc_mode() == "off" for m in members)
    # round 3: desired 'on' again. n1 alone (n0's agent "slow"): n1 must
    # NOT flip off the stale on:<e1> commit — its done epoch e2 > e1.
    got = {}

    def run_n1():
        try:
            got["n1"] = members[1].apply("on")
        except SliceAbortError:
            got["n1"] = "aborted"

    members[1].coord.commit_timeout_s = 1.0
    t = threading.Thread(target=run_n1)
    t.start()
    t.join(timeout=10)
    assert got["n1"] == "aborted"  # waited for a FRESH commit; none came
    assert members[1].chip.query_cc_mode() == "off"


def test_shutdown_interrupts_pending_round():
    # review finding: agent shutdown must not block for commit_timeout_s
    kube = FakeKube()
    m = SliceMember(kube, "n0", "slice-a", commit_timeout_s=60)
    # a second member that never acks keeps the round pending
    kube.add_node(make_node("n1", labels={L.TPU_SLICE_LABEL: "slice-a"}))
    kube.set_node_annotations("n1", {HB_ANNOTATION: str(time.time() + 1000)})
    result = {}

    def run():
        t0 = time.monotonic()
        try:
            m.apply("on")
        except SliceAbortError:
            pass
        result["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.5)
    m.coord.stop()
    t.join(timeout=5)
    assert result["elapsed"] < 3  # returned promptly, not after 60s


def test_heartbeat_thread_updates_annotation():
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={L.TPU_SLICE_LABEL: "s"}))
    coord = SliceCoordinator(kube, "n1", hb_period_s=0.05)
    coord.start()
    try:
        time.sleep(0.3)
        ann = kube.get_node("n1")["metadata"]["annotations"]
        assert HB_ANNOTATION in ann
        first = float(ann[HB_ANNOTATION])
        time.sleep(0.2)
        second = float(
            kube.get_node("n1")["metadata"]["annotations"][HB_ANNOTATION]
        )
        assert second > first
    finally:
        coord.stop()


def test_agent_restart_reapplies_completed_mode_without_quorum():
    # review finding: a routine agent restart re-reconciling the unchanged
    # label must NOT wait for a new slice round (which would never come)
    kube = FakeKube()
    members = [SliceMember(kube, f"n{i}", "slice-a") for i in range(2)]
    results = {}

    def run(m, mode):
        try:
            results[m.name] = m.apply(mode)
        except SliceAbortError:
            results[m.name] = "aborted"

    ts = [threading.Thread(target=run, args=(m, "on")) for m in members]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=20)
    assert all(results[m.name] is True for m in members)

    # "restart": same member re-applies the already-done mode alone
    t0 = time.monotonic()
    assert members[1].apply("on") is True  # immediate, no coordination
    assert time.monotonic() - t0 < 1.0
    assert members[1].states[-1] == "on"


def test_shutdown_abort_is_flagged():
    kube = FakeKube()
    m = SliceMember(kube, "n0", "slice-a", commit_timeout_s=60)
    kube.add_node(make_node("n1", labels={L.TPU_SLICE_LABEL: "slice-a"}))
    kube.set_node_annotations("n1", {HB_ANNOTATION: str(time.time() + 1000)})
    caught = {}

    def run():
        try:
            m.apply("on")
        except SliceAbortError as e:
            caught["shutting_down"] = e.shutting_down

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.5)
    m.coord.stop()
    t.join(timeout=5)
    assert caught.get("shutting_down") is True
