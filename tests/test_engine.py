"""L1 mode engine tests — the state machine the reference never tested.

Each test pins one behavior documented in SURVEY.md §2.5/§3.4 with its
reference file:line.
"""

import pytest

from tpu_cc_manager.device.base import set_backend
from tpu_cc_manager.device.fake import FakeBackend, FakeChip, fake_backend
from tpu_cc_manager.engine import FatalModeError, ModeEngine, Drainer
from tpu_cc_manager.modes import InvalidModeError


class RecordingDrainer(Drainer):
    def __init__(self):
        self.events = []

    def evict(self):
        self.events.append("evict")

    def reschedule(self):
        self.events.append("reschedule")


class Harness:
    def __init__(self, backend, evict=True):
        set_backend(backend)
        self.backend = backend
        self.states = []
        self.drainer = RecordingDrainer()
        self.engine = ModeEngine(
            set_state_label=self.states.append,
            drainer=self.drainer,
            evict_components=evict,
        )


def test_set_mode_on_full_cycle():
    h = Harness(fake_backend(n_chips=4))
    assert h.engine.set_mode("on") is True
    for c in h.backend.chips:
        assert c.query_cc_mode() == "on"
        assert c.resets == 1
    assert h.states == ["on"]  # observed-state label (main.py:310)
    assert h.drainer.events == ["evict", "reschedule"]


def test_idempotent_fast_path_no_device_work():
    # all chips already at target -> no set/reset, state still published
    # (reference main.py:227-230; scripts/cc-manager.sh:342-346)
    h = Harness(fake_backend(n_chips=2, cc_mode="on"))
    assert h.engine.set_mode("on") is True
    for c in h.backend.chips:
        assert c.sets == 0 and c.resets == 0
    assert h.states == ["on"]
    assert h.drainer.events == []  # no eviction on fast path


def test_zero_devices_is_success():
    # 0 capable devices -> success, nothing to do (cc-manager.sh:338-340)
    h = Harness(FakeBackend(chips=[]))
    assert h.engine.set_mode("on") is True
    assert h.states == []


def test_mixed_capability_bailout_is_fatal():
    chips = [FakeChip(path="/dev/accel0"), FakeChip(path="/dev/accel1", cc_capable=False, ici_capable=False)]
    h = Harness(FakeBackend(chips=chips))
    # protected mode on a mixed node -> hard abort (main.py:214-217)
    with pytest.raises(FatalModeError):
        h.engine.set_mode("on")
    # but mode off is allowed on a mixed node
    assert h.engine.set_mode("off") is True


def test_invalid_mode_rejected():
    h = Harness(fake_backend(n_chips=1))
    with pytest.raises(InvalidModeError):
        h.engine.set_mode("enabled")


def test_device_failure_sets_failed_state_and_restores_components():
    h = Harness(fake_backend(n_chips=2))
    h.backend.chips[1].fail_set = True
    assert h.engine.set_mode("on") is False
    assert h.states == ["failed"]  # main.py:300-307
    # never-leave-drained invariant (cc-manager.sh:210-215)
    assert h.drainer.events == ["evict", "reschedule"]


def test_verify_mismatch_fails():
    h = Harness(fake_backend(n_chips=1))
    h.backend.chips[0].drop_staged_mode = True
    assert h.engine.set_mode("devtools") is False
    assert h.states == ["failed"]  # main.py:291-296


def test_boot_timeout_fails():
    h = Harness(fake_backend(n_chips=1))
    h.backend.chips[0].fail_boot = True
    assert h.engine.set_mode("on") is False
    assert h.states == ["failed"]


def test_ici_mode_covers_switches_and_forces_cc_off():
    h = Harness(fake_backend(n_chips=2, n_switches=1, cc_mode="on"))
    assert h.engine.set_mode("ici") is True
    for c in h.backend.chips:
        if not c.is_ici_switch():
            assert c.query_cc_mode() == "off"  # mutual exclusion (main.py:512-532)
        assert c.query_ici_mode() == "on"
    assert h.states[-1] == "ici"


def test_cc_mode_forces_ici_off():
    h = Harness(fake_backend(n_chips=2, ici_mode="on"))
    assert h.engine.set_mode("on") is True
    for c in h.backend.chips:
        assert c.query_ici_mode() == "off"  # main.py:534-559
        assert c.query_cc_mode() == "on"
    assert h.states[-1] == "on"


def test_off_disables_both_domains():
    h = Harness(fake_backend(n_chips=2, cc_mode="on", ici_mode="on"))
    assert h.engine.set_mode("off") is True
    for c in h.backend.chips:
        assert c.query_cc_mode() == "off"
        assert c.query_ici_mode() == "off"
    assert h.states[-1] == "off"  # main.py:561-583


def test_evict_components_false_skips_drain():
    # EVICT_OPERATOR_COMPONENTS=false analog (main.py:94-96,232-235)
    h = Harness(fake_backend(n_chips=1), evict=False)
    assert h.engine.set_mode("on") is True
    assert h.drainer.events == []


def test_get_modes_reports_all_domains():
    h = Harness(fake_backend(n_chips=1, n_switches=1))
    modes = h.engine.get_modes()
    assert modes["/dev/accel0"] == {"cc": "off", "ici": "off"}
    # find_tpus returns switches too; switch reports only ici
    assert modes["/dev/ici-switch0"] == {"ici": "off"}


def test_partial_failure_aborts_node_flip(monkeypatch):
    # SERIAL loop (TPU_CC_FLIP_CONCURRENCY=1): first chip flips, second
    # fails -> whole node reports failed, and the engine stops (no
    # attempt to continue past the failure). The parallel executor's
    # failure semantics — in-flight siblings complete, queued items are
    # skipped untouched — are pinned in test_engine_parallel.py.
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    h = Harness(fake_backend(n_chips=3))
    h.backend.chips[1].fail_reset = True
    assert h.engine.set_mode("on") is False
    assert h.backend.chips[2].sets == 0
    assert h.states == ["failed"]
