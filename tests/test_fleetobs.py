"""fleetobs (ISSUE 9): exposition parse/merge/re-render validity, the
SLO schema, burn-rate math, multi-window alerting, and the
flight-recorder alert event."""

import time

import pytest

from tpu_cc_manager.flightrec import FlightRecorder
from tpu_cc_manager.obs import Metrics, validate_exposition
from tpu_cc_manager.fleetobs import (
    FleetObserver,
    SloError,
    SloObjective,
    load_slo,
    merge_snapshots,
    parse_exposition,
    render_snapshot,
    validate_slo_doc,
)


def _objective(**kw):
    base = dict(
        name="flip-success", kind="error_ratio",
        metric="tpu_cc_reconciles_total",
        bad_labels=(("outcome", ("failure", "error")),),
        target=0.99, fast_window_s=2.0, slow_window_s=10.0,
        burn_threshold=2.0,
    )
    base.update(kw)
    return SloObjective(**base)


def _metrics(success=0, failure=0, durations=()):
    m = Metrics()
    for _ in range(success):
        m.reconciles_total.inc("success")
    for _ in range(failure):
        m.reconciles_total.inc("failure")
    for d in durations:
        m.reconcile_duration.observe(d)
    return m


# ------------------------------------------------------- parse and merge
def test_parse_roundtrips_a_real_metric_set():
    m = _metrics(success=3, failure=1, durations=(0.2, 0.4))
    snap, helps = parse_exposition(m.render())
    assert snap["tpu_cc_reconciles_total"]["series"][
        'outcome="success"'] == 3
    hist = snap["tpu_cc_reconcile_duration_seconds"]["hist"][""]
    assert hist["count"] == 2
    assert hist["buckets"]["+Inf"] == 2
    assert "tpu_cc_reconciles_total" in helps


def test_merged_fleet_exposition_validates_at_scale():
    """ISSUE 9 satellite: merging MANY replicas must yield an
    exposition with no duplicate series and monotone buckets — checked
    by the same strict validator every live /metrics passes."""
    sources = [
        _metrics(success=i % 5, failure=i % 3,
                 durations=(0.01 * i, 0.5))
        for i in range(64)
    ]
    snaps = [parse_exposition(m.render())[0] for m in sources]
    merged = merge_snapshots(snaps)
    text = render_snapshot(merged)
    assert validate_exposition(text) == []
    # counters summed fleet-wide
    total = sum(i % 5 for i in range(64))
    assert merged["tpu_cc_reconciles_total"]["series"][
        'outcome="success"'] == total
    hist = merged["tpu_cc_reconcile_duration_seconds"]["hist"][""]
    assert hist["count"] == 128
    assert hist["buckets"]["+Inf"] == 128


def test_merge_survives_bucket_layout_drift():
    """Replicas from two code versions may expose different bucket
    ladders; the carry-forward merge must stay monotone (and
    therefore valid) across the union of bounds."""
    a = parse_exposition(
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="0.1"} 2\nh_bucket{le="1"} 5\n'
        'h_bucket{le="+Inf"} 6\nh_sum 3.0\nh_count 6\n'
    )[0]
    b = parse_exposition(
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="0.5"} 3\nh_bucket{le="+Inf"} 4\n'
        "h_sum 1.5\nh_count 4\n"
    )[0]
    merged = merge_snapshots([a, b])
    assert validate_exposition(render_snapshot(merged)) == []
    assert merged["h"]["hist"][""]["buckets"]["+Inf"] == 10


def test_merge_survives_type_drift_under_one_name():
    """A counter and a histogram under one family name (two code
    versions in one fleet): first seen wins, the drifted input is
    skipped — never a crash, and the merge still validates."""
    a = parse_exposition("# HELP h x\n# TYPE h counter\nh 3\n")[0]
    b = parse_exposition(
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="+Inf"} 4\nh_sum 1.5\nh_count 4\n'
    )[0]
    for order in ([a, b], [b, a]):
        merged = merge_snapshots(order)
        assert validate_exposition(render_snapshot(merged)) == []


def test_observer_skips_invalid_scrape_and_counts_it():
    good = _metrics(success=2)
    bad = "# HELP a x\n# TYPE a gauge\na 1\na 2\n"  # duplicate series
    obs = FleetObserver([_objective()])
    merged = obs.observe([good.render, lambda: bad, lambda: 1 / 0])
    assert merged["tpu_cc_reconciles_total"]["series"][
        'outcome="success"'] == 2
    assert obs.metrics.scrapes_total.value("ok") == 1
    assert obs.metrics.scrapes_total.value("invalid") == 1
    assert obs.metrics.scrapes_total.value("unreachable") == 1
    assert obs.aggregation_problems == []


# ------------------------------------------------------------ slo schema
def test_slo_schema_accepts_the_committed_file():
    yaml = pytest.importorskip("yaml")
    from tpu_cc_manager.fleetobs import default_slo_path

    objectives = load_slo(default_slo_path())
    names = {o.name for o in objectives}
    assert {"flip-success", "reconcile-latency", "publish-loss"} <= names
    for o in objectives:
        assert 0 < o.target < 1
        assert o.fast_window_s < o.slow_window_s


def test_slo_schema_rejections():
    def errs(doc):
        return validate_slo_doc(doc)[1]

    assert errs([])  # not a mapping
    assert errs({"version": 2, "objectives": []})
    base = {
        "name": "x", "kind": "error_ratio",
        "metric": "tpu_cc_reconciles_total",
        "bad_labels": {"outcome": ["failure"]},
        "target": 0.99, "windows": {"fast_s": 2, "slow_s": 10},
        "burn_threshold": 2.0,
    }
    ok_doc = {"version": 1, "objectives": [base]}
    objectives, errors = validate_slo_doc(ok_doc)
    assert errors == [] and len(objectives) == 1

    def variant(**kw):
        o = dict(base)
        o.update(kw)
        return {"version": 1, "objectives": [o]}

    assert any("unknown key" in e for e in errs(variant(bogus=1)))
    assert errs(variant(target=1.5))
    assert errs(variant(windows={"fast_s": 10, "slow_s": 2}))
    assert errs(variant(burn_threshold=0.5))
    # booleans are int subclasses; `fast_s: true` must not validate
    # as a 1-second window (same stance the scenario schema takes)
    assert errs(variant(windows={"fast_s": True, "slow_s": 10}))
    assert errs(variant(burn_threshold=True))
    assert errs(variant(target=True))
    assert errs(variant(kind="latency"))  # latency needs threshold_s
    assert errs(variant(threshold_s=1.0))  # only for latency
    assert errs(variant(kind="nope"))
    # error_ratio with NEITHER bad_labels nor total_metric
    o = dict(base)
    del o["bad_labels"]
    assert errs({"version": 1, "objectives": [o]})
    # duplicate names
    assert any("duplicate" in e
               for e in errs({"version": 1, "objectives": [base, base]}))


def test_load_slo_raises_on_bad_file(tmp_path):
    pytest.importorskip("yaml")
    p = tmp_path / "slo.yaml"
    p.write_text("version: 1\nobjectives:\n  - name: x\n")
    with pytest.raises(SloError):
        load_slo(str(p))


# ------------------------------------------------------------- burn math
def test_burn_rate_rises_under_failures_and_alert_fires():
    rec = FlightRecorder(name="obs-test")
    obs = FleetObserver(
        [_objective(burn_threshold=2.0)], recorder=rec,
    )
    m = _metrics(success=10)
    t0 = time.time()
    obs.observe([m.render], now=t0)
    # a clean second sample: no burn
    for _ in range(5):
        m.reconciles_total.inc("success")
    obs.observe([m.render], now=t0 + 1)
    assert obs.metrics.burn_rate.value("flip-success", "fast") == 0.0
    assert obs.alerts == []
    # failure storm: 50% bad over the window -> burn 50/1% = 50x
    for _ in range(10):
        m.reconciles_total.inc("failure")
        m.reconciles_total.inc("success")
    obs.observe([m.render], now=t0 + 2)
    fast = obs.metrics.burn_rate.value("flip-success", "fast")
    assert fast > 2.0
    assert len(obs.alerts) == 1
    alert = obs.alerts[0]
    assert alert["objective"] == "flip-success"
    # the alert event landed in the flight recorder's black box
    events = rec.snapshot("test")["events"]
    assert any(e["kind"] == "slo_burn"
               and e["objective"] == "flip-success" for e in events)
    # budget burned below 1.0
    assert obs.metrics.budget_remaining.value("flip-success") < 1.0
    # problems line while firing
    assert any("flip-success" in p for p in obs.problems())
    # still firing: no duplicate alert entry
    for _ in range(4):
        m.reconciles_total.inc("failure")
    obs.observe([m.render], now=t0 + 3)
    assert len(obs.alerts) == 1
    # recovery: clean traffic drives the fast window under threshold
    for _ in range(400):
        m.reconciles_total.inc("success")
    obs.observe([m.render], now=t0 + 30)
    assert not obs._firing["flip-success"]
    assert obs.problems() == []


def test_clean_run_burns_no_budget():
    """The acceptance pin's unit half: all-success traffic leaves every
    budget untouched and fires nothing."""
    obs = FleetObserver([
        _objective(),
        _objective(name="latency", kind="latency",
                   bad_labels=(), threshold_s=2.5, target=0.9,
                   metric="tpu_cc_reconcile_duration_seconds"),
    ])
    m = _metrics(success=5, durations=(0.1, 0.2))
    t0 = time.time()
    for i in range(4):
        m.reconciles_total.inc("success")
        m.reconcile_duration.observe(0.05)
        obs.observe([m.render], now=t0 + i)
    assert obs.alerts == []
    assert obs.metrics.budget_remaining.value("flip-success") == 1.0
    assert obs.metrics.budget_remaining.value("latency") == 1.0
    assert obs.problems() == []


def test_budget_judges_the_retained_span_not_process_lifetime():
    """Counters are cumulative; the budget must be charged only for
    events INSIDE the observer's retained span — failures from before
    it started watching (simlab's initial-convergence traffic, an
    incident before a restart of the observer) never depress the
    gauge."""
    obs = FleetObserver([_objective()])
    # 5 failures happened BEFORE the first observation
    m = _metrics(success=10, failure=5)
    t0 = time.time()
    obs.observe([m.render], now=t0)
    for _ in range(10):
        m.reconciles_total.inc("success")
    obs.observe([m.render], now=t0 + 1)
    assert obs.metrics.budget_remaining.value("flip-success") == 1.0
    assert obs.alerts == []
    # failures inside the span DO charge it
    for _ in range(10):
        m.reconciles_total.inc("failure")
    obs.observe([m.render], now=t0 + 2)
    assert obs.metrics.budget_remaining.value("flip-success") < 1.0


def test_latency_objective_counts_observations_over_threshold():
    obs = FleetObserver([
        _objective(name="lat", kind="latency", bad_labels=(),
                   threshold_s=2.5, target=0.5,
                   metric="tpu_cc_reconcile_duration_seconds",
                   burn_threshold=1.5),
    ])
    m = _metrics(durations=(0.1,))
    t0 = time.time()
    obs.observe([m.render], now=t0)
    for _ in range(10):
        m.reconcile_duration.observe(30.0)  # way over threshold
    obs.observe([m.render], now=t0 + 1)
    fast = obs.metrics.burn_rate.value("lat", "fast")
    # 10/10 bad over the window against a 50% budget -> 2x
    assert fast == pytest.approx(2.0)
    assert len(obs.alerts) == 1


def test_error_ratio_with_separate_total_metric():
    obs = FleetObserver([
        _objective(name="publish-loss", bad_labels=(),
                   metric="tpu_cc_publications_dropped_total",
                   total_metric="tpu_cc_reconciles_total",
                   target=0.9, burn_threshold=1.5),
    ])
    m = _metrics(success=10)
    t0 = time.time()
    obs.observe([m.render], now=t0)
    for _ in range(10):
        m.reconciles_total.inc("success")
    m.publications_dropped_total.inc("evidence", amount=5.0)
    obs.observe([m.render], now=t0 + 1)
    # 5 drops / 10 reconciles in window = 50% bad vs 10% budget -> 5x
    assert obs.metrics.burn_rate.value(
        "publish-loss", "fast") == pytest.approx(5.0)


def test_kind_metric_type_mismatch_is_a_dead_objective_problem():
    """Schema-valid but type-wrong (error_ratio over a histogram
    family): the objective evaluates to a permanent 0 — the
    alert-that-can-never-fire class. The first evaluation must record
    it and surface a problems line, never stay silent."""
    obs = FleetObserver([
        _objective(name="dead", bad_labels=(),
                   metric="tpu_cc_reconcile_duration_seconds",
                   total_metric="tpu_cc_reconciles_total"),
    ])
    m = _metrics(success=3, durations=(0.2,))
    t0 = time.time()
    obs.observe([m.render], now=t0)
    obs.observe([m.render], now=t0 + 1)
    assert any("dead" in p and "can never fire" in p
               for p in obs.problems())
    assert "dead" in obs.summary()["misconfigured"]
    # and the inverse: latency over a counter family
    obs2 = FleetObserver([
        _objective(name="dead2", kind="latency", bad_labels=(),
                   threshold_s=1.0, target=0.9,
                   metric="tpu_cc_reconciles_total"),
    ])
    obs2.observe([m.render], now=t0)
    assert any("dead2" in p for p in obs2.problems())


def test_fleet_controller_surfaces_observer():
    """Wiring half (fleet.py): a burning SLO joins the report's
    problems digest + /report gains the slo status block, and the
    rollup serves on /fleet/metrics (a separate route — concatenating
    it with the controller's own set would duplicate agent families)."""
    import urllib.request

    from tpu_cc_manager import labels as L
    from tpu_cc_manager.fleet import FleetController
    from tpu_cc_manager.k8s.fake import FakeKube
    from tpu_cc_manager.k8s.objects import make_node

    obs = FleetObserver([_objective(burn_threshold=1.5)])
    m = _metrics(success=10)
    t0 = time.time()
    obs.observe([m.render], now=t0)
    for _ in range(10):
        m.reconciles_total.inc("failure")
    obs.observe([m.render], now=t0 + 1)
    assert obs.problems()

    kube = FakeKube()
    kube.add_node(make_node("n1", labels={
        L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on",
    }))
    ctrl = FleetController(kube, port=0, observer=obs)
    report = ctrl.scan_once()
    assert any("SLO flip-success burning" in p
               for p in report["problems"])
    assert report["slo"]["flip-success"]["burning"] is True
    ctrl._server.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{ctrl.port}/fleet/metrics", timeout=5
        ).read().decode()
    finally:
        ctrl._server.stop()
    assert validate_exposition(body) == []
    assert "tpu_cc_slo_burn_rate" in body
    assert "tpu_cc_reconciles_total" in body  # the merged rollup


def test_observer_render_is_a_valid_exposition():
    obs = FleetObserver([_objective()])
    sources = [_metrics(success=3, durations=(0.2,)).render
               for _ in range(8)]
    obs.observe(sources)
    assert validate_exposition(obs.render()) == []
    assert "tpu_cc_slo_budget_remaining" in obs.render()
