"""Deployment-manifest invariants (VERDICT r3 weak #1 and #6).

These are pure-YAML checks — no cluster — guarding the sharp edges the
manifests shipped with in round 3:

- EVIDENCE-KEY SYMMETRY: every workload that *publishes* evidence (the
  three agent DaemonSets) and every workload that *verifies* it (policy
  and fleet controllers) must mount the same optional
  ``tpu-cc-evidence-key`` Secret and point ``TPU_CC_EVIDENCE_KEY_FILE``
  at it. The no-downgrade rule (evidence.py verify_evidence) makes a
  keyed verifier reject unsigned documents — so a manifest set where
  only the verifier holds the key bricks every rollout the moment the
  Secret is created. That asymmetry shipped once; this test keeps it
  from shipping again.
"""

import glob
import os

import pytest

# PyYAML is not one of the pinned dev deps (requirements-dev.txt): the
# whole file skips, rather than erroring at collection, where it is
# absent — same posture as the inline imports in test_agent/test_modes
yaml = pytest.importorskip("yaml")

MANIFEST_DIR = os.path.join(
    os.path.dirname(__file__), "..", "deployments", "manifests"
)

EVIDENCE_SECRET = "tpu-cc-evidence-key"
EVIDENCE_KEY_ENV = "TPU_CC_EVIDENCE_KEY_FILE"

# workloads touching evidence: name -> (file, kind)
EVIDENCE_WORKLOADS = {
    "tpu-cc-manager": ("daemonset.yaml", "DaemonSet"),
    "tpu-cc-manager-native": ("daemonset-native.yaml", "DaemonSet"),
    "tpu-cc-manager-native-tls": ("daemonset-native-tls.yaml", "DaemonSet"),
    "tpu-policy-controller": ("policy-controller.yaml", "Deployment"),
    "tpu-fleet-controller": ("fleet-controller.yaml", "Deployment"),
}


def _load(fname):
    with open(os.path.join(MANIFEST_DIR, fname)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def _find(docs, kind, name):
    for d in docs:
        if d.get("kind") == kind and d["metadata"]["name"] == name:
            return d
    raise AssertionError(f"{kind}/{name} not found")


def _pod_spec(workload):
    return workload["spec"]["template"]["spec"]


def test_all_manifests_parse():
    files = sorted(glob.glob(os.path.join(MANIFEST_DIR, "*.yaml")))
    assert files, "no manifests found"
    for path in files:
        with open(path) as f:
            docs = [d for d in yaml.safe_load_all(f) if d]
        assert docs, f"{path} parsed to nothing"
        for d in docs:
            assert "kind" in d and "metadata" in d, path


@pytest.mark.parametrize("name", sorted(EVIDENCE_WORKLOADS))
def test_evidence_key_symmetry(name):
    fname, kind = EVIDENCE_WORKLOADS[name]
    spec = _pod_spec(_find(_load(fname), kind, name))

    key_vols = [
        v for v in spec.get("volumes", [])
        if (v.get("secret") or {}).get("secretName") == EVIDENCE_SECRET
    ]
    assert key_vols, (
        f"{fname}: {kind}/{name} does not mount the {EVIDENCE_SECRET} "
        "Secret — unkeyed publishers/verifiers break the fleet the "
        "moment the Secret exists (no-downgrade rule)"
    )
    secret_vols = [v["name"] for v in key_vols]
    vol_entry = key_vols[0]
    assert vol_entry["secret"].get("optional") is True, (
        f"{fname}: the evidence-key Secret must be optional — pods must "
        "start on clusters that have not created it"
    )

    # the main container (not the proxy sidecar) wires env + mount
    containers = spec["containers"]
    main = containers[0]
    env = {e["name"]: e.get("value") for e in main.get("env", [])}
    assert EVIDENCE_KEY_ENV in env, (
        f"{fname}: container {main['name']} lacks {EVIDENCE_KEY_ENV}"
    )
    key_path = env[EVIDENCE_KEY_ENV]
    mounts = main.get("volumeMounts", [])
    mount = next(
        (m for m in mounts if m["name"] in secret_vols), None
    )
    assert mount is not None, (
        f"{fname}: container {main['name']} never mounts the key volume"
    )
    assert key_path.startswith(mount["mountPath"]), (
        f"{fname}: {EVIDENCE_KEY_ENV}={key_path} is outside the key "
        f"mount at {mount['mountPath']}"
    )


def test_evidence_key_paths_agree_across_manifests():
    """All five workloads read the key from the SAME in-container path,
    so one Secret + one docs/security.md instruction covers the fleet."""
    paths = set()
    for name, (fname, kind) in EVIDENCE_WORKLOADS.items():
        spec = _pod_spec(_find(_load(fname), kind, name))
        env = {
            e["name"]: e.get("value")
            for e in spec["containers"][0].get("env", [])
        }
        paths.add(env.get(EVIDENCE_KEY_ENV))
    assert paths == {"/etc/tpu-cc/evidence-key"}, paths


# ------------------------------------------------- one-command deploy
def _gen_kustomize():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gen_kustomize",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "gen_kustomize.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_kustomize_renders_single_coherent_stack():
    """`kubectl apply -k deployments/kustomize` must deploy ONE
    coherent stack: zero duplicate resource IDs (the standalone
    manifests each redeclare the Namespace/RBAC), exactly one
    Namespace, and exactly ONE agent DaemonSet (the three agent
    manifests are alternatives — deploying all three would schedule
    three agents per node)."""
    mod = _gen_kustomize()
    docs = [d for d in yaml.safe_load_all(
        mod.render()["resources.yaml"]) if d]
    ids = [(d["kind"], d["metadata"].get("namespace", ""),
            d["metadata"]["name"]) for d in docs]
    assert len(ids) == len(set(ids)), "duplicate resource IDs"
    assert ids.count(("Namespace", "", "tpu-system")) == 1
    daemonsets = [i for i in ids if i[0] == "DaemonSet"]
    assert daemonsets == [("DaemonSet", "tpu-system", "tpu-cc-manager")]


def test_kustomize_covers_every_source_resource():
    """Deduplication must only drop IDENTICAL shared declarations —
    every resource of the default-stack manifests is present in the
    rendering."""
    mod = _gen_kustomize()
    rendered = {
        (d["kind"], d["metadata"].get("namespace", ""),
         d["metadata"]["name"])
        for d in yaml.safe_load_all(mod.render()["resources.yaml"]) if d
    }
    for fname in mod.SOURCES:
        for d in _load(fname):
            rid = (d["kind"], d["metadata"].get("namespace", ""),
                   d["metadata"]["name"])
            assert rid in rendered, f"{fname}: {rid} missing"


def test_kustomize_tree_is_fresh():
    """The committed deployments/kustomize tree matches a fresh render
    — the generated tree can never drift from the standalone manifests
    (CI runs gen_kustomize.py --check too)."""
    mod = _gen_kustomize()
    out_dir = os.path.join(MANIFEST_DIR, "..", "kustomize")
    for name, content in mod.render().items():
        with open(os.path.join(out_dir, name)) as f:
            assert f.read() == content, f"{name} is stale"
