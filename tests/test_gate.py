"""Workload-visible mode enforcement (VERDICT r2 item 1).

The flip must have a node-local consequence a workload can observe:

- mid-flip, a process that could open the device node beforehand cannot
  (access-revocation analog of the reference's driver unbind,
  reference scripts/cc-manager.sh:40-50);
- after a verified commit, the node's permission bits encode the mode —
  cc=on is detectably different from cc=off to an unprivileged opener;
- a failed flip leaves the node locked (fail-secure), never half-open;
- the node carries the flip taint for exactly the duration of the cycle.

Privilege note: these tests run as root (the sandbox default), so the
"can a workload open it?" probes run in a subprocess that drops to
uid/gid 65534 (nobody) first — root bypasses permission bits.
"""

import os
import stat
import subprocess
import sys

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.fake import FakeBackend, FakeChip
from tpu_cc_manager.device.gate import DeviceGate, FLIP_LOCK_PERMS, MODE_PERMS
from tpu_cc_manager.engine import ModeEngine
from tpu_cc_manager.drain import NodeFlipTaint
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node


def _can_open_as_nobody(path: str) -> bool:
    """Try to open `path` read-only as uid/gid 65534 in a subprocess."""
    code = (
        "import os,sys\n"
        "os.setgid(65534); os.setuid(65534)\n"
        f"os.close(os.open({path!r}, os.O_RDONLY))\n"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True)
    return r.returncode == 0


needs_root = pytest.mark.skipif(
    os.geteuid() != 0, reason="needs root to drop privileges for the probe"
)


class PermProbeChip(FakeChip):
    """FakeChip whose device path is a real file; records the file's
    permission bits at reset time (i.e. mid-flip)."""

    def __init__(self, path, **kw):
        super().__init__(path=path, **kw)
        self.perms_at_reset = None

    def reset(self):
        self.perms_at_reset = stat.S_IMODE(os.stat(self.path).st_mode)
        super().reset()


def _dev_file(tmp_path, name="accel0", perms=0o666):
    # pytest tmp dirs are 0700; open the directory chain so the
    # dropped-privilege probe can traverse to the "device node"
    d = tmp_path
    while str(d).startswith("/tmp/") and str(d) != "/tmp":
        os.chmod(d, 0o711)
        d = d.parent
    p = tmp_path / name
    p.write_text("")
    os.chmod(p, perms)
    return str(p)


def _engine(backend, states=None, **kw):
    states = states if states is not None else []
    kw.setdefault("evict_components", False)
    kw.setdefault("gate", DeviceGate(enabled=True))
    return ModeEngine(set_state_label=states.append, backend=backend, **kw)


@needs_root
def test_workload_loses_access_mid_flip_and_mode_is_detectable(tmp_path):
    dev = _dev_file(tmp_path)
    chip = PermProbeChip(dev)
    engine = _engine(FakeBackend(chips=[chip]))

    assert _can_open_as_nobody(dev)  # before: open
    assert engine.set_mode("on") is True
    # mid-flip (at reset time) the node was fully locked
    assert chip.perms_at_reset == FLIP_LOCK_PERMS
    # after the verified commit: cc=on means unprivileged open FAILS —
    # the mode-on/mode-off difference a workload can detect
    assert stat.S_IMODE(os.stat(dev).st_mode) == MODE_PERMS["on"]
    assert not _can_open_as_nobody(dev)

    assert engine.set_mode("off") is True
    assert stat.S_IMODE(os.stat(dev).st_mode) == MODE_PERMS["off"]
    assert _can_open_as_nobody(dev)


def test_failed_flip_leaves_device_locked(tmp_path):
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    chip.fail_reset = True
    states = []
    engine = _engine(FakeBackend(chips=[chip]), states)
    assert engine.set_mode("on") is False
    assert states == ["failed"]
    # fail-secure: the half-flipped device is NOT handed back to workloads
    assert stat.S_IMODE(os.stat(dev).st_mode) == FLIP_LOCK_PERMS


def test_verify_mismatch_leaves_device_locked(tmp_path):
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    chip.drop_staged_mode = True
    engine = _engine(FakeBackend(chips=[chip]))
    assert engine.set_mode("on") is False
    assert stat.S_IMODE(os.stat(dev).st_mode) == FLIP_LOCK_PERMS


def test_fast_path_reasserts_gate_perms(tmp_path):
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev, cc_mode="on")
    engine = _engine(FakeBackend(chips=[chip]))
    # chip already in mode 'on' but someone re-opened the node perms
    os.chmod(dev, 0o666)
    assert engine.set_mode("on") is True  # idempotent fast path
    assert stat.S_IMODE(os.stat(dev).st_mode) == MODE_PERMS["on"]


def test_gating_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_CC_DEVICE_GATING", "none")
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    engine = ModeEngine(
        set_state_label=lambda v: None,
        backend=FakeBackend(chips=[chip]),
        evict_components=False,
        gate=None,  # engine builds one from env
    )
    assert engine.set_mode("on") is True
    assert stat.S_IMODE(os.stat(dev).st_mode) == 0o666  # untouched


def test_missing_device_node_is_skipped(tmp_path):
    # fake/jax identities (e.g. "tpu:0") have no devfs entry: gating is
    # silently skipped, the flip still succeeds
    chip = FakeChip(path=str(tmp_path / "does-not-exist"))
    engine = _engine(FakeBackend(chips=[chip]))
    assert engine.set_mode("on") is True


def test_idle_tick_heals_perms_drift(tmp_path):
    """Gate perms drift while the agent is idle (no label event) must
    heal on the idle tick, not wait for the next flip."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev, cc_mode="on")
    kube = FakeKube()
    kube.add_node(make_node("gd-node"))
    cfg = AgentConfig(node_name="gd-node", drain_strategy="none",
                      health_port=0, emit_events=False,
                      emit_evidence=False, repair_interval_s=5)
    agent = CCManagerAgent(kube, cfg, backend=FakeBackend(chips=[chip]))
    # engine built from env: force gating on for this agent's gate
    agent.engine._gate = DeviceGate(enabled=True)
    os.chmod(dev, 0o666)  # drift
    agent._maybe_repair()  # idle tick
    assert stat.S_IMODE(os.stat(dev).st_mode) == MODE_PERMS["on"]
    # throttled: a second tick inside the interval doesn't re-scan
    os.chmod(dev, 0o666)
    agent._maybe_repair()
    assert stat.S_IMODE(os.stat(dev).st_mode) == 0o666
    # after the interval it heals again
    agent._gate_reassert_due = 0.0
    agent._maybe_repair()
    assert stat.S_IMODE(os.stat(dev).st_mode) == MODE_PERMS["on"]


def test_idle_tick_never_reopens_fail_secure_lock(tmp_path):
    """A device left at the flip-lock perms by a FAILED flip must stay
    locked: the drift-heal may only reopen devices whose flip verified.
    (Without this guard the idle tick would chmod a half-flipped chip
    back to its queried mode's perms.)"""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    chip.fail_reset = True
    kube = FakeKube()
    kube.add_node(make_node("fs-node"))
    cfg = AgentConfig(node_name="fs-node", drain_strategy="none",
                      health_port=0, emit_events=False,
                      emit_evidence=False, repair_interval_s=5)
    agent = CCManagerAgent(kube, cfg, backend=FakeBackend(chips=[chip]))
    agent.engine._gate = DeviceGate(enabled=True)
    assert agent.reconcile("on") is False  # flip fails -> locked
    assert stat.S_IMODE(os.stat(dev).st_mode) == FLIP_LOCK_PERMS
    agent._gate_reassert_due = 0.0
    agent._maybe_repair()  # repair backoff hasn't elapsed; only drift-heal
    assert stat.S_IMODE(os.stat(dev).st_mode) == FLIP_LOCK_PERMS


class TaintCheckingDrainer:
    """Asserts the flip taint is present while the drain runs (taint must
    precede eviction so the scheduler stops backfilling the node)."""

    def __init__(self, kube, node_name):
        self.kube = kube
        self.node_name = node_name
        self.taint_seen_at_evict = None

    def _has_taint(self):
        taints = self.kube.get_node(self.node_name).get("spec", {}).get(
            "taints") or []
        return any(t.get("key") == L.FLIP_TAINT_KEY for t in taints)

    def evict(self):
        self.taint_seen_at_evict = self._has_taint()

    def reschedule(self):
        pass


def test_flip_taint_held_for_exactly_the_flip_cycle(tmp_path):
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    drainer = TaintCheckingDrainer(kube, "n1")
    chip = FakeChip(path=_dev_file(tmp_path))
    engine = ModeEngine(
        set_state_label=lambda v: None,
        backend=FakeBackend(chips=[chip]),
        drainer=drainer,
        evict_components=True,
        gate=DeviceGate(enabled=True),
        flip_taint=NodeFlipTaint(kube, "n1"),
    )
    assert engine.set_mode("on") is True
    assert drainer.taint_seen_at_evict is True
    taints = kube.get_node("n1").get("spec", {}).get("taints") or []
    assert not any(t.get("key") == L.FLIP_TAINT_KEY for t in taints)


def test_flip_taint_cleared_even_on_failure(tmp_path):
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    chip = FakeChip(path=_dev_file(tmp_path))
    chip.fail_reset = True
    states = []
    observed = []
    engine = ModeEngine(
        set_state_label=states.append,
        notify_state_label=observed.append,
        backend=FakeBackend(chips=[chip]),
        evict_components=False,
        gate=DeviceGate(enabled=True),
        flip_taint=NodeFlipTaint(kube, "n1"),
    )
    assert engine.set_mode("on") is False
    # the taint-clear replace carried the failed label in the same
    # write; observers (metric gauge hook) still heard the transition
    labels = kube.get_node("n1")["metadata"].get("labels", {})
    assert labels.get(L.CC_MODE_STATE_LABEL) == "failed"
    assert observed == ["failed"]
    assert states == []  # no separate label write happened
    taints = kube.get_node("n1").get("spec", {}).get("taints") or []
    assert not any(t.get("key") == L.FLIP_TAINT_KEY for t in taints)


def test_flip_taint_survives_concurrent_taint_writer():
    """spec.taints is a list: a blind merge patch would wipe taints other
    controllers add concurrently. The taint uses read-edit-replace with
    409 retry; a not-ready taint added between the read and the write
    must survive."""
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    t = NodeFlipTaint(kube, "n1")

    real_replace = kube.replace_node
    raced = {"done": False}

    def racing_replace(name, node):
        if not raced["done"]:
            raced["done"] = True
            # node-lifecycle controller wins the race
            kube.patch_node(name, {"spec": {"taints": [
                {"key": "node.kubernetes.io/not-ready", "value": "",
                 "effect": "NoExecute"},
            ]}})
        return real_replace(name, node)  # first call: 409

    kube.replace_node = racing_replace
    t.set()
    keys = {x["key"] for x in kube.get_node("n1")["spec"]["taints"]}
    assert keys == {"node.kubernetes.io/not-ready", L.FLIP_TAINT_KEY}


def test_flip_taint_preserves_foreign_taints():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    kube.patch_node("n1", {"spec": {"taints": [
        {"key": "example.com/other", "value": "x", "effect": "NoExecute"},
    ]}})
    t = NodeFlipTaint(kube, "n1")
    t.set()
    t.set()  # idempotent
    taints = kube.get_node("n1")["spec"]["taints"]
    assert len(taints) == 2
    t.clear()
    t.clear()  # idempotent
    taints = kube.get_node("n1")["spec"]["taints"]
    assert [x["key"] for x in taints] == ["example.com/other"]
