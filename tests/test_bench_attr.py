"""bench_attr (ISSUE 9): automated regression attribution — the
synthetic-regression fixture the acceptance pins (phase A inflated in
round N must be named, ranked first), the sentinel readings, and the
automatic invocation from bench_trend on a gated-axis failure."""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_attr = _load("bench_attr")
bench_trend = _load("bench_trend")


def _bench(value, extras):
    return {"metric": "pool32_reconcile_p50_s", "value": value,
            "unit": "s", "extras": extras}


def _real_chip_round(flip_s, phases, probe_pre=0.21, probe=0.23,
                     deps=None):
    return _bench(0.09, {
        "real_chip_flip_s": flip_s,
        "real_chip_phase_s": dict(phases),
        "real_chip_probe_pre_s": probe_pre,
        "real_chip_probe_s": probe,
        "bench_deps": deps or {"jax": "0.4.37", "libtpu": "0.0.6"},
    })


BASE_PHASES = {"stage": 0.31, "reset": 0.52, "wait_ready": 0.41,
               "verify": 0.33}


def test_synthetic_regression_names_the_inflated_phase():
    """The acceptance fixture: phase A (wait_ready) inflated in round
    N; the attribution must rank it first and conclude chip-side."""
    prev = _real_chip_round(1.87, BASE_PHASES)
    inflated = dict(BASE_PHASES, wait_ready=2.71)
    cur = _real_chip_round(4.43, inflated)
    (report,) = bench_attr.attribute(prev, cur, ["real_chip_flip_s"])
    assert report["ranked"][0]["phase"] == "wait_ready"
    assert report["ranked"][0]["delta"] == 2.3
    assert report["probe"] == "flat"
    assert report["dep_changes"] == {}
    assert "wait_ready" in report["verdict"]
    assert "chip-side" in report["verdict"]
    assert "probe flat" in report["verdict"]
    assert "deps unchanged" in report["verdict"]


def test_inflated_probe_reads_as_host_contention():
    prev = _real_chip_round(1.87, BASE_PHASES)
    cur = _real_chip_round(
        4.43, {k: v * 2.3 for k, v in BASE_PHASES.items()},
        probe_pre=0.9, probe=1.1,
    )
    (report,) = bench_attr.attribute(prev, cur, ["real_chip_flip_s"])
    assert report["probe"] == "inflated"
    assert "host contention" in report["verdict"]


def test_changed_deps_lead_the_verdict():
    prev = _real_chip_round(1.87, BASE_PHASES)
    cur = _real_chip_round(
        4.43, dict(BASE_PHASES, wait_ready=2.7),
        deps={"jax": "0.4.38", "libtpu": "0.0.7"},
    )
    (report,) = bench_attr.attribute(prev, cur, ["real_chip_flip_s"])
    assert report["dep_changes"] == {
        "jax": "0.4.37 -> 0.4.38", "libtpu": "0.0.6 -> 0.0.7",
    }
    assert "toolchain" in report["verdict"]


def test_missing_phase_data_is_stated_not_invented():
    """The honest r05 case: the previous round predates the per-phase
    sub-spans — the verdict must say the data is missing."""
    prev = _bench(0.09, {"real_chip_flip_s": 1.87})
    cur = _bench(0.09, {"real_chip_flip_s": 4.43,
                        "real_chip_phase_s": {}})
    (report,) = bench_attr.attribute(prev, cur, ["real_chip_flip_s"])
    assert "missing" in report["verdict"]


def test_axes_from_problems_maps_problem_lines_back():
    problems = [
        "real_chip_flip_s 1.87 -> 4.43 (2.4x slower)",
        "p50 0.04 -> 0.18 (4.8x slower)",
        "flips_per_min_windowed 21000 -> 5000 (4.2x fewer)",
    ]
    assert bench_attr.axes_from_problems(problems) == [
        "real_chip_flip_s", "p50", "flips_per_min_windowed",
    ]


def _write_rounds(tmp_path, prev, cur):
    (tmp_path / "BENCH_r07.json").write_text(json.dumps(prev))
    (tmp_path / "BENCH_r08.json").write_text(json.dumps(cur))


def test_bench_trend_runs_attribution_on_gated_failure(
        tmp_path, capsys):
    """The integration pin: an unexplained gated-axis regression makes
    bench_trend print the ranked attribution next to its verdict."""
    prev = _real_chip_round(1.87, BASE_PHASES)
    cur = _real_chip_round(4.43, dict(BASE_PHASES, wait_ready=2.71))
    _write_rounds(tmp_path, prev, cur)
    rc = bench_trend.main(str(tmp_path))
    assert rc == 1  # unexplained regression still fails the gate
    err = capsys.readouterr().err
    assert "attribution: real_chip_flip_s" in err
    assert "wait_ready" in err
    assert "chip-side" in err


def test_bench_trend_attribution_does_not_unfail_the_gate(tmp_path):
    """Attribution is commentary; an acknowledged regression still
    passes and an unexplained one still fails."""
    prev = _real_chip_round(1.87, BASE_PHASES)
    cur = _real_chip_round(4.43, dict(BASE_PHASES, wait_ready=2.71))
    cur["extras"]["regression_note"] = "known slow chip day"
    _write_rounds(tmp_path, prev, cur)
    assert bench_trend.main(str(tmp_path)) == 0


def test_bench_attr_cli_runs_on_committed_history(capsys):
    """The standalone CLI never crashes on the real BENCH_r*.json
    history (whatever mixed-era extras it carries)."""
    rc = bench_attr.main([REPO])
    assert rc == 0
    out = capsys.readouterr().out
    assert "bench-attr:" in out


def test_committed_round_with_phase_data_is_never_data_missing():
    """ISSUE 13 satellite: bench.py now ALWAYS persists
    extras.real_chip_phase_s (CPU-PJRT fallback on TPU-less hosts), so
    any pair of committed rounds carries a phase surface on both sides
    — the attribution must produce a verdict, never the "data missing"
    degradation BENCH_NOTES r10 documented for the uncommitted era."""
    prev = _real_chip_round(1.87, BASE_PHASES)
    cur = _real_chip_round(2.05, dict(BASE_PHASES, reset=0.70))
    for rnd in (prev, cur):
        rnd["extras"]["real_chip_phase_source"] = "tpu"
    (report,) = bench_attr.attribute(prev, cur, ["real_chip_flip_s"])
    assert "data missing" not in report["verdict"]
    assert report["missing"] == []
    assert report["ranked"][0]["phase"] == "reset"


def test_cross_substrate_phase_comparison_carries_caveat():
    """A TPU round next to a CPU-fallback round must not pass its
    phase deltas off as evidence — the verdict names the substrate
    mismatch."""
    prev = _bench(0.09, {
        "real_chip_phase_s": {"wait_ready": 0.04, "reset": 0.002},
        "real_chip_phase_source": "cpu-pjrt-fallback",
    })
    cur = _real_chip_round(4.43, BASE_PHASES)
    cur["extras"]["real_chip_phase_source"] = "tpu"
    (report,) = bench_attr.attribute(prev, cur, ["real_chip_flip_s"])
    assert "phase sources differ" in report["verdict"]
    assert "cpu-pjrt-fallback" in report["verdict"]


def test_flip_write_rtt_axis_attributes_from_kube_io():
    """The new r13 axis: a flip_write_rtt_p50_s regression diffs the
    async core's own accounting (dials/requests) plus the phase
    budget."""
    prev = _bench(0.09, {
        "flip_write_rtt_p50_s": 0.027,
        "kube_io": {"dials": 8, "requests": 700, "replays": 0},
        "phase_p50_s": {"taint_set": 0.02, "taint_clear": 0.02},
    })
    cur = _bench(0.11, {
        "flip_write_rtt_p50_s": 0.09,
        "kube_io": {"dials": 300, "requests": 700, "replays": 0},
        "phase_p50_s": {"taint_set": 0.06, "taint_clear": 0.05},
    })
    (report,) = bench_attr.attribute(prev, cur,
                                     ["flip_write_rtt_p50_s"])
    assert "data missing" not in report["verdict"]
    # the dial explosion (multiplexing loss) ranks at the top
    assert report["ranked"][0]["phase"] == "dials"
