"""k8s layer tests: fake store semantics, then the HTTP client against the
HTTP API server (wire-protocol round trip)."""

import threading
import time

import pytest

from tpu_cc_manager.k8s import ApiException, ConflictError, FakeKube, HttpKubeClient
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.client import KubeConfig
from tpu_cc_manager.k8s.objects import make_node, make_pod, match_selector, merge_patch


# ------------------------------------------------------------------ objects
def test_merge_patch_semantics():
    base = {"metadata": {"labels": {"a": "1", "b": "2"}}}
    out = merge_patch(base, {"metadata": {"labels": {"b": None, "c": "3"}}})
    assert out["metadata"]["labels"] == {"a": "1", "c": "3"}
    assert base["metadata"]["labels"] == {"a": "1", "b": "2"}  # no mutation


def test_match_selector():
    labels = {"app": "x", "tier": "gpu"}
    assert match_selector(labels, "app=x")
    assert match_selector(labels, "app==x,tier=gpu")
    assert not match_selector(labels, "app=y")
    assert match_selector(labels, "app!=y")
    assert match_selector(labels, "app")
    assert not match_selector(labels, "missing")
    assert match_selector(labels, None)


# --------------------------------------------------------------- fake store
def test_fake_node_crud_and_rv_monotonic():
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={"x": "1"}))
    n = kube.get_node("n1")
    rv1 = int(n["metadata"]["resourceVersion"])
    kube.set_node_labels("n1", {"x": "2"})
    n2 = kube.get_node("n1")
    assert n2["metadata"]["labels"]["x"] == "2"
    assert int(n2["metadata"]["resourceVersion"]) > rv1
    with pytest.raises(ApiException) as ei:
        kube.get_node("missing")
    assert ei.value.status == 404


def test_fake_label_delete_via_none():
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={"x": "1"}))
    kube.set_node_labels("n1", {"x": None})
    assert "x" not in kube.get_node("n1")["metadata"]["labels"]


def test_fake_replace_node_cas():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    n = kube.get_node("n1")
    n["metadata"]["annotations"]["owner"] = "a"
    kube.replace_node("n1", n)  # fresh rv: ok
    n_stale = dict(n)  # still carries the old rv
    with pytest.raises(ConflictError):
        kube.replace_node("n1", n_stale)


def test_fake_pods_list_delete_evict_pdb():
    kube = FakeKube()
    kube.add_pod(make_pod("p1", "ns1", labels={"app": "a"}, node_name="n1"))
    kube.add_pod(make_pod("p2", "ns1", labels={"app": "b"}, node_name="n2"))
    assert len(kube.list_pods("ns1")) == 2
    assert [p["metadata"]["name"] for p in kube.list_pods("ns1", "app=a")] == ["p1"]
    assert [
        p["metadata"]["name"]
        for p in kube.list_pods("ns1", field_selector="spec.nodeName=n2")
    ] == ["p2"]
    kube.pdb_blocked.add(("ns1", "p1"))
    with pytest.raises(ApiException) as ei:
        kube.evict_pod("ns1", "p1")
    assert ei.value.status == 429
    kube.pdb_blocked.clear()
    kube.evict_pod("ns1", "p1")
    kube.delete_pod("ns1", "p2")
    assert kube.list_pods("ns1") == []


def test_fake_watch_replays_history_then_streams():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    rv0 = kube.latest_rv
    kube.set_node_labels("n1", {"step": "1"})

    got = []

    def run():
        for etype, obj in kube.watch_nodes(name="n1", resource_version=rv0, timeout_s=5):
            got.append((etype, obj["metadata"]["labels"].get("step")))
            if len(got) == 2:
                return

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.2)
    kube.set_node_labels("n1", {"step": "2"})
    t.join(timeout=5)
    assert got == [("MODIFIED", "1"), ("MODIFIED", "2")]


def test_fake_watch_scopes_to_node_name():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    kube.add_node(make_node("n2"))
    rv0 = kube.latest_rv
    kube.set_node_labels("n2", {"x": "1"})
    kube.set_node_labels("n1", {"x": "1"})
    events = []
    for etype, obj in kube.watch_nodes(name="n1", resource_version=rv0, timeout_s=1):
        events.append(obj["metadata"]["name"])
        break
    assert events == ["n1"]


def test_fake_watch_410_after_compaction():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    rv0 = kube.latest_rv
    kube.set_node_labels("n1", {"x": "1"})
    kube.compact_watch_history()
    with pytest.raises(ApiException) as ei:
        next(iter(kube.watch_nodes(name="n1", resource_version=rv0, timeout_s=1)))
    assert ei.value.status == 410


def test_fake_watch_timeout_clean_end():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    rv = kube.latest_rv
    start = time.monotonic()
    events = list(kube.watch_nodes(name="n1", resource_version=rv, timeout_s=1))
    assert events == []
    assert time.monotonic() - start >= 0.9


def test_fake_watch_error_injection():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    kube.fail_next_watches = 1
    with pytest.raises(ApiException) as ei:
        next(iter(kube.watch_nodes(name="n1", timeout_s=1)))
    assert ei.value.status == 500
    # next call succeeds
    list(kube.watch_nodes(name="n1", resource_version=kube.latest_rv, timeout_s=1))


# -------------------------------------------------- HTTP client <-> server
@pytest.fixture()
def server():
    with FakeApiServer() as s:
        yield s


@pytest.fixture()
def client(server):
    return HttpKubeClient(KubeConfig("127.0.0.1", server.port, use_tls=False))


def test_http_node_roundtrip(server, client):
    server.store.add_node(make_node("tpu-node-0", labels={"a": "1"}))
    node = client.get_node("tpu-node-0")
    assert node["metadata"]["labels"]["a"] == "1"
    client.set_node_labels("tpu-node-0", {"a": "2", "b": None})
    assert client.get_node("tpu-node-0")["metadata"]["labels"] == {"a": "2"}
    nodes = client.list_nodes("a=2")
    assert [n["metadata"]["name"] for n in nodes] == ["tpu-node-0"]
    assert client.list_nodes("a=nope") == []
    with pytest.raises(ApiException) as ei:
        client.get_node("missing")
    assert ei.value.status == 404


def test_http_replace_conflict(server, client):
    server.store.add_node(make_node("n1"))
    n = client.get_node("n1")
    client.replace_node("n1", n)
    with pytest.raises(ConflictError):
        client.replace_node("n1", n)


def test_http_pods_and_eviction(server, client):
    server.store.add_pod(make_pod("p1", "tpu-system", labels={"app": "dp"}))
    pods = client.list_pods("tpu-system", label_selector="app=dp")
    assert len(pods) == 1
    server.store.pdb_blocked.add(("tpu-system", "p1"))
    with pytest.raises(ApiException) as ei:
        client.evict_pod("tpu-system", "p1")
    assert ei.value.status == 429
    server.store.pdb_blocked.clear()
    client.evict_pod("tpu-system", "p1")
    assert client.list_pods("tpu-system") == []


def test_http_watch_stream_and_timeout(server, client):
    server.store.add_node(make_node("n1"))
    rv = server.store.latest_rv

    got = []

    def run():
        for etype, obj in client.watch_nodes(
            name="n1", resource_version=rv, timeout_s=3
        ):
            got.append(obj["metadata"]["labels"].get("m"))
            if len(got) == 2:
                return

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)
    server.store.set_node_labels("n1", {"m": "on"})
    time.sleep(0.3)
    server.store.set_node_labels("n1", {"m": "off"})
    t.join(timeout=10)
    assert got == ["on", "off"]


def test_http_watch_410_surfaces_as_api_exception(server, client):
    server.store.add_node(make_node("n1"))
    rv = server.store.latest_rv
    server.store.set_node_labels("n1", {"x": "1"})
    server.store.compact_watch_history()
    with pytest.raises(ApiException) as ei:
        for _ in client.watch_nodes(name="n1", resource_version=rv, timeout_s=2):
            pass
    assert ei.value.status == 410


def test_http_watch_clean_timeout_eof(server, client):
    server.store.add_node(make_node("n1"))
    rv = server.store.latest_rv
    events = list(client.watch_nodes(name="n1", resource_version=rv, timeout_s=1))
    assert events == []


# ------------------------------------- keep-alive, pagination, bookmarks


def test_keepalive_reuses_one_connection(server, client, monkeypatch):
    """Repeated requests from one thread ride a single TCP connection
    (r1 VERDICT weak #3: one handshake per request at pool scale)."""
    server.store.add_node(make_node("n0"))
    dials = []
    real_connect = HttpKubeClient._connect

    def counting_connect(self, read_timeout):
        dials.append(1)
        return real_connect(self, read_timeout)

    monkeypatch.setattr(HttpKubeClient, "_connect", counting_connect)
    for _ in range(5):
        client.get_node("n0")
    client.list_nodes()
    assert len(dials) == 1


def test_keepalive_stale_connection_replayed(server, client):
    """A request racing the server's idle-connection close is replayed
    once on a fresh connection, invisibly to the caller."""
    server.store.add_node(make_node("n0"))
    client.get_node("n0")  # pool a connection
    client._conns[0].sock.close()  # simulate server-side close
    node = client.get_node("n0")  # must not raise
    assert node["metadata"]["name"] == "n0"


def test_shared_pool_replays_merge_patch_exactly_once(server, client):
    """Satellite pin (ISSUE 6): a REUSED pooled connection the server
    closed before sending any response bytes replays its merge patch
    exactly once — the write lands one time, never twice, even though
    the conn was checked out of the SHARED pool rather than being
    thread-local."""
    from http.client import RemoteDisconnected

    server.store.add_node(make_node("n0"))
    client.get_node("n0")  # pool a warm connection
    assert len(client._conns) == 1
    stale = client._conns[0]
    real_request = stale.request
    calls = {"n": 0}

    def dying_request(*a, **kw):
        # the server closed this idle keep-alive conn; the first reuse
        # observes it only at response time (no bytes ever sent back)
        calls["n"] += 1
        raise RemoteDisconnected("closed by server while idle")

    stale.request = dying_request
    w0 = server.store.node_write_stats()
    out = client.patch_node("n0", {"metadata": {"labels": {"k": "v"}}})
    assert out["metadata"]["labels"]["k"] == "v"
    assert calls["n"] == 1  # the stale conn was tried once, then dropped
    w1 = server.store.node_write_stats()
    # exactly ONE write landed server-side: the replay, not a double-apply
    assert w1["requests"] - w0["requests"] == 1
    stale.request = real_request


def test_fresh_connection_failure_is_not_replayed(server, client, monkeypatch):
    """A BadStatusLine on a FRESH connection may have executed
    server-side; replaying a non-idempotent PATCH could double-apply it,
    so the client surfaces the transport error instead."""
    from http.client import HTTPConnection, RemoteDisconnected

    server.store.add_node(make_node("n0"))
    client.close()  # no pooled conns: the next request dials fresh
    attempts = {"n": 0}
    real_request = HTTPConnection.request

    def dying_request(self, *a, **kw):
        attempts["n"] += 1
        raise RemoteDisconnected("mid-flight failure on a fresh conn")

    monkeypatch.setattr(HTTPConnection, "request", dying_request)
    with pytest.raises(ApiException) as ei:
        client.patch_node("n0", {"metadata": {"labels": {"k": "v"}}})
    assert ei.value.status == 0
    assert attempts["n"] == 1  # no silent replay of a possible write
    monkeypatch.setattr(HTTPConnection, "request", real_request)


def test_shared_pool_bounded_and_reused_across_threads(server):
    """N worker threads (the flip executor shape) share the pool: the
    total number of dials stays at/below the pool bound across a burst
    of concurrent requests, instead of one dial per thread."""
    client = HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False), pool_maxsize=4
    )
    server.store.add_node(make_node("n0"))
    dials = []
    dial_lock = threading.Lock()
    real_connect = HttpKubeClient._connect

    def counting_connect(self, read_timeout):
        with dial_lock:
            dials.append(1)
        return real_connect(self, read_timeout)

    client._connect = counting_connect.__get__(client)
    threads = [
        threading.Thread(
            target=lambda: [client.get_node("n0") for _ in range(5)]
        )
        for _ in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # 8 threads x 5 requests rode at most "concurrency peak" dials, and
    # the idle pool retains at most the configured bound
    assert len(dials) <= 8  # far fewer than the 40 requests
    assert len(client._conns) <= 4
    # a follow-up burst from fresh threads reuses the warm pool: no dials
    before = len(dials)
    t = threading.Thread(target=lambda: [client.get_node("n0") for _ in range(3)])
    t.start(); t.join()
    assert len(dials) == before


def test_list_pagination_follows_continue(server):
    for i in range(5):
        server.store.add_node(make_node(f"n{i}", labels={"pool": "a"}))
    paged = HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False), list_page_limit=2
    )
    names = sorted(n["metadata"]["name"] for n in paged.list_nodes("pool=a"))
    assert names == [f"n{i}" for i in range(5)]
    # the server really is chunking: a raw limited request returns a
    # partial page plus a continue token
    first = paged._request("GET", "/api/v1/nodes?limit=2")
    assert len(first["items"]) == 2
    assert first["metadata"]["continue"]


def test_pod_list_pagination(server):
    for i in range(7):
        server.store.add_pod({
            "metadata": {"name": f"p{i}", "namespace": "ns", "labels": {}},
            "spec": {"nodeName": "n0"},
        })
    paged = HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False), list_page_limit=3
    )
    pods = paged.list_pods("ns")
    assert sorted(p["metadata"]["name"] for p in pods) == [f"p{i}" for i in range(7)]


def test_watch_bookmarks_streamed_over_http(server, client):
    server.store.add_node(make_node("n0"))
    server.store.bookmark_every_s = 0.05
    events = list(client.watch_nodes(name="n0", timeout_s=1))
    bookmarks = [obj for t, obj in events if t == "BOOKMARK"]
    assert bookmarks, "expected at least one BOOKMARK on an idle watch"
    assert bookmarks[-1]["metadata"]["resourceVersion"] == server.store.latest_rv


def test_bookmark_rv_survives_foreign_churn(server, client):
    """Bookmarks advance a node-scoped watcher's rv past other-node churn
    so its reconnect stays inside retained history (no 410 re-list)."""
    store = server.store
    store._history_limit = 5
    store.bookmark_every_s = 0.05
    store.add_node(make_node("mine"))
    store.add_node(make_node("other"))
    stale_rv = client.get_node("mine")["metadata"]["resourceVersion"]

    # churn the *other* node far past the retained history window
    for i in range(20):
        store.patch_node("other", {"metadata": {"labels": {"i": str(i)}}})

    # a resume from the pre-churn rv is hopeless without bookmarks
    with pytest.raises(ApiException) as ei:
        list(client.watch_nodes(name="mine", resource_version=stale_rv,
                                timeout_s=1))
    assert ei.value.status == 410

    # with bookmarks: an open stream fast-forwards rv through mid-stream
    # churn on the other node...
    rv = None
    churned = False
    for t, o in client.watch_nodes(name="mine", timeout_s=3):
        if t != "BOOKMARK":
            continue
        rv = o["metadata"]["resourceVersion"]
        if not churned:
            for i in range(20):
                store.patch_node("other", {"metadata": {"labels": {"j": str(i)}}})
            churned = True
        elif int(rv) >= int(store.latest_rv):
            break  # bookmark caught up past the churn
    assert churned and rv is not None

    # ...so the next resume sees only the real change on our node
    store.patch_node("mine", {"metadata": {"labels": {"x": "y"}}})
    etypes = [t for t, _ in client.watch_nodes(name="mine",
                                              resource_version=rv,
                                              timeout_s=1)]
    assert "MODIFIED" in etypes


def test_client_side_flow_control(server, monkeypatch):
    """client-go rest.Config QPS/Burst parity: a QPS-limited client
    delays (never drops) requests past the burst, off by default, and
    reads TPU_CC_KUBE_QPS/_BURST from the env — the shipped controller
    manifests set it so a fleet-scale scan can't hammer the API
    server."""
    import time as _time

    server.store.add_node(make_node("fc-node"))

    # burst=1, 10 QPS: 5 calls -> at least 4 waits of ~0.1 s
    limited = HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False),
        qps=10, burst=1,
    )
    t0 = _time.monotonic()
    for _ in range(5):
        limited.get_node("fc-node")
    elapsed = _time.monotonic() - t0
    assert elapsed >= 0.35, elapsed

    # default: no limiter (flip latency must not pay for politeness)
    assert HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False)
    )._bucket is None

    # env wiring, ctor args win; garbage env reads as off
    monkeypatch.setenv("TPU_CC_KUBE_QPS", "25")
    env_client = HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False)
    )
    assert env_client._bucket is not None
    assert env_client._bucket.qps == 25 and env_client._bucket.burst == 50
    monkeypatch.setenv("TPU_CC_KUBE_BURST", "5")
    assert HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False)
    )._bucket.burst == 5
    monkeypatch.setenv("TPU_CC_KUBE_QPS", "not-a-number")
    assert HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False)
    )._bucket is None

    # a burst is spent without waiting: 3 calls under burst=10 consume
    # tokens instead of sleeping (bucket state, not wall clock — a
    # loaded CI machine must not flake a timing bound)
    burst_client = HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False),
        qps=1, burst=10,
    )
    for _ in range(3):
        burst_client.get_node("fc-node")
    assert burst_client._bucket._tokens <= 7.5


def test_http_client_creates_events_over_the_wire():
    with FakeApiServer() as srv:
        kube = HttpKubeClient(KubeConfig("127.0.0.1", srv.port, use_tls=False))
        out = kube.create_event(
            "tpu-system",
            {
                "kind": "Event", "apiVersion": "v1",
                "metadata": {"name": "n1.cc-reconcile.1",
                             "namespace": "tpu-system"},
                "involvedObject": {"kind": "Node", "apiVersion": "v1",
                                   "name": "n1"},
                "reason": "CCModeApplied", "message": "m", "type": "Normal",
            },
        )
        assert out["metadata"]["resourceVersion"]
        assert srv.store.cluster_events[0]["reason"] == "CCModeApplied"
        assert srv.store.cluster_events[0]["metadata"]["namespace"] == "tpu-system"


def test_http_apiserver_lists_events_by_namespace():
    with FakeApiServer() as srv:
        kube = HttpKubeClient(KubeConfig("127.0.0.1", srv.port, use_tls=False))
        for ns, name in (("default", "e1"), ("default", "e2"), ("other", "e3")):
            kube.create_event(ns, {
                "kind": "Event", "apiVersion": "v1",
                "metadata": {"name": name},
                "involvedObject": {"kind": "Node", "name": "n"},
                "reason": "CCModeApplied", "message": "m", "type": "Normal"})
        items = kube.list_events("default")
        assert [e["metadata"]["name"] for e in items] == ["e1", "e2"]


# ------------------------------------------- accept-layer error handling
def test_rude_disconnect_prints_no_traceback(server, client, capfd):
    """VERDICT r5 weak #6: a client vanishing at the accept/readline
    layer (RST mid-request) used to print socketserver's full traceback
    into the smoke's green log. handle_error must swallow the benign
    disconnect classes — and the server must keep serving."""
    import socket
    import struct

    s = socket.create_connection(("127.0.0.1", server.port))
    # SO_LINGER(on, 0): close() sends RST, so the handler thread gets
    # ECONNRESET at the readline layer, not a clean FIN
    s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                 struct.pack("ii", 1, 0))
    s.send(b"GET /api/v1/nodes HTT")  # partial request line
    time.sleep(0.05)
    s.close()
    time.sleep(0.2)
    # still serving after the rude client
    server.store.add_node(make_node("post-rst", labels={}))
    assert client.get_node("post-rst")["metadata"]["name"] == "post-rst"
    out, err = capfd.readouterr()
    assert "Traceback" not in err and "Traceback" not in out


def test_handle_error_swallows_benign_logs_others(server, caplog):
    """Direct contract: client-gone classes are silent; anything else
    logs ONE warning line (no traceback)."""
    import logging

    httpd = server.httpd
    try:
        raise ConnectionResetError("peer reset")
    except ConnectionResetError:
        httpd.handle_error(None, ("127.0.0.1", 1))  # must not print
    with caplog.at_level(logging.WARNING,
                         logger="tpu-cc-manager.fake-apiserver"):
        try:
            raise RuntimeError("genuinely unexpected")
        except RuntimeError:
            httpd.handle_error(None, ("127.0.0.1", 2))
    assert any("genuinely unexpected" in r.message
               for r in caplog.records)


def test_replay_dials_fresh_even_when_whole_pool_is_stale(server):
    """After a server restart EVERY pooled idle connection can be
    stale. The replay attempt must dial fresh (pool bypass) — popping
    another stale conn would turn the replayable keep-alive race into
    a terminal error on a write that never executed."""
    from http.client import RemoteDisconnected

    client = HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False), pool_maxsize=4
    )
    server.store.add_node(make_node("n0"))
    # warm two pooled connections deterministically: check both out
    # (forcing two dials), connect them, and return them to the pool
    c1, _ = client._acquire_conn(5.0)
    c2, _ = client._acquire_conn(5.0)
    c1.connect()
    c2.connect()
    client._release_conn(c1)
    client._release_conn(c2)
    assert len(client._conns) == 2
    # the server "restarted": every pooled conn dies on next use
    for conn in client._conns:
        real = conn.request

        def dying(*a, **kw):
            raise RemoteDisconnected("closed while idle")

        conn.request = dying
    out = client.patch_node("n0", {"metadata": {"labels": {"k": "v"}}})
    assert out["metadata"]["labels"]["k"] == "v"
