"""The asyncio kube I/O core (k8s/aio.py + aio_bridge.py, ISSUE 13):
multiplexing, bounded connection budget, exactly-once replay under
pipelining, and the sync façade — all over the real wire against
FakeApiServer."""

import threading
import time

import pytest

from tpu_cc_manager.k8s.aio import AsyncKubeClient
from tpu_cc_manager.k8s.aio_bridge import SyncKubeFacade, get_bridge
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.client import ApiException, ConflictError, KubeConfig
from tpu_cc_manager.k8s.objects import make_node


@pytest.fixture()
def server():
    with FakeApiServer() as s:
        yield s


def _facade(server, **kw):
    return SyncKubeFacade(
        KubeConfig("127.0.0.1", server.port, use_tls=False), **kw
    )


def _arm_kill_next_patch(server, n=1):
    """Make the server abruptly close the connection (zero response
    bytes, request body unread — the write never executes) for the
    next ``n`` PATCH requests. This is the stale-keep-alive /
    BadStatusLine shape from the server side."""
    handler_cls = server.httpd.RequestHandlerClass
    orig = handler_cls.do_PATCH
    remaining = {"n": n}
    lock = threading.Lock()

    def do_PATCH(self):
        with lock:
            kill = remaining["n"] > 0
            if kill:
                remaining["n"] -= 1
        if kill:
            self.close_connection = True
            self.connection.close()  # no status line, nothing executed
            return
        orig(self)

    handler_cls.do_PATCH = do_PATCH
    return remaining


# --------------------------------------------------------------- basics


def test_facade_node_roundtrip_and_errors(server):
    server.store.add_node(make_node("n0", labels={"a": "1"}))
    kube = _facade(server)
    assert kube.get_node("n0")["metadata"]["labels"]["a"] == "1"
    kube.set_node_labels("n0", {"a": "2", "b": None})
    assert kube.get_node("n0")["metadata"]["labels"] == {"a": "2"}
    n = kube.get_node("n0")
    kube.replace_node("n0", n)
    with pytest.raises(ConflictError):
        kube.replace_node("n0", n)
    with pytest.raises(ApiException) as ei:
        kube.get_node("missing")
    assert ei.value.status == 404
    kube.close()


def test_facade_watch_streams_and_clean_timeout(server):
    server.store.add_node(make_node("n0"))
    kube = _facade(server)
    rv = server.store.latest_rv
    got = []

    def run():
        for _etype, obj in kube.watch_nodes(
            name="n0", resource_version=rv, timeout_s=3
        ):
            got.append(obj["metadata"]["labels"].get("m"))
            if len(got) == 2:
                return

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)
    server.store.set_node_labels("n0", {"m": "on"})
    time.sleep(0.3)
    server.store.set_node_labels("n0", {"m": "off"})
    t.join(timeout=10)
    assert got == ["on", "off"]
    # clean server-side timeout = clean iterator end, and a 410 on a
    # compacted resume surfaces as ApiException exactly like the
    # threaded client
    assert list(kube.watch_nodes(
        name="n0", resource_version=server.store.latest_rv, timeout_s=1
    )) == []
    stale_rv = server.store.latest_rv
    server.store.set_node_labels("n0", {"x": "1"})
    server.store.compact_watch_history()
    with pytest.raises(ApiException) as ei:
        list(kube.watch_nodes(name="n0", resource_version=stale_rv,
                              timeout_s=2))
    assert ei.value.status == 410
    kube.close()


# ------------------------------------------------- pool exhaustion pin


def test_writers_beyond_conn_budget_queue_not_error(server):
    """Satellite pin (ISSUE 13): concurrent writers exceeding
    TPU_CC_KUBE_CONNS must QUEUE on the per-connection window — every
    write lands, none errors, and the socket count stays at the
    budget (no unbounded dials)."""
    server.store.add_node(make_node("n0"))
    kube = _facade(server, max_conns=3, window=2)
    errors = []

    def writer(i):
        try:
            for j in range(6):
                kube.patch_node(
                    "n0", {"metadata": {"labels": {f"w{i}": str(j)}}}
                )
        except Exception as e:  # pragma: no cover - the failure surface
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    stats = kube.stats()
    # 24 writers x 6 writes multiplexed over at most 3 sockets
    assert stats["requests"] >= 144
    assert stats["dials"] <= 3
    labels = server.store.get_node("n0")["metadata"]["labels"]
    assert all(labels[f"w{i}"] == "5" for i in range(24))
    kube.close()


# ------------------------------------------- exactly-once replay pins


def test_stale_close_replays_merge_patch_exactly_once(server):
    """The BadStatusLine-analog on the async core: a reused pipelined
    connection the server closed with ZERO response bytes replays its
    merge patch exactly once on a fresh dial — the write lands one
    time, never twice."""
    server.store.add_node(make_node("n0"))
    kube = _facade(server, max_conns=1, window=2)
    kube.get_node("n0")  # the conn has served: replay is legal
    _arm_kill_next_patch(server, 1)
    w0 = server.store.node_write_stats()
    out = kube.patch_node("n0", {"metadata": {"labels": {"k": "v"}}})
    assert out["metadata"]["labels"]["k"] == "v"
    w1 = server.store.node_write_stats()
    assert w1["requests"] - w0["requests"] == 1  # once, not twice
    assert kube.stats()["replays"] == 1
    kube.close()


def test_replay_holds_when_racing_a_pool_mate(server):
    """Satellite pin (ISSUE 13): the exactly-once replay must hold
    when the replayed request RACED other in-flight requests on the
    shared pool — every write still lands exactly once (the store's
    request accounting equals the number of issued writes)."""
    server.store.add_node(make_node("n0"))
    kube = _facade(server, max_conns=2, window=2)
    # warm both conns so any victim connection has served >= 1
    for _ in range(8):
        kube.get_node("n0")
    _arm_kill_next_patch(server, 1)
    w0 = server.store.node_write_stats()
    errors = []
    n_writers, n_each = 6, 4

    def writer(i):
        try:
            for j in range(n_each):
                kube.patch_node(
                    "n0", {"metadata": {"labels": {f"r{i}": str(j)}}}
                )
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    w1 = server.store.node_write_stats()
    # EXACTLY one server-side round trip per issued write: the killed
    # request replayed once, no pool-mate was double-applied, no
    # write was lost
    assert w1["requests"] - w0["requests"] == n_writers * n_each
    assert kube.stats()["replays"] >= 1
    labels = server.store.get_node("n0")["metadata"]["labels"]
    assert all(labels[f"r{i}"] == str(n_each - 1)
               for i in range(n_writers))
    kube.close()


def test_fresh_connection_failure_is_terminal_not_replayed(server):
    """A connection that never served a response may have executed the
    request server-side — the sync client's rule, preserved: terminal
    ApiException(0), zero silent replays."""
    server.store.add_node(make_node("n0"))
    kube = _facade(server, max_conns=1, window=1)
    _arm_kill_next_patch(server, 1)
    w0 = server.store.node_write_stats()
    with pytest.raises(ApiException) as ei:
        kube.patch_node("n0", {"metadata": {"labels": {"k": "v"}}})
    assert ei.value.status == 0
    assert kube.stats()["replays"] == 0
    assert server.store.node_write_stats()["requests"] == w0["requests"]
    kube.close()


# -------------------------------------------------- bridge primitives


def test_bridge_submit_and_gather_run_blocking_work():
    bridge = get_bridge()
    seen = []

    def side(tag):
        time.sleep(0.05)
        seen.append(tag)
        return tag

    futs = [bridge.submit(side, i) for i in range(4)]
    assert sorted(bridge.gather(futs)) == [0, 1, 2, 3]
    assert sorted(seen) == [0, 1, 2, 3]


def test_bridge_gather_joins_all_before_raising():
    """The fail-secure join: gather must not abandon siblings when one
    fails — everything settles first, then the first exception
    surfaces."""
    bridge = get_bridge()
    done = []

    def ok():
        time.sleep(0.15)
        done.append("ok")
        return "ok"

    def boom():
        raise RuntimeError("side failure")

    futs = [bridge.submit(boom), bridge.submit(ok)]
    with pytest.raises(RuntimeError):
        bridge.gather(futs)
    assert done == ["ok"]  # the sibling ran to completion first


def test_facade_throttle_surface_matches_threaded_client(server):
    """set_qps/throttle accounting parity: the simlab runner and fault
    injector drive either I/O core through the same attributes."""
    server.store.add_node(make_node("n0"))
    kube = _facade(server, qps=10, burst=1)
    waits = []
    kube.add_throttle_observer(waits.append)
    t0 = time.monotonic()
    for _ in range(4):
        kube.get_node("n0")
    assert time.monotonic() - t0 >= 0.25
    assert kube.throttle_waits >= 2
    assert kube.throttle_wait_s_total > 0
    assert len(waits) == 4  # observed on EVERY flow-controlled request
    kube.set_qps(0)  # limiter off: burst through instantly
    t0 = time.monotonic()
    for _ in range(5):
        kube.get_node("n0")
    assert time.monotonic() - t0 < 0.5
    kube.close()


def test_async_client_rtt_observer_sees_writes(server):
    server.store.add_node(make_node("n0"))
    aio = AsyncKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False)
    )
    samples = []
    aio.add_rtt_observer(
        lambda method, path, rtt: samples.append((method, rtt))
    )
    kube = SyncKubeFacade(
        KubeConfig("127.0.0.1", server.port, use_tls=False), aio=aio
    )
    kube.patch_node("n0", {"metadata": {"labels": {"a": "1"}}})
    kube.get_node("n0")
    methods = [m for m, _ in samples]
    assert methods == ["PATCH", "GET"]
    assert all(rtt > 0 for _, rtt in samples)
    kube.close()


def _arm_slow_serve_then_close(server, delay_s=0.4):
    """The next PATCH is served slowly, then the connection closes
    cleanly WITHOUT reading pipelined followers — the follower gets
    zero response bytes and was never executed server-side."""
    handler_cls = server.httpd.RequestHandlerClass
    orig = handler_cls.do_PATCH
    armed = {"on": True}

    def do_PATCH(self):
        fire = armed["on"]
        armed["on"] = False
        if fire:
            time.sleep(delay_s)
            orig(self)
            self.close_connection = True
            return
        orig(self)

    handler_cls.do_PATCH = do_PATCH


def _paired_pipeline(kube, server):
    """Issue PATCH A then (0.15s later, while A is still being served
    slowly) PATCH B — max_conns=1 forces B to pipeline behind A on the
    same connection. Returns (result_a, result_b) where each is the
    response dict or the raised ApiException."""
    results = {}

    def do(idx, label):
        try:
            results[idx] = kube.patch_node(
                "n0", {"metadata": {"labels": {label: "1"}}}
            )
        except ApiException as e:
            results[idx] = e

    ta = threading.Thread(target=do, args=(0, "a"))
    ta.start()
    time.sleep(0.15)
    tb = threading.Thread(target=do, args=(1, "b"))
    tb.start()
    ta.join(timeout=10)
    tb.join(timeout=10)
    return results[0], results[1]


def test_pipelined_follower_on_never_served_conn_is_terminal(server):
    """Replay legality is judged AT WRITE TIME: a request pipelined
    onto a connection that had never served a response when its bytes
    went out must NOT become replayable just because a sibling's
    response arrived before the close — the server may have executed
    it (code-review finding, pinned)."""
    server.store.add_node(make_node("n0"))
    kube = _facade(server, max_conns=1, window=2)
    _arm_slow_serve_then_close(server)
    w0 = server.store.node_write_stats()
    res_a, res_b = _paired_pipeline(kube, server)
    # A (the head) was served; B had zero response bytes on a conn
    # that had served NOTHING when B was written -> terminal
    assert isinstance(res_a, dict)
    assert isinstance(res_b, ApiException) and res_b.status == 0
    assert kube.stats()["replays"] == 0
    assert (server.store.node_write_stats()["requests"]
            - w0["requests"]) == 1  # only A executed
    kube.close()


def test_pipelined_follower_on_served_conn_replays_once(server):
    """The legal twin: the conn HAD served (a prior GET) before the
    follower was written, so the zero-bytes close is the stale
    keep-alive shape — the follower replays exactly once and both
    writes land exactly once."""
    server.store.add_node(make_node("n0"))
    kube = _facade(server, max_conns=1, window=2)
    kube.get_node("n0")  # served >= 1 before either PATCH is written
    _arm_slow_serve_then_close(server)
    w0 = server.store.node_write_stats()
    res_a, res_b = _paired_pipeline(kube, server)
    assert isinstance(res_a, dict)
    assert isinstance(res_b, dict)  # replayed, landed
    assert kube.stats()["replays"] == 1
    assert (server.store.node_write_stats()["requests"]
            - w0["requests"]) == 2  # each write exactly once
    labels = server.store.get_node("n0")["metadata"]["labels"]
    assert labels["a"] == "1" and labels["b"] == "1"
    kube.close()
