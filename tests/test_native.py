"""Native component tests: build via make, then exercise tpudevctl's
state-store interop with the Python device layer, and the C++ agent
end-to-end against the HTTP fake API server."""

import os
import shutil
import subprocess
import time

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.statefile import ModeStateStore
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.objects import make_node

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    """A free ephemeral port for the agent's health server (bind 0,
    read it back, release — the agent re-binds moments later)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
NATIVE = os.path.join(REPO, "native")
BUILD = os.path.join(NATIVE, "build")


@pytest.fixture(scope="module")
def native_build():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("native toolchain unavailable")
    r = subprocess.run(
        ["make", "-C", NATIVE], capture_output=True, text=True
    )
    assert r.returncode == 0, r.stderr
    return BUILD


def make_accel_tree(root, n=2):
    sysfs = root / "sysfs"
    dev = root / "dev"
    dev.mkdir(exist_ok=True)
    for i in range(n):
        d = sysfs / f"accel{i}" / "device"
        d.mkdir(parents=True)
        (d / "vendor").write_text("0x1ae0\n")
        (d / "device").write_text("0x0063\n")
        (dev / f"accel{i}").write_text("")
    return str(sysfs), str(dev)


def ctl_env(tmp_path, sysfs, dev):
    env = dict(os.environ)
    env.update(
        TPU_SYSFS_ROOT=sysfs,
        TPU_DEV_ROOT=dev,
        TPU_CC_STATE_DIR=str(tmp_path / "state"),
    )
    env.pop("CC_CAPABLE_DEVICE_IDS", None)
    return env


def ctl(native_build, env, *args):
    return subprocess.run(
        [os.path.join(native_build, "tpudevctl"), *args],
        capture_output=True, text=True, env=env,
    )


def test_tpudevctl_list(native_build, tmp_path):
    sysfs, dev = make_accel_tree(tmp_path)
    r = ctl(native_build, ctl_env(tmp_path, sysfs, dev), "list")
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert len(lines) == 2
    path, name, devid, is_switch, capable = lines[0].split()
    assert path.endswith("/accel0") and name == "tpu-v5p"
    assert devid == "0x0063" and is_switch == "0" and capable == "1"


def test_tpudevctl_allowlist(native_build, tmp_path):
    sysfs, dev = make_accel_tree(tmp_path)
    env = ctl_env(tmp_path, sysfs, dev)
    env["CC_CAPABLE_DEVICE_IDS"] = "0x005e"
    r = ctl(native_build, env, "list")
    assert all(line.split()[-1] == "0" for line in r.stdout.strip().splitlines())


def test_tpudevctl_state_interop_with_python(native_build, tmp_path):
    """C++ writes, Python reads (and vice versa) — same on-disk layout."""
    sysfs, dev = make_accel_tree(tmp_path, n=1)
    env = ctl_env(tmp_path, sysfs, dev)
    devpath = dev + "/accel0"
    state_dir = env["TPU_CC_STATE_DIR"]

    # C++ stage + commit -> Python sees effective
    assert ctl(native_build, env, "stage", devpath, "cc", "on").returncode == 0
    store = ModeStateStore(state_dir)
    assert store.staged(devpath, "cc") == "on"
    assert store.effective(devpath, "cc") == "off"
    assert ctl(native_build, env, "commit", devpath).returncode == 0
    assert store.effective(devpath, "cc") == "on"

    # Python stage -> C++ query staged; C++ discard -> staged reverts
    store.stage(devpath, "ici", "on")
    r = ctl(native_build, env, "staged", devpath, "ici")
    assert r.stdout.strip() == "on"
    assert ctl(native_build, env, "discard", devpath).returncode == 0
    assert store.staged(devpath, "ici") == "off"
    r = ctl(native_build, env, "query", devpath, "cc")
    assert r.stdout.strip() == "on"


@pytest.fixture()
def apiserver():
    with FakeApiServer() as s:
        yield s


def test_cpp_agent_reconciles_label_changes(native_build, apiserver, tmp_path):
    """The native agent watches the node and execs the engine command per
    change (coalesced). The engine command here is a stub that appends the
    mode to a file."""
    out_file = tmp_path / "engine-calls.txt"
    apiserver.store.add_node(
        make_node("cnode", labels={L.CC_MODE_LABEL: "off"})
    )
    env = dict(os.environ)
    env.update(
        NODE_NAME="cnode",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if out_file.exists() and "off" in out_file.read_text():
                break
            time.sleep(0.05)
        assert out_file.exists() and out_file.read_text().split() == ["off"]

        apiserver.store.set_node_labels("cnode", {L.CC_MODE_LABEL: "on"})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if out_file.read_text().split() == ["off", "on"]:
                break
            time.sleep(0.05)
        assert out_file.read_text().split() == ["off", "on"]

        # label removal -> nothing (no default set); unrelated label -> no call
        apiserver.store.set_node_labels("cnode", {"unrelated": "x"})
        time.sleep(1)
        assert out_file.read_text().split() == ["off", "on"]
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_applies_default_when_label_absent(
    native_build, apiserver, tmp_path
):
    out_file = tmp_path / "calls.txt"
    apiserver.store.add_node(make_node("dnode"))
    env = dict(os.environ)
    env.update(
        NODE_NAME="dnode",
        DEFAULT_CC_MODE="devtools",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if out_file.exists() and out_file.read_text().strip():
                break
            time.sleep(0.05)
        assert out_file.read_text().split() == ["devtools"]
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_requires_node_name(native_build):
    env = dict(os.environ)
    env.pop("NODE_NAME", None)
    r = subprocess.run(
        [os.path.join(BUILD, "tpu-cc-manager-agent")],
        capture_output=True, text=True, env=env, timeout=10,
    )
    assert r.returncode == 1
    assert "NODE_NAME" in r.stderr


def test_cpp_agent_coalesces_burst(native_build, apiserver, tmp_path):
    """A burst of label flips while the engine is busy collapses to the
    latest value (reference cmd/main.go:48-76 semantics)."""
    out_file = tmp_path / "calls.txt"
    apiserver.store.add_node(make_node("bnode", labels={L.CC_MODE_LABEL: "off"}))
    env = dict(os.environ)
    env.update(
        NODE_NAME="bnode",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        # engine takes 1s: the burst lands while it runs
        TPU_CC_ENGINE_CMD=f"sh -c 'sleep 1; echo %s >> {out_file}'",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if out_file.exists() and "off" in out_file.read_text():
                break
            time.sleep(0.05)
        for m in ("on", "devtools", "ici", "on"):
            apiserver.store.set_node_labels("bnode", {L.CC_MODE_LABEL: m})
        # poll for convergence + quiescence (1-core sandbox: fixed sleeps
        # are flaky; breaking on the first trailing "on" could sample
        # mid-burst and miss extra per-flip engine runs)
        deadline = time.monotonic() + 20
        calls: list = []
        stable_since = time.monotonic()
        while time.monotonic() < deadline:
            new = out_file.read_text().split()
            if new != calls:
                calls, stable_since = new, time.monotonic()
            elif calls and calls[-1] == "on" and \
                    time.monotonic() - stable_since > 2.5:
                break
            time.sleep(0.2)
        assert calls[0] == "off"
        assert calls[-1] == "on"
        # the burst must NOT have produced one call per flip
        assert len(calls) <= 3
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_python_ctypes_binding_interop(native_build, tmp_path, monkeypatch):
    """SysfsTpuBackend routes through libtpudev.so when TPU_CC_NATIVE_LIB
    is set; state written natively is identical to the pure-Python layout."""
    from tpu_cc_manager.device.native import load_native_store
    from tpu_cc_manager.device.tpu import SysfsTpuBackend

    lib = os.path.join(native_build, "libtpudev.so")
    monkeypatch.setenv("TPU_CC_NATIVE_LIB", lib)
    state_dir = str(tmp_path / "state")
    native = load_native_store(state_dir)
    assert native is not None

    sysfs, dev = make_accel_tree(tmp_path, n=1)
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev, state_dir=state_dir)
    assert type(be.store).__name__ == "NativeModeStateStore"
    (chip,), _ = be.find_tpus()
    chip.set_cc_mode("on")
    chip.reset()
    assert chip.query_cc_mode() == "on"
    # pure-Python store reads the same bytes
    py_store = ModeStateStore(state_dir)
    assert py_store.effective(chip.path, "cc") == "on"


# ---------------------------------------------------------------------
# Proxy-sidecar topology (deployments/manifests/daemonset-native.yaml)
# ---------------------------------------------------------------------

class LoopbackProxy:
    """Pod-local loopback relay standing in for the `kubectl proxy`
    sidecar the native DaemonSet manifest declares: the agent and the
    bash engine dial 127.0.0.1:<port>, the relay forwards the byte
    stream (including the chunked watch long-poll) to the API server.
    kubectl proxy additionally owns TLS + SA auth; the fake API server
    speaks plain HTTP, so a transparent relay reproduces the exact
    in-pod network topology."""

    def __init__(self, upstream_port):
        import socket

        self.upstream_port = upstream_port
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self.connections = 0
        import threading

        self._t = threading.Thread(target=self._accept_loop, daemon=True)
        self._t.start()

    def _accept_loop(self):
        import socket
        import threading

        while not self._stop:
            try:
                client, _ = self._srv.accept()
            except OSError:
                return
            self.connections += 1
            try:
                upstream = socket.create_connection(
                    ("127.0.0.1", self.upstream_port)
                )
            except OSError:
                client.close()
                continue
            for a, b in ((client, upstream), (upstream, client)):
                threading.Thread(
                    target=self._pump, args=(a, b), daemon=True
                ).start()

    @staticmethod
    def _pump(src, dst):
        import socket

        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def stop(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass


def test_cpp_agent_full_native_path_through_proxy_sidecar(
    native_build, apiserver, tmp_path
):
    """The exact wiring daemonset-native.yaml schedules: C++ agent →
    loopback proxy hop → API server for the watch, and per reconcile the
    agent execs the bash engine, which drives devices through tpudevctl
    and publishes the state label through the same proxy hop."""
    sysfs, dev = make_accel_tree(tmp_path, n=2)
    apiserver.store.add_node(
        make_node("native-node", labels={L.CC_MODE_LABEL: "off"})
    )
    proxy = LoopbackProxy(apiserver.port)
    script = os.path.join(REPO, "scripts", "tpu-cc-manager.sh")
    env = dict(os.environ)
    env.pop("CC_CAPABLE_DEVICE_IDS", None)
    env.update(
        NODE_NAME="native-node",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(proxy.port),
        TPU_CC_ENGINE_CMD=f"bash {script} set-cc-mode -a -m %s",
        TPU_SYSFS_ROOT=sysfs,
        TPU_DEV_ROOT=dev,
        TPU_CC_STATE_DIR=str(tmp_path / "state"),
        TPUDEVCTL=os.path.join(native_build, "tpudevctl"),
        EVICT_OPERATOR_COMPONENTS="false",
        CC_READINESS_FILE=str(tmp_path / "run" / ".ready"),
        # the TEE rung rides the native path too: the bash engine
        # extends the measured log before publishing evidence
        TPU_CC_ATTESTATION="fake",
        TPU_CC_TPM_STATE_DIR=str(tmp_path / "tpm"),
        TPU_CC_TPM_KEY="native-aik",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )

    def state_label():
        node = apiserver.store.get_node("native-node")
        return node["metadata"]["labels"].get(L.CC_MODE_STATE_LABEL)

    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and state_label() != "off":
            time.sleep(0.1)
        assert state_label() == "off", "initial reconcile never completed"

        apiserver.store.set_node_labels(
            "native-node", {L.CC_MODE_LABEL: "on"}
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and state_label() != "on":
            time.sleep(0.1)
        assert state_label() == "on", "flip through proxy never completed"

        # the device store really flipped (bash engine → tpudevctl)
        store = ModeStateStore(str(tmp_path / "state"))
        for i in range(2):
            assert store.effective(f"{dev}/accel{i}", "cc") == "on"
        # every byte travelled the sidecar hop
        assert proxy.connections > 0
        # the engine touches the readiness file after the state label
        # (with evidence publication in between — poll, don't race)
        deadline = time.monotonic() + 15
        while (time.monotonic() < deadline
               and not os.path.exists(env["CC_READINESS_FILE"])):
            time.sleep(0.1)
        # reference :536 parity
        assert os.path.exists(env["CC_READINESS_FILE"])

        # the TEE rung on the native path: the bash engine extended
        # the measured log before publishing, so the evidence carries
        # a quote whose history ends at the real flip
        import json as _json

        from tpu_cc_manager.attest import judge_attestation

        from tpu_cc_manager.evidence import evidence_mode

        deadline = time.monotonic() + 15
        verdict, detail = "missing", ""
        while time.monotonic() < deadline:
            raw = apiserver.store.get_node("native-node")[
                "metadata"].get("annotations", {}).get(
                L.EVIDENCE_ANNOTATION)
            if raw:
                doc = _json.loads(raw)
                # wait for the POST-FLIP document (the initial off
                # reconcile publishes an attested doc too, with an
                # empty measured log)
                if doc.get("attestation") and \
                        evidence_mode(doc) == "on":
                    verdict, detail = judge_attestation(
                        doc, "native-node",
                        key=env["TPU_CC_TPM_KEY"].encode())
                    break
            time.sleep(0.2)
        assert verdict == "ok", (verdict, detail)
        from tpu_cc_manager.attest import measured_mode

        assert measured_mode(doc["attestation"]["log"]) == "on"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
        proxy.stop()


def test_cpp_agent_publishes_failed_on_invalid_mode(
    native_build, apiserver, tmp_path
):
    """An invalid desired mode is refused before exec (shell-injection
    guard), but the refusal must still be visible cluster-wide as
    cc.mode.state=failed (reference main.py:300-307 contract)."""
    out_file = tmp_path / "calls.txt"
    apiserver.store.add_node(
        make_node("inode", labels={L.CC_MODE_LABEL: "off"})
    )
    env = dict(os.environ)
    env.update(
        NODE_NAME="inode",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )

    def state_label():
        node = apiserver.store.get_node("inode")
        return node["metadata"]["labels"].get(L.CC_MODE_STATE_LABEL)

    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if out_file.exists() and "off" in out_file.read_text():
                break
            time.sleep(0.05)
        assert out_file.exists()

        apiserver.store.set_node_labels("inode", {L.CC_MODE_LABEL: "rm -rf"})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and state_label() != "failed":
            time.sleep(0.05)
        assert state_label() == "failed"
        # the invalid value never reached a shell
        assert out_file.read_text().split() == ["off"]
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_bookmarks_prevent_410_relists(
    native_build, apiserver, tmp_path
):
    """With allowWatchBookmarks the agent's resume rv stays current
    through idle periods, so short watch reconnects never hit 410 even
    after the server compacts its event history (client-go informer
    parity; Python twin behavior in watch.py)."""
    out_file = tmp_path / "calls.txt"
    err_file = open(tmp_path / "agent-stderr.log", "w")
    apiserver.store.bookmark_every_s = 0.2
    apiserver.store.add_node(
        make_node("bmnode", labels={L.CC_MODE_LABEL: "off"})
    )
    env = dict(os.environ)
    env.update(
        NODE_NAME="bmnode",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
        TPU_CC_WATCH_TIMEOUT_S="1",  # force frequent resumes
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=err_file, text=True,
    )
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if out_file.exists() and "off" in out_file.read_text():
                break
            time.sleep(0.05)
        assert out_file.exists()

        # several reconnect cycles, each with the history compacted so a
        # stale-rv resume would 410 into a re-list
        for _ in range(3):
            time.sleep(1.3)
            apiserver.store.compact_watch_history()

        apiserver.store.set_node_labels("bmnode", {L.CC_MODE_LABEL: "on"})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if "on" in out_file.read_text():
                break
            time.sleep(0.05)
        assert out_file.read_text().split()[-1] == "on"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
        err_file.close()
    stderr = (tmp_path / "agent-stderr.log").read_text()
    assert "watch 410" not in stderr, stderr


def test_cpp_agent_bearer_token_auth(native_build, tmp_path):
    """BEARER_TOKEN_FILE path: the agent authenticates every request
    (list, watch, state-label PATCH via the engine stub's curl-free
    echo) against a token-gated API server — the direct plain-HTTP
    deployment shape the agent header documents."""
    out_file = tmp_path / "calls.txt"
    token_file = tmp_path / "token"
    token_file.write_text("s3cret-token\n")  # trailing newline is stripped
    with FakeApiServer(required_token="s3cret-token") as srv:
        srv.store.add_node(
            make_node("authnode", labels={L.CC_MODE_LABEL: "off"})
        )
        env = dict(os.environ)
        env.update(
            NODE_NAME="authnode",
            KUBE_API_HOST="127.0.0.1",
            KUBE_API_PORT=str(srv.port),
            BEARER_TOKEN_FILE=str(token_file),
            TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
        )
        proc = subprocess.Popen(
            [os.path.join(native_build, "tpu-cc-manager-agent")],
            env=env, stderr=subprocess.DEVNULL,
        )
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if out_file.exists() and "off" in out_file.read_text():
                    break
                time.sleep(0.05)
            assert out_file.exists() and "off" in out_file.read_text()
            srv.store.set_node_labels("authnode", {L.CC_MODE_LABEL: "on"})
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if "on" in out_file.read_text():
                    break
                time.sleep(0.05)
            assert out_file.read_text().split() == ["off", "on"]

            # the agent's own state PATCH (invalid-mode path) also carries
            # the token: it must succeed against the gated server
            srv.store.set_node_labels("authnode", {L.CC_MODE_LABEL: "nope"})
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                labels = srv.store.get_node("authnode")["metadata"]["labels"]
                if labels.get(L.CC_MODE_STATE_LABEL) == "failed":
                    break
                time.sleep(0.05)
            assert labels.get(L.CC_MODE_STATE_LABEL) == "failed"
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()


# ------------------------------------------------------------ direct TLS
# (self-signed PKI comes from the shared tls_pki session fixture in
# conftest.py — also used by the bash engine's KUBE_API_TLS test)


def test_cpp_agent_direct_https(native_build, tmp_path, tls_pki):
    """VERDICT r2 item 8: the native agent speaks HTTPS directly — no
    kubectl-proxy sidecar — verifying the cluster CA and sending the
    service-account bearer token. Transport is an `openssl s_client`
    child per connection (no TLS library is linked); a full label->state
    watch round trip must work over it."""
    cert, key = tls_pki
    token_file = tmp_path / "token"
    token_file.write_text("sa-secret-token\n")
    out_file = tmp_path / "calls.txt"
    with FakeApiServer(required_token="sa-secret-token",
                       tls_cert=cert, tls_key=key) as srv:
        srv.store.add_node(make_node("tls-node",
                                     labels={L.CC_MODE_LABEL: "off"}))
        env = dict(os.environ)
        env.update(
            NODE_NAME="tls-node",
            KUBE_API_HOST="127.0.0.1",
            KUBE_API_PORT=str(srv.port),
            KUBE_API_TLS="true",
            KUBE_CA_FILE=cert,
            BEARER_TOKEN_FILE=str(token_file),
            TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
            TPU_CC_WATCH_TIMEOUT_S="5",
        )
        proc = subprocess.Popen(
            [os.path.join(native_build, "tpu-cc-manager-agent")],
            env=env, stderr=subprocess.PIPE, text=True)
        try:
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if out_file.exists() and "off" in out_file.read_text():
                    break
                time.sleep(0.05)
            assert out_file.exists(), "initial reconcile never ran over TLS"

            srv.store.set_node_labels("tls-node", {L.CC_MODE_LABEL: "on"})
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if out_file.read_text().split() == ["off", "on"]:
                    break
                time.sleep(0.05)
            assert out_file.read_text().split() == ["off", "on"]
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()


def test_cpp_agent_tls_requires_readable_ca(native_build, tmp_path):
    """Fail-closed config: KUBE_API_TLS without a readable CA file must
    exit immediately, never run a trust-anything client."""
    env = dict(os.environ)
    env.update(
        NODE_NAME="x",
        KUBE_API_TLS="true",
        KUBE_CA_FILE=str(tmp_path / "missing-ca.pem"),
    )
    r = subprocess.run(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, capture_output=True, text=True, timeout=10)
    assert r.returncode == 1
    assert "unreadable" in r.stderr


def test_cpp_agent_wrong_ca_rejected(native_build, tmp_path, tls_pki):
    """A server whose cert doesn't chain to the configured CA must be
    rejected (s_client -verify_return_error): no request succeeds."""
    cert, key = tls_pki
    # a DIFFERENT self-signed CA the server's cert does not chain to
    other = tmp_path / "other.pem"
    r = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(tmp_path / "other-key.pem"), "-out", str(other),
         "-days", "1", "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    out_file = tmp_path / "calls.txt"
    with FakeApiServer(tls_cert=cert, tls_key=key) as srv:
        srv.store.add_node(make_node("bad-ca-node",
                                     labels={L.CC_MODE_LABEL: "on"}))
        env = dict(os.environ)
        env.update(
            NODE_NAME="bad-ca-node",
            KUBE_API_HOST="127.0.0.1",
            KUBE_API_PORT=str(srv.port),
            KUBE_API_TLS="true",
            KUBE_CA_FILE=str(other),
            TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
        )
        proc = subprocess.Popen(
            [os.path.join(native_build, "tpu-cc-manager-agent")],
            env=env, stderr=subprocess.DEVNULL)
        try:
            time.sleep(4)  # several startup read attempts
            assert not out_file.exists()  # nothing EVER reconciled
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()


def test_cpp_agent_runs_doctor_on_idle_tick(native_build, apiserver, tmp_path):
    """Native-path parity with the Python agent's periodic doctor
    self-check: on idle ticks (never concurrently with a reconcile) the
    agent execs TPU_CC_DOCTOR_CMD every TPU_CC_DOCTOR_INTERVAL_S."""
    engine_file = tmp_path / "engine.txt"
    doctor_file = tmp_path / "doctor.txt"
    apiserver.store.add_node(
        make_node("docnode", labels={L.CC_MODE_LABEL: "off"})
    )
    env = dict(os.environ)
    env.update(
        NODE_NAME="docnode",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD=f"echo %s >> {engine_file}",
        TPU_CC_DOCTOR_CMD=f"echo tick >> {doctor_file}",
        TPU_CC_DOCTOR_INTERVAL_S="1",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if (doctor_file.exists()
                    and len(doctor_file.read_text().split()) >= 2):
                break
            time.sleep(0.1)
        ticks = doctor_file.read_text().split() if doctor_file.exists() else []
        assert len(ticks) >= 2, f"doctor never ran periodically: {ticks}"
        # the reconcile path still worked alongside
        assert engine_file.read_text().split() == ["off"]
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_doctor_disabled_with_zero_interval(
    native_build, apiserver, tmp_path
):
    doctor_file = tmp_path / "doctor.txt"
    apiserver.store.add_node(
        make_node("nodoc", labels={L.CC_MODE_LABEL: "off"})
    )
    env = dict(os.environ)
    env.update(
        NODE_NAME="nodoc",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD="true",
        TPU_CC_DOCTOR_CMD=f"echo tick >> {doctor_file}",
        TPU_CC_DOCTOR_INTERVAL_S="0",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        time.sleep(3)
        assert not doctor_file.exists()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_health_surface(native_build, apiserver, tmp_path):
    """VERDICT r3 weak #5: the native agent serves its own /healthz +
    /metrics (watch-loop liveness, last reconcile outcome, doctor
    verdict) so daemonset-native*.yaml can probe the agent container
    directly instead of a sidecar."""
    import urllib.request

    out_file = tmp_path / "calls.txt"
    apiserver.store.add_node(
        make_node("hnode", labels={L.CC_MODE_LABEL: "on"})
    )
    port = _free_port()
    env = dict(os.environ)
    env.update(
        NODE_NAME="hnode",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
        HEALTH_PORT=str(port),
        TPU_CC_DOCTOR_INTERVAL_S="1",
        TPU_CC_DOCTOR_CMD="exit 1",  # a failing doctor, visible in metrics
        TPU_CC_WATCH_TIMEOUT_S="2",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=2) as r:
                return r.status, r.read().decode()

        deadline = time.monotonic() + 10
        body = ""
        while time.monotonic() < deadline:
            try:
                status, body = get("/healthz")
                if status == 200:
                    break
            except OSError:
                time.sleep(0.1)
        assert body.strip() == "ok"

        # metrics reflect the initial reconcile and, shortly, the
        # (failing) doctor verdict from the idle tick
        deadline = time.monotonic() + 15
        metrics = ""
        while time.monotonic() < deadline:
            _, metrics = get("/metrics")
            if ('tpu_cc_native_reconciles_total{outcome="success"} 1'
                    in metrics
                    and "tpu_cc_native_doctor_last_rc 1" in metrics):
                break
            time.sleep(0.2)
        assert 'tpu_cc_native_reconciles_total{outcome="success"} 1' \
            in metrics
        assert "tpu_cc_native_last_reconcile_rc 0" in metrics
        assert "tpu_cc_native_doctor_last_rc 1" in metrics
        assert "tpu_cc_native_watch_idle_seconds" in metrics

        status, _ = get("/healthz")
        assert status == 200  # watch loop alive

        # unknown route
        import urllib.error
        try:
            get("/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_doctor_timeout_does_not_stall_reconciles(
        native_build, apiserver, tmp_path):
    """ADVICE r3: a wedged doctor child must not convert the idle-tick
    diagnostic into an enforcement outage — the agent kills it at
    TPU_CC_DOCTOR_TIMEOUT_S and keeps reconciling label changes."""
    out_file = tmp_path / "calls.txt"
    apiserver.store.add_node(
        make_node("dnode", labels={L.CC_MODE_LABEL: "off"})
    )
    env = dict(os.environ)
    env.update(
        NODE_NAME="dnode",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
        TPU_CC_DOCTOR_INTERVAL_S="1",
        TPU_CC_DOCTOR_CMD="sleep 300",  # wedged doctor
        TPU_CC_DOCTOR_TIMEOUT_S="1",
        TPU_CC_WATCH_TIMEOUT_S="2",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if out_file.exists() and "off" in out_file.read_text():
                break
            time.sleep(0.05)
        assert out_file.exists(), "initial reconcile never ran"
        # let the idle tick start (and kill) the wedged doctor, then
        # prove reconciliation still works
        time.sleep(2.5)
        apiserver.store.set_node_labels("dnode", {L.CC_MODE_LABEL: "on"})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if "on" in out_file.read_text():
                break
            time.sleep(0.05)
        assert out_file.read_text().split() == ["off", "on"], \
            "a wedged doctor stalled reconciliation"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_evidence_sync_heals_missing_evidence(
        native_build, apiserver, tmp_path):
    """The native path's idle-tick evidence healer: the agent execs
    `python -m tpu_cc_manager.evidence --sync` periodically, so a node
    whose evidence never got published (here: a stub engine that
    publishes nothing) converges to verifiable on-cluster evidence
    without any flip."""
    import json

    from tpu_cc_manager.evidence import verify_evidence

    out_file = tmp_path / "calls.txt"
    sysfs, dev = make_accel_tree(tmp_path)
    kubeconfig = tmp_path / "kubeconfig.yaml"
    kubeconfig.write_text(f"""
apiVersion: v1
kind: Config
current-context: t
contexts: [{{name: t, context: {{cluster: c, user: u}}}}]
clusters: [{{name: c, cluster: {{server: "http://127.0.0.1:{apiserver.port}"}}}}]
users: [{{name: u, user: {{}}}}]
""")
    apiserver.store.add_node(
        make_node("ev-sync-node", labels={L.CC_MODE_LABEL: "on"})
    )
    env = dict(os.environ)
    env.update(
        NODE_NAME="ev-sync-node",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        KUBECONFIG=str(kubeconfig),
        PYTHONPATH=REPO,
        TPU_SYSFS_ROOT=sysfs,
        TPU_DEV_ROOT=dev,
        TPU_CC_STATE_DIR=str(tmp_path / "state"),
        TPU_CC_DEVICE_GATING="none",
        TPU_CC_IDENTITY="none",
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",  # publishes nothing
        TPU_CC_EVIDENCE_SYNC_INTERVAL_S="1",
        TPU_CC_DOCTOR_INTERVAL_S="0",
        TPU_CC_WATCH_TIMEOUT_S="2",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 20
        doc = None
        while time.monotonic() < deadline:
            ann = apiserver.store.get_node("ev-sync-node")["metadata"] \
                .get("annotations", {})
            raw = ann.get(L.EVIDENCE_ANNOTATION)
            if raw:
                doc = json.loads(raw)
                break
            time.sleep(0.2)
        assert doc is not None, "evidence sync never published"
        assert doc["node"] == "ev-sync-node"
        assert verify_evidence(doc, key=None)[0] is True
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_key_posture_change_syncs_immediately(
        native_build, apiserver, tmp_path):
    """The native agent's key-posture watch: the evidence-key Secret
    landing (kubelet updates the mounted file in place) must trigger
    the evidence sync NOW, not after the full
    TPU_CC_EVIDENCE_SYNC_INTERVAL_S (the residual 300 s window the
    round-3 security doc recorded). Here the interval is set far past
    the test horizon, so a prompt re-sign can only come from the
    stat-signature watch."""
    import json

    from tpu_cc_manager.evidence import verify_evidence

    out_file = tmp_path / "calls.txt"
    sysfs, dev = make_accel_tree(tmp_path)
    kubeconfig = tmp_path / "kubeconfig.yaml"
    kubeconfig.write_text(f"""
apiVersion: v1
kind: Config
current-context: t
contexts: [{{name: t, context: {{cluster: c, user: u}}}}]
clusters: [{{name: c, cluster: {{server: "http://127.0.0.1:{apiserver.port}"}}}}]
users: [{{name: u, user: {{}}}}]
""")
    apiserver.store.add_node(
        make_node("key-watch-node", labels={L.CC_MODE_LABEL: "on"})
    )
    key_file = tmp_path / "evidence-key"  # absent at start
    env = dict(os.environ)
    env.update(
        NODE_NAME="key-watch-node",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        KUBECONFIG=str(kubeconfig),
        PYTHONPATH=REPO,
        TPU_SYSFS_ROOT=sysfs,
        TPU_DEV_ROOT=dev,
        TPU_CC_STATE_DIR=str(tmp_path / "state"),
        TPU_CC_DEVICE_GATING="none",
        TPU_CC_IDENTITY="none",
        TPU_CC_EVIDENCE_KEY_FILE=str(key_file),
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",  # publishes nothing
        # far beyond the poll deadline: only the posture watch can
        # make the second sync happen in time
        TPU_CC_EVIDENCE_SYNC_INTERVAL_S="3600",
        TPU_CC_DOCTOR_INTERVAL_S="0",
        TPU_CC_WATCH_TIMEOUT_S="2",
    )
    health_port = _free_port()
    env["HEALTH_PORT"] = str(health_port)
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        def evidence(pred, deadline_s):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                raw = (apiserver.store.get_node("key-watch-node")
                       ["metadata"].get("annotations", {})
                       .get(L.EVIDENCE_ANNOTATION))
                if raw:
                    doc = json.loads(raw)
                    if pred(doc):
                        return doc
                time.sleep(0.2)
            return None

        # startup sync (due=0) publishes a plain-sha256 document
        doc = evidence(
            lambda d: d["digest"].startswith("sha256:"), 20,
        )
        assert doc is not None, "startup evidence sync never published"

        # the Secret lands: the posture watch must re-sign promptly,
        # 3600 s before the interval would
        key_file.write_bytes(b"pool-key")
        doc = evidence(
            lambda d: d["digest"].startswith("hmac-sha256:"), 15,
        )
        assert doc is not None, (
            "evidence not re-signed after key file appeared"
        )
        assert verify_evidence(doc, key=b"pool-key") == (True, "ok")

        # rotation visibility on /metrics: the posture watch fired and
        # both syncs (startup + posture-change) succeeded. Polled: the
        # annotation lands while the sync CHILD is still exiting, and
        # the counter only advances once the parent reaps it
        import urllib.request

        deadline = time.monotonic() + 10
        metrics = ""
        while time.monotonic() < deadline:
            metrics = urllib.request.urlopen(
                f"http://127.0.0.1:{health_port}/metrics", timeout=5,
            ).read().decode()
            if ('tpu_cc_native_evidence_syncs_total'
                    '{outcome="success"} 2') in metrics:
                break
            time.sleep(0.2)
        assert "tpu_cc_native_key_posture_changes_total 1" in metrics
        assert ('tpu_cc_native_evidence_syncs_total{outcome="success"}'
                " 2") in metrics
        assert ('tpu_cc_native_evidence_syncs_total{outcome="failure"}'
                " 0") in metrics

        # the ATTESTATION key is part of the same posture signature: a
        # rotated TPM key must re-quote as promptly as a rotated pool
        # key re-signs (the sync rebuild picks the new key up)
        tpm_key_file = tmp_path / "tpm-key"
        # note: TPU_CC_TPM_KEY_FILE was NOT in env at start — the env
        # var must be set for the watch to consider it; this test
        # restarts with it set
        proc.terminate()
        proc.wait(timeout=5)
        env["TPU_CC_TPM_KEY_FILE"] = str(tpm_key_file)
        env["TPU_CC_ATTESTATION"] = "fake"
        env["TPU_CC_TPM_STATE_DIR"] = str(tmp_path / "tpm")
        proc = subprocess.Popen(
            [os.path.join(native_build, "tpu-cc-manager-agent")],
            env=env, stderr=subprocess.PIPE, text=True,
        )
        doc = evidence(lambda d: d.get("attestation") is not None, 20)
        assert doc is not None, "attested evidence never published"
        before_sig = doc["attestation"].get("sig")
        tpm_key_file.write_bytes(b"aik-rotated")
        doc = evidence(
            lambda d: d.get("attestation", {}).get("sig")
            not in (None, before_sig), 15,
        )
        assert doc is not None, (
            "quote not re-signed after TPM key rotation"
        )
        from tpu_cc_manager.attest import judge_attestation

        assert judge_attestation(
            doc, "key-watch-node", key=b"aik-rotated")[0] == "ok"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cpp_agent_metrics_complete_and_new_counters(
        native_build, apiserver, tmp_path):
    """VERDICT r4 weak #5: the native /metrics body is assembled
    dynamically (the old fixed 1536-byte snprintf silently truncated
    mid-line once more series were added, and Prometheus rejects a
    truncated scrape wholesale). Assert the exposition is COMPLETE —
    every # TYPE has at least one sample, every non-comment line is a
    well-formed sample, the body ends in a newline — and that the two
    round-5 series are live: watch reconnects climb under a 1s stream
    timeout, and reconciles on a slice-labeled node count as slice
    delegations."""
    import re
    import urllib.request

    out_file = tmp_path / "calls.txt"
    apiserver.store.add_node(make_node("mnode", labels={
        L.CC_MODE_LABEL: "on",
        L.TPU_SLICE_LABEL: "slice-7",
    }))
    port = _free_port()
    env = dict(os.environ)
    env.update(
        NODE_NAME="mnode",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(apiserver.port),
        TPU_CC_ENGINE_CMD=f"echo %s >> {out_file}",
        HEALTH_PORT=str(port),
        TPU_CC_DOCTOR_INTERVAL_S="0",
        TPU_CC_WATCH_TIMEOUT_S="1",
    )
    proc = subprocess.Popen(
        [os.path.join(native_build, "tpu-cc-manager-agent")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        def metrics():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=2) as r:
                return r.read().decode()

        deadline = time.monotonic() + 15
        body = ""
        while time.monotonic() < deadline:
            try:
                body = metrics()
            except OSError:
                time.sleep(0.2)
                continue
            if ("tpu_cc_native_watch_reconnects_total 0" not in body
                    and "tpu_cc_native_watch_reconnects_total" in body
                    and 'outcome="success"} 1' in body):
                break
            time.sleep(0.3)

        # -- completeness: the exposition parses as full Prometheus text
        assert body.endswith("\n"), "body must not be cut mid-line"
        sample_re = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?\d+$"
        )
        declared = []
        samples = {}
        for line in body.splitlines():
            if line.startswith("# TYPE "):
                declared.append(line.split()[2])
            elif line.startswith("#"):
                continue
            else:
                assert sample_re.match(line), f"malformed line: {line!r}"
                samples.setdefault(line.split("{")[0].split()[0], []
                                   ).append(line)
        assert declared, body
        for name in declared:
            assert samples.get(name), (
                f"# TYPE {name} has no sample — truncated exposition"
            )

        # -- the two round-5 series
        assert "tpu_cc_native_slice_delegations_total 1" in body, body
        m = re.search(r"tpu_cc_native_watch_reconnects_total (\d+)",
                      body)
        assert m and int(m.group(1)) >= 1, (
            "1s stream timeouts must produce reconnects: " + body
        )
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
