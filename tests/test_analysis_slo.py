"""ccaudit slo cross-check (ISSUE 9 satellite): slo.yaml schema gating
plus the metric-liveness extension of the one-declaration-per-name
rule, with the pragma escape hatch."""

import os
import textwrap

import pytest

yaml = pytest.importorskip("yaml")

from tpu_cc_manager.analysis.slo import slo_findings  # noqa: E402

DECLARED = {
    "tpu_cc_reconciles_total",
    "tpu_cc_reconcile_duration_seconds",
    "tpu_cc_publications_dropped_total",
}

GOOD = """\
version: 1
objectives:
  - name: flip-success
    kind: error_ratio
    metric: tpu_cc_reconciles_total
    bad_labels:
      outcome: [failure]
    target: 0.99
    windows: {fast_s: 2, slow_s: 10}
    burn_threshold: 2.0
"""


def _write(tmp_path, text):
    d = tmp_path / "deployments"
    d.mkdir(exist_ok=True)
    (d / "slo.yaml").write_text(textwrap.dedent(text))
    return str(tmp_path)


def test_clean_file_yields_no_findings(tmp_path):
    root = _write(tmp_path, GOOD)
    assert slo_findings(root, DECLARED) == []


def test_missing_file_is_loud(tmp_path):
    with pytest.raises(FileNotFoundError):
        slo_findings(str(tmp_path), DECLARED)


def test_schema_violation_is_a_manifest_drift_finding(tmp_path):
    root = _write(tmp_path, GOOD.replace("target: 0.99", "target: 1.5"))
    (f,) = slo_findings(root, DECLARED)
    assert f.rule == "manifest-drift"
    assert "schema violation" in f.message
    assert "flip-success" in f.message


def test_unparseable_yaml_is_a_finding_not_a_crash(tmp_path):
    root = _write(tmp_path, "version: 1\nobjectives: [\n")
    (f,) = slo_findings(root, DECLARED)
    assert f.rule == "manifest-drift"
    assert "unparseable" in f.message


def test_undeclared_metric_fails_liveness(tmp_path):
    """The extended one-declaration-per-metric-name rule: an objective
    over a metric nobody declares (and so nobody renders) can never
    fire — that must fail the lint tier."""
    root = _write(tmp_path, GOOD.replace(
        "tpu_cc_reconciles_total", "tpu_cc_reconciles_typo_total"))
    (f,) = slo_findings(root, DECLARED)
    assert f.rule == "metric-name"
    assert "tpu_cc_reconciles_typo_total" in f.message
    assert "never fire" in f.message
    # the finding anchors on the referencing line
    assert "tpu_cc_reconciles_typo_total" in f.text


def test_total_metric_is_liveness_checked_too(tmp_path):
    root = _write(tmp_path, """\
        version: 1
        objectives:
          - name: publish-loss
            kind: error_ratio
            metric: tpu_cc_publications_dropped_total
            total_metric: tpu_cc_nope_total
            target: 0.999
            windows: {fast_s: 2, slow_s: 10}
            burn_threshold: 2.0
        """)
    (f,) = slo_findings(root, DECLARED)
    assert f.rule == "metric-name"
    assert "tpu_cc_nope_total" in f.message


def test_pragma_escape_hatch_suppresses_liveness(tmp_path):
    """Externally-scraped series are legitimate objectives; the pragma
    (with a mandatory reason, on or above the line) sanctions them."""
    root = _write(tmp_path, """\
        version: 1
        objectives:
          - name: external
            kind: error_ratio
            # ccaudit: allow-metric-name(scraped from kube-state-metrics)
            metric: tpu_cc_external_errors_total
            bad_labels:
              outcome: [failure]
            target: 0.99
            windows: {fast_s: 2, slow_s: 10}
            burn_threshold: 2.0
        """)
    assert slo_findings(root, DECLARED) == []


def test_committed_slo_yaml_is_clean_against_the_live_registry():
    """The repo's own deployments/slo.yaml must reference only metrics
    the code declares — the in-repo half of the CI gate."""
    from tpu_cc_manager.analysis.core import (
        iter_python_files, load_module, repo_root,
    )
    from tpu_cc_manager.analysis.rules import audit_module

    root = repo_root()
    declared = set()
    for rel in iter_python_files(root, ["tpu_cc_manager/obs.py",
                                        "tpu_cc_manager/fleetobs.py"]):
        mod = load_module(root, rel)
        if mod is not None:
            declared.update(audit_module(mod).metric_decls)
    assert slo_findings(root, declared) == []
    assert os.path.exists(os.path.join(root, "deployments", "slo.yaml"))
    # the registry subset above genuinely declares the referenced names
    assert "tpu_cc_reconciles_total" in declared
