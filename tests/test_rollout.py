"""Operator-side rolling mode changes (tpu_cc_manager.rollout).

The reference has no pool-level orchestration (admins label nodes by
hand, reference README_PYTHON.md:77-102); these tests cover the rollout
tool built for BASELINE config 3 ("rolling CC enable").
"""

import threading
import time

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.modes import InvalidModeError
from tpu_cc_manager.rollout import GroupResult, Rollout, RolloutError


def _node(name, desired=None, state=None, slice_id=None):
    labels = {L.TPU_ACCELERATOR_LABEL: "tpu-v5e-slice"}
    if desired:
        labels[L.CC_MODE_LABEL] = desired
    if state:
        labels[L.CC_MODE_STATE_LABEL] = state
    if slice_id:
        labels[L.TPU_SLICE_LABEL] = slice_id
    return make_node(name, labels=labels)


def _pool(kube, *nodes):
    for n in nodes:
        kube.add_node(n)


def test_plan_groups_slices_and_singletons():
    groups = Rollout.plan_groups([
        _node("b1", slice_id="s-beta"),
        _node("a2", slice_id="s-alpha"),
        _node("a1", slice_id="s-alpha"),
        _node("z-solo"),
        _node("a-solo"),
    ])
    assert groups == [
        ("slice/s-alpha", ["a1", "a2"]),
        ("slice/s-beta", ["b1"]),
        ("node/a-solo", ["a-solo"]),
        ("node/z-solo", ["z-solo"]),
    ]


def test_invalid_mode_rejected_before_any_patch():
    with pytest.raises(InvalidModeError):
        Rollout(FakeKube(), "bogus")


def test_empty_selector_refused():
    with pytest.raises(RolloutError, match="no nodes"):
        Rollout(FakeKube(), "on").run()


def test_dry_run_plans_without_patching():
    kube = FakeKube()
    _pool(
        kube,
        _node("n1", desired="off", state="off"),
        _node("n2", desired="on", state="on"),
    )
    report = Rollout(kube, "on", dry_run=True).run()
    by_name = {g.name: g for g in report.groups}
    assert by_name["node/n1"].outcome == "planned"
    assert by_name["node/n2"].outcome == "skipped"
    # nothing patched
    assert (
        kube.get_node("n1")["metadata"]["labels"][L.CC_MODE_LABEL] == "off"
    )
    assert report.ok


def test_preflight_refuses_broken_fleet():
    kube = FakeKube()
    _pool(kube, _node("n1", desired="off", state="failed"))
    with pytest.raises(RolloutError, match="failed nodes"):
        Rollout(kube, "on").run()
    # force overrides; group converges once the 'agent' recovers
    done = threading.Event()

    def fake_agent():
        while not done.is_set():
            labels = kube.get_node("n1")["metadata"]["labels"]
            if labels.get(L.CC_MODE_LABEL) == "on":
                kube.set_node_labels("n1", {L.CC_MODE_STATE_LABEL: "on"})
                return
            time.sleep(0.02)

    t = threading.Thread(target=fake_agent, daemon=True)
    t.start()
    try:
        report = Rollout(kube, "on", force=True, poll_s=0.02,
                         group_timeout_s=10).run()
    finally:
        done.set()
        t.join(timeout=2)
    assert report.ok and report.succeeded == ["node/n1"]


class _ReactiveAgents(threading.Thread):
    """Simulated per-node agents: when a node's desired label changes,
    publish the observed state after a small delay (or 'failed' for nodes
    in fail_nodes). Records the order in which groups converged."""

    def __init__(self, kube, node_names, fail_nodes=(), delay_s=0.05):
        super().__init__(daemon=True)
        self.kube = kube
        self.node_names = list(node_names)
        self.fail_nodes = set(fail_nodes)
        self.delay_s = delay_s
        self.stop = threading.Event()
        self.converge_times = {}

    def run(self):
        while not self.stop.is_set():
            for name in self.node_names:
                labels = self.kube.get_node(name)["metadata"]["labels"]
                desired = labels.get(L.CC_MODE_LABEL)
                state = labels.get(L.CC_MODE_STATE_LABEL)
                if desired and state != desired and state != "failed":
                    time.sleep(self.delay_s)
                    value = (
                        "failed" if name in self.fail_nodes else desired
                    )
                    self.kube.set_node_labels(
                        name, {L.CC_MODE_STATE_LABEL: value}
                    )
                    self.converge_times[name] = time.monotonic()
            time.sleep(0.01)


def test_rolling_window_serializes_groups():
    """max_unavailable=1: the second slice's desired label must not be
    patched until the first slice fully converged."""
    kube = FakeKube()
    _pool(
        kube,
        _node("a1", desired="off", state="off", slice_id="s-a"),
        _node("a2", desired="off", state="off", slice_id="s-a"),
        _node("b1", desired="off", state="off", slice_id="s-b"),
        _node("b2", desired="off", state="off", slice_id="s-b"),
    )
    patch_times = {}
    orig = kube.patch_node

    # desired writes are ONE patch_node carrying the label plus the
    # cc.trace annotation (ISSUE 8) — hook the patch verb
    def recording_patch(name, patch):
        if L.CC_MODE_LABEL in (
                (patch.get("metadata") or {}).get("labels") or {}):
            patch_times[name] = time.monotonic()
        return orig(name, patch)

    kube.patch_node = recording_patch
    agents = _ReactiveAgents(kube, ["a1", "a2", "b1", "b2"])
    agents.start()
    try:
        report = Rollout(kube, "on", max_unavailable=1, poll_s=0.02,
                         group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.ok
    assert set(report.succeeded) == {"slice/s-a", "slice/s-b"}
    # both members of s-a converged before either member of s-b was patched
    s_a_done = max(agents.converge_times["a1"], agents.converge_times["a2"])
    s_b_start = min(patch_times["b1"], patch_times["b2"])
    assert s_a_done <= s_b_start


def test_window_2_runs_groups_concurrently():
    kube = FakeKube()
    _pool(
        kube,
        *[_node(f"n{i}", desired="off", state="off") for i in range(4)],
    )
    patch_times = {}
    orig = kube.patch_node

    def recording_patch(name, patch):
        if L.CC_MODE_LABEL in (
                (patch.get("metadata") or {}).get("labels") or {}):
            patch_times[name] = time.monotonic()
        return orig(name, patch)

    kube.patch_node = recording_patch
    agents = _ReactiveAgents(kube, [f"n{i}" for i in range(4)], delay_s=0.2)
    agents.start()
    try:
        report = Rollout(kube, "on", max_unavailable=2, poll_s=0.02,
                         group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.ok
    # first two launches happen together, before any node converged
    t0, t1 = sorted(patch_times.values())[:2]
    first_converge = min(agents.converge_times.values())
    assert t1 <= first_converge


def test_failure_budget_aborts_rollout():
    kube = FakeKube()
    _pool(
        kube,
        _node("f1", desired="off", state="off"),
        _node("g1", desired="off", state="off"),
        _node("h1", desired="off", state="off"),
    )
    agents = _ReactiveAgents(kube, ["f1", "g1", "h1"], fail_nodes={"f1"})
    agents.start()
    try:
        report = Rollout(kube, "on", max_unavailable=1, poll_s=0.02,
                         group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert report.aborted and not report.ok
    by_name = {g.name: g for g in report.groups}
    assert by_name["node/f1"].outcome == "failed"
    # groups after the failure were never attempted
    untouched = [
        g for g in report.groups if g.outcome == "not_attempted"
    ]
    assert len(untouched) == 2
    for g in untouched:
        labels = kube.get_node(g.nodes[0])["metadata"]["labels"]
        assert labels.get(L.CC_MODE_LABEL) == "off"


def test_failure_budget_allows_continuing():
    kube = FakeKube()
    _pool(
        kube,
        _node("f1", desired="off", state="off"),
        _node("g1", desired="off", state="off"),
    )
    agents = _ReactiveAgents(kube, ["f1", "g1"], fail_nodes={"f1"})
    agents.start()
    try:
        report = Rollout(kube, "on", failure_budget=1, poll_s=0.02,
                         group_timeout_s=10).run()
    finally:
        agents.stop.set()
        agents.join(timeout=2)
    assert not report.aborted
    assert report.failed == ["node/f1"]
    assert report.succeeded == ["node/g1"]
    assert not report.ok  # failures still fail the rollout exit code


def test_partial_launch_rolls_back_slice():
    """If patching a slice member fails mid-launch, already-patched
    members are reverted — a slice never gets incoherent desired labels."""
    kube = FakeKube()
    _pool(
        kube,
        _node("s1", desired="off", state="off", slice_id="s-x"),
        _node("s2", desired="off", state="off", slice_id="s-x"),
    )
    from tpu_cc_manager.k8s.client import ApiException

    orig = kube.patch_node

    def failing_patch(name, patch):
        labels = (patch.get("metadata") or {}).get("labels") or {}
        if name == "s2" and labels.get(L.CC_MODE_LABEL) == "on":
            raise ApiException(500, "injected patch failure")
        return orig(name, patch)

    kube.patch_node = failing_patch
    report = Rollout(kube, "on", poll_s=0.02, group_timeout_s=5).run()
    assert report.failed == ["slice/s-x"]
    # s1 was patched first, then rolled back to 'off'
    meta = kube.get_node("s1")["metadata"]
    assert meta["labels"][L.CC_MODE_LABEL] == "off"
    # the aborted launch's trace annotation was cleared by the same
    # rollback write — later reconciles must not stitch under the dead
    # rollout's trace id
    assert L.CC_TRACE_ANNOTATION not in (meta.get("annotations") or {})


def test_dry_run_allowed_on_broken_fleet():
    kube = FakeKube()
    _pool(kube, _node("n1", desired="off", state="failed"))
    report = Rollout(kube, "on", dry_run=True).run()
    assert report.preflight["failed"] == ["n1"]
    assert {g.outcome for g in report.groups} == {"planned"}


def test_group_timeout():
    kube = FakeKube()
    _pool(kube, _node("slow", desired="off", state="off"))
    # no agent running: nobody ever publishes the state label
    report = Rollout(kube, "on", poll_s=0.02, group_timeout_s=0.2).run()
    assert report.failed == ["node/slow"]
    by_name = {g.name: g for g in report.groups}
    assert by_name["node/slow"].outcome == "timeout"


def test_cli_rollout_dry_run(capsys):
    from tpu_cc_manager.k8s.apiserver import FakeApiServer
    import tpu_cc_manager.__main__ as cli

    with FakeApiServer() as srv:
        srv.store.add_node(_node("n1", desired="off", state="off"))
        kubeconfig = None
        # point the CLI at the fake server via a kubeconfig file
        import tempfile, textwrap, os

        with tempfile.NamedTemporaryFile(
            "w", suffix=".yaml", delete=False
        ) as f:
            f.write(textwrap.dedent(f"""\
                apiVersion: v1
                kind: Config
                current-context: t
                contexts: [{{name: t, context: {{cluster: c, user: u}}}}]
                clusters: [{{name: c, cluster: {{server: "{srv.url}"}}}}]
                users: [{{name: u, user: {{}}}}]
            """))
            kubeconfig = f.name
        try:
            rc = cli.main([
                "--kubeconfig", kubeconfig, "rollout", "-m", "on",
                "--dry-run",
            ])
        finally:
            os.unlink(kubeconfig)
    assert rc == 0
    out = capsys.readouterr().out
    assert '"outcome": "planned"' in out
    assert '"mode": "on"' in out


def test_rollout_divergent_slice_policies_full_stack(tmp_path):
    """BASELINE config 5, full stack: two 2-node slices with slice
    coordination enabled, driven to DIVERGENT modes by two rollouts. Each
    slice flips coherently (two-phase protocol) while holding a different
    policy than its neighbor."""
    from tests.test_multinode import SimNode, _wait

    kube = FakeKube()
    sims = [
        SimNode(kube, n, tmp_path, label="off", slice_id=s, coordinate=True)
        for n, s in [
            ("a0", "s-a"), ("a1", "s-a"), ("b0", "s-b"), ("b1", "s-b"),
        ]
    ]
    for s in sims:
        s.start()
    try:
        assert _wait(
            lambda: all(
                kube.get_node(n)["metadata"]["labels"].get(
                    L.CC_MODE_STATE_LABEL
                ) == "off"
                for n in ("a0", "a1", "b0", "b1")
            )
        )
        rep_a = Rollout(
            kube, "on", selector=f"{L.TPU_SLICE_LABEL}=s-a",
            poll_s=0.05, group_timeout_s=30,
        ).run()
        rep_b = Rollout(
            kube, "devtools", selector=f"{L.TPU_SLICE_LABEL}=s-b",
            poll_s=0.05, group_timeout_s=30,
        ).run()
        assert rep_a.ok and rep_a.succeeded == ["slice/s-a"]
        assert rep_b.ok and rep_b.succeeded == ["slice/s-b"]
        by = {s.agent.cfg.node_name: s for s in sims}
        assert all(
            c.query_cc_mode() == "on"
            for n in ("a0", "a1") for c in by[n].backend.chips
        )
        assert all(
            c.query_cc_mode() == "devtools"
            for n in ("b0", "b1") for c in by[n].backend.chips
        )
    finally:
        for s in sims:
            s.stop()


def test_real_agents_rolling_enable(tmp_path):
    """End-to-end BASELINE config 3 shape: real agents on 4 nodes, rolling
    CC enable with window 1 — uses the same agent harness as the
    multi-node simulation."""
    from tests.test_multinode import SimNode, _wait

    kube = FakeKube()
    sims = [SimNode(kube, f"r-{i}", tmp_path, label="off") for i in range(4)]
    for s in sims:
        s.start()
    try:
        assert _wait(
            lambda: all(
                kube.get_node(f"r-{i}")["metadata"]["labels"].get(
                    L.CC_MODE_STATE_LABEL
                ) == "off"
                for i in range(4)
            )
        )
        report = Rollout(
            kube, "on",
            selector=L.TPU_ACCELERATOR_LABEL,
            max_unavailable=1, poll_s=0.05, group_timeout_s=30,
        ).run()
        assert report.ok
        assert len(report.succeeded) == 4
        assert all(
            c.query_cc_mode() == "on" for s in sims for c in s.backend.chips
        )
    finally:
        for s in sims:
            s.stop()


def test_vanished_node_fails_group_fast():
    # GKE node repair deletes a node mid-rollout: the group must fail
    # immediately with a distinct detail, not burn the group timeout.
    kube = FakeKube()
    _pool(kube, _node("doomed", desired="off", state="off"))

    class VanishingKube:
        """Delegates to FakeKube but drops 'doomed' from polls after the
        desired label lands (simulating node deletion)."""

        def __init__(self, inner):
            self._inner = inner
            self.patched = False

        def list_nodes(self, selector=None):
            nodes = self._inner.list_nodes(selector)
            if self.patched:
                nodes = [
                    n for n in nodes if n["metadata"]["name"] != "doomed"
                ]
            return nodes

        def set_node_labels(self, name, labels):
            self._inner.set_node_labels(name, labels)
            self.patched = True

        def patch_node(self, name, patch):
            # the desired-write verb since ISSUE 8 (label + cc.trace
            # annotation in one write)
            result = self._inner.patch_node(name, patch)
            self.patched = True
            return result

        def __getattr__(self, item):
            return getattr(self._inner, item)

    t0 = time.monotonic()
    report = Rollout(
        VanishingKube(kube), "on", poll_s=0.02, group_timeout_s=30.0
    ).run()
    assert time.monotonic() - t0 < 5.0  # far under the group timeout
    by_name = {g.name: g for g in report.groups}
    assert by_name["node/doomed"].outcome == "failed"
    assert "disappeared" in by_name["node/doomed"].detail


def test_vanished_node_in_pending_group_fails_at_launch():
    # A member of a not-yet-launched group deleted mid-rollout must fail
    # that group at launch time (from the refreshed snapshot), not crash
    # the rollout with a KeyError.
    kube = FakeKube()
    _pool(
        kube,
        _node("a", desired="off", state="off"),
        _node("b", desired="off", state="off"),
    )

    class VanishingKube:
        """Drops node 'b' from every list after the first patch lands
        (while group node/a is still in flight)."""

        def __init__(self, inner):
            self._inner = inner
            self.patched = False

        def list_nodes(self, selector=None):
            nodes = self._inner.list_nodes(selector)
            if self.patched:
                nodes = [n for n in nodes if n["metadata"]["name"] != "b"]
            return nodes

        def set_node_labels(self, name, labels):
            self._inner.set_node_labels(name, labels)
            self.patched = True

        def patch_node(self, name, patch):
            # the desired-write verb since ISSUE 8 (label + cc.trace
            # annotation in one write)
            result = self._inner.patch_node(name, patch)
            self.patched = True
            return result

        def __getattr__(self, item):
            return getattr(self._inner, item)

    report = Rollout(
        VanishingKube(kube), "on", max_unavailable=1, failure_budget=3,
        poll_s=0.02, group_timeout_s=0.2,
    ).run()
    by_name = {g.name: g for g in report.groups}
    assert by_name["node/a"].outcome == "timeout"  # nobody converges it
    assert by_name["node/b"].outcome == "failed"
    assert "before launch" in by_name["node/b"].detail


# ----------------------------------------------------- durable record/resume
class SimulatedCrash(Exception):
    pass


class _CrashableWake:
    """Wraps a Rollout's ``_wake`` event so the driving loop's wait —
    the successor of its old poll-sleep — raises SimulatedCrash once
    armed. Only the rollout's own driver thread crashes; judge threads
    delegating set()/clear() are untouched."""

    def __init__(self, inner, crash, thread_box):
        self._inner = inner
        self._crash = crash
        self._thread_box = thread_box

    def wait(self, timeout=None):
        if (self._crash.is_set()
                and threading.current_thread() is self._thread_box.get("t")):
            raise SimulatedCrash()
        return self._inner.wait(timeout)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _crash_rollout_at(kube, monkeypatch, rollout, record_ready):
    """Run `rollout` in a thread and kill it (SimulatedCrash raised from
    its own wake wait — the poll-sleep's successor) once
    `record_ready(record)` is true. Returns the record at crash time."""
    from tpu_cc_manager.rollout import load_rollout_record

    crash = threading.Event()
    died = threading.Event()
    thread_box = {}

    def target():
        try:
            rollout.run()
        except SimulatedCrash:
            died.set()

    t = threading.Thread(target=target, daemon=True)
    thread_box["t"] = t
    rollout._wake = _CrashableWake(rollout._wake, crash, thread_box)
    t.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        rec, _ = load_rollout_record(kube, kube.list_nodes(None))
        if rec is not None and record_ready(rec):
            break
        time.sleep(0.02)
    else:
        raise AssertionError("crash precondition never reached")
    crash.set()
    assert died.wait(10), "rollout thread did not crash"
    rec, _ = load_rollout_record(kube, kube.list_nodes(None))
    return rec


def test_resume_after_crash_one_coherent_report(monkeypatch):
    """VERDICT r2 item 6: kill the rollout mid-window, resume, and get
    one coherent final report with no group double-counted."""
    kube = FakeKube()
    names = [f"n{i}" for i in range(4)]
    _pool(kube, *[_node(n, desired="off", state="off") for n in names])
    # n0 converges; n1 stalls (agent not simulated yet) -> stays in_flight
    agents = _ReactiveAgents(kube, ["n0"])
    agents.start()
    roll = Rollout(kube, "on", max_unavailable=1, group_timeout_s=60,
                   poll_s=0.05)

    def ready(rec):
        g = rec.get("groups", {})
        return (g.get("node/n0", {}).get("outcome") == "succeeded"
                and g.get("node/n1", {}).get("outcome") == "in_flight")

    rec = _crash_rollout_at(kube, monkeypatch, roll, ready)
    agents.stop.set()
    assert rec["complete"] is False
    assert rec["groups"]["node/n2"]["outcome"] == "pending"

    # a fresh rollout is refused while the record is unfinished
    with pytest.raises(RolloutError, match="--resume"):
        Rollout(kube, "on").run()

    # resume: all agents now converge
    agents2 = _ReactiveAgents(kube, names)
    agents2.start()
    try:
        report = Rollout.resume(kube, poll_s=0.05, group_timeout_s=60).run()
    finally:
        agents2.stop.set()
    assert report.ok
    assert [g.name for g in report.groups] == sorted(
        f"node/{n}" for n in names)          # every group exactly once
    outcomes = {g.name: g.outcome for g in report.groups}
    assert outcomes == {f"node/{n}": "succeeded" for n in names}
    # the durable record is now complete; a fresh rollout is allowed again
    from tpu_cc_manager.rollout import load_rollout_record
    rec, _ = load_rollout_record(kube, kube.list_nodes(None))
    assert rec["complete"] is True


def test_resume_preserves_spent_failure_budget(monkeypatch):
    """Budget spent before the crash carries over: one more failure
    after resume exhausts it and aborts, with the remainder
    not_attempted."""
    kube = FakeKube()
    names = [f"m{i}" for i in range(4)]
    _pool(kube, *[_node(n, desired="off", state="off") for n in names])
    agents = _ReactiveAgents(kube, ["m0", "m1"], fail_nodes={"m1"})
    agents.start()
    roll = Rollout(kube, "on", max_unavailable=1, failure_budget=1,
                   group_timeout_s=60, poll_s=0.05)

    def ready(rec):
        g = rec.get("groups", {})
        return (g.get("node/m1", {}).get("outcome") == "failed"
                and g.get("node/m2", {}).get("outcome") == "in_flight")

    _crash_rollout_at(kube, monkeypatch, roll, ready)
    agents.stop.set()

    agents2 = _ReactiveAgents(kube, names, fail_nodes={"m1", "m2"})
    agents2.start()
    try:
        report = Rollout.resume(kube, poll_s=0.05, group_timeout_s=60).run()
    finally:
        agents2.stop.set()
    outcomes = {g.name: g.outcome for g in report.groups}
    assert outcomes["node/m0"] == "succeeded"
    assert outcomes["node/m1"] == "failed"        # judged pre-crash
    assert outcomes["node/m2"] == "failed"        # budget now exhausted
    assert outcomes["node/m3"] == "not_attempted"
    assert report.aborted
    assert len(report.groups) == 4


def test_resume_with_nothing_to_resume():
    kube = FakeKube()
    _pool(kube, _node("x1", desired="on", state="on"))
    with pytest.raises(RolloutError, match="no unfinished rollout"):
        Rollout.resume(kube)
    # a COMPLETED record is also not resumable
    report = Rollout(kube, "on", poll_s=0.05).run()
    assert report.ok
    with pytest.raises(RolloutError, match="no unfinished rollout"):
        Rollout.resume(kube)


def _write_record(kube, node, record):
    import json as _json
    kube.set_node_annotations(node, {
        L.ROLLOUT_ANNOTATION: _json.dumps(record)})


def test_resume_of_aborted_rollout_drains_in_flight():
    """Groups in flight when an already-aborted rollout crashed have
    patched labels and flipping nodes: resume must JUDGE them, not
    report them not_attempted."""
    kube = FakeKube()
    _pool(kube,
          _node("d0", desired="on", state="on"),      # succeeded pre-crash
          _node("d1", desired="on", state="off"),     # in flight at crash
          _node("d2", desired="off", state="off"))    # pending at crash
    _write_record(kube, "d0", {
        "id": "abc", "started": 1.0, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL,
        "max_unavailable": 1, "failure_budget": 0,
        "complete": False, "aborted": True,
        "groups": {
            "node/d0": {"nodes": ["d0"], "outcome": "succeeded"},
            "node/dX": {"nodes": ["dX"], "outcome": "failed",
                        "detail": "budget burner"},
            "node/d1": {"nodes": ["d1"], "outcome": "in_flight"},
            "node/d2": {"nodes": ["d2"], "outcome": "pending"},
        },
    })
    agents = _ReactiveAgents(kube, ["d1"])
    agents.start()
    try:
        report = Rollout.resume(kube, poll_s=0.05, group_timeout_s=30).run()
    finally:
        agents.stop.set()
    outcomes = {g.name: g.outcome for g in report.groups}
    assert outcomes["node/d1"] == "succeeded"       # drained, not dropped
    assert outcomes["node/d2"] == "not_attempted"   # launches stay blocked
    assert report.aborted
    # d2's desired label was never patched
    assert kube.get_node("d2")["metadata"]["labels"][L.CC_MODE_LABEL] == "off"


def test_resume_uses_recorded_selector_and_guard_sees_foreign_records():
    """The record persists its selector: resume scopes the SAME node
    set even when invoked with the default selector, and a new rollout
    with a different selector is refused while any unfinished record
    exists anywhere in the cluster."""
    kube = FakeKube()
    # pool under a custom selector; nodes lack the default accel label
    kube.add_node(make_node("c0", labels={
        "pool": "custom", L.CC_MODE_LABEL: "on",
        L.CC_MODE_STATE_LABEL: "off"}))
    kube.add_node(make_node("c1", labels={
        "pool": "custom", L.CC_MODE_LABEL: "off",
        L.CC_MODE_STATE_LABEL: "off"}))
    _write_record(kube, "c0", {
        "id": "sel1", "started": 2.0, "mode": "on",
        "selector": "pool=custom",
        "max_unavailable": 1, "failure_budget": 0,
        "complete": False, "aborted": False,
        "groups": {
            "node/c0": {"nodes": ["c0"], "outcome": "in_flight"},
            "node/c1": {"nodes": ["c1"], "outcome": "pending"},
        },
    })
    # a new rollout whose pool OVERLAPS the record's nodes is refused
    # (here: the same custom selector) — selector strings differing is
    # irrelevant, node overlap is what the guard scopes on
    with pytest.raises(RolloutError, match="--resume"):
        Rollout(kube, "off", selector="pool=custom").run()
    # a DISJOINT pool may roll concurrently (per-pool records): the
    # default-selector node is untouched by the custom-pool record
    kube.add_node(_node("other1", desired="off", state="off"))
    agents_d = _ReactiveAgents(kube, ["other1"])
    agents_d.start()
    try:
        rep_d = Rollout(kube, "on", poll_s=0.05,
                        group_timeout_s=30).run()
    finally:
        agents_d.stop.set()
    assert rep_d.ok
    # resume with the DEFAULT selector still finds + scopes the record
    agents = _ReactiveAgents(kube, ["c0", "c1"])
    agents.start()
    try:
        report = Rollout.resume(kube, poll_s=0.05, group_timeout_s=30).run()
    finally:
        agents.stop.set()
    assert report.ok
    assert {g.name for g in report.groups} == {"node/c0", "node/c1"}


def test_resume_dry_run_previews_without_patching():
    kube = FakeKube()
    _pool(kube, _node("p0", desired="off", state="off"),
          _node("p1", desired="off", state="off"))
    _write_record(kube, "p0", {
        "id": "dr1", "started": 3.0, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL,
        "max_unavailable": 1, "failure_budget": 0,
        "complete": False, "aborted": False,
        "groups": {
            "node/p0": {"nodes": ["p0"], "outcome": "in_flight"},
            "node/p1": {"nodes": ["p1"], "outcome": "pending"},
        },
    })
    report = Rollout.resume(kube, dry_run=True).run()
    outcomes = {g.name: g.outcome for g in report.groups}
    assert outcomes == {"node/p0": "planned", "node/p1": "planned"}
    # nothing patched, record still unfinished (resumable for real)
    assert kube.get_node("p0")["metadata"]["labels"][L.CC_MODE_LABEL] == "off"
    from tpu_cc_manager.rollout import load_rollout_record
    rec, _ = load_rollout_record(kube, kube.list_nodes(None))
    assert rec["complete"] is False


def test_rollout_distrusts_lying_convergence_labels(tmp_path, monkeypatch):
    """A member whose state label claims the target while its evidence
    attests another mode must NOT count as converged: the group resolves
    as timeout with the evidence contradiction in the detail. Members
    with no evidence at all (pre-evidence agents) still pass."""
    import json as _json

    from tpu_cc_manager.evidence import build_evidence

    # real statefile-backed evidence attesting cc=off
    be = _statefile_backend(tmp_path)
    off_evidence = _json.dumps(build_evidence("liar", be, key=None))

    kube = FakeKube()
    kube.add_node(make_node("liar", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: off_evidence}))
    kube.add_node(_node("honest", desired="off", state="off"))

    # agents set only the label — the liar's evidence stays at "off"
    agents = _ReactiveAgents(kube, ["liar", "honest"])
    agents.start()
    try:
        report = Rollout(kube, "on", max_unavailable=2, failure_budget=2,
                         group_timeout_s=2, poll_s=0.05).run()
    finally:
        agents.stop.set()
    outcomes = {g.name: g for g in report.groups}
    assert outcomes["node/honest"].outcome == "succeeded"  # no evidence: ok
    liar = outcomes["node/liar"]
    assert liar.outcome == "timeout"
    assert "evidence" in liar.detail


def test_preconverged_liar_and_replayed_evidence_not_skipped(tmp_path):
    """Two label-forgery variants the evidence cross-check must catch:
    a node already AT the target labels before the rollout starts (would
    previously be 'skipped' unchecked), and a node carrying another
    node's valid evidence (replay — the node binding is part of the
    claim)."""
    import json as _json

    from tpu_cc_manager.evidence import build_evidence

    be = _statefile_backend(tmp_path)
    chips, _ = be.find_tpus()
    be.store.stage(chips[0].path, "cc", "on")
    be.store.commit(chips[0].path)
    on_evidence_for_other = _json.dumps(build_evidence("real-node", be))
    be.store.stage(chips[0].path, "cc", "off")
    be.store.commit(chips[0].path)
    off_evidence_forged = _json.dumps(build_evidence("forged", be))

    kube = FakeKube()
    # labels forged to on/on BEFORE the rollout; evidence attests off
    kube.add_node(make_node("forged", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on"},
        annotations={L.EVIDENCE_ANNOTATION: off_evidence_forged}))
    # labels forged on/on with VALID evidence replayed from real-node
    kube.add_node(make_node("copycat", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "on"},
        annotations={L.EVIDENCE_ANNOTATION: on_evidence_for_other}))

    report = Rollout(kube, "on", max_unavailable=2, failure_budget=2,
                     group_timeout_s=1.5, poll_s=0.05).run()
    outcomes = {g.name: g for g in report.groups}
    assert outcomes["node/forged"].outcome == "timeout"
    assert "evidence" in outcomes["node/forged"].detail
    assert outcomes["node/copycat"].outcome == "timeout"
    assert "evidence" in outcomes["node/copycat"].detail


def _statefile_backend(tmp_path):
    from tpu_cc_manager.device.tpu import SysfsTpuBackend

    sysfs = tmp_path / "sysfs"
    devd = sysfs / "accel0" / "device"
    devd.mkdir(parents=True)
    (devd / "vendor").write_text("0x1ae0\n")
    (devd / "device").write_text("0x0063\n")
    (tmp_path / "dev").mkdir()
    (tmp_path / "dev" / "accel0").write_text("")
    return SysfsTpuBackend(sysfs_root=str(sysfs),
                           dev_root=str(tmp_path / "dev"),
                           state_dir=str(tmp_path / "state"))


def test_keyed_agents_keyed_verifier_converge(tmp_path, monkeypatch):
    """The intended production posture after the evidence-key Secret is
    deployed everywhere (daemonset*.yaml + controllers all mount it):
    agents sign with the pool key, the rollout verifier holds the same
    key, and convergence counts. Guards the end-to-end keyed path the
    round-3 manifests never exercised."""
    import json as _json

    from tpu_cc_manager.evidence import build_evidence

    be = _statefile_backend(tmp_path)
    chips, _ = be.find_tpus()
    be.store.stage(chips[0].path, "cc", "on")
    be.store.commit(chips[0].path)
    signed_on = _json.dumps(build_evidence("k1", be, key=b"pool-secret"))

    kube = FakeKube()
    kube.add_node(make_node("k1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: signed_on}))
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-secret")
    agents = _ReactiveAgents(kube, ["k1"])
    agents.start()
    try:
        report = Rollout(kube, "on", group_timeout_s=10, poll_s=0.05).run()
    finally:
        agents.stop.set()
    assert report.ok
    assert [g.outcome for g in report.groups] == ["succeeded"]


def test_unkeyed_agents_keyed_verifier_fail_actionably(tmp_path,
                                                       monkeypatch):
    """The round-3 shipped-manifest bug, now made LOUD: agents publish
    unsigned (plain-sha256) evidence while the rollout verifier holds
    the pool key. The no-downgrade rule still refuses convergence — but
    the verdict must name the fix (mount the key Secret into the agent
    DaemonSets), not read as a mystery timeout."""
    import json as _json

    from tpu_cc_manager.evidence import build_evidence

    be = _statefile_backend(tmp_path)
    chips, _ = be.find_tpus()
    be.store.stage(chips[0].path, "cc", "on")
    be.store.commit(chips[0].path)
    # built BEFORE the key lands in the env: genuinely unsigned
    unsigned_on = _json.dumps(build_evidence("u1", be, key=None))
    assert "hmac" not in unsigned_on

    kube = FakeKube()
    kube.add_node(make_node("u1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: unsigned_on}))
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-secret")
    agents = _ReactiveAgents(kube, ["u1"])
    agents.start()
    try:
        report = Rollout(kube, "on", group_timeout_s=1.5,
                         poll_s=0.05).run()
    finally:
        agents.stop.set()
    assert not report.ok
    (group,) = report.groups
    assert group.outcome == "timeout"
    assert "unsigned" in group.detail
    # the detail is the operator's runbook: it names the Secret, the
    # env knob, and the enablement order
    assert "tpu-cc-evidence-key" in group.detail
    assert "TPU_CC_EVIDENCE_KEY_FILE" in group.detail


def test_tampered_plain_doc_not_blamed_on_manifests(tmp_path, monkeypatch):
    """An attack dressed as 'unsigned' — a plain-sha256 doc with a
    broken digest under a keyed verifier — must keep its forensic
    classification: the timeout verdict says digest_mismatch and does
    NOT append the mount-the-Secret runbook, so a forgery is never
    triaged as a deployment gap."""
    import json as _json

    from tpu_cc_manager.evidence import build_evidence

    be = _statefile_backend(tmp_path)
    chips, _ = be.find_tpus()
    be.store.stage(chips[0].path, "cc", "on")
    be.store.commit(chips[0].path)
    doc = build_evidence("t1", be, key=None)
    doc["statefile_digest"] = "sha256:beef"  # tamper AFTER digesting

    kube = FakeKube()
    kube.add_node(make_node("t1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: _json.dumps(doc)}))
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-secret")
    agents = _ReactiveAgents(kube, ["t1"])
    agents.start()
    try:
        report = Rollout(kube, "on", group_timeout_s=1.5,
                         poll_s=0.05).run()
    finally:
        agents.stop.set()
    (group,) = report.groups
    assert group.outcome == "timeout"
    assert "digest_mismatch" in group.detail
    assert "tpu-cc-evidence-key" not in group.detail


def test_unkeyed_verifier_still_catches_keyless_contradictions(
        tmp_path, monkeypatch):
    """Mid-enablement the OTHER way: agents sign, the rollout operator
    has no key. The digest is a tolerated blind spot (warned once) —
    but a signed doc whose unauthenticated mode claim contradicts the
    rollout target, and a signed doc replayed from another node, need
    no key to read and must stay suspects (same triage as the fleet
    audit's judge_evidence)."""
    import json as _json

    from tpu_cc_manager.evidence import build_evidence

    be = _statefile_backend(tmp_path)
    # evidence attests cc=off, signed with a key this verifier lacks
    signed_off = _json.dumps(
        build_evidence("contra", be, key=b"agents-only-key")
    )

    kube = FakeKube()
    kube.add_node(make_node("contra", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: signed_off}))
    monkeypatch.delenv("TPU_CC_EVIDENCE_KEY", raising=False)
    monkeypatch.delenv("TPU_CC_EVIDENCE_KEY_FILE", raising=False)
    agents = _ReactiveAgents(kube, ["contra"])
    agents.start()
    try:
        report = Rollout(kube, "on", group_timeout_s=1.5,
                         poll_s=0.05).run()
    finally:
        agents.stop.set()
    (group,) = report.groups
    assert group.outcome == "timeout"
    assert "attests 'off'" in group.detail
    assert "no key here" in group.detail


def test_unsigned_doc_attesting_wrong_mode_is_forensic(tmp_path,
                                                       monkeypatch):
    """Forensic outranks the runbook in the rollout judge too (audit
    lockstep): an unsigned doc whose mode claim contradicts the target
    reports 'attests', not the mount-the-Secret runbook — re-keying
    agents would not make this node honest."""
    import json as _json

    from tpu_cc_manager.evidence import build_evidence

    be = _statefile_backend(tmp_path)
    # device truth stays 'off'; the doc attests it honestly, unsigned
    unsigned_off = _json.dumps(build_evidence("w1", be, key=None))

    kube = FakeKube()
    kube.add_node(make_node("w1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: unsigned_off}))
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-secret")
    agents = _ReactiveAgents(kube, ["w1"])
    agents.start()
    try:
        report = Rollout(kube, "on", group_timeout_s=1.5,
                         poll_s=0.05).run()
    finally:
        agents.stop.set()
    (group,) = report.groups
    assert group.outcome == "timeout"
    assert "attests 'off'" in group.detail
    assert "tpu-cc-evidence-key" not in group.detail


def test_report_surfaces_stopped_groups_as_handoff():
    """A cooperative stop's groups are first-class: named by
    ``report.stopped``, excluded from ``failed``, and flagged
    ``stopped_early`` in the serialized report — downstream consumers
    (policy lastRollout, operators reading --json) must be able to
    tell a handoff from a failure."""
    from tpu_cc_manager.rollout import GroupResult, RolloutReport

    report = RolloutReport(
        "on",
        [
            GroupResult("g0", ["n1"], "succeeded"),
            GroupResult("g1", ["n2"], "stopped", "leadership lost"),
            GroupResult("g2", ["n3"], "stopped", "leadership lost"),
        ],
        aborted=True,
        preflight={},
        stopped_early=True,
        stop_reason="leadership lost",
    )
    assert report.stopped == ["g1", "g2"]
    assert report.failed == []  # a handoff is not a failure
    assert not report.ok  # but work remains
    d = report.to_dict()
    assert d["stopped_early"] is True
    assert d["stop_reason"] == "leadership lost"
    # a finished report carries no stop keys at all
    done = RolloutReport(
        "on", [GroupResult("g0", ["n1"], "succeeded")],
        aborted=False, preflight={},
    )
    assert "stopped_early" not in done.to_dict()
    assert done.stopped == []


def test_stop_of_already_aborted_rollout_stays_a_failure():
    """A demotion stop arriving while an ALREADY-aborted rollout
    (canary/budget failure, record persisted aborted=True) drains its
    in-flight groups must not relabel the failure as a clean handoff:
    ``stopped_early`` stays False so the policy still goes Degraded,
    emits the Warning event, and applies backoff."""
    kube = FakeKube()
    _pool(kube,
          _node("e0", desired="on", state="on"),     # succeeded pre-crash
          _node("e1", desired="on", state="off"))    # in flight, no agent
    _write_record(kube, "e0", {
        "id": "stopabort", "started": 1.0, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL,
        "max_unavailable": 1, "failure_budget": 0,
        "complete": False, "aborted": True,
        "groups": {
            "node/e0": {"nodes": ["e0"], "outcome": "succeeded"},
            "node/eX": {"nodes": ["eX"], "outcome": "failed",
                        "detail": "budget burner"},
            "node/e1": {"nodes": ["e1"], "outcome": "in_flight"},
        },
    })
    roll = Rollout.resume(kube, poll_s=0.05, group_timeout_s=30)
    box = {}

    def run():
        box["report"] = roll.run()

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)  # let the drain loop spin on the dead in-flight group
    roll.request_stop("leadership lost")
    t.join(timeout=10)
    assert not t.is_alive()
    report = box["report"]
    assert report.aborted
    assert report.stopped_early is False, \
        "a pre-existing abort must not be masked as a handoff"
    assert "node/eX" in report.failed


def test_record_schema_version_round_trip_and_skew():
    """The durable record carries a schema version (the rollout sibling
    of EVIDENCE_VERSION): new records are stamped v1; versionless
    records (pre-versioning controllers) resume as v1 and get the
    stamp on their next persist; records from the FUTURE — including
    unparseable versions — are refused with a message naming both
    versions, never misparsed."""
    import json as _json

    from tpu_cc_manager.rollout import (
        ROLLOUT_RECORD_VERSION, rollout_record_version,
    )

    # fresh rollouts stamp the current version into the record
    kube = FakeKube()
    _pool(kube, _node("v0", desired="off", state="off"))
    agents = _ReactiveAgents(kube, ["v0"])
    agents.start()
    try:
        Rollout(kube, "on", poll_s=0.05, group_timeout_s=10).run()
    finally:
        agents.stop.set()
    rec = _json.loads(
        kube.get_node("v0")["metadata"]["annotations"][
            L.ROLLOUT_ANNOTATION]
    )
    assert rec["version"] == ROLLOUT_RECORD_VERSION == 1

    # versionless = v1 (claim helper), unparseable = future
    assert rollout_record_version({}) == 1
    assert rollout_record_version({"version": 1}) == 1
    assert rollout_record_version({"version": "2"}) == 2
    assert rollout_record_version({"version": "two"}) > 1


def test_resume_accepts_versionless_record_and_stamps_v1():
    """A record written by a pre-versioning controller (no "version"
    key) resumes cleanly — the old-record/new-controller skew
    direction — and the resumed run's persists stamp it v1."""
    kube = FakeKube()
    _pool(kube, _node("w0", desired="on", state="off"))
    _write_record(kube, "w0", {
        "id": "oldrec", "started": 1.0, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL,
        "max_unavailable": 1, "failure_budget": 0,
        "complete": False, "aborted": False,
        "groups": {"node/w0": {"nodes": ["w0"], "outcome": "in_flight"}},
    })
    agents = _ReactiveAgents(kube, ["w0"])
    agents.start()
    try:
        report = Rollout.resume(kube, poll_s=0.05,
                                group_timeout_s=10).run()
    finally:
        agents.stop.set()
    import json as _json

    assert report.ok
    rec = _json.loads(
        kube.get_node("w0")["metadata"]["annotations"][
            L.ROLLOUT_ANNOTATION]
    )
    assert rec["complete"] is True
    assert rec["version"] == 1


def test_resume_refuses_future_record_version():
    """The new-record/old-controller skew direction: a record whose
    shape evolved under a newer schema version (here: group state moved
    to an unknown key) must be refused with both versions named — a
    silent misparse would resume the rollout with every group
    invisible."""
    kube = FakeKube()
    _pool(kube, _node("f0", desired="on", state="off"))
    _write_record(kube, "f0", {
        "version": 99, "id": "futurerec", "started": 1.0, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL,
        "complete": False,
        # the migrated shape this controller cannot understand:
        "phases": [{"wave": 1, "members": ["f0"], "state": "rolling"}],
    })
    with pytest.raises(RolloutError) as ei:
        Rollout.resume(kube, poll_s=0.05)
    assert "version 99" in str(ei.value)
    assert "v1" in str(ei.value)
    # unparseable version strings are refused the same way
    _write_record(kube, "f0", {
        "version": "two", "id": "junkver", "started": 1.0, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL, "complete": False,
        "groups": {},
    })
    with pytest.raises(RolloutError):
        Rollout.resume(kube, poll_s=0.05)


def test_explicit_selector_resume_never_wanders_to_another_pool():
    """`rollout --resume --selector pool=a` with pool a's record
    COMPLETE must refuse — not fall back to a cluster-wide search and
    force-claim pool b's (possibly live) rollout out from under its
    driver. The unscoped default still finds pool b's record."""
    kube = FakeKube()
    kube.add_node(make_node("pa0", labels={
        "pool": "a", L.CC_MODE_LABEL: "on",
        L.CC_MODE_STATE_LABEL: "on"}))
    _write_record(kube, "pa0", {
        "version": 1, "id": "adone", "started": 5.0, "mode": "on",
        "selector": "pool=a", "complete": True, "aborted": False,
        "groups": {"node/pa0": {"nodes": ["pa0"],
                                "outcome": "succeeded"}},
    })
    _pool(kube, _node("pb0", desired="on", state="off"))
    _write_record(kube, "pb0", {
        "version": 1, "id": "blive", "started": 6.0, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL,
        "max_unavailable": 1, "failure_budget": 0,
        "complete": False, "aborted": False,
        "groups": {"node/pb0": {"nodes": ["pb0"],
                                "outcome": "in_flight"}},
    })
    with pytest.raises(RolloutError, match="no unfinished rollout"):
        Rollout.resume(kube, selector="pool=a", poll_s=0.05)
    # unscoped: pool b's unfinished record is fair game
    r = Rollout.resume(kube, poll_s=0.05, dry_run=True)
    assert r._resume_from[0]["id"] == "blive"


def test_legacy_record_without_selector_scopes_default_pool():
    """A pre-selector-persisting record (no 'selector' key) must
    resume scoped to the default TPU pool, never to the whole cluster
    — a None selector would drain and flip non-TPU nodes."""
    kube = FakeKube()
    _pool(kube, _node("lg0", desired="on", state="off"))
    # a non-TPU node the resume must never touch
    kube.add_node(make_node("web-1", labels={"role": "web"}))
    _write_record(kube, "lg0", {
        "id": "legacy", "started": 1.0, "mode": "on",
        "max_unavailable": 1, "failure_budget": 0,
        "complete": False, "aborted": False,
        "groups": {"node/lg0": {"nodes": ["lg0"],
                                "outcome": "in_flight"}},
    })
    r = Rollout.resume(kube, poll_s=0.05, dry_run=True)
    assert r.selector == L.TPU_ACCELERATOR_LABEL
    report = r.run()
    assert all("web-1" not in g.nodes for g in report.groups)


def test_explicit_selector_with_no_record_refuses():
    """A typo'd (or churned-away) --selector that matches no record
    must refuse, not widen to the cluster and force-claim another
    pool's live rollout."""
    kube = FakeKube()
    _pool(kube, _node("lv0", desired="on", state="off"))
    _write_record(kube, "lv0", {
        "version": 1, "id": "live0", "started": 1.0, "mode": "on",
        "selector": L.TPU_ACCELERATOR_LABEL,
        "max_unavailable": 1, "failure_budget": 0,
        "complete": False, "aborted": False,
        "groups": {"node/lv0": {"nodes": ["lv0"],
                                "outcome": "in_flight"}},
    })
    with pytest.raises(RolloutError, match="no unfinished rollout"):
        Rollout.resume(kube, selector="pool=typo", poll_s=0.05)


def test_launch_stamps_trace_context_in_the_same_write():
    """ISSUE 8 propagation contract at the controller: the desired-mode
    label and the cc.trace annotation land in ONE patch_node write
    (zero extra round trips), every member of a group shares one
    desired_write span's context, and the annotation parses back to
    that span's ids."""
    from tpu_cc_manager.trace import parse_traceparent

    kube = FakeKube()
    _pool(
        kube,
        _node("s1", desired="off", state="on", slice_id="s-x"),
        _node("s2", desired="off", state="on", slice_id="s-x"),
    )
    writes = []
    orig = kube.patch_node

    def recording_patch(name, patch):
        writes.append((name, patch))
        return orig(name, patch)

    kube.patch_node = recording_patch
    # state starts converged to "on" so the group completes instantly
    report = Rollout(kube, "on", poll_s=0.02, group_timeout_s=5).run()
    assert report.ok
    desired_writes = [
        (n, p) for n, p in writes
        if L.CC_MODE_LABEL in ((p.get("metadata") or {}).get("labels")
                               or {})
    ]
    assert {n for n, _ in desired_writes} == {"s1", "s2"}
    contexts = set()
    for name, patch in desired_writes:
        meta = patch["metadata"]
        assert meta["labels"][L.CC_MODE_LABEL] == "on"
        ctx = meta["annotations"][L.CC_TRACE_ANNOTATION]
        assert parse_traceparent(ctx) is not None
        contexts.add(ctx)
    # one desired_write span per group: both members share its context
    assert len(contexts) == 1
    # the annotation landed on the node object itself
    ann = kube.get_node("s1")["metadata"]["annotations"]
    assert ann[L.CC_TRACE_ANNOTATION] in contexts


# ------------------------------------------------- event-driven judge (r14)


class _InformerAgents:
    """Watch-fed fake agents: converge state labels off the SAME
    informer delta stream the judge rides, paying ZERO node read round
    trips — so a test's read-count pin isolates the judge's reads."""

    def __init__(self, kube, informer, delay_s=0.03, fail_nodes=()):
        self.kube = kube
        self.delay_s = delay_s
        self.fail_nodes = set(fail_nodes)
        self._timers = []
        self.token = informer.subscribe(on_event=self._on_event)
        self.informer = informer

    def _on_event(self, etype, node):
        if etype == "DELETED":
            return
        meta = node.get("metadata") or {}
        name = meta.get("name")
        labels = meta.get("labels") or {}
        desired = labels.get(L.CC_MODE_LABEL)
        state = labels.get(L.CC_MODE_STATE_LABEL)
        if not desired or state == desired or state == "failed":
            return
        value = "failed" if name in self.fail_nodes else desired

        t = threading.Timer(
            self.delay_s,
            lambda: self.kube.set_node_labels(
                name, {L.CC_MODE_STATE_LABEL: value}
            ),
        )
        t.daemon = True
        t.start()
        self._timers.append(t)

    def close(self):
        self.informer.unsubscribe(self.token)
        for t in self._timers:
            t.cancel()


def _informer_for(kube):
    from tpu_cc_manager.watch import NodeInformer

    inf = NodeInformer(kube, name="test-rollout")
    inf.prime()
    inf.start()
    return inf


def test_event_driven_judge_zero_steady_state_node_reads():
    """ISSUE 14 acceptance: with a healthy informer feed, steady-state
    group judging performs ZERO node read round trips — pinned against
    FakeKube's node_read_requests over a judging window where nothing
    terminal happens (the test_shard.py pattern)."""
    kube = FakeKube()
    names = [f"n{i}" for i in range(3)]
    _pool(kube, *[_node(n, desired="off", state="off") for n in names])
    informer = _informer_for(kube)
    # slow agents: the first group stays in flight long enough to
    # observe a pure judging window with several fallback ticks
    agents = _InformerAgents(kube, informer, delay_s=0.9)
    roll = Rollout(kube, "on", max_unavailable=1, poll_s=0.02,
                   group_timeout_s=30, informer=informer)
    box = {}

    def target():
        box["report"] = roll.run()

    t = threading.Thread(target=target, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not roll._in_flight:
            time.sleep(0.005)
        assert roll._in_flight, "first group never launched"
        reads_before = kube.node_read_requests
        ticks_before = roll.stats["judge_ticks"]
        time.sleep(0.4)  # many poll-cadence judge ticks, no transitions
        assert kube.node_read_requests == reads_before, (
            "steady-state judging must not read nodes"
        )
        assert roll.stats["judge_ticks"] > ticks_before + 3, (
            "the liveness fallback tick must keep running"
        )
        t.join(timeout=20)
        assert not t.is_alive()
    finally:
        agents.close()
        informer.stop()
    report = box["report"]
    assert report.ok
    assert roll.stats["judge_node_reads"] == 0
    assert roll.stats["delta_judges"] > 0


def test_pipelined_window_advance_beats_the_poll_clock():
    """The moment a group settles, the next group's desired writes
    launch from the wake path: four serial groups with 30ms agents
    complete well inside ONE 5s poll interval, and every recorded
    advance latency sits far under poll_s."""
    kube = FakeKube()
    names = [f"n{i}" for i in range(4)]
    _pool(kube, *[_node(n, desired="off", state="off") for n in names])
    informer = _informer_for(kube)
    agents = _InformerAgents(kube, informer, delay_s=0.03)
    t0 = time.monotonic()
    roll = Rollout(kube, "on", max_unavailable=1, poll_s=5.0,
                   group_timeout_s=30, informer=informer)
    try:
        report = roll.run()
    finally:
        agents.close()
        informer.stop()
    elapsed = time.monotonic() - t0
    assert report.ok
    assert elapsed < 5.0, (
        f"4 serial groups took {elapsed:.2f}s — window advancement is "
        "waiting out the poll tick"
    )
    adv = list(roll.stats["advance_latencies_s"])
    assert len(adv) == 3
    assert max(adv) < 1.0


def test_watch_drop_mid_rollout_falls_back_to_interval_judging():
    """Degradation contract: a watch drop the informer cannot heal
    mid-rollout flips the judge back to its own interval LISTs — the
    rollout still converges, and the fallback reads are visible in
    stats. The sabotage fires from the first group's settlement, so
    the run crosses the healthy -> degraded boundary mid-flight."""
    kube = FakeKube()
    names = [f"n{i}" for i in range(3)]
    _pool(kube, *[_node(n, desired="off", state="off") for n in names])
    informer = _informer_for(kube)
    # poll-based agents: they must keep converging nodes after the
    # informer (and its delta-fed fake agents) is dead
    agents = _ReactiveAgents(kube, names, delay_s=0.02)
    agents.start()

    dropped = threading.Event()

    def sabotage(gname, outcome, done, total):
        if not dropped.is_set():
            dropped.set()
            with informer._lock:
                informer._watch_supported = False
            informer.stop()

    roll = Rollout(kube, "on", max_unavailable=1, poll_s=0.02,
                   group_timeout_s=15, informer=informer,
                   on_group=sabotage)
    try:
        report = roll.run()
    finally:
        agents.stop.set()
        informer.stop()
    assert report.ok
    assert {g.outcome for g in report.groups} == {"succeeded"}
    assert roll.stats["judge_node_reads"] > 0, (
        "the degraded judge must have paid real LIST round trips"
    )


def test_resume_works_identically_under_event_driven_judge(monkeypatch):
    """The crash/resume contract is feed-independent: kill an
    event-driven rollout mid-window, resume WITH a feed, and get one
    coherent report with every group exactly once — and the resumed
    run's judge still performs zero node reads."""
    kube = FakeKube()
    names = [f"n{i}" for i in range(4)]
    _pool(kube, *[_node(n, desired="off", state="off") for n in names])
    informer = _informer_for(kube)
    agents = _InformerAgents(kube, informer, delay_s=0.02)

    class _OnlyN0(_InformerAgents):
        def _on_event(self, etype, node):
            if (node.get("metadata") or {}).get("name") == "n0":
                super()._on_event(etype, node)

    agents.close()
    agents = _OnlyN0(kube, informer, delay_s=0.02)
    roll = Rollout(kube, "on", max_unavailable=1, group_timeout_s=60,
                   poll_s=0.05, informer=informer)

    def ready(rec):
        g = rec.get("groups", {})
        return (g.get("node/n0", {}).get("outcome") == "succeeded"
                and g.get("node/n1", {}).get("outcome") == "in_flight")

    rec = _crash_rollout_at(kube, monkeypatch, roll, ready)
    agents.close()
    assert rec["complete"] is False

    agents2 = _InformerAgents(kube, informer, delay_s=0.02)
    try:
        resumed = Rollout.resume(kube, poll_s=0.05, group_timeout_s=60,
                                 informer=informer)
        report = resumed.run()
    finally:
        agents2.close()
        informer.stop()
    assert report.ok
    assert [g.name for g in report.groups] == sorted(
        f"node/{n}" for n in names)
    assert {g.outcome for g in report.groups} == {"succeeded"}
    assert resumed.stats["judge_node_reads"] == 0


def test_delta_judge_racing_group_timeout_picks_one_outcome():
    """The exactly-once pin: a delta-fed judge (convergence) and the
    fallback tick (expired deadline) racing over the same group must
    produce exactly ONE terminal outcome — whichever wins, the loser
    finds nothing in flight."""
    kube = FakeKube()
    _pool(kube, _node("n1", desired="off", state="off"))
    roll = Rollout(kube, "on", poll_s=0.05, group_timeout_s=60)
    # admit with the pre-flip snapshot (non-terminal — the admit-time
    # judge must leave the group in flight); the racing delta carries
    # the converged node
    node_off = kube.get_node("n1")
    node_on = kube.get_node("n1")
    node_on["metadata"]["labels"][L.CC_MODE_LABEL] = "on"
    node_on["metadata"]["labels"][L.CC_MODE_STATE_LABEL] = "on"
    for _ in range(20):
        roll._admit_group("node/n1", ["n1"], {"n1": node_off}, set())
        with roll._judge_lock:
            members, _, sf = roll._in_flight["node/n1"]
            # force the deadline into the past: the tick path will
            # judge timeout, the delta path judges convergence
            roll._in_flight["node/n1"] = (
                members, time.monotonic() - 1.0, sf,
            )
        barrier = threading.Barrier(2)

        def delta():
            barrier.wait()
            roll._on_delta("MODIFIED", node_on)

        def tick():
            barrier.wait()
            with roll._judge_lock:
                roll._judge_locked("node/n1")

        t1 = threading.Thread(target=delta)
        t2 = threading.Thread(target=tick)
        t1.start(); t2.start()
        t1.join(5); t2.join(5)
        with roll._judge_lock:
            assert len(roll._ready) == 1, (
                "a racing judge pair must settle exactly one outcome"
            )
            assert not roll._in_flight
            roll._ready.clear()
            roll._watched.clear()
            roll._live.clear()
