"""The parallel per-device flip pipeline (ISSUE 4 tentpole).

Pins the flipexec/engine contract documented in docs/engine.md:

- fail-secure under concurrency: one device's verify mismatch fails the
  whole flip, leaves THAT device at FLIP_LOCK_PERMS, lets in-flight
  siblings finish (and re-open on their own success), and skips
  not-yet-started items untouched;
- the concurrency cap is honored;
- ``TPU_CC_FLIP_CONCURRENCY=1`` is byte-identical in trace-span order to
  the historical serial loop;
- cross-thread span parenting: every per-device span still nests under
  the enclosing reconcile-side span, in one trace;
- ICI switches flip strictly after all chips, serially;
- the mode snapshot kills the duplicate device queries (one query per
  domain per device per reconcile).
"""

import os
import stat
import threading

import pytest

from tpu_cc_manager.device.base import DeviceError, set_backend
from tpu_cc_manager.device.fake import FakeBackend, FakeChip, fake_backend
from tpu_cc_manager.device.gate import DeviceGate, FLIP_LOCK_PERMS, MODE_PERMS
from tpu_cc_manager.engine import ModeEngine
from tpu_cc_manager.flipexec import flip_concurrency
from tpu_cc_manager.trace import Tracer


def _dev_file(tmp_path, name, perms=0o666):
    p = tmp_path / name
    p.write_text("")
    os.chmod(p, perms)
    return str(p)


def _perms(path):
    return stat.S_IMODE(os.stat(path).st_mode)


def _engine(backend, states=None, **kw):
    states = states if states is not None else []
    kw.setdefault("evict_components", False)
    kw.setdefault("gate", DeviceGate(enabled=True))
    return ModeEngine(set_state_label=states.append, backend=backend, **kw)


# ------------------------------------------------------------ knob parsing


def test_flip_concurrency_default_is_min_4_plan_size(monkeypatch):
    monkeypatch.delenv("TPU_CC_FLIP_CONCURRENCY", raising=False)
    assert flip_concurrency(1) == 1
    assert flip_concurrency(3) == 3
    assert flip_concurrency(8) == 4
    assert flip_concurrency(0) == 1  # degenerate plan still a valid cap


def test_flip_concurrency_env_and_override(monkeypatch):
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "2")
    assert flip_concurrency(8) == 2
    assert flip_concurrency(8, override=6) == 6  # constructor wins
    assert flip_concurrency(4, override=16) == 4  # clamped to plan


def test_flip_concurrency_invalid_env_fails_loudly(monkeypatch):
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "many")
    with pytest.raises(DeviceError):
        flip_concurrency(4)
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "0")
    with pytest.raises(DeviceError):
        flip_concurrency(4)


# ------------------------------------------------- parallel failure modes


class SlowResetChip(FakeChip):
    """Reset blocks until ``release`` is set — the in-flight sibling."""

    def __init__(self, path, release, **kw):
        super().__init__(path=path, **kw)
        self._release = release

    def reset(self):
        assert self._release.wait(timeout=30), "release event never set"
        super().reset()


def _mirror_abort_into(monkeypatch, release):
    """Make flipexec's abort Event mirror into ``release`` when set.

    Determinism glue: the in-flight sibling (SlowResetChip) stays
    blocked until the executor's abort flag is ACTUALLY set, so by the
    time it completes and a worker dequeues the queued item, the skip
    is guaranteed — no race between the failing worker's abort.set()
    and the sibling's worker reaching the queue."""
    import types

    from tpu_cc_manager import flipexec as flipexec_mod

    class MirroringEvent(threading.Event):
        def set(self):
            super().set()
            release.set()

    monkeypatch.setattr(
        flipexec_mod, "threading", types.SimpleNamespace(Event=MirroringEvent)
    )


def test_parallel_verify_failure_is_fail_secure(tmp_path, monkeypatch):
    """One chip verify-fails mid-parallel-flip: set_mode is False, the
    failed chip stays locked, the completed sibling is re-gated open,
    the queued item is skipped untouched."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "2")
    release = threading.Event()
    _mirror_abort_into(monkeypatch, release)
    slow = SlowResetChip(_dev_file(tmp_path, "accel0"), release)
    failing = FakeChip(path=_dev_file(tmp_path, "accel1"))
    failing.drop_staged_mode = True  # set "succeeds", never takes effect
    queued = FakeChip(path=_dev_file(tmp_path, "accel2", perms=0o644))
    states = []
    engine = _engine(FakeBackend(chips=[slow, failing, queued]), states)

    assert engine.set_mode("on") is False
    assert states == ["failed"]

    # the failing device: fail-secure, left at the flip-lock perms
    assert _perms(failing.path) == FLIP_LOCK_PERMS
    # the in-flight sibling ran its own sequence to completion and
    # re-opened with the verified mode's perms
    assert slow.resets == 1
    assert slow.query_cc_mode() == "on"
    assert _perms(slow.path) == MODE_PERMS["on"]
    # the not-yet-started item was skipped untouched: no stage, no
    # reset, gate never locked it (original perms survive)
    assert queued.sets == 0
    assert queued.resets == 0
    assert _perms(queued.path) == 0o644


def test_parallel_device_error_semantics(tmp_path, monkeypatch):
    """Same contract when the failure is a DeviceError (reset explodes)
    rather than a verify mismatch."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "2")
    release = threading.Event()
    _mirror_abort_into(monkeypatch, release)

    class ExplodingResetChip(FakeChip):
        def reset(self):
            raise DeviceError(f"{self.path}: reset failed (injected)")

    slow = SlowResetChip(_dev_file(tmp_path, "accel0"), release)

    failing = ExplodingResetChip(path=_dev_file(tmp_path, "accel1"))
    queued = FakeChip(path=_dev_file(tmp_path, "accel2"))
    states = []
    engine = _engine(FakeBackend(chips=[slow, failing, queued]), states)

    assert engine.set_mode("on") is False
    assert states == ["failed"]
    assert _perms(failing.path) == FLIP_LOCK_PERMS
    assert _perms(slow.path) == MODE_PERMS["on"]
    assert queued.sets == 0 and queued.resets == 0


def test_parallel_unexpected_exception_still_publishes_failed(monkeypatch):
    """A non-DeviceError from a worker propagates (after siblings
    complete) into _drain_wrapped's unexpected-failure handler — the
    state label still reads failed, exactly like the serial path."""
    monkeypatch.delenv("TPU_CC_FLIP_CONCURRENCY", raising=False)

    class BuggyChip(FakeChip):
        def reset(self):
            raise RuntimeError("not a DeviceError")

    chips = [BuggyChip(path=f"/dev/accel{i}") for i in range(3)]
    states = []
    engine = _engine(FakeBackend(chips=chips), states,
                     gate=DeviceGate(enabled=False))
    assert engine.set_mode("on") is False
    assert states == ["failed"]


# ------------------------------------------------------- cap enforcement


class GaugedChip(FakeChip):
    """Tracks how many resets overlap across ALL GaugedChips."""

    gauge_lock = threading.Lock()
    active = 0
    max_active = 0

    @classmethod
    def reset_gauge(cls):
        with cls.gauge_lock:
            cls.active = cls.max_active = 0

    def reset(self):
        cls = GaugedChip
        with cls.gauge_lock:
            cls.active += 1
            cls.max_active = max(cls.max_active, cls.active)
        try:
            super().reset()
        finally:
            with cls.gauge_lock:
                cls.active -= 1


def test_concurrency_cap_is_honored(monkeypatch):
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "3")
    GaugedChip.reset_gauge()
    chips = [
        GaugedChip(path=f"/dev/accel{i}", reset_latency_s=0.05)
        for i in range(8)
    ]
    engine = _engine(FakeBackend(chips=chips),
                     gate=DeviceGate(enabled=False))
    assert engine.set_mode("on") is True
    assert all(c.resets == 1 for c in chips)
    assert GaugedChip.max_active <= 3
    # with 8 x 50ms resets through 3 workers, overlap must actually
    # have happened — otherwise the "pipeline" is a serial loop
    assert GaugedChip.max_active >= 2


# ---------------------------------------------- serial byte-identity


def _span_sig(tracer):
    """(name, device-attr) per completed span, in completion order."""
    return [
        (s["name"], (s.get("attrs") or {}).get("device"))
        for s in tracer.recent()
    ]


def test_concurrency_1_is_byte_identical_serial_span_order(monkeypatch):
    """The exact completion order the pre-pipeline serial loop emitted,
    device by device, in plan order."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    tr = Tracer()
    backend = fake_backend(n_chips=3)
    engine = ModeEngine(
        set_state_label=lambda v: None, evict_components=False,
        backend=backend, tracer=tr, gate=DeviceGate(enabled=False),
    )
    assert engine.set_mode("on") is True
    expected = [("enumerate", None), ("plan", None), ("taint_set", None)]
    for i in range(3):
        d = f"/dev/accel{i}"
        expected += [
            ("stage", d), ("holder_check", d), ("reset", d),
            ("wait_ready", d), ("verify", d), ("flip", d),
        ]
    expected += [("taint_clear", None), ("state_label", None)]
    assert _span_sig(tr) == expected


# ------------------------------------------- cross-thread span parenting


def test_parallel_spans_stay_in_one_reconcile_trace(monkeypatch):
    """Worker-thread spans adopt the submitting thread's current span:
    one trace, flips parented under the enclosing span, sub-phases
    parented under their own device's flip."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "4")
    tr = Tracer()
    backend = fake_backend(n_chips=4, reset_latency_s=0.01)
    engine = ModeEngine(
        set_state_label=lambda v: None, evict_components=False,
        backend=backend, tracer=tr, gate=DeviceGate(enabled=False),
    )
    with tr.span("reconcile") as root:
        assert engine.set_mode("on") is True
    spans = tr.recent()
    assert all(s["trace"] == root.trace_id for s in spans)
    flips = {s["attrs"]["device"]: s for s in spans if s["name"] == "flip"}
    assert len(flips) == 4
    for s in spans:
        if s["name"] in ("stage", "holder_check", "reset", "wait_ready",
                         "verify"):
            # each sub-phase hangs off ITS device's flip span, not some
            # sibling thread's
            assert s["parent"] == flips[s["attrs"]["device"]]["span"]
    # flip spans parent under what the submitting thread had open: the
    # taint/evict wrapper runs directly under our reconcile span
    for f in flips.values():
        assert f["parent"] == root.span_id
    # per-phase attribution intact: one span of each sub-phase per chip
    names = [s["name"] for s in spans]
    for phase in ("stage", "reset", "wait_ready", "verify"):
        assert names.count(phase) == 4


def test_parallel_spans_without_enclosing_span_are_rooted(monkeypatch):
    """No enclosing span (one-shot CLI shape): worker spans must still
    record without error and each flip becomes its own root."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "2")
    tr = Tracer()
    backend = fake_backend(n_chips=2)
    engine = ModeEngine(
        set_state_label=lambda v: None, evict_components=False,
        backend=backend, tracer=tr, gate=DeviceGate(enabled=False),
    )
    assert engine.set_mode("on") is True
    flips = [s for s in tr.recent() if s["name"] == "flip"]
    assert len(flips) == 2
    assert all(s.get("parent") is None for s in flips)


# -------------------------------------------------- switch serialization


def test_switches_flip_after_all_chips_and_serially(monkeypatch):
    """ICI switches are excluded from the parallel wave: they flip only
    after every chip landed, one at a time."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "4")
    chips_done = []
    order_lock = threading.Lock()

    class OrderChip(FakeChip):
        def reset(self):
            super().reset()
            with order_lock:
                chips_done.append(self.path)

    chips = [
        OrderChip(path=f"/dev/accel{i}", reset_latency_s=0.01)
        for i in range(4)
    ]
    switches = [
        OrderChip(path=f"/dev/ici-switch{i}", name="ici-switch",
                  is_switch=True, cc_capable=False)
        for i in range(2)
    ]
    engine = _engine(FakeBackend(chips=chips + switches),
                     gate=DeviceGate(enabled=False))
    assert engine.set_mode("ici") is True
    # every chip reset strictly precedes every switch reset
    switch_idx = [chips_done.index(s.path) for s in switches]
    assert min(switch_idx) >= 4


def test_chip_failure_leaves_switches_untouched(monkeypatch, caplog):
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "4")
    chips = [FakeChip(path=f"/dev/accel{i}") for i in range(2)]
    chips[1].fail_reset = True
    switch = FakeChip(path="/dev/ici-switch0", name="ici-switch",
                      is_switch=True, cc_capable=False)
    engine = _engine(FakeBackend(chips=chips + [switch]),
                     gate=DeviceGate(enabled=False))
    with caplog.at_level("WARNING", logger="tpu-cc-manager.engine"):
        assert engine.set_mode("ici") is False
    assert switch.sets == 0 and switch.resets == 0
    # uniform disposition reporting: the untouched switch gets an
    # explicit skip line, same as a queued chip would
    assert any(
        "/dev/ici-switch0" in r.message and "skipped" in r.message
        for r in caplog.records
    )


# ----------------------------------------------- snapshot query dedup


def test_fast_path_queries_each_domain_once(monkeypatch):
    """Satellite: the converged-subset gate reassert reads the plan's
    snapshot instead of re-querying — ONE cc + ONE ici query per device
    on the idempotent fast path (it used to be two cc queries)."""
    monkeypatch.delenv("TPU_CC_FLIP_CONCURRENCY", raising=False)
    backend = fake_backend(n_chips=4, cc_mode="on")
    set_backend(backend)
    engine = ModeEngine(
        set_state_label=lambda v: None, evict_components=False,
        backend=backend, gate=DeviceGate(enabled=True),
    )
    assert engine.set_mode("on") is True
    for c in backend.chips:
        assert c.cc_queries == 1
        assert c.ici_queries == 1


def test_flip_path_has_no_pre_flip_requery(monkeypatch):
    """A divergent device is queried once per domain at plan time; the
    only later reads are the verify-phase query-backs."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    backend = fake_backend(n_chips=2)
    engine = ModeEngine(
        set_state_label=lambda v: None, evict_components=False,
        backend=backend, gate=DeviceGate(enabled=False),
    )
    assert engine.set_mode("on") is True
    for c in backend.chips:
        # 1 snapshot read + 1 verify query-back per domain: cc flipped
        # (verify re-reads it), ici already at target (no verify)
        assert c.cc_queries == 2
        assert c.ici_queries == 1


def test_invalid_concurrency_fails_before_drain(monkeypatch):
    """A typo'd TPU_CC_FLIP_CONCURRENCY must fail at plan time — before
    the taint/evict cycle churns workloads (the agent's generic handler
    still publishes cc.mode.state=failed)."""
    from tpu_cc_manager.engine import Drainer

    class RecordingDrainer(Drainer):
        def __init__(self):
            self.events = []

        def evict(self):
            self.events.append("evict")

        def reschedule(self):
            self.events.append("reschedule")

    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "four")
    drainer = RecordingDrainer()
    states = []
    engine = ModeEngine(
        set_state_label=states.append, drainer=drainer,
        evict_components=True, backend=fake_backend(n_chips=2),
        gate=DeviceGate(enabled=False),
    )
    with pytest.raises(DeviceError):
        engine.set_mode("on")
    assert drainer.events == []  # no evict/reschedule round trip


# ---------------------------------------- async-core serial equivalence


def test_aio_window1_span_order_byte_identical_to_threaded(monkeypatch):
    """ISSUE 13 acceptance: with the async core at window=1 serving
    the engine's state/taint writes through the sync façade, flip
    trace-span order is byte-identical to the threaded-client path —
    the façade blocks the calling thread per call, so submit order ==
    completion order and nothing about the span tree moves."""
    from tpu_cc_manager.drain import NodeFlipTaint
    from tpu_cc_manager.k8s.aio_bridge import SyncKubeFacade
    from tpu_cc_manager.k8s.apiserver import FakeApiServer
    from tpu_cc_manager.k8s.batch import NodePatchBatcher
    from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig
    from tpu_cc_manager.k8s.objects import make_node

    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")

    def flip_spans(make_kube):
        with FakeApiServer() as srv:
            srv.store.add_node(make_node("n0"))
            kube = make_kube(srv)
            tr = Tracer()
            batcher = NodePatchBatcher(kube, "n0", tracer=tr)
            engine = ModeEngine(
                set_state_label=batcher.write_state_label,
                evict_components=False,
                backend=fake_backend(n_chips=3),
                tracer=tr,
                gate=DeviceGate(enabled=False),
                flip_taint=NodeFlipTaint(kube, "n0", batcher=batcher),
            )
            assert engine.set_mode("on") is True
            if hasattr(kube, "close"):
                kube.close()
            return _span_sig(tr)

    threaded = flip_spans(lambda srv: HttpKubeClient(
        KubeConfig("127.0.0.1", srv.port, use_tls=False)
    ))
    aio = flip_spans(lambda srv: SyncKubeFacade(
        KubeConfig("127.0.0.1", srv.port, use_tls=False),
        max_conns=1, window=1,
    ))
    assert aio == threaded
    # and the sequence really is the full serial flip shape, wire
    # writes included
    names = [n for n, _ in aio]
    assert names[:3] == ["enumerate", "plan", "taint_set"]
    assert names[-2:] == ["taint_clear", "state_label"] or (
        "taint_clear" in names
    )


# --------------------------------------- stage/holder-scan overlap


class _RecordingHolder:
    """HolderCheck stand-in: records scan start/end stamps."""

    enabled = True

    def __init__(self, scan_s=0.0, fail=False):
        self.scan_s = scan_s
        self.fail = fail
        self.calls = []
        self.done = []
        self._lock = threading.Lock()

    def ensure_free(self, path):
        import time as _time

        with self._lock:
            self.calls.append((path, _time.monotonic()))
        if self.scan_s:
            _time.sleep(self.scan_s)
        with self._lock:
            self.done.append((path, _time.monotonic()))
        if self.fail:
            raise DeviceError(f"{path}: held by pid 4242 (injected)")


class _SlowStageChip(FakeChip):
    """set_cc_mode (the stage body) takes ``stage_s``."""

    def __init__(self, path, stage_s=0.0, fail_stage=False, **kw):
        super().__init__(path=path, **kw)
        self.stage_s = stage_s
        self.fail_stage = fail_stage

    def set_cc_mode(self, mode):
        import time as _time

        if self.stage_s:
            _time.sleep(self.stage_s)
        if self.fail_stage:
            raise DeviceError(f"{self.path}: stage failed (injected)")
        super().set_cc_mode(mode)


def test_holder_scan_overlaps_stage(tmp_path, monkeypatch):
    """The scan runs CONCURRENTLY with the stage (disjoint resources):
    it starts before the stage finishes, and the flip pays
    ~max(stage, scan), not their sum."""
    import time as _time

    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    holder = _RecordingHolder(scan_s=0.25)
    chip = _SlowStageChip(_dev_file(tmp_path, "accel0"), stage_s=0.25)
    engine = _engine(FakeBackend(chips=[chip]),
                     gate=DeviceGate(enabled=False),
                     holder_check=holder)
    t0 = _time.monotonic()
    assert engine.set_mode("on") is True
    elapsed = _time.monotonic() - t0
    assert holder.calls and holder.done
    # overlapped: 0.25s stage + 0.25s scan took well under their sum
    assert elapsed < 0.45, elapsed
    # ordering contract: the scan completed before the reset ran
    assert chip.resets == 1


def test_stage_failure_during_overlapped_scan_is_fail_secure(
    tmp_path, monkeypatch
):
    """ISSUE 13 acceptance: a stage failure while the holder scan is
    in flight leaves the device at FLIP_LOCK_PERMS and NEVER resets —
    the scan is joined (not abandoned), the stage's error owns the
    outcome, and gate-lock-before-reset ordering holds."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    holder = _RecordingHolder(scan_s=0.3)
    chip = _SlowStageChip(
        _dev_file(tmp_path, "accel0"), fail_stage=True
    )
    states = []
    engine = _engine(FakeBackend(chips=[chip]), states,
                     holder_check=holder)
    assert engine.set_mode("on") is False
    assert states == ["failed"]
    # fail-secure: locked, never reset
    assert _perms(chip.path) == FLIP_LOCK_PERMS
    assert chip.resets == 0
    # the overlapped scan was started AND joined, not abandoned
    assert len(holder.calls) == 1
    assert len(holder.done) == 1


def test_holder_failure_with_clean_stage_still_fails_secure(
    tmp_path, monkeypatch
):
    """The symmetric case: the stage lands, the overlapped scan finds
    a holder — the device stays locked and un-reset, exactly the
    pre-overlap semantics."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    holder = _RecordingHolder(fail=True)
    chip = FakeChip(path=_dev_file(tmp_path, "accel0"))
    states = []
    engine = _engine(FakeBackend(chips=[chip]), states,
                     holder_check=holder)
    assert engine.set_mode("on") is False
    assert states == ["failed"]
    assert _perms(chip.path) == FLIP_LOCK_PERMS
    assert chip.resets == 0


def test_overlap_keeps_serial_span_order(monkeypatch):
    """The holder_check span keeps its historical position between
    stage and reset (byte-identical serial trace), and carries the
    overlapped attr so phase attribution knows the number is the
    residual wait."""
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "1")
    tr = Tracer()
    holder = _RecordingHolder()
    engine = ModeEngine(
        set_state_label=lambda v: None, evict_components=False,
        backend=fake_backend(n_chips=2), tracer=tr,
        gate=DeviceGate(enabled=False), holder_check=holder,
    )
    assert engine.set_mode("on") is True
    sig = _span_sig(tr)
    for i in range(2):
        d = f"/dev/accel{i}"
        idx = sig.index(("stage", d))
        assert sig[idx + 1] == ("holder_check", d)
        assert sig[idx + 2] == ("reset", d)
    holder_spans = [s for s in tr.recent()
                    if s["name"] == "holder_check"]
    assert all(s["attrs"].get("overlapped") for s in holder_spans)
