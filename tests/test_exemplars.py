"""Trace exemplars (ISSUE 15): the histogram-side contract.

Three surfaces under test: the Histogram/HistogramVec exemplar
retention + OpenMetrics-style render, the validate_exposition exemplar
GRAMMAR (well-formed bucket-line exemplars accepted; everything
else — non-bucket lines, unescaped quotes, empty-bucket exemplars,
values above the bucket bound — rejected), and the fleet-observatory
merge policy (exemplars are STRIPPED deterministically: the merged
exposition never carries them, pinned here)."""

import pytest

from tpu_cc_manager.fleetobs import (
    merge_snapshots, parse_exposition, render_snapshot,
)
from tpu_cc_manager.obs import (
    Histogram, HistogramVec, Metrics, split_exemplar,
    validate_exposition,
)


def _hist(observations):
    h = Histogram("tpu_cc_lat_seconds", "latency", buckets=(0.1, 1))
    for value, tid in observations:
        h.observe(value, trace_id=tid)
    return h


# ----------------------------------------------------------- rendering


def test_histogram_retains_last_exemplar_per_bucket():
    h = _hist([(0.05, "a1"), (0.07, "a2"), (0.5, "b1"), (5.0, "c1")])
    exs = h.exemplars()
    assert [(e["le"], e["trace_id"]) for e in exs] == [
        ("0.1", "a2"),  # newest wins within the bucket
        ("1", "b1"),
        ("+Inf", "c1"),
    ]
    assert exs[0]["value"] == 0.07


def test_render_carries_openmetrics_style_suffix():
    h = _hist([(0.05, "abc")])
    lines = h.render()
    bucket = [l for l in lines if 'le="0.1"' in l][0]
    assert ' # {trace_id="abc"} 0.05 ' in bucket
    # untraced observations render no suffix
    h2 = _hist([(0.05, None)])
    assert all(" # " not in l for l in h2.render())
    # the exemplar-carrying exposition is VALID under the strict
    # validator (the whole point of teaching it the grammar)
    assert validate_exposition("\n".join(lines) + "\n") == []


def test_vec_exemplars_pass_through():
    vec = HistogramVec("tpu_cc_phase_seconds", "p", "phase",
                       buckets=(0.1, 1))
    vec.observe("reset", 0.4, trace_id="t1")
    vec.observe("stage", 0.05)
    exs = vec.exemplars()
    assert list(exs) == ["reset"]  # untraced child carries none
    assert exs["reset"][0]["trace_id"] == "t1"
    text = "\n".join(vec.render()) + "\n"
    assert '# {trace_id="t1"}' in text
    assert validate_exposition(text) == []


def test_metrics_set_with_exemplars_validates():
    m = Metrics()
    m.reconcile_duration.observe(0.3, trace_id="deadbeef1")
    m.phase_duration.observe("reset", 0.2, trace_id="deadbeef2")
    assert validate_exposition(m.render()) == []


# ------------------------------------------------------------- grammar


HEAD = "# HELP x h\n# TYPE x histogram\n"


def _problems(body):
    return validate_exposition(HEAD + body)


def test_wellformed_exemplar_accepted():
    assert _problems(
        'x_bucket{le="1"} 2 # {trace_id="ab12"} 0.5 1700000000.123\n'
        'x_bucket{le="+Inf"} 2\nx_sum 1.0\nx_count 2\n'
    ) == []


def test_exemplar_timestamp_optional():
    assert _problems(
        'x_bucket{le="1"} 1 # {trace_id="ab12"} 0.5\n'
        'x_bucket{le="+Inf"} 1\nx_sum 0.5\nx_count 1\n'
    ) == []


def test_exemplar_on_non_bucket_line_rejected():
    probs = _problems(
        'x_bucket{le="1"} 1\nx_bucket{le="+Inf"} 1\n'
        'x_sum 0.5 # {trace_id="ab"} 0.5 1.0\nx_count 1\n'
    )
    assert any("non-bucket" in p for p in probs)


def test_exemplar_unescaped_quote_rejected():
    probs = _problems(
        'x_bucket{le="1"} 1 # {trace_id="a"b"} 0.5 1.0\n'
        'x_bucket{le="+Inf"} 1\nx_sum 0.5\nx_count 1\n'
    )
    assert any("exemplar" in p and "malformed" in p for p in probs)


def test_exemplar_on_empty_bucket_rejected():
    # an exemplar claims an observation; a zero cumulative count says
    # there never was one — the "disagrees with no observation" case
    probs = _problems(
        'x_bucket{le="1"} 0 # {trace_id="ab"} 0.5 1.0\n'
        'x_bucket{le="+Inf"} 0\nx_sum 0\nx_count 0\n'
    )
    assert any("empty bucket" in p for p in probs)


def test_exemplar_value_above_bucket_bound_rejected():
    probs = _problems(
        'x_bucket{le="1"} 1 # {trace_id="ab"} 4.2 1.0\n'
        'x_bucket{le="+Inf"} 1\nx_sum 0.5\nx_count 1\n'
    )
    assert any("above its bucket bound" in p for p in probs)


@pytest.mark.parametrize("suffix", [
    ' # {trace_id="ab"} notanumber 1.0',
    ' # {trace_id="ab"} 0.5 notatime',
])
def test_exemplar_non_numeric_fields_rejected(suffix):
    probs = _problems(
        f'x_bucket{{le="1"}} 1{suffix}\n'
        'x_bucket{le="+Inf"} 1\nx_sum 0.5\nx_count 1\n'
    )
    assert any("non-numeric exemplar" in p for p in probs)


def test_split_exemplar_no_suffix_roundtrip():
    line = 'x_bucket{le="1"} 3'
    assert split_exemplar(line) == (line, None)


# ------------------------------------------------- fleetobs merge policy


def test_merge_strips_exemplars_deterministically():
    """The pinned policy (ISSUE 15 satellite): the fleet merge STRIPS
    exemplars — parse drops them, so the merged render can never emit
    one, while bucket counts survive the strip intact."""
    m1, m2 = Metrics(), Metrics()
    m1.reconcile_duration.observe(0.3, trace_id="replica-one")
    m2.reconcile_duration.observe(0.4, trace_id="replica-two")
    snaps = []
    for m in (m1, m2):
        text = m.render()
        assert "trace_id=" in text  # the inputs DO carry exemplars
        snap, _helps = parse_exposition(text)
        snaps.append(snap)
    merged = merge_snapshots(snaps)
    out = render_snapshot(merged)
    assert "trace_id=" not in out
    assert " # " not in out
    assert validate_exposition(out) == []
    # the strip lost no accounting: both observations merged
    hist = merged["tpu_cc_reconcile_duration_seconds"]["hist"][""]
    assert hist["count"] == 2
