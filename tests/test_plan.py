"""Fleet-planner tests: encoding, divergence/failure detection, slice
coherence auditing, and the sharded dry run."""

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.plan import (
    MODE_CODES,
    analyze_fleet,
    encode_fleet,
    encode_mode,
)


def _node(name, desired=None, observed=None, slice_id=None):
    labels = {}
    if desired:
        labels[L.CC_MODE_LABEL] = desired
    if observed:
        labels[L.CC_MODE_STATE_LABEL] = observed
    if slice_id:
        labels[L.TPU_SLICE_LABEL] = slice_id
    return make_node(name, labels=labels)


def test_encode_mode():
    assert encode_mode("on") == MODE_CODES["on"]
    assert encode_mode(None) == MODE_CODES["unknown"]
    assert encode_mode("garbage") == MODE_CODES["unknown"]
    assert encode_mode("failed") == MODE_CODES["failed"]


def test_encode_fleet_dense_slice_ids():
    nodes = [
        _node("a", slice_id="s1"),
        _node("b", slice_id="s2"),
        _node("c", slice_id="s1"),
        _node("d"),  # solo node gets its own singleton slice
    ]
    desired, observed, slice_ids, names, slice_index = encode_fleet(nodes)
    assert names == ["a", "b", "c", "d"]
    assert slice_ids[0] == slice_ids[2] != slice_ids[1]
    assert len(slice_index) == 3


def test_analyze_fleet_divergence_and_failures():
    nodes = [
        _node("ok", desired="on", observed="on"),
        _node("lagging", desired="on", observed="off"),
        _node("broken", desired="on", observed="failed"),
        _node("unlabeled"),  # no desired -> never in needs_flip
    ]
    report = analyze_fleet(nodes)
    assert report["nodes"] == 4
    assert set(report["needs_flip"]) == {"lagging", "broken"}
    assert report["failed"] == ["broken"]
    assert report["mode_counts"]["on"] == 1
    assert report["mode_counts"]["failed"] == 1


def test_analyze_fleet_slice_coherence():
    nodes = [
        # coherent slice: all at target
        _node("a0", desired="on", observed="on", slice_id="sa"),
        _node("a1", desired="on", observed="on", slice_id="sa"),
        # half-flipped slice: uniform desired, mixed observed
        _node("b0", desired="on", observed="on", slice_id="sb"),
        _node("b1", desired="on", observed="off", slice_id="sb"),
        # divergent desired (operator error): incoherent but not half-flipped
        _node("c0", desired="on", observed="off", slice_id="sc"),
        _node("c1", desired="off", observed="off", slice_id="sc"),
    ]
    report = analyze_fleet(nodes)
    assert "sa" not in report["incoherent_slices"]
    assert set(report["incoherent_slices"]) == {"sb", "sc"}
    assert report["half_flipped_slices"] == ["sb"]


def test_analyze_fleet_empty():
    assert analyze_fleet([])["nodes"] == 0


def test_graft_entry_single_device():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graft_entry", "__graft_entry__.py"
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    import jax

    fn, args = m.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert int(out["mode_counts"].sum()) == 256


def test_graft_entry_multichip_dryrun():
    import importlib.util

    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    spec = importlib.util.spec_from_file_location(
        "graft_entry", "__graft_entry__.py"
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    m.dryrun_multichip(8)  # asserts sharded == unsharded internally


def test_slice_relabel_churn_never_overflows_bucket():
    """Regression: slice-id assignment is monotonic and the release-side
    compaction is amortized, so a relabel churn on a mid-size fleet
    could push live slice ids past the node bucket's slot count —
    crashing analyze_encoding with an IndexError (and silently dropping
    scatter rows before that). snapshot() now compacts whenever ids
    would not fit."""
    from tpu_cc_manager.plan import FleetEncoding, analyze_encoding, bucket_nodes

    enc = FleetEncoding()
    for i in range(50):
        enc.apply(_node(f"base-{i}", desired="on", observed="on"))
    churner = "churn-node"
    for round_ in range(60):
        enc.apply(make_node(churner, labels={
            L.CC_MODE_LABEL: "on",
            L.CC_MODE_STATE_LABEL: "off",
            L.TPU_SLICE_LABEL: f"ephemeral-{round_}",
        }))
        report = analyze_encoding(enc)  # must never throw
        assert report["nodes"] == 51
        assert report["needs_flip"] == [churner]
    snap = enc.snapshot()
    nb = bucket_nodes(snap.n_nodes)
    assert all(v < nb for v in snap.slice_index.values())


def test_analyze_pools_counts_and_failed_stays_eligible():
    """Per-pool kernel counts — and the recovery contract: FAILED nodes
    stay rollout-eligible (re-driving desired labels is how a failed
    flip recovers), while mid-flip taints and failing doctors hold."""
    import json as _json

    from tpu_cc_manager.plan import analyze_pools

    def taint_node(name):
        n = _node(name, desired="on", observed="off")
        n.setdefault("spec", {})["taints"] = [
            {"key": L.FLIP_TAINT_KEY, "effect": "NoSchedule"}
        ]
        return n

    def doctor_node(name):
        n = _node(name, desired="on", observed="off")
        n["metadata"].setdefault("annotations", {})[
            L.DOCTOR_ANNOTATION
        ] = _json.dumps({"ok": False, "fail": ["iommu"], "at": None})
        return n

    stats = analyze_pools([
        ("mixed", "on", [
            _node("m-conv", desired="on", observed="on"),
            _node("m-div", desired="off", observed="off"),
            taint_node("m-flip"),
            doctor_node("m-doc"),
        ]),
        ("all-failed", "on", [
            _node(f"f-{i}", desired="off", observed="failed")
            for i in range(3)
        ]),
    ])
    mixed = stats["mixed"]
    assert mixed == {
        "nodes": 4, "converged": 1, "failed": 0, "divergent": 3,
        # observed modes: on/off/off/off -> 1 off the dominant mode;
        # of 3 divergent, the tainted and doctor-failing nodes hold
        "skew": 1, "eligible": 1,
    }
    af = stats["all-failed"]
    assert af["nodes"] == 3 and af["failed"] == 3 and af["divergent"] == 3
    # the regression pin: an all-failed pool must NOT read eligible=0
    # (that held its rollout launch forever)
    assert af["eligible"] == 3


def test_doctor_timestamp_only_republish_does_not_reencode():
    """The feature block's O(changed) contract under periodic doctor
    republishing: a verdict whose CONTENT is unchanged (only the
    timestamp moved) must not dirty the fingerprint — the same stable
    {ok, fail} reduction the watch wake-filter uses."""
    import json as _json

    from tpu_cc_manager.plan import FleetEncoding

    def doctored(ok, fail, at):
        n = _node("doc-n", desired="on", observed="on")
        n["metadata"].setdefault("annotations", {})[
            L.DOCTOR_ANNOTATION
        ] = _json.dumps({"ok": ok, "fail": fail, "at": at})
        return n

    enc = FleetEncoding()
    assert enc.apply(doctored(True, [], "2026-08-03T00:00:00Z"))
    assert not enc.apply(doctored(True, [], "2026-08-03T00:01:00Z"))
    # content change still re-encodes
    assert enc.apply(doctored(False, ["iommu"], "2026-08-03T00:02:00Z"))
    assert not enc.apply(doctored(False, ["iommu"], "2026-08-03T00:03:00Z"))


def test_unchanged_slice_membership_keeps_its_id():
    """Mode/taint/doctor updates must not release/re-acquire the row's
    slice id — slot churn on every update would lean on compaction and
    cost O(slices) per update."""
    from tpu_cc_manager.plan import FleetEncoding

    enc = FleetEncoding()
    enc.apply(_node("churn", desired="on", observed="off",
                    slice_id="s-stable"))
    sid_before = dict(enc._slice_index)["s-stable"]
    next_before = enc._next_slice
    for observed in ("on", "off", "on"):
        enc.apply(_node("churn", desired="on", observed=observed,
                        slice_id="s-stable"))
    assert dict(enc._slice_index)["s-stable"] == sid_before
    assert enc._next_slice == next_before
