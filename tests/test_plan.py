"""Fleet-planner tests: encoding, divergence/failure detection, slice
coherence auditing, and the sharded dry run."""

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.plan import (
    MODE_CODES,
    analyze_fleet,
    encode_fleet,
    encode_mode,
)


def _node(name, desired=None, observed=None, slice_id=None):
    labels = {}
    if desired:
        labels[L.CC_MODE_LABEL] = desired
    if observed:
        labels[L.CC_MODE_STATE_LABEL] = observed
    if slice_id:
        labels[L.TPU_SLICE_LABEL] = slice_id
    return make_node(name, labels=labels)


def test_encode_mode():
    assert encode_mode("on") == MODE_CODES["on"]
    assert encode_mode(None) == MODE_CODES["unknown"]
    assert encode_mode("garbage") == MODE_CODES["unknown"]
    assert encode_mode("failed") == MODE_CODES["failed"]


def test_encode_fleet_dense_slice_ids():
    nodes = [
        _node("a", slice_id="s1"),
        _node("b", slice_id="s2"),
        _node("c", slice_id="s1"),
        _node("d"),  # solo node gets its own singleton slice
    ]
    desired, observed, slice_ids, names, slice_index = encode_fleet(nodes)
    assert names == ["a", "b", "c", "d"]
    assert slice_ids[0] == slice_ids[2] != slice_ids[1]
    assert len(slice_index) == 3


def test_analyze_fleet_divergence_and_failures():
    nodes = [
        _node("ok", desired="on", observed="on"),
        _node("lagging", desired="on", observed="off"),
        _node("broken", desired="on", observed="failed"),
        _node("unlabeled"),  # no desired -> never in needs_flip
    ]
    report = analyze_fleet(nodes)
    assert report["nodes"] == 4
    assert set(report["needs_flip"]) == {"lagging", "broken"}
    assert report["failed"] == ["broken"]
    assert report["mode_counts"]["on"] == 1
    assert report["mode_counts"]["failed"] == 1


def test_analyze_fleet_slice_coherence():
    nodes = [
        # coherent slice: all at target
        _node("a0", desired="on", observed="on", slice_id="sa"),
        _node("a1", desired="on", observed="on", slice_id="sa"),
        # half-flipped slice: uniform desired, mixed observed
        _node("b0", desired="on", observed="on", slice_id="sb"),
        _node("b1", desired="on", observed="off", slice_id="sb"),
        # divergent desired (operator error): incoherent but not half-flipped
        _node("c0", desired="on", observed="off", slice_id="sc"),
        _node("c1", desired="off", observed="off", slice_id="sc"),
    ]
    report = analyze_fleet(nodes)
    assert "sa" not in report["incoherent_slices"]
    assert set(report["incoherent_slices"]) == {"sb", "sc"}
    assert report["half_flipped_slices"] == ["sb"]


def test_analyze_fleet_empty():
    assert analyze_fleet([])["nodes"] == 0


def test_graft_entry_single_device():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graft_entry", "__graft_entry__.py"
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    import jax

    fn, args = m.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert int(out["mode_counts"].sum()) == 256


def test_graft_entry_multichip_dryrun():
    import importlib.util

    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual cpu devices")
    spec = importlib.util.spec_from_file_location(
        "graft_entry", "__graft_entry__.py"
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    m.dryrun_multichip(8)  # asserts sharded == unsharded internally
