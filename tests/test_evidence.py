"""Per-flip attestation evidence + fleet evidence-vs-label audit
(VERDICT r2 items 2 and 7).

- evidence round-trips through the FakeApiServer as a node annotation;
- a statefile tampered after the flip is detected;
- a node whose state label lies (label says one mode, evidence attests
  another — the crashed-after-labeling window) is flagged fleet-wide;
- HMAC keys make evidence unforgeable without the key.
"""

import json


from tpu_cc_manager import labels as L
from tpu_cc_manager.config import AgentConfig
from tpu_cc_manager.agent import CCManagerAgent
from tpu_cc_manager.device.fake import FakeBackend, FakeChip
from tpu_cc_manager.device.statefile import device_key
from tpu_cc_manager.device.tpu import SysfsTpuBackend
from tpu_cc_manager.evidence import (
    audit_evidence, build_evidence, evidence_mode, verify_evidence,
)
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node


def _sysfs_backend(tmp_path, monkeypatch, n=2):
    sysfs = tmp_path / "sysfs"
    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(n):
        d = sysfs / f"accel{i}" / "device"
        d.mkdir(parents=True)
        (d / "vendor").write_text("0x1ae0\n")
        (d / "device").write_text("0x0063\n")
        (dev / f"accel{i}").write_text("")
    monkeypatch.setenv("TPU_CC_DEVICE_GATING", "none")
    return SysfsTpuBackend(
        sysfs_root=str(sysfs), dev_root=str(dev),
        state_dir=str(tmp_path / "state"),
    )


# ------------------------------------------------------------ document
def test_build_and_verify_roundtrip(tmp_path, monkeypatch):
    be = _sysfs_backend(tmp_path, monkeypatch)
    doc = build_evidence("n1", be, key=None)
    assert doc["node"] == "n1"
    assert len(doc["devices"]) == 2
    assert doc["statefile_digest"].startswith("sha256:")
    assert evidence_mode(doc) == "off"
    ok, reason = verify_evidence(doc, key=None, backend=be)
    assert (ok, reason) == (True, "ok")

    # any field tamper breaks the digest
    bad = dict(doc, node="other")
    assert verify_evidence(bad, key=None) == (False, "digest_mismatch")


def test_tampered_statefile_detected(tmp_path, monkeypatch):
    be = _sysfs_backend(tmp_path, monkeypatch)
    chips, _ = be.find_tpus()
    be.store.stage(chips[0].path, "cc", "on")
    be.store.commit(chips[0].path)
    doc = build_evidence("n1", be, key=None)
    assert evidence_mode(doc) == "mixed"  # one on, one off
    assert verify_evidence(doc, key=None, backend=be)[0] is True

    # attacker rewrites the statefile after evidence was published
    eff = tmp_path / "state" / device_key(chips[0].path) / "cc.effective"
    eff.write_text("off\n")
    ok, reason = verify_evidence(doc, key=None, backend=be)
    assert (ok, reason) == (False, "statefile_mismatch")


def test_hmac_key_required_to_forge(tmp_path, monkeypatch):
    be = _sysfs_backend(tmp_path, monkeypatch)
    doc = build_evidence("n1", be, key=b"pool-secret")
    assert doc["digest"].startswith("hmac-sha256:")
    assert verify_evidence(doc, key=b"pool-secret")[0] is True
    assert verify_evidence(doc, key=b"wrong") == (False, "digest_mismatch")
    assert verify_evidence(doc, key=None) == (False, "no_key")

    # a forger without the key can only produce plain-sha256 documents,
    # which a keyed verifier rejects outright (no downgrade path)
    forged = build_evidence("n1", be, key=None)
    assert verify_evidence(forged, key=b"pool-secret") == (
        False, "unsigned",
    )


def test_evidence_mode_summary():
    def doc(devs):
        return {"devices": devs}

    assert evidence_mode(doc([])) is None
    assert evidence_mode(doc([{"cc": "on", "ici": "off"}])) == "on"
    assert evidence_mode(doc([{"cc": "off", "ici": "on"}])) == "ici"
    assert evidence_mode(
        doc([{"cc": "on", "ici": "off"}, {"cc": "off", "ici": "off"}])
    ) == "mixed"
    # a HALF-flipped ici node is mixed, not protected
    assert evidence_mode(
        doc([{"cc": "off", "ici": "on"}, {"cc": "off", "ici": "off"}])
    ) == "mixed"
    # switch entries (no cc domain) must not poison the cc summary
    assert evidence_mode(
        doc([{"cc": "on", "ici": "off"}, {"cc": None, "ici": "off"}])
    ) == "on"


def test_switch_bearing_node_not_mixed(tmp_path, monkeypatch):
    """An ICI switch has no cc domain; its evidence entry must not make
    a healthy cc=on node read as 'mixed' (the false-alarm class)."""
    be = _sysfs_backend(tmp_path, monkeypatch, n=1)
    # add a switch device to the sysfs tree
    d = tmp_path / "sysfs" / "sw0" / "device"
    d.mkdir(parents=True)
    (d / "vendor").write_text("0x1ae0\n")
    (d / "device").write_text("0x00ff\n")
    (d / "kind").write_text("ici-switch\n")
    (tmp_path / "dev" / "sw0").write_text("")
    chips, _ = be.find_tpus()
    be.store.stage(chips[0].path, "cc", "on")
    be.store.commit(chips[0].path)
    doc = build_evidence("n1", be, key=None)
    assert len(doc["devices"]) == 2
    sw = next(d for d in doc["devices"] if d["name"] == "ici-switch")
    assert sw["cc"] is None
    assert evidence_mode(doc) == "on"


def test_audit_survives_hostile_annotations(tmp_path, monkeypatch):
    """Malformed evidence content must count as invalid, never crash the
    fleet scan."""
    hostile = [
        '{"digest": 1}',                       # non-string digest
        'not json at all',
        '[]',                                  # not a dict
        json.dumps({"digest": "sha256:" + "0" * 64, "devices": "xyz"}),
    ]
    nodes = []
    for i, raw in enumerate(hostile):
        nodes.append(make_node(
            f"h{i}",
            labels={L.CC_MODE_STATE_LABEL: "on",
                    L.TPU_ACCELERATOR_LABEL: "v5p"},
            annotations={L.EVIDENCE_ANNOTATION: raw},
        ))
    audit = audit_evidence(nodes, key=None)
    assert audit["invalid"] == ["h0", "h1", "h2", "h3"]
    assert audit["missing"] == []


# ------------------------------------------------- agent publication
def test_agent_publishes_evidence_through_apiserver(tmp_path, monkeypatch):
    """End-to-end: the agent reconciles against the real-wire fake API
    server, and the evidence annotation round-trips (read back + verify)."""
    from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig

    be = _sysfs_backend(tmp_path, monkeypatch)
    server = FakeApiServer().start()
    try:
        server.store.add_node(make_node("ev-node"))
        kube = HttpKubeClient(
            KubeConfig("127.0.0.1", server.port, use_tls=False)
        )
        cfg = AgentConfig(node_name="ev-node", drain_strategy="none",
                          health_port=0, emit_events=False)
        agent = CCManagerAgent(kube, cfg, backend=be)
        assert agent.reconcile("on") is True
        # evidence rides the async recorder worker (like Events)
        assert agent.flush_events(timeout=10)
        node = server.store.get_node("ev-node")
        raw = node["metadata"]["annotations"][L.EVIDENCE_ANNOTATION]
        doc = json.loads(raw)
        assert verify_evidence(doc, key=None, backend=be) == (True, "ok")
        assert evidence_mode(doc) == "on"
        assert node["metadata"]["labels"][L.CC_MODE_STATE_LABEL] == "on"
    finally:
        server.stop()


def test_failed_reconcile_publishes_no_evidence(tmp_path):
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    chip = FakeChip(path=str(tmp_path / "accel0"))
    chip.fail_reset = True
    cfg = AgentConfig(node_name="n1", drain_strategy="none",
                      health_port=0, emit_events=False)
    agent = CCManagerAgent(kube, cfg, backend=FakeBackend(chips=[chip]))
    assert agent.reconcile("on") is False
    ann = kube.get_node("n1")["metadata"].get("annotations", {})
    assert L.EVIDENCE_ANNOTATION not in ann


# ------------------------------------------------------- fleet audit
def _evidenced_node(name, state, backend, key=None, mode_override=None):
    doc = build_evidence(name, backend, key=key)
    if mode_override is not None:
        for d in doc["devices"]:
            d["cc"] = mode_override
        # re-digest so the doc itself is internally valid
        doc = {k: v for k, v in doc.items() if k != "digest"}
        from tpu_cc_manager.evidence import _canonical, _digest
        doc["digest"] = _digest(_canonical(doc), key)
    return make_node(
        name,
        labels={L.CC_MODE_STATE_LABEL: state,
                L.TPU_ACCELERATOR_LABEL: "v5p"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(
            doc, sort_keys=True, separators=(",", ":"))},
    )


def test_fleet_audit_flags_lying_missing_and_tampered(tmp_path, monkeypatch):
    be = _sysfs_backend(tmp_path, monkeypatch)
    # truthful node: label off, evidence off
    honest = _evidenced_node("honest", "off", be)
    # lying node: label claims on, device evidence says off — the
    # crashed-after-labeling window the VERDICT describes
    liar = _evidenced_node("liar", "on", be)
    # missing evidence under a success label
    bare = make_node("bare", labels={L.CC_MODE_STATE_LABEL: "on",
                                     L.TPU_ACCELERATOR_LABEL: "v5p"})
    # tampered evidence (digest broken)
    tampered = _evidenced_node("tampered", "off", be)
    ann = json.loads(
        tampered["metadata"]["annotations"][L.EVIDENCE_ANNOTATION])
    ann["node"] = "someone-else"
    tampered["metadata"]["annotations"][L.EVIDENCE_ANNOTATION] = (
        json.dumps(ann))
    # failed node: exempt (no successful claim to audit)
    failed = make_node("failed", labels={L.CC_MODE_STATE_LABEL: "failed",
                                         L.TPU_ACCELERATOR_LABEL: "v5p"})

    audit = audit_evidence([honest, liar, bare, tampered, failed], key=None)
    assert {k: v for k, v in audit.items() if v} == {
        "missing": ["bare"],
        "invalid": ["tampered"],
        "label_device_mismatch": ["liar"],
    }


def test_fleet_controller_report_carries_audit(tmp_path, monkeypatch):
    import urllib.request

    from tpu_cc_manager.fleet import FleetController

    be = _sysfs_backend(tmp_path, monkeypatch)
    kube = FakeKube()
    kube.add_node(_evidenced_node("liar", "on", be))
    ctrl = FleetController(kube, interval_s=60, port=0)
    ctrl._server.start()
    try:
        # scan 1: the mismatch is TRANSIENT (the debounce tolerates the
        # coalescing publish core's label-before-evidence skew window);
        # scan 2 confirms it as the real lying-label finding
        ctrl.scan_once()
        first = ctrl.last_report["evidence_audit"]
        assert first["label_device_mismatch"] == []
        assert first["label_device_mismatch_transient"] == ["liar"]
        ctrl.scan_once()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ctrl.port}/report") as r:
            report = json.loads(r.read())
        assert report["evidence_audit"]["label_device_mismatch"] == ["liar"]
        assert report["evidence_audit"]["label_device_mismatch_transient"] == []
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ctrl.port}/metrics") as r:
            metrics = r.read().decode()
        assert ('tpu_cc_fleet_evidence_issues'
                '{issue="label_device_mismatch"} 1') in metrics
    finally:
        ctrl.stop()


def test_dropped_evidence_publish_retried_from_idle_tick(tmp_path,
                                                         monkeypatch):
    """A failed async evidence write must not leave stale evidence on
    the cluster until the next label change (which may never come): the
    idle tick republishes."""
    be = _sysfs_backend(tmp_path, monkeypatch, n=1)
    kube = FakeKube()
    kube.add_node(make_node("rt-node"))
    cfg = AgentConfig(node_name="rt-node", drain_strategy="none",
                      health_port=0, emit_events=False)
    agent = CCManagerAgent(kube, cfg, backend=be)

    real_set = kube.set_node_annotations
    fail = {"on": True}

    def flaky_set(name, ann):
        if fail["on"] and L.EVIDENCE_ANNOTATION in ann:
            raise RuntimeError("annotation write blip")
        return real_set(name, ann)

    kube.set_node_annotations = flaky_set
    assert agent.reconcile("on") is True
    assert agent.flush_events(timeout=10)
    ann = kube.get_node("rt-node")["metadata"].get("annotations", {})
    assert L.EVIDENCE_ANNOTATION not in ann  # the write failed
    assert agent._evidence_published_gen < agent._evidence_wanted_gen

    fail["on"] = False
    agent._maybe_repair()  # idle tick
    assert agent.flush_events(timeout=10)
    ann = kube.get_node("rt-node")["metadata"]["annotations"]
    doc = json.loads(ann[L.EVIDENCE_ANNOTATION])
    assert verify_evidence(doc, key=None) == (True, "ok")
    assert evidence_mode(doc) == "on"
    assert agent._evidence_published_gen == agent._evidence_wanted_gen
    # retry is throttled: the next tick doesn't republish
    due = agent._evidence_retry_due
    agent._maybe_repair()
    assert agent._evidence_retry_due == due


def test_audit_distinguishes_unsigned_from_invalid(tmp_path, monkeypatch):
    """A keyed auditor must separate two very different findings:
    'unsigned' (internally-consistent plain-sha256 doc — almost always
    the agent DaemonSet missing the key Secret, a DEPLOYMENT fix) from
    'invalid' (digest mismatch / replay / garbage — a node to distrust).
    The fleet problem line for unsigned names the manifest fix."""
    from tpu_cc_manager.fleet import fleet_problems

    be = _sysfs_backend(tmp_path, monkeypatch)
    unsigned = build_evidence("n-unsigned", be, key=None)
    tampered = dict(build_evidence("n-bad", be, key=b"pool-key"),
                    node="someone-else")

    nodes = [
        make_node("n-unsigned", labels={
            L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: "off"},
            annotations={L.EVIDENCE_ANNOTATION: json.dumps(unsigned)}),
        make_node("n-bad", labels={
            L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: "off"},
            annotations={L.EVIDENCE_ANNOTATION: json.dumps(tampered)}),
    ]
    audit = audit_evidence(nodes, key=b"pool-key")
    assert audit["unsigned"] == ["n-unsigned"]
    assert audit["invalid"] == ["n-bad"]
    assert audit["missing"] == []

    # an attack dressed as 'unsigned' keeps its forensic class: a plain
    # doc with a broken digest, and a replayed plain doc bound to a
    # different node, are both 'invalid' — never the fix-the-manifest
    # bucket
    broken = dict(unsigned, statefile_digest="sha256:beef")
    replayed = build_evidence("elsewhere", be, key=None)
    forged_nodes = [
        make_node("n-broken", labels={
            L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: "off"},
            annotations={L.EVIDENCE_ANNOTATION: json.dumps(broken)}),
        make_node("n-replay", labels={
            L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: "off"},
            annotations={L.EVIDENCE_ANNOTATION: json.dumps(replayed)}),
    ]
    audit_forged = audit_evidence(forged_nodes, key=b"pool-key")
    assert audit_forged["invalid"] == ["n-broken", "n-replay"]
    assert audit_forged["unsigned"] == []

    # a LYING label on an unkeyed node is still the lie this audit
    # exists to catch: unsigned-but-consistent evidence contradicting
    # the state label lands in label_device_mismatch, never in the
    # benign fix-the-manifest bucket
    lying = [make_node("n-unsigned", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: "on"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(unsigned)})]
    audit_lying = audit_evidence(lying, key=b"pool-key")
    assert audit_lying["label_device_mismatch"] == ["n-unsigned"]
    assert audit_lying["unsigned"] == []

    # agents-first enablement window: signed docs under an UNKEYED
    # auditor are 'unverifiable' (close the blind spot by keying the
    # controller), never 'invalid' — the whole fleet must not page
    # mid-enablement
    signed = build_evidence("n-signed", be, key=b"pool-key")
    signed_nodes = [make_node("n-signed", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(signed)})]
    audit_nokey = audit_evidence(signed_nodes, key=None)
    assert audit_nokey["unverifiable"] == ["n-signed"]
    assert audit_nokey["invalid"] == []
    from tpu_cc_manager.fleet import fleet_problems as _fp
    assert _fp({"evidence_audit": audit_nokey}) == []

    # ...but 'unverifiable' never launders keyless-checkable problems:
    # a signed doc replayed to another node is invalid, and a signed
    # doc whose attested mode contradicts the label is a mismatch —
    # node binding and mode claims need no key to read
    nokey_bad = [
        make_node("n-other", labels={
            L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: "off"},
            annotations={L.EVIDENCE_ANNOTATION: json.dumps(signed)}),
        make_node("n-signed", labels={
            L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: "on"},
            annotations={L.EVIDENCE_ANNOTATION: json.dumps(signed)}),
    ]
    audit_nokey2 = audit_evidence(nokey_bad, key=None)
    assert audit_nokey2["invalid"] == ["n-other"]
    assert audit_nokey2["label_device_mismatch"] == ["n-signed"]
    assert audit_nokey2["unverifiable"] == []

    problems = fleet_problems({"evidence_audit": audit})
    unsigned_lines = [p for p in problems if "unsigned" in p]
    assert len(unsigned_lines) == 1
    # actionable: names the Secret and the enablement order
    assert "tpu-cc-evidence-key" in unsigned_lines[0]
    assert "BEFORE" in unsigned_lines[0]
    assert any("invalid" in p for p in problems)

    # an UNKEYED auditor sees the same unsigned doc as simply valid —
    # the bucket only exists once a key is deployed
    audit2 = audit_evidence(nodes, key=None)
    assert audit2["unsigned"] == []


def test_key_appearing_on_idle_node_resigns_evidence(tmp_path,
                                                     monkeypatch):
    """The agents-first enablement path on an ALREADY-CONVERGED fleet:
    when the evidence-key Secret lands (kubelet populates the optional
    mount in place), no mode flip will ever come to re-sign the stale
    unsigned annotation — the idle tick must notice the key-posture
    change and republish, or a keyed verifier reads the whole idle
    fleet as 'unsigned' and tells the operator to apply the fix they
    already applied."""
    be = _sysfs_backend(tmp_path, monkeypatch, n=1)
    kube = FakeKube()
    kube.add_node(make_node("idle-node"))
    key_file = tmp_path / "evidence-key"
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY_FILE", str(key_file))
    cfg = AgentConfig(node_name="idle-node", drain_strategy="none",
                      health_port=0, emit_events=True)
    agent = CCManagerAgent(kube, cfg, backend=be)

    # converge while the Secret is absent: evidence is plain-sha256
    assert agent.reconcile("on") is True
    assert agent.flush_events(timeout=10)
    ann = kube.get_node("idle-node")["metadata"]["annotations"]
    assert json.loads(ann[L.EVIDENCE_ANNOTATION])["digest"].startswith(
        "sha256:"
    )

    # idle ticks with no posture change do NOT republish
    before = ann[L.EVIDENCE_ANNOTATION]
    agent._maybe_repair()
    assert agent.flush_events(timeout=10)
    assert (kube.get_node("idle-node")["metadata"]["annotations"]
            [L.EVIDENCE_ANNOTATION]) == before

    # the Secret appears in place; next idle tick re-signs (the check
    # itself is throttled to the repair cadence — force it due)
    key_file.write_bytes(b"pool-secret")
    agent._evidence_key_check_due = 0.0
    agent._maybe_repair()
    assert agent.flush_events(timeout=10)
    doc = json.loads(kube.get_node("idle-node")["metadata"]
                     ["annotations"][L.EVIDENCE_ANNOTATION])
    assert doc["digest"].startswith("hmac-sha256:")
    assert verify_evidence(doc, key=b"pool-secret") == (True, "ok")
    # keyed audit now sees a clean fleet
    audit = audit_evidence(kube.list_nodes(None), key=b"pool-secret")
    assert audit["unsigned"] == [] and audit["invalid"] == []
    # ...and the re-sign is fleet-visible as a node Event, so rotation
    # progress shows in `kubectl get events` while stale_key drains
    reasons = [e["reason"] for e in kube.list_events("default")]
    assert "CCEvidenceResigned" in reasons


def test_sync_evidence_heals_posture_and_staleness(tmp_path,
                                                   monkeypatch):
    """The native-path idle-tick healer (`evidence --sync`): republish
    ONLY when the on-cluster doc is out of sync — missing, unsigned
    under a new key, stale device truth — and no-op otherwise."""
    from tpu_cc_manager.evidence import sync_evidence

    be = _sysfs_backend(tmp_path, monkeypatch, n=1)
    kube = FakeKube()
    kube.add_node(make_node("s-node"))
    writes = []
    real_set = kube.set_node_annotations

    def counting_set(name, ann):
        writes.append(name)
        return real_set(name, ann)

    kube.set_node_annotations = counting_set

    # missing annotation: published
    assert sync_evidence(kube, "s-node", backend=be)
    assert len(writes) == 1
    # in sync: no write
    assert sync_evidence(kube, "s-node", backend=be)
    assert len(writes) == 1
    # the evidence-key Secret lands: posture changed -> re-signed
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool-key")
    assert sync_evidence(kube, "s-node", backend=be)
    assert len(writes) == 2
    doc = json.loads(kube.get_node("s-node")["metadata"]["annotations"]
                     [L.EVIDENCE_ANNOTATION])
    assert doc["digest"].startswith("hmac-sha256:")
    assert sync_evidence(kube, "s-node", backend=be)  # now in sync
    assert len(writes) == 2
    # device truth moves without a flip: healed
    chips, _ = be.find_tpus()
    be.store.stage(chips[0].path, "cc", "on")
    be.store.commit(chips[0].path)
    assert sync_evidence(kube, "s-node", backend=be)
    assert len(writes) == 3
    doc = json.loads(kube.get_node("s-node")["metadata"]["annotations"]
                     [L.EVIDENCE_ANNOTATION])
    assert evidence_mode(doc) == "on"


def test_sync_evidence_refreshes_aging_identity(tmp_path, monkeypatch):
    from tpu_cc_manager.evidence import evidence_in_sync

    monkeypatch.setenv("TPU_CC_IDENTITY", "fake")
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", "ik")
    be = _sysfs_backend(tmp_path, monkeypatch, n=1)
    fresh = build_evidence("n", be)
    assert fresh["identity"]["provider"] == "fake"
    assert evidence_in_sync(fresh, fresh)
    # an on-cluster doc whose token is inside its last 20% of life is
    # out of sync even though nothing else changed
    from tpu_cc_manager.identity import mint_fake_token
    import time as _time

    aging = dict(fresh, identity={
        "provider": "fake",
        "token": mint_fake_token("n", b"ik",
                                 now=_time.time() - 3300, ttl_s=3600)})
    assert not evidence_in_sync(aging, fresh)


def test_key_rotation_tail_is_stale_not_attack(tmp_path, monkeypatch):
    """Rotating the evidence-key Secret to ``<new>\\n<old>`` must never
    read as an attack: verifiers accept the rotation-tail signature,
    the fleet audit buckets still-old signatures as ``stale_key`` (not
    invalid), the sync healer re-signs with the new primary, and the
    bucket empties — the operator's cue to drop the old line."""
    from tpu_cc_manager.evidence import (
        evidence_key, evidence_keys, signed_with_primary, sync_evidence,
    )

    be = _sysfs_backend(tmp_path, monkeypatch, n=1)
    kube = FakeKube()
    kube.add_node(make_node(
        "rot-node", labels={L.CC_MODE_STATE_LABEL: "off"},
    ))
    key_file = tmp_path / "evkey"
    old_keys_file = tmp_path / "old-keys"
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY_FILE", str(key_file))
    monkeypatch.setenv("TPU_CC_EVIDENCE_OLD_KEYS_FILE",
                       str(old_keys_file))

    key_file.write_bytes(b"old-key\n")
    assert evidence_keys() == (b"old-key",)
    assert sync_evidence(kube, "rot-node", backend=be)
    old_doc = json.loads(kube.get_node("rot-node")["metadata"]
                         ["annotations"][L.EVIDENCE_ANNOTATION])
    assert signed_with_primary(old_doc)

    # rotate: the new key signs, the old key moves to the verify-only
    # old-keys entry of the same Secret
    key_file.write_bytes(b"new-key\n")
    old_keys_file.write_bytes(b"old-key\n")
    assert evidence_keys() == (b"new-key", b"old-key")
    assert evidence_key() == b"new-key"
    # the fleet's still-old signature verifies (NOT digest_mismatch)...
    assert verify_evidence(old_doc) == (True, "ok")
    # ...but is recognisably not fresh, and a key outside the set fails
    assert not signed_with_primary(old_doc)
    assert verify_evidence(old_doc, key=b"other") == (
        False, "digest_mismatch",
    )
    # audit: rotation-in-progress, not forgery
    audit = audit_evidence(kube.list_nodes(None))
    assert audit["stale_key"] == ["rot-node"]
    assert audit["invalid"] == []

    # the healer treats tail-signed as out of sync and re-signs
    assert sync_evidence(kube, "rot-node", backend=be)
    doc = json.loads(kube.get_node("rot-node")["metadata"]
                     ["annotations"][L.EVIDENCE_ANNOTATION])
    assert signed_with_primary(doc)
    audit = audit_evidence(kube.list_nodes(None))
    assert audit["stale_key"] == [] and audit["invalid"] == []

    # rotation complete: the old-keys entry goes, everything verifies
    old_keys_file.unlink()
    assert evidence_keys() == (b"new-key",)
    assert verify_evidence(doc) == (True, "ok")
    assert sync_evidence(kube, "rot-node", backend=be)  # in-sync no-op
    assert (kube.get_node("rot-node")["metadata"]["annotations"]
            [L.EVIDENCE_ANNOTATION]) == json.dumps(
        doc, sort_keys=True, separators=(",", ":"))


def test_newline_bearing_primary_key_keeps_whole_file_semantics(
        tmp_path, monkeypatch):
    """The primary key file is the WHOLE stripped content — exactly
    the pre-rotation reader's semantics. A raw-random-bytes Secret
    containing 0x0A must neither change meaning on upgrade (rejecting
    the fleet's signatures) nor silently truncate to its first line (a
    few-byte HMAC key would be offline-brute-forceable). Rotation
    state lives in the SEPARATE old-keys file, which is line-split."""
    from tpu_cc_manager.evidence import (
        evidence_key, evidence_keys, signed_with_primary,
    )

    be = _sysfs_backend(tmp_path, monkeypatch, n=1)
    legacy = b"rand\nom-bytes"
    doc = build_evidence("n1", be, key=legacy)  # signed pre-upgrade

    key_file = tmp_path / "evkey"
    key_file.write_bytes(legacy)
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY_FILE", str(key_file))
    assert evidence_keys() == (legacy,)
    assert evidence_key() == legacy
    assert verify_evidence(doc) == (True, "ok")
    assert signed_with_primary(doc)  # nothing to re-sign on upgrade

    # retired keys ride the old-keys file; an absent/empty file and
    # duplicate-of-primary lines are no-ops
    old_keys = tmp_path / "old-keys"
    monkeypatch.setenv("TPU_CC_EVIDENCE_OLD_KEYS_FILE", str(old_keys))
    assert evidence_keys() == (legacy,)
    old_keys.write_bytes(b"retired-1\n\nretired-2\n")
    assert evidence_keys() == (legacy, b"retired-1", b"retired-2")
    retired_doc = build_evidence("n1", be, key=b"retired-1")
    assert verify_evidence(retired_doc) == (True, "ok")
    assert not signed_with_primary(retired_doc)

    # old keys WITHOUT a primary must not make this process a keyed
    # verifier (it would refuse an unkeyed fleet's plain documents)
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY_FILE",
                       str(tmp_path / "absent"))
    assert evidence_keys() == ()
    plain = build_evidence("n1", be, key=None)
    assert verify_evidence(plain) == (True, "ok")


def test_sync_evidence_heals_key_rotation_and_keeps_identity_on_blip(
        tmp_path, monkeypatch):
    from tpu_cc_manager.evidence import evidence_in_sync, sync_evidence

    be = _sysfs_backend(tmp_path, monkeypatch, n=1)
    kube = FakeKube()
    kube.add_node(make_node("r-node"))
    # signed with the OLD key
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "old-key")
    assert sync_evidence(kube, "r-node", backend=be)
    # key ROTATES: same scheme, different key -> out of sync, healed
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "new-key")
    assert sync_evidence(kube, "r-node", backend=be)
    doc = json.loads(kube.get_node("r-node")["metadata"]["annotations"]
                     [L.EVIDENCE_ANNOTATION])
    assert verify_evidence(doc, key=b"new-key")[0] is True

    # a fresh build that LOST identity (metadata blip) must not strip a
    # still-valid token from the on-cluster doc (docs built properly so
    # their digests cover the identity field)
    from tpu_cc_manager.identity import (
        FakePlatformIdentity, mint_fake_token,
    )

    cur = build_evidence("r-node", be,
                         identity_provider=FakePlatformIdentity(b"ik"))
    fresh_no_ident = build_evidence("r-node", be, identity_provider=None)
    assert evidence_in_sync(cur, fresh_no_ident) is True

    # a token with exp but NO iat must not read as perpetually aging
    # (that would republish every tick forever)
    import base64 as _b64

    tok = mint_fake_token("r-node", b"ik", ttl_s=3600)
    h, p, s = tok.split(".")
    claims = json.loads(_b64.urlsafe_b64decode(p + "=="))
    del claims["iat"]
    p2 = _b64.urlsafe_b64encode(
        json.dumps(claims, sort_keys=True).encode()
    ).rstrip(b"=").decode()
    no_iat = ".".join([h, p2, s])

    class NoIatProvider:
        provider = "fake"

        def token(self, node_name, audience=None):
            return no_iat

    cur2 = build_evidence("r-node", be,
                          identity_provider=NoIatProvider())
    assert evidence_in_sync(cur2, cur2) is True
