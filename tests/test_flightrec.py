"""Flight recorder (ISSUE 8): the bounded per-process black box.

Pins the dump schema (spans + events + host-contention samples +
metrics snapshot), the three dump triggers (reconcile failure, SIGTERM,
on-demand GET), the dump throttle, and the fleet-wide stitch-by-trace
primitive simlab's timeline artifact builds on.
"""

import json
import os
import signal
import subprocess
import sys
import urllib.request

from tpu_cc_manager.device.fake import fake_backend
from tpu_cc_manager.flightrec import (
    FlightRecorder, get_recorder, install_sigterm_dump, sample_host,
    set_recorder, stitch_by_trace,
)
from tpu_cc_manager.trace import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the pinned dump/snapshot schema — a breaking change here must bump
#: flightrec.SCHEMA_VERSION and update docs/observability.md
SCHEMA_KEYS = {
    "flightrec_version", "reason", "at", "name",
    "spans", "events", "host_samples", "metrics",
}

#: a recorder wired with a time-series ring (the agent's, ISSUE 9)
#: additionally embeds the windowed metric history — an optional
#: section, so v1 readers keep working and bare recorders keep the
#: historical shape
AGENT_SCHEMA_KEYS = SCHEMA_KEYS | {"timeseries"}


def test_sample_host_reads_proc():
    s = sample_host()
    assert s["at"] > 0
    if not s.get("unavailable"):  # Linux CI/sandbox
        assert s["load1"] >= 0.0
        assert s["cpu_total_jiffies"] >= s["cpu_idle_jiffies"] >= 0
        assert s["self_utime_jiffies"] >= 0
        assert s["mem_available_kb"] > 0


def test_rings_bounded_and_snapshot_schema():
    rec = FlightRecorder(name="n1", span_ring=4, event_ring=3,
                         sample_ring=2)
    tr = Tracer()
    tr.add_sink(rec.observe_span)
    for i in range(10):
        with tr.span("reconcile", i=i):
            pass
        rec.note("tick", i=i)
        rec.sample("idle")
    doc = rec.snapshot("inspect")
    assert set(doc) == SCHEMA_KEYS
    assert doc["flightrec_version"] == 1
    assert doc["name"] == "n1"
    assert len(doc["spans"]) == 4  # ring, not archive
    assert doc["spans"][-1]["attrs"]["i"] == 9  # newest retained
    assert len(doc["events"]) == 3
    assert len(doc["host_samples"]) == 2
    assert doc["metrics"] is None  # none wired
    # snapshot is JSON-able as-is (the dump body contract)
    json.dumps(doc)


def test_bracket_takes_pre_and_post_samples():
    rec = FlightRecorder()
    with rec.bracket("flip:/dev/accel0"):
        pass
    tags = [s["tag"] for s in rec.snapshot()["host_samples"]]
    assert tags == ["flip:/dev/accel0:pre", "flip:/dev/accel0:post"]


def test_dump_writes_whole_artifact_and_throttles(tmp_path):
    rec = FlightRecorder(name="n1", dump_dir=str(tmp_path),
                         min_dump_interval_s=3600.0,
                         metrics=lambda: {"k": 1})
    rec.note("boom", why="test")
    path = rec.maybe_dump("reconcile_failure")
    assert path is not None and os.path.exists(path)
    assert "reconcile_failure" in os.path.basename(path)
    doc = json.loads(open(path).read())
    assert set(doc) == SCHEMA_KEYS
    assert doc["reason"] == "reconcile_failure"
    assert doc["metrics"] == {"k": 1}
    assert doc["events"][-1]["kind"] == "boom"
    # no torn half-dump left behind
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    # second failure inside the throttle window: no second dump
    assert rec.maybe_dump("reconcile_failure") is None
    assert rec.dumps_total == 1
    # an explicit dump (SIGTERM, operator) bypasses the throttle
    assert rec.dump("sigterm") is not None
    assert rec.dumps_total == 2


def test_dump_without_dir_is_a_noop():
    rec = FlightRecorder(name="n1")  # no dump_dir, no env
    assert rec.dump_dir is None or isinstance(rec.dump_dir, str)
    rec.dump_dir = None
    assert rec.dump("sigterm") is None


def test_metrics_snapshot_uses_render():
    class Ms:
        def render(self):
            return "# HELP x y\n"

    rec = FlightRecorder(metrics=Ms())
    assert rec.snapshot()["metrics"] == {"exposition": "# HELP x y\n"}


def test_sigterm_dump_chains_previous_handler(tmp_path):
    rec = FlightRecorder(name="n1", dump_dir=str(tmp_path))
    rec.note("alive")
    called = []
    prev = signal.getsignal(signal.SIGTERM)
    try:
        signal.signal(signal.SIGTERM, lambda s, f: called.append(s))
        handler = install_sigterm_dump(rec)
        assert handler is not None
        handler(signal.SIGTERM, None)
    finally:
        signal.signal(signal.SIGTERM, prev)
    dumps = [f for f in os.listdir(tmp_path) if "sigterm" in f]
    assert len(dumps) == 1
    doc = json.loads(open(os.path.join(tmp_path, dumps[0])).read())
    assert doc["reason"] == "sigterm"
    # the clean-shutdown handler installed before still ran
    assert called == [signal.SIGTERM]


def test_sigterm_default_action_still_kills(tmp_path):
    """With no previous handler the process must still DIE of SIGTERM
    (exit status honest for the kubelet) — after the dump lands."""
    code = (
        "import os, signal, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from tpu_cc_manager.flightrec import (FlightRecorder,"
        " install_sigterm_dump)\n"
        f"rec = FlightRecorder(name='sub', dump_dir={str(tmp_path)!r})\n"
        "rec.note('boot')\n"
        "install_sigterm_dump(rec)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "print('UNREACHABLE')\n"
    )
    p = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == -signal.SIGTERM, (p.returncode, p.stderr)
    assert "UNREACHABLE" not in p.stdout
    assert any("sigterm" in f for f in os.listdir(tmp_path))


def test_process_recorder_swap():
    original = get_recorder()
    try:
        mine = FlightRecorder(name="mine")
        set_recorder(mine)
        assert get_recorder() is mine
    finally:
        set_recorder(original)


def test_stitch_by_trace_joins_across_recordings():
    a = {"name": "controller", "spans": [
        {"name": "desired_write", "trace": "t1", "span": "c1",
         "start_ts": 1.0, "dur_s": 0.1},
    ]}
    b = {"name": "node-1", "spans": [
        {"name": "reconcile", "trace": "t1", "span": "r1",
         "parent": "c1", "start_ts": 1.5, "dur_s": 0.2},
        {"name": "reconcile", "trace": "local", "span": "r2",
         "start_ts": 0.5, "dur_s": 0.1},
        {"name": "junk"},  # no trace id: dropped, not crashed
    ]}
    out = stitch_by_trace([a, b])
    assert set(out) == {"t1", "local"}
    t1 = out["t1"]
    assert [s["name"] for s in t1] == ["desired_write", "reconcile"]
    assert [s["recorder"] for s in t1] == ["controller", "node-1"]


def test_engine_brackets_flips_with_host_samples():
    rec = FlightRecorder()
    from tpu_cc_manager.engine import ModeEngine, NullDrainer

    engine = ModeEngine(
        set_state_label=lambda v: None,
        drainer=NullDrainer(),
        evict_components=False,
        backend=fake_backend(n_chips=2),
        recorder=rec,
    )
    assert engine.set_mode("on")
    tags = [s["tag"] for s in rec.snapshot()["host_samples"]]
    pres = [t for t in tags if t.endswith(":pre")]
    posts = [t for t in tags if t.endswith(":post")]
    assert len(pres) == 2 and len(posts) == 2  # one bracket per chip


def test_failed_flip_items_noted():
    rec = FlightRecorder()
    backend = fake_backend(n_chips=2)
    backend.chips[0].fail_reset = True
    from tpu_cc_manager.engine import ModeEngine, NullDrainer

    engine = ModeEngine(
        set_state_label=lambda v: None,
        drainer=NullDrainer(),
        evict_components=False,
        backend=backend,
        recorder=rec,
        flip_concurrency=1,  # serial: deterministic fail-stop skips
    )
    assert engine.set_mode("on") is False
    flips = [e for e in rec.snapshot()["events"]
             if e["kind"] == "flip_item"]
    assert {e["status"] for e in flips} == {"failed", "skipped"}
    failed = next(e for e in flips if e["status"] == "failed")
    assert failed["device"] == "/dev/accel0"
    assert "reset failed" in failed["error"]


def _agent(tmp_path, backend, annotations=None, labels=None):
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig
    from tpu_cc_manager.k8s.fake import FakeKube
    from tpu_cc_manager.k8s.objects import make_node

    kube = FakeKube()
    kube.add_node(make_node("n1", labels=labels, annotations=annotations))
    cfg = AgentConfig(
        node_name="n1", drain_strategy="none", health_port=0,
        readiness_file=str(tmp_path / "ready"),
        flightrec_dir=str(tmp_path / "flightrec"),
    )
    return CCManagerAgent(kube, cfg, backend=backend)


def test_reconcile_failure_dumps_black_box(tmp_path):
    backend = fake_backend(n_chips=1)
    backend.chips[0].fail_reset = True
    agent = _agent(tmp_path, backend)
    assert agent.reconcile("on") is False
    dumps = os.listdir(tmp_path / "flightrec")
    assert len(dumps) == 1 and "reconcile_failure" in dumps[0]
    doc = json.loads(open(tmp_path / "flightrec" / dumps[0]).read())
    assert set(doc) == AGENT_SCHEMA_KEYS
    assert doc["name"] == "n1"
    # spans: the failed flip is in the ring with its error, and —
    # because the dump runs AFTER the span context closes — so is the
    # root reconcile span of the very failure being documented
    flip = next(s for s in doc["spans"] if s["name"] == "flip")
    assert flip["status"] == "error"
    root = next(s for s in doc["spans"] if s["name"] == "reconcile")
    assert root["attrs"]["outcome"] == "failure"
    assert root["dur_s"] > 0
    # host samples bracket the flip window (ROADMAP item 1's sensor)
    tags = [s["tag"] for s in doc["host_samples"]]
    assert any(t.endswith(":pre") for t in tags)
    assert any(t.endswith(":post") for t in tags)
    # events: the reconcile outcome landed before the dump
    rec_events = [e for e in doc["events"] if e["kind"] == "reconcile"]
    assert rec_events and rec_events[-1]["outcome"] == "failure"
    # metrics snapshot is the agent's full exposition
    assert "tpu_cc_reconciles_total" in doc["metrics"]["exposition"]


def test_successful_reconcile_does_not_dump(tmp_path):
    agent = _agent(tmp_path, fake_backend(n_chips=1))
    assert agent.reconcile("on")
    assert not os.path.exists(tmp_path / "flightrec")


def test_health_server_serves_flightrec_snapshot(tmp_path):
    agent = _agent(tmp_path, fake_backend(n_chips=1))
    assert agent.reconcile("on")
    from tpu_cc_manager.obs import HealthServer

    srv = HealthServer(agent.metrics, port=0, tracer=agent.tracer,
                       flightrec=agent.flightrec).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/flightrec"
        ) as resp:
            doc = json.load(resp)
    finally:
        srv.stop()
    assert set(doc) == AGENT_SCHEMA_KEYS
    assert doc["reason"] == "debug_get"
    assert any(s["name"] == "reconcile" for s in doc["spans"])
    # the GET wrote no file — it's the live snapshot, not a dump
    assert not os.path.exists(tmp_path / "flightrec")


def test_agent_adopts_desired_write_trace(tmp_path):
    """Cross-process propagation end to end at the agent: the cc.trace
    annotation stamped by a controller rides the watched node; the
    reconcile root adopts its trace id and parents the remote span."""
    from tpu_cc_manager import labels as L

    # the restart-rejoin shape: desired label AND the writer's
    # annotation both already on the node at prime time
    agent = _agent(
        tmp_path, fake_backend(n_chips=1),
        labels={L.CC_MODE_LABEL: "on"},
        annotations={L.CC_TRACE_ANNOTATION: "00-cafe1-feed2-01"},
    )
    agent.watcher.prime()  # reads the node (and its annotation)
    assert agent.watcher.latest_trace_context() == "00-cafe1-feed2-01"
    assert agent.reconcile("on")
    root = next(s for s in agent.tracer.recent()
                if s["name"] == "reconcile")
    assert root["trace"] == "cafe1"
    assert root["parent"] == "feed2"
    # children keep nesting under the adopted root as usual
    flip = next(s for s in agent.tracer.recent() if s["name"] == "flip")
    assert flip["trace"] == "cafe1"


def test_agent_garbled_annotation_degrades_to_local_root(tmp_path):
    from tpu_cc_manager import labels as L

    agent = _agent(
        tmp_path, fake_backend(n_chips=1),
        labels={L.CC_MODE_LABEL: "on"},
        annotations={L.CC_TRACE_ANNOTATION: "not-a-traceparent"},
    )
    agent.watcher.prime()
    assert agent.reconcile("on")
    root = next(s for s in agent.tracer.recent()
                if s["name"] == "reconcile")
    assert root.get("parent") is None
