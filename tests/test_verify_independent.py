"""Non-tautological engine verify (VERDICT r2 item 5).

The engine's verify must not merely re-read the bookkeeping the flip
itself wrote (reference main.py:291-296 re-queries hardware that can
genuinely disagree). Statefile-backed chips therefore cross-read through
an independent path — the tpudevctl binary when installed, else the
other store implementation — and the flip fails if the independent
reader disagrees.
"""

import os
import shutil
import subprocess

import pytest

from tpu_cc_manager.device.statefile import (
    ModeStateStore, device_key, independent_read,
)
from tpu_cc_manager.device.tpu import SysfsTpuBackend, find_tpudevctl
from tpu_cc_manager.engine import ModeEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sysfs_env(tmp_path, monkeypatch, n=1):
    sysfs = tmp_path / "sysfs"
    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(n):
        d = sysfs / f"accel{i}" / "device"
        d.mkdir(parents=True)
        (d / "vendor").write_text("0x1ae0\n")
        (d / "device").write_text("0x0063\n")
        (dev / f"accel{i}").write_text("")
    monkeypatch.setenv("TPU_CC_DEVICE_GATING", "none")
    return SysfsTpuBackend(
        sysfs_root=str(sysfs), dev_root=str(dev),
        state_dir=str(tmp_path / "state"),
    )


def _engine(backend, states=None):
    states = states if states is not None else []
    return ModeEngine(
        set_state_label=states.append, backend=backend,
        evict_components=False,
    )


def test_statefile_tamper_between_commit_and_verify_fails_flip(
        tmp_path, monkeypatch):
    """The VERDICT-prescribed test: corrupt the statefile between commit
    and verify -> the flip must fail, not report success."""
    be = _sysfs_env(tmp_path, monkeypatch)
    chips, _ = be.find_tpus()
    chip = chips[0]
    eff_file = (tmp_path / "state" / device_key(chip.path) / "cc.effective")

    real_reset = type(chip).reset

    def tampering_reset(self):
        real_reset(self)
        eff_file.write_text("off\n")  # attacker/bug rewrites post-commit

    monkeypatch.setattr(type(chip), "reset", tampering_reset)
    states = []
    assert _engine(be, states).set_mode("on") is False
    assert states == ["failed"]


def test_lying_flip_handle_is_caught_by_independent_reader(
        tmp_path, monkeypatch):
    """The tautology proof: the flip path's OWN store handle claims the
    commit took (query returns the target) while the bytes on disk never
    changed. Plain verify — which re-reads the same handle — passes;
    only the independent cross-read (separate binary / fresh store
    instance) catches the lie. Instance-level patching, so the fresh
    reader built by independent_read stays truthful."""
    be = _sysfs_env(tmp_path, monkeypatch)
    chips, _ = be.find_tpus()
    chip = chips[0]
    store = chip._store

    def broken_commit(path):
        # the staging-bug class: commit "succeeds" in-memory only — from
        # here on this handle reports the staged value as effective
        # without ever writing the bytes
        store.effective = lambda p, d: store.staged(p, d)

    store.commit = broken_commit  # instance attr; ModeStateStore untouched
    states = []
    assert _engine(be, states).set_mode("on") is False
    assert states == ["failed"]
    # the same-handle read would have passed verify...
    assert chip.query_cc_mode() == "on"
    # ...but the disk never changed, and the independent reader knew
    del store.effective, store.commit
    assert independent_read(store, chip.path, "cc") == "off"


def test_successful_flip_passes_independent_verify(tmp_path, monkeypatch):
    be = _sysfs_env(tmp_path, monkeypatch, n=2)
    states = []
    assert _engine(be, states).set_mode("on") is True
    assert states == ["on"]
    chips, _ = be.find_tpus()
    for c in chips:
        assert c.verify_independent("cc") == "on"
        assert c.verify_independent("ici") == "off"


@pytest.fixture(scope="module")
def tpudevctl_bin():
    if shutil.which("g++") is None:
        pytest.skip("native toolchain unavailable")
    r = subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return os.path.join(REPO, "native", "build", "tpudevctl")


def test_independent_verify_uses_tpudevctl_binary(
        tmp_path, monkeypatch, tpudevctl_bin):
    """With the binary installed, the independent reader is a separate
    executable — different binary, same fcntl-locked store."""
    monkeypatch.setenv("TPUDEVCTL", tpudevctl_bin)
    be = _sysfs_env(tmp_path, monkeypatch)
    assert _engine(be).set_mode("devtools") is True
    chips, _ = be.find_tpus()
    assert chips[0].verify_independent("cc") == "devtools"
    # the subprocess really is consulted: point it at an empty state dir
    # and the reading changes while the in-process store still says
    # devtools
    monkeypatch.setenv("TPUDEVCTL", tpudevctl_bin)
    empty = tmp_path / "other-state"
    empty.mkdir()
    chip = chips[0]
    real_dir = chip._store.state_dir
    chip._store.state_dir = str(empty)
    try:
        assert chip.verify_independent("cc") == "off"
    finally:
        chip._store.state_dir = real_dir
    assert chip.query_cc_mode() == "devtools"


def test_find_tpudevctl_prefers_env(tmp_path, monkeypatch):
    fake = tmp_path / "tpudevctl"
    fake.write_text("#!/bin/sh\necho on\n")
    os.chmod(fake, 0o755)
    monkeypatch.setenv("TPUDEVCTL", str(fake))
    assert find_tpudevctl() == str(fake)
    monkeypatch.setenv("TPUDEVCTL", str(tmp_path / "missing"))
    got = find_tpudevctl()
    assert got != str(tmp_path / "missing")  # falls through, never bogus
