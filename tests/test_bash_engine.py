"""Bash mode-engine tests: drive scripts/tpu-cc-manager.sh end-to-end
against the HTTP fake API server and a synthetic sysfs tree, with device
access through the real tpudevctl binary."""

import os
import shutil
import subprocess

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.statefile import ModeStateStore
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.objects import make_node

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "tpu-cc-manager.sh")
DP = "tpu.google.com/pool.deploy.device-plugin"


@pytest.fixture(scope="module")
def tpudevctl():
    if shutil.which("g++") is None:
        pytest.skip("native toolchain unavailable")
    r = subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return os.path.join(REPO, "native", "build", "tpudevctl")


@pytest.fixture()
def env(tmp_path, tpudevctl):
    sysfs = tmp_path / "sysfs"
    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(2):
        d = sysfs / f"accel{i}" / "device"
        d.mkdir(parents=True)
        (d / "vendor").write_text("0x1ae0\n")
        (d / "device").write_text("0x0063\n")
        (dev / f"accel{i}").write_text("")
    server = FakeApiServer().start()
    server.store.add_node(make_node("bash-node", labels={DP: "true"}))
    e = dict(os.environ)
    e.update(
        NODE_NAME="bash-node",
        KUBE_API_HOST="127.0.0.1",
        KUBE_API_PORT=str(server.port),
        TPU_SYSFS_ROOT=str(sysfs),
        TPU_DEV_ROOT=str(dev),
        TPU_CC_STATE_DIR=str(tmp_path / "state"),
        TPUDEVCTL=tpudevctl,
        EVICTION_TIMEOUT_S="2",
        EVICTION_POLL_S="0.2",
        CC_READINESS_FILE=str(tmp_path / "run" / ".ready"),
    )
    e.pop("CC_CAPABLE_DEVICE_IDS", None)
    yield e, server, tmp_path
    server.stop()


def run_sh(env, *args):
    return subprocess.run(["bash", SCRIPT, *args], capture_output=True,
                          text=True, env=env, timeout=60)


def test_set_and_get_cc_mode(env):
    e, server, tmp_path = env
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 0, r.stderr
    labels = server.store.get_node("bash-node")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "on"
    assert labels[DP] == "true"  # paused then restored
    store = ModeStateStore(str(tmp_path / "state"))
    for i in range(2):
        assert store.effective(str(tmp_path / "dev" / f"accel{i}"), "cc") == "on"
    assert (tmp_path / "run" / ".ready").exists()

    r = run_sh(e, "get-cc-mode")
    assert r.returncode == 0
    lines = r.stdout.strip().splitlines()
    assert len(lines) == 2
    assert all("cc=on" in ln and "ici=off" in ln for ln in lines)


def test_idempotent_fast_path(env):
    e, server, _ = env
    assert run_sh(e, "set-cc-mode", "-a", "-m", "devtools").returncode == 0
    r = run_sh(e, "set-cc-mode", "-a", "-m", "devtools")
    assert r.returncode == 0
    assert "already in mode" in r.stderr


def test_invalid_mode_rejected(env):
    e, _, _ = env
    r = run_sh(e, "set-cc-mode", "-a", "-m", "bogus")
    assert r.returncode == 1
    assert "invalid mode" in r.stderr


def test_ici_mode_and_off(env):
    e, server, tmp_path = env
    assert run_sh(e, "set-cc-mode", "-a", "-m", "ici").returncode == 0
    store = ModeStateStore(str(tmp_path / "state"))
    dev0 = str(tmp_path / "dev" / "accel0")
    assert store.effective(dev0, "ici") == "on"
    assert store.effective(dev0, "cc") == "off"
    assert run_sh(e, "set-cc-mode", "-a", "-m", "off").returncode == 0
    assert store.effective(dev0, "ici") == "off"
    labels = server.store.get_node("bash-node")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "off"


def test_single_device_scope(env):
    e, _, tmp_path = env
    dev1 = str(tmp_path / "dev" / "accel1")
    r = run_sh(e, "set-cc-mode", "-d", dev1, "-m", "on")
    assert r.returncode == 0, r.stderr
    store = ModeStateStore(str(tmp_path / "state"))
    assert store.effective(dev1, "cc") == "on"
    assert store.effective(str(tmp_path / "dev" / "accel0"), "cc") == "off"


def test_mixed_capability_bailout(env):
    e, _, _ = env
    e2 = dict(e)
    e2["CC_CAPABLE_DEVICE_IDS"] = "0x005e"  # nothing matches 0x0063
    r = run_sh(e2, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 1
    assert "not CC-capable" in r.stderr


def test_missing_node_name(env):
    e, _, _ = env
    e2 = dict(e)
    del e2["NODE_NAME"]
    r = run_sh(e2, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 1
    assert "NODE_NAME" in r.stderr


def test_bash_engine_posts_events(env):
    e, server, tmp_path = env
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 0, r.stderr
    evs = server.store.list_events("default")
    assert [x["reason"] for x in evs] == ["CCModeApplied"]
    assert evs[0]["type"] == "Normal"
    assert evs[0]["involvedObject"]["name"] == "bash-node"
    assert evs[0]["source"]["component"] == "tpu-cc-manager.sh"

    # failure path: mixed-capability bailout posts a Warning
    e3 = dict(e)
    e3["CC_CAPABLE_DEVICE_IDS"] = "0xdead"
    r = run_sh(e3, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode != 0
    reasons = [x["reason"] for x in server.store.list_events("default")]
    assert reasons == ["CCModeApplied", "CCModeFailed"]

    # disabled via env (same knob as the Python agent)
    e2 = dict(e)
    e2["EMIT_EVENTS"] = "false"
    r = run_sh(e2, "set-cc-mode", "-a", "-m", "off")
    assert r.returncode == 0, r.stderr
    assert len(server.store.list_events("default")) == 2


def test_bash_engine_publishes_evidence(env):
    """Parity with the Python engines: a successful bash flip publishes
    the evidence annotation (same wire format, built by
    `python -m tpu_cc_manager.evidence`), and it verifies."""
    import json
    from tpu_cc_manager.evidence import evidence_mode, verify_evidence
    e, server, tmp_path = env
    assert run_sh(e, "set-cc-mode", "-a", "-m", "on").returncode == 0
    ann = server.store.get_node("bash-node")["metadata"]["annotations"]
    doc = json.loads(ann[L.EVIDENCE_ANNOTATION])
    assert verify_evidence(doc, key=None)[0] is True
    assert doc["node"] == "bash-node"
    assert evidence_mode(doc) == "on"


def test_device_gating_perms(env):
    """Parity with device/gate.py: after a verified flip the device
    node's permission bits encode the effective CC mode (on=0600,
    off=0666) — the workload-visible consequence of the mode."""
    import stat as st
    e, server, tmp_path = env
    assert run_sh(e, "set-cc-mode", "-a", "-m", "on").returncode == 0
    assert st.S_IMODE(os.stat(tmp_path / "dev" / "accel0").st_mode) == 0o600
    assert run_sh(e, "set-cc-mode", "-a", "-m", "off").returncode == 0
    assert st.S_IMODE(os.stat(tmp_path / "dev" / "accel0").st_mode) == 0o666

    # TPU_CC_DEVICE_GATING=none leaves the node alone
    e2 = dict(e)
    e2["TPU_CC_DEVICE_GATING"] = "none"
    os.chmod(tmp_path / "dev" / "accel0", 0o644)
    assert run_sh(e2, "set-cc-mode", "-a", "-m", "on").returncode == 0
    assert st.S_IMODE(os.stat(tmp_path / "dev" / "accel0").st_mode) == 0o644


def test_exclusive_hold_check(env):
    """Parity with device/holders.py: the bash engine refuses to commit
    while a foreign process holds the device node, and the configured
    runtime restart hook evicts the holder so the flip can proceed."""
    import subprocess as sp
    import sys
    e, server, tmp_path = env
    dev = str(tmp_path / "dev" / "accel0")
    holder = sp.Popen(
        [sys.executable, "-c",
         f"import time\nf=open({dev!r})\nprint('held',flush=True)\n"
         "time.sleep(120)"],
        stdout=sp.PIPE, text=True)
    assert holder.stdout.readline().strip() == "held"
    try:
        e2 = dict(e)
        e2["TPU_CC_HOLD_WAIT_S"] = "1"
        r = run_sh(e2, "set-cc-mode", "-a", "-m", "on")
        assert r.returncode != 0
        assert "held by" in r.stderr

        # with a restart hook that kills the holder, the flip proceeds
        e3 = dict(e)
        e3["TPU_CC_RUNTIME_RESTART_CMD"] = f"kill {holder.pid}"
        r = run_sh(e3, "set-cc-mode", "-a", "-m", "on")
        assert r.returncode == 0, r.stderr
    finally:
        holder.poll() is not None or holder.kill()
        holder.wait()


def test_bash_engine_flip_taint(env):
    """Parity with the Python engine's NodeFlipTaint: the flip taint is
    cleared by the end of the cycle (success AND failure paths), and
    foreign taints survive the read-edit-replace."""
    e, server, tmp_path = env
    server.store.patch_node("bash-node", {"spec": {"taints": [
        {"key": "example.com/other", "value": "x", "effect": "NoExecute"},
    ]}})
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 0, r.stderr
    taints = server.store.get_node("bash-node")["spec"]["taints"]
    assert [t["key"] for t in taints] == ["example.com/other"]

    # failure path (holder blocks the flip): taint still cleared
    import subprocess as sp
    import sys as _sys
    dev = str(tmp_path / "dev" / "accel0")
    holder = sp.Popen(
        [_sys.executable, "-c",
         f"import time\nf=open({dev!r})\nprint('held',flush=True)\n"
         "time.sleep(120)"],
        stdout=sp.PIPE, text=True)
    assert holder.stdout.readline().strip() == "held"
    try:
        e2 = dict(e)
        e2["TPU_CC_HOLD_WAIT_S"] = "1"
        r = run_sh(e2, "set-cc-mode", "-a", "-m", "off")
        assert r.returncode != 0
        taints = server.store.get_node("bash-node")["spec"]["taints"]
        assert [t["key"] for t in taints] == ["example.com/other"]
    finally:
        holder.kill()
        holder.wait()


def test_bash_engine_direct_tls(env, tls_pki, tmp_path):
    """KUBE_API_TLS=true: the bash engine's curl path verifies the
    cluster CA and sends the bearer token — parity with the native
    agent's direct-TLS transport (daemonset-native-tls.yaml)."""
    e, _, _tmp = env
    cert, key = tls_pki
    token = tmp_path / "token"
    token.write_text("tls-engine-token\n")
    tls_server = FakeApiServer(required_token="tls-engine-token",
                               tls_cert=str(cert), tls_key=str(key)).start()
    try:
        tls_server.store.add_node(make_node("bash-node", labels={DP: "true"}))
        e2 = dict(e)
        e2.update(
            KUBE_API_PORT=str(tls_server.port),
            KUBE_API_TLS="true",
            KUBE_CA_FILE=str(cert),
            BEARER_TOKEN_FILE=str(token),
        )
        r = run_sh(e2, "set-cc-mode", "-a", "-m", "on")
        assert r.returncode == 0, r.stderr
        labels = tls_server.store.get_node("bash-node")["metadata"]["labels"]
        assert labels[L.CC_MODE_STATE_LABEL] == "on"
    finally:
        tls_server.stop()


def test_drain_wait_counts_typemeta_less_pod_items(env):
    """A still-present component pod must be seen by the drain wait even
    though the apiserver (like a real one) omits kind/apiVersion from
    list items — a grep for '"kind":"Pod"' would count 0 and skip the
    wait entirely. Present pod -> the wait runs to its deadline and
    warns (reference gpu_operator_eviction.py:205-207 parity); pod gone
    -> no warn."""
    e, server, tmp_path = env
    from tpu_cc_manager.k8s.objects import make_pod
    server.store.add_pod(make_pod(
        "dp-1", "tpu-system", labels={"app": "tpu-device-plugin"},
        node_name="bash-node"))
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 0, r.stderr
    assert "timed out waiting" in r.stderr

    server.store.delete_pod("tpu-system", "dp-1")
    r = run_sh(e, "set-cc-mode", "-a", "-m", "off")
    assert r.returncode == 0, r.stderr
    assert "timed out waiting" not in r.stderr


def test_drain_wait_fails_when_pods_never_listable(env):
    """Eviction deadline reached without ever obtaining a pod list ->
    the flip must FAIL (state label + event), not proceed over possibly
    still-running workloads."""
    e, server, tmp_path = env
    # point k8s at a dead port AFTER device discovery needs nothing from
    # it; the engine's label writes will also fail (best-effort), so the
    # outcome is the nonzero exit
    e2 = dict(e)
    e2["KUBE_API_PORT"] = "1"  # nothing listens
    e2["EVICTION_TIMEOUT_S"] = "1"
    e2["EVICTION_POLL_S"] = "0.2"
    e2["EVICT_OPERATOR_COMPONENTS"] = "true"
    r = run_sh(e2, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode != 0
    # devices untouched: the flip never ran
    q = run_sh(e2, "get-cc-mode", "-a")
    assert "cc=off" in q.stdout


# ------------------------------------------------- slice coherence guard
def _add_slice_node(server, name, slice_id):
    server.store.add_node(make_node(name, labels={
        DP: "true", L.TPU_SLICE_LABEL: slice_id}))


def test_slice_member_delegates_not_flips(env, tmp_path):
    """A slice-labeled node must never be flipped unilaterally by the
    bash engine: it execs the slice-aware delegate instead, leaving
    devices and labels for the delegate to own."""
    e, server, root = env
    server.store.add_node(make_node("slice-node", labels={
        DP: "true", L.TPU_SLICE_LABEL: "s-1"}))
    e = dict(e, NODE_NAME="slice-node")
    marker = tmp_path / "delegated"
    stub = tmp_path / "stub.sh"
    stub.write_text(f"#!/bin/sh\necho \"$@\" > {marker}\nexit 0\n")
    stub.chmod(0o755)
    e["TPU_CC_SLICE_DELEGATE_CMD"] = f"{stub} %s"
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 0, r.stderr
    assert marker.read_text().strip() == "on"
    # the bash engine touched NOTHING itself
    store = ModeStateStore(str(root / "state"))
    assert store.effective(str(root / "dev" / "accel0"), "cc") == "off"
    labels = server.store.get_node("slice-node")["metadata"]["labels"]
    assert L.CC_MODE_STATE_LABEL not in labels


def test_slice_member_refuses_without_delegate(env):
    """No slice-aware engine available: refuse loudly (Event + rc 1)
    rather than produce a half-flipped slice."""
    e, server, root = env
    server.store.add_node(make_node("slice-node", labels={
        DP: "true", L.TPU_SLICE_LABEL: "s-1"}))
    e = dict(e, NODE_NAME="slice-node")
    e["TPU_CC_SLICE_DELEGATE_CMD"] = "/nonexistent-slice-engine %s"
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 1
    assert "refusing a unilateral flip" in r.stderr
    store = ModeStateStore(str(root / "state"))
    assert store.effective(str(root / "dev" / "accel0"), "cc") == "off"
    reasons = [ev.get("reason") for ev in server.store.cluster_events]
    assert "CCSliceAborted" in reasons


def test_slice_optout_flips_locally(env):
    """SLICE_COORDINATION=false is the explicit single-host opt-out:
    the engine flips directly even on a slice-labeled node."""
    e, server, root = env
    server.store.add_node(make_node("slice-node", labels={
        DP: "true", L.TPU_SLICE_LABEL: "s-1"}))
    e = dict(e, NODE_NAME="slice-node", SLICE_COORDINATION="false")
    e["TPU_CC_SLICE_DELEGATE_CMD"] = "/nonexistent-slice-engine %s"
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 0, r.stderr
    store = ModeStateStore(str(root / "state"))
    assert store.effective(str(root / "dev" / "accel0"), "cc") == "on"


def test_slice_delegation_runs_real_python_engine(env, tmp_path):
    """Full native-path drill with the DEFAULT delegate: bash engine ->
    python one-shot -> slice quorum protocol -> devices flipped +
    state label set. A single-member slice reaches quorum alone, so
    the whole chain runs hermetically."""
    import sys

    e, server, root = env
    server.store.add_node(make_node("slice-node", labels={
        DP: "true", L.TPU_SLICE_LABEL: "s-solo"}))
    kubeconfig = tmp_path / "kubeconfig.yaml"
    kubeconfig.write_text(f"""
apiVersion: v1
kind: Config
current-context: t
contexts: [{{name: t, context: {{cluster: c, user: u}}}}]
clusters: [{{name: c, cluster: {{server: "http://127.0.0.1:{server.port}"}}}}]
users: [{{name: u, user: {{}}}}]
""")
    e = dict(e, NODE_NAME="slice-node", KUBECONFIG=str(kubeconfig),
             PYTHONPATH=REPO, DRAIN_STRATEGY="none",
             TPU_CC_DEVICE_GATING="none", HEALTH_PORT="0")
    e["TPU_CC_SLICE_DELEGATE_CMD"] = (
        f"{sys.executable} -m tpu_cc_manager set-cc-mode -m %s"
    )
    r = run_sh(e, "set-cc-mode", "-a", "-m", "devtools")
    assert r.returncode == 0, r.stderr + r.stdout
    store = ModeStateStore(str(root / "state"))
    assert store.effective(str(root / "dev" / "accel0"), "cc") == "devtools"
    labels = server.store.get_node("slice-node")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "devtools"


def test_slice_delegate_aborts_on_missing_quorum(env, tmp_path):
    """Two-member slice, one member silent: the delegated one-shot
    times out on quorum WITHOUT flipping — exactly the half-flipped
    state the delegation exists to prevent — and the abort propagates
    as the engine's exit code."""
    import sys

    import time as _time

    e, server, root = env
    for name in ("m1", "m2"):
        server.store.add_node(make_node(name, labels={
            DP: "true", L.TPU_SLICE_LABEL: "s-pair"}))
    # m2 must be ALIVE (fresh slice heartbeat) to be counted into the
    # quorum — dead members are deliberately excluded so they cannot
    # brick a slice forever
    server.store.set_node_annotations(
        "m2", {"tpu.google.com/cc.slice.hb": str(_time.time())})
    kubeconfig = tmp_path / "kubeconfig.yaml"
    kubeconfig.write_text(f"""
apiVersion: v1
kind: Config
current-context: t
contexts: [{{name: t, context: {{cluster: c, user: u}}}}]
clusters: [{{name: c, cluster: {{server: "http://127.0.0.1:{server.port}"}}}}]
users: [{{name: u, user: {{}}}}]
""")
    e = dict(e, NODE_NAME="m1", KUBECONFIG=str(kubeconfig),
             PYTHONPATH=REPO, DRAIN_STRATEGY="none",
             TPU_CC_DEVICE_GATING="none", HEALTH_PORT="0",
             TPU_CC_SLICE_COMMIT_TIMEOUT_S="3")
    e["TPU_CC_SLICE_DELEGATE_CMD"] = (
        f"{sys.executable} -m tpu_cc_manager set-cc-mode -m %s"
    )
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 1
    store = ModeStateStore(str(root / "state"))
    assert store.effective(str(root / "dev" / "accel0"), "cc") == "off"
    labels = server.store.get_node("m1")["metadata"]["labels"]
    assert labels.get(L.CC_MODE_STATE_LABEL) != "on"
    reasons = [ev.get("reason") for ev in server.store.cluster_events]
    assert "CCSliceAborted" in reasons


def test_slice_guard_fails_closed_on_unreadable_node(env):
    """Membership unknown = refuse: if the node can't be read the
    engine cannot prove it isn't a slice member, so it must not flip."""
    e, server, root = env
    e = dict(e, NODE_NAME="never-created-node")
    r = run_sh(e, "set-cc-mode", "-a", "-m", "on")
    assert r.returncode == 1
    assert "cannot read node to check slice membership" in r.stderr
    store = ModeStateStore(str(root / "state"))
    assert store.effective(str(root / "dev" / "accel0"), "cc") == "off"


def test_slice_member_refuses_per_device_flip(env):
    """-d on a slice member is refused: slice rounds are whole-node,
    and silently broadening a single-device request would be worse."""
    e, server, root = env
    server.store.add_node(make_node("slice-node", labels={
        DP: "true", L.TPU_SLICE_LABEL: "s-1"}))
    e = dict(e, NODE_NAME="slice-node")
    dev0 = str(root / "dev" / "accel0")
    r = run_sh(e, "set-cc-mode", "-d", dev0, "-m", "on")
    assert r.returncode == 1
    assert "per-device flip" in r.stderr
    store = ModeStateStore(str(root / "state"))
    assert store.effective(dev0, "cc") == "off"
