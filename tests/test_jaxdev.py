"""JAX/PJRT device backend (device/jaxdev.py) — the real-chip bridge.

Runs against the virtual CPU mesh (conftest forces JAX_PLATFORMS=cpu with
8 host devices); the same code path enumerates/probes/resets the real TPU
chip on the bench host (TPU_CC_DEVICE_BACKEND=jax there).
"""

import json

import pytest

from tpu_cc_manager.device import base as device_base
from tpu_cc_manager.device.base import DeviceError, set_backend
from tpu_cc_manager.device.jaxdev import JaxTpuBackend
from tpu_cc_manager.engine import ModeEngine


@pytest.fixture
def jax_backend(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_CC_JAX_ALLOW_CPU", "1")
    return JaxTpuBackend(state_dir=str(tmp_path / "state"))


def test_enumerates_live_pjrt_devices(jax_backend):
    chips, err = jax_backend.find_tpus()
    assert err is None
    assert len(chips) == 8  # the virtual CPU mesh
    assert all(c.path.startswith("jax:cpu:") for c in chips)
    assert all(c.is_cc_query_supported for c in chips)
    assert sorted(c.device_id for c in chips) == list(range(8))


def test_cpu_devices_excluded_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("TPU_CC_JAX_ALLOW_CPU", raising=False)
    be = JaxTpuBackend(state_dir=str(tmp_path / "state"))
    chips, err = be.find_tpus()
    assert err is None
    assert chips == []  # no TPU platform devices in the test env


def test_capability_filter_by_device_kind(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_CC_JAX_ALLOW_CPU", "1")
    monkeypatch.setenv("CC_CAPABLE_DEVICE_KINDS", "v5 lite,v5p")
    be = JaxTpuBackend(state_dir=str(tmp_path / "state"))
    chips, _ = be.find_tpus()
    assert chips and all(not c.is_cc_query_supported for c in chips)
    monkeypatch.setenv("CC_CAPABLE_DEVICE_KINDS", "cpu")
    chips, _ = be.find_tpus()
    assert chips and all(c.is_cc_query_supported for c in chips)


def test_probe_runs_computation_on_device(jax_backend):
    dt = jax_backend.probe_device(0)
    assert dt >= 0
    with pytest.raises(DeviceError):
        jax_backend.probe_device(99)


def test_full_flip_through_live_runtime(jax_backend):
    # stage -> reset (PJRT teardown + commit) -> wait_ready (on-device
    # probe) -> verify: the reference's per-GPU sequence (main.py:258-296)
    # driven end-to-end through the live runtime.
    set_backend(jax_backend)
    states = []
    engine = ModeEngine(set_state_label=states.append, evict_components=False)
    assert engine.set_mode("on") is True
    assert states == ["on"]
    chips, _ = jax_backend.find_tpus()
    assert all(c.query_cc_mode() == "on" for c in chips)
    # idempotent fast path on the second application
    states.clear()
    assert engine.set_mode("on") is True
    assert states == ["on"]


def test_describe_inventory_shape(jax_backend):
    desc = jax_backend.describe()
    assert desc["backend"] == "jax"
    assert desc["error"] is None
    assert len(desc["devices"]) == 8
    d0 = desc["devices"][0]
    assert {"path", "device_kind", "platform", "device_id", "process_index",
            "coords", "cc_capable", "cc_mode", "ici_mode"} <= set(d0)
    json.dumps(desc)  # serializable as-is


def test_one_teardown_per_multichip_plan(jax_backend, monkeypatch):
    # The PJRT teardown is runtime-global: flipping all 8 chips must cost
    # exactly ONE physical teardown, not 8 (chips share the runtime
    # generation they were enumerated under).
    set_backend(jax_backend)
    calls = []
    real = JaxTpuBackend.teardown_runtime

    def counting(self):
        calls.append(1)
        real(self)

    monkeypatch.setattr(JaxTpuBackend, "teardown_runtime", counting)
    engine = ModeEngine(set_state_label=lambda v: None,
                        evict_components=False)
    assert engine.set_mode("on") is True
    assert len(calls) == 1
    chips, _ = jax_backend.find_tpus()
    assert all(c.query_cc_mode() == "on" for c in chips)


def test_parallel_flip_pays_one_teardown(jax_backend, monkeypatch):
    # Same invariant under the EXPLICIT parallel executor: N workers
    # racing JaxTpuChip.reset serialize on the backend's teardown lock
    # and exactly one of them restarts the runtime.
    monkeypatch.setenv("TPU_CC_FLIP_CONCURRENCY", "4")
    set_backend(jax_backend)
    calls = []
    real = JaxTpuBackend.teardown_runtime

    def counting(self):
        calls.append(1)
        real(self)

    monkeypatch.setattr(JaxTpuBackend, "teardown_runtime", counting)
    engine = ModeEngine(set_state_label=lambda v: None,
                        evict_components=False)
    assert engine.set_mode("on") is True
    assert len(calls) == 1
    chips, _ = jax_backend.find_tpus()
    assert all(c.query_cc_mode() == "on" for c in chips)


def test_jax_wait_ready_backoff(jax_backend, monkeypatch):
    # Adaptive retry (ISSUE 4 satellite): two probe failures cost
    # ~0.15s of backoff (0.05 + 0.1), not the old 2 x 0.5s floor.
    import time

    chips, _ = jax_backend.find_tpus()
    chip = chips[0]
    failures = {"left": 2}
    real_probe = JaxTpuBackend.probe_device

    def flaky(self, device_id):
        if failures["left"] > 0:
            failures["left"] -= 1
            raise RuntimeError("runtime still rebooting")
        return real_probe(self, device_id)

    monkeypatch.setattr(JaxTpuBackend, "probe_device", flaky)
    t0 = time.monotonic()
    chip.wait_ready(timeout_s=5)
    elapsed = time.monotonic() - t0
    assert elapsed < 0.5, elapsed


def test_statefile_reads_have_no_side_effects(tmp_path):
    import os

    from tpu_cc_manager.device.statefile import ModeStateStore

    store = ModeStateStore(str(tmp_path / "never-created"))
    assert store.effective("/dev/accel0", "cc") == "off"
    assert store.staged("/dev/accel0", "cc") == "off"
    assert not os.path.exists(str(tmp_path / "never-created"))


def test_backend_registry_env_selection(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_CC_DEVICE_BACKEND", "jax")
    monkeypatch.setenv("TPU_CC_JAX_ALLOW_CPU", "1")
    monkeypatch.setenv("TPU_CC_STATE_DIR", str(tmp_path / "state"))
    device_base.set_backend(None)
    assert isinstance(device_base.get_backend(), JaxTpuBackend)
    monkeypatch.setenv("TPU_CC_DEVICE_BACKEND", "bogus")
    device_base.set_backend(None)
    with pytest.raises(DeviceError):
        device_base.get_backend()


def test_probe_devices_cli(tmp_path, monkeypatch, capsys):
    import tpu_cc_manager.__main__ as cli

    monkeypatch.setenv("TPU_CC_JAX_ALLOW_CPU", "1")
    monkeypatch.setenv("TPU_CC_STATE_DIR", str(tmp_path / "state"))
    assert cli.main(["probe-devices"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["backend"] == "jax"
    assert len(out["devices"]) == 8


def test_probe_devices_cli_backend_flag_and_error_containment(
    tmp_path, monkeypatch, capsys
):
    import tpu_cc_manager.__main__ as cli

    # --backend sysfs probes the sysfs surface (empty tree -> no devices,
    # still valid JSON, rc 0)
    monkeypatch.setenv("TPU_SYSFS_ROOT", str(tmp_path / "no-sysfs"))
    monkeypatch.setenv("TPU_CC_STATE_DIR", str(tmp_path / "state"))
    assert cli.main(["probe-devices", "--backend", "sysfs"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["backend"] == "sysfs"
    assert out["devices"] == []

    # a crashing backend yields JSON + rc 1, never a traceback
    monkeypatch.setenv("TPU_CC_JAX_ALLOW_CPU", "1")

    def boom(self):
        raise RuntimeError("runtime gone")

    monkeypatch.setattr(JaxTpuBackend, "find_tpus", boom)
    assert cli.main(["probe-devices", "--backend", "jax"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["backend"] == "jax"
    assert "runtime gone" in out["error"]


def test_wait_ready_early_exits_on_generation_bump(jax_backend, monkeypatch):
    """ISSUE 13 satellite: a teardown landing MID-WAIT bumps the
    runtime generation; the backoff poll must fail fast (naming the
    supersession) instead of busy-holding its whole deadline slice
    probing a dead session."""
    import threading
    import time

    chips, _ = jax_backend.find_tpus()
    chip = chips[0]

    def failing_probe(device_id):
        raise RuntimeError("runtime not up (injected)")

    monkeypatch.setattr(jax_backend, "probe_device", failing_probe)

    def bump():
        time.sleep(0.15)
        with jax_backend._devices_lock:
            jax_backend.runtime_gen += 1

    t = threading.Thread(target=bump)
    t.start()
    t0 = time.monotonic()
    with pytest.raises(DeviceError) as ei:
        chip.wait_ready(timeout_s=30.0)
    elapsed = time.monotonic() - t0
    t.join()
    # failed on the bump (~0.15s), nowhere near the 30s deadline
    assert elapsed < 5.0, elapsed
    assert "generation advanced" in str(ei.value)


def test_wait_ready_still_times_out_without_a_bump(jax_backend, monkeypatch):
    """Control: with the generation stable, the loop keeps its
    historical timeout semantics."""
    import time

    chips, _ = jax_backend.find_tpus()
    chip = chips[0]
    monkeypatch.setattr(
        jax_backend, "probe_device",
        lambda device_id: (_ for _ in ()).throw(
            RuntimeError("still down")
        ),
    )
    t0 = time.monotonic()
    with pytest.raises(DeviceError) as ei:
        chip.wait_ready(timeout_s=0.4)
    assert time.monotonic() - t0 >= 0.35
    assert "not ready after" in str(ei.value)
