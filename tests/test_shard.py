"""Sharded control plane (tpu_cc_manager.shard, ISSUE 11): the
consistent-hash ring's stability contract, the shared NodeInformer's
zero-read scan path, partition-scoped clients, lease-per-shard
placement and kill->survivor failover, and the merged fleet view.
Plus the FakeKube watch-history compaction + pre-encoded fan-out the
1,024-replica scenario leans on."""

import json
import threading
import time

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.client import ApiException
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.shard import (
    HashRing, ShardManager, ShardScopedClient,
)
from tpu_cc_manager.watch import InformerKubeClient, NodeInformer

POOL_LABEL = "simlab.pool"


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _fleet_kube(n=8, pools=4):
    kube = FakeKube()
    for i in range(n):
        kube.add_node(make_node(f"n{i:03d}", labels={
            L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
            POOL_LABEL: f"p{i % pools}",
            L.CC_MODE_LABEL: "off",
            L.CC_MODE_STATE_LABEL: "off",
        }))
    return kube


# --------------------------------------------------------------- hash ring
def test_ring_is_deterministic_and_total():
    a = HashRing(["shard-0", "shard-1", "shard-2"])
    b = HashRing(["shard-0", "shard-1", "shard-2"])
    pools = [f"p{i}" for i in range(64)]
    assert [a.owner_of(p) for p in pools] == [b.owner_of(p) for p in pools]
    part = a.partition(pools)
    assert sorted(sum(part.values(), [])) == sorted(pools)
    # every shard gets work at 64 pools / 3 shards with vnodes
    assert all(part[s] for s in a.members)


def test_ring_without_moves_only_the_removed_members_keys():
    """THE consistent-hash property: dropping one shard reassigns only
    that shard's pools — everything else stays put (the repartition
    storm's movement bound)."""
    ring = HashRing(["shard-0", "shard-1", "shard-2", "shard-3"])
    pools = [f"p{i}" for i in range(128)]
    before = {p: ring.owner_of(p) for p in pools}
    smaller = ring.without("shard-2")
    after = {p: smaller.owner_of(p) for p in pools}
    for p in pools:
        if before[p] != "shard-2":
            assert after[p] == before[p], p
        else:
            assert after[p] != "shard-2"


def test_ring_rejects_empty_and_duplicates():
    with pytest.raises(ValueError):
        HashRing([])
    with pytest.raises(ValueError):
        HashRing(["a", "a"])


# ---------------------------------------------------------- node informer
def test_informer_serves_reads_from_watch_fed_cache():
    kube = _fleet_kube(n=4)
    inf = NodeInformer(kube, name="t")
    inf.prime()
    inf.start()
    try:
        assert len(inf.list_nodes()) == 4
        assert len(inf.list_nodes(f"{POOL_LABEL}=p0")) == 1
        assert inf.get_node("n000")["metadata"]["name"] == "n000"
        with pytest.raises(ApiException) as ei:
            inf.get_node("ghost")
        assert ei.value.status == 404
        # a write lands in the cache via the watch, no reads needed
        kube.set_node_labels("n000", {L.CC_MODE_LABEL: "on"})
        assert _wait(lambda: inf.get_node("n000")["metadata"]["labels"]
                     [L.CC_MODE_LABEL] == "on")
        kube.add_node(make_node("n999", labels={POOL_LABEL: "p0"}))
        assert _wait(lambda: len(inf.list_nodes()) == 5)
    finally:
        inf.stop()


def test_informer_resumes_from_list_rv_no_gap():
    """The informer's LIST-then-WATCH-from-rv contract: a write landing
    between the priming list and the watch establishment is replayed,
    never missed — a read cache cannot tolerate run_node_watch's
    wake-covered gap."""
    kube = _fleet_kube(n=2)
    inf = NodeInformer(kube, name="gap")
    inf.prime()
    # the gap write: after the list, before the watch
    kube.set_node_labels("n000", {L.CC_MODE_LABEL: "devtools"})
    inf.start()
    try:
        assert _wait(lambda: inf.get_node("n000")["metadata"]["labels"]
                     [L.CC_MODE_LABEL] == "devtools")
    finally:
        inf.stop()


def test_informer_relists_through_410():
    kube = _fleet_kube(n=3)
    inf = NodeInformer(kube, name="g410", backoff_s=0.05)
    inf.prime()
    inf.start()
    try:
        kube.set_node_labels("n001", {L.CC_MODE_LABEL: "on"})
        assert _wait(lambda: inf.get_node("n001")["metadata"]["labels"]
                     [L.CC_MODE_LABEL] == "on")
        # compact under the informer, then churn: the resume 410s and
        # the informer must relist back to truth
        kube.compact_watch_history()
        kube.set_node_labels("n002", {L.CC_MODE_LABEL: "on"})
        assert _wait(lambda: inf.get_node("n002")["metadata"]["labels"]
                     [L.CC_MODE_LABEL] == "on")
    finally:
        inf.stop()


def test_informer_wake_fires_on_relist_and_events_fan_out():
    kube = _fleet_kube(n=2)
    inf = NodeInformer(kube, name="subs")
    events, wakes = [], []
    token = inf.subscribe(
        on_event=lambda e, n: events.append(
            (e, n["metadata"]["name"])),
        on_wake=lambda: wakes.append(1),
    )
    inf.prime()
    assert wakes  # relist covers the gap -> wake
    inf.start()
    try:
        kube.set_node_labels("n000", {L.CC_MODE_LABEL: "on"})
        assert _wait(lambda: ("MODIFIED", "n000") in events)
        inf.unsubscribe(token)
        n = len(events)
        kube.set_node_labels("n001", {L.CC_MODE_LABEL: "on"})
        time.sleep(0.2)
        assert len(events) == n  # unsubscribed: no more deliveries
    finally:
        inf.stop()


def test_steady_state_scan_does_zero_node_reads():
    """THE ISSUE 11 pin: an informer-fed FleetController's scans
    perform 0 node read round trips — the priming LIST is the last
    node read the control plane ever pays."""
    from tpu_cc_manager.fleet import FleetController

    kube = _fleet_kube(n=6)
    inf = NodeInformer(kube, name="zero")
    inf.prime()
    ctrl = FleetController(
        InformerKubeClient(inf, kube), port=0, informer=inf,
    )
    reads_after_prime = kube.node_read_requests
    for _ in range(3):
        report = ctrl.scan_once()
    assert report["nodes"] == 6
    assert kube.node_read_requests == reads_after_prime, (
        "steady-state scans must be informer-fed: 0 node GET/LIST "
        "round trips"
    )


# ------------------------------------------------------------ scoped client
def test_scoped_client_filters_nodes_and_customs_writes_pass_through():
    kube = _fleet_kube(n=8, pools=4)
    kube.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
        "metadata": {"name": "pol-a"}, "spec": {}})
    kube.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
        "metadata": {"name": "pol-b"}, "spec": {}})

    def own(node):
        labels = node["metadata"].get("labels") or {}
        return labels.get(POOL_LABEL) in ("p0", "p1")

    scoped = ShardScopedClient(
        kube, node_filter=own,
        custom_filter=lambda name: name == "pol-a",
    )
    assert len(scoped.list_nodes()) == 4
    assert {o["metadata"]["name"] for o in scoped.list_cluster_custom(
        L.POLICY_GROUP, L.POLICY_VERSION, L.POLICY_PLURAL)} == {"pol-a"}
    # writes and unscoped verbs delegate untouched
    scoped.set_node_labels("n002", {L.CC_MODE_LABEL: "on"})
    assert kube.get_node("n002")["metadata"]["labels"][
        L.CC_MODE_LABEL] == "on"
    assert scoped.get_node("n002")["metadata"]["name"] == "n002"


# ------------------------------------------------------------ shard manager
def _manager(kube, **kw):
    kw.setdefault("shards", 2)
    kw.setdefault("pools", ["p0", "p1", "p2", "p3"])
    kw.setdefault("pool_label", POOL_LABEL)
    kw.setdefault("fleet_interval_s", 0.2)
    kw.setdefault("lease_duration_s", 0.4)
    kw.setdefault("renew_period_s", 0.1)
    kw.setdefault("retry_period_s", 0.05)
    return ShardManager(lambda: kube, **kw)


def test_shards_settle_one_per_host_and_scope_their_partition():
    kube = _fleet_kube(n=8, pools=4)
    mgr = _manager(kube)
    mgr.start()
    try:
        assert mgr.wait_covered(timeout_s=10)
        coverage = mgr.coverage()
        # the initial-delay handicap: each preferred host wins its own
        # shard's create race
        assert coverage == {"shard-0": "host-0", "shard-1": "host-1"}
        bundles = {b.shard_id: b for b in mgr.bundles()}
        assert set(bundles) == {"shard-0", "shard-1"}
        # each shard's fleet controller sees EXACTLY its partition
        for sid, bundle in bundles.items():
            report = bundle.fleet.scan_once()
            want = sum(
                2 for p in mgr.pools_of(sid)  # 8 nodes / 4 pools
            )
            assert report["nodes"] == want, (sid, report["nodes"])
        # partition tables and the ring agree
        for pool in ("p0", "p1", "p2", "p3"):
            sid = mgr.shard_of_pool(pool)
            assert pool in mgr.pools_of(sid)
    finally:
        mgr.stop()


def test_shard_kill_survivor_reacquires_partition():
    """The failover contract: crash one host (no lease release), the
    survivor waits out staleness, takes the orphaned lease, and its
    fresh ControllerShard covers the dead shard's pools."""
    kube = _fleet_kube(n=8, pools=4)
    mgr = _manager(kube)
    mgr.start()
    try:
        assert mgr.wait_covered(timeout_s=10)
        entry = mgr.kill_host(0)
        assert entry["orphaned_shards"] == ["shard-0"]
        assert mgr.wait_failovers(timeout_s=10)
        stats = mgr.stats()
        (failover,) = stats["failovers"]
        assert failover["handoff_s"] is not None
        # staleness, not instant theft: the takeover waited out at
        # least one lease duration
        assert failover["handoff_s"] >= 0.3
        assert stats["coverage"] == {
            "shard-0": "host-1", "shard-1": "host-1",
        }
        # the survivor runs BOTH partitions' controller bundles now
        held = {b.shard_id for b in mgr.bundles()}
        assert held == {"shard-0", "shard-1"}
        # and the whole fleet is still scanned: union of shard scans
        total = sum(
            b.fleet.scan_once()["nodes"] for b in mgr.bundles()
        )
        assert total == 8
    finally:
        mgr.stop()


def test_shard_restart_rejoins_as_standby_without_preemption():
    kube = _fleet_kube(n=4, pools=4)
    # a roomy lease: the no-preemption check below reads coverage
    # INSIDE the staleness window, where takeover is impossible by
    # construction — a loaded CI box must not turn renew starvation
    # into a false preemption
    mgr = _manager(kube, lease_duration_s=2.0, renew_period_s=0.1)
    mgr.start()
    try:
        assert mgr.wait_covered(timeout_s=10)
        mgr.kill_host(0)
        assert mgr.wait_failovers(timeout_s=15)
        out = mgr.restart_host(0)
        assert out["restarted"] is True
        assert mgr.hosts[0].alive
        # no preemption: the survivor keeps renewing; the restarted
        # host observes a live holder and stays standby (read well
        # inside the lease duration — instant theft would show here)
        time.sleep(0.5)
        assert mgr.coverage()["shard-0"] == "host-1"
    finally:
        mgr.stop()


def test_merged_fleet_metrics_is_one_valid_exposition():
    from tpu_cc_manager.obs import validate_exposition

    kube = _fleet_kube(n=8, pools=4)
    mgr = _manager(kube)
    mgr.start()
    try:
        assert mgr.wait_covered(timeout_s=10)
        for b in mgr.bundles():
            b.fleet.scan_once()
        text = mgr.merged_fleet_metrics()
        assert validate_exposition(text) == []
        # the merge really aggregates: fleet-wide node count is the sum
        # of the partitions, on one series
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("tpu_cc_fleet_nodes ")
        )
        assert float(line.split()[1]) == 8.0
        assert "tpu_cc_shard_partitions_covered 2" in text
    finally:
        mgr.stop()


# ------------------------------------------- fake watch history / fan-out
def test_watch_history_compaction_is_chunked_and_bounded():
    kube = FakeKube(watch_history_limit=10)
    for i in range(200):
        kube.add_node(make_node(f"c{i}"))
    # bounded: never beyond limit + chunk; compacted back to limit
    assert len(kube._events) <= 10 + kube._compact_chunk
    assert len(kube._events) == len(kube._event_rvs)
    # 410 contract intact: resuming below the retained window fails
    with pytest.raises(ApiException) as ei:
        list(kube.watch_nodes(resource_version="1", timeout_s=0.05))
    assert ei.value.status == 410


def test_cluster_events_are_bounded():
    kube = FakeKube(watch_history_limit=10)
    for i in range(200):
        kube.create_event("default", {
            "metadata": {"name": f"e{i}"}, "reason": "R",
        })
    assert len(kube.cluster_events) <= 10 + kube._compact_chunk
    # newest retained
    assert kube.cluster_events[-1]["metadata"]["name"] == "e199"


def test_wire_watch_matches_clientset_watch_and_caches_encoding():
    kube = FakeKube()
    kube.add_node(make_node("w0", labels={L.CC_MODE_LABEL: "off"}))
    kube.set_node_labels("w0", {L.CC_MODE_LABEL: "on"})
    plain = list(kube.watch_nodes(resource_version="1", timeout_s=0.05))
    wire = list(kube.watch_nodes_wire(resource_version="1",
                                      timeout_s=0.05))
    assert len(plain) == len(wire) == 1
    decoded = json.loads(wire[0])
    assert decoded["type"] == plain[0][0]
    assert decoded["object"] == plain[0][1]
    # the encode is cached: every watcher gets the same bytes object
    wire2 = list(kube.watch_nodes_wire(resource_version="1",
                                       timeout_s=0.05))
    assert wire[0] is wire2[0]


def test_wire_watch_fans_out_over_http():
    """The apiserver's node-watch route rides the pre-encoded path:
    same NDJSON the clientset sees, one encode fleet-wide."""
    from tpu_cc_manager.k8s.apiserver import FakeApiServer
    from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig

    with FakeApiServer() as srv:
        srv.store.add_node(make_node("h0", labels={
            L.CC_MODE_LABEL: "off"}))
        kube = HttpKubeClient(
            KubeConfig("127.0.0.1", srv.port, use_tls=False)
        )
        got = []
        done = threading.Event()

        def watch():
            for etype, node in kube.watch_nodes(
                    resource_version=srv.store.latest_rv, timeout_s=3):
                got.append((etype, node["metadata"]["labels"]
                            [L.CC_MODE_LABEL]))
                done.set()
                return

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        time.sleep(0.2)
        srv.store.set_node_labels("h0", {L.CC_MODE_LABEL: "on"})
        assert done.wait(5)
        assert got == [("MODIFIED", "on")]


def test_informer_fed_controller_wakes_fingerprint_filtered():
    """The informer feed must preserve run_node_watch's wake filter:
    a report-relevant label change wakes the scan loop; a
    doctor-republish that only moves its timestamp does not."""
    from tpu_cc_manager.fleet import FleetController

    kube = _fleet_kube(n=2)
    kube.set_node_labels("n000", {L.DOCTOR_ANNOTATION: None})
    inf = NodeInformer(kube, name="wake")
    inf.prime()
    ctrl = FleetController(
        InformerKubeClient(inf, kube), port=0, informer=inf,
    )
    # wire the subscription exactly as run() does, without the loop
    ctrl._informer_token = inf.subscribe(
        on_event=ctrl._on_informer_event, on_wake=ctrl._wake.set,
    )
    inf.start()
    try:
        doc = {"ok": False, "fail": ["hbm"], "ts": 1}
        kube.set_node_annotations(
            "n000", {L.DOCTOR_ANNOTATION: json.dumps(doc)})
        assert _wait(ctrl._wake.is_set)
        ctrl._wake.clear()
        # timestamp-only republish: same stable digest, no wake
        doc2 = {"ok": False, "fail": ["hbm"], "ts": 2}
        kube.set_node_annotations(
            "n000", {L.DOCTOR_ANNOTATION: json.dumps(doc2)})
        time.sleep(0.3)
        assert not ctrl._wake.is_set()
        # but the ENCODING still saw the delta (list truth aside)
        kube.set_node_labels("n001", {L.CC_MODE_LABEL: "on"})
        assert _wait(ctrl._wake.is_set)
    finally:
        ctrl.stop()
        inf.stop()
