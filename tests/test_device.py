"""L0 device layer tests: fake backend semantics + sysfs backend against a
synthetic accel tree (the reference has no equivalent tests — SURVEY.md §4)."""

import os

import pytest

from tpu_cc_manager import device
from tpu_cc_manager.device.base import DeviceError, set_backend
from tpu_cc_manager.device.fake import FakeChip, fake_backend
from tpu_cc_manager.device.statefile import ModeStateStore
from tpu_cc_manager.device.tpu import SysfsTpuBackend


# ---------------------------------------------------------------- fake chip
def test_fake_chip_mode_takes_effect_only_after_reset():
    chip = FakeChip()
    assert chip.query_cc_mode() == "off"
    chip.set_cc_mode("on")
    assert chip.query_cc_mode() == "off"  # staged, not yet effective
    chip.reset()
    chip.wait_ready()
    assert chip.query_cc_mode() == "on"


def test_fake_chip_fault_injection():
    chip = FakeChip()
    chip.fail_set = True
    with pytest.raises(DeviceError):
        chip.set_cc_mode("on")
    chip.fail_set = False
    chip.fail_reset = True
    chip.set_cc_mode("on")
    with pytest.raises(DeviceError):
        chip.reset()
    chip.fail_reset = False
    chip.drop_staged_mode = True
    chip.reset()
    assert chip.query_cc_mode() == "off"  # verify-mismatch scenario


def test_fake_chip_capability_gates():
    chip = FakeChip(cc_capable=False)
    with pytest.raises(DeviceError):
        chip.query_cc_mode()
    with pytest.raises(DeviceError):
        chip.set_cc_mode("on")


def test_fake_backend_enumeration_shape():
    set_backend(fake_backend(n_chips=4, n_switches=2))
    chips, err = device.find_tpus()
    assert err is None
    # find_tpus returns chips and switches (like find_gpus returns all
    # devices, reference main.py:128-131); switches identified by predicate.
    assert len(chips) == 6
    assert sum(c.is_ici_switch() for c in chips) == 2
    assert len(device.find_ici_switches()) == 2


def test_fake_backend_enum_error():
    from tpu_cc_manager.device.fake import FakeBackend

    set_backend(FakeBackend(enum_error="no accel driver"))
    chips, err = device.find_tpus()
    assert chips == [] and err == "no accel driver"


# ------------------------------------------------------------- state store
def test_state_store_staged_vs_effective(tmp_path):
    store = ModeStateStore(str(tmp_path))
    assert store.effective("/dev/accel0", "cc") == "off"
    store.stage("/dev/accel0", "cc", "on")
    assert store.effective("/dev/accel0", "cc") == "off"
    assert store.staged("/dev/accel0", "cc") == "on"
    store.commit("/dev/accel0")
    assert store.effective("/dev/accel0", "cc") == "on"
    # durable across store instances (resumable flip, SURVEY.md §7.4)
    store2 = ModeStateStore(str(tmp_path))
    assert store2.effective("/dev/accel0", "cc") == "on"


# ------------------------------------------------------------ sysfs backend
def make_accel_tree(root, n=2, vendor="0x1ae0", device_id="0x0063", kinds=None):
    sysfs = root / "sys_class_accel"
    dev = root / "dev"
    dev.mkdir(exist_ok=True)
    for i in range(n):
        d = sysfs / f"accel{i}" / "device"
        d.mkdir(parents=True)
        (d / "vendor").write_text(vendor + "\n")
        (d / "device").write_text(device_id + "\n")
        if kinds and kinds[i]:
            (d / "kind").write_text(kinds[i] + "\n")
        (dev / f"accel{i}").write_text("")  # stand-in for the char device
    return str(sysfs), str(dev)


def test_sysfs_backend_enumerates_google_chips(tmp_path):
    sysfs, dev = make_accel_tree(tmp_path, n=3)
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev, state_dir=str(tmp_path / "st"))
    chips, err = be.find_tpus()
    assert err is None
    assert [c.path for c in chips] == [dev + f"/accel{i}" for i in range(3)]
    assert all(c.name == "tpu-v5p" for c in chips)
    assert all(c.is_cc_query_supported for c in chips)


def test_sysfs_backend_skips_foreign_vendor(tmp_path):
    sysfs, dev = make_accel_tree(tmp_path, n=2, vendor="0x10de")
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev, state_dir=str(tmp_path / "st"))
    chips, err = be.find_tpus()
    assert chips == [] and err is None


def test_sysfs_backend_capability_allowlist(tmp_path, monkeypatch):
    # analog of CC_CAPABLE_DEVICE_IDS filtering (cc-manager.sh:102-109)
    sysfs, dev = make_accel_tree(tmp_path, n=2, device_id="0x005e")
    monkeypatch.setenv("CC_CAPABLE_DEVICE_IDS", "0x0063,0x0062")
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev, state_dir=str(tmp_path / "st"))
    chips, _ = be.find_tpus()
    assert len(chips) == 2
    assert not any(c.is_cc_query_supported for c in chips)
    monkeypatch.setenv("CC_CAPABLE_DEVICE_IDS", "0x005E")  # case-insensitive hex
    chips, _ = be.find_tpus()
    assert all(c.is_cc_query_supported for c in chips)


def test_sysfs_backend_ici_switch_kind(tmp_path):
    sysfs, dev = make_accel_tree(tmp_path, n=3, kinds=[None, None, "ici-switch"])
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev, state_dir=str(tmp_path / "st"))
    chips, _ = be.find_tpus()
    assert len(chips) == 2  # switches excluded from find_tpus
    switches = be.find_ici_switches()
    assert len(switches) == 1 and switches[0].name == "ici-switch"
    assert switches[0].is_ici_query_supported
    assert not switches[0].is_cc_query_supported


def test_wait_ready_backoff_detects_fast_reset_quickly(tmp_path):
    """Adaptive wait_ready polling (ISSUE 4 satellite): a device that
    becomes healthy ~150ms after reset is detected well under the old
    mandatory 0.5s sleep floor — the saving the parallel flip pipeline
    multiplies across every chip."""
    import threading
    import time

    sysfs, dev = make_accel_tree(tmp_path, n=1)
    health = tmp_path / "sys_class_accel" / "accel0" / "health"
    health.write_text("bad\n")
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev,
                         state_dir=str(tmp_path / "st"))
    (chip,), _ = be.find_tpus()

    def heal():
        time.sleep(0.15)
        health.write_text("ok\n")

    t = threading.Thread(target=heal)
    t.start()
    t0 = time.monotonic()
    chip.wait_ready(timeout_s=5)
    elapsed = time.monotonic() - t0
    t.join()
    # 0.05+0.1+0.2+... backoff lands within ~0.35s of the heal; the old
    # fixed poll couldn't return before 0.5s
    assert elapsed < 0.5, elapsed


def test_wait_ready_backoff_clamps_to_deadline(tmp_path):
    """A never-ready device times out at ~timeout_s, not at the next
    backoff multiple past it."""
    import time

    sysfs, dev = make_accel_tree(tmp_path, n=1)
    (tmp_path / "sys_class_accel" / "accel0" / "health").write_text("bad\n")
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev,
                         state_dir=str(tmp_path / "st"))
    (chip,), _ = be.find_tpus()
    t0 = time.monotonic()
    with pytest.raises(DeviceError):
        chip.wait_ready(timeout_s=0.3)
    elapsed = time.monotonic() - t0
    assert 0.25 <= elapsed < 0.8, elapsed


def test_sysfs_chip_full_mode_cycle(tmp_path):
    sysfs, dev = make_accel_tree(tmp_path, n=1)
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev, state_dir=str(tmp_path / "st"))
    (chip,), _ = be.find_tpus()
    assert chip.query_cc_mode() == "off"
    chip.set_cc_mode("devtools")
    assert chip.query_cc_mode() == "off"
    chip.reset()
    chip.wait_ready(timeout_s=2)
    assert chip.query_cc_mode() == "devtools"
