"""AioBridge.gather's fail-secure join (ISSUE 17 satellite).

The static ``async-exception`` rule checks the gather-settles-everything
contract (docs/io.md §"The async core") over the call graph; these
tests pin it dynamically: an exception raised ON the loop thread
mid-gather must not abandon the other in-flight futures — every future
settles BEFORE the first exception propagates to the joining thread,
so no write is left in an unknown state behind the caller's back (the
flip path's join depends on exactly this).
"""

import threading
import time

import pytest

from tpu_cc_manager.k8s.aio_bridge import AioBridge


@pytest.fixture()
def bridge():
    # a dedicated loop per test: the process-wide get_bridge() singleton
    # must not inherit test wreckage
    b = AioBridge(name="test-gather-loop")
    yield b
    b.shutdown()


class _Boom(RuntimeError):
    pass


def test_gather_settles_everything_before_raising(bridge):
    """A mid-gather loop-thread exception: the slow sibling still runs
    to completion before gather re-raises — nothing is abandoned."""
    import asyncio

    release = threading.Event()
    slow_done = threading.Event()

    async def fast():
        return "fast"

    async def boom():
        raise _Boom("mid-gather failure on the loop thread")

    async def slow():
        while not release.is_set():
            await asyncio.sleep(0.005)
        slow_done.set()
        return "slow"

    futs = [bridge.submit(fast), bridge.submit(boom), bridge.submit(slow)]

    # let fast+boom settle while slow is genuinely still in flight,
    # then release it from a side thread mid-join
    t = threading.Timer(0.15, release.set)
    t.start()
    try:
        with pytest.raises(_Boom):
            bridge.gather(futs, timeout=10)
    finally:
        t.cancel()
        release.set()

    # the contract: by the time gather raised, EVERY future had settled
    assert all(f.done() for f in futs)
    assert slow_done.is_set()
    assert futs[2].result(timeout=0) == "slow"


def test_gather_first_exception_wins_after_all_settle(bridge):
    """Two failures: the one earliest in list order propagates, and the
    other is still retrievable from its (settled) future."""

    async def boom_a():
        raise _Boom("a")

    async def boom_b():
        raise ValueError("b")

    async def ok():
        return 42

    futs = [bridge.submit(boom_a), bridge.submit(boom_b), bridge.submit(ok)]
    with pytest.raises(_Boom, match="a"):
        bridge.gather(futs, timeout=10)
    assert all(f.done() for f in futs)
    with pytest.raises(ValueError, match="b"):
        futs[1].result(timeout=0)
    assert futs[2].result(timeout=0) == 42


def test_gather_empty_and_all_success(bridge):
    assert bridge.gather([], timeout=1) == []

    async def ok(n):
        return n

    futs = [bridge.submit(ok, n) for n in range(5)]
    assert bridge.gather(futs, timeout=10) == list(range(5))


def test_gather_blocking_callable_mixed_with_coroutines(bridge):
    """submit() routes plain callables to the loop's executor; gather
    joins the mixed batch under the same settle-first contract."""
    started = threading.Event()

    def blocking_side():
        started.set()
        time.sleep(0.05)
        return "side"

    async def boom():
        raise _Boom("coroutine failed while the side callable ran")

    futs = [bridge.submit(blocking_side), bridge.submit(boom)]
    with pytest.raises(_Boom):
        bridge.gather(futs, timeout=10)
    assert started.is_set()
    assert all(f.done() for f in futs)
    assert futs[0].result(timeout=0) == "side"
