"""Fleet-scale control-plane validation (VERDICT r4 weak #2): the
QPS/Burst flow control exists for "thousands of nodes", but nothing
past 32 validated it. These scenarios drive a 256-node fleet — 8x the
bench pool — through ONE controller over the real HTTP client with
the manifests' QPS=50, and assert the control plane stays inside its
operating envelope: scans converge well inside the interval, /report
answers promptly, the node-watch pump coalesces a 256-node label
storm instead of thrashing, and the token bucket's throttle wait is a
measured histogram (tpu_cc_kube_throttle_wait_seconds), not a guess.

No per-node agents run here: 256 reactive agent threads would swamp
the 1-core sandbox and measure the sandbox, not the controller. The
nodes carry pre-set labels/annotations; the cost under test is the
control plane's own (list + audit + status writes + flow control).
"""

import json
import threading
import time


from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig
from tpu_cc_manager.k8s.objects import make_node

N_NODES = 256
N_POLICIES = 8
#: the shipped controller manifests' flow-control setting
QPS = 50.0


def _client(server, qps=QPS):
    return HttpKubeClient(
        KubeConfig("127.0.0.1", server.port, use_tls=False), qps=qps
    )


def _populate(store, n=N_NODES, pools=N_POLICIES, mode="on"):
    """n nodes spread over ``pools`` pools, converged at ``mode``, each
    carrying a doctor verdict annotation (so the doctor aggregation
    path — a per-node JSON parse — is on the measured path too)."""
    names = []
    verdict = json.dumps({"ok": True, "checks": [], "ts": 1})
    for i in range(n):
        name = f"sc{i % pools}-{i:04d}"
        store.add_node(make_node(name, labels={
            L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
            "scale.pool": f"p{i % pools}",
            L.CC_MODE_LABEL: mode,
            L.CC_MODE_STATE_LABEL: mode,
        }, annotations={L.DOCTOR_ANNOTATION: verdict}))
        names.append(name)
    return names


def test_fleet_scan_256_nodes_inside_interval():
    """One fleet scan over 256 nodes (list + analyze + evidence audit +
    doctor aggregation + problems digest) through the QPS=50 client
    must finish well inside the 30s interval the manifests ship."""
    from tpu_cc_manager.fleet import FleetController

    with FakeApiServer() as server:
        _populate(server.store)
        c = FleetController(_client(server), interval_s=30, port=0)
        t0 = time.monotonic()
        report = c.scan_once()
        dur = time.monotonic() - t0
        assert report["nodes"] == N_NODES
        assert dur < 15.0, (
            f"fleet scan took {dur:.1f}s over {N_NODES} nodes — "
            "more than half the 30s interval"
        )
        # the scan is list-driven: the flow-control budget is a
        # handful of paginated lists, nowhere near 50 QPS — no
        # meaningful throttle wait expected
        assert c.kube.throttle_wait_s_total < 1.0


def test_policy_scan_256_nodes_8_policies_inside_interval():
    """8 policies x 32 nodes each: one scan derives all statuses and
    publishes them inside half the interval; every pool reads
    Converged (no rollouts — the cost under test is the scan)."""
    from tpu_cc_manager.policy import PolicyController

    with FakeApiServer() as server:
        _populate(server.store)
        for p in range(N_POLICIES):
            server.store.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
                "apiVersion": f"{L.POLICY_GROUP}/{L.POLICY_VERSION}",
                "kind": L.POLICY_KIND,
                "metadata": {"name": f"scale-{p}"},
                "spec": {"mode": "on",
                         "nodeSelector": f"scale.pool=p{p}"},
            })
        c = PolicyController(_client(server), interval_s=30, port=0)
        t0 = time.monotonic()
        report = c.scan_once()
        dur = time.monotonic() - t0
        assert report["scanned"] == N_POLICIES
        assert report["claimed_nodes"] == N_NODES
        for p in range(N_POLICIES):
            st = report["policies"][f"scale-{p}"]
            assert st["phase"] == "Converged", st
            assert st["nodes"] == N_NODES // N_POLICIES
        assert dur < 15.0, (
            f"policy scan took {dur:.1f}s — more than half the "
            "30s interval"
        )


def test_report_latency_with_256_node_fleet():
    """/report (the operator's fleet view) must serialize a 256-node
    report promptly — the route serves the last scan's dict, so this
    bounds the JSON cost an operator's curl pays."""
    from tpu_cc_manager.fleet import FleetController

    with FakeApiServer() as server:
        _populate(server.store)
        c = FleetController(_client(server), interval_s=30, port=0)
        c.scan_once()
        t0 = time.monotonic()
        body = json.dumps(c.last_report)
        dur = time.monotonic() - t0
        assert len(body) > 1000
        assert dur < 1.0, f"/report serialization took {dur:.2f}s"


def test_throttle_wait_is_a_measured_histogram():
    """A request storm past the bucket's burst must (a) be throttled
    to ~qps and (b) surface the waits on the controller's histogram
    (tpu_cc_kube_throttle_wait_seconds) and the client's totals — the
    flow control's whole point, finally measured."""
    from tpu_cc_manager.fleet import FleetController

    with FakeApiServer() as server:
        _populate(server.store, n=4)
        # qps must sit well under the sandbox's natural HTTP rate
        # (~20-25 req/s on 1 core) or the storm never drains the
        # bucket and nothing is measured
        kube = _client(server, qps=10.0)  # burst 20
        c = FleetController(kube, interval_s=30, port=0)
        # 45 sequential reads: ~20 ride the burst, the rest wait
        # ~1/qps each
        t0 = time.monotonic()
        for _ in range(45):
            kube.get_node("sc0-0000")
        elapsed = time.monotonic() - t0
        assert elapsed >= 2.0, (
            f"45 reqs at qps=10 burst=20 finished in {elapsed:.2f}s — "
            "the bucket is not limiting"
        )
        assert kube.throttle_waits >= 10, kube.throttle_waits
        assert kube.throttle_wait_s_total > 0.5
        hist = c.metrics.kube_throttle_wait
        assert hist._total >= 45  # zero-wait requests observed too
        assert "tpu_cc_kube_throttle_wait_seconds" in c.metrics.render()


def test_node_watch_pump_coalesces_256_node_churn():
    """A 256-node label storm through the shared node-watch pump must
    wake the fleet controller (divergence surfaces within the
    coalescing gap + one scan, NOT the 1h interval) without scan
    thrashing — the gap bounds watch-driven scans, so 256 changes
    collapse into a couple of scans."""
    from tpu_cc_manager.fleet import FleetController

    with FakeApiServer() as server:
        names = _populate(server.store)
        c = FleetController(_client(server), interval_s=3600, port=0)
        c.min_scan_gap_s = 1.0
        scans = []
        orig = c.scan_once

        def counting():
            scans.append(time.monotonic())
            return orig()

        c.scan_once = counting
        t = threading.Thread(target=c.run, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 10
            while not scans and time.monotonic() < deadline:
                time.sleep(0.05)
            assert scans, "controller never scanned"
            baseline = len(scans)
            # the storm: every node flips desired to off
            t0 = time.monotonic()
            for n in names:
                server.store.set_node_labels(
                    n, {L.CC_MODE_LABEL: "off"}
                )
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                r = c.last_report
                if r and len(r.get("needs_flip") or []) == N_NODES:
                    break
                time.sleep(0.1)
            lag = time.monotonic() - t0
            r = c.last_report
            assert len(r.get("needs_flip") or []) == N_NODES
            assert lag < 15.0, f"watch-pump lag {lag:.1f}s"
            # coalescing: 256 label changes must not mean 256 scans
            storm_scans = len(scans) - baseline
            assert storm_scans <= 8, (
                f"{storm_scans} scans for one 256-node storm — the "
                "coalescing gap is not coalescing"
            )
        finally:
            c.stop()
            t.join(timeout=5)


def test_shared_client_feeds_both_controllers_histograms():
    """Two controllers sharing ONE client (combined-process embedders)
    must BOTH see the flow-control waits — the observer is a list,
    not a last-writer-wins slot."""
    from tpu_cc_manager.fleet import FleetController
    from tpu_cc_manager.policy import PolicyController

    with FakeApiServer() as server:
        _populate(server.store, n=2)
        kube = _client(server, qps=50.0)
        f = FleetController(kube, interval_s=30, port=0)
        p = PolicyController(kube, interval_s=30, port=0)
        for _ in range(5):
            kube.get_node("sc0-0000")
        assert f.metrics.kube_throttle_wait._total >= 5
        assert p.metrics.kube_throttle_wait._total >= 5
