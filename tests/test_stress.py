"""Concurrency stress tests — the race-detection analog the reference
never had (SURVEY.md §5.2: CI never runs `go test -race`; its one shared
structure is hand-synchronized). Python has no race detector, so these
tests hammer the shared structures from many threads and assert the
invariants that a race would break.
"""

import threading
import time

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.statefile import ModeStateStore
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.watch import SyncableModeConfig


def test_mailbox_coalescing_under_concurrent_setters():
    """N writers race Set(); the single consumer must (a) never observe a
    value nobody wrote, (b) terminate, and (c) end on the final value."""
    box = SyncableModeConfig()
    n_writers, n_values = 8, 200
    written = set()
    lock = threading.Lock()

    def writer(wid):
        for i in range(n_values):
            v = f"w{wid}-{i}"
            with lock:
                written.add(v)
            box.set(v)

    threads = [
        threading.Thread(target=writer, args=(w,)) for w in range(n_writers)
    ]
    observed = []

    def consumer():
        while True:
            got, value = box.get(timeout=0.5)
            if not got:
                return  # writers done and no pending value
            observed.append(value)

    c = threading.Thread(target=consumer)
    for t in threads:
        t.start()
    c.start()
    for t in threads:
        t.join()
    # the sentinel write is the last value: everyone must end on it
    box.set("FINAL")
    c.join(timeout=10)
    assert not c.is_alive()
    assert observed, "consumer never observed anything"
    assert observed[-1] == "FINAL"
    # every observed value was actually written (no torn/phantom reads)
    assert set(observed[:-1]) <= written
    # coalescing happened: far fewer observations than writes
    assert len(observed) < n_writers * n_values


def test_agent_survives_label_storm(tmp_path):
    """Rapid desired-label churn: the agent must coalesce, never crash,
    and converge on the final value."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig
    from tpu_cc_manager.device.fake import fake_backend

    kube = FakeKube()
    kube.add_node(make_node("storm", labels={L.CC_MODE_LABEL: "off"}))
    cfg = AgentConfig(
        node_name="storm",
        default_mode="off",
        readiness_file=str(tmp_path / "ready"),
        health_port=0,
        drain_strategy="none",
    )
    backend = fake_backend(n_chips=2)
    agent = CCManagerAgent(kube, cfg, backend=backend)
    agent.watcher.watch_timeout_s = 2
    agent.watcher.backoff_s = 0.05
    runner = threading.Thread(target=agent.run, daemon=True)
    runner.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            labels = kube.get_node("storm")["metadata"]["labels"]
            if labels.get(L.CC_MODE_STATE_LABEL) == "off":
                break
            time.sleep(0.02)
        modes = ["on", "off", "devtools", "ici"]
        n_writes = 300
        for i in range(n_writes):
            kube.set_node_labels(
                "storm", {L.CC_MODE_LABEL: modes[i % len(modes)]}
            )
        kube.set_node_labels("storm", {L.CC_MODE_LABEL: "devtools"})
        deadline = time.monotonic() + 20
        ok = False
        while time.monotonic() < deadline:
            labels = kube.get_node("storm")["metadata"]["labels"]
            if labels.get(L.CC_MODE_STATE_LABEL) == "devtools":
                # settled: no reconcile in flight and mailbox drained
                ok = all(
                    c.query_cc_mode() == "devtools" for c in backend.chips
                )
                if ok:
                    break
            time.sleep(0.05)
        assert ok, "agent never converged on the final mode"
        # coalescing absorbed most of the storm
        assert agent.reconcile_count < n_writes / 2
    finally:
        agent.shutdown()
        runner.join(timeout=10)
        assert not runner.is_alive()


def test_statefile_concurrent_stage_commit(tmp_path):
    """Writers race stage/commit/discard on one device; every read must
    return a well-formed mode (atomic writes, no torn state)."""
    store = ModeStateStore(str(tmp_path))
    path = "/dev/accel0"
    valid = {"on", "off", "devtools"}
    errors = []
    stop = threading.Event()

    def stager():
        i = 0
        while not stop.is_set():
            store.stage(path, "cc", ["on", "devtools"][i % 2])
            i += 1

    def committer():
        while not stop.is_set():
            store.commit(path)

    def discarder():
        while not stop.is_set():
            store.discard(path)

    def reader():
        while not stop.is_set():
            for fn in (store.effective, store.staged):
                v = fn(path, "cc")
                if v not in valid:
                    errors.append(v)

    threads = [
        threading.Thread(target=f)
        for f in (stager, committer, discarder, reader, reader)
    ]
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
        assert not t.is_alive()
    assert not errors, f"torn/invalid reads observed: {errors[:5]}"
    # effective must equal one of the staged values ever written (or off)
    assert store.effective(path, "cc") in valid


def test_concurrent_set_node_labels_no_lost_updates():
    """FakeKube label patches from many threads must all land (the store
    is the coordination fabric; lost updates would corrupt the protocol)."""
    kube = FakeKube()
    kube.add_node(make_node("n"))
    n_threads, n_keys = 8, 25

    def patcher(tid):
        for k in range(n_keys):
            kube.set_node_labels("n", {f"stress/{tid}-{k}": str(k)})

    threads = [
        threading.Thread(target=patcher, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    labels = kube.get_node("n")["metadata"]["labels"]
    stress_keys = [k for k in labels if k.startswith("stress/")]
    assert len(stress_keys) == n_threads * n_keys


def test_policy_controller_survives_spec_churn():
    """Operator churn on the declarative surface: policy specs flip
    repeatedly while the controller's watch+scan loop and real reactive
    node 'agents' run. The controller must neither crash nor wedge, and
    once the churn stops the fleet converges to the final spec."""
    from tpu_cc_manager import labels as L
    from tpu_cc_manager.k8s.client import ApiException
    from tpu_cc_manager.policy import PolicyController

    G, V, P = L.POLICY_GROUP, L.POLICY_VERSION, L.POLICY_PLURAL
    kube = FakeKube()
    names = [f"ch-{i}" for i in range(4)]
    for n in names:
        kube.add_node(make_node(n, labels={
            L.TPU_ACCELERATOR_LABEL: "tpu-v5e-slice",
            L.CC_MODE_LABEL: "off",
            L.CC_MODE_STATE_LABEL: "off",
        }))
    kube.add_custom(G, P, {
        "apiVersion": f"{G}/{V}", "kind": L.POLICY_KIND,
        "metadata": {"name": "churny"},
        "spec": {"mode": "off",
                 "nodeSelector": L.TPU_ACCELERATOR_LABEL,
                 "strategy": {"maxUnavailable": 4,
                              "groupTimeoutSeconds": 10}},
    })

    stop = threading.Event()

    def agent_sim():
        while not stop.is_set():
            for n in names:
                labels = kube.get_node(n)["metadata"]["labels"]
                desired = labels.get(L.CC_MODE_LABEL)
                if desired and labels.get(L.CC_MODE_STATE_LABEL) != desired:
                    kube.set_node_labels(
                        n, {L.CC_MODE_STATE_LABEL: desired})
            time.sleep(0.01)

    sim = threading.Thread(target=agent_sim, daemon=True)
    sim.start()
    ctrl = PolicyController(kube, interval_s=0.3, poll_s=0.02)
    t = threading.Thread(target=ctrl.run, daemon=True)
    t.start()
    try:
        # churn: flip the spec through the mode vocabulary rapidly
        modes = ["on", "devtools", "ici", "on", "off", "devtools"]
        for m in modes:
            kube.patch_cluster_custom(G, V, P, "churny",
                                      {"spec": {"mode": m}})
            time.sleep(0.15)
        final = "on"
        kube.patch_cluster_custom(G, V, P, "churny",
                                  {"spec": {"mode": final}})
        deadline = time.monotonic() + 30
        done = False
        while time.monotonic() < deadline and not done:
            labels_ok = all(
                kube.get_node(n)["metadata"]["labels"].get(
                    L.CC_MODE_STATE_LABEL) == final
                for n in names
            )
            try:
                phase = kube.get_cluster_custom(
                    G, V, P, "churny").get("status", {}).get("phase")
            except ApiException:
                phase = None
            done = labels_ok and phase == "Converged"
            time.sleep(0.1)
        assert done, "fleet never converged to the final spec"
        assert ctrl.healthy
    finally:
        stop.set()
        sim.join(timeout=5)
        ctrl.stop()
        t.join(timeout=10)


def test_full_control_plane_soak():
    """Everything at once: 12 real agents (two 4-host slices + 4 solo),
    the policy controller (watch + rollouts), and the fleet controller,
    all live while the declarative mode flips twice. Ends converged,
    audit-clean, both controllers healthy."""
    from tpu_cc_manager import labels as L
    from tpu_cc_manager.fleet import FleetController, fleet_problems
    from tpu_cc_manager.k8s.client import ApiException
    from tpu_cc_manager.policy import PolicyController

    G, V, P = L.POLICY_GROUP, L.POLICY_VERSION, L.POLICY_PLURAL
    kube = FakeKube()
    names = (
        [f"sA-{i}" for i in range(4)]
        + [f"sB-{i}" for i in range(4)]
        + [f"solo-{i}" for i in range(4)]
    )
    for n in names:
        labels = {
            L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
            L.CC_MODE_LABEL: "off",
            L.CC_MODE_STATE_LABEL: "off",
        }
        if n.startswith("sA-"):
            labels[L.TPU_SLICE_LABEL] = "sA"
        if n.startswith("sB-"):
            labels[L.TPU_SLICE_LABEL] = "sB"
        kube.add_node(make_node(n, labels=labels))

    stop = threading.Event()

    def agent_sim():
        while not stop.is_set():
            for n in names:
                lb = kube.get_node(n)["metadata"]["labels"]
                want = lb.get(L.CC_MODE_LABEL)
                if want and lb.get(L.CC_MODE_STATE_LABEL) != want:
                    time.sleep(0.02)
                    kube.set_node_labels(
                        n, {L.CC_MODE_STATE_LABEL: want})
            time.sleep(0.01)

    sim = threading.Thread(target=agent_sim, daemon=True)
    sim.start()
    kube.add_custom(G, P, {
        "apiVersion": f"{G}/{V}", "kind": L.POLICY_KIND,
        "metadata": {"name": "soak"},
        "spec": {"mode": "on",
                 "nodeSelector": L.TPU_ACCELERATOR_LABEL,
                 "strategy": {"maxUnavailable": 3,
                              "groupTimeoutSeconds": 15}},
    })
    policy = PolicyController(kube, interval_s=0.5, poll_s=0.02)
    fleet = FleetController(kube, interval_s=0.2)
    pt = threading.Thread(target=policy.run, daemon=True)
    ft = threading.Thread(target=fleet.run, daemon=True)
    pt.start()
    ft.start()
    try:
        def converged_to(mode, timeout=30):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if all(
                    kube.get_node(n)["metadata"]["labels"].get(
                        L.CC_MODE_STATE_LABEL) == mode
                    for n in names
                ):
                    return True
                time.sleep(0.1)
            return False

        assert converged_to("on"), "soak: never converged to on"
        kube.patch_cluster_custom(G, V, P, "soak",
                                  {"spec": {"mode": "devtools"}})
        assert converged_to("devtools"), "soak: flip to devtools failed"
        # settle, then the audit must be clean (sim nodes have no
        # evidence, but they also never CLAIM... they do claim success;
        # evidence missing is therefore expected here — filter it, the
        # point of the soak is control-plane health, not the sim's
        # fidelity)
        deadline = time.monotonic() + 10
        report = None
        while time.monotonic() < deadline:
            try:
                report = fleet.scan_once()
                break
            except ApiException:
                time.sleep(0.1)
        assert report is not None
        problems = [
            p for p in fleet_problems(report)
            if not p.startswith("evidence missing")
        ]
        assert problems == [], problems
        assert policy.healthy and fleet.healthy
        # the status phase flips Rolling->Converged on the policy
        # controller's NEXT scan after the last node lands (interval_s
        # cadence): wait for that tick instead of racing it
        deadline = time.monotonic() + 10
        st = kube.get_cluster_custom(G, V, P, "soak")["status"]
        while st["phase"] != "Converged" and time.monotonic() < deadline:
            time.sleep(0.1)
            st = kube.get_cluster_custom(G, V, P, "soak")["status"]
        assert st["phase"] == "Converged"
    finally:
        stop.set()
        sim.join(timeout=5)
        policy.stop()
        fleet.stop()
        pt.join(timeout=10)
        ft.join(timeout=10)


def test_leader_churn_soak():
    """Election under churn: three controller replicas with aggressive
    lease timing while the leader is repeatedly killed. Invariants:
    at most one leader at any sampled instant, scans never come from a
    non-leader, and the policy still converges through the churn."""
    import threading
    import time

    from tpu_cc_manager import labels as L
    from tpu_cc_manager.k8s.fake import FakeKube
    from tpu_cc_manager.k8s.objects import make_node
    from tpu_cc_manager.leader import LeaderElector
    from tpu_cc_manager.policy import PolicyController

    kube = FakeKube()
    kube.add_node(make_node("n1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"}))
    kube.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
        "apiVersion": f"{L.POLICY_GROUP}/{L.POLICY_VERSION}",
        "kind": L.POLICY_KIND, "metadata": {"name": "churn"},
        "spec": {"mode": "on",
                 "nodeSelector": L.TPU_ACCELERATOR_LABEL},
    })
    stop = threading.Event()

    def agent():
        while not stop.is_set():
            labels = kube.get_node("n1")["metadata"]["labels"]
            want = labels.get(L.CC_MODE_LABEL)
            if want and labels.get(L.CC_MODE_STATE_LABEL) != want:
                kube.set_node_labels("n1", {L.CC_MODE_STATE_LABEL: want})
            time.sleep(0.01)

    threading.Thread(target=agent, daemon=True).start()

    controllers = {}
    threads = {}
    bad_scans = []

    def make(ident):
        elector = LeaderElector(
            kube, name="tpu-cc-policy-controller", identity=ident,
            lease_duration_s=0.4, renew_period_s=0.08,
            retry_period_s=0.04,
        )
        c = PolicyController(kube, interval_s=0.05, poll_s=0.02,
                             port=0, leader_elector=elector)
        orig = c.scan_once

        def guarded(wait_rollout=True):
            # grant the deposition window: run()'s gate and this check
            # straddle the elector thread's demotion, and a scan that
            # STARTED while leading is legitimate (same inherent gap as
            # the dual-leader tolerance above)
            if (not elector.is_leader
                    and time.monotonic() - elector.deposed_at > 0.5):
                bad_scans.append(ident)
            return orig(wait_rollout=wait_rollout)

        c.scan_once = guarded
        controllers[ident] = c
        t = threading.Thread(target=c.run, daemon=True)
        threads[ident] = t
        t.start()

    for ident in ("r0", "r1", "r2"):
        make(ident)

    leaders_seen = set()
    overlap_started = None
    sustained_overlaps = []
    start = time.monotonic()
    deadline = start + 8
    kills = 0
    while time.monotonic() < deadline:
        leading = [i for i, c in controllers.items()
                   if c.leader_elector.is_leader]
        # a BRIEF dual-true window is inherent to lease elections (a
        # GIL-starved leader learns of its deposition at its next
        # failed renew — client-go has the same gap); what must never
        # happen is SUSTAINED dual leadership beyond a lease duration
        now = time.monotonic()
        if len(leading) > 1:
            if overlap_started is None:
                overlap_started = now
            elif now - overlap_started > 0.4:
                sustained_overlaps.append(tuple(leading))
        else:
            overlap_started = None
        if leading:
            leaders_seen.add(leading[0])
            if kills < 2 and now - start > (kills + 1) * 2.5:
                # kill the current leader (clean stop releases the
                # lease); a standby must take over
                controllers[leading[0]].stop()
                kills += 1
        time.sleep(0.02)

    try:
        assert sustained_overlaps == [], (
            f"sustained dual leadership: {sustained_overlaps}"
        )
        assert len(leaders_seen) >= 2, "failover never happened"
        assert bad_scans == [], f"non-leader scanned: {bad_scans}"
        st = (kube.get_cluster_custom(
            L.POLICY_GROUP, L.POLICY_VERSION, L.POLICY_PLURAL, "churn"
        ).get("status") or {})
        assert st.get("phase") == "Converged", st
    finally:
        stop.set()
        for c in controllers.values():
            c.stop()
        for t in threads.values():
            t.join(timeout=5)


def test_concurrent_rollout_churn_soak():
    """Round-5 concurrency under churn: four disjoint pools repeatedly
    re-diverged while a controller with multiple worker slots drives
    them and leadership flaps demote/promote it mid-roll. Invariants:
    never more than max_rollouts live workers; no two live workers
    ever share a node; every record on the cluster stays parseable
    (version 1, sane shape); and once the churn stops, every pool
    converges and every record completes."""
    import json
    import threading
    import time

    from tpu_cc_manager import labels as L
    from tpu_cc_manager.k8s.fake import FakeKube
    from tpu_cc_manager.k8s.objects import make_node
    from tpu_cc_manager.policy import PolicyController
    from tpu_cc_manager.rollout import load_rollout_records

    N_POOLS = 4
    kube = FakeKube()
    names = []
    for p in range(N_POOLS):
        for i in range(2):
            name = f"cs{p}-{i}"
            names.append(name)
            kube.add_node(make_node(name, labels={
                L.TPU_ACCELERATOR_LABEL: "v5p", "churn.pool": f"p{p}",
                L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"}))
        kube.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
            "apiVersion": f"{L.POLICY_GROUP}/{L.POLICY_VERSION}",
            "kind": L.POLICY_KIND, "metadata": {"name": f"cp{p}"},
            "spec": {"mode": "on", "nodeSelector": f"churn.pool=p{p}",
                     "strategy": {"maxUnavailable": 2,
                                  "groupTimeoutSeconds": 10}},
        })
    stop = threading.Event()

    def agent():
        while not stop.is_set():
            for n in names:
                labels = kube.get_node(n)["metadata"]["labels"]
                want = labels.get(L.CC_MODE_LABEL)
                if want and labels.get(L.CC_MODE_STATE_LABEL) != want:
                    kube.set_node_labels(
                        n, {L.CC_MODE_STATE_LABEL: want})
            time.sleep(0.01)

    threading.Thread(target=agent, daemon=True).start()

    c = PolicyController(kube, interval_s=0.05, poll_s=0.02, port=0,
                         adopt_after_s=0.3, max_rollouts=3)
    run_t = threading.Thread(target=c.run, daemon=True)
    run_t.start()

    violations = []
    deadline = time.monotonic() + 8
    last_churn = 0.0
    churn_i = 0
    while time.monotonic() < deadline:
        # invariant sampling
        with c._active_lock:
            workers = [dict(w) for w in c._workers.values()]
        if len(workers) > c.max_rollouts:
            violations.append(f"{len(workers)} workers > slots")
        seen_nodes: set = set()
        for w in workers:
            if w["nodes"] & seen_nodes:
                violations.append(
                    f"two live workers share node(s) "
                    f"{sorted(w['nodes'] & seen_nodes)}"
                )
            seen_nodes |= w["nodes"]
        for rec, _ in load_rollout_records(kube, kube.list_nodes(None)):
            if rec.get("version") not in (None, 1):
                violations.append(f"record version {rec.get('version')}")
            if not isinstance(rec.get("groups"), dict):
                violations.append("record without groups dict")
        # churn: every ~0.5s, re-diverge a pool and flap leadership
        now = time.monotonic()
        if now - last_churn > 0.5:
            last_churn = now
            # deterministic rotation: every pool gets churned mid-roll
            # (a timing-derived pick can alias to half the pools)
            p = churn_i % N_POOLS
            churn_i += 1
            for i in range(2):
                kube.set_node_labels(f"cs{p}-{i}", {
                    L.CC_MODE_LABEL: "off",
                    L.CC_MODE_STATE_LABEL: "off"})
            c._on_demoted()
            time.sleep(0.05)
            c._on_promoted()
        time.sleep(0.02)

    try:
        assert not violations, violations[:5]
        # churn over: everything converges and every record completes
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            labels_ok = all(
                kube.get_node(n)["metadata"]["labels"].get(
                    L.CC_MODE_STATE_LABEL) == "on"
                for n in names
            )
            recs = load_rollout_records(kube, kube.list_nodes(None))
            recs_done = all(r.get("complete") for r, _ in recs)
            if labels_ok and recs_done and not c._workers:
                break
            time.sleep(0.1)
        assert not c._workers, "worker slot leaked past convergence"
        assert all(
            kube.get_node(n)["metadata"]["labels"].get(
                L.CC_MODE_STATE_LABEL) == "on"
            for n in names
        ), "pools never reconverged after churn"
        for rec, anchor in load_rollout_records(
                kube, kube.list_nodes(None)):
            assert rec.get("complete"), (
                f"record {rec.get('id')} on {anchor} never completed: "
                f"{json.dumps(rec)[:300]}"
            )
    finally:
        c.stop()
        run_t.join(timeout=5)
        stop.set()
    assert not run_t.is_alive(), "controller run loop hung on shutdown"
