"""The bench trend gate (scripts/bench_trend.py) — VERDICT r3 weak #4:
the next silent >2x regression must fail CI unless it comes with an
explanation."""

import importlib.util
import json
import os

spec = importlib.util.spec_from_file_location(
    "bench_trend",
    os.path.join(os.path.dirname(__file__), "..", "scripts",
                 "bench_trend.py"),
)
bench_trend = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench_trend)


def _write(root, n, value, fpm, extras=None, envelope=False):
    body = {"metric": "pool32_reconcile_p50_s", "value": value,
            "unit": "s", "vs_baseline": 1.0,
            "extras": dict({"flips_per_min": fpm}, **(extras or {}))}
    path = root / f"BENCH_r{n:02d}.json"
    if envelope:
        # the driver's wrapper shape: bench JSON inside "tail" text
        path.write_text(json.dumps({
            "n": n, "rc": 0,
            "tail": "some log noise\n" + json.dumps(body) + "\n",
        }))
    else:
        path.write_text(json.dumps(body))
    return path


def test_within_budget_passes(tmp_path):
    _write(tmp_path, 1, 0.10, 1000)
    _write(tmp_path, 2, 0.15, 800)
    assert bench_trend.main(str(tmp_path)) == 0


def test_unexplained_p50_regression_fails(tmp_path):
    _write(tmp_path, 1, 0.10, 1000)
    _write(tmp_path, 2, 0.50, 1000)
    assert bench_trend.main(str(tmp_path)) == 1


def test_unexplained_throughput_regression_fails(tmp_path):
    _write(tmp_path, 1, 0.10, 1000)
    _write(tmp_path, 2, 0.10, 300)
    assert bench_trend.main(str(tmp_path)) == 1


def test_note_in_extras_acknowledges(tmp_path):
    _write(tmp_path, 1, 0.10, 1000)
    _write(tmp_path, 2, 0.50, 1000,
           extras={"regression_note": "added per-flip attestation"})
    assert bench_trend.main(str(tmp_path)) == 0


def test_notes_md_acknowledges(tmp_path):
    _write(tmp_path, 1, 0.10, 1000)
    _write(tmp_path, 2, 0.50, 1000)
    (tmp_path / "BENCH_NOTES.md").write_text(
        "# notes\n\n## r02: slower on purpose\nbecause reasons\n"
    )
    assert bench_trend.main(str(tmp_path)) == 0


def test_driver_envelope_shape_parsed(tmp_path):
    _write(tmp_path, 1, 0.10, 1000, envelope=True)
    _write(tmp_path, 2, 0.50, 1000, envelope=True)
    assert bench_trend.main(str(tmp_path)) == 1


def test_single_file_or_empty_passes(tmp_path):
    assert bench_trend.main(str(tmp_path)) == 0
    _write(tmp_path, 1, 0.10, 1000)
    assert bench_trend.main(str(tmp_path)) == 0


def test_gate_prefers_windowed_flips(tmp_path):
    """Round 5+: when both rounds carry flips_per_min_windowed, the
    gate judges THAT number — a whole-elapsed drop caused by
    setup/teardown dilution (the r03->r04 story) no longer trips it,
    and a real windowed drop does."""
    # un-windowed fell 3x (would trip the old gate) but windowed flat
    # (values above the r07 absolute floor, which is tested separately)
    _write(tmp_path, 1, 0.1, 6000,
           extras={"flips_per_min_windowed": 26000})
    _write(tmp_path, 2, 0.1, 2000,
           extras={"flips_per_min_windowed": 25000})
    assert bench_trend.main(str(tmp_path)) == 0
    # windowed itself fell 3x: trips even though un-windowed is flat
    _write(tmp_path, 3, 0.1, 2000,
           extras={"flips_per_min_windowed": 8200})
    assert bench_trend.main(str(tmp_path)) == 1


def test_windowed_throughput_floor_gate(tmp_path):
    """ISSUE 6 acceptance bar: the newest round's windowed throughput
    must clear the absolute 21k floor (2x the r05 10.7k steady state),
    regardless of trend — and a miss is acknowledgeable through the
    same BENCH_NOTES escape as any regression."""
    _write(tmp_path, 1, 0.1, 2000,
           extras={"flips_per_min_windowed": 22000})
    _write(tmp_path, 2, 0.1, 2000,
           extras={"flips_per_min_windowed": 15000})
    assert bench_trend.main(str(tmp_path)) == 1  # above prev/2, below floor
    (tmp_path / "BENCH_NOTES.md").write_text(
        "## r2\ndegraded sandbox host; see variance note\n")
    assert bench_trend.main(str(tmp_path)) == 0


def test_node_writes_per_flip_ceiling_gate(tmp_path):
    """A silent un-batching regression (writes per flip drifting back
    toward the historical ~5) fails the gate even when every trend
    axis is flat."""
    _write(tmp_path, 1, 0.1, 2000, extras={"node_writes_per_flip": 2.1})
    _write(tmp_path, 2, 0.1, 2000, extras={"node_writes_per_flip": 4.8})
    assert bench_trend.main(str(tmp_path)) == 1
    _write(tmp_path, 2, 0.1, 2000, extras={"node_writes_per_flip": 2.2})
    assert bench_trend.main(str(tmp_path)) == 0


def test_gated_extra_axis_real_chip_regression_fails(tmp_path):
    """The r05 lesson (VERDICT r5 weak #3): the one real-hardware
    number regressed 2.4x and nothing noticed — the extras axes are
    now compared like the headline pair."""
    _write(tmp_path, 1, 0.10, 1000, extras={"real_chip_flip_s": 1.87})
    _write(tmp_path, 2, 0.10, 1000, extras={"real_chip_flip_s": 4.43})
    assert bench_trend.main(str(tmp_path)) == 1


def test_gated_extra_axis_simlab_convergence_fails(tmp_path):
    _write(tmp_path, 1, 0.10, 1000,
           extras={"pool256_convergence_s": 8.0})
    _write(tmp_path, 2, 0.10, 1000,
           extras={"pool256_convergence_s": 30.0})
    assert bench_trend.main(str(tmp_path)) == 1


def test_gated_extra_axis_mixed_era_skips(tmp_path):
    """A CPU-only host (no real_chip number) or a pre-simlab round must
    not fail the comparison — absent on either side skips the axis."""
    _write(tmp_path, 1, 0.10, 1000, extras={"real_chip_flip_s": 1.87})
    _write(tmp_path, 2, 0.10, 1000)  # no hardware this round
    assert bench_trend.main(str(tmp_path)) == 0


def test_gated_extra_axis_noted_regression_passes(tmp_path):
    _write(tmp_path, 1, 0.10, 1000, extras={"real_chip_flip_s": 1.87})
    _write(tmp_path, 2, 0.10, 1000,
           extras={"real_chip_flip_s": 4.43,
                   "regression_note": "firmware reflash mid-bench"})
    assert bench_trend.main(str(tmp_path)) == 0
