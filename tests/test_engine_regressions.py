"""Regression tests for review findings on the engine/device layer."""

import pytest

from tpu_cc_manager.device.base import set_backend
from tpu_cc_manager.device.fake import fake_backend
from tpu_cc_manager.device.tpu import SysfsTpuBackend
from tpu_cc_manager.engine import Drainer, ModeEngine
from tests.test_device import make_accel_tree


class FlakyEvictDrainer(Drainer):
    def __init__(self, fail_evict=False):
        self.fail_evict = fail_evict
        self.events = []

    def evict(self):
        self.events.append("evict")
        if self.fail_evict:
            raise RuntimeError("API blip during evict")

    def reschedule(self):
        self.events.append("reschedule")


def test_evict_failure_still_reschedules_and_reports_failed():
    # always-restore invariant must hold even when evict() itself raises
    # (cc-manager.sh:210-215 parity); the failure is contained and the
    # state label publishes 'failed' (main.py:300-307 parity) rather than
    # the exception escaping with no label written.
    set_backend(fake_backend(n_chips=1))
    states = []
    drainer = FlakyEvictDrainer(fail_evict=True)
    engine = ModeEngine(set_state_label=states.append, drainer=drainer)
    assert engine.set_mode("on") is False
    assert drainer.events == ["evict", "reschedule"]
    assert states == ["failed"]


def test_stale_staged_mode_does_not_leak_into_next_flip(tmp_path):
    # A failed ICI flip leaves ici.staged=on on disk; a later CC flip must
    # NOT promote it (mutual-exclusion invariant).
    sysfs, dev = make_accel_tree(tmp_path, n=1)
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev,
                         state_dir=str(tmp_path / "st"))
    (chip,), _ = be.find_tpus()
    # simulate the crashed/failed ici flip: staged but never committed
    chip.set_ici_mode("on")
    assert be.store.staged(chip.path, "ici") == "on"

    set_backend(be)
    states = []
    engine = ModeEngine(set_state_label=states.append, evict_components=False)
    assert engine.set_mode("on") is True
    assert chip.query_cc_mode() == "on"
    assert chip.query_ici_mode() == "off"  # stale intent discarded
    assert states == ["on"]


def test_cross_domain_transition_single_drain_single_reset():
    # ici=on -> cc=on used to cost two evict/restore cycles and two resets
    # per chip in the reference (main.py:534-559); the planner does one.
    backend = fake_backend(n_chips=2, ici_mode="on")
    set_backend(backend)
    states = []
    drainer = FlakyEvictDrainer()
    engine = ModeEngine(set_state_label=states.append, drainer=drainer)
    assert engine.set_mode("on") is True
    assert drainer.events == ["evict", "reschedule"]  # exactly one cycle
    for c in backend.chips:
        assert c.resets == 1  # both domains committed by one reset
        assert c.query_cc_mode() == "on"
        assert c.query_ici_mode() == "off"


def test_enum_error_from_bad_allowlist_is_contained(tmp_path, monkeypatch):
    # malformed CC_CAPABLE_DEVICE_IDS -> (devices=[], error) tuple, not a
    # raw ValueError escaping find_tpus()
    sysfs, dev = make_accel_tree(tmp_path, n=1)
    monkeypatch.setenv("CC_CAPABLE_DEVICE_IDS", "v5p;0x63")
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev,
                         state_dir=str(tmp_path / "st"))
    chips, err = be.find_tpus()
    assert chips == []
    assert "CC_CAPABLE_DEVICE_IDS" in err


def test_disk_full_staging_publishes_failed(tmp_path, monkeypatch):
    # Simulated ENOSPC while staging a mode: the store raises DeviceError
    # (not bare OSError), the engine contains it, components are restored,
    # and cc.mode.state=failed is published (main.py:300-307 parity).
    import errno

    from tpu_cc_manager.device import statefile

    sysfs, dev = make_accel_tree(tmp_path, n=1)
    be = SysfsTpuBackend(sysfs_root=sysfs, dev_root=dev,
                         state_dir=str(tmp_path / "st"))
    set_backend(be)

    real_mkstemp = statefile.tempfile.mkstemp

    def failing_mkstemp(*a, **kw):
        raise OSError(errno.ENOSPC, "No space left on device")

    states = []
    drainer = FlakyEvictDrainer()
    engine = ModeEngine(set_state_label=states.append, drainer=drainer)
    monkeypatch.setattr(statefile.tempfile, "mkstemp", failing_mkstemp)
    try:
        assert engine.set_mode("on") is False
    finally:
        monkeypatch.setattr(statefile.tempfile, "mkstemp", real_mkstemp)
    assert drainer.events == ["evict", "reschedule"]
    assert states == ["failed"]


def test_store_oserror_is_wrapped_as_device_error(tmp_path, monkeypatch):
    import errno

    from tpu_cc_manager.device import statefile
    from tpu_cc_manager.device.base import DeviceError
    from tpu_cc_manager.device.statefile import ModeStateStore

    store = ModeStateStore(str(tmp_path / "st"))

    def failing_mkstemp(*a, **kw):
        raise OSError(errno.ENOSPC, "No space left on device")

    monkeypatch.setattr(statefile.tempfile, "mkstemp", failing_mkstemp)
    with pytest.raises(DeviceError):
        store.stage("/dev/accel0", "cc", "on")


def test_oneshot_cli_publishes_failed_on_backend_crash(tmp_path):
    # A crashing device backend must not let `set-cc-mode` exit without
    # publishing cc.mode.state=failed (the reference's failure-visibility
    # contract; VERDICT r1 weak #1).
    import os
    import tempfile
    import textwrap

    import tpu_cc_manager.__main__ as cli
    from tpu_cc_manager import labels as L
    from tpu_cc_manager.device.base import Backend
    from tpu_cc_manager.k8s.apiserver import FakeApiServer
    from tpu_cc_manager.k8s.objects import make_node

    class ExplodingBackend(Backend):
        def find_tpus(self):
            raise RuntimeError("backend exploded")

        def find_ici_switches(self):
            return []

    set_backend(ExplodingBackend())
    with FakeApiServer() as srv:
        srv.store.add_node(make_node("n1"))
        with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                         delete=False) as f:
            f.write(textwrap.dedent(f"""\
                apiVersion: v1
                kind: Config
                current-context: t
                contexts: [{{name: t, context: {{cluster: c, user: u}}}}]
                clusters: [{{name: c, cluster: {{server: "{srv.url}"}}}}]
                users: [{{name: u, user: {{}}}}]
            """))
            kubeconfig = f.name
        try:
            rc = cli.main([
                "--kubeconfig", kubeconfig, "--node-name", "n1",
                "set-cc-mode", "-m", "on",
            ])
            assert rc == 1
            node = srv.store.get_node("n1")
            assert node["metadata"]["labels"][L.CC_MODE_STATE_LABEL] == "failed"
        finally:
            os.unlink(kubeconfig)
