"""Property-based lifecycle scenario generation + the invariants
oracle (ISSUE 12, tpu_cc_manager/simlab/propgen.py + invariants.py).

Four surfaces under test:

1. the GENERATOR — deterministic by seed, only emits schema-valid
   docs, covers all lifecycle fault families over a seed range;
2. the SHRINKER — demonstrably reduces a synthetic multi-fault
   counterexample to the minimal reproducing pair (ddmin
   1-minimality), deterministically, never proposing invalid docs
   (the ISSUE 12 acceptance pin);
3. the FIND loop — a violated invariant produces a replayable
   canonical ``gen-*.json`` that reproduces the violation when
   reloaded (the second acceptance pin);
4. the ORACLE — unit-level detection with crafted lab stubs (the
   live-green paths run in test_simlab.py and the propgen-smoke CI
   job; here we prove the checks can FIRE), plus the lifecycle
   drills end to end through LIVE replicas: the revoked-root path
   latching ``attestation_outage`` with a fleet problems line (the
   satellite), key rotation re-verifying, and the policy-conflict
   parking rule.
"""

import json

import pytest

from tpu_cc_manager.simlab.invariants import (
    INVARIANTS, Violation, check_run,
)
from tpu_cc_manager.simlab.propgen import (
    FAMILIES, dump_find, generate_episode, run_episode, shrink,
)
from tpu_cc_manager.simlab.scenario import (
    canonical_scenario_text, load_scenario, validate_scenario,
)


# ------------------------------------------------------------ generator
def test_generator_deterministic_and_valid():
    for seed in range(30):
        a = generate_episode(seed)
        b = generate_episode(seed)
        assert a == b, f"seed {seed} not deterministic"
        validate_scenario(a)  # only schema-valid docs, ever
        assert a["name"] == f"gen-{seed}"


def test_generator_covers_every_family():
    seen = set()
    for seed in range(60):
        doc = generate_episode(seed)
        kinds = {a.get("fault") for a in doc["actions"]
                 if a["action"] == "fault"}
        if doc.get("regions"):
            # the exclusive multi-region family (ISSUE 16): its
            # root_revoked drill is region-scoped, not the attestation
            # family's env-global one
            seen.add("federation")
            continue
        if kinds & {"key_rotation", "root_revoked"}:
            seen.add("attestation")
        if "agent_upgrade" in kinds:
            seen.add("upgrade")
        if "policy_conflict" in kinds:
            seen.add("policy")
        if "evacuation_drain" in kinds:
            seen.add("evacuation")
        if "shard_kill" in kinds:
            seen.add("shards")
    assert seen == set(FAMILIES)


def test_generator_family_override():
    doc = generate_episode(7, families=["policy"])
    kinds = {a.get("fault") for a in doc["actions"]}
    assert "policy_conflict" in kinds
    assert doc["controllers"]["policy"] is True
    with pytest.raises(ValueError, match="unknown families"):
        generate_episode(7, families=["chaos-monkey"])


def test_attestation_episodes_carry_the_whole_posture():
    """An attestation episode must be self-sufficient: evidence on,
    per-node TPMs on, and a fleet audit plane to read the verdicts."""
    doc = generate_episode(3, families=["attestation"])
    assert doc["evidence"] is True and doc["attestation"] is True
    assert doc["controllers"].get("fleet") or \
        doc["controllers"].get("shards")


# ------------------------------------------------------------- shrinker
def _padded_counterexample():
    base = generate_episode(1, families=["upgrade"])
    doc = dict(base)
    doc["actions"] = sorted(base["actions"] + [
        {"at": 0.05, "action": "fault", "fault": "write_429",
         "count": 5},
        {"at": 0.1, "action": "fault", "fault": "agent_crash",
         "count": 2, "restart_after_s": 0.5},
        {"at": 0.15, "action": "fault", "fault": "watch_410"},
        {"at": 0.35, "action": "fault", "fault": "list_429",
         "count": 1},
    ], key=lambda a: a["at"])
    return doc


def test_shrinker_reduces_synthetic_multifault_counterexample():
    """THE acceptance pin: the violation 'needs write_429 AND
    agent_crash together' must shrink from a 7-action episode to
    exactly that pair — and every candidate the shrinker proposes must
    be schema-valid."""
    doc = _padded_counterexample()
    proposed = []

    def repro(cand):
        validate_scenario(cand)  # invalid candidates must never reach us
        proposed.append(cand)
        kinds = [a.get("fault") for a in cand["actions"]]
        return "write_429" in kinds and "agent_crash" in kinds

    shrunk, runs = shrink(doc, repro, seed=7, max_runs=64)
    kinds = sorted(a.get("fault") for a in shrunk["actions"]
                   if a["action"] == "fault")
    assert kinds == ["agent_crash", "write_429"]
    # 1-minimal modulo the structural rule: the converge-driving wave
    # is never dropped (see test_shrinker_never_drops_the_converge_driver)
    others = [a for a in shrunk["actions"] if a["action"] != "fault"]
    assert len(others) == 1 and others[0]["action"] == "set_mode"
    assert len(shrunk["actions"]) == 3
    assert 0 < runs <= 64 and len(proposed) == runs
    validate_scenario(shrunk)


def test_shrinker_deterministic_by_seed():
    doc = _padded_counterexample()

    def repro(cand):
        kinds = [a.get("fault") for a in cand["actions"]]
        return "write_429" in kinds and "agent_crash" in kinds

    a, runs_a = shrink(doc, repro, seed=7, max_runs=64)
    b, runs_b = shrink(doc, repro, seed=7, max_runs=64)
    assert a == b and runs_a == runs_b


def test_shrinker_respects_run_budget():
    doc = _padded_counterexample()
    calls = []

    def repro(cand):
        calls.append(1)
        return False  # nothing reproduces: every attempt is spent

    shrunk, runs = shrink(doc, repro, seed=1, max_runs=5)
    assert runs == 5 and len(calls) == 5
    assert shrunk == doc  # nothing reproduced -> nothing changed


def test_shrinker_never_drops_the_converge_driver():
    """A convergence-violation shrink must not degenerate: dropping
    the action that initiates converge.mode makes ANY candidate
    trivially non-convergent, so the rule keeps one converge driver
    in every candidate — even against an always-True predicate."""
    doc = {
        "version": 1, "name": "gen-driver", "nodes": 4, "pools": 1,
        "chips_per_node": 1, "initial_mode": "off", "workers": 2,
        "qps": 0, "evidence": False, "watch_timeout_s": 2,
        "actions": [
            {"at": 0.1, "action": "set_mode", "mode": "on"},
            {"at": 0.2, "action": "fault", "fault": "watch_410"},
            {"at": 0.3, "action": "fault", "fault": "list_429",
             "count": 1},
        ],
        "converge": {"mode": "on", "timeout_s": 5},
    }
    shrunk, _runs = shrink(doc, lambda cand: True, seed=11,
                           max_runs=64)
    (kept,) = shrunk["actions"]  # only the driver survives
    assert kept["action"] == "set_mode" and kept["mode"] == "on"


def test_shrinker_reorder_pass_pulls_faults_earlier():
    """A violation that only reproduces when the fault is FIRST in the
    timeline is found by the reorder pass, not the drop pass."""
    doc = _padded_counterexample()

    def repro(cand):
        acts = cand["actions"]
        return (acts[0].get("fault") == "watch_410"
                and len(acts) == len(doc["actions"]))

    shrunk, _runs = shrink(doc, repro, seed=3, max_runs=64)
    assert shrunk["actions"][0].get("fault") == "watch_410"
    assert shrunk["actions"][0]["at"] == 0.0


# ------------------------------------------------------ replayable finds
def test_violation_dumps_replayable_find(tmp_path):
    """THE other acceptance pin, live: a violated invariant produces a
    canonical scenarios/gen-*.json that reproduces the violation when
    reloaded and re-run."""
    broken = {
        "version": 1, "name": "gen-4242", "nodes": 4, "pools": 1,
        "chips_per_node": 1, "initial_mode": "off", "workers": 2,
        "qps": 0, "evidence": False, "watch_timeout_s": 2,
        "actions": [
            {"at": 0.1, "action": "set_mode", "mode": "devtools"},
        ],
        "converge": {"mode": "on", "timeout_s": 2},
    }
    result = run_episode(broken)
    assert any(v.invariant == "convergence" for v in result.violations)
    spath, rpath = dump_find(
        broken, result.violations, result.artifact,
        scenario_dir=str(tmp_path / "scenarios"),
        report_dir=str(tmp_path / "finds"),
    )
    # the find is a first-class canonical scenario file
    text = open(spath).read()
    assert text == canonical_scenario_text(json.loads(text))
    sc = load_scenario(spath)
    assert sc.name == "gen-4242"
    # ... and it REPRODUCES under re-run
    replay = run_episode(json.loads(text))
    assert any(v.invariant == "convergence" for v in replay.violations)
    report = json.load(open(rpath))
    assert report["violations"][0]["invariant"] == "convergence"
    assert "timeline" in report  # the stitched flight-recorder story
    assert report["scenario_path"] == spath


def test_dump_find_enforces_gen_prefix(tmp_path):
    doc = {
        "version": 1, "name": "oops", "nodes": 2,
        "actions": [{"at": 0, "action": "set_mode", "mode": "on"}],
        "converge": {"mode": "on", "timeout_s": 5},
    }
    spath, _ = dump_find(
        doc, [Violation("convergence", "x")],
        scenario_dir=str(tmp_path / "s"), report_dir=str(tmp_path / "r"),
    )
    assert spath.endswith("gen-oops.json")


# ------------------------------------------------------ oracle (units)
class _StubChip:
    def __init__(self, path, mode):
        self.path = path
        self.is_cc_query_supported = True
        self._mode = mode

    def query_cc_mode(self):
        return self._mode


class _StubBackend:
    def __init__(self, modes):
        self.chips = [_StubChip(f"/dev/accel{i}", m)
                      for i, m in enumerate(modes)]


class _StubGate:
    def __init__(self, perms):
        self._perms = perms

    def perms_snapshot(self):
        return dict(self._perms)


class _StubReplica:
    def __init__(self, modes=("on",), perms=None, version="v1",
                 alive=True, outcomes=None):
        self.backend = _StubBackend(modes)
        self.gate = _StubGate(perms or {})
        self.version = version
        self.alive = alive
        self.outcomes = outcomes or {"success": 1}
        self.attestor = None


class _StubStore:
    def __init__(self, labels=None, mutations=0):
        self._labels = labels or {}
        self._mutations = mutations

    def peek_node_label(self, name, key):
        return self._labels.get(name)

    def get_node(self, name):
        return {"metadata": {"name": name,
                             "labels": {}, "annotations": {}},
                "spec": {}}

    def node_write_stats(self):
        return {"requests": self._mutations,
                "mutations": self._mutations}


class _StubServer:
    def __init__(self, store):
        self.store = store


class _StubScenario:
    def __init__(self, nodes, evidence=False):
        self.nodes = nodes
        self.evidence = evidence


class _StubLab:
    def __init__(self, replicas, store=None, nodes=None,
                 evidence=False):
        self.replicas = replicas
        self.server = _StubServer(store or _StubStore())
        self.scenario = _StubScenario(nodes or len(replicas), evidence)
        self.injector = None
        self.shard_manager = None
        self.attest_lab = None

    def final_fleet_reports(self):
        return []


_GREEN_ARTIFACT = {"ok": True, "metrics": {}, "faults": [],
                   "controllers": {}}


def test_oracle_detects_half_flipped_node():
    lab = _StubLab({"n1": _StubReplica(modes=("on", "off"))})
    (v,) = check_run(lab, _GREEN_ARTIFACT)
    assert v.invariant == "half_flipped" and v.nodes == ("n1",)


def test_oracle_detects_fail_secure_breach():
    """A node whose label claims success while a device is still at
    FLIP_LOCK_PERMS handed workloads a gated chip."""
    lab = _StubLab(
        {"n1": _StubReplica(perms={"/dev/accel0": 0o000})},
        store=_StubStore(labels={"n1": "on"}),
    )
    (v,) = check_run(lab, _GREEN_ARTIFACT)
    assert v.invariant == "fail_secure"
    # ... but a FAILED node keeping its device locked is the contract
    # working, not a violation
    lab2 = _StubLab(
        {"n1": _StubReplica(perms={"/dev/accel0": 0o000})},
        store=_StubStore(labels={"n1": "failed"}),
    )
    assert check_run(lab2, _GREEN_ARTIFACT) == []


def test_oracle_detects_write_budget_blowout():
    # 1 flip, no evidence, 40 mutation units: the historical ~5
    # writes/flip world would look like this
    lab = _StubLab(
        {"n1": _StubReplica()},
        store=_StubStore(labels={"n1": "on"}, mutations=40),
    )
    violations = check_run(lab, _GREEN_ARTIFACT)
    assert [v.invariant for v in violations] == ["writes_per_flip"]


def test_oracle_orders_and_catalogs_violations():
    assert set(INVARIANTS) >= {
        "convergence", "half_flipped", "fail_secure",
        "writes_per_flip", "leader_uniqueness", "forged_evidence",
        "attestation_outage", "attestation_rotation",
        "policy_conflict", "upgrade_completeness",
        "evacuation_restored", "exposition_valid",
    }
    lab = _StubLab(
        {"n1": _StubReplica(modes=("on", "off"))},
    )
    art = dict(_GREEN_ARTIFACT)
    art["ok"] = False
    violations = check_run(lab, art)
    # catalog order: convergence before half_flipped
    assert [v.invariant for v in violations] == [
        "convergence", "half_flipped"]


# ----------------------------------------------- lifecycle drills, LIVE
def test_root_revoked_latches_outage_through_live_replicas():
    """The satellite pin: attest.py's revoked-root path driven END TO
    END through live simlab replicas — per-node TPMs quote real
    measured histories, a fleet scan VERIFIES (arming the latch), the
    trust root is revoked, and the final audit must latch
    ``attestation_outage`` with a fleet problems line; the planted
    node-root forgery must land in ``attestation_mismatch`` and never
    flip a chip."""
    doc = {
        "version": 1, "name": "gen-revoked-live", "nodes": 6,
        "pools": 2, "chips_per_node": 1, "initial_mode": "off",
        "workers": 4, "qps": 0, "evidence": True, "attestation": True,
        "watch_timeout_s": 2, "controllers": {"fleet": True},
        "actions": [
            {"at": 0.2, "action": "set_mode", "mode": "devtools"},
            {"at": 1.5, "action": "fault", "fault": "root_revoked",
             "forge": True},
        ],
        "converge": {"mode": "devtools", "timeout_s": 60},
    }
    result = run_episode(doc)
    assert result.ok, [v.to_dict() for v in result.violations]
    # the oracle said green — now assert the DRILL ITSELF happened
    (revoke,) = [f for f in result.artifact["faults"]
                 if f.get("fault") == "root_revoked"]
    assert revoke["armed_before_revoke"] is True
    assert revoke["revoked"] is True
    forged_node = revoke["forged"]
    assert forged_node is not None
    (report,) = result.lab.final_fleet_reports()
    audit = report["evidence_audit"]
    assert audit["attestation_outage"], "outage latch never filled"
    assert forged_node in audit["attestation_mismatch"]
    assert audit["attestation_seen"] is False
    assert any("attestation went unverifiable" in p
               for p in report["problems"])
    assert any("attestation mismatch" in p for p in report["problems"])
    # the forged claim never reached the silicon
    claim = revoke["forged_claim"]
    victim = result.lab.replicas[forged_node]
    assert all(c.query_cc_mode() != claim
               for c in victim.backend.chips)
    # lifecycle block reached the artifact
    att = result.artifact["metrics"]["lifecycle"]["attestation"]
    assert att["revoked"] is True
    assert att["forged_nodes"] == [forged_node]


def test_key_rotation_reverifies_through_live_replicas():
    """Rotated signing key mid-scan: the verifier keeps the old key in
    its rotation tail (attest.tpm_keys), the next wave re-quotes, and
    the oracle requires every settled document to verify under the NEW
    primary alone."""
    doc = {
        "version": 1, "name": "gen-rotation-live", "nodes": 6,
        "pools": 2, "chips_per_node": 1, "initial_mode": "off",
        "workers": 4, "qps": 0, "evidence": True, "attestation": True,
        "watch_timeout_s": 2, "controllers": {"fleet": True},
        "actions": [
            {"at": 0.2, "action": "set_mode", "mode": "on"},
            {"at": 1.0, "action": "fault", "fault": "key_rotation"},
            {"at": 1.3, "action": "set_mode", "mode": "devtools"},
        ],
        "converge": {"mode": "devtools", "timeout_s": 60},
    }
    result = run_episode(doc)
    assert result.ok, [v.to_dict() for v in result.violations]
    assert result.lab.attest_lab.rotations == 1
    # no mismatch tail: rotation is routine, not attack-shaped
    (report,) = result.lab.final_fleet_reports()
    audit = report["evidence_audit"]
    assert audit["attestation_mismatch"] == []
    assert audit["attestation_seen"] is True


def test_policy_conflict_parks_rival_through_live_replicas():
    doc = generate_episode(2, families=["policy"])
    result = run_episode(doc)
    assert result.ok, [v.to_dict() for v in result.violations]
    phases = result.artifact["controllers"]["policy_phases"]
    assert phases["zz-conflict-rival"] == "Conflicted"
    assert phases["aa-conflict-owner"] != "Conflicted"


def test_upgrade_and_evacuation_live_episode():
    """Rolling upgrade racing an evacuation drain: two code versions
    reconcile one pool, cordons race flips, and at quiescence every
    replica runs v2, advertises it, and no node is left cordoned."""
    doc = {
        "version": 1, "name": "gen-upgrade-live", "nodes": 8,
        "pools": 2, "chips_per_node": 2, "initial_mode": "off",
        "workers": 4, "qps": 0, "evidence": False,
        "watch_timeout_s": 2,
        "actions": [
            {"at": 0.2, "action": "set_mode", "mode": "on"},
            {"at": 0.3, "action": "fault", "fault": "agent_upgrade",
             "cohorts": 2, "stagger_s": 0.2},
            {"at": 0.4, "action": "fault", "fault": "evacuation_drain",
             "count": 3, "duration_s": 0.8},
        ],
        "converge": {"mode": "on", "timeout_s": 60},
    }
    result = run_episode(doc)
    assert result.ok, [v.to_dict() for v in result.violations]
    lc = result.artifact["metrics"]["lifecycle"]
    assert lc["versions"] == {"v2": 8}
    assert lc["upgraded"] == 8 and lc["evacuated"] == 3
    from tpu_cc_manager import labels as L

    store = result.lab.server.store
    for name in result.lab.replicas:
        node = store.get_node(name)
        ann = node["metadata"].get("annotations") or {}
        assert ann.get(L.AGENT_VERSION_ANNOTATION) == "v2", name
        assert not (node.get("spec") or {}).get("unschedulable"), name
