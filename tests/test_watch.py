"""L3 watcher tests: lossy coalescing semantics and watch-stream
robustness (resume, 410 resync, consecutive-error fatal)."""

import threading
import time

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.watch import (
    FatalWatchError,
    NodeWatcher,
    SyncableModeConfig,
)


# ----------------------------------------------------- coalescing mailbox
def test_mailbox_blocks_until_change():
    m = SyncableModeConfig()
    got, val = m.get(timeout=0.1)
    assert not got
    m.set("on")
    got, val = m.get(timeout=1)
    assert got and val == "on"
    # same value again: no wakeup (cmd/main.go:68-76 blocks until change)
    got, val = m.get(timeout=0.1)
    assert not got


def test_mailbox_coalesces_burst_to_latest():
    # N rapid updates collapse to ONE read of the latest value
    # (the deliberate lossy semantics, SURVEY.md §5.2)
    m = SyncableModeConfig()
    for v in ("on", "off", "devtools", "ici"):
        m.set(v)
    got, val = m.get(timeout=1)
    assert got and val == "ici"
    got, _ = m.get(timeout=0.1)
    assert not got


def test_mailbox_none_value_is_consumable():
    # label removal publishes None, which is a real value (not a timeout)
    m = SyncableModeConfig()
    m.set("on")
    assert m.get(timeout=1) == (True, "on")
    m.set(None)
    assert m.get(timeout=1) == (True, None)


def test_mailbox_close_unblocks():
    m = SyncableModeConfig()
    results = []

    def run():
        results.append(m.get(timeout=5))

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.1)
    m.close()
    t.join(timeout=2)
    assert results == [(False, None)]


# -------------------------------------------------------------- watcher
def _watch_env(label=None):
    kube = FakeKube()
    labels = {L.CC_MODE_LABEL: label} if label else {}
    kube.add_node(make_node("n1", labels=labels))
    m = SyncableModeConfig()
    w = NodeWatcher(kube, "n1", m, backoff_s=0.05, watch_timeout_s=2)
    return kube, m, w


def test_watcher_prime_reads_initial_label():
    kube, m, w = _watch_env(label="on")
    assert w.prime() == "on"
    assert w.resource_version == kube.latest_rv


def test_watcher_pushes_label_changes():
    kube, m, w = _watch_env(label="off")
    w.prime()
    w.start()
    try:
        kube.set_node_labels("n1", {L.CC_MODE_LABEL: "on"})
        got, val = m.get(timeout=5)
        assert got and val == "on"
        # unrelated label change does not push (value dedup, main.py:651-661)
        kube.set_node_labels("n1", {"other": "x"})
        got, _ = m.get(timeout=0.3)
        assert not got
    finally:
        w.stop()


def test_watcher_survives_watch_timeout_and_resumes():
    kube, m, w = _watch_env(label="off")
    w.prime()
    w.watch_timeout_s = 1  # quick server-side timeouts
    w.start()
    try:
        time.sleep(1.5)  # at least one timeout/reconnect cycle
        kube.set_node_labels("n1", {L.CC_MODE_LABEL: "devtools"})
        got, val = m.get(timeout=5)
        assert got and val == "devtools"
        assert w.consecutive_errors == 0
    finally:
        w.stop()


def test_watcher_410_resync_reconciles_missed_change():
    kube, m, w = _watch_env(label="off")
    w.prime()
    # change + compact while the watcher is NOT running: resume rv is stale
    kube.set_node_labels("n1", {L.CC_MODE_LABEL: "on"})
    kube.compact_watch_history()
    w.start()
    try:
        got, val = m.get(timeout=5)  # re-list path must deliver the change
        assert got and val == "on"
    finally:
        w.stop()


def test_watcher_error_backoff_then_recovery():
    kube, m, w = _watch_env(label="off")
    w.prime()
    kube.fail_next_watches = 3
    w.start()
    try:
        kube.set_node_labels("n1", {L.CC_MODE_LABEL: "ici"})
        got, val = m.get(timeout=10)
        assert got and val == "ici"
    finally:
        w.stop()


def test_watcher_consecutive_errors_fatal():
    kube, m, w = _watch_env(label="off")
    w.prime()
    kube.fail_next_watches = 10**6
    fatal = []
    w.on_fatal = fatal.append
    w.max_consecutive_errors = 5
    w.backoff_s = 0.01
    w.run()  # returns after invoking on_fatal
    assert len(fatal) == 1
    assert isinstance(fatal[0], FatalWatchError)


def test_watcher_fatal_raises_without_handler():
    kube, m, w = _watch_env(label="off")
    w.prime()
    kube.fail_next_watches = 10**6
    w.max_consecutive_errors = 3
    w.backoff_s = 0.01
    with pytest.raises(FatalWatchError):
        w.run()


def test_watcher_latest_trace_context(monkeypatch):
    """The cc.trace annotation (ISSUE 8) surfaces off the SAME watch
    event as the desired-label change; missing or non-string values
    degrade to None."""
    kube, m, w = _watch_env(label="off")
    assert w.latest_trace_context() is None  # before the prime read
    w.prime()
    assert w.latest_trace_context() is None  # no writer stamped one
    w.start()
    try:
        kube.patch_node("n1", {"metadata": {
            "labels": {L.CC_MODE_LABEL: "on"},
            "annotations": {L.CC_TRACE_ANNOTATION: "00-t1-s1-01"},
        }})
        got, val = m.get(timeout=5)
        assert got and val == "on"
        assert w.latest_trace_context() == "00-t1-s1-01"
        # newest desired write's context wins (mailbox coalescing)
        kube.patch_node("n1", {"metadata": {
            "labels": {L.CC_MODE_LABEL: "off"},
            "annotations": {L.CC_TRACE_ANNOTATION: "00-t2-s2-01"},
        }})
        got, val = m.get(timeout=5)
        assert got and val == "off"
        assert w.latest_trace_context() == "00-t2-s2-01"
        # an UNSTAMPED desired write (operator kubectl): the node still
        # carries t2's annotation, but this write didn't stamp a fresh
        # one — adopting it would attribute the new reconcile to the
        # finished t2 trace. Must degrade to a local root.
        kube.set_node_labels("n1", {L.CC_MODE_LABEL: "on"})
        got, val = m.get(timeout=5)
        assert got and val == "on"
        assert w.latest_trace_context() is None
    finally:
        w.stop()
