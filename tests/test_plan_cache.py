"""Compile economics of the array-native planner (ISSUE 7).

Two contracts keep the planner's XLA cost off the scan path:

- **Shape buckets**: node counts pad to power-of-two buckets
  (plan.bucket_nodes), so fleet-geometry drift inside a bucket reuses
  the compiled tick — pinned here by counting actual retraces
  (plan.TRACE_COUNTS, incremented by a Python side effect inside the
  traced body, so it moves ONLY when XLA re-traces).
- **Persistent AOT cache**: plan.configure_cache + plan.warmup
  serialize the bucket ladder's compiles to disk; a restarted process
  deserializes instead of recompiling — pinned here with two real
  subprocesses sharing one cache dir and jax.monitoring's
  cache_hit/cache_miss events.
"""

import json
import os
import subprocess
import sys

from tpu_cc_manager import labels as L
from tpu_cc_manager import plan
from tpu_cc_manager.plan import bucket_nodes, bucket_pools


def _node(name, slice_id=None, desired="on", state="off"):
    labels = {
        L.CC_MODE_LABEL: desired,
        L.CC_MODE_STATE_LABEL: state,
    }
    if slice_id:
        labels[L.TPU_SLICE_LABEL] = slice_id
    return {"metadata": {"name": name, "labels": labels}}


# ----------------------------------------------------------- bucket shape
def test_bucket_nodes_power_of_two_and_reserves_padding_slot():
    for n in (0, 1, 5, 63, 64, 100, 1000, 100_000):
        b = bucket_nodes(n)
        assert b & (b - 1) == 0, f"bucket_nodes({n})={b} not a power of 2"
        # every node may be a solo slice; +1 reserves the padding slot
        assert b >= n + 1
        assert b >= 64
    assert bucket_nodes(63) == 64
    assert bucket_nodes(64) == 128  # 64 nodes need 65 slice slots
    assert bucket_nodes(100_000) == 131072


def test_bucket_pools_power_of_two_with_padding():
    assert bucket_pools(0) == 8
    assert bucket_pools(7) == 8
    assert bucket_pools(8) == 16  # 8 pools + the padding slot


# ------------------------------------------------------- retrace counting
def test_node_count_drift_within_bucket_never_recompiles():
    """The no-recompile guarantee: every fleet size in [1, 63] shares
    the 64-row bucket, so the tick traces at most once across all of
    them — geometry drift costs a fingerprint-diffed re-encode, not an
    XLA compile."""
    plan.analyze_fleet([_node("seed-0")])  # ensure the bucket is traced
    base = plan.TRACE_COUNTS.get("fleet_tick", 0)
    for n in (1, 2, 17, 40, 63):
        report = plan.analyze_fleet(
            [_node(f"d{n}-{i}") for i in range(n)]
        )
        assert report["nodes"] == n
    assert plan.TRACE_COUNTS.get("fleet_tick", 0) == base, (
        "node-count drift inside one shape bucket re-traced the kernel"
    )


def test_bucket_step_recompiles_exactly_once():
    plan.analyze_fleet([_node("seed-1")])
    base = plan.TRACE_COUNTS.get("fleet_tick", 0)
    # 100 nodes cross into the 128-row bucket: exactly one new trace,
    # and further drift inside THAT bucket is free again
    for n in (100, 80, 127):
        plan.analyze_fleet([_node(f"s{n}-{i}") for i in range(n)])
    grown = plan.TRACE_COUNTS.get("fleet_tick", 0) - base
    assert grown <= 1, f"one bucket step cost {grown} traces"


def test_pool_batch_shares_the_bucketed_kernel():
    """analyze_pools rides the same (node-bucket, pool-bucket) compiled
    tick as the fleet scan — policy-count drift inside the pool bucket
    must not recompile either."""
    pools = [
        (f"pool-{p}", "on", [_node(f"p{p}-{i}") for i in range(4)])
        for p in range(7)
    ]
    plan.analyze_pools(pools[:1])
    base = plan.TRACE_COUNTS.get("fleet_tick", 0)
    for n_pools in (1, 2, 3, 5, 7):
        stats = plan.analyze_pools(pools[:n_pools])
        assert len(stats) == n_pools
    assert plan.TRACE_COUNTS.get("fleet_tick", 0) == base, (
        "pool-count drift inside one pool bucket re-traced the kernel"
    )


# -------------------------------------------------- persistent AOT cache
_CHILD = r"""
import json, os, sys
import jax, jax.monitoring

events = []
jax.monitoring.register_event_listener(lambda name, **kw: events.append(name))
from tpu_cc_manager import plan

assert plan.configure_cache(os.environ["TPU_CC_COMPILE_CACHE_DIR"])
timings = plan.warmup(max_nodes=int(os.environ.get("WARM_NODES", "32")))
print(json.dumps({
    "timings": timings,
    "hits": sum(1 for e in events if "cache_hit" in e),
    "misses": sum(1 for e in events if "cache_miss" in e),
}))
"""


def _run_child(cache_dir):
    env = dict(
        os.environ,
        TPU_CC_COMPILE_CACHE_DIR=str(cache_dir),
        WARM_NODES="32",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD], env=env, capture_output=True,
        text=True, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_warmup_populates_cache_and_restart_is_compile_free(tmp_path):
    """The restart contract (ISSUE 7 acceptance): process 1 warms up
    cold (every bucket a cache miss, serialized to disk); process 2 —
    same geometry, same cache dir — deserializes every bucket (all
    hits, ZERO misses). The first scan after a controller restart pays
    deserialization, not XLA."""
    cache_dir = tmp_path / "xla-cache"
    cold = _run_child(cache_dir)
    assert cold["misses"] >= 1, cold
    assert cold["hits"] == 0, cold
    assert os.listdir(cache_dir), "warmup serialized nothing to disk"
    warm = _run_child(cache_dir)
    assert warm["misses"] == 0, (
        f"restart recompiled {warm['misses']} bucket(s): {warm}"
    )
    assert warm["hits"] >= cold["misses"], warm
    # the deserialize path must also be strictly cheaper than the
    # compile it replaced, bucket for bucket
    for key, cold_s in cold["timings"].items():
        assert warm["timings"][key] < cold_s, (key, cold, warm)


def test_configure_cache_unset_is_noop(monkeypatch):
    monkeypatch.delenv("TPU_CC_COMPILE_CACHE_DIR", raising=False)
    assert plan.configure_cache() is None
