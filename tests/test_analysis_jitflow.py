"""ccaudit v5 jitflow families (ISSUE 18): retrace-hazard,
host-sync-in-hot-path, unserialized-dispatch, donation-violation,
tracer-leak. Positive/negative/pragma fixtures per family, severity
pins, the fact-cache contract, the ``--files`` slicing soundness pin,
and the live-surface cleanliness pin (the shipped tree passes its own
v5 rules)."""

import os

import pytest

from tpu_cc_manager.analysis.core import (
    CACHE_DIR_NAME,
    analyze_paths,
    analyze_source,
    analyzer_version_hash,
    load_audit_cached,
)
from tpu_cc_manager.analysis.jitflow import (
    DISPATCH_RULE,
    DONATION_RULE,
    JITFLOW_RULES,
    RETRACE_RULE,
    SYNC_RULE,
    TRACER_RULE,
)

#: in-scope module path for fixtures (jitflow only arms under the
#: package tree; bench/scripts/simlab are exempt)
MOD = "tpu_cc_manager/jitfix.py"


def _hits(src, rule, relpath=MOD):
    return [f for f in analyze_source(src, relpath) if f.rule == rule]


# ------------------------------------------------------ retrace-hazard

JIT_HEADER = (
    "import jax\n"
    "def _plan(state, num):\n"
    "    return state\n"
    "plan_jit = jax.jit(_plan, static_argnames=('num',))\n"
)


def test_retrace_dynamic_static_argname_flagged():
    src = JIT_HEADER + (
        "def tick(state, n):\n"
        "    return plan_jit(state, num=n)\n"
    )
    hits = _hits(src, RETRACE_RULE)
    assert len(hits) == 1
    assert hits[0].line == 6
    assert "num" in hits[0].message
    assert hits[0].severity == "warning"


def test_retrace_bucketed_and_constant_static_args_clean():
    src = JIT_HEADER + (
        "from tpu_cc_manager.plan import bucket_nodes\n"
        "MAX_NODES = 256\n"
        "def tick(state, n, snap):\n"
        "    nb = bucket_nodes(n)\n"
        "    a = plan_jit(state, num=nb)\n"       # bucket ladder
        "    b = plan_jit(state, num=8)\n"        # literal
        "    c = plan_jit(state, num=MAX_NODES)\n"  # module constant
        "    d = plan_jit(state, num=snap.bucket)\n"  # snapshot bucket
        "    return a, b, c, d\n"
    )
    assert _hits(src, RETRACE_RULE) == []


def test_retrace_static_argnums_positional_flagged():
    src = (
        "import jax\n"
        "def _plan(state, num):\n"
        "    return state\n"
        "plan_jit = jax.jit(_plan, static_argnums=(1,))\n"
        "def tick(state, n):\n"
        "    return plan_jit(state, n)\n"
    )
    hits = _hits(src, RETRACE_RULE)
    assert len(hits) == 1
    assert hits[0].line == 6


FACTORY = (
    "import jax\n"
    "def make_step(nb):\n"
    "    def f(x):\n"
    "        return x\n"
    "    return jax.jit(f)\n"
)


def test_retrace_factory_called_with_dynamic_geometry_flagged():
    src = FACTORY + (
        "def tick(n):\n"
        "    return make_step(n)(0)\n"
    )
    hits = _hits(src, RETRACE_RULE)
    assert len(hits) == 1
    assert "make_step" in hits[0].message


def test_retrace_factory_called_with_bucketed_geometry_clean():
    src = FACTORY + (
        "from tpu_cc_manager.plan import bucket_nodes\n"
        "def tick(n):\n"
        "    nb = bucket_nodes(n)\n"
        "    return make_step(nb)(0)\n"
    )
    assert _hits(src, RETRACE_RULE) == []


def test_retrace_pragma_alias_suppresses():
    src = JIT_HEADER + (
        "def tick(state, n):\n"
        "    return plan_jit(state, num=n)"
        "  # ccaudit: allow-retrace(one-shot admin path)\n"
    )
    assert _hits(src, RETRACE_RULE) == []


def test_retrace_exempt_under_simlab():
    src = JIT_HEADER + (
        "def tick(state, n):\n"
        "    return plan_jit(state, num=n)\n"
    )
    assert _hits(src, RETRACE_RULE,
                 relpath="tpu_cc_manager/simlab/drive.py") == []


# ---------------------------------------------- host-sync-in-hot-path

HOT_HEADER = (
    "import jax\n"
    "def _plan(x):\n"
    "    return x\n"
    "step = jax.jit(_plan)\n"
)


def test_host_sync_float_on_jit_output_in_hot_path_flagged():
    src = HOT_HEADER + (
        "def scan_once():\n"
        "    out = step(1)\n"
        "    return float(out)\n"
    )
    hits = _hits(src, SYNC_RULE)
    assert len(hits) == 1
    assert hits[0].line == 7
    assert hits[0].severity == "warning"


def test_host_sync_block_until_ready_in_hot_path_flagged():
    src = HOT_HEADER + (
        "def scan_once():\n"
        "    out = step(1)\n"
        "    out.block_until_ready()\n"
    )
    assert len(_hits(src, SYNC_RULE)) == 1


def test_host_sync_device_get_is_the_sanctioned_path():
    src = HOT_HEADER + (
        "def scan_once():\n"
        "    out = step(1)\n"
        "    host = jax.device_get(out)\n"
        "    return float(host)\n"
    )
    assert _hits(src, SYNC_RULE) == []


def test_host_sync_silent_off_the_hot_path():
    src = HOT_HEADER + (
        "def helper():\n"
        "    out = step(1)\n"
        "    return float(out)\n"
    )
    assert _hits(src, SYNC_RULE) == []


def test_host_sync_pragma_alias_suppresses():
    src = HOT_HEADER + (
        "def scan_once():\n"
        "    out = step(1)\n"
        "    return float(out)"
        "  # ccaudit: allow-host-sync(single scalar, measured cheap)\n"
    )
    assert _hits(src, SYNC_RULE) == []


# --------------------------------------------- unserialized-dispatch

COLLECTIVE = (
    "import threading\n"
    "import jax\n"
    "from jax.experimental.shard_map import shard_map\n"
    "_DISPATCH_LOCK = threading.Lock()\n"
    "def _tick(x):\n"
    "    return x\n"
    "sharded = shard_map(_tick)\n"
    "jitted = jax.jit(sharded)\n"
)


def test_dispatch_without_lock_flagged_as_error():
    src = COLLECTIVE + (
        "def go(x):\n"
        "    return jitted(x)\n"
    )
    hits = _hits(src, DISPATCH_RULE)
    assert len(hits) == 1
    assert hits[0].line == 10
    assert hits[0].severity == "error"
    assert "_DISPATCH_LOCK" in hits[0].message


def test_dispatch_under_lexical_lock_clean():
    src = COLLECTIVE + (
        "def go(x):\n"
        "    with _DISPATCH_LOCK:\n"
        "        return jitted(x)\n"
    )
    assert _hits(src, DISPATCH_RULE) == []


def test_dispatch_under_caller_held_lock_clean():
    # the ⋂-fixpoint: every resolved path into `inner` holds the lock
    src = COLLECTIVE + (
        "def outer(x):\n"
        "    with _DISPATCH_LOCK:\n"
        "        return inner(x)\n"
        "def inner(x):\n"
        "    return jitted(x)\n"
    )
    assert _hits(src, DISPATCH_RULE) == []


def test_dispatch_pragma_suppresses():
    src = COLLECTIVE + (
        "def go(x):\n"
        "    return jitted(x)"
        "  # ccaudit: allow-unserialized-dispatch(single-threaded tool)\n"
    )
    assert _hits(src, DISPATCH_RULE) == []


def test_non_collective_jit_needs_no_lock():
    src = (
        "import jax\n"
        "def _plan(x):\n"
        "    return x\n"
        "plain = jax.jit(_plan)\n"
        "def go(x):\n"
        "    return plain(x)\n"
    )
    assert _hits(src, DISPATCH_RULE) == []


# ------------------------------------------------- donation-violation

DONATE = (
    "import jax\n"
    "def _upd(buf):\n"
    "    return buf\n"
    "upd = jax.jit(_upd, donate_argnums=(0,))\n"
)


def test_donated_buffer_read_after_call_flagged():
    src = DONATE + (
        "def apply(buf):\n"
        "    out = upd(buf)\n"
        "    return buf + out\n"
    )
    hits = _hits(src, DONATION_RULE)
    assert len(hits) == 1
    assert hits[0].line == 7
    assert "donate" in hits[0].message


def test_donated_name_rebound_before_read_clean():
    src = DONATE + (
        "def apply(buf):\n"
        "    out = upd(buf)\n"
        "    buf = out\n"
        "    return buf\n"
    )
    assert _hits(src, DONATION_RULE) == []


def test_donation_pragma_alias_suppresses():
    src = DONATE + (
        "def apply(buf):\n"
        "    out = upd(buf)\n"
        "    return buf + out"
        "  # ccaudit: allow-donation(aliasing checked upstream)\n"
    )
    assert _hits(src, DONATION_RULE) == []


# ------------------------------------------------------- tracer-leak

def test_tracer_global_store_in_jitted_body_flagged():
    src = (
        "import jax\n"
        "LAST = None\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    global LAST\n"
        "    LAST = x\n"
        "    return x\n"
    )
    hits = _hits(src, TRACER_RULE)
    assert len(hits) == 1
    assert "LAST" in hits[0].message


def test_tracer_store_in_function_reachable_from_target_flagged():
    src = (
        "import jax\n"
        "LAST = None\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return helper(x)\n"
        "def helper(x):\n"
        "    global LAST\n"
        "    LAST = x\n"
        "    return x\n"
    )
    assert len(_hits(src, TRACER_RULE)) == 1


def test_tracer_condition_on_traced_param_flagged():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    if x:\n"
        "        return x\n"
        "    return -x\n"
    )
    hits = _hits(src, TRACER_RULE)
    assert len(hits) == 1
    assert "TracerBoolConversionError" in hits[0].message


def test_tracer_python_level_tests_are_clean():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def kernel(x, *, debug=False):\n"
        "    if x is None:\n"
        "        return x\n"
        "    if debug:\n"          # kwonly: config, not an array
        "        return -x\n"
        "    if isinstance(x, tuple):\n"
        "        return x[0]\n"
        "    return x\n"
    )
    assert _hits(src, TRACER_RULE) == []


def test_tracer_condition_on_static_argname_clean():
    src = (
        "import jax\n"
        "def _plan(x, n):\n"
        "    if n:\n"
        "        return x\n"
        "    return x\n"
        "plan2 = jax.jit(_plan, static_argnames=('n',))\n"
    )
    assert _hits(src, TRACER_RULE) == []


def test_tracer_pragma_suppresses():
    src = (
        "import jax\n"
        "LAST = None\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    global LAST\n"
        "    # ccaudit: allow-tracer-leak(stores a python int, not a tracer)\n"
        "    LAST = 1\n"
        "    return x\n"
    )
    assert _hits(src, TRACER_RULE) == []


# --------------------------------------------------------- fact cache

CACHED_SRC = (
    "def f():\n"
    "    try:\n"
    "        pass\n"
    "    except Exception:\n"
    "        pass\n"
)


def _audit_keys(audit):
    return sorted(f.key() for f in audit.findings)


def test_cache_hit_returns_identical_facts(tmp_path):
    pkg = tmp_path / "tpu_cc_manager"
    pkg.mkdir()
    (pkg / "m.py").write_text(CACHED_SRC)
    cache = tmp_path / CACHE_DIR_NAME
    cache.mkdir()
    v = analyzer_version_hash()
    rel = "tpu_cc_manager/m.py"
    a1 = load_audit_cached(str(tmp_path), rel, str(cache), v)
    assert len(list(cache.iterdir())) == 1  # entry written
    a2 = load_audit_cached(str(tmp_path), rel, str(cache), v)
    assert a2.module.relpath == rel
    assert _audit_keys(a1) == _audit_keys(a2)
    assert any(f.rule == "swallow" for f in a2.findings)


def test_cache_content_change_invalidates(tmp_path):
    pkg = tmp_path / "tpu_cc_manager"
    pkg.mkdir()
    (pkg / "m.py").write_text(CACHED_SRC)
    cache = tmp_path / CACHE_DIR_NAME
    cache.mkdir()
    v = analyzer_version_hash()
    rel = "tpu_cc_manager/m.py"
    load_audit_cached(str(tmp_path), rel, str(cache), v)
    (pkg / "m.py").write_text("def f():\n    return 1\n")
    a2 = load_audit_cached(str(tmp_path), rel, str(cache), v)
    # fresh facts for the new content, under a new key
    assert a2.findings == []
    assert len(list(cache.iterdir())) == 2


def test_cache_corrupt_entry_falls_back_to_fresh_parse(tmp_path):
    pkg = tmp_path / "tpu_cc_manager"
    pkg.mkdir()
    (pkg / "m.py").write_text(CACHED_SRC)
    cache = tmp_path / CACHE_DIR_NAME
    cache.mkdir()
    v = analyzer_version_hash()
    rel = "tpu_cc_manager/m.py"
    a1 = load_audit_cached(str(tmp_path), rel, str(cache), v)
    (entry,) = cache.iterdir()
    entry.write_bytes(b"not a pickle")
    a2 = load_audit_cached(str(tmp_path), rel, str(cache), v)
    assert _audit_keys(a1) == _audit_keys(a2)


def test_version_hash_self_invalidates_on_analyzer_change():
    v = analyzer_version_hash()
    assert len(v) == 16
    assert v == analyzer_version_hash()  # stable within a tree
    # the digest covers every analysis/*.py source, so editing any rule
    # module yields a different key prefix-set; pinned structurally:
    import tpu_cc_manager.analysis as pkg

    pkg_dir = os.path.dirname(pkg.__file__)
    assert any(f == "jitflow.py" for f in os.listdir(pkg_dir))


# -------------------------------------------- live surface + slicing


@pytest.fixture(scope="module")
def full_scan():
    return analyze_paths()


def test_live_tree_passes_v5_clean(full_scan):
    # the shipped tree passes its own jitflow rules: every deliberate
    # sync/dispatch/trace-time effect carries an in-source pragma, and
    # nothing rides the baseline (zero new entries — the ratchet only
    # burns down)
    assert [f for f in full_scan if f.rule in JITFLOW_RULES] == []


def test_files_subset_reports_exactly_the_full_runs_slice(full_scan):
    # --files runs the ANALYSIS whole-program and slices only the
    # REPORT, so jitflow facts (hot set, caller-held locksets, the jit
    # inventory) never degrade on a changed-files pass
    target = "tpu_cc_manager/plan.py"
    sub = analyze_paths(targets=[target], subset=True)
    assert sorted(sub) == sorted(
        f for f in full_scan if f.file == target
    )
