"""ISSUE 9 acceptance pins, live-replica half: a simlab fault scenario
drives a measurable SLO burn (burn-rate gauge rises, alert event lands
in the flight recorder, artifact carries the verdict) and a clean run
burns no budget. Unit-level burn math lives in test_fleetobs.py."""

import os

import pytest

yaml = pytest.importorskip("yaml")

from tpu_cc_manager.simlab.runner import SimLab  # noqa: E402
from tpu_cc_manager.simlab.scenario import load_scenario  # noqa: E402

SCENARIO_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scenarios",
)


@pytest.fixture(autouse=True)
def _fast_scrapes(monkeypatch):
    # the smoke scenarios' fault window is a few seconds wide; scrape
    # responsively so the windows see it
    monkeypatch.setenv("TPU_CC_FLEETOBS_INTERVAL_S", "0.25")


def _run(name):
    lab = SimLab(load_scenario(os.path.join(SCENARIO_DIR, name)))
    return lab, lab.run()


def test_write_429_storm_burns_the_flip_success_budget():
    lab, art = _run("slo-fault-24.json")
    assert art["ok"], art.get("notes")
    slo = art["metrics"]["slo"]
    assert "objectives" in slo, slo
    # the storm fired the multi-window alert and burned real budget
    fired = [a for a in slo["alerts"]
             if a["objective"] == "flip-success"]
    assert fired, slo["alerts"]
    assert fired[0]["fast_burn"] >= 2.0
    assert fired[0]["slow_burn"] >= 2.0
    assert fired[0]["budget_remaining"] < 1.0
    assert slo["objectives"]["flip-success"]["budget_remaining"] < 1.0
    # the alert event is IN the black box (the dump surface)
    events = [e for e in lab.obs_rec.snapshot("test")["events"]
              if e["kind"] == "slo_burn"
              and e["objective"] == "flip-success"]
    assert events
    # merging every replica's exposition stayed strictly valid
    assert slo["aggregation_problems"] == []
    assert slo["scrapes"]["ok"] > 0
    assert slo["scrapes"]["invalid"] == 0


def test_clean_run_burns_no_budget():
    _, art = _run("slo-clean-16.json")
    assert art["ok"], art.get("notes")
    slo = art["metrics"]["slo"]
    assert slo["alerts"] == []
    for name in ("flip-success", "publish-loss"):
        assert slo["objectives"][name]["budget_remaining"] == 1.0, name
        assert not slo["objectives"][name]["burning"]
    assert slo["aggregation_problems"] == []
