"""Fleet controller: periodic pool audit served as metrics + report."""

import json
import threading
import time
import urllib.request

from tpu_cc_manager import labels as L
from tpu_cc_manager.fleet import FleetController
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node


def _node(name, desired=None, state=None, slice_id=None):
    labels = {L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice"}
    if desired:
        labels[L.CC_MODE_LABEL] = desired
    if state:
        labels[L.CC_MODE_STATE_LABEL] = state
    if slice_id:
        labels[L.TPU_SLICE_LABEL] = slice_id
    return make_node(name, labels=labels)


def _mixed_fleet():
    kube = FakeKube()
    # 2 converged, 1 divergent, 1 failed, one half-flipped 2-node slice
    kube.add_node(_node("ok-1", desired="on", state="on"))
    kube.add_node(_node("ok-2", desired="off", state="off"))
    kube.add_node(_node("lag-1", desired="on", state="off"))
    kube.add_node(_node("bad-1", desired="on", state="failed"))
    kube.add_node(_node("s1-a", desired="on", state="on", slice_id="s1"))
    kube.add_node(_node("s1-b", desired="on", state="off", slice_id="s1"))
    return kube


def test_scan_once_updates_metrics_and_report():
    ctrl = FleetController(_mixed_fleet())
    report = ctrl.scan_once()
    assert report["nodes"] == 6
    # divergent: lag-1, bad-1 (failed != on), s1-b
    assert set(report["needs_flip"]) == {"lag-1", "bad-1", "s1-b"}
    assert report["failed"] == ["bad-1"]
    assert report["half_flipped_slices"] == ["s1"]
    m = ctrl.metrics
    assert m.nodes.value() == 6
    assert m.needs_flip.value() == 3
    assert m.failed.value() == 1
    assert m.half_flipped_slices.value() == 1
    assert m.nodes_by_mode.value("on") == 2  # ok-1, s1-a
    assert m.scans_total.value("success") == 1


def test_metrics_zero_out_vanished_modes():
    kube = FakeKube()
    kube.add_node(_node("n", desired="on", state="on"))
    ctrl = FleetController(kube)
    ctrl.scan_once()
    assert ctrl.metrics.nodes_by_mode.value("on") == 1
    kube.set_node_labels("n", {L.CC_MODE_STATE_LABEL: "off"})
    ctrl.scan_once()
    assert ctrl.metrics.nodes_by_mode.value("on") == 0
    assert ctrl.metrics.nodes_by_mode.value("off") == 1


def test_http_endpoints_and_run_loop():
    ctrl = FleetController(_mixed_fleet(), interval_s=0.05, port=0)
    t = threading.Thread(target=ctrl.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while ctrl.last_report is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ctrl.last_report is not None
        base = f"http://127.0.0.1:{ctrl.port}"
        with urllib.request.urlopen(f"{base}/healthz") as r:
            assert r.read() == b"ok"
        with urllib.request.urlopen(f"{base}/report") as r:
            report = json.load(r)
        assert report["nodes"] == 6
        with urllib.request.urlopen(f"{base}/metrics") as r:
            text = r.read().decode()
        assert "tpu_cc_fleet_nodes 6" in text
        assert 'tpu_cc_fleet_nodes_by_mode{mode="failed"} 1' in text
        assert "tpu_cc_fleet_half_flipped_slices 1" in text
        try:
            urllib.request.urlopen(f"{base}/metrics/bogus")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        ctrl.stop()
        t.join(timeout=5)
        assert not t.is_alive()


def test_persistent_api_failure_exits_unhealthy():
    from tpu_cc_manager.k8s.client import ApiException

    kube = FakeKube()
    kube.add_node(_node("n"))

    calls = {"n": 0}
    orig = kube.list_nodes

    def flaky(selector=None):
        calls["n"] += 1
        raise ApiException(500, "injected outage")

    kube.list_nodes = flaky
    ctrl = FleetController(
        kube, interval_s=0.01, port=0, max_consecutive_errors=3
    )
    rc = ctrl.run()
    assert rc == 1
    assert calls["n"] == 3
    assert not ctrl.healthy


def test_rejects_nonpositive_interval():
    import pytest

    with pytest.raises(ValueError, match="interval"):
        FleetController(FakeKube(), interval_s=0)


def test_report_503_before_first_scan():
    ctrl = FleetController(FakeKube(), port=0)
    ctrl._server.start()
    try:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{ctrl.port}/report"
            )
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
    finally:
        ctrl.stop()


def test_scan_loop_survives_non_api_exceptions():
    # A malformed node object (analyze_fleet KeyError) must count as a
    # failed scan and degrade /healthz, not crash the controller process.
    from tpu_cc_manager.fleet import FleetController

    class BrokenKube:
        def list_nodes(self, selector=None):
            return [{"spec": {}}]  # no metadata -> KeyError in analyze

    ctrl = FleetController(BrokenKube(), interval_s=30.0, port=0,
                           max_consecutive_errors=2)
    for _ in range(2):
        try:
            ctrl.scan_once()
        except Exception:
            pass
    assert ctrl.consecutive_errors == 2
    assert not ctrl.healthy
    assert ctrl.metrics.scans_total.value("error") == 2


def test_doctor_aggregation_and_policy_summaries():
    """/report is the single operator pane: published doctor verdicts
    are aggregated (malformed ones count as failing) and TPUCCPolicy
    statuses are summarized; both disappear gracefully when absent."""
    kube = FakeKube()
    kube.add_node(_node("n-ok", desired="on", state="on"))
    kube.add_node(_node("n-bad", desired="on", state="on"))
    kube.add_node(_node("n-silent", desired="on", state="on"))
    kube.add_node(_node("n-garbled", desired="on", state="on"))
    kube.set_node_annotations("n-ok", {L.DOCTOR_ANNOTATION: json.dumps(
        {"ok": True, "fail": [], "warn": [], "at": "2026-07-30T00:00:00Z"}
    )})
    kube.set_node_annotations("n-bad", {L.DOCTOR_ANNOTATION: json.dumps(
        {"ok": False, "fail": ["state-label"], "warn": [],
         "at": "2026-07-30T00:00:00Z"}
    )})
    kube.set_node_annotations("n-garbled", {L.DOCTOR_ANNOTATION: "{nope"})
    kube.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
        "apiVersion": f"{L.POLICY_GROUP}/{L.POLICY_VERSION}",
        "kind": L.POLICY_KIND,
        "metadata": {"name": "prod"},
        "spec": {"mode": "on",
                 "nodeSelector": L.TPU_ACCELERATOR_LABEL},
        "status": {"phase": "Converged", "nodes": 4, "converged": 4,
                   "message": "all good"},
    })
    ctrl = FleetController(kube, port=0)
    report = ctrl.scan_once()
    doctor = report["doctor"]
    assert doctor["reported"] == 3
    assert [d["node"] for d in doctor["failing"]] == ["n-bad", "n-garbled"]
    assert doctor["failing"][0]["fail"] == ["state-label"]
    assert report["policies"] == [{
        "name": "prod", "mode": "on", "phase": "Converged",
        "nodes": 4, "converged": 4, "message": "all good",
    }]
    # the REQUIRE_DOCTOR preflight: silent nodes are named, and the
    # gauge lets an operator alert on "enforce only at zero"
    assert doctor["unreported"] == ["n-silent"]
    rendered = ctrl.metrics.render().splitlines()
    assert any(
        "tpu_cc_fleet_doctor_failing_nodes 2" in line
        for line in rendered
    )
    assert any(
        "tpu_cc_fleet_doctor_unreported_nodes 1" in line
        for line in rendered
    )


def test_doctor_publish_round_trip(tmp_path, monkeypatch):
    """doctor --publish -> fleet aggregation, end to end through the
    annotation channel."""
    from test_doctor import _backend, _flip

    from tpu_cc_manager.doctor import publish_report, run_doctor

    backend = _backend(tmp_path, monkeypatch)
    _flip(backend, "on")
    kube = FakeKube()
    kube.add_node(_node("pub-node", desired="on", state="off"))  # lying
    report = run_doctor(kube=kube, node_name="pub-node", backend=backend)
    assert report["ok"] is False  # state label contradicts devices
    assert publish_report(kube, "pub-node", report)
    fleet = FleetController(kube, port=0).scan_once()
    assert [d["node"] for d in fleet["doctor"]["failing"]] == ["pub-node"]
    assert "state-label" in fleet["doctor"]["failing"][0]["fail"]
    # the selectable mirror: kubectl get nodes -l cc.doctor.ok=false
    assert kube.get_node("pub-node")["metadata"]["labels"][
        L.DOCTOR_OK_LABEL] == "false"
    assert kube.list_nodes(f"{L.DOCTOR_OK_LABEL}=false")


def test_fleet_problems_classification():
    from tpu_cc_manager.fleet import fleet_problems

    clean = {
        "failed": [], "needs_flip": ["n1"],  # divergence alone is fine
        "evidence_audit": {"missing": [], "invalid": [],
                           "label_device_mismatch": []},
        "doctor": {"reported": 1, "failing": []},
        "half_flipped_slices": [], "incoherent_slices": [],
    }
    assert fleet_problems(clean) == []
    # missing evidence IS a problem: the audit only reports it for
    # nodes whose label claims success with nothing behind it — the
    # simplest forgery, or an agent that died before committing
    assert fleet_problems(dict(clean, evidence_audit={
        "missing": ["n9"], "invalid": [],
        "label_device_mismatch": [],
    })) == ["evidence missing: ['n9']"]
    # incoherent slices can never self-converge: operator action needed
    assert fleet_problems(dict(clean, incoherent_slices=["s2"])) == [
        "incoherent slices: ['s2']"
    ]
    dirty = {
        "failed": ["n2"],
        "evidence_audit": {"missing": ["n9"], "invalid": ["n3"],
                           "label_device_mismatch": ["n4"]},
        "doctor": {"failing": [{"node": "n5", "fail": ["gate-perms"]}]},
        "half_flipped_slices": ["s1"], "incoherent_slices": ["s2"],
    }
    problems = fleet_problems(dirty)
    assert len(problems) == 7
    assert any("n2" in p for p in problems)
    assert any("s1" in p for p in problems)


def test_cli_fleet_controller_once(monkeypatch, capsys):
    from tpu_cc_manager import __main__ as cli

    kube = FakeKube()
    # a node claiming success must carry evidence to count as clean —
    # bare labels are the forgery case the audit flags. A node with no
    # mode claim yet is clean.
    kube.add_node(_node("n1"))
    monkeypatch.setattr(cli, "_kube_client", lambda cfg: kube)
    rc = cli.main(["fleet-controller", "--once"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["nodes"] == 1

    # a success claim without evidence now fails the audit
    kube.add_node(_node("n2", desired="on", state="on"))
    rc = cli.main(["fleet-controller", "--once"])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["evidence_audit"]["missing"] == ["n2"]

    kube.add_node(_node("n3", desired="on", state="failed"))
    rc = cli.main(["fleet-controller", "--once"])
    assert rc == 1


def test_report_carries_election_state(monkeypatch):
    """/report is the one operator pane: when leader election is live,
    the report names each controller's lease holder and failover
    count; absent Leases contribute nothing; with election disabled
    the lookups are skipped entirely."""
    from tpu_cc_manager.fleet import FleetController

    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    kube.create_lease("tpu-system", {
        "metadata": {"name": "tpu-cc-policy-controller"},
        "spec": {"holderIdentity": "replica-a",
                 "renewTime": "2026-07-30T00:00:00.000000Z",
                 "leaseTransitions": 3},
    })
    monkeypatch.setenv("TPU_CC_LEADER_ELECT", "true")
    c = FleetController(kube, interval_s=30, port=0)
    report = c.scan_once()
    elections = report["leader_elections"]
    assert elections["tpu-cc-policy-controller"]["holder"] == "replica-a"
    assert elections["tpu-cc-policy-controller"]["transitions"] == 3
    assert "tpu-cc-fleet-controller" not in elections  # no Lease: absent

    # election off (no elector, no env): the report stays empty and
    # no lease GETs are issued
    monkeypatch.delenv("TPU_CC_LEADER_ELECT")
    calls = []
    orig = kube.get_lease
    kube.get_lease = lambda *a: (calls.append(a), orig(*a))[1]
    c2 = FleetController(kube, interval_s=30, port=0)
    assert c2.scan_once()["leader_elections"] == {}
    assert calls == []


def test_node_fingerprint_ignores_doctor_timestamp():
    """The watch wake filter: a periodic doctor republish that changes
    only its timestamp must not wake a scan; a state-label or verdict
    change must."""
    from tpu_cc_manager.fleet import FleetController

    def node(state="on", doctor_at="t1", ok=True, evidence="e1"):
        return {
            "metadata": {
                "name": "n1",
                "labels": {L.TPU_ACCELERATOR_LABEL: "v5p",
                           L.CC_MODE_STATE_LABEL: state,
                           "unrelated": "x"},
                "annotations": {
                    L.DOCTOR_ANNOTATION: json.dumps(
                        {"ok": ok, "fail": [], "at": doctor_at}),
                    L.EVIDENCE_ANNOTATION: evidence,
                },
            },
        }

    fp = FleetController._node_fingerprint
    base = fp(node())
    assert fp(node(doctor_at="t2")) == base           # timestamp only
    assert fp(node(state="off")) != base              # mode moved
    assert fp(node(ok=False)) != base                 # verdict flipped
    assert fp(node(evidence="e2")) != base            # evidence moved
    # unrelated label churn (kubelet heartbeat analogs) is invisible
    n = node()
    n["metadata"]["labels"]["unrelated"] = "y"
    assert fp(n) == base

    # the annotation is node-writable (hostile input): odd-but-parseable
    # shapes must normalise stably, never throw in the watch thread
    for hostile in ('{"ok": true, "fail": 5}', "null", "5", "{nope"):
        h = node()
        h["metadata"]["annotations"][L.DOCTOR_ANNOTATION] = hostile
        assert fp(h) == fp(h)  # total + deterministic


def test_watch_triggered_scan_beats_the_interval():
    """A state change on a node must surface in /report within the min
    scan gap, not the interval — the watch wakes the loop. The interval
    here is far beyond the test horizon, so only the watch can explain
    a fresh report."""
    import threading as _threading

    from tpu_cc_manager.fleet import FleetController

    kube = FakeKube()
    kube.add_node(_node("n1", desired="on", state="on"))
    ctrl = FleetController(kube, interval_s=300.0, port=0)
    ctrl.min_scan_gap_s = 0.2
    t = _threading.Thread(target=ctrl.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            r = ctrl.last_report
            if r and r.get("nodes") == 1:
                break
            time.sleep(0.1)
        assert ctrl.last_report and ctrl.last_report["nodes"] == 1

        # divergence appears; the watch must surface it well before the
        # 300 s interval
        kube.set_node_labels("n1", {L.CC_MODE_LABEL: "off"})
        deadline = time.monotonic() + 15
        seen = None
        while time.monotonic() < deadline:
            r = ctrl.last_report
            if r and r.get("needs_flip"):
                seen = r["needs_flip"]
                break
            time.sleep(0.1)
        assert seen == ["n1"], ctrl.last_report
    finally:
        ctrl.stop()
        t.join(timeout=10)
        assert not t.is_alive()


def test_watchless_client_degrades_to_polling():
    """A minimal clientset without node-watch support must not crash
    the watch thread — the controller degrades to interval polling."""
    from tpu_cc_manager.fleet import FleetController
    from tpu_cc_manager.k8s.client import ApiException

    class Minimal(FakeKube):
        def watch_nodes(self, *a, **kw):
            raise ApiException(501, "no watch here")

    ctrl = FleetController(Minimal(), port=0)
    ctrl._watch_loop()  # returns promptly instead of raising/looping


def test_watch_feed_filters_foreign_nodes():
    """The node watch streams EVERY cluster node; only fleet-selector
    matches may enter the planner's feature block (a foreign failed
    node must never surface in a report snapshotted before the next
    list sync prunes it). DELETED always forwards."""
    ctrl = FleetController(_mixed_fleet())
    foreign = make_node("pet-vm", labels={
        L.CC_MODE_LABEL: "on", L.CC_MODE_STATE_LABEL: "failed"})
    ctrl._on_watch_event("ADDED", foreign)
    assert len(ctrl._encoding) == 0
    member = make_node("tpu-1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_LABEL: "on",
        L.CC_MODE_STATE_LABEL: "on"})
    ctrl._on_watch_event("ADDED", member)
    assert len(ctrl._encoding) == 1
    ctrl._on_watch_event("DELETED", member)
    assert len(ctrl._encoding) == 0
