"""ccaudit: every rule's positive hit, negative pass, and pragma
suppression; the ABBA-cycle detector on a synthetic two-lock inversion;
the baseline ratchet (new findings fail, stale entries fail); and the
committed-baseline freshness gate — the same staleness discipline the
scenario and kustomize trees get (test_simlab.py / test_manifests.py).

Fixtures are inline source snippets fed through ``analyze_source`` —
no filesystem, no fixtures directory to drift.
"""

import json
import subprocess
import sys
import textwrap


from tpu_cc_manager.analysis import (
    BASELINE_PATH,
    analyze_paths,
    analyze_source,
    diff_against_baseline,
    load_baseline,
    repo_root,
    write_baseline,
)


def run(src: str, relpath: str = "tpu_cc_manager/snippet.py"):
    return analyze_source(textwrap.dedent(src), relpath)


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------ raw-acquire


def test_raw_acquire_flagged():
    (f,) = run(
        """
        import threading
        lock = threading.Lock()
        def f():
            lock.acquire()
        """
    )
    assert f.rule == "raw-acquire"
    assert f.line == 5


def test_acquire_with_try_finally_release_passes():
    assert run(
        """
        import threading
        lock = threading.Lock()
        def f():
            lock.acquire()
            try:
                x = 1
            finally:
                lock.release()
        """
    ) == []


def test_with_statement_passes():
    assert run(
        """
        import threading
        lock = threading.Lock()
        def f():
            with lock:
                x = 1
        """
    ) == []


def test_raw_acquire_pragma_suppresses():
    assert run(
        """
        import threading
        lock = threading.Lock()
        def f():
            lock.acquire()  # ccaudit: allow-raw-acquire(handed to a callback that releases)
        """
    ) == []


def test_nonstandard_lock_name_caught_via_assignment():
    # `gate = threading.Lock()` has no lock-ish name; the known-lock
    # assignment tracker still sees it
    (f,) = run(
        """
        import threading
        gate = threading.Lock()
        def f():
            gate.acquire()
        """
    )
    assert f.rule == "raw-acquire"


# ----------------------------------------------------------- lock-order


def test_abba_two_lock_inversion_detected():
    findings = run(
        """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def f(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def g(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """
    )
    assert rules_of(findings) == ["lock-order"]
    assert "ABBA" in findings[0].message


def test_consistent_lock_order_passes():
    assert run(
        """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def f(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def g(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
        """
    ) == []


def test_abba_through_one_call_hop():
    # f holds A and calls take_b (which takes B); g nests A under B:
    # the inversion is only visible through the call summary
    findings = run(
        """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def take_b(self):
                with self._b_lock:
                    pass

            def f(self):
                with self._a_lock:
                    self.take_b()

            def g(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """
    )
    assert rules_of(findings) == ["lock-order"]


def test_abba_via_multi_item_with():
    # `with a, b:` acquires left to right — same ordering constraint as
    # the nested form, same inversion
    findings = run(
        """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def f(self):
                with self._a_lock, self._b_lock:
                    pass

            def g(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """
    )
    assert rules_of(findings) == ["lock-order"]
    assert "ABBA" in findings[0].message


def test_blocking_call_in_later_with_item_flagged():
    # item 2's context expression evaluates while item 1's lock is held
    (f,) = run(
        """
        import threading, subprocess
        lock = threading.Lock()
        def f():
            with lock, subprocess.Popen(["true"]) as p:
                pass
        """
    )
    assert f.rule == "blocking-under-lock"


def test_abba_in_async_with():
    findings = run(
        """
        import asyncio

        class S:
            def __init__(self):
                self._a_lock = asyncio.Lock()
                self._b_lock = asyncio.Lock()

            async def f(self):
                async with self._a_lock:
                    async with self._b_lock:
                        pass

            async def g(self):
                async with self._b_lock:
                    async with self._a_lock:
                        pass
        """
    )
    assert rules_of(findings) == ["lock-order"]


def test_nonreentrant_self_nesting_detected():
    findings = run(
        """
        import threading
        lock = threading.Lock()
        def f():
            with lock:
                with lock:
                    pass
        """
    )
    assert rules_of(findings) == ["lock-order"]
    assert "re-acquired" in findings[0].message


def test_rlock_self_nesting_is_legal():
    assert run(
        """
        import threading
        lock = threading.RLock()
        def f():
            with lock:
                with lock:
                    pass
        """
    ) == []


def test_lock_order_pragma_suppresses():
    assert run(
        """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def f(self):
                with self._a_lock:
                    # ccaudit: allow-lock-order(g only runs before threads start)
                    with self._b_lock:
                        pass

            def g(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """
    ) == []


# -------------------------------------------------- blocking-under-lock


def test_sleep_under_lock_flagged():
    (f,) = run(
        """
        import threading, time
        lock = threading.Lock()
        def f():
            with lock:
                time.sleep(1)
        """
    )
    assert f.rule == "blocking-under-lock"
    assert "time.sleep" in f.message


def test_blocking_prefixes_seen_through_import_aliases():
    findings = run(
        """
        import threading
        import subprocess as sp
        from time import sleep
        lock = threading.Lock()
        def f():
            with lock:
                sleep(1)
                sp.run(["true"])
        """
    )
    assert rules_of(findings) == [
        "blocking-under-lock", "blocking-under-lock"
    ]


def test_sleep_outside_lock_passes():
    assert run(
        """
        import threading, time
        lock = threading.Lock()
        def f():
            with lock:
                x = 1
            time.sleep(1)
        """
    ) == []


def test_sleep_in_nested_def_under_lock_passes():
    # the nested function body does not run while the lock is held
    assert run(
        """
        import threading, time
        lock = threading.Lock()
        def f():
            with lock:
                def cb():
                    time.sleep(1)
                return cb
        """
    ) == []


def test_blocking_under_lock_pragma_suppresses():
    assert run(
        """
        import threading, time
        lock = threading.Lock()
        def f():
            with lock:
                time.sleep(1)  # ccaudit: allow-blocking-under-lock(test-only fake latency)
        """
    ) == []


# ------------------------------------- executor waits under a held lock


def test_future_result_under_lock_flagged():
    # the flip-executor pattern's one forbidden shape: Future.result()
    # blocks on a worker thread that may need the held lock — deadlock
    (f,) = run(
        """
        import threading
        lock = threading.Lock()
        def f(futures):
            with lock:
                return [fut.result() for fut in futures]
        """
    )
    assert f.rule == "blocking-under-lock"
    assert "result" in f.message


def test_concurrent_futures_wait_under_lock_flagged():
    findings = run(
        """
        import threading
        import concurrent.futures as cf
        from concurrent.futures import wait
        lock = threading.Lock()
        def f(futures):
            with lock:
                wait(futures)
                cf.as_completed(futures)
        """
    )
    assert rules_of(findings) == [
        "blocking-under-lock", "blocking-under-lock"
    ]


def test_future_result_outside_lock_passes():
    # the engine/flipexec shape: collect under no lock
    assert run(
        """
        import threading
        lock = threading.Lock()
        def f(pool, items):
            with lock:
                todo = list(items)
            futures = [pool.submit(work, i) for i in todo]
            return [fut.result() for fut in futures]
        def work(i):
            return i
        """
    ) == []


def test_future_result_under_lock_pragma_suppresses():
    assert run(
        """
        import threading
        lock = threading.Lock()
        def f(fut):
            with lock:
                return fut.result()  # ccaudit: allow-blocking-under-lock(single-worker pool, lock never shared)
        """
    ) == []


# --------------------------------------------------------- label-literal


def test_label_literal_flagged():
    (f,) = run('MODE = "tpu.google.com/cc.mode"\n')
    assert f.rule == "label-literal"


def test_label_literal_in_labels_py_passes():
    assert run(
        'MODE = "tpu.google.com/cc.mode"\n',
        relpath="tpu_cc_manager/labels.py",
    ) == []


def test_label_literal_in_docstring_passes():
    assert run(
        '''
        def f():
            """Writes tpu.google.com/cc.mode on the node."""
        '''
    ) == []


def test_label_literal_in_fstring_flagged():
    (f,) = run('def f(m):\n    return f"tpu.google.com/{m}"\n')
    assert f.rule == "label-literal"


def test_label_literal_pragma_suppresses():
    assert run(
        'X = "tpu.google.com/cc.mode"  # ccaudit: allow-label-literal(CLI help text)\n'
    ) == []


# --------------------------------------------------------------- swallow


def test_silent_broad_except_flagged():
    (f,) = run(
        """
        try:
            x = 1
        except Exception:
            pass
        """
    )
    assert f.rule == "swallow"
    assert f.line == 4


def test_bare_except_flagged():
    assert rules_of(run("try:\n    x = 1\nexcept:\n    pass\n")) == ["swallow"]


def test_handler_that_logs_passes():
    assert run(
        """
        import logging
        log = logging.getLogger(__name__)
        try:
            x = 1
        except Exception:
            log.warning("failed", exc_info=True)
        """
    ) == []


def test_handler_that_reraises_passes():
    assert run(
        """
        try:
            x = 1
        except Exception:
            raise RuntimeError("wrapped")
        """
    ) == []


def test_handler_using_bound_exception_passes():
    assert run(
        """
        def f():
            try:
                return 1
            except Exception as e:
                return f"failed: {e}"
        """
    ) == []


def test_handler_binding_but_ignoring_exception_flagged():
    assert rules_of(run(
        """
        try:
            x = 1
        except Exception as e:
            y = 2
        """
    )) == ["swallow"]


def test_swallow_pragma_on_except_line():
    assert run(
        """
        try:
            x = 1
        except Exception:  # ccaudit: allow-swallow(best-effort cache warm)
            pass
        """
    ) == []


def test_swallow_pragma_on_first_body_line():
    assert run(
        """
        try:
            x = 1
        except Exception:
            pass  # ccaudit: allow-swallow(best-effort cache warm)
        """
    ) == []


def test_pragma_requires_reason():
    # an empty reason is not a suppression
    assert rules_of(run(
        """
        try:
            x = 1
        except Exception:  # ccaudit: allow-swallow()
            pass
        """
    )) == ["swallow"]


def test_narrow_except_never_flagged():
    assert run(
        """
        try:
            x = 1
        except (ValueError, OSError):
            pass
        """
    ) == []


# ----------------------------------------------------------- metric-name


def test_undeclared_metric_use_flagged():
    (f,) = run('NAME = "tpu_cc_bogus_total"\n')
    assert f.rule == "metric-name"
    assert "tpu_cc_bogus_total" in f.message


def test_declared_metric_use_passes():
    assert run(
        """
        from tpu_cc_manager.obs import Counter
        c = Counter("tpu_cc_things_total", "things")
        NAME = "tpu_cc_things_total"
        """
    ) == []


def test_series_suffixes_resolve_to_declaration():
    assert run(
        """
        from tpu_cc_manager.obs import Histogram
        h = Histogram("tpu_cc_lat_seconds", "latency")
        SERIES = "tpu_cc_lat_seconds_bucket"
        """
    ) == []


def test_duplicate_metric_declaration_flagged():
    (f,) = run(
        """
        from tpu_cc_manager.obs import Counter
        a = Counter("tpu_cc_things_total", "things")
        b = Counter("tpu_cc_things_total", "things again")
        """
    )
    assert f.rule == "metric-name"
    assert "more than once" in f.message


def test_metric_pragma_suppresses():
    assert run(
        'NAME = "tpu_cc_retired_total"  # ccaudit: allow-metric-name(grafana migration note)\n'
    ) == []


# ------------------------------------- metric-name: watchdog series
# (ISSUE 15: every WatchSeries metric must be a declared family — an
# anomaly detector over a metric nobody renders can never fire)


def test_watchdog_series_over_declared_metric_passes():
    assert run(
        """
        from tpu_cc_manager.obs import Histogram
        from tpu_cc_manager.watchdog import WatchSeries
        h = Histogram("tpu_cc_lat_seconds", "latency")
        SERIES = (WatchSeries("tpu_cc_lat_seconds", "p99"),)
        """
    ) == []


def test_watchdog_series_unknown_metric_flagged():
    (f,) = run(
        """
        from tpu_cc_manager.watchdog import WatchSeries
        SERIES = (WatchSeries("tpu_cc_nope_seconds", "p99"),)
        """
    )
    assert f.rule == "metric-name"
    assert "watchdog series" in f.message
    assert "can never fire" in f.message


def test_watchdog_series_non_prefixed_typo_flagged():
    # the generic literal pass only sees tpu_cc_* strings; the
    # watchdog check must catch a typo OUTSIDE the prefix too
    (f,) = run(
        """
        from tpu_cc_manager.watchdog import WatchSeries
        SERIES = (WatchSeries(metric="node_flips"),)
        """
    )
    assert f.rule == "metric-name"
    assert "watchdog series" in f.message


def test_watchdog_series_pragma_suppresses():
    assert run(
        """
        from tpu_cc_manager.watchdog import WatchSeries
        SERIES = (WatchSeries("node_cpu_seconds"),)  # ccaudit: allow-metric-name(kubelet-scraped family)
        """
    ) == []


# ------------------------------------------------------ baseline ratchet


def _findings_fixture():
    return run(
        """
        try:
            x = 1
        except Exception:
            pass
        """
    )


def test_baseline_suppresses_known_finding(tmp_path):
    findings = _findings_fixture()
    path = str(tmp_path / "baseline.json")
    write_baseline(findings, path)
    new, suppressed, stale = diff_against_baseline(
        findings, load_baseline(path)
    )
    assert new == [] and stale == [] and len(suppressed) == 1


def test_new_finding_not_in_baseline_fails(tmp_path):
    path = str(tmp_path / "baseline.json")
    write_baseline([], path)
    new, _, stale = diff_against_baseline(
        _findings_fixture(), load_baseline(path)
    )
    assert len(new) == 1 and stale == []


def test_stale_baseline_entry_fails(tmp_path):
    # entry points at a line whose text no longer matches: stale
    findings = _findings_fixture()
    path = str(tmp_path / "baseline.json")
    write_baseline(findings, path)
    entries = load_baseline(path)
    entries[0]["text"] = "except Exception as e:"
    new, _, stale = diff_against_baseline(findings, entries)
    assert len(new) == 1 and len(stale) == 1


def test_moved_finding_is_both_new_and_stale(tmp_path):
    findings = _findings_fixture()
    path = str(tmp_path / "baseline.json")
    write_baseline(findings, path)
    entries = load_baseline(path)
    entries[0]["line"] += 10
    new, _, stale = diff_against_baseline(findings, entries)
    assert len(new) == 1 and len(stale) == 1


def test_same_line_duplicates_are_multiset(tmp_path):
    # two violations on one line share a (rule, file, line, text) key;
    # one baseline entry must suppress exactly one of them
    findings = run('PAIR = ("tpu.google.com/a", "tpu.google.com/b")\n')
    assert len(findings) == 2
    path = str(tmp_path / "baseline.json")
    write_baseline(findings[:1], path)
    new, suppressed, stale = diff_against_baseline(
        findings, load_baseline(path)
    )
    assert len(new) == 1 and len(suppressed) == 1 and stale == []


# ----------------------------------------- the repo itself, gated in CI


def test_repo_is_clean_against_committed_baseline():
    """The ccaudit CI gate, as a test: zero new findings and — the
    freshness half — zero stale baseline entries. A baseline entry whose
    file/line/text no longer matches a live finding fails here, so a
    stale suppression can never mask a regression."""
    findings = analyze_paths(repo_root())
    new, _, stale = diff_against_baseline(findings, load_baseline())
    assert new == [], "\n".join(f.render() for f in new)
    assert stale == [], f"stale baseline entries: {stale}"


def test_committed_baseline_is_canonically_formatted(tmp_path):
    """Byte-for-byte regeneration — the schema-example treatment
    test_simlab.py gives scenarios/: hand-edits that drift from
    --write-baseline output are errors."""
    import os

    committed = os.path.join(repo_root(), BASELINE_PATH)
    with open(committed, "r", encoding="utf-8") as f:
        committed_bytes = f.read()
    entries = load_baseline(committed)
    regen = str(tmp_path / "regen.json")
    findings = analyze_paths(repo_root())
    keep = {
        (e["rule"], e["file"], int(e["line"]), e["text"]) for e in entries
    }
    write_baseline([f for f in findings if f.key() in keep], regen)
    with open(regen, "r", encoding="utf-8") as f:
        assert f.read() == committed_bytes


def test_cli_exits_nonzero_on_new_finding(tmp_path):
    """Acceptance check for the CLI contract: a fresh violation in the
    scan surface flips the exit code."""
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "bad.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "[swallow]" in proc.stdout

    (root / "pkg" / "bad.py").write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0


def test_cli_errors_on_target_matching_no_files(tmp_path):
    """A typo'd or renamed scan target must fail loud, not pass vacuous."""
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "ok.py").write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "pkg", "no_such_dir"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2
    assert "no_such_dir" in proc.stderr


def test_files_mode_surface_filter():
    """--files drops paths the merge gate never scans: tests/ fixtures
    hard-code protocol literals by design, docs and deletions ride
    every diff."""
    from tpu_cc_manager.analysis.core import on_default_surface

    assert on_default_surface("tpu_cc_manager/policy.py")
    assert on_default_surface("scripts/bench_trend.py")
    assert on_default_surface("bench.py")
    assert not on_default_surface("tests/test_federation.py")
    assert not on_default_surface("docs/analysis.md")
    assert not on_default_surface("tpu_cc_manager/native/foo.py")


def test_cli_files_mode_nothing_to_scan_exits_zero():
    """A diff of only docs/tests/deletions must pass without running
    the analysis at all."""
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis", "--files",
         "README.md", "tests/test_federation.py", "no/such/file.py"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0
    assert "nothing to scan" in proc.stderr


def test_files_mode_keeps_whole_program_context():
    """The soundness contract of --files: the report is restricted to
    the slice, but the ANALYSIS is whole-program. policy.py is the
    regression case — under slice-only analysis its guarded writes
    false-fired race-lockset because the callers holding the lock (and
    the thread roots) live outside the slice."""
    findings = analyze_paths(
        targets=["tpu_cc_manager/policy.py"], subset=True
    )
    assert [f for f in findings if f.file != "tpu_cc_manager/policy.py"] == []
    assert findings == []


def test_cli_exits_nonzero_on_stale_baseline(tmp_path):
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "ok.py").write_text("x = 1\n")
    baseline = root / "stale.json"
    baseline.write_text(json.dumps({
        "version": 1,
        "findings": [{
            "rule": "swallow", "file": "pkg/ok.py", "line": 1,
            "text": "except Exception:",
        }],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--baseline", str(baseline), "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "stale-baseline" in proc.stdout


# ------------------------------------------------------ direct-node-write
def test_direct_node_write_flagged_in_reconcile_path_module():
    """ISSUE 6: node-write verbs called directly from a reconcile-path
    module bypass the coalescing batcher and silently re-inflate the
    flip's write round trips."""
    src = """
    class A:
        def publish(self):
            self.kube.set_node_labels("n1", {"k": "v"})
            self.kube.set_node_annotations("n1", {"a": "b"})
            self.kube.patch_node("n1", {})
            self.kube.replace_node("n1", {})
    """
    findings = run(src, relpath="tpu_cc_manager/agent.py")
    hits = [f for f in findings if f.rule == "direct-node-write"]
    assert len(hits) == 4
    assert "NodePatchBatcher" in hits[0].message


def test_direct_node_write_ignores_other_modules():
    """The rule scopes to the reconcile path: controllers, rollout, and
    test doubles write directly by design."""
    src = """
    class A:
        def publish(self):
            self.kube.set_node_labels("n1", {"k": "v"})
    """
    for relpath in ("tpu_cc_manager/rollout.py",
                    "tpu_cc_manager/k8s/batch.py", "snippet.py"):
        findings = run(src, relpath=relpath)
        assert not [f for f in findings if f.rule == "direct-node-write"], relpath


def test_direct_node_write_pragma_allows_ordered_writes():
    src = """
    class A:
        def publish(self):
            self.kube.set_node_labels("n1", {"k": "v"})  # ccaudit: allow-direct-node-write(fail-secure state write)
    """
    findings = run(src, relpath="tpu_cc_manager/engine.py")
    assert not [f for f in findings if f.rule == "direct-node-write"]


# --------------------------------------------------------- planner-bypass
def test_planner_bypass_flags_mode_loop_in_scan_controller():
    """ISSUE 7: per-node mode-label reads inside a loop in fleet/policy
    reintroduce exactly the Python scan loops the batched planner
    kernel replaced — O(fleet) work back on the scan path."""
    src = """
    def derive(nodes):
        converged = 0
        for n in nodes:
            if n["metadata"]["labels"].get(L.CC_MODE_STATE_LABEL) == "on":
                converged += 1
        return converged
    """
    for relpath in ("tpu_cc_manager/fleet.py", "tpu_cc_manager/policy.py"):
        findings = run(src, relpath=relpath)
        hits = [f for f in findings if f.rule == "planner-bypass"]
        assert len(hits) == 1, relpath
        assert "analyze_pools" in hits[0].message


def test_planner_bypass_scopes_to_scan_controllers_and_loops():
    # rollout's per-node label touches are actuation, not analysis —
    # out of scope by module; a loop-free read in fleet.py is fine too
    loop_src = """
    def derive(nodes):
        for n in nodes:
            x = n["metadata"]["labels"].get(L.CC_MODE_LABEL)
    """
    for relpath in ("tpu_cc_manager/rollout.py", "tpu_cc_manager/plan.py",
                    "snippet.py"):
        findings = run(loop_src, relpath=relpath)
        assert not [f for f in findings if f.rule == "planner-bypass"], relpath
    flat_src = """
    def derive(node):
        return node["metadata"]["labels"].get(L.CC_MODE_LABEL)
    """
    findings = run(flat_src, relpath="tpu_cc_manager/fleet.py")
    assert not [f for f in findings if f.rule == "planner-bypass"]


def test_planner_bypass_pragma_allows_deliberate_reads():
    src = """
    def derive(nodes):
        for n in nodes:
            x = n["metadata"]["labels"].get(L.CC_MODE_STATE_LABEL)  # ccaudit: allow-planner-bypass(evidence audit cross-checks label text against attestation)
    """
    findings = run(src, relpath="tpu_cc_manager/fleet.py")
    assert not [f for f in findings if f.rule == "planner-bypass"]


def test_planner_bypass_nested_loop_reports_once():
    # ast.walk visits a nested loop's body once per enclosing loop;
    # the rule dedupes by position or one read double-reports into
    # baselines and SARIF
    src = """
    def derive(pools):
        for pool in pools:
            for n in pool:
                if n["metadata"]["labels"].get(L.CC_MODE_STATE_LABEL) == "on":
                    pass
    """
    findings = run(src, relpath="tpu_cc_manager/policy.py")
    hits = [f for f in findings if f.rule == "planner-bypass"]
    assert len(hits) == 1


# ----------------------------------------------------------- shard-bypass
def test_shard_bypass_flags_partition_subscript_without_ring():
    """ISSUE 11: indexing a shard partition table with anything but a
    hash-ring lookup couples a shard to a partition it does not own —
    the cross-shard double-writer the ring exists to prevent."""
    src = """
    class M:
        def steal(self, other):
            return self._partition[other]

        def hardcode(self):
            return self.mgr.pools_of("shard-2")
    """
    findings = run(src, relpath="tpu_cc_manager/shard.py")
    hits = [f for f in findings if f.rule == "shard-bypass"]
    assert len(hits) == 2
    assert "owner_of" in hits[0].message
    assert "hard-coded" in hits[1].message


def test_shard_bypass_ring_lookup_and_other_modules_pass():
    ring_src = """
    class M:
        def route(self, pool):
            return self._partition[self.ring.owner_of(pool)]

        def scoped(self, pool):
            return self.mgr.pools_of(self.shard_of_pool(pool))
    """
    findings = run(ring_src, relpath="tpu_cc_manager/shard.py")
    assert not [f for f in findings if f.rule == "shard-bypass"]
    # the rule scopes to shard-aware modules: a dict named _partition
    # elsewhere is someone else's business
    naked = """
    def f(d):
        return d["_partition"] or _partition["x"]
    """
    for relpath in ("tpu_cc_manager/plan.py", "snippet.py"):
        findings = run(naked, relpath=relpath)
        assert not [f for f in findings if f.rule == "shard-bypass"], relpath


def test_shard_bypass_pragma_allows_deliberate_access():
    src = """
    class M:
        def debug_dump(self):
            return self._partition["shard-0"]  # ccaudit: allow-shard-bypass(read-only debug surface enumerates every partition)
    """
    findings = run(src, relpath="tpu_cc_manager/shard.py")
    assert not [f for f in findings if f.rule == "shard-bypass"]


# ---------------------------------------------------------- region-bypass
def test_region_bypass_flags_region_table_subscript_without_lookup():
    """ISSUE 16: indexing the pool->region table with anything but the
    sanctioned federation lookup couples a controller to a sibling
    region's API server — the cross-region writer the federation
    boundary exists to prevent."""
    src = """
    class M:
        def steal(self, pool):
            return self._pool_region[pool]

        def hardcode(self):
            return self.fed.region_of_pool("pool-7")
    """
    findings = run(src, relpath="tpu_cc_manager/federation.py")
    hits = [f for f in findings if f.rule == "region-bypass"]
    assert len(hits) == 2
    assert "owner_of" in hits[0].message
    assert "hard-coded" in hits[1].message


def test_region_bypass_sanctioned_lookup_and_other_modules_pass():
    ok_src = """
    class M:
        def route(self, pool):
            return self.region_pools[self.fed.region_of_pool(pool)]

        def place(self, pool):
            region, member = self.fed.owner_of(pool)
            return region
    """
    findings = run(ok_src, relpath="tpu_cc_manager/federation.py")
    assert not [f for f in findings if f.rule == "region-bypass"]
    # the rule scopes to region-aware modules: a dict named
    # region_pools elsewhere is someone else's business
    naked = """
    def f(d):
        return d["region_pools"] or region_pools["us-east"]
    """
    for relpath in ("tpu_cc_manager/shard.py", "snippet.py"):
        findings = run(naked, relpath=relpath)
        assert not [f for f in findings if f.rule == "region-bypass"], relpath


def test_region_bypass_pragma_allows_deliberate_access():
    src = """
    class M:
        def debug_dump(self):
            return self._pool_region["p0"]  # ccaudit: allow-region-bypass(read-only debug surface enumerates every region)
    """
    findings = run(src, relpath="tpu_cc_manager/federation.py")
    assert not [f for f in findings if f.rule == "region-bypass"]


def test_shard_module_joins_write_and_planner_rule_scopes():
    """ISSUE 11 satellite: shard.py is covered by the direct-node-write
    and planner-bypass module sets — the shard layer hosts controllers,
    it must never write nodes or re-grow Python mode loops itself."""
    write_src = """
    class S:
        def bad(self):
            self.kube.patch_node("n1", {})
    """
    findings = run(write_src, relpath="tpu_cc_manager/shard.py")
    assert [f for f in findings if f.rule == "direct-node-write"]
    loop_src = """
    def derive(nodes):
        for n in nodes:
            x = n["metadata"]["labels"].get(L.CC_MODE_STATE_LABEL)
    """
    findings = run(loop_src, relpath="tpu_cc_manager/shard.py")
    assert [f for f in findings if f.rule == "planner-bypass"]


# ----------------------------------------------------- poll-in-watch-path


def test_poll_in_watch_path_flagged_in_loop():
    """ISSUE 14: a time.sleep-clocked loop in a watch-fed
    reconcile-path module re-introduces the interval tax the
    event-driven judge removed."""
    src = """
    import time

    def wait_converged(stop):
        while not stop.is_set():
            time.sleep(0.5)
    """
    for relpath in ("tpu_cc_manager/rollout.py",
                    "tpu_cc_manager/drain.py",
                    "tpu_cc_manager/agent.py"):
        findings = run(src, relpath=relpath)
        hits = [f for f in findings if f.rule == "poll-in-watch-path"]
        assert len(hits) == 1, relpath
        assert "wake primitive" in hits[0].message


def test_poll_in_watch_path_sees_aliased_sleep_and_for_loops():
    src = """
    from time import sleep

    def drain(pods):
        for p in pods:
            sleep(2)
    """
    findings = run(src, relpath="tpu_cc_manager/drain.py")
    assert len([f for f in findings
                if f.rule == "poll-in-watch-path"]) == 1


def test_poll_in_watch_path_ignores_one_shot_sleeps_and_other_modules():
    """A backoff sleep outside a loop is not a poll; modules without a
    wake primitive (or outside the reconcile path) are out of scope."""
    backoff = """
    import time

    def backoff_once():
        time.sleep(5)
    """
    findings = run(backoff, relpath="tpu_cc_manager/rollout.py")
    assert not [f for f in findings if f.rule == "poll-in-watch-path"]
    loop = """
    import time

    def wait(stop):
        while not stop.is_set():
            time.sleep(0.5)
    """
    for relpath in ("tpu_cc_manager/engine.py", "snippet.py",
                    "tpu_cc_manager/k8s/fake.py"):
        findings = run(loop, relpath=relpath)
        assert not [f for f in findings
                    if f.rule == "poll-in-watch-path"], relpath


def test_poll_in_watch_path_pragma_escape():
    src = """
    import time

    def wait(stop):
        while not stop.is_set():
            time.sleep(0.5)  # ccaudit: allow-poll(no wake source wired: bare one-shot CLI drainer)
    """
    findings = run(src, relpath="tpu_cc_manager/drain.py")
    assert not [f for f in findings if f.rule == "poll-in-watch-path"]
